"""Pallas TPU kernel: one-pass max-pool backward (first-max-wins).

Why: the round-4 AmoebaNet@1024 profile puts ~16% of the train step in
max-pool backwards — ``select_and_scatter`` for the reduction cells'
stride-2 pools (6.9%) plus the stride-1 shifted-maximum tree's
select/accumulate chains (most of the 10.3% ``mul`` + 4.0% ``max``
classes; the genotype runs a 3x3 s1 max pool in every cell,
``models/amoebanet.py``). Both existing backwards are multi-pass at HBM:
``select_and_scatter`` walks windows sequentially, and the kh+kw tree
backward re-materializes the select chain pass by pass. The reference
leaves all of this to cuDNN (``MaxPool2d`` inside ``Pool``,
``spatial.py:1416-1509``); on TPU the op is ours to schedule.

This kernel computes dx in ONE streaming pass: per (batch, window-row
chunk, channel chunk) grid step it loads the padded input, the pooled
output and the cotangent once into VMEM, recomputes each window's winner
in-register (kh*kw compare/claim steps, row-major first-max-wins —
the same tie semantics as ``select_and_scatter``'s GE select; the
row-major first-claim decomposition was proved bit-equal to it on
tie-heavy data in ``tests/test_spatial_layers.py``), and accumulates the
scattered contributions in VMEM. HBM traffic is x + y + dy read once,
dx written once — the roofline for this op.

Layout notes (mirrors ``wgrad_pallas``): blocks keep NHWC with C on
lanes and W on sublanes; all in-kernel shifts are static ``lax.slice`` /
``jnp.pad`` on values; window-chunk overlap rows arrive through a second
aligned BlockSpec ("tail"), and the per-chunk rows that spill past the
chunk (a window's last kh-sh rows) leave through a second output the
wrapper folds back in — Pallas index maps cannot express overlapping
blocks in either direction.

Stride-2 support uses a parity ("polyphase") decomposition: dx rows/cols
of each residue class (r mod sh, c mod sw) are produced as separate
dense sub-arrays inside the kernel (taps grouped by parity; per class
the scatter offsets are plain static shifts), and the wrapper
interleaves the sh*sw classes back with one strided-set each — no
interior-padded full-resolution scatter terms (the failure mode that
made the XLA-level decomposition 32% SLOWER end-to-end,
``pool_bwd_impl``/docs/PERF.md round 4).

Dispatch: ``usable()`` = shape gate + cached on-device compile probe
(Mosaic failures only surface on real hardware); fallbacks are the
existing tree / reduce_window paths, so the step cannot be broken by a
kernel regression. ``MPI4DL_TPU_POOL_PALLAS=off`` disables for A/B;
``=on`` additionally neutralizes trainer-armed ``disable()`` heuristics
(the >=2048px gate) for A/B re-validation.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG = float("-inf")
_VMEM_BUDGET = 10 * 1024 * 1024


def pool_pallas_mode() -> str:
    """auto: shape/probe gates decide, and trainers may arm ``disable()``
    heuristics (e.g. the >=2048px gate). off: never dispatch. on: like
    auto but ``disable()`` becomes a no-op, so the >=2048px heuristic can
    be A/B-revalidated if the compiler/runtime VMEM behavior improves —
    correctness gates (shape plan, compile probe, batched traces) still
    apply."""
    mode = os.environ.get("MPI4DL_TPU_POOL_PALLAS", "auto")
    if mode not in ("auto", "off", "on"):
        raise ValueError(
            f"MPI4DL_TPU_POOL_PALLAS must be auto|off|on, got {mode!r}"
        )
    return mode


_DISABLED = [False]


class disable:
    """Trace-time off-switch for the pool kernel dispatch (context
    manager, same pattern as ``fastconv.wgrad_taps_threshold``).

    ``Trainer.train_step`` arms this for images >= 2048px: per-shape the
    kernels pass their gates there, but injecting VMEM-stack-allocated
    custom-call results into a program already compiled against the HBM
    ceiling kills the compile helper (measured: AmoebaNet@2048 bs1
    compiles with the kernels off, dies with them on — round 4). The
    @1024 headline regime, where the kernel is measured bit-exact at
    end-to-end parity, keeps the dispatch. ``MPI4DL_TPU_POOL_PALLAS=off``
    disables everywhere regardless; ``=on`` makes THIS switch a no-op so
    the heuristics that arm it can be A/B-revalidated."""

    def __enter__(self):
        self._prev = _DISABLED[0]
        if pool_pallas_mode() != "on":
            _DISABLED[0] = True

    def __exit__(self, *exc):
        _DISABLED[0] = self._prev
        return False


def _class_geometry(kh, kw, sh, sw):
    """Per parity class (cr, cc): max row/col shift (D, E). Class (cr, cc)
    holds dx rows r ≡ cr (mod sh) / cols ≡ cc (mod sw); tap (u, v) with
    u ≡ cr, v ≡ cc scatters window (a, b) to class position
    (a + (u-cr)//sh, b + (v-cc)//sw) — a plain static shift."""
    geo = {}
    for cr in range(sh):
        for cc in range(sw):
            ups = [u for u in range(kh) if u % sh == cr]
            vps = [v for v in range(kw) if v % sw == cc]
            if not ups or not vps:
                continue
            geo[(cr, cc)] = (
                max((u - cr) // sh for u in ups),
                max((v - cc) // sw for v in vps),
            )
    return geo


def _pool_bwd_kernel(*refs, kh, kw, sh, sw, to, wo):
    """One (batch, window-row chunk, channel chunk) grid step.

    refs: per parity plane (in geometry order) a main x ref
    [1, to, Wp_p, Cc] and — when the plane has row spill D > 0 — a tail
    ref [1, D, Wp_p, Cc]; then the dy ref [1, to, Wo, Cc]; then the
    outputs: per class a main ref [1, to, Wc, Cc] and (D > 0) a tail ref
    [1, D, Wc, Cc] carved from a 4-D chunk-flattened [b, nrows*D, Wc, C]
    array (a 5-D [b, nrows, D, Wc, C] form was rejected: the compiler
    assigned it VMEM memory space and stack-allocated the whole array —
    see the out_specs comment). Input planes and output classes share the same
    parity geometry: tap (u, v) lives on plane (u%sh, v%sw) at offset
    (u//sh, v//sw), and scatters window (a, b) to dx class (u%sh, v%sw)
    at the same offset — dx is in input coordinates.
    """
    geo = _class_geometry(kh, kw, sh, sw)
    ri = 0
    planes = {}
    for key, (dmax, emax) in geo.items():
        xpl = refs[ri][0]
        ri += 1
        if dmax:
            xpl = jnp.concatenate([xpl, refs[ri][0]], axis=0)
            ri += 1
        planes[key] = xpl
    dy = refs[ri][0]
    outs = refs[ri + 1 :]
    c = dy.shape[-1]
    zero = jnp.zeros((), dy.dtype)

    def tap(u, v):
        """This tap's value per window: a contiguous plane slice."""
        xpl = planes[(u % sh, v % sw)]
        d, e = u // sh, v // sw
        return lax.slice(xpl, (d, e, 0), (d + to, e + wo, c))

    # Online argmax in window order: strict > keeps the FIRST maximum —
    # select_and_scatter's tie rule. Compares run in f32 (Mosaic on this
    # target rejects bf16 cmpf, 16-bit ordered cmpi, AND 16-bit cmpi-eq
    # whose mask feeds a bf16 select — all probed; docs/PERF.md round 4
    # has the full support matrix). The f32 widening unpacks the
    # (8,128,2) VMEM tiling and is the kernel's main device cost;
    # every leaner formulation tried (single whole-block convert,
    # 16-bit bit-equality claims, u16 radix keys, pltpu.roll W-shifts,
    # grouped pads, XLA-level chunked calls) either hits an unsupported
    # Mosaic op or trips the runtime's VMEM stack allocation of
    # custom-call operands/results — this exact structure is the one
    # that compiles. Measured ledger in docs/PERF.md round 4.
    best = tap(0, 0).astype(jnp.float32)
    idx = jnp.zeros(best.shape, jnp.int32)
    ti = 0
    for u in range(kh):
        for v in range(kw):
            if ti:
                x_uv = tap(u, v).astype(jnp.float32)
                better = x_uv > best
                best = jnp.where(better, x_uv, best)
                idx = jnp.where(better, ti, idx)
            ti += 1

    # Per-class accumulation: static shifted adds inside VMEM.
    oi = 0
    for (cr, cc), (dmax, emax) in geo.items():
        acc = None
        for u in range(cr, kh, sh):
            d = (u - cr) // sh
            for v in range(cc, kw, sw):
                e = (v - cc) // sw
                contrib = jnp.where(idx == (u * kw + v), dy, zero)
                term = jnp.pad(
                    contrib,
                    ((d, dmax - d), (e, emax - e), (0, 0)),
                )
                acc = term if acc is None else acc + term
        outs[oi][0] = acc[:to]
        oi += 1
        if dmax:
            outs[oi][0] = acc[to:]
            oi += 1


def _chunk_c(c: int) -> int:
    """Channel chunk: whole when narrow or not 128-divisible (Mosaic
    requires the lane-dim block size to be a multiple of 128 or the
    whole array dim — e.g. 416 and 832 stay whole and _plan's VMEM
    budget decides viability), else the smallest 128-multiple divisor;
    C on lanes means chunks are independent."""
    if c <= 256 or c % 128:
        return c
    for mult in range(128, c, 128):
        if c % mult == 0:
            return mult
    return c


def _plan(c, ho, wo, kh, kw, sh, sw, itemsize):
    """Pick (row chunk ``to``, channel chunk); None when nothing fits."""
    cc = _chunk_c(c)
    geo = _class_geometry(kh, kw, sh, sw)
    for to in (32, 16, 8, 4, 2, 1):
        if ho % to:
            continue
        # Each plane's tail BlockSpec needs element row (i+1)*to to be a
        # multiple of its own block height D.
        if any(d > 0 and to % d for d, _ in geo.values()):
            continue
        plane_bytes = sum(
            (to + d) * (wo + e) * cc * itemsize for d, e in geo.values()
        )
        dy_bytes = to * wo * cc * itemsize
        argmax_bytes = to * wo * cc * 8  # f32 best + i32 idx
        acc_bytes = max(
            (to + d) * (wo + e) * cc * itemsize * 2  # acc + pad temp
            for d, e in geo.values()
        )
        if (
            plane_bytes + dy_bytes + argmax_bytes + acc_bytes
            < _VMEM_BUDGET
        ):
            return to, cc
    return None


def _out_geom(hp, wp, kh, kw, sh, sw):
    """(ho, wo, covered hp, covered wp) under reduce_window "valid"."""
    ho = (hp - kh) // sh + 1
    wo = (wp - kw) // sw + 1
    return ho, wo, (ho - 1) * sh + kh, (wo - 1) * sw + kw


def supported(x_shape, kh, kw, sh, sw, ph, pw, itemsize=2) -> bool:
    b, h, w, c = x_shape
    if kh <= sh and kw <= sw:
        return False  # non-overlapping: XLA's backward is already a reshape
    # This runtime's AOT compiler stack-allocates Pallas custom-call
    # results in VMEM (docs/PERF.md round 4), so the kernel's output set
    # (~dx-sized) must fit well under the 128 MB VMEM alongside the
    # working set. Gate cheaply here instead of paying a doomed 10-30 s
    # compile probe per >=2048px pool shape during bench runs.
    if b * (h + 2 * ph) * (w + 2 * pw) * c * itemsize > 100 * 1024 * 1024:
        return False
    hp, wp = h + 2 * ph, w + 2 * pw
    if hp < kh or wp < kw:
        return False
    ho, wo, _, _ = _out_geom(hp, wp, kh, kw, sh, sw)
    return _plan(c, ho, wo, kh, kw, sh, sw, itemsize) is not None


@functools.lru_cache(maxsize=None)
def _compiles(x_shape, dtype, kh, kw, sh, sw, ph, pw) -> bool:
    """Cached on-device compile probe (pattern: wgrad_pallas._compiles)."""
    import warnings

    try:
        b, h, w, c = x_shape
        hp, wp = h + 2 * ph, w + 2 * pw
        ho, wo, _, _ = _out_geom(hp, wp, kh, kw, sh, sw)
        jax.jit(
            functools.partial(_bwd_padded, kh=kh, kw=kw, sh=sh, sw=sw)
        ).lower(
            jax.ShapeDtypeStruct((b, hp, wp, c), dtype),
            jax.ShapeDtypeStruct((b, ho, wo, c), dtype),
        ).compile()
        return True
    except Exception as e:
        warnings.warn(
            "Pallas max-pool backward failed to compile for "
            f"x={x_shape} k=({kh},{kw}) s=({sh},{sw}) p=({ph},{pw}); "
            f"using the XLA backward instead. Error: {str(e)[:400]}"
        )
        return False


def usable(x, kh, kw, sh, sw, ph, pw) -> bool:
    if pool_pallas_mode() == "off":
        return False
    if jax.default_backend() != "tpu":
        return False
    if x.ndim != 4:
        return False
    if not supported(tuple(x.shape), kh, kw, sh, sw, ph, pw, x.dtype.itemsize):
        return False
    return _compiles(
        tuple(x.shape), jnp.dtype(x.dtype).name, kh, kw, sh, sw, ph, pw
    )


def dispatchable(x, kh, kw, sh, sw, ph, pw) -> bool:
    """``usable`` + not under a batched (vmapped) trace. The pipeline's
    micro-batched front vmaps the cell stack; a batched ``pallas_call``
    compiles through an added grid dimension only sometimes, and the
    compile probe (which runs on the UN-batched shape) cannot vouch for
    it — so batched contexts keep the XLA/tree backward, exactly like the
    halo kernel's policy (``parallel/halo.py:124-146``). The sniffs are
    shared with that policy: the pipeline front's ``xla_halo_only``
    context, plus a direct batch-tracer check."""
    from mpi4dl_tpu.parallel.halo import _is_batch_tracer, _xla_only_active

    if _DISABLED[0] or _xla_only_active() or _is_batch_tracer(x):
        return False
    return usable(x, kh, kw, sh, sw, ph, pw)


def _bwd_padded(xp, dy, *, kh, kw, sh, sw, interpret=False):
    """dxp [B, Hp, Wp, C] from the padded input and the cotangent."""
    b, hp, wp, c = xp.shape
    _, ho, wo, _ = dy.shape
    _, _, hp_eff, wp_eff = _out_geom(hp, wp, kh, kw, sh, sw)
    plan = _plan(c, ho, wo, kh, kw, sh, sw, xp.dtype.itemsize)
    assert plan is not None, (xp.shape, kh, kw, sh, sw)
    to, cchunk = plan
    nrows = ho // to
    nc = c // cchunk
    geo = _class_geometry(kh, kw, sh, sw)

    # Windows cover padded rows/cols [0, hp_eff) x [0, wp_eff); anything
    # past that (possible when the torch floor-mode output size leaves a
    # trailing pad row uncovered, e.g. k3 s2 p1 on even sizes) gets zero
    # gradient and is appended after the kernel. Parity planes are built
    # HERE (XLA-side strided slices): Mosaic rejects strided vector
    # extracts in-kernel, and planes make every kernel slice contiguous.
    xe = xp[:, :hp_eff, :wp_eff, :]

    grid = (b * nrows * nc,)

    def idx(i):
        return (i // (nrows * nc), (i // nc) % nrows, i % nc)

    in_specs, args = [], []
    for (pr, pc), (dmax, emax) in geo.items():
        plane = xe[:, pr::sh, pc::sw, :] if (sh, sw) != (1, 1) else xe
        wpl = wo + emax
        in_specs.append(
            pl.BlockSpec(
                (1, to, wpl, cchunk),
                lambda i: (idx(i)[0], idx(i)[1], 0, idx(i)[2]),
            )
        )
        args.append(plane)
        if dmax:
            # Overlap rows [ (i+1)*to, +dmax ) as an aligned block of
            # height dmax (to % dmax == 0 via _plan).
            in_specs.append(
                pl.BlockSpec(
                    (1, dmax, wpl, cchunk),
                    lambda i, d=dmax: (
                        idx(i)[0], (idx(i)[1] + 1) * (to // d), 0, idx(i)[2]
                    ),
                )
            )
            args.append(plane)
    in_specs.append(
        pl.BlockSpec(
            (1, to, wo, cchunk), lambda i: (idx(i)[0], idx(i)[1], 0, idx(i)[2])
        )
    )
    args.append(dy)

    out_specs, out_shapes = [], []
    for (cr, cc_), (dmax, emax) in geo.items():
        wc = wo + emax
        out_specs.append(
            pl.BlockSpec(
                (1, to, wc, cchunk),
                lambda i: (idx(i)[0], idx(i)[1], 0, idx(i)[2]),
            )
        )
        out_shapes.append(jax.ShapeDtypeStruct((b, ho, wc, c), dy.dtype))
        if dmax:
            # 4-D, chunk-flattened: [b, nrows*dmax, wc, c] — a 5-D
            # [b, nrows, dmax, ...] form was assigned VMEM memory space
            # by the compiler and stack-allocated the whole array.
            out_specs.append(
                pl.BlockSpec(
                    (1, dmax, wc, cchunk),
                    lambda i: (idx(i)[0], idx(i)[1], 0, idx(i)[2]),
                )
            )
            out_shapes.append(
                jax.ShapeDtypeStruct((b, nrows * dmax, wc, c), dy.dtype)
            )

    outs = pl.pallas_call(
        functools.partial(
            _pool_bwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw, to=to, wo=wo
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(*args)
    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]

    # Reassemble: fold tails into each class, then interleave the classes
    # with one strided-set each (sh*sw sub-arrays, not kh*kw full-res
    # scatter terms).
    dxe = jnp.zeros((b, hp_eff, wp_eff, c), dy.dtype)
    oi = 0
    for (cr, cc_), (dmax, emax) in geo.items():
        main = outs[oi]
        oi += 1
        if dmax:
            tails = outs[oi]
            oi += 1
            wc = wo + emax
            # Chunk i's tail rows are class rows (i+1)*to + [0, dmax) —
            # the next chunk's first rows (to >= dmax via _plan's choices).
            # Lay the tails on a to-strided grid shifted by to, add, crop
            # back to the class extent ho + dmax.
            sub = jnp.concatenate(
                [main, jnp.zeros((b, to, wc, c), dy.dtype)], axis=1
            )
            flat = jnp.pad(
                tails.reshape(b, nrows, dmax, wc, c),
                ((0, 0), (0, 0), (0, to - dmax), (0, 0), (0, 0)),
            )
            flat = flat.reshape(b, nrows * to, wc, c)
            sub = sub.at[:, to : to + ho].add(flat)
            sub = sub[:, : ho + dmax]
        else:
            sub = main
        # Class (cr, cc_) rows/cols of dxe are exactly sub's extent:
        # ceil((hp_eff - cr)/sh) == ho + dmax, same in W.
        dxe = dxe.at[:, cr :: sh, cc_ :: sw, :].add(sub)
    if hp_eff < hp or wp_eff < wp:
        dxe = jnp.pad(
            dxe,
            (
                (0, 0),
                (0, hp - hp_eff),
                (0, wp - wp_eff),
                (0, 0),
            ),
        )
    return dxe


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def max_pool(x, kh, kw, sh, sw, ph, pw):
    """Max pool (−inf edge padding, torch ``MaxPool2d`` parity) whose
    backward is the one-pass Pallas kernel. Forward ==
    ``lax.reduce_window(max)`` — the same values every other path here
    produces; only the backward's tie rule (first-max-wins) differs from
    the shifted-maximum tree's maximum-chain subgradients, which callers
    gate on (see ``max_pool_s1_valid``)."""
    return _fwd_val(x, kh, kw, sh, sw, ph, pw)


def _fwd_val(x, kh, kw, sh, sw, ph, pw):
    neg = jnp.asarray(_NEG, x.dtype)
    xp = lax.pad(x, neg, ((0, 0, 0), (ph, ph, 0), (pw, pw, 0), (0, 0, 0)))
    return lax.reduce_window(
        xp, neg, lax.max, (1, kh, kw, 1), (1, sh, sw, 1), "valid"
    )


def _fwd(x, kh, kw, sh, sw, ph, pw):
    # Residual is x alone: the backward recomputes each window's winner
    # in-register (online argmax), so the pooled output never needs to
    # be saved or re-read.
    return _fwd_val(x, kh, kw, sh, sw, ph, pw), x


def _bwd(kh, kw, sh, sw, ph, pw, x, dy):
    neg = jnp.asarray(_NEG, x.dtype)
    xp = lax.pad(x, neg, ((0, 0, 0), (ph, ph, 0), (pw, pw, 0), (0, 0, 0)))
    dxp = _bwd_padded(xp, dy, kh=kh, kw=kw, sh=sh, sw=sw)
    h, w = x.shape[1], x.shape[2]
    return (dxp[:, ph : ph + h, pw : pw + w, :],)


max_pool.defvjp(_fwd, _bwd)
