"""Model FLOP accounting + MFU.

The north-star target for this framework is stated in MFU (BASELINE.json:
>=45% on the flagship configs), but the reference reports only images/sec —
it has no FLOP counter. Here we count *model* FLOPs analytically from the
jaxpr of the forward pass (convs + matmuls; elementwise/BN ignored, <1%),
so the number is independent of implementation tricks: the MXU-packed conv
(ops/fastconv.py) executes ~1.7x more device FLOPs than the model math
needs, and counting those would flatter MFU. The count is taken with
``MPI4DL_TPU_CONV_IMPL=xla`` for the same reason.

Training FLOPs per example use the standard 3x rule (forward + input-grad +
weight-grad each cost ~one forward; e.g. the PaLM appendix convention):

    train_flops = 3 * forward_flops

MFU = train_flops * images_per_sec / peak_flops(device).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Sequence

import jax
import numpy as np


# Peak dense bf16 FLOP/s per chip (public spec sheets). device_kind strings
# as reported by jax.devices()[0].device_kind. Longest-prefix match so lite
# variants never fall through to their full-size generation.
_PEAK_FLOPS = {
    "TPU v4 lite": 138e12,  # v4i
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,  # Trillium
    "TPU v6e": 918e12,
}


def peak_flops(device=None) -> float | None:
    """Peak bf16 FLOP/s for ``device`` (default: first visible device), or
    None when unknown (CPU, unlisted TPU generations)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for name in sorted(_PEAK_FLOPS, key=len, reverse=True):
        if kind.startswith(name):
            return _PEAK_FLOPS[name]
    return None


def _eqn_flops(eqn) -> float:
    """FLOPs of one jaxpr equation (matmul-class primitives only)."""
    prim = eqn.primitive.name
    if prim == "conv_general_dilated":
        out = eqn.outvars[0].aval
        rhs = eqn.invars[1].aval
        dnums = eqn.params["dimension_numbers"]
        # rhs spatial extents + input-feature dim from the kernel spec.
        kernel_spatial = [rhs.shape[d] for d in dnums.rhs_spec[2:]]
        cin = rhs.shape[dnums.rhs_spec[1]]
        # The kernel's input-feature dim is ALREADY Cin/feature_group_count
        # in XLA's convention, so grouped/depthwise convs need no extra
        # divisor here.
        return 2.0 * out.size * float(np.prod(kernel_spatial)) * cin
    if prim == "dot_general":
        lhs, rhs = (v.aval for v in eqn.invars[:2])
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        batch = float(np.prod([lhs.shape[d] for d in lb], initial=1.0))
        k = float(np.prod([lhs.shape[d] for d in lc], initial=1.0))
        m = float(
            np.prod(
                [s for d, s in enumerate(lhs.shape) if d not in set(lc) | set(lb)],
                initial=1.0,
            )
        )
        n = float(
            np.prod(
                [s for d, s in enumerate(rhs.shape) if d not in set(rc) | set(rb)],
                initial=1.0,
            )
        )
        return 2.0 * batch * m * n * k
    return 0.0


def _subjaxprs(val):
    """Yield every jaxpr reachable from one eqn param value: a bare jaxpr, a
    ClosedJaxpr, or a tuple/list of either (``cond``'s ``branches``,
    ``custom_*`` residuals). Misses would silently deflate the MFU
    denominator (ADVICE r2), so unknown shapes fall through to zero yields
    only when they genuinely hold no jaxpr."""
    if hasattr(val, "eqns"):
        yield val
    elif hasattr(val, "jaxpr"):
        yield val.jaxpr
    elif isinstance(val, (tuple, list)):
        for item in val:
            yield from _subjaxprs(item)


def _jaxpr_flops(jaxpr) -> float:
    total = 0.0
    for eqn in jaxpr.eqns:
        total += _eqn_flops(eqn)
        # Recurse into every call-like primitive (pjit, remat, custom_vjp,
        # scan bodies × length, cond/while branches, etc.). ``cond``
        # branches: count the MAX branch — an upper bound that matches the
        # convention of counting what the model would execute; for
        # same-shape branches (the only use in this codebase's models: none
        # today) the branches cost the same anyway.
        for name, val in eqn.params.items():
            subs = list(_subjaxprs(val))
            if not subs:
                continue
            if name == "branches":
                inner = max(_jaxpr_flops(j) for j in subs)
            else:
                inner = sum(_jaxpr_flops(j) for j in subs)
            if eqn.primitive.name == "scan":
                inner *= eqn.params.get("length", 1)
            total += inner
    return total


def forward_flops(cells: Sequence[Any], x_shape, dtype=None) -> float:
    """Model forward FLOPs for one batch of shape ``x_shape`` through the
    (non-spatial) cell list. Counted on the stock conv lowering so packing
    inflation never flatters the number."""
    import jax.numpy as jnp

    from mpi4dl_tpu.parallel.partition import init_cells

    dtype = dtype or jnp.float32
    x = jax.ShapeDtypeStruct(tuple(x_shape), dtype)

    prev = os.environ.get("MPI4DL_TPU_CONV_IMPL")
    os.environ["MPI4DL_TPU_CONV_IMPL"] = "xla"
    # Packed-layout cells execute MORE device FLOPs than the model math by
    # design (scattered kernels), and PackedConv has no xla-impl escape —
    # counting them would overstate MFU (ADVICE r2). PackedConv checks this
    # env at trace time and raises, forcing callers to pass the logical
    # (stock-layout) twin.
    os.environ["MPI4DL_TPU_COUNTING_FLOPS"] = "1"
    try:
        # Init OUTSIDE the counted jaxpr (init traces each cell's forward,
        # which would triple-count every conv).
        params = jax.eval_shape(
            lambda xx: init_cells(cells, jax.random.PRNGKey(0), xx), x
        )

        def run(vs, xx):
            for cell, v in zip(cells, vs):
                xx = cell.apply(v, xx)
            return xx

        jaxpr = jax.make_jaxpr(run)(params, x)
    finally:
        os.environ.pop("MPI4DL_TPU_COUNTING_FLOPS", None)
        if prev is None:
            os.environ.pop("MPI4DL_TPU_CONV_IMPL", None)
        else:
            os.environ["MPI4DL_TPU_CONV_IMPL"] = prev
    return _jaxpr_flops(jaxpr.jaxpr)


def train_flops_per_image(cells: Sequence[Any], image_size: int, dtype=None) -> float:
    """3x-forward training FLOPs for ONE image (batch-independent)."""
    fwd = forward_flops(cells, (1, image_size, image_size, 3), dtype)
    return 3.0 * fwd


def mfu(images_per_sec: float, flops_per_image: float, n_devices: int = 1,
        device=None) -> float | None:
    """Model FLOP utilization in [0, 1], or None off-TPU/unknown device."""
    peak = peak_flops(device)
    if not peak:
        return None
    return images_per_sec * flops_per_image / (peak * n_devices)
