"""mpi4dl_tpu — a TPU-native framework with the capabilities of MPI4DL.

MPI4DL (reference: /root/reference, the OSU ``torchgems`` package) trains
out-of-core CNNs on very-high-resolution images by composing five parallelism
dimensions: Layer (LP), Pipeline (PP), Spatial (SP, image-tile sharding with
halo exchange), Data (DP), and GEMS bidirectional parallelism.

This package re-designs those capabilities TPU-first:

- one ``jax.sharding.Mesh`` with axes ``("data", "pipe", "tile_h", "tile_w")``
  replaces the reference's MPI process groups (``src/torchgems/comm.py``);
- the LP/PP send/recv pipeline (``src/torchgems/mp_pipeline.py``) becomes a
  collective-permute GPipe schedule inside one jitted SPMD program
  (:mod:`mpi4dl_tpu.parallel.pipeline`);
- halo-exchange spatial convolution (``src/torchgems/spatial.py``) becomes
  ``shard_map`` + ``lax.ppermute`` neighbor shifts (:mod:`mpi4dl_tpu.ops.spatial`);
- GEMS-MASTER (``src/torchgems/gems_master.py``) becomes a mirrored dual
  pipeline in the same program (:mod:`mpi4dl_tpu.parallel.gems`);
- gradient sync (``SyncAllreduce``) becomes ``psum`` over mesh axes.
"""

__version__ = "0.1.0"

from mpi4dl_tpu import utils  # noqa: F401
from mpi4dl_tpu.config import ParallelConfig  # noqa: F401
