"""mpi4dl_tpu — a TPU-native framework with the capabilities of MPI4DL.

MPI4DL (reference: /root/reference, the OSU ``torchgems`` package) trains
out-of-core CNNs on very-high-resolution images by composing five parallelism
dimensions: Layer (LP), Pipeline (PP), Spatial (SP, image-tile sharding with
halo exchange), Data (DP), and GEMS bidirectional parallelism.

This package re-designs those capabilities TPU-first:

- one ``jax.sharding.Mesh`` with axes ``("data", "pipe", "tile_h", "tile_w")``
  replaces the reference's MPI process groups (``src/torchgems/comm.py``);
- halo-exchange spatial convolution (``src/torchgems/spatial.py``) becomes
  ``shard_map`` + ``lax.ppermute`` neighbor shifts
  (:mod:`mpi4dl_tpu.parallel.halo`, :mod:`mpi4dl_tpu.ops.layers`);
- the LP/PP send/recv pipeline (``src/torchgems/mp_pipeline.py``) becomes a
  spatial front phase + a scan/switch/ppermute GPipe schedule inside one
  jitted SPMD program (:class:`mpi4dl_tpu.parallel.pipeline.PipelineTrainer`);
- GEMS-MASTER (``src/torchgems/gems_master.py``) becomes the mirrored dual
  schedule :class:`mpi4dl_tpu.parallel.pipeline.GemsMasterTrainer`;
- gradient sync (``SyncAllreduce``) disappears into ``jax.grad`` + ``psum``
  (:mod:`mpi4dl_tpu.train`);
- stage partitioning / shape discovery (``model_generator``) becomes
  ``jax.eval_shape`` (:mod:`mpi4dl_tpu.parallel.partition`).
"""

__version__ = "0.1.0"

from mpi4dl_tpu import utils  # noqa: F401
from mpi4dl_tpu.config import ParallelConfig  # noqa: F401
