"""Profiling / step timing.

The reference's observability is paired CUDA events around each batch plus
prints (``benchmark_amoebanet_sp.py:322-367``; SURVEY.md §5.1). The TPU
equivalents:

- :class:`StepTimer` — host wall-clock per step with ``block_until_ready``
  (async dispatch means a bare ``time.time()`` measures nothing), tracking
  the same statistics every reference benchmark prints (per-step seconds,
  images/sec, mean/median) plus p50/p90/p99 tail percentiles — a serving
  path lives and dies by tail latency, not means;
- :func:`percentiles` — the shared percentile helper (linear interpolation
  on the sorted sample, numpy's default method) used by :class:`StepTimer`
  and the serving load generator;
- :func:`trace` — ``jax.profiler`` trace context writing a TensorBoard/XProf
  trace directory (device timelines, HLO cost, ICI collectives); enabled by
  path or the ``MPI4DL_TPU_TRACE_DIR`` env var, no-op otherwise.
"""

from __future__ import annotations

import contextlib
import os
import statistics
import time
from typing import Any


def percentiles(values, pcts=(50, 90, 99)) -> dict:
    """``{"p50": v, ...}`` by linear interpolation on the sorted sample
    (numpy's default "linear" method, hand-rolled so callers measuring
    latency need no array round-trip). Empty input → empty dict."""
    vals = sorted(values)
    if not vals:
        return {}
    out = {}
    for p in pcts:
        rank = (len(vals) - 1) * p / 100.0
        lo = int(rank)
        hi = min(lo + 1, len(vals) - 1)
        out[f"p{p:g}"] = vals[lo] + (vals[hi] - vals[lo]) * (rank - lo)
    return out


class StepTimer:
    """Times steps and accumulates throughput stats.

    Usage::

        timer = StepTimer(batch_size=B, warmup=1)
        for ... :
            with timer.step(result_to_block_on_setter) as rec:
                state, metrics = trainer.train_step(...)
                rec(metrics)           # anything with .block_until_ready leaves
        print(timer.summary())
    """

    def __init__(self, batch_size: int, warmup: int = 1):
        self.batch_size = batch_size
        self.warmup = warmup
        self.times: list[float] = []
        self._seen = 0

    @contextlib.contextmanager
    def step(self):
        import jax

        out: list[Any] = []
        t0 = time.perf_counter()
        yield out.append
        if out:
            jax.block_until_ready(out[-1])
        dt = time.perf_counter() - t0
        self._seen += 1
        if self._seen > self.warmup:
            self.times.append(dt)

    @property
    def images_per_sec(self) -> list[float]:
        return [self.batch_size / t for t in self.times]

    def summary(self) -> dict:
        if not self.times:
            return {"steps": 0}
        ips = self.images_per_sec
        out = {
            "steps": len(self.times),
            "step_time_mean_s": statistics.mean(self.times),
            "step_time_median_s": statistics.median(self.times),
            "images_per_sec_mean": statistics.mean(ips),
            "images_per_sec_median": statistics.median(ips),
        }
        for k, v in percentiles(self.times).items():
            out[f"step_time_{k}_s"] = v
        return out


@contextlib.contextmanager
def trace(logdir: str | None = None):
    """``jax.profiler.trace`` context. ``logdir`` (or ``MPI4DL_TPU_TRACE_DIR``)
    unset → no-op."""
    logdir = logdir or os.environ.get("MPI4DL_TPU_TRACE_DIR")
    if not logdir:
        yield None
        return
    import jax

    with jax.profiler.trace(logdir):
        yield logdir
