"""Profiling / step timing.

The reference's observability is paired CUDA events around each batch plus
prints (``benchmark_amoebanet_sp.py:322-367``; SURVEY.md §5.1). The TPU
equivalents:

- :class:`StepTimer` — host wall-clock per step with ``block_until_ready``
  (async dispatch means a bare ``time.time()`` measures nothing), tracking
  the same statistics every reference benchmark prints (per-step seconds,
  images/sec, mean/median) plus p50/p90/p99 tail percentiles — a serving
  path lives and dies by tail latency, not means;
- :func:`percentiles` — the shared percentile helper (linear interpolation
  on the sorted sample, numpy's default method) used by :class:`StepTimer`
  and the serving load generator;
- :func:`trace` — ``jax.profiler`` trace context writing a TensorBoard/XProf
  trace directory (device timelines, HLO cost, ICI collectives); enabled by
  path or the ``MPI4DL_TPU_TRACE_DIR`` env var, no-op otherwise;
- :func:`annotate_step` — ``jax.profiler.StepTraceAnnotation`` wrapper the
  train/serve dispatch paths use, so XProf step boundaries carry the same
  step/batch ids as the telemetry span log
  (:mod:`mpi4dl_tpu.telemetry.spans`) and the two can be joined;
- :func:`capture` — programmatic trace capture: wraps :func:`trace` around
  N annotated, fully-blocked invocations of a step function and returns a
  :class:`Capture` whose :meth:`Capture.attribution` parses the emitted
  Chrome trace into a compute/collective/transfer/host-gap device-time
  report (:mod:`mpi4dl_tpu.analysis.trace`) — the runtime counterpart of
  hlolint's static overlap rule.

:class:`StepTimer` optionally publishes into a telemetry registry
(:mod:`mpi4dl_tpu.telemetry`): per-step ``train_step_seconds`` histogram
observations, a ``train_steps_total`` counter, and a
``train_images_per_sec`` gauge — the training side of the unified metric
catalog (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import statistics
import tempfile
import time
from typing import Any


def percentiles(values, pcts=(50, 90, 99)) -> dict:
    """``{"p50": v, ...}`` by linear interpolation on the sorted sample
    (numpy's default "linear" method, hand-rolled so callers measuring
    latency need no array round-trip). Empty input → empty dict."""
    vals = sorted(values)
    if not vals:
        return {}
    out = {}
    for p in pcts:
        rank = (len(vals) - 1) * p / 100.0
        lo = int(rank)
        hi = min(lo + 1, len(vals) - 1)
        out[f"p{p:g}"] = vals[lo] + (vals[hi] - vals[lo]) * (rank - lo)
    return out


class StepTimer:
    """Times steps and accumulates throughput stats.

    ``step()`` takes no argument — the context target (``as rec``) IS the
    setter for the result to block on::

        timer = StepTimer(batch_size=B, warmup=1)
        for ... :
            with timer.step() as rec:
                state, metrics = trainer.train_step(...)
                rec(metrics)           # anything with .block_until_ready leaves
        print(timer.summary())

    ``registry``: an optional :class:`mpi4dl_tpu.telemetry.MetricsRegistry`;
    each post-warmup step then also lands in the cataloged ``train_*``
    metrics (histogram + counter + throughput gauge).

    ``watchdog``: an optional :class:`mpi4dl_tpu.telemetry.Watchdog`; the
    timer then reports step begin/completion to it, so a hung step (no
    completion within K× the rolling p99) trips the same liveness
    machinery the serving engine uses.
    """

    def __init__(
        self, batch_size: int, warmup: int = 1, registry=None, watchdog=None
    ):
        self.batch_size = batch_size
        self.warmup = warmup
        self.times: list[float] = []
        self._seen = 0
        self._metrics = None
        self._watchdog = watchdog
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._metrics = (
                telemetry.declare(registry, "train_step_seconds"),
                telemetry.declare(registry, "train_steps_total"),
                telemetry.declare(registry, "train_images_per_sec"),
            )

    @contextlib.contextmanager
    def step(self):
        import jax

        out: list[Any] = []
        if self._watchdog is not None:
            self._watchdog.begin()
        dt = None
        try:
            t0 = time.perf_counter()
            yield out.append
            if out:
                jax.block_until_ready(out[-1])
            dt = time.perf_counter() - t0
        finally:
            if self._watchdog is not None:
                self._watchdog.done(dt)
        self._seen += 1
        if self._seen > self.warmup:
            self.times.append(dt)
            if self._metrics is not None:
                hist, total, ips = self._metrics
                hist.observe(dt)
                total.inc()
                ips.set(self.batch_size / dt if dt > 0 else 0.0)

    @property
    def images_per_sec(self) -> list[float]:
        # dt == 0 (a clock too coarse for a trivial step) reports 0.0
        # throughput — same convention as the telemetry gauge above —
        # instead of raising ZeroDivisionError in summary().
        return [self.batch_size / t if t > 0 else 0.0 for t in self.times]

    def summary(self) -> dict:
        if not self.times:
            return {"steps": 0}
        ips = self.images_per_sec
        out = {
            "steps": len(self.times),
            "step_time_mean_s": statistics.mean(self.times),
            "step_time_median_s": statistics.median(self.times),
            "images_per_sec_mean": statistics.mean(ips),
            "images_per_sec_median": statistics.median(ips),
        }
        for k, v in percentiles(self.times).items():
            out[f"step_time_{k}_s"] = v
        return out


@contextlib.contextmanager
def trace(logdir: str | None = None):
    """``jax.profiler.trace`` context. ``logdir`` (or ``MPI4DL_TPU_TRACE_DIR``)
    unset → no-op."""
    logdir = logdir or os.environ.get("MPI4DL_TPU_TRACE_DIR")
    if not logdir:
        yield None
        return
    import jax

    with jax.profiler.trace(logdir):
        yield logdir


@contextlib.contextmanager
def annotate_step(name: str, step: "int | None" = None):
    """``jax.profiler.StepTraceAnnotation`` around one dispatch, so XProf
    traces (:func:`trace`) slice the device timeline at the same step ids
    the telemetry span log records. Host-side step counters (not device
    arrays) only — reading a traced scalar here would force a sync.
    Degrades to a no-op if the profiler annotation API is unavailable."""
    import jax

    try:
        ann = (
            jax.profiler.StepTraceAnnotation(name, step_num=step)
            if step is not None
            else jax.profiler.StepTraceAnnotation(name)
        )
    except Exception:  # noqa: BLE001 — observability must not break dispatch
        yield
        return
    with ann:
        yield


#: Annotation name :func:`capture` wraps around each step. Distinct from
#: the dispatch-path names ("mpi4dl_train_step"/"mpi4dl_serve_batch") so
#: a capture window strictly CONTAINS each step's device work (the block
#: happens inside the annotation), even when the step function annotates
#: its own async dispatch internally.
CAPTURE_STEP_NAME = "mpi4dl_capture"


@dataclasses.dataclass
class Capture:
    """One finished :func:`capture`: where the trace landed, plus the
    host-measured wall time of each annotated step (the independent
    ground truth the attribution's per-step sums are checked against)."""

    trace_dir: str
    step_name: str
    n_steps: int
    step_times_s: list

    def attribution(self, registry=None, program: str = "capture") -> dict:
        """Parse the emitted Chrome trace into the per-step
        compute/collective/transfer/host-gap report
        (:func:`mpi4dl_tpu.analysis.trace.analyze_trace_dir`); with a
        ``registry``, also publish the cataloged ``trace_*`` gauges
        under ``program``."""
        from mpi4dl_tpu.analysis.trace import (
            analyze_trace_dir,
            publish_attribution,
        )

        summary = analyze_trace_dir(self.trace_dir, step_name=self.step_name)
        summary["host_step_times_s"] = list(self.step_times_s)
        if registry is not None:
            publish_attribution(summary, registry, program=program)
        return summary


def capture(
    step_fn,
    steps: int = 3,
    logdir: "str | None" = None,
    name: str = CAPTURE_STEP_NAME,
) -> Capture:
    """Trace ``steps`` invocations of ``step_fn(i)`` under
    ``jax.profiler.trace``, each wrapped in a step annotation with the
    result blocked to completion INSIDE the annotation — so every step's
    device work falls within its window and the attribution buckets sum
    to the step wall time. ``logdir=None`` captures into a fresh temp
    directory (reported on :attr:`Capture.trace_dir`)."""
    import jax

    if logdir is None:
        logdir = tempfile.mkdtemp(prefix="mpi4dl-capture-")
    times: list[float] = []
    with trace(logdir):
        for i in range(int(steps)):
            t0 = time.perf_counter()
            with annotate_step(name, i):
                out = step_fn(i)
                if out is not None:
                    jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
    return Capture(
        trace_dir=logdir, step_name=name, n_steps=int(steps),
        step_times_s=times,
    )
