"""Multi-tenant QoS: quotas, weighted-fair admission, fleet-wide dedupe.

The serving stack partitions *latency* by SLO class (PR 11) and
survives *deaths* (PR 12), but until this subsystem nothing partitioned
*capacity*: one tenant's flood could fill every batch slot and burn
every other tenant's error budget. ``mpi4dl_tpu.tenancy`` is that
missing layer, enforced at both admission edges:

- :class:`Tenant` / :func:`parse_tenants` — the tenant model
  (``NAME=RPS:BURST[:WEIGHT][@CLASSES]``), parsed exactly like the SLO
  class spec it composes with.
- :class:`TokenBucket` / :class:`TenantAdmission` — per-tenant
  token-bucket quotas applied by the fleet router AND the engine; an
  over-quota flood is shed with a typed :class:`QuotaExceededError`
  whose ``retry_after_s`` is the bucket's own refill time, BEFORE the
  flood occupies a queue slot.
- :class:`DeficitRoundRobin` — the deficit-weighted-round-robin fill
  the per-class EDF heaps use across tenants, so batch formation cannot
  be monopolized even by in-quota traffic.
- :mod:`mpi4dl_tpu.tenancy.dedupe` — rendezvous pinning + served-cache
  fan-out, closing the docs/FLEET.md double-execute residual for
  ``retried:true`` requests racing a router death.
"""

from mpi4dl_tpu.tenancy.model import (  # noqa: F401
    DEFAULT_TENANT,
    DeficitRoundRobin,
    QuotaExceededError,
    Tenant,
    TenantAdmission,
    TokenBucket,
    default_tenants,
    normalize_tenants,
    parse_tenants,
)
from mpi4dl_tpu.tenancy.dedupe import pin_order, pin_replica  # noqa: F401
