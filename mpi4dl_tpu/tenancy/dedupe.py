"""Fleet-wide exactly-once for retried requests: pin + probe.

docs/FLEET.md documented a double-execute residual: a ``retried:true``
request parked in a SURVIVING router's pending queue for longer than a
successor's ``replay_grace_s`` is invisible to both the successor's
served-cache poll and the replay dedupe — the successor re-dispatches
the journal orphan while the survivor still holds a live copy, and the
two copies can land on DIFFERENT replicas, each executing once.

The fix is two independent mechanisms that compose:

1. **Probe** — before ANY dispatch of a record marked ``retried`` (set
   from the client's ``retried:true`` RPC field, or by journal replay),
   the router fans a ``/served`` probe across every reachable replica.
   A voucher anywhere means some earlier attempt already executed: the
   router completes from that replica's idempotency cache instead of
   dispatching (``fleet_requests_total{outcome="served_cached"}``; a
   replayed orphan additionally counts
   ``fleet_router_journal_replays_total{outcome="deduped"}``).
2. **Pin** — when the probe finds nothing (the race window: neither
   copy has reached an engine yet), retried dispatches are pinned to
   the RENDEZVOUS replica for the trace id. Racing dispatches from any
   number of routers then land on the SAME engine, whose
   ``_ServedCache`` either returns the cached payload or joins the
   in-flight future — execution is at-most-once on that replica by
   construction.

What remains (the honest residual, docs/FLEET.md): if the pinned
replica dies BETWEEN the racing dispatches, the survivors re-pin to the
next rendezvous choice whose cache never saw the first attempt —
at-least-once re-execution of an idempotent inference, never a lost or
double-completed future.
"""

from __future__ import annotations

import hashlib


def pin_order(trace_id: str, names) -> "list[str]":
    """Rendezvous (highest-random-weight) order of ``names`` for this
    trace id: every router computes the same ranking from the same
    membership with no coordination, and a dead head falls through to
    the same successor everywhere."""
    return sorted(
        (str(n) for n in names),
        key=lambda n: hashlib.sha1(
            f"{n}\x00{trace_id}".encode()
        ).digest(),
        reverse=True,
    )


def pin_replica(trace_id: str, names) -> "str | None":
    """The rendezvous head — where every retried dispatch of this trace
    id must land. None when the membership is empty."""
    order = pin_order(trace_id, names)
    return order[0] if order else None
