"""Tenant model, token-bucket quotas, and the DWRR fair-fill policy.

A :class:`Tenant` is to capacity what an
:class:`~mpi4dl_tpu.serve.scheduler.SLOClass` is to latency: a named
policy identity that rides every metric label and CLI token. The spec
grammar mirrors ``parse_slo_classes``::

    NAME=RPS:BURST[:WEIGHT][@CLASSES]

    bulk=200:400            # 200 req/s sustained, bursts to 400
    tight=50:100:4@tight    # 4x the fair-share weight, tight class only
    free=none               # declared but unlimited (weight/classes ok)

``RPS`` is the sustained refill rate of the tenant's token bucket,
``BURST`` its capacity (tokens). ``WEIGHT`` is the tenant's share in
the scheduler's deficit-weighted-round-robin batch fill (default 1).
``@CLASSES`` (``+``-separated) restricts which SLO classes the tenant
may submit to; empty means all. A tenant named ``default`` is always
present (implicitly unlimited) — untenanted submissions land there, so
a tenancy-enabled engine serves legacy clients unchanged.

Enforcement is :class:`TenantAdmission`: one instance per admission
edge (the fleet router's front door and the engine's ``submit``). An
over-quota admission raises :class:`QuotaExceededError` carrying
``retry_after_s`` computed from the bucket's OWN refill rate — not the
batch-cadence EMA the queue-full path uses — so a compliant retrying
client converges to exactly its quota instead of thundering.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time

#: Tenant names must survive as metric label values and CLI tokens.
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

DEFAULT_TENANT = "default"


class QuotaExceededError(RuntimeError):
    """A tenant exceeded its token-bucket quota at an admission edge.

    Deliberately NOT a :class:`~mpi4dl_tpu.serve.QueueFullError`
    subclass (that would import the engine into this leaf module): it
    carries the same ``retry_after_s``/``slo_class``/``shed`` attribute
    shape so every retry/backoff path can treat the two uniformly, plus
    the ``tenant`` that blew its budget — the label forensics and 429
    payloads carry."""

    def __init__(self, msg: str, tenant: str,
                 retry_after_s: "float | None" = None,
                 slo_class: "str | None" = None):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = retry_after_s
        self.slo_class = slo_class
        self.shed = True


@dataclasses.dataclass(frozen=True)
class Tenant:
    """One named tenant: quota + fair-share weight + class allowlist.

    rate_rps: sustained token refill rate; None = unlimited (no bucket).
    burst: bucket capacity in tokens; defaults to ``rate_rps`` (one
        second of sustained rate) when a rate is set.
    weight: deficit-round-robin share in batch formation (> 0).
    classes: SLO class names this tenant may submit to; () = all.
    """

    name: str
    rate_rps: "float | None" = None
    burst: "float | None" = None
    weight: float = 1.0
    classes: "tuple[str, ...]" = ()

    def __post_init__(self):
        if not _NAME_RE.match(self.name):
            raise ValueError(
                f"tenant name {self.name!r} must match {_NAME_RE.pattern}"
            )
        if self.rate_rps is not None and self.rate_rps <= 0:
            raise ValueError(
                f"tenant {self.name}: rate must be > 0, got {self.rate_rps}"
            )
        if self.rate_rps is not None and self.burst is None:
            object.__setattr__(self, "burst", float(self.rate_rps))
        if self.burst is not None and self.burst < 1:
            raise ValueError(
                f"tenant {self.name}: burst must be >= 1, got {self.burst}"
            )
        if self.weight <= 0:
            raise ValueError(
                f"tenant {self.name}: weight must be > 0, got {self.weight}"
            )


def default_tenants() -> "tuple[Tenant, ...]":
    """The implicit single-tenant configuration: one unlimited
    ``default`` tenant — exactly the pre-tenancy behavior."""
    return (Tenant(DEFAULT_TENANT),)


def parse_tenants(spec: str) -> "tuple[Tenant, ...]":
    """``"bulk=200:400,tight=50:100:4@tight"`` → Tenant tuple.

    Per tenant: ``NAME=RPS:BURST[:WEIGHT][@CLASSES]`` — ``RPS`` of
    ``none`` declares an unlimited tenant (``BURST`` then omitted:
    ``NAME=none[:WEIGHT][@CLASSES]``). A ``default`` tenant is appended
    (unlimited) when the spec does not declare one, so untenanted
    submissions always resolve."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad tenant {part!r}: expected NAME=RPS:BURST"
                "[:WEIGHT][@CLASSES]"
            )
        name, rest = part.split("=", 1)
        classes: "tuple[str, ...]" = ()
        if "@" in rest:
            rest, cls = rest.split("@", 1)
            classes = tuple(
                c.strip() for c in cls.split("+") if c.strip()
            )
        toks = [t.strip() for t in rest.split(":")]
        if toks and toks[0] in ("none", ""):
            rate = burst = None
            weight = float(toks[1]) if len(toks) > 1 and toks[1] else 1.0
        else:
            if len(toks) < 2 or not toks[1]:
                raise ValueError(
                    f"tenant {name.strip()!r}: RPS needs a BURST "
                    f"(NAME=RPS:BURST[:WEIGHT]), got {rest!r}"
                )
            rate = float(toks[0])
            burst = float(toks[1])
            weight = float(toks[2]) if len(toks) > 2 and toks[2] else 1.0
        out.append(Tenant(
            name=name.strip(), rate_rps=rate, burst=burst,
            weight=weight, classes=classes,
        ))
    if not out:
        raise ValueError(f"no tenants in {spec!r}")
    names = [t.name for t in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate tenant names in {spec!r}")
    if DEFAULT_TENANT not in names:
        out.append(Tenant(DEFAULT_TENANT))
    return tuple(out)


def normalize_tenants(tenants) -> "tuple[Tenant, ...] | None":
    """Constructor input → Tenant tuple, or None (tenancy OFF — the
    zero-overhead path). A string parses; a sequence is validated and
    gains the implicit ``default`` tenant."""
    if tenants is None:
        return None
    if isinstance(tenants, str):
        return parse_tenants(tenants)
    out = list(tenants)
    if not out:
        return None
    names = [t.name for t in out]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate tenant names: {names}")
    if DEFAULT_TENANT not in names:
        out.append(Tenant(DEFAULT_TENANT))
    return tuple(out)


class TokenBucket:
    """Classic token bucket: ``rate_rps`` tokens/s refill up to
    ``burst``. ``try_take`` is the whole API — atomic take-or-hint,
    where the hint is the exact wall time until the missing tokens
    refill (what a compliant client should sleep)."""

    def __init__(self, rate_rps: float, burst: float,
                 clock=time.monotonic):
        self.rate_rps = float(rate_rps)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._stamp = clock()
        self._lock = threading.Lock()

    def try_take(self, n: int = 1) -> "float | None":
        """Take ``n`` tokens: None on success, else the seconds until
        the bucket will hold ``n`` (the ``retry_after_s`` hint)."""
        now = self._clock()
        with self._lock:
            self._tokens = min(
                self.burst,
                self._tokens + (now - self._stamp) * self.rate_rps,
            )
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return None
            return (n - self._tokens) / self.rate_rps

    def tokens(self) -> float:
        """Current level (refreshed) — the quota gauge's value."""
        now = self._clock()
        with self._lock:
            self._tokens = min(
                self.burst,
                self._tokens + (now - self._stamp) * self.rate_rps,
            )
            self._stamp = now
            return self._tokens


class TenantAdmission:
    """Per-tenant quota + class-allowlist enforcement for one edge.

    One instance guards one admission point (the fleet router's front
    door, or the engine's ``submit``) — each edge refills its own
    buckets, so with R routers a tenant's effective fleet-wide rate is
    R x its configured RPS unless the operator divides the spec (the
    documented per-edge semantics; see docs/SERVING.md).
    """

    def __init__(self, tenants, registry=None, clock=time.monotonic):
        normalized = normalize_tenants(tenants)
        if normalized is None:
            normalized = default_tenants()
        self.tenants = normalized
        self._by_name = {t.name: t for t in self.tenants}
        self._buckets = {
            t.name: TokenBucket(t.rate_rps, t.burst, clock=clock)
            for t in self.tenants if t.rate_rps is not None
        }
        self._m_tokens = self._m_sheds = self._m_admitted = None
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._m_tokens = telemetry.declare(
                registry, "tenant_quota_tokens"
            )
            self._m_sheds = telemetry.declare(
                registry, "tenant_quota_sheds_total"
            )
            self._m_admitted = telemetry.declare(
                registry, "tenant_admitted_total"
            )
            for name, bucket in self._buckets.items():
                self._m_tokens.set(bucket.tokens(), tenant=name)

    def weights(self) -> "dict[str, float]":
        """Tenant → DWRR weight (the scheduler's fair-fill input)."""
        return {t.name: t.weight for t in self.tenants}

    def resolve(self, name: "str | None") -> Tenant:
        """``tenant`` argument → Tenant. None lands in ``default``;
        unknown names raise — a client/config mismatch is a deployment
        bug and must be loud, not silently billed to default."""
        if name is None:
            return self._by_name[DEFAULT_TENANT]
        ten = self._by_name.get(str(name))
        if ten is None:
            raise ValueError(
                f"unknown tenant {name!r} (configured: "
                f"{sorted(self._by_name)})"
            )
        return ten

    def admit(self, name: "str | None", n: int = 1,
              slo_class: "str | None" = None) -> Tenant:
        """Charge ``n`` requests to the tenant's bucket. Returns the
        resolved Tenant, or raises :class:`QuotaExceededError` with the
        bucket's refill-time hint. Class-allowlist violations raise
        ``ValueError`` (a config bug, not load)."""
        ten = self.resolve(name)
        if ten.classes and slo_class is not None \
                and slo_class not in ten.classes:
            raise ValueError(
                f"tenant {ten.name!r} may not submit to class "
                f"{slo_class!r} (allowed: {list(ten.classes)})"
            )
        bucket = self._buckets.get(ten.name)
        if bucket is not None:
            retry_after = bucket.try_take(n)
            if self._m_tokens is not None:
                self._m_tokens.set(bucket.tokens(), tenant=ten.name)
            if retry_after is not None:
                if self._m_sheds is not None:
                    self._m_sheds.inc(n, tenant=ten.name)
                raise QuotaExceededError(
                    f"tenant {ten.name!r} over quota "
                    f"({bucket.rate_rps:g} rps, burst {bucket.burst:g}); "
                    f"refill in {retry_after:.3f}s",
                    tenant=ten.name, retry_after_s=retry_after,
                    slo_class=slo_class,
                )
        if self._m_admitted is not None:
            self._m_admitted.inc(n, tenant=ten.name)
        return ten

    def state(self) -> dict:
        """The stats()/debugz payload: per-tenant quota config + level."""
        return {
            t.name: {
                "rate_rps": t.rate_rps,
                "burst": t.burst,
                "weight": t.weight,
                "classes": list(t.classes),
                "tokens": (
                    self._buckets[t.name].tokens()
                    if t.name in self._buckets else None
                ),
            }
            for t in self.tenants
        }


class DeficitRoundRobin:
    """Per-request deficit-weighted round robin over tenants.

    Each tenant earns credits proportional to its weight per pointer
    rotation and spends one per dispatched request; a tenant whose
    queue is empty when the pointer passes forfeits its accumulated
    credit (work-conserving: an idle tenant cannot bank a burst).
    Increments are normalized so the smallest weight earns exactly one
    request per rotation — ``pick`` therefore always terminates within
    two rotations when any tenant is active.
    """

    def __init__(self, weights: "dict[str, float]"):
        if not weights:
            raise ValueError("DWRR needs at least one tenant weight")
        self._weights = {t: float(w) for t, w in weights.items()}
        if min(self._weights.values()) <= 0:
            raise ValueError(f"weights must be > 0: {weights}")
        scale = 1.0 / min(self._weights.values())
        self._quantum = {
            t: w * scale for t, w in self._weights.items()
        }
        self._order = list(self._weights)
        self._deficit = {t: 0.0 for t in self._order}
        self._idx = 0

    def pick(self, active) -> "str | None":
        """The tenant the next batch slot goes to, among ``active``
        (tenant names with queued work). None when nothing is active."""
        act = {t for t in active if t in self._deficit}
        if not act:
            return None
        n = len(self._order)
        for _ in range(2 * n + 1):
            t = self._order[self._idx % n]
            if t not in act:
                self._deficit[t] = 0.0
                self._idx += 1
                continue
            if self._deficit[t] >= 1.0:
                # Spend remaining credit before the pointer moves on.
                self._deficit[t] -= 1.0
                return t
            self._deficit[t] += self._quantum[t]
            self._idx += 1
        # Unreachable by construction (min quantum is 1.0); stay safe.
        return sorted(act)[0]

    def state(self) -> dict:
        return {"deficit": dict(self._deficit), "weights": dict(self._weights)}
