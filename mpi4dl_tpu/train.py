"""Training engine: loss, optimizer, and the single-jit spatial(+DP) trainer.

This is the TPU-native counterpart of the reference's training orchestration
(``src/torchgems/train_spatial.py`` + the ``SyncAllreduce`` gradient engine,
``src/torchgems/comm.py:335-522``). The reference coordinates dozens of MPI
ranks with tagged isend/irecv and hand-rolled flat-gradient allreduces; here
one jitted SPMD program runs over a ``jax.sharding.Mesh`` and XLA inserts the
collectives:

- input ``split_input`` (``train_spatial.py:241-290``) → ``shard_map``
  in_specs sharding the batch over ``data`` and H/W over ``tile_h``/``tile_w``;
- join-rank tile merge (``train_spatial.py:1083-1188``) → tiled
  ``all_gather`` (:func:`mpi4dl_tpu.parallel.halo.gather_tiles`);
- ``SyncAllreduce`` flat-grad allreduce + ``divide_bs`` mean semantics
  (``comm.py:414-514``) → nothing: gradients come out of ``jax.grad``
  already globally correct because the loss is written as a *sum of
  per-device contributions* psum-ed over every mesh axis (see
  ``_local_loss``); XLA fuses the resulting reduction with the backward pass.

Optimizer parity: SGD lr=0.001 momentum=0.9 (``mp_pipeline.py:230-234``),
loss = cross entropy (``mp_pipeline.py:225-228``; we feed logits, not the
reference's double softmax — see ``models/resnet.py`` docstring).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi4dl_tpu.config import (
    AXIS_DATA,
    AXIS_PIPE,
    AXIS_TILE_H,
    AXIS_TILE_W,
    ParallelConfig,
)
from mpi4dl_tpu.parallel.halo import gather_tiles


def make_optimizer(learning_rate: float = 0.001, momentum: float = 0.9):
    """Reference default optimizer (``mp_pipeline.py:230-234``)."""
    return optax.sgd(learning_rate, momentum=momentum)


def cross_entropy_sum(logits, labels) -> jax.Array:
    """Sum (not mean) of per-example CE — callers normalize explicitly so the
    psum-of-contributions bookkeeping stays exact under sharding."""
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    )
    return jnp.sum(ce)


def correct_count(logits, labels) -> jax.Array:
    return jnp.sum(jnp.argmax(logits, axis=-1) == labels)


@struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def apply_cells(cells: Sequence[Any], params: Sequence[Any], x):
    for cell, p in zip(cells, params):
        x = cell.apply(p, x)
    return x


class Trainer:
    """Single-program trainer for plain / DP / SP / SP+DP configs
    (``split_size == 1`` — no pipeline; the pipeline engine composes the same
    pieces over the ``pipe`` axis).

    cells: flat cell list (spatial flags baked in by the model builder).
    plain_cells: non-spatial twin with identical param structure, used for
        initialization and available to tests as the golden model. Required
        when ``num_spatial_cells > 0``.
    """

    def __init__(
        self,
        cells: Sequence[Any],
        num_spatial_cells: int,
        config: ParallelConfig,
        plain_cells: Sequence[Any] | None = None,
        mesh=None,
        learning_rate: float = 0.001,
        momentum: float = 0.9,
        remat: bool = False,
    ):
        if num_spatial_cells > 0 and plain_cells is None:
            raise ValueError("spatial models need plain_cells for initialization")
        self.remat = remat
        self.cells = list(cells)
        self.plain_cells = list(plain_cells) if plain_cells is not None else self.cells
        self.n_spatial = num_spatial_cells
        self.config = config
        self.mesh = mesh if mesh is not None else config.make_mesh()
        self.tx = make_optimizer(learning_rate, momentum)
        if self.n_spatial > 0:
            self.x_spec = P(AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W, None)
        else:
            # No spatial section → the input is only batch-sharded; any tile
            # axes in the mesh run the whole model redundantly (still correct
            # via the psum-of-contributions normalization).
            self.x_spec = P(AXIS_DATA, None, None, None)
        self.y_spec = P(AXIS_DATA)
        self._jit_step = jax.jit(self._train_step, donate_argnums=0)

    # -- initialization ------------------------------------------------------
    def init(self, rng, sample_shape: Sequence[int], dtype=jnp.float32) -> TrainState:
        """Init on the plain twin (spatial cells can't trace outside a mesh
        context; param structure is identical — ``partition.init_cells``)."""
        from mpi4dl_tpu.parallel.partition import init_cells

        x = jnp.zeros(tuple(sample_shape), dtype)
        params = init_cells(self.plain_cells, rng, x)
        return TrainState(
            params=params,
            opt_state=self.tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    # -- loss ----------------------------------------------------------------
    def _local_loss(self, params, x, y):
        """Per-device loss contribution; runs inside shard_map.

        Contributions are scaled so that ``psum`` over every mesh axis equals
        the global batch mean — forward value and gradients are then exact
        regardless of how many devices redundantly compute the post-join
        (replicated) section. This one line replaces the reference's
        ``divide_bs`` case analysis (``comm.py:349-358``).
        """
        h = x
        for i, cell in enumerate(self.cells):
            if i == self.n_spatial and self.n_spatial > 0:
                h = gather_tiles(h)
            apply = jax.checkpoint(cell.apply) if self.remat else cell.apply
            h = apply(params[i], h)
        logits = h

        d = lax.axis_size(AXIS_DATA)
        replicas = lax.axis_size(AXIS_TILE_H) * lax.axis_size(AXIS_TILE_W)
        global_b = y.shape[0] * d
        denom = global_b * replicas
        axes = (AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W)
        loss = lax.psum(cross_entropy_sum(logits, y) / denom, axes)
        acc = lax.psum(correct_count(logits, y).astype(jnp.float32) / denom, axes)
        return loss, acc

    def _sharded_loss(self, params, x, y):
        fn = shard_map(
            self._local_loss,
            mesh=self.mesh,
            in_specs=(P(), self.x_spec, self.y_spec),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return fn(params, x, y)

    # -- step ----------------------------------------------------------------
    def _train_step(self, state: TrainState, x, y):
        def loss_fn(params):
            return self._sharded_loss(params, x, y)

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params, opt_state=opt_state, step=state.step + 1
        )
        return new_state, {"loss": loss, "accuracy": acc}

    def shard_batch(self, x, y):
        """Place a host batch onto the mesh with the trainer's sharding
        (the ``split_input`` moment, minus the hand-slicing)."""
        xs = jax.device_put(x, NamedSharding(self.mesh, self.x_spec))
        ys = jax.device_put(y, NamedSharding(self.mesh, self.y_spec))
        return xs, ys

    def train_step(self, state: TrainState, x, y):
        return self._jit_step(state, x, y)


def single_device_step(cells: Sequence[Any], learning_rate=0.001, momentum=0.9, parts=1):
    """Golden single-device train step (tests compare distributed runs
    against this — the role the reference's sequential-conv golden runs play
    in ``benchmark_sp_halo_exchange_with_compute_val.py:704-780``).

    parts > 1 reproduces micro-batched semantics: each micro-batch flows
    through the model separately (so BatchNorm statistics are per
    micro-batch, exactly like the pipeline schedule and the reference's
    ``parts`` loop, ``mp_pipeline.py:509-534``), losses averaged.
    """
    tx = make_optimizer(learning_rate, momentum)

    @jax.jit
    def step(state: TrainState, x, y):
        def loss_fn(params):
            b = y.shape[0]
            xm = x.reshape((parts, b // parts) + tuple(x.shape[1:]))
            ym = y.reshape((parts, b // parts))
            ce = jnp.zeros((), jnp.float32)
            cc = jnp.zeros((), jnp.float32)
            for m in range(parts):
                logits = apply_cells(cells, params, xm[m])
                ce += cross_entropy_sum(logits, ym[m])
                cc += correct_count(logits, ym[m]).astype(jnp.float32)
            return ce / b, cc / b

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            {"loss": loss, "accuracy": acc},
        )

    return tx, step
