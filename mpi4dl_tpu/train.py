"""Training engine: loss, optimizer, and the single-jit spatial(+DP) trainer.

This is the TPU-native counterpart of the reference's training orchestration
(``src/torchgems/train_spatial.py`` + the ``SyncAllreduce`` gradient engine,
``src/torchgems/comm.py:335-522``). The reference coordinates dozens of MPI
ranks with tagged isend/irecv and hand-rolled flat-gradient allreduces; here
one jitted SPMD program runs over a ``jax.sharding.Mesh`` and XLA inserts the
collectives:

- input ``split_input`` (``train_spatial.py:241-290``) → ``shard_map``
  in_specs sharding the batch over ``data`` and H/W over ``tile_h``/``tile_w``;
- join-rank tile merge (``train_spatial.py:1083-1188``) → tiled
  ``all_gather`` (:func:`mpi4dl_tpu.parallel.halo.gather_tiles`);
- ``SyncAllreduce`` flat-grad allreduce + ``divide_bs`` mean semantics
  (``comm.py:414-514``) → nothing: gradients come out of ``jax.grad``
  already globally correct because the loss is written as a *sum of
  per-device contributions* psum-ed over every mesh axis (see
  ``_local_loss``); XLA fuses the resulting reduction with the backward pass.

Optimizer parity: SGD lr=0.001 momentum=0.9 (``mp_pipeline.py:230-234``),
loss = cross entropy (``mp_pipeline.py:225-228``; we feed logits, not the
reference's double softmax — see ``models/resnet.py`` docstring).
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi4dl_tpu.compat import axis_size, optimization_barrier, shard_map
from mpi4dl_tpu.config import (
    AXIS_DATA,
    AXIS_PIPE,
    AXIS_TILE_H,
    AXIS_TILE_W,
    ParallelConfig,
)
from mpi4dl_tpu.parallel.halo import gather_tiles


def _conv_save_ckpt():
    """jax.checkpoint saving the ``conv_out``-tagged conv outputs — the one
    constructor for every conv-saving remat policy (scan_save / cell_save /
    group_save), so the tag name and policy cannot drift between them."""
    return functools.partial(
        jax.checkpoint,
        policy=jax.checkpoint_policies.save_only_these_names("conv_out"),
    )


def _no_ckpt(fn):
    """The no-checkpoint tier of :meth:`Trainer._nockpt_grants`: residuals
    stored, nothing replayed."""
    return fn


def chain_quadratic(apply_fn, stacked, x0):
    """``fold(apply_fn, x0, stacked)`` whose backward holds O(1) live
    boundaries: cell k's input is recomputed from the run's INPUT anchor
    by a masked forward sweep (``j < k`` cells apply, the rest pass
    through at ~zero cost under ``lax.cond``), so the only full-size
    tensors alive during the backward are the anchor, one rolling
    recompute value, the cotangent, and ONE cell's vjp residuals —
    against "scan"'s n stored carries and "scanlog"'s ~log2(n) recursion
    boundaries (still 23.7 GB live at 4096px, docs/PERF.md round 4).

    Cost: ~n²/2 extra cell forwards across the whole backward (n/2 per
    cell), in a program whose size stays O(1) cell bodies (one forward
    scan + one fori-of-scan backward) — unlike nested-checkpoint
    formulations whose backward inlines O(n²) cell instances and kills
    this runtime's remote-compile helper on program size. Numerics are
    exact: this is a scheduling choice, golden-tested like scan2/scanlog
    (``tests/test_train.py``). This is the "slice time, not space" answer
    to >3072px single-chip training (VERDICT r4 next #2): the reference
    reaches such sizes only by adding GPUs (spatial tiles,
    ``torchgems/spatial.py``); an exact single-chip H-strip decomposition
    is blocked by BatchNorm's whole-image statistics (docs/PERF.md
    round 5), while trading recompute for boundary storage is
    semantics-free."""
    n = jax.tree.leaves(stacked)[0].shape[0]
    # Static (numpy) so the closure holds a constant, not a tracer from
    # the forward trace — bwd runs under a DIFFERENT trace later.
    idx = np.arange(n)

    def _run(ps, h):
        def body(h, p):
            return apply_fn(p, h), None

        y, _ = lax.scan(body, h, ps)
        return y

    chain = jax.custom_vjp(_run)

    def fwd(ps, h):
        # Residuals are the anchor + params only — no per-cell boundaries.
        return _run(ps, h), (ps, h)

    def bwd(res, dy):
        ps, x0 = res

        def outer(i, carry):
            d_h, dps = carry
            k = n - 1 - i

            def rec_body(h, jp):
                j, p = jp
                h2 = lax.cond(
                    j < k, lambda: apply_fn(p, h), lambda: h
                )
                # Serialize the sweep so XLA holds ONE rolling value, not
                # several cells' temps (the scan2/scanlog discipline).
                return optimization_barrier(h2), None

            hk, _ = lax.scan(rec_body, x0, (idx, ps))
            pk = jax.tree.map(lambda a: a[k], ps)
            _, cell_vjp = jax.vjp(apply_fn, pk, hk)
            dp_k, d_h = cell_vjp(d_h)
            dps = jax.tree.map(lambda acc, g: acc.at[k].add(g), dps, dp_k)
            return optimization_barrier((d_h, dps))

        zeros = jax.tree.map(jnp.zeros_like, ps)
        d_h, dps = lax.fori_loop(0, n, outer, (dy, zeros))
        return dps, d_h

    chain.defvjp(fwd, bwd)
    return chain(stacked, x0)


def xla_compiler_options() -> "dict[str, str] | None":
    """Per-compile XLA option overrides from ``MPI4DL_TPU_XLA_OPTS``
    ("k=v,k2=v2"), passed via ``jax.jit(compiler_options=...)``. This is
    the only way to reach TPU-backend flags on the tunneled runtime: the
    CLIENT process has no libtpu, so TPU-only names in ``XLA_FLAGS`` are
    fatally rejected by its parser, while proto-backed per-compile
    options are forwarded to the remote compile helper (its own log says
    so). None when unset, so stock configs share the jit cache."""
    spec = os.environ.get("MPI4DL_TPU_XLA_OPTS", "").strip()
    if not spec:
        return None
    opts = {}
    for item in spec.split(","):
        k, _, v = item.partition("=")
        if not k or not v:
            raise ValueError(
                f"MPI4DL_TPU_XLA_OPTS items must be k=v, got {item!r}"
            )
        opts[k.strip()] = v.strip()
    return opts


def scan_unroll() -> int:
    """Resolved lax.scan unroll factor for scanned cell runs (default 3,
    ``MPI4DL_TPU_SCAN_UNROLL`` overrides — measurements in the
    ``_apply_scan_plan`` comment / docs/PERF.md). The single source of
    truth: anything keying compiled-program identity (bench known-fatal
    cache) must use THIS, not its own copy of the default."""
    return int(os.environ.get("MPI4DL_TPU_SCAN_UNROLL", "3"))


def make_optimizer(learning_rate: float = 0.001, momentum: float = 0.9):
    """Reference default optimizer (``mp_pipeline.py:230-234``)."""
    return optax.sgd(learning_rate, momentum=momentum)


def cross_entropy_sum(logits, labels) -> jax.Array:
    """Sum (not mean) of per-example CE — callers normalize explicitly so the
    psum-of-contributions bookkeeping stays exact under sharding."""
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    )
    return jnp.sum(ce)


def correct_count(logits, labels) -> jax.Array:
    return jnp.sum(jnp.argmax(logits, axis=-1) == labels)


@struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def apply_cells(cells: Sequence[Any], params: Sequence[Any], x):
    for cell, p in zip(cells, params):
        x = cell.apply(p, x)
    return x


class Trainer:
    """Single-program trainer for plain / DP / SP / SP+DP configs
    (``split_size == 1`` — no pipeline; the pipeline engine composes the same
    pieces over the ``pipe`` axis).

    cells: flat cell list (spatial flags baked in by the model builder).
    plain_cells: non-spatial twin with identical param structure, used for
        initialization and available to tests as the golden model. Required
        when ``num_spatial_cells > 0``.
    """

    def __init__(
        self,
        cells: Sequence[Any],
        num_spatial_cells: int,
        config: ParallelConfig,
        plain_cells: Sequence[Any] | None = None,
        mesh=None,
        learning_rate: float = 0.001,
        momentum: float = 0.9,
        remat: bool | str = False,
        grad_accum: int = 1,
    ):
        """remat: False = store everything; True/"cell" = ``jax.checkpoint``
        per cell; "sqrt" = nested two-level remat (cells grouped into ~√N
        outer checkpoints, each cell checkpointed inside, so live residuals
        are ~2√N boundaries); "scan2" = "scan" with the same two-level
        nesting applied INSIDE each scan run (see :meth:`_scan_nested`) —
        carry storage drops from one boundary per cell to ~2√n per run;
        "scanq" = "scan" with each run's backward replaced by the
        anchored-quadratic sweep (:func:`chain_quadratic`, O(1) live
        boundaries per run at ~n/2 extra forwards per cell — the deepest
        memory tier, for >3072px); "scan" = the high-resolution
        workhorse:

        - consecutive cells with identical parameter structure and
          input==output shape (a ResNet stage's repeated blocks) run under
          ONE ``lax.scan`` with stacked parameters — XLA compiles a single
          checkpointed body, so conv working-set temps exist once instead of
          once per cell, and compile time drops with depth;
        - scan carries and residuals are stored as ``[B, H, W*C]`` — on TPU
          a small channel count (ResNet stage 1 has 16) otherwise sits in
          the 128-lane minormost tile dim and every stored activation pays
          up to 8x padding; flattening W*C removes that;
        - ``lax.optimization_barrier`` between the remaining un-scanned
          cells stops the scheduler from hoisting several rematerialized
          cell backwards into flight at once (each holds ~1GB of padded
          conv temps at 2048px).

        Measured on one v5e chip, ResNet-110 @1024px bs2: "scan" trains
        2.4x faster than "cell" (680 vs 278 img/s) and cuts peak HBM at
        2048px bs1 from 24.8G to 16.3G."""
        if num_spatial_cells > 0 and plain_cells is None:
            raise ValueError("spatial models need plain_cells for initialization")
        if remat not in (
            False, True, "cell", "sqrt", "scan", "scan2", "scanlog",
            "scanq", "scan_save", "cell_save", "group_save",
        ):
            raise ValueError(
                "remat must be False, True, 'cell', 'sqrt', 'scan', 'scan2', "
                f"'scanlog', 'scanq', 'scan_save', 'cell_save' or "
                f"'group_save', got {remat!r}"
            )
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

        self.grad_accum = grad_accum
        self.remat = remat
        self.cells = list(cells)
        self.plain_cells = list(plain_cells) if plain_cells is not None else self.cells
        self.n_spatial = num_spatial_cells
        self.config = config
        self.mesh = mesh if mesh is not None else config.make_mesh()
        self.tx = make_optimizer(learning_rate, momentum)
        if self.n_spatial > 0:
            self.x_spec = P(AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W, None)
        else:
            # No spatial section → the input is only batch-sharded; any tile
            # axes in the mesh run the whole model redundantly (still correct
            # via the psum-of-contributions normalization).
            self.x_spec = P(AXIS_DATA, None, None, None)
        self.y_spec = P(AXIS_DATA)
        self._jit_step = jax.jit(
            self._train_step,
            donate_argnums=0,
            compiler_options=xla_compiler_options(),
        )
        # Host-side step counter for XProf step annotation (profiling.
        # annotate_step): reading state.step would force a device sync.
        self._host_steps = 0

    # -- initialization ------------------------------------------------------
    def init(self, rng, sample_shape: Sequence[int], dtype=jnp.float32) -> TrainState:
        """Init on the plain twin (spatial cells can't trace outside a mesh
        context; param structure is identical — ``partition.init_cells``)."""
        from mpi4dl_tpu.parallel.partition import init_cells

        x = jnp.zeros(tuple(sample_shape), dtype)
        params = init_cells(self.plain_cells, rng, x)
        return TrainState(
            params=params,
            opt_state=self.tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    def _plan_scan_runs(self, params, x):
        """Group consecutive cells into ``lax.scan`` runs: a run extends
        while the parameter structure+shapes repeat and the activation
        pytree (shape/dtype/treedef) is a fixed point of the cell — a
        ResNet stage's repeated blocks, or AmoebaNet's repeated normal
        cells, whose ``(concat, skip)`` tuple state is a pytree fixed point
        from the run's second cell on (round-1 VERDICT weak: the planner
        only accepted single-tensor fixed points, so AmoebaNet degenerated
        to per-cell checkpointing). Runs never span the SP→LP join.
        Returns a list of index lists."""

        def shapes_of(tree):
            return jax.tree.map(lambda a: (tuple(a.shape), jnp.asarray(a).dtype), tree)

        def fixed_point(o, h):
            """Same treedef + leaf shapes/dtypes: o can feed the same cell."""
            lo, to = jax.tree.flatten(o)
            lh, th = jax.tree.flatten(h)
            if to != th:
                return False
            return all(
                tuple(a.shape) == tuple(b.shape) and a.dtype == b.dtype
                for a, b in zip(lo, lh)
            )

        h = jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        at_join = self._at_join
        plans: list[list[int]] = []
        i, n = 0, len(self.cells)
        while i < n:
            h = at_join(i, h)
            o = jax.eval_shape(self.cells[i].apply, params[i], h)
            run = [i]
            if fixed_point(o, h) and jax.tree.leaves(params[i]):
                sig = shapes_of(params[i])
                j = i + 1
                while j < n and j != self.n_spatial:
                    # The run reuses cells[run[0]].apply for every
                    # iteration, so the modules must be configured
                    # identically, not merely shape-compatible (flax
                    # modules are dataclasses — == compares their config).
                    if self.cells[j] != self.cells[i]:
                        break
                    if shapes_of(params[j]) != sig:
                        break
                    oj = jax.eval_shape(self.cells[j].apply, params[j], o)
                    if not fixed_point(oj, o):
                        break
                    run.append(j)
                    j += 1
            plans.append(run)
            for k in run:
                h = jax.eval_shape(self.cells[k].apply, params[k], h)
            i = run[-1] + 1
        return plans

    def _at_join(self, i, h):
        """Account for the SP→LP tile merge in an abstract shape walk —
        shared by the scan planner and the save-budget estimator so their
        post-join footprints cannot drift apart."""
        if i == self.n_spatial and self.n_spatial > 0:

            def merge(a):
                b, hh, ww, c = a.shape
                th = self.mesh.shape[AXIS_TILE_H]
                tw = self.mesh.shape[AXIS_TILE_W]
                return jax.ShapeDtypeStruct((b, hh * th, ww * tw, c), a.dtype)

            return jax.tree.map(merge, h)
        return h

    def _apply_cells_scan(self, params, x):
        """The "scan" / "scan_save" remat policies (see ``__init__``): scan
        over repeated cells with compact ``[B, H, W*C]`` carries, barriers
        between the rest. "scan_save" additionally saves every conv output
        (tagged ``conv_out`` by ``FastConv``), so the backward recomputes
        only the elementwise/BN segments between convs — +25% conv FLOPs
        avoided for ~the activations' footprint in HBM."""
        key = (tuple(x.shape), x.dtype, self.remat)
        if getattr(self, "_scan_plan_key", None) != key:
            if self.remat == "cell_save":
                # "cell_save": per-cell checkpoints with conv-output saves,
                # NO stacked-parameter scans. Measured FASTER than
                # "scan_save" on the packed-layout bench (3.12 vs 2.35
                # img/s @1024px): separately-compiled cell bodies let XLA
                # optimize each stage globally, where the single scanned
                # body pays slicing/uniformity costs. "scan_save" remains
                # the leaner-memory / faster-compile fallback.
                self._scan_plan = [[i] for i in range(len(self.cells))]
            else:
                self._scan_plan = self._plan_scan_runs(params, x)
            self._scan_plan_key = key
        if self.remat in ("scan_save", "cell_save"):
            from mpi4dl_tpu.ops.fastconv import save_conv_outputs

            save_ckpt = _conv_save_ckpt()
            # MPI4DL_TPU_SAVE_BUDGET_MB caps TOTAL estimated conv-output
            # save bytes; runs beyond the budget fall back to plain
            # checkpoint (recompute). Full scan_save at >=2048px stores
            # ~8.5 GB of saves and reproducibly kills this runtime's
            # remote-compile helper (docs/PERF.md round 3) — a partial
            # budget keeps the save win where it is cheapest (the
            # small-activation late stages) while fitting the wall.
            # Numerics are identical either way (scheduling choice only).
            budget_mb = float(os.environ.get("MPI4DL_TPU_SAVE_BUDGET_MB", "0"))
            if budget_mb > 0:
                ckpts = self._budgeted_ckpts(params, x, budget_mb, save_ckpt)
            else:
                ckpts = [save_ckpt] * len(self._scan_plan)
            ckpts = self._nockpt_grants(params, x, ckpts)
            with save_conv_outputs():
                return self._apply_scan_plan(params, x, ckpts)
        return self._apply_scan_plan(
            params,
            x,
            self._nockpt_grants(
                params, x, [jax.checkpoint] * len(self._scan_plan)
            ),
        )

    def _budgeted_ckpts(self, params, x, budget_mb: float, save_ckpt):
        """Per-run checkpoint choice under a save-byte budget: estimate
        each run's conv-output save footprint as ~2x its input activation
        bytes per cell (bottleneck conv outputs sum to 1.5x the cell I/O
        channels; 2x is a safe planning bound), then grant saves to the
        cheapest runs first — maximum recompute avoided per saved byte."""
        def tree_bytes(t):
            return sum(
                int(np.prod(a.shape)) * a.dtype.itemsize
                for a in jax.tree.leaves(t)
            )

        shapes = []
        h = jax.ShapeDtypeStruct(x.shape, x.dtype)
        for run in self._scan_plan:
            h = self._at_join(run[0], h)  # SP→LP merge, like the planner
            shapes.append(2.0 * tree_bytes(h) * len(run))
            for k in run:
                h = jax.eval_shape(self.cells[k].apply, params[k], h)
        # Grant order (MPI4DL_TPU_SAVE_ORDER): "small" (default) packs the
        # most runs under the budget — late high-channel stages, the best
        # FLOPs-avoided-per-byte; "big" spends it on the early high-
        # resolution stages instead, whose absolute recompute time is
        # largest. An A/B lever for the >=2048px regime where the full
        # save set exceeds the compile-helper wall.
        order_pref = os.environ.get("MPI4DL_TPU_SAVE_ORDER", "small")
        if order_pref not in ("small", "big"):
            raise ValueError(
                f"MPI4DL_TPU_SAVE_ORDER must be small|big, got {order_pref!r}"
            )
        order = sorted(
            range(len(shapes)),
            key=lambda i: shapes[i],
            reverse=order_pref == "big",
        )
        budget = budget_mb * 1e6
        ckpts = [jax.checkpoint] * len(shapes)
        for i in order:
            if shapes[i] <= budget:
                ckpts[i] = save_ckpt
                budget -= shapes[i]
        return ckpts

    def _nockpt_grants(self, params, x, ckpts):
        """Third remat tier (``MPI4DL_TPU_NOCKPT_BUDGET_MB``, default off):
        runs whose FULL residual set fits the budget run with NO checkpoint
        at all — their backward replays nothing. Rationale: the AmoebaNet
        profile (docs/PERF.md round 4) shows the step is elementwise/HBM-
        bound, not FLOPs-bound, and checkpointing makes the backward re-run
        exactly those elementwise chains; the late stages' residuals are
        small (pixels shrink 4x per reduction while channels only double,
        so per-stage bytes HALVE), making them the cheapest recompute to
        buy back. Residual bytes are estimated from the cell jaxpr (sum of
        every equation output aval), cheapest runs first. Numerics are
        identical — checkpointing is a scheduling choice."""
        nockpt_mb = float(os.environ.get("MPI4DL_TPU_NOCKPT_BUDGET_MB", "0"))
        if nockpt_mb <= 0:
            return ckpts

        def eqn_out_bytes(jaxpr) -> float:
            total = 0.0
            for eqn in jaxpr.eqns:
                # Call-like equations (pjit / custom_vjp / remat wrappers):
                # count ONLY the sub-jaxpr — the outer eqn's outvars are the
                # sub-jaxpr's final outputs and would double-count.
                subs = [
                    val.jaxpr
                    for val in eqn.params.values()
                    if hasattr(val, "jaxpr")
                ]
                if subs:
                    total += sum(eqn_out_bytes(j) for j in subs)
                    continue
                for v in eqn.outvars:
                    aval = v.aval
                    if hasattr(aval, "shape"):
                        total += float(np.prod(aval.shape)) * aval.dtype.itemsize
            return total

        est = []
        h = jax.ShapeDtypeStruct(x.shape, x.dtype)
        for run in self._scan_plan:
            h = self._at_join(run[0], h)
            i = run[0]
            closed = jax.make_jaxpr(self.cells[i].apply)(params[i], h)
            est.append(eqn_out_bytes(closed.jaxpr) * len(run))
            for k in run:
                h = jax.eval_shape(self.cells[k].apply, params[k], h)

        budget = nockpt_mb * 1e6
        ckpts = list(ckpts)
        for i in sorted(range(len(est)), key=lambda i: est[i]):
            if est[i] <= budget:
                ckpts[i] = _no_ckpt
                budget -= est[i]
        return ckpts

    @staticmethod
    def _compact(tree):
        """[B, H, W, C] leaves → [B, H, W*C] (the 128-lane pad-tax dodge
        for scan carries/residuals) — but only where the tax is real:
        leaves whose stored padding factor ceil(C/128)*128/C is >= 2
        (ResNet stage carries: C=16/32/64 pay 8x/4x/2x; note C=65..127
        pays up to 1.97x and stays 4-D under this gate — a model carrying
        such widths at fit-barely resolutions trades carry HBM for the
        reshape cost below). AmoebaNet's >=104-channel carries pay at
        most 1.23x, and the flatten around them was far worse than its
        reshape self-time: Pallas custom calls can't fuse, so every pool
        kernel operand/result paid a full-res relayout at the carry
        boundary — un-flattening them measured +15.5% end-to-end on the
        @1024 headline (docs/PERF.md round-4 "flatten interaction").
        Other ranks pass through. Returns
        (compact_tree, (treedef, shape_list)) for :meth:`_restore`."""

        def pad_tax(c: int) -> float:
            return (-(-c // 128) * 128) / c

        leaves, treedef = jax.tree.flatten(tree)
        shapes = [tuple(a.shape) for a in leaves]
        out = [
            a.reshape(a.shape[0], a.shape[1], -1)
            if a.ndim == 4 and pad_tax(a.shape[-1]) >= 2
            else a
            for a in leaves
        ]
        return jax.tree.unflatten(treedef, out), (treedef, shapes)

    @staticmethod
    def _restore(tree, meta):
        treedef, shapes = meta
        leaves = jax.tree.leaves(tree)
        return jax.tree.unflatten(
            treedef, [a.reshape(s) for a, s in zip(leaves, shapes)]
        )

    def _apply_scan_plan(self, params, x, ckpts):
        h = x
        for ckpt, run in zip(ckpts, self._scan_plan):
            if len(run) == 1:
                i = run[0]
                if i == self.n_spatial and self.n_spatial > 0:
                    h = jax.tree.map(gather_tiles, h)
                h = ckpt(self.cells[i].apply)(params[i], h)
                h = optimization_barrier(h)
                continue
            if run[0] == self.n_spatial and self.n_spatial > 0:
                h = jax.tree.map(gather_tiles, h)
            stacked = jax.tree.map(
                lambda *leaves: jnp.stack(leaves), *[params[k] for k in run]
            )
            cell = self.cells[run[0]]
            hc, shapes = self._compact(h)

            def apply_compact(p, hc, cell=cell, shapes=shapes):
                o = cell.apply(p, self._restore(hc, shapes))
                # Output compact-shapes equal the input's: the planner only
                # groups fixed-point cells.
                return self._compact(o)[0]

            def body(hc, p):
                return ckpt(apply_compact)(p, hc), None

            # Unrolling amortizes the scan machinery (parameter
            # dynamic-slices, carry copies, loop overhead) at the cost of a
            # proportionally bigger program. Measured on one v5e (docs/
            # PERF.md round 3): unroll=3 takes AmoebaNet-D @1024 bs2 from
            # 4.92 to 6.37 img/s (+29%) and @2048 bs1 from 1.09 to 1.27
            # (+16%); ResNet is neutral (its hot path is cell_save, and its
            # @2048 scan is recompute-bound, 0.495 -> 0.492). unroll=6
            # matches unroll=3, so 3 is the default — the smallest program
            # that captures the win. MPI4DL_TPU_SCAN_UNROLL overrides.
            unroll = scan_unroll()
            if (
                self.remat == "scanq"
                and len(run) >= 3
                and ckpt is not _no_ckpt
                and not self._scanq_store_granted(run, params, x)
            ):
                # Anchored-quadratic backward: O(1) live boundaries per
                # run (the >3072px policy — chain_quadratic docstring).
                # Short runs stay on the plain checkpointed scan: the
                # masked-sweep machinery only pays past ~2 cells.
                hc = chain_quadratic(apply_compact, stacked, hc)
                hc = optimization_barrier(hc)
            elif (
                self.remat == "scan2"
                and len(run) >= 4
                and ckpt is not _no_ckpt
            ):
                # A _nockpt_grants grant overrides the nesting: the whole
                # point of the no-checkpoint tier is to store residuals and
                # replay nothing, which the plain scan body below (with
                # ckpt == _no_ckpt) does.
                hc = self._scan_nested(hc, stacked, apply_compact)
            else:
                hc, _ = lax.scan(body, hc, stacked, unroll=unroll)
            h = self._restore(hc, shapes)
        return h

    def _scanq_store_granted(self, run, params, x) -> bool:
        """``MPI4DL_TPU_SCANQ_STORE_MB`` (default 0 = off): under "scanq",
        runs whose full carry set (len(run) x compact carry bytes) fits
        the budget keep the plain checkpointed scan — storing a cheap
        run's carries avoids its quadratic recompute while the expensive
        runs stay anchored. The budget is granted BACK-TO-FRONT over the
        scan plan (decided for every eligible run at the first call of a
        trace, via the same abstract shape walk as ``_budgeted_ckpts``):
        the late small-activation stages free their stored carries before
        the early stages' backward runs, so they are the safe grants —
        and the cheapest, so the budget covers more runs. (ADVICE-r5:
        consuming the budget front-to-back handed the storage to the
        EARLIEST fitting run — the opposite of this rationale.) A pure
        scheduling choice; golden-tested with the budget set.

        Caveat: a granted run later downgraded to the no-checkpoint tier
        by ``_nockpt_grants`` (both budgets set at once) keeps its
        deduction — the unused reservation wastes budget, never
        correctness."""
        budget_mb = float(os.environ.get("MPI4DL_TPU_SCANQ_STORE_MB", "0"))
        if budget_mb <= 0:
            return False
        # Keyed by run identity (its first cell index — stable for a given
        # scan plan), NOT by carry shape: two distinct same-shaped runs
        # must EACH deduct the budget, while retraces of the same plan
        # must reuse the original decision.
        if getattr(self, "_scanq_budget_key", None) != self._scan_plan_key:
            self._scanq_budget_key = self._scan_plan_key
            self._scanq_grants = {}
            self._scanq_grant_bytes = {}
            # Abstract walk over the plan (same shape math as the
            # planner / _budgeted_ckpts: _at_join then per-cell
            # eval_shape) — the carry at a run's input has the same byte
            # count compacted or not.
            carry_bytes_at: dict[int, int] = {}
            h = jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
            for r in self._scan_plan:
                h = self._at_join(r[0], h)
                carry_bytes_at[r[0]] = sum(
                    int(np.prod(a.shape)) * a.dtype.itemsize
                    for a in jax.tree.leaves(h)
                ) * len(r)
                for k in r:
                    h = jax.eval_shape(self.cells[k].apply, params[k], h)
            left = budget_mb * 1e6
            for r in reversed(self._scan_plan):
                if len(r) < 3:
                    continue  # short runs never take the scanq path
                granted = carry_bytes_at[r[0]] <= left
                if granted:
                    left -= carry_bytes_at[r[0]]
                    # Recorded per run for the analyzer's remat-
                    # effectiveness rule (Trainer.remat_report):
                    # grants vs budget vs peak.
                    self._scanq_grant_bytes[r[0]] = carry_bytes_at[r[0]]
                self._scanq_grants[r[0]] = granted
            self._scanq_budget_left = left
        return self._scanq_grants.get(run[0], False)

    def _run_cell(self, i, p, h):
        """Apply cell ``i`` (inserting the SP→LP tile merge before cell
        ``n_spatial``) — the one definition of the merge point, shared by
        every remat policy."""
        if i == self.n_spatial and self.n_spatial > 0:
            h = jax.tree.map(gather_tiles, h)
        return self.cells[i].apply(p, h)

    def _apply_cells_scanlog(self, params, x):
        """remat="scanlog": logarithmic recursive checkpointing over the
        WHOLE cell sequence — split in half, checkpoint the left half,
        recurse into both; leaves are per-cell checkpoints. Live saved
        boundaries are one per recursion level (~log2 N of MIXED sizes:
        the path into the expensive early-stage cells is mostly small
        early boundaries, and the later stages' saves are freed before
        the early stages' backward runs), versus scan2's ~2*sqrt(n)
        same-size set per run PLUS every singleton cell's pinned input.
        Measured @3072px (docs/PERF.md round 4): recursive structures
        pack with ~7% buffer-assignment fragmentation where scan runs
        fragment 36-46%. Cost: each cell's forward recomputes ~depth
        times (~5-6x at N=38). This is the deepest-memory policy — it is
        what lands 3072px on one 16 GB chip (0.165 img/s; its ~23.7 GB
        live set still exceeds HBM at 4096px, where the "scanq"
        anchored-quadratic tier — O(1) live boundaries per run,
        :func:`chain_quadratic` — takes over as the overall deepest
        memory policy, docs/PERF.md round 5); barriers keep one rematted
        backward in flight."""

        def rec(i, j, ps, h):
            if j - i == 1:
                h = jax.checkpoint(functools.partial(self._run_cell, i))(
                    ps[0], h
                )
                return optimization_barrier(h)
            mid = (i + j) // 2

            def left(ps_left, h):
                return rec(i, mid, ps_left, h)

            h = jax.checkpoint(left)(ps[: mid - i], h)
            h = optimization_barrier(h)
            return rec(mid, j, ps[mid - i :], h)

        return rec(0, len(self.cells), list(params), x)

    @staticmethod
    def _scan_nested(hc, stacked, apply_compact):
        """Two-level (~sqrt-depth) checkpointing over one scan run — the
        "scan2" policy's heart. The run's n cells split into ~sqrt(n)-sized
        chunks; an outer lax.scan carries only CHUNK boundaries and each
        chunk is one jax.checkpoint whose backward re-runs its inner
        (per-cell-checkpointed) scan. Live residuals drop from n cell
        boundaries ("scan") to ~2*sqrt(n), at the price of one extra
        forward recompute. This is what fits ResNet-110 @4096px bs=1 on one
        16 GB chip: under "scan" the three stages' stored carries alone are
        ~16 GB (18 x 512 MB + 18 x 256 MB + 18 x 128 MB, docs/PERF.md
        round 4), which the tunneled runtime's remote-compile helper
        rejects at buffer-assignment time — the 4096px "compile wall" was
        an out-of-memory program, not a compiler defect."""
        n = jax.tree.leaves(stacked)[0].shape[0]
        g = max(2, int(round(n ** 0.5)))
        m, rem = divmod(n, g)

        def chunk(hc, ps):
            def body(hc, p):
                # The barrier serializes consecutive cells' (rematted)
                # backwards — its transpose is also a barrier — so only
                # ONE cell's recompute temps are in flight. scan2 exists
                # to fit, not to overlap: without this the @3072 compile
                # holds ~2 cells' temps and misses HBM by ~400 MB
                # (docs/PERF.md round 4). Inner unroll stays 1 for the
                # same reason (MPI4DL_TPU_SCAN2_UNROLL overrides).
                hc = jax.checkpoint(apply_compact)(p, hc)
                return optimization_barrier(hc), None

            inner_unroll = int(os.environ.get("MPI4DL_TPU_SCAN2_UNROLL", "1"))
            hc, _ = lax.scan(body, hc, ps, unroll=inner_unroll)
            return hc

        if os.environ.get("MPI4DL_TPU_SCAN2_OFFLOAD") == "1":
            # Offload variant: the outer level is a Python loop whose
            # INTERIOR chunk boundaries are pinned-host tensors — each
            # chunk's jax.checkpoint then saves the host copy, so between
            # that chunk's forward and backward the boundary occupies zero
            # HBM (measured 5.9 GB/s effective roundtrip). The first and
            # last chunks keep device inputs: host values adjacent to the
            # program's entry/exit trip the XLA offloader ("moved to host
            # ... returned from the entry computation"), and the
            # optimization barriers around each transfer stop placement
            # propagation into neighboring fusions; memory-space transfers
            # (compat.put_on_host/put_on_device) preserve the traced
            # sharding, so the path is mesh-shape-agnostic. (A single outer
            # checkpoint with a save_and_offload policy was measured
            # WORSE — one big recompute region overlaps chunks'
            # backwards, docs/PERF.md round 4.)
            def chunk_off(hc_host, ps):
                from mpi4dl_tpu.compat import put_on_device

                hc = jax.tree.map(put_on_device, hc_host)
                hc = optimization_barrier(hc)
                return chunk(hc, ps)

            chunk_off_ck = jax.checkpoint(chunk_off)
            chunk_ck_plain = jax.checkpoint(chunk)
            bounds = [0, rem] if rem else [0]
            while bounds[-1] < n:
                bounds.append(bounds[-1] + g)
            for i, (lo, hi) in enumerate(zip(bounds, bounds[1:])):
                ps = jax.tree.map(lambda a: a[lo:hi], stacked)
                interior = 0 < i < len(bounds) - 2
                if interior:
                    from mpi4dl_tpu.compat import put_on_host

                    hc = optimization_barrier(hc)
                    hc_host = jax.tree.map(put_on_host, hc)
                    hc = chunk_off_ck(hc_host, ps)
                else:
                    hc = chunk_ck_plain(hc, ps)
            return hc

        chunk_ck = jax.checkpoint(chunk)
        if rem:
            head = jax.tree.map(lambda a: a[:rem], stacked)
            hc = chunk_ck(hc, head)
        tail = jax.tree.map(
            lambda a: a[rem:].reshape((m, g) + a.shape[1:]), stacked
        )
        hc, _ = lax.scan(lambda hc, ps: (chunk_ck(hc, ps), None), hc, tail)
        return hc

    def _apply_cells_remat(self, params, x):
        """Run all cells under the configured remat policy (inserting the
        SP→LP tile merge before cell ``n_spatial``)."""
        run_cell = self._run_cell

        if self.remat == "scanlog":
            return self._apply_cells_scanlog(params, x)
        if self.remat in ("scan", "scan2", "scanq", "scan_save", "cell_save"):
            return self._apply_cells_scan(params, x)
        if self.remat in (True, "cell"):
            h = x
            for i in range(len(self.cells)):
                h = jax.checkpoint(functools.partial(run_cell, i))(params[i], h)
            return h
        if self.remat == "sqrt":
            n = len(self.cells)
            g = max(int(np.sqrt(n)), 1)
            h = x
            for start in range(0, n, g):
                idx = list(range(start, min(start + g, n)))

                def run_group(group_params, h, idx=idx):
                    for i, p in zip(idx, group_params):
                        h = jax.checkpoint(functools.partial(run_cell, i))(p, h)
                    return h

                h = jax.checkpoint(run_group)([params[i] for i in idx], h)
            return h
        if self.remat == "group_save":
            # The scan-unroll lesson (docs/PERF.md round 3: +29% AmoebaNet)
            # applied to the no-scan path: checkpoint GROUPS of consecutive
            # cells (MPI4DL_TPU_GROUP_SIZE, default 3) with conv-output
            # saves, so XLA schedules/fuses across the cell boundaries that
            # per-cell checkpoints (cell_save) wall off, while the group
            # barrier still bounds how many rematerialized backwards are in
            # flight.
            from mpi4dl_tpu.ops.fastconv import save_conv_outputs

            g = max(int(os.environ.get("MPI4DL_TPU_GROUP_SIZE", "3")), 1)
            save_ckpt = _conv_save_ckpt()
            n = len(self.cells)
            h = x
            with save_conv_outputs():
                for start in range(0, n, g):
                    idx = list(range(start, min(start + g, n)))

                    def run_group(group_params, h, idx=idx):
                        for i, p in zip(idx, group_params):
                            h = run_cell(i, p, h)
                        return h

                    h = save_ckpt(run_group)([params[i] for i in idx], h)
                    h = optimization_barrier(h)
            return h
        h = x
        for i in range(len(self.cells)):
            h = run_cell(i, params[i], h)
        return h

    # -- loss ----------------------------------------------------------------
    def _local_loss(self, params, x, y):
        """Per-device loss contribution; runs inside shard_map.

        Contributions are scaled so that ``psum`` over every mesh axis equals
        the global batch mean — forward value and gradients are then exact
        regardless of how many devices redundantly compute the post-join
        (replicated) section. This one line replaces the reference's
        ``divide_bs`` case analysis (``comm.py:349-358``).
        """
        logits = self._apply_cells_remat(params, x)

        d = axis_size(AXIS_DATA)
        replicas = axis_size(AXIS_TILE_H) * axis_size(AXIS_TILE_W)
        global_b = y.shape[0] * d
        denom = global_b * replicas
        axes = (AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W)
        loss = lax.psum(cross_entropy_sum(logits, y) / denom, axes)
        acc = lax.psum(correct_count(logits, y).astype(jnp.float32) / denom, axes)
        return loss, acc

    def _sharded_loss(self, params, x, y):
        fn = shard_map(
            self._local_loss,
            mesh=self.mesh,
            in_specs=(P(), self.x_spec, self.y_spec),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return fn(params, x, y)

    # -- step ----------------------------------------------------------------
    def _train_step(self, state: TrainState, x, y):
        from mpi4dl_tpu.ops.halo_pallas import reset_collective_ids

        reset_collective_ids()  # deterministic per-program ids (see there)

        if self.grad_accum == 1:
            def loss_fn(params):
                return self._sharded_loss(params, x, y)

            (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params
            )
        else:
            loss, acc, grads = self._accum_grads(state.params, x, y)
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            params=params, opt_state=opt_state, step=state.step + 1
        )
        return new_state, {"loss": loss, "accuracy": acc}

    def _accum_grads(self, params, x, y):
        """Gradient accumulation: the batch runs as ``grad_accum`` equal
        chunks under ONE ``lax.scan`` — a bs=B/k working set and a bs=B/k
        program (one compiled chunk body). The update applies the MEAN of
        the per-chunk gradients (mean-of-chunk-means == global mean for
        equal chunks). BatchNorm statistics are per-chunk (a batch-of-B/k
        forward), so for BN models this is not bit-identical to the
        unchunked batch — it has exactly the semantics of the reference's
        GEMS ``--times`` chunks, each of which runs its own BN batch
        (``gems_master.py:72-103``), and of ``GemsMasterTrainer`` here.

        This is what lands large-image configs whose unchunked program
        kills the compile pipeline or HBM (e.g. AmoebaNet-D @2048px bs=2 —
        docs/PERF.md round 3): the per-step batch stays at the reference's
        published size while the device only ever holds one chunk. The
        reference's only equivalent is GEMS ``--times`` replication
        (``gems_master.py:72-103``), which requires the mirrored-model
        scheme; here it is a plain Trainer knob.

        Chunks are contiguous batch slices: on a DP-sharded batch axis the
        reshape may insert resharding collectives — grad_accum targets the
        single-chip / spatial-parallel memory wall, not DP scaling.
        """
        k = self.grad_accum
        b = x.shape[0]
        if b % k != 0:
            raise ValueError(f"batch {b} not divisible by grad_accum={k}")
        xs = x.reshape(k, b // k, *x.shape[1:])
        ys = y.reshape(k, b // k)

        def chunk_loss(params, xc, yc):
            return self._sharded_loss(params, xc, yc)

        def body(carry, xy):
            gsum, lsum, asum = carry
            (l, a), g = jax.value_and_grad(chunk_loss, has_aux=True)(
                params, *xy
            )
            carry = (jax.tree.map(jnp.add, gsum, g), lsum + l, asum + a)
            return carry, None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (gsum, lsum, asum), _ = lax.scan(
            body, (zeros, jnp.zeros(()), jnp.zeros(())), (xs, ys)
        )
        grads = jax.tree.map(lambda t: t / k, gsum)
        return lsum / k, asum / k, grads

    def shard_batch(self, x, y):
        """Place a host batch onto the mesh with the trainer's sharding
        (the ``split_input`` moment, minus the hand-slicing). Multi-process,
        (x, y) are this host's local batch shard
        (:func:`mpi4dl_tpu.parallel.multihost.put_global`)."""
        from mpi4dl_tpu.parallel.multihost import put_global

        return put_global(self.mesh, (self.x_spec, self.y_spec), x, y)

    # -- static analysis support (mpi4dl_tpu.analysis) -----------------------
    def halo_shift_count(self, params, x_shape, dtype=jnp.float32) -> int:
        """Forward halo shift ppermutes in ONE un-scanned pass over the
        cells — the partition-math floor the analyzer's permute rule checks
        the compiled inventory against (each shift lowers to exactly one
        ``collective-permute``; the backward at most doubles it). Counted
        by abstract tracing (``jax.eval_shape``) with the per-cell loop
        shared by every remat policy, so scan-carried cells are counted
        once per ITERATION, not once per compiled body."""
        from mpi4dl_tpu.parallel.halo import count_halo_shifts

        def local(ps, x):
            h = x
            for i in range(len(self.cells)):
                h = self._run_cell(i, ps[i], h)
            return jax.tree.map(lambda a: jnp.sum(a, dtype=jnp.float32), h)

        fn = shard_map(
            local,
            mesh=self.mesh,
            in_specs=(P(), self.x_spec),
            out_specs=P(),
            check_vma=False,
        )
        x = jax.ShapeDtypeStruct(tuple(x_shape), dtype)
        with count_halo_shifts() as box:
            jax.eval_shape(fn, params, x)
        return box[0]

    def collective_deltas(self, params, x_shape, dtype=jnp.float32):
        """This trainer's layer deltas for the expectations algebra
        (:mod:`mpi4dl_tpu.analysis.expectations`): the spatial front's
        halo entitlement over the counted forward shifts when cells are
        spatially partitioned, else the pure-DP entitlement. Gate a
        compiled step with ``compose(*trainer.collective_deltas(...))``."""
        from mpi4dl_tpu.analysis.expectations import (
            data_parallel_delta,
            spatial_delta,
        )

        if self.n_spatial > 0:
            return (
                spatial_delta(
                    self.config.tile_shape,
                    self.halo_shift_count(params, x_shape, dtype=dtype),
                ),
            )
        return (data_parallel_delta(),)

    def publish_telemetry(
        self, registry=None, params=None, x_shape=None, dtype=jnp.float32
    ):
        """Publish the trainer's static facts as cataloged gauges
        (docs/OBSERVABILITY.md): the remat policy's store budget and
        granted bytes (:meth:`remat_report`), plus — when ``params`` and
        ``x_shape`` are given — the forward halo-shift count
        (:meth:`halo_shift_count`, an abstract trace; no device work).
        ``registry=None`` uses the process-wide default. Step-time series
        come from :class:`mpi4dl_tpu.profiling.StepTimer(registry=...)`,
        not from here. Returns the registry."""
        from mpi4dl_tpu import telemetry

        reg = registry if registry is not None else telemetry.default_registry()
        rep = self.remat_report()
        telemetry.declare(reg, "train_remat_store_budget_mb").set(
            rep["store_budget_mb"]
        )
        telemetry.declare(reg, "train_remat_granted_bytes").set(
            rep["granted_bytes"]
        )
        if params is not None and x_shape is not None:
            telemetry.declare(reg, "train_halo_shifts").set(
                self.halo_shift_count(params, x_shape, dtype=dtype)
            )
        return reg

    def capture_trace_attribution(
        self,
        state,
        x,
        y,
        steps: int = 3,
        logdir: "str | None" = None,
        registry=None,
        program: str = "train_step",
    ):
        """Capture an XProf trace of ``steps`` live train steps and
        attribute device time (:mod:`mpi4dl_tpu.analysis.trace`): per-step
        compute / collective / transfer / host-gap buckets plus the
        measured collective-overlap ratio — the runtime cross-check of
        hlolint's static start→done rule. With a ``registry``, publishes
        the cataloged ``trace_*`` gauges under ``program``.

        Returns ``(state, summary)`` — the state advances by ``steps``
        real optimizer updates (the capture measures the genuine step,
        not a replay)."""
        from mpi4dl_tpu import profiling

        box = {"state": state}

        def one_step(i):
            del i
            box["state"], metrics = self.train_step(box["state"], x, y)
            return metrics["loss"]

        cap = profiling.capture(one_step, steps=steps, logdir=logdir)
        summary = cap.attribution(registry=registry, program=program)
        return box["state"], summary

    def remat_report(self) -> dict:
        """Remat/store-budget metadata for the analyzer's effectiveness
        rule: the configured policy + scanq store budget, and the grant
        bytes actually recorded at the last trace (empty before tracing)."""
        grants = getattr(self, "_scanq_grant_bytes", {})
        return {
            "policy": self.remat if isinstance(self.remat, str) else str(self.remat),
            "store_budget_mb": float(
                os.environ.get("MPI4DL_TPU_SCANQ_STORE_MB", "0")
            ),
            "granted_bytes": sum(grants.values()),
            "grants": dict(grants),
        }

    def train_step(self, state: TrainState, x, y):
        from contextlib import ExitStack

        from mpi4dl_tpu.ops import pool_pallas
        from mpi4dl_tpu.ops.fastconv import wgrad_taps_threshold
        from mpi4dl_tpu.profiling import annotate_step

        step_id = self._host_steps
        self._host_steps += 1
        with ExitStack() as stack:
            # XProf step boundary carrying the same host-side step id the
            # telemetry layer records, so profiling.trace dumps align with
            # StepTimer/span data (docs/OBSERVABILITY.md).
            stack.enter_context(annotate_step("mpi4dl_train_step", step_id))
            if self.config.image_size >= 3072:
                # Arm the aggressive per-tap wgrad gate for this trace:
                # at these sizes the backward-filter conv's padded
                # operand copies are what OOMs the step (docs/PERF.md
                # round 4). A trace-time context, not process state —
                # other Trainers in the process keep the 3072 MB
                # default; the env override still wins inside
                # taps_min_mb.
                stack.enter_context(wgrad_taps_threshold(256))
            if self.config.image_size >= 2048:
                # Keep the Pallas pool + fused-1x1 backwards out of
                # large-image programs: their VMEM-stack-allocated
                # results kill the compile against the HBM ceiling
                # (measured: AmoebaNet@2048 bs1 compiles with them off,
                # dies with them on — pool_pallas.disable docstring;
                # re-validated round 5 via MPI4DL_TPU_POOL_PALLAS=on).
                from mpi4dl_tpu.ops import dot1x1_pallas

                stack.enter_context(pool_pallas.disable())
                stack.enter_context(dot1x1_pallas.disable())
            try:
                return call_with_halo_hint(self._jit_step, state, x, y)
            except Exception as e:
                # OOM forensics (telemetry/memory.py): a RESOURCE_EXHAUSTED
                # train step emits a structured oom.report — the parsed HBM
                # table + largest buffers — into the env-gated JSONL log
                # before the exception surfaces. Three rounds of PERF.md
                # debugging were spent re-discovering what the truncated
                # message already carried; the report keeps it.
                from mpi4dl_tpu.telemetry import memory as memobs

                if memobs.is_oom_error(e):
                    from mpi4dl_tpu import telemetry

                    events = telemetry.JsonlWriter()  # env-gated; no-op
                    try:  # without MPI4DL_TPU_TELEMETRY_DIR
                        memobs.emit_oom_report(
                            e, program="train_step",
                            events=events if events.enabled else None,
                            attrs={
                                "image_size": self.config.image_size,
                                "remat": self.remat
                                if isinstance(self.remat, str)
                                else str(self.remat),
                            },
                        )
                    finally:
                        events.close()
                raise

    def record_memory_footprint(
        self, state, x, y, ledger=None, registry=None,
        program: str = "train_step",
    ) -> dict:
        """Record the compiled train step's predicted peak into a
        :class:`~mpi4dl_tpu.telemetry.memory.FootprintLedger` (a fresh
        one when none is given). ``lower().compile()`` is a warm-cache
        no-op for a step the process already traced, so calling this
        after training costs no extra compile; before any execution it
        is the feasibility planner's compile-only prediction."""
        from mpi4dl_tpu.telemetry.memory import FootprintLedger

        if ledger is None:
            ledger = FootprintLedger(registry=registry)
        return ledger.record_lowered(program, self._jit_step, state, x, y)


def call_with_halo_hint(fn, *args):
    """Invoke a jitted step, annotating compile errors that look like
    Pallas collective-id-space exhaustion with the operator hint
    (:func:`mpi4dl_tpu.ops.halo_pallas.annotate_id_space_error`). Shared by
    both trainers so the caught-type/hint logic cannot drift."""
    try:
        return fn(*args)
    except jax.errors.JaxRuntimeError as e:
        from mpi4dl_tpu.ops.halo_pallas import annotate_id_space_error

        annotate_id_space_error(e)  # operator hint; no-op off-pallas
        raise


def single_device_step(cells: Sequence[Any], learning_rate=0.001, momentum=0.9, parts=1):
    """Golden single-device train step (tests compare distributed runs
    against this — the role the reference's sequential-conv golden runs play
    in ``benchmark_sp_halo_exchange_with_compute_val.py:704-780``).

    parts > 1 reproduces micro-batched semantics: each micro-batch flows
    through the model separately (so BatchNorm statistics are per
    micro-batch, exactly like the pipeline schedule and the reference's
    ``parts`` loop, ``mp_pipeline.py:509-534``), losses averaged.
    """
    tx = make_optimizer(learning_rate, momentum)

    @jax.jit
    def step(state: TrainState, x, y):
        def loss_fn(params):
            b = y.shape[0]
            xm = x.reshape((parts, b // parts) + tuple(x.shape[1:]))
            ym = y.reshape((parts, b // parts))
            ce = jnp.zeros((), jnp.float32)
            cc = jnp.zeros((), jnp.float32)
            for m in range(parts):
                logits = apply_cells(cells, params, xm[m])
                ce += cross_entropy_sum(logits, ym[m])
                cc += correct_count(logits, ym[m]).astype(jnp.float32)
            return ce / b, cc / b

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            {"loss": loss, "accuracy": acc},
        )

    return tx, step
