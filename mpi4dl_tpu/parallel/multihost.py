"""Multi-host / multi-slice execution: process init, DCN-aware meshes,
per-host data feeding.

The reference scales across nodes by launching one MPI process per GPU under
``mpirun_rsh`` and calling ``dist.init_process_group(backend="mpi")``
(``src/torchgems/comm.py:154-159``) over CUDA-aware MVAPICH2-GDR; every
cross-node pattern (halo P2P, pipeline send/recv, flat-grad allreduce) then
rides InfiniBand through MPI. The TPU-native equivalents here:

- :func:`initialize_distributed` — ``jax.distributed.initialize``: one
  process per host, after which ``jax.devices()`` is the *global* device
  list and every jitted collective spans hosts transparently;
- :func:`make_multihost_mesh` — a hybrid ICI/DCN mesh: the ``data`` axis
  spans slices over DCN while ``pipe``/``tile_h``/``tile_w`` stay inside a
  slice on ICI. That placement is the whole performance story: halo
  exchanges (per conv, per micro-batch — the innermost hot loop,
  SURVEY.md §3) and pipeline wire hops ride ICI; the only DCN traffic is
  the once-per-step DP gradient ``psum``, which is exactly the collective
  DCN bandwidth is provisioned for;
- :func:`host_local_batch` — builds the global sharded batch from each
  host's local shard (``jax.make_array_from_process_local_data``), the
  multi-host form of the reference's per-rank ``split_input``
  (``train_spatial.py:241-290``): each host loads only the examples its
  devices consume instead of materializing the global batch everywhere.

Single-process (one host, or CPU simulation) everything degrades to the
plain ``config.make_mesh()`` path, so the same training script runs
unchanged from a laptop CPU mesh to a multi-slice pod — the property the
reference approximates with its SPMD rank-branching scripts.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from mpi4dl_tpu.config import (
    AXIS_DATA,
    AXIS_PIPE,
    AXIS_TILE_H,
    AXIS_TILE_W,
    ParallelConfig,
)

MESH_AXES = (AXIS_DATA, AXIS_PIPE, AXIS_TILE_H, AXIS_TILE_W)


# Env vars that mean "a multi-host world is configured" — if any is set and
# init still fails, that's an operator error we must surface, not swallow.
_COORDINATOR_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


# Environment markers that unambiguously mean "more than one process was
# launched" even when no coordinator address is spelled out (the launcher or
# pod runtime provides it). Checked besides jax's own cluster auto-detection
# so a jax-internal API move cannot silently disable the propagation of
# multi-host init failures.
_MULTIPROC_ENV_MARKERS = (
    "OMPI_COMM_WORLD_SIZE",
    "SLURM_NTASKS",
    "MEGASCALE_NUM_SLICES",
)


def _cluster_autodetected() -> bool:
    """True when this environment is recognizably a multi-process launch
    (GKE / GCE TPU pods, Slurm, OpenMPI, …) — there, no coordinator env var
    is set by the operator, yet a multi-host world IS configured and init
    failures must propagate."""
    for k in _MULTIPROC_ENV_MARKERS:
        v = os.environ.get(k)
        try:
            if v is not None and int(v) > 1:
                return True
        except ValueError:
            pass
    try:
        from jax._src.clusters import ClusterEnv

        return any(c.is_env_present() for c in ClusterEnv._cluster_types)
    except Exception:
        return False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join the multi-host world (ref ``dist.init_process_group``,
    ``comm.py:154-159``; launcher contract ``README.md:121-125``).

    On TPU pods all three arguments are discovered from the environment, so
    a bare ``initialize_distributed()`` at the top of a training script is
    the entire multi-host setup. Must run before anything that initializes
    the XLA backend (``jax.devices()``, array creation, …) — like
    ``jax.distributed.initialize`` itself. Calling it again once
    initialized is a no-op, and so is a plain single-process run with no
    coordinator configured anywhere; but if a coordinator IS configured
    (argument or environment), failures propagate — silently degrading a
    pod launch into N independent single-host jobs is the one outcome this
    wrapper must never produce.
    """
    from mpi4dl_tpu.compat import distributed_is_initialized

    if distributed_is_initialized():
        return
    configured = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
        or any(os.environ.get(k) for k in _COORDINATOR_ENV_VARS)
        or _cluster_autodetected()
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        if configured:
            raise
        # No coordinator anywhere → single-process run, nothing to join.


def num_slices(devices: Sequence[jax.Device] | None = None) -> int:
    """Count DCN-connected slices (granules). 1 on a single slice / CPU."""
    devices = jax.devices() if devices is None else list(devices)
    ids = {getattr(d, "slice_index", 0) for d in devices}
    return max(len(ids), 1)


def make_multihost_mesh(
    config: ParallelConfig, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Hybrid ICI/DCN mesh for ``config`` over all (global) devices.

    The ``data`` axis factors as ``slices × per-slice replicas``: DP spans
    DCN first, and any remaining DP extent stays on ICI inside a slice.
    ``pipe``/``tile_h``/``tile_w`` never cross a slice boundary — pipeline
    wires and halo rings are latency-sensitive per-micro-batch traffic and
    must ride ICI. Falls back to ``config.make_mesh()`` when there is a
    single slice (including CPU simulation).
    """
    devices = jax.devices() if devices is None else list(devices)
    slices = num_slices(devices)
    if slices == 1:
        return config.make_mesh(devices)

    groups: dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0), []).append(d)
    first_slice = groups[sorted(groups)[0]]

    dp, pipe, th, tw = config.mesh_shape
    if dp % slices:
        # DP doesn't factor over the slices. If the whole mesh fits inside
        # one slice, run it there (pure SP/LP configs on multi-slice
        # systems) — but only single-process: in a multi-process world the
        # processes on the other slices would own no devices of that mesh,
        # which JAX cannot execute; reject with a clear error instead.
        # Otherwise the config is genuinely unplaceable without non-data
        # axes crossing DCN, which we refuse.
        if config.num_devices <= len(first_slice) and jax.process_count() == 1:
            return config.make_mesh(first_slice)
        raise ValueError(
            f"data_parallel={dp} must divide by the {slices} DCN slices "
            "(the data axis is the only axis allowed to cross DCN) and "
            f"mesh {config.mesh_shape} does not fit inside one slice "
            f"({len(first_slice)} devices)"
        )
    from jax.experimental import mesh_utils

    per_slice = (dp // slices, pipe, th, tw)
    need = int(np.prod(per_slice))
    # Tolerate surplus devices (parity with config.make_mesh's prefix-take):
    # use the first `need` devices of every slice.
    chosen = []
    for idx in sorted(groups):
        g = groups[idx]
        if len(g) < need:
            raise ValueError(
                f"slice {idx} has {len(g)} devices but the config needs "
                f"{need} per slice (mesh {config.mesh_shape} spread over "
                f"{slices} slices)"
            )
        chosen.extend(g[:need])
    dev = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=per_slice,
        dcn_mesh_shape=(slices, 1, 1, 1),
        devices=chosen,
    )
    return Mesh(dev, MESH_AXES)


def host_local_batch(mesh: Mesh, spec, *arrays) -> tuple:
    """Assemble global arrays from per-host local data.

    Each host passes ONLY its local shard (its devices' slice of the global
    batch, in the global order implied by ``spec``); the returned
    ``jax.Array``s are global and feed ``train_step`` directly. This is the
    multi-host ``split_input`` / DataLoader contract: no host ever holds the
    global batch (the reference loads the full batch on every rank and
    slices, ``benchmark_amoebanet_sp.py:329-340``).

    Single-process, local == global and this is equivalent to
    ``jax.device_put`` with the same sharding. Always returns a tuple with
    one entry per input array.
    """
    spec = tuple(spec)
    if len(spec) != len(arrays):
        raise ValueError(
            f"host_local_batch got {len(arrays)} arrays but {len(spec)} specs"
        )
    return tuple(
        jax.make_array_from_process_local_data(NamedSharding(mesh, s), np.asarray(a))
        for s, a in zip(spec, arrays)
    )


def put_global(mesh: Mesh, spec, *arrays) -> tuple:
    """Place batches on the mesh, single- or multi-process.

    Single-process: plain ``device_put`` (the array IS the global batch).
    Multi-process: the arrays are each host's LOCAL shard and the global
    array is assembled without any host ever holding the global batch
    (:func:`host_local_batch`). Trainers route ``shard_batch`` through this,
    so the same training script scales from one chip to a pod.
    """
    spec = tuple(spec)
    if jax.process_count() > 1:
        return host_local_batch(mesh, spec, *arrays)
    if len(spec) != len(arrays):
        raise ValueError(
            f"put_global got {len(arrays)} arrays but {len(spec)} specs"
        )
    return tuple(
        jax.device_put(a, NamedSharding(mesh, s)) for s, a in zip(spec, arrays)
    )


def data_shard(mesh: Mesh, axis: str = AXIS_DATA) -> tuple[int, int]:
    """(shard_id, num_shards) of THIS process along the batch axis.

    Hosts whose devices sit at the same data coordinates must feed
    IDENTICAL data (they jointly assemble the same global-batch rows via
    ``make_array_from_process_local_data``), so the shard id is derived
    from the data coordinates this process owns — NOT from
    ``jax.process_index()``, which would hand model-parallel co-hosts
    disjoint data and silently corrupt the global batch."""
    if jax.process_count() == 1:
        return 0, 1
    local = mesh.local_mesh.shape
    glob = dict(mesh.shape)
    num_shards = glob[axis] // local[axis]
    axes = list(mesh.axis_names)
    dim = axes.index(axis)
    my_coords = sorted(
        {
            int(np.argwhere(mesh.devices == d)[0][dim])
            for d in mesh.local_devices
        }
    )
    return my_coords[0] // local[axis], num_shards


def local_batch_size(mesh: Mesh, global_batch: int, axis: str = AXIS_DATA) -> int:
    """This host's share of the global batch: the batch (``data``) axis may
    cross processes, every other axis must be process-local (the placement
    :func:`make_multihost_mesh` produces; anything else would mean pipeline
    wires / halo rings over DCN, which we refuse rather than silently run
    slow)."""
    local = mesh.local_mesh.shape
    glob = dict(mesh.shape)
    for name in glob:
        if name != axis and local[name] != glob[name]:
            raise ValueError(
                f"mesh axis {name!r} crosses process boundaries "
                f"(local {local[name]} != global {glob[name]}); only the "
                f"{axis!r} axis may span hosts"
            )
    if global_batch % glob[axis]:
        raise ValueError(
            f"global batch {global_batch} must divide by the {axis!r} axis "
            f"extent {glob[axis]}"
        )
    return global_batch * local[axis] // glob[axis]
