"""Pipeline (LP/PP) engine: spatial front phase + GPipe fill-drain back phase.

TPU-native replacement for the reference's ``train_model`` engine
(``src/torchgems/mp_pipeline.py:171-538``) and its spatial subclass's routing
(``train_spatial.py:1256-1458``). The reference runs one process per GPU,
pre-allocates tagged recv buffers per micro-batch, and drives a fill-drain
schedule with blocking MPI isend/irecv (``run_step`` ``mp_pipeline.py:509-534``:
all forwards, then all backwards). Here the whole step is ONE jitted SPMD
program over the mesh ``(data, pipe, tile_h, tile_w)``, in two phases:

**Front phase (spatial stages).** All cells of stages ``0..spatial_size-1``
run for ALL micro-batches up front, ``vmap``-ed over the micro-batch axis (so
BatchNorm statistics stay per-micro-batch, exactly like the reference's
``parts`` loop), H/W sharded over the tile axes with halo exchange, and the
``pipe`` axis reused as extra micro-batch parallelism (micro-batches divide
across pipe coordinates when ``parts % pipe == 0``; otherwise the front is
computed replicated — correct, just redundant). The SP→LP join
(``train_spatial.py:506-555, 1083-1188``) is the ``gather_tiles`` at the end
of the front. Every collective in this phase executes unconditionally on
every device — no divergent control flow around collectives, which the
collective runtime rejects (and the reference would call a deadlock).

Contrast with the reference topology: there the spatial stage owns its own
ranks which idle while LP ranks compute (``comm.py:59-67``); here the front
uses the whole mesh, then the whole mesh pipelines the back.

**Back phase (LP pipeline).** The remaining collective-free stages run the
GPipe fill-drain schedule:

- stage placement   → ``lax.switch`` on ``lax.axis_index("pipe")``: each pipe
  device executes its own stage body (heterogeneous shapes per stage are fine
  because each switch branch un/re-flattens to its stage's static shapes);
- activation send/recv (``mp_pipeline.py:294-432``) → per-boundary flat
  "wire" buffers rotated with ``lax.ppermute`` each tick — exact sizes, no
  tags, no waits;
- micro-batch loop ("parts") → ``lax.scan`` over ``parts + stages - 1``
  fill-drain ticks;
- the backward schedule (``backward_pass`` ``mp_pipeline.py:475-507``) is not
  hand-written at all: JAX AD transposes the scan+ppermute program into the
  reverse drain automatically (transpose of a forward ppermute is the
  backward grad hop the reference implements by hand);
- per-stage activation memory is bounded by ``jax.checkpoint`` around each
  stage body (recompute-in-backward; GPipe-standard), which also keeps
  ``lax.switch`` residuals uniform across branches;
- fill/drain ticks whose stage has no valid micro-batch dispatch to a
  cheap idle branch (switch index ``S``) instead of computing masked
  garbage — numerically identical, but the schedule's bubble becomes
  PHYSICAL device idle the trace-attribution lens can measure
  (``capture_trace_attribution`` → ``pipeline_bubble_fraction``, checked
  against the analytic ``(S-1)/(S-1+M)``; docs/OBSERVABILITY.md
  "Pipeline").

``schedule="1f1b"`` swaps the fill-drain for the interleaved
virtual-stage schedule (Megatron's interleaved-1F1B family): each pipe
device hosts ``virtual_stages`` non-contiguous model chunks and
micro-batches ring through ``v*S`` hops, shrinking the bubble to
``(S-1)/(parts + v*S - 1)`` at the same loss (golden-equal; the AD
transpose is the reverse-interleaved backward).

GEMS mirror support: ``mirror=True`` places back-phase stage ``s`` on pipe
device ``S-1-s`` and reverses wire flow — the reference's ``GEMS_INVERSE``
rank arithmetic (``mp_pipeline.py:238-248``) reduced to an index map.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi4dl_tpu.compat import axis_size, shard_map
from mpi4dl_tpu.config import (
    AXIS_DATA,
    AXIS_PIPE,
    AXIS_TILE_H,
    AXIS_TILE_W,
    ParallelConfig,
)
from mpi4dl_tpu.parallel.halo import gather_tiles
from mpi4dl_tpu.parallel.partition import (
    init_cells,
    split_cells,
    stage_bounds,
)
from mpi4dl_tpu.train import TrainState, correct_count, cross_entropy_sum, make_optimizer


# -- pytree <-> flat vector plumbing ----------------------------------------


class _TreeMeta:
    """Static recipe to rebuild a pytree from one flat vector.

    ``vec_dtype`` is the flat vector's dtype. Parameters stay f32 (they are
    the optimizer's master weights), but activation wires take the model's
    compute dtype: under ``--precision bf16`` the inter-stage ppermute
    traffic — the pipeline's ICI hot path — halves its bytes, and since the
    activations are already bf16 the bf16→f32→bf16 roundtrip this replaces
    was exact, so goldens are unchanged (round-1 VERDICT weak #4)."""

    def __init__(self, tree, vec_dtype=jnp.float32):
        leaves, self.treedef = jax.tree.flatten(tree)
        self.shapes = [
            tuple(l.shape) if hasattr(l, "shape") else np.shape(l) for l in leaves
        ]
        self.dtypes = [
            l.dtype if hasattr(l, "dtype") else jnp.asarray(l).dtype for l in leaves
        ]
        self.sizes = [int(np.prod(s)) for s in self.shapes]
        self.size = int(sum(self.sizes))
        self.vec_dtype = jnp.dtype(vec_dtype)

    def flatten(self, tree) -> jax.Array:
        leaves = jax.tree.leaves(tree)
        if not leaves:
            return jnp.zeros((0,), self.vec_dtype)
        return jnp.concatenate(
            [jnp.ravel(l).astype(self.vec_dtype) for l in leaves]
        )

    def unflatten(self, vec: jax.Array):
        out, off = [], 0
        for shape, dtype, size in zip(self.shapes, self.dtypes, self.sizes):
            out.append(
                lax.slice(vec, (off,), (off + size,)).reshape(shape).astype(dtype)
            )
            off += size
        return jax.tree.unflatten(self.treedef, out)

def _is_shape(s):
    return isinstance(s, tuple) and all(isinstance(i, int) for i in s)


class PipelineTrainer:
    """Front-phase + GPipe back-phase trainer over
    ``(data, pipe, tile_h, tile_w)``.

    cells: flat cell list with spatial flags baked in (first
        ``spatial_cell_count`` cells spatial when ``config.spatial_size > 0``;
        use :meth:`spatial_cell_count` to build a matching model).
    plain_cells: non-spatial twin for init + shape tracing (identical param
        structure). Required when the model has spatial cells.
    schedule: ``"gpipe"`` (fill-drain, the default) or ``"1f1b"`` — the
        interleaved-virtual-stage schedule (Megatron-LM's interleaved 1F1B
        family, arXiv:2104.04473): each pipe device hosts ``virtual_stages``
        non-contiguous model chunks (device ``d`` gets virtual stages ``d,
        S+d, ...``), micro-batches ring through ``v*S`` hops, and the AD
        transpose of the scan yields the matching reverse-interleaved
        backward. Non-interleaved 1F1B has the SAME bubble as GPipe at
        equal (stages, micro-batches) — its win is memory; the interleaved
        variant is the one that shrinks the bubble, to
        ``(S-1)/(parts + v*S - 1)`` from GPipe's ``(S-1)/(parts + S - 1)``,
        which the trace-attribution lens measures on the real timeline.
    virtual_stages: model chunks per pipe device under ``schedule="1f1b"``
        (``v`` above, default 2; ignored for gpipe).
    """

    def __init__(
        self,
        cells: Sequence[Any],
        config: ParallelConfig,
        plain_cells: Sequence[Any] | None = None,
        mesh=None,
        learning_rate: float = 0.001,
        momentum: float = 0.9,
        remat: bool = True,
        mirror: bool = False,
        num_spatial_cells: int | None = None,
        schedule: str = "gpipe",
        virtual_stages: int = 2,
    ):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule must be 'gpipe' or '1f1b', got {schedule!r}"
            )
        if schedule == "1f1b":
            if mirror:
                raise ValueError(
                    "schedule='1f1b' does not compose with the GEMS mirror "
                    "placement (the interleaved ring already wraps the pipe "
                    "axis) — use schedule='gpipe' for GEMS"
                )
            if int(virtual_stages) < 2:
                raise ValueError(
                    "schedule='1f1b' needs virtual_stages >= 2 (v=1 IS "
                    "gpipe; the bubble shrinks by the interleave depth)"
                )
            if config.lp_stages < 2:
                raise ValueError(
                    "schedule='1f1b' needs >= 2 pipeline stages — a 1-deep "
                    "pipe has no bubble to interleave away"
                )
        self.schedule = schedule
        self.v = int(virtual_stages) if schedule == "1f1b" else 1
        if config.spatial_size:
            if config.spatial_size >= config.split_size:
                raise ValueError(
                    "spatial stages must be followed by at least one LP stage "
                    "(the join rank) — need spatial_size < split_size"
                )
        elif config.split_size < 2:
            raise ValueError("PipelineTrainer needs split_size >= 2 (use Trainer)")
        self.cells = list(cells)
        self.plain_cells = list(plain_cells) if plain_cells is not None else self.cells
        if len(self.plain_cells) != len(self.cells):
            raise ValueError("plain_cells must mirror cells one-to-one")
        self.config = config
        self.mesh = mesh if mesh is not None else config.make_mesh()
        self.tx = make_optimizer(learning_rate, momentum)
        self.remat = remat
        self.mirror = mirror

        cfg = config
        self.S = cfg.lp_stages  # back-phase pipeline depth == pipe axis extent
        self.parts = cfg.parts
        if cfg.batch_size % (cfg.parts * cfg.data_parallel):
            raise ValueError("batch_size must divide by parts * data_parallel")
        self.mb_local = cfg.batch_size // cfg.parts // cfg.data_parallel
        # LOCAL_DP_LP (ref train_spatial.py:809-1028): the reference's join
        # rank dist.scatters its batch over an SP∪LP group so the LP stages
        # run data-parallel instead of idle. Here the equivalent is a batch
        # slice by tile coordinate: each of the th*tw tile devices pipelines
        # a distinct 1/local_dp of every micro-batch (redundant back-phase
        # compute becomes data-parallel compute, no communication added —
        # the "scatter" is choosing a different slice of the already-joined,
        # replicated activation).
        self.local_dp = cfg.local_dp
        if self.local_dp > 1:
            if self.mb_local % self.local_dp:
                raise ValueError(
                    "micro-batch size must divide by local_dp "
                    f"({self.mb_local} % {self.local_dp})"
                )
            self.mb_back = self.mb_local // self.local_dp
        else:
            self.mb_back = self.mb_local
        if num_spatial_cells is not None:
            # Explicit front length (e.g. D2 models whose expanded cell list
            # no longer matches D1 stage bounds — the reference mutates
            # balance[0] for the same reason, resnet_spatial_d2.py:667-697).
            self.n_spatial_cells = num_spatial_cells
            back = self.cells[self.n_spatial_cells :]
            back_balance = (
                list(cfg.balance)
                if cfg.balance is not None and len(cfg.balance) == self.S
                else None
            )
        else:
            bounds = stage_bounds(len(self.cells), cfg.split_size, cfg.balance)
            self.n_spatial_cells = self.spatial_cell_count(len(self.cells), cfg)
            back = self.cells[self.n_spatial_cells :]
            back_balance = (
                [e - s for s, e in bounds[cfg.spatial_size :]]
                if cfg.balance is not None or cfg.spatial_size
                else None
            )
        self.front_cells = self.cells[: self.n_spatial_cells]
        # n_virtual model chunks ring through the pipe: S contiguous stages
        # for gpipe, v*S interleaved virtual stages for 1f1b (a user balance
        # list only applies when it addresses every virtual stage).
        self.n_virtual = self.v * self.S
        if self.v > 1 and (back_balance is None or
                           len(back_balance) != self.n_virtual):
            back_balance = None
        if len(back) < self.n_virtual:
            raise ValueError(
                f"{len(back)} back-phase cells cannot split into "
                f"{self.n_virtual} virtual stages (schedule={schedule!r})"
            )
        self.stages = split_cells(back, self.n_virtual, back_balance)
        self._build_static_plan()
        self._jit_step = jax.jit(self._train_step, donate_argnums=0)

    def _stages_of_device(self, d: int) -> "list[int]":
        """Virtual stages hosted by pipe device ``d``: the one stage
        ``mirror``-mapped for gpipe; the interleaved set ``d, S+d, ...``
        (Megatron chunk placement) for 1f1b."""
        if self.v == 1:
            return [(self.S - 1 - d) if self.mirror else d]
        return [j * self.S + d for j in range(self.v)]

    # -- static planning -----------------------------------------------------
    @staticmethod
    def spatial_cell_count(num_cells: int, config: ParallelConfig) -> int:
        """How many leading cells are spatial: all cells of stages
        ``0..spatial_size-1`` (ref boundary logic ``resnet_spatial.py:545-633``:
        spatial cells up to the SP stage's end layer)."""
        if not config.spatial_size:
            return 0
        bounds = stage_bounds(num_cells, config.split_size, config.balance)
        return bounds[config.spatial_size - 1][1]

    def _build_static_plan(self):
        """Trace the front output and per-boundary wire shapes via
        ``jax.eval_shape`` on the plain twin (replaces the reference's
        GPU dry-run + rescale dance, ``mp_pipeline.py:126-168`` +
        ``train_spatial.py:61-238``)."""
        cfg = self.config
        x = jax.ShapeDtypeStruct(
            (self.mb_local, cfg.image_size, cfg.image_size, 3), jnp.float32
        )
        rng = jax.random.PRNGKey(0)

        def trace(cells, xx):
            def run(xx):
                vs = init_cells(cells, rng, xx)
                for cell, v in zip(cells, vs):
                    xx = cell.apply(v, xx)
                return xx

            out = jax.eval_shape(run, xx)
            shapes = jax.tree.map(
                lambda s: tuple(s.shape),
                out,
                is_leaf=lambda s: isinstance(s, jax.ShapeDtypeStruct),
            )
            return out, shapes

        plain_front = self.plain_cells[: self.n_spatial_cells]
        if plain_front:
            x, self.front_out_shape = trace(plain_front, x)
        else:
            self.front_out_shape = tuple(x.shape)
        if self.mb_back != self.mb_local:
            # LOCAL_DP_LP: back-phase wires carry the per-tile batch slice.
            x = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(
                    (self.mb_back,) + tuple(s.shape[1:]), s.dtype
                ),
                x,
                is_leaf=lambda s: isinstance(s, jax.ShapeDtypeStruct),
            )
        # Boundary wires are traced through the REAL back-phase cells (they
        # are collective-free, so eval_shape is safe even for spatial
        # configs) to capture the model's true activation dtypes — a bf16
        # model gets bf16 wires regardless of the f32 plain twin / input.
        boundary_trees, out_shape = [], None
        for si, stage in enumerate(self.stages):
            x, shapes = trace(stage, x)
            if si < self.n_virtual - 1:
                boundary_trees.append(x)
            else:
                out_shape = shapes
        if not _is_shape(out_shape):
            raise ValueError(f"final stage must emit logits, got {out_shape}")
        self.num_classes = out_shape[-1]

        def wire_dtype(tree):
            dts = {jnp.dtype(l.dtype) for l in jax.tree.leaves(tree)}
            return dts.pop() if len(dts) == 1 else jnp.dtype(jnp.float32)

        self.wire_metas = [
            _TreeMeta(t, vec_dtype=wire_dtype(t)) for t in boundary_trees
        ]

    # -- init ----------------------------------------------------------------
    def init_params(self, rng, dtype=jnp.float32):
        """Params = (front_flat, stacked_back [S, MAXP]). Front params are
        replicated over ``pipe`` (every device computes the front); back-stage
        rows are sharded over ``pipe``. Flattening gives ``lax.switch``
        branches a uniform operand type (the reference GEMS engine flattens
        whole-model params for one-shot P2P for the same reason,
        ``train_spatial_master.py:117-138``)."""
        cfg = self.config
        x = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), dtype)
        per_cell = init_cells(self.plain_cells, rng, x)
        front_tree = per_cell[: self.n_spatial_cells]
        back_per_stage = split_cells(
            per_cell[self.n_spatial_cells :],
            self.n_virtual,
            [len(st) for st in self.stages],
        )
        self.front_meta = _TreeMeta(front_tree)
        self.param_metas = [_TreeMeta(t) for t in back_per_stage]
        front_flat = self.front_meta.flatten(front_tree)
        flats = [
            meta.flatten(tree)
            for meta, tree in zip(self.param_metas, back_per_stage)
        ]
        # Device row d concatenates its hosted virtual stages' flats (one
        # stage for gpipe — the original layout — v chunks for 1f1b); each
        # chunk's static (offset, size) within the row lets the switch
        # branch slice its params without gathers.
        self._chunk_offsets: list = []
        rows = []
        for d in range(self.S):
            offs, off = [], 0
            for k in self._stages_of_device(d):
                offs.append((k, off, self.param_metas[k].size))
                off += self.param_metas[k].size
            self._chunk_offsets.append(offs)
            rows.append(jnp.concatenate([flats[k] for k, _, _ in offs]))
        self.max_p = max(int(r.shape[0]) for r in rows)
        stacked = jnp.stack(
            [jnp.pad(r, (0, self.max_p - int(r.shape[0]))) for r in rows]
        )  # [S, MAXP]
        return (
            jax.device_put(front_flat, NamedSharding(self.mesh, P())),
            jax.device_put(stacked, NamedSharding(self.mesh, P(AXIS_PIPE, None))),
        )

    def init(self, rng, dtype=jnp.float32) -> TrainState:
        params = self.init_params(rng, dtype)
        return TrainState(
            params=params,
            opt_state=self.tx.init(params),
            step=jnp.zeros((), jnp.int32),
        )

    def unstack_params(self, params) -> list:
        """(front, stacked) → flat per-cell variables list (tests /
        checkpoints)."""
        front_flat, stacked = params
        out = list(self.front_meta.unflatten(jnp.asarray(front_flat)))
        stacked = jnp.asarray(stacked)
        where = {
            k: (d, off, size)
            for d in range(self.S)
            for k, off, size in self._chunk_offsets[d]
        }
        for k in range(self.n_virtual):
            d, off, size = where[k]
            out.extend(
                self.param_metas[k].unflatten(stacked[d][off : off + size])
            )
        return out

    # -- front phase ---------------------------------------------------------
    def _front(self, front_flat, x):
        """Spatial stages on all micro-batches; returns [parts, mb, ...]
        joined (full-image) activations, replicated over ``pipe``.

        Micro-batches divide across pipe coordinates when possible (the
        ``pipe`` axis moonlights as data parallelism for the front — the
        LBANN-style trick the reference implements as LOCAL_DP_LP
        scatter/gather, ``train_spatial.py:809-1028``, here in reverse);
        otherwise every pipe device computes the full set redundantly.
        """
        if not self.front_cells:
            return x
        params = self.front_meta.unflatten(front_flat)
        lp = self.S

        def one_microbatch(xm):
            h = xm
            for cell, p in zip(self.front_cells, params):
                h = cell.apply(p, h)
            return jax.tree.map(gather_tiles, h)

        shard_over_pipe = lp > 1 and self.parts % lp == 0
        if shard_over_pipe:
            chunk = self.parts // lp
            pipe_idx = lax.axis_index(AXIS_PIPE)
            my = jax.tree.map(
                lambda a: lax.dynamic_slice_in_dim(a, pipe_idx * chunk, chunk, 0), x
            )
        else:
            my = x
        from mpi4dl_tpu.parallel.halo import xla_halo_only

        with xla_halo_only():  # Pallas halo deadlocks under vmap batching
            out = jax.vmap(one_microbatch)(my)
        if shard_over_pipe:
            out = jax.tree.map(
                lambda a: lax.all_gather(a, AXIS_PIPE, axis=0, tiled=True), out
            )
        return out

    # -- back-phase stage bodies ---------------------------------------------
    def _stage_fn(self, s: int):
        cells = self.stages[s]
        meta = self.param_metas[s]

        def fn(flat_params, h):
            params = meta.unflatten(flat_params[: meta.size])
            for cell, p in zip(cells, params):
                h = cell.apply(p, h)
            return h

        return jax.checkpoint(fn) if self.remat else fn

    def _make_branch(self, s: int):
        """Switch branch for pipe devices hosting back-stage ``s``: consume
        this tick's input (front output for stage 0, wire ``s-1`` otherwise),
        emit wire ``s`` (or logits for the last stage)."""
        stage = self._stage_fn(s)
        wire_metas = self.wire_metas

        def branch(flat_params, wires, x_mb):
            if s == 0:
                inp = x_mb
            else:
                inp = wire_metas[s - 1].unflatten(wires[s - 1])
            out = stage(flat_params, inp)
            new_wires = [jnp.zeros_like(w) for w in wires]
            if s < self.S - 1:
                new_wires[s] = wire_metas[s].flatten(out)
                logits = jnp.zeros((self.mb_back, self.num_classes), jnp.float32)
            else:
                logits = out.astype(jnp.float32)
            return tuple(new_wires), logits

        return branch

    def _idle_branch(self):
        """Extra switch branch (index ``S``) a device takes on ticks where
        its stage has no valid micro-batch (fill/drain). Returning zeros is
        semantically identical to the garbage the ungated schedule computed
        there (nothing derived from an invalid tick ever reaches a valid
        prediction, and the masked preds give those paths zero cotangent) —
        but it makes the GPipe bubble PHYSICAL: an idle device spends no
        device time, so the trace-attribution lens can measure the
        fill-drain fraction instead of watching every device burn full
        compute on micro-batches that don't exist."""
        def branch(flat_params, wires, x_mb, *tick):
            del flat_params, x_mb, tick
            new_wires = tuple(jnp.zeros_like(w) for w in wires)
            logits = jnp.zeros((self.mb_back, self.num_classes), jnp.float32)
            return new_wires, logits

        return branch

    def _make_branch_1f1b(self, d: int):
        """Switch branch for pipe device ``d`` under the interleaved
        schedule: apply each hosted virtual-stage chunk (``d, S+d, ...``)
        whose micro-batch ``t - k`` is in range this tick, consuming wire
        ``k-1`` (front output for ``k == 0``) and emitting wire ``k`` (or
        logits for the final chunk). Out-of-range chunks take the cheap
        zero path of a per-chunk ``lax.cond``, so the interleave's partial
        edge ticks stay as physically idle as gpipe's fill/drain."""
        chunks = self._chunk_offsets[d]
        wire_metas = self.wire_metas
        nv, parts = self.n_virtual, self.parts

        def branch(flat_params, wires, x_mb, t):
            new_wires = [jnp.zeros_like(w) for w in wires]
            logits = jnp.zeros((self.mb_back, self.num_classes), jnp.float32)
            for k, off, size in chunks:
                stage = self._stage_fn(k)
                p_k = lax.slice(flat_params, (off,), (off + size,))
                m = t - k
                valid = (m >= 0) & (m < parts)
                inp = (
                    x_mb if k == 0
                    else wire_metas[k - 1].unflatten(wires[k - 1])
                )

                def run(op, _stage=stage, _k=k):
                    out = _stage(op[0], op[1])
                    return out if _k < nv - 1 else out.astype(jnp.float32)

                def skip(op, _k=k):
                    del op
                    if _k < nv - 1:
                        meta = wire_metas[_k]
                        return meta.unflatten(
                            jnp.zeros((meta.size,), meta.vec_dtype)
                        )
                    return jnp.zeros(
                        (self.mb_back, self.num_classes), jnp.float32
                    )

                out = lax.cond(valid, run, skip, (p_k, inp))
                if k < nv - 1:
                    new_wires[k] = wire_metas[k].flatten(out)
                else:
                    logits = out
            return tuple(new_wires), logits

        return branch

    # -- the schedule --------------------------------------------------------
    def _schedule(self, flat, front_out, mirror: bool):
        """Fill-drain over one chunk. Returns ``(preds, stage_of)`` — preds
        valid only on the last stage's devices, callers mask with
        ``stage_of == S-1``. Ticks where a device's stage has no valid
        micro-batch dispatch to the cheap idle branch (index ``S``), so the
        schedule's bubble shows up as measurable device idle time."""
        if self.schedule == "1f1b":
            if mirror:
                raise ValueError(
                    "schedule='1f1b' does not support the mirror placement"
                )
            return self._schedule_1f1b(flat, front_out)
        S, parts = self.S, self.parts
        pipe_idx = lax.axis_index(AXIS_PIPE)
        stage_of = (S - 1 - pipe_idx) if mirror else pipe_idx

        def dev_of(s):
            return (S - 1 - s) if mirror else s

        branches = [self._make_branch(s) for s in range(S)]
        branches.append(self._idle_branch())
        wires0 = tuple(
            jnp.zeros((m.size,), m.vec_dtype) for m in self.wire_metas
        )
        preds0 = jnp.zeros((parts, self.mb_back, self.num_classes), jnp.float32)
        perm = [(dev_of(s), dev_of(s + 1)) for s in range(S - 1)]

        def tick(carry, t):
            wires, preds = carry
            m0 = jnp.clip(t, 0, parts - 1)
            x_mb = jax.tree.map(lambda a: a[m0], front_out)
            m = t - stage_of
            valid = (m >= 0) & (m < parts)
            new_wires, logits = lax.switch(
                jnp.where(valid, stage_of, S), branches, flat, wires, x_mb
            )
            valid_last = (stage_of == S - 1) & valid
            mc = jnp.clip(m, 0, parts - 1)
            preds = jnp.where(
                valid_last,
                lax.dynamic_update_index_in_dim(preds, logits, mc, 0),
                preds,
            )
            sent = tuple(
                lax.ppermute(w, AXIS_PIPE, [pair]) for pair, w in zip(perm, new_wires)
            )
            return (sent, preds), None

        (_, preds), _ = lax.scan(tick, (wires0, preds0), jnp.arange(parts + S - 1))
        return preds, stage_of

    def _schedule_1f1b(self, flat, front_out):
        """Interleaved schedule: micro-batches ring through ``v*S`` virtual
        stages (wire ``k`` hops device ``k%S -> (k+1)%S``, wrapping at the
        chunk boundary), one tick per hop, ``parts + v*S - 1`` ticks. Each
        device is busy for ``parts + (v-1)*S`` of them, so the fill/drain
        idle stays ``S-1`` ticks per device while the tick count grows —
        bubble ``(S-1)/(parts + v*S - 1)``, strictly below gpipe's
        ``(S-1)/(parts + S - 1)``. The AD transpose of this scan is the
        reverse-interleaved backward with the same occupancy."""
        S, parts, nv = self.S, self.parts, self.n_virtual
        stage_of = lax.axis_index(AXIS_PIPE)
        branches = [self._make_branch_1f1b(d) for d in range(S)]
        branches.append(self._idle_branch())
        wires0 = tuple(
            jnp.zeros((m.size,), m.vec_dtype) for m in self.wire_metas
        )
        preds0 = jnp.zeros((parts, self.mb_back, self.num_classes), jnp.float32)
        perm = [[(k % S, (k + 1) % S)] for k in range(nv - 1)]

        def tick(carry, t):
            wires, preds = carry
            m0 = jnp.clip(t, 0, parts - 1)
            x_mb = jax.tree.map(lambda a: a[m0], front_out)
            # Device d's hosted chunks cover micro-batches over the
            # contiguous tick span [d, d + (v-1)S + parts - 1]; outside it
            # the device takes the idle branch (inner conds handle the
            # per-chunk holes of a short pipeline, parts < S).
            active = (t >= stage_of) & (
                t <= stage_of + (self.v - 1) * S + parts - 1
            )
            new_wires, logits = lax.switch(
                jnp.where(active, stage_of, S), branches, flat, wires, x_mb, t
            )
            m = t - (nv - 1)
            valid_last = (stage_of == S - 1) & (m >= 0) & (m < parts)
            mc = jnp.clip(m, 0, parts - 1)
            preds = jnp.where(
                valid_last,
                lax.dynamic_update_index_in_dim(preds, logits, mc, 0),
                preds,
            )
            sent = tuple(
                lax.ppermute(w, AXIS_PIPE, pr)
                for pr, w in zip(perm, new_wires)
            )
            return (sent, preds), None

        (_, preds), _ = lax.scan(
            tick, (wires0, preds0), jnp.arange(parts + nv - 1)
        )
        return preds, stage_of

    # -- pipeline observability ----------------------------------------------
    def analytic_bubble_fraction(self) -> float:
        """The schedule-model bubble the measured one is cross-checked
        against: GPipe fill-drain ``(S-1)/(S-1+M)`` (the ROADMAP's open
        number), interleaved 1F1B ``(S-1)/(M + v*S - 1)`` (per-device idle
        stays ``S-1`` ticks of a longer, busier tick count)."""
        S, M = self.S, self.parts
        if self.schedule == "1f1b":
            return (S - 1) / (M + self.n_virtual - 1)
        return (S - 1) / (S - 1 + M)

    def stage_permute_count(self) -> int:
        """EXACT stage-boundary ``collective-permute`` count of the
        compiled train step, beyond halo traffic: one per wire in the
        forward scan body plus its AD-transpose twin — ``2*(n_virtual-1)``
        (the scan executes them T times, the static inventory counts the
        body once). This is the value hlolint's
        ``Expectations.extra_permutes`` pins the permute window with."""
        return 2 * (self.n_virtual - 1)

    def halo_shift_count(self, state, x_shape, dtype=jnp.float32) -> int:
        """Forward halo shift ppermutes of the SPATIAL FRONT in one
        un-scanned pass — the same partition-math floor as
        :meth:`mpi4dl_tpu.train.Trainer.halo_shift_count`, counted by
        abstract tracing of ``_front`` alone (no back-phase scan, no
        backward: the stage wires ride the EXACT budget from
        :meth:`stage_permute_count`, not this window). ``x_shape`` is the
        unsharded global batch shape ``[B, H, W, C]``. 0 when the model
        has no spatial cells."""
        from mpi4dl_tpu.parallel.halo import count_halo_shifts

        if not self.front_cells:
            return 0

        def local(front_flat, x):
            out = self._front(front_flat, x)
            return jax.tree.map(lambda a: jnp.sum(a, dtype=jnp.float32), out)

        fn = shard_map(
            local,
            mesh=self.mesh,
            in_specs=(P(), self.x_spec),
            out_specs=P(),
            check_vma=False,
        )
        b = int(x_shape[0])
        xs = jax.ShapeDtypeStruct(
            (self.parts, b // self.parts) + tuple(x_shape[1:]), dtype
        )
        with count_halo_shifts() as box:
            jax.eval_shape(fn, state.params[0], xs)
        return box[0]

    def collective_deltas(self, state, x_shape, dtype=jnp.float32):
        """This trainer's layer deltas for the expectations algebra
        (:mod:`mpi4dl_tpu.analysis.expectations`): the spatial front's
        halo window + the SP→LP join gather pair (when spatial cells
        exist) stacked with the back phase's exact stage-permute budget.
        Gate a compiled step with
        ``compose(*trainer.collective_deltas(state, x_shape))``."""
        from mpi4dl_tpu.analysis.expectations import (
            pipeline_delta,
            spatial_delta,
            spatial_join_delta,
        )

        deltas = []
        if self.front_cells:
            deltas.append(spatial_delta(
                self.config.tile_shape,
                self.halo_shift_count(state, x_shape, dtype=dtype),
            ))
            if not (self.S > 1 and self.parts % self.S == 0):
                # Tile join into the replicated head: fwd gather + its
                # backward re-gather. When the front instead shards
                # micro-batches over the pipe axis, its pipe all_gather
                # (and the AD transpose) joins the gather class with a
                # fusion-dependent count — no exact claim then.
                deltas.append(spatial_join_delta(2))
        deltas.append(pipeline_delta(self.stage_permute_count()))
        return tuple(deltas)

    def capture_trace_attribution(
        self,
        state,
        x,
        y,
        steps: int = 3,
        logdir: "str | None" = None,
        registry=None,
        program: "str | None" = None,
        hlo_text: "str | None" = None,
    ):
        """Capture an XProf trace of ``steps`` live pipeline train steps
        and attribute device time (:mod:`mpi4dl_tpu.analysis.trace`) — the
        standard compute/collective/transfer/host-gap report plus the
        PIPELINE lens (``summary["pipeline"]``): per-stage device seconds,
        per-stage/idle slot occupancy counted from the compiled program's
        stage-switch branches, and the measured ``bubble_fraction``
        cross-checked against :meth:`analytic_bubble_fraction`. With a
        ``registry``, publishes the cataloged ``trace_*`` AND
        ``pipeline_*`` gauges under ``program`` (default
        ``pipeline_<schedule>``).

        Returns ``(state, summary)`` — the state advances by ``steps``
        real optimizer updates."""
        from mpi4dl_tpu import profiling
        from mpi4dl_tpu.analysis.trace import (
            analyze_pipeline_trace_dir,
            publish_pipeline_attribution,
        )

        program = program or f"pipeline_{self.schedule}"
        box = {"state": state}

        def one_step(i):
            del i
            box["state"], metrics = self.train_step(box["state"], x, y)
            return metrics["loss"]

        cap = profiling.capture(one_step, steps=steps, logdir=logdir)
        summary = cap.attribution(registry=registry, program=program)
        if hlo_text is None:
            # Callers that already AOT-compiled this step (the pipeline
            # bench's lint pass, tests) pass its as_text() — the AOT path
            # does not share the jit cache, so this lower+compile is a
            # real second compile otherwise.
            hlo_text = (
                self._jit_step.lower(box["state"], x, y).compile().as_text()
            )
        summary["pipeline"] = analyze_pipeline_trace_dir(
            cap.trace_dir,
            hlo_text,
            n_stages=self.S,
            step_name=cap.step_name,
            analytic_bubble=self.analytic_bubble_fraction(),
            schedule=self.schedule,
        )
        # Throughput of the captured steps: the pipeline bench's img/s arm
        # (global batch images flow through the schedule per step).
        chunks = getattr(self, "chunks", 1)
        images = chunks * self.config.batch_size
        mean_wall = sum(cap.step_times_s) / max(1, len(cap.step_times_s))
        summary["pipeline"]["img_per_s"] = (
            images / mean_wall if mean_wall > 0 else 0.0
        )
        if registry is not None:
            publish_pipeline_attribution(
                summary["pipeline"], registry, program=program
            )
        return box["state"], summary

    def _contributions(self, preds, y, stage_of):
        """Per-device (ce_sum, correct) masked to the last stage — pre-psum."""
        is_last = stage_of == self.S - 1
        logits_all = preds.reshape(-1, self.num_classes)
        labels = y.reshape(-1)
        zero = jnp.zeros((), jnp.float32)
        ce = jnp.where(is_last, cross_entropy_sum(logits_all, labels), zero)
        cc = jnp.where(
            is_last, correct_count(logits_all, labels).astype(jnp.float32), zero
        )
        return ce, cc

    def _reduce_metrics(self, ce, cc, n_examples_local):
        """psum-of-contributions normalization (see ``train.Trainer``).

        With LOCAL_DP_LP the tile devices hold DISTINCT batch slices (no
        redundancy), so the replica divisor drops to 1 — the ``divide_bs``
        distinction the reference special-cases at ``comm.py:349-358``."""
        if self.local_dp > 1:
            replicas = 1
        else:
            replicas = axis_size(AXIS_TILE_H) * axis_size(AXIS_TILE_W)
        denom = n_examples_local * axis_size(AXIS_DATA) * replicas
        axes = (AXIS_DATA, AXIS_PIPE, AXIS_TILE_H, AXIS_TILE_W)
        return lax.psum(ce / denom, axes), lax.psum(cc / denom, axes)

    def _back_inputs(self, front_out, y):
        """Select this device's back-phase batch slice: identity without
        LOCAL_DP_LP; the tile-coordinate slice of every micro-batch with it
        (the reference's join-rank ``dist.scatter``,
        ``send_input_spatial_MP_joint_LP_DP`` ``train_spatial.py:809-854``,
        with the scatter replaced by slicing the already-joined tensor)."""
        if self.local_dp <= 1:
            return front_out, y
        tw = axis_size(AXIS_TILE_W)
        idx = lax.axis_index(AXIS_TILE_H) * tw + lax.axis_index(AXIS_TILE_W)
        k = self.mb_back

        def sl(a):
            return lax.dynamic_slice_in_dim(a, idx * k, k, axis=1)

        return jax.tree.map(sl, front_out), sl(y)

    def _local_loss(self, params, x, y):
        """Runs inside shard_map. x: [parts, mb_local, H(/th), W(/tw), C]
        local tile of the micro-batched input; y: [parts, mb_local]."""
        front_flat, stacked_local = params
        flat = stacked_local[0]  # [MAXP] — this device's back-stage params
        front_out = self._front(front_flat, x)
        front_out, y = self._back_inputs(front_out, y)
        preds, stage_of = self._schedule(flat, front_out, self.mirror)
        ce, cc = self._contributions(preds, y, stage_of)
        return self._reduce_metrics(ce, cc, self.parts * self.mb_local)

    # -- step ----------------------------------------------------------------
    @property
    def x_spec(self):
        if self.n_spatial_cells > 0:
            return P(None, AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W, None)
        return P(None, AXIS_DATA, None, None, None)

    @property
    def y_spec(self):
        return P(None, AXIS_DATA)

    def _sharded_loss(self, params, x, y):
        fn = shard_map(
            self._local_loss,
            mesh=self.mesh,
            in_specs=((P(), P(AXIS_PIPE, None)), self.x_spec, self.y_spec),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return fn(params, x, y)

    def _train_step(self, state: TrainState, x, y):
        from mpi4dl_tpu.ops.halo_pallas import reset_collective_ids

        reset_collective_ids()  # deterministic per-program ids (see there)

        def loss_fn(params):
            return self._sharded_loss(params, x, y)

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            {"loss": loss, "accuracy": acc},
        )

    def train_step(self, state: TrainState, x, y):
        from mpi4dl_tpu.train import call_with_halo_hint

        return call_with_halo_hint(self._jit_step, state, x, y)

    def shard_batch(self, x, y):
        """[B, H, W, C] → micro-batched [parts, mb, H, W, C] placed on the
        mesh (batch over ``data``, H/W over tile axes for spatial configs).
        Multi-process, (x, y) are this host's local batch shard
        (:func:`mpi4dl_tpu.parallel.multihost.put_global`)."""
        from mpi4dl_tpu.parallel.multihost import put_global

        b = x.shape[0]
        x = x.reshape((self.parts, b // self.parts) + tuple(x.shape[1:]))
        y = y.reshape((self.parts, b // self.parts))
        return put_global(self.mesh, (self.x_spec, self.y_spec), x, y)


class GemsMasterTrainer(PipelineTrainer):
    """GEMS-MASTER: bidirectional pipeline pairs (ref ``train_model_master``,
    ``gems_master.py:23-103``, and the SP flavor ``train_spatial_model_master``,
    ``train_spatial_master.py:87-501``).

    The reference keeps TWO model replicas resident: model2's stage ``s``
    lives on rank ``mp_size-1-s`` (``GEMS_INVERSE``), the pair alternates
    half-batches, and gradients merge through carefully ordered allreduces
    (``comm.py:460-504``) — or through pairwise flat-parameter/grad P2P in the
    ``--enable-master-comm-opt`` path (``train_spatial_master.py:229-455``).

    TPU-native form: ONE parameter copy. The reverse direction materializes
    its stage row by a mirror ``ppermute`` of the stacked per-stage params
    over the pipe axis — which *is* the comm-opt pairwise exchange, expressed
    as a collective; its AD transpose routes the reverse-direction gradients
    back to the owning devices, replacing both hand-written allreduce
    orderings and the deadlock-avoidance dance. The step runs ``2 × times``
    chunks (ref ``--times`` replication, ``gems_master.py:87-102``),
    alternating normal/mirrored placement, in one jitted program: effective
    batch ``2·times·batch_size`` at one parameter copy's memory.

    SP+GEMS composes for free: the spatial front is direction-agnostic, so
    the reference's rank-disjointness constraint ``mp_size ≥ 2×spatial_parts``
    (``verify_spatial_master_config``, ``train_spatial_master.py:33-84``) has
    no analog here — any SP config can run GEMS.

    Note: with the scan-based engine, plain GPipe already fills bubbles by
    raising ``parts`` at no extra memory (remat), so bidirectionality is kept
    for capability/CLI parity and for the mirrored-placement machinery GEMS
    needs, not because bubbles demand it.
    """

    def __init__(self, *args, **kw):
        if kw.get("schedule", "gpipe") != "gpipe":
            raise ValueError(
                "GemsMasterTrainer runs the gpipe schedule: the GEMS pair "
                "fills bubbles with the mirrored direction, not by "
                "interleaving virtual stages"
            )
        super().__init__(*args, **kw)

    @property
    def chunks(self) -> int:
        return 2 * self.config.times

    @property
    def x_spec(self):
        if self.n_spatial_cells > 0:
            return P(None, None, AXIS_DATA, AXIS_TILE_H, AXIS_TILE_W, None)
        return P(None, None, AXIS_DATA, None, None, None)

    @property
    def y_spec(self):
        return P(None, None, AXIS_DATA)

    def _local_loss(self, params, x, y):
        """x: [2*times, parts, mb_local, ...]; chunk 2k → normal direction,
        chunk 2k+1 → mirrored (ref alternation, ``gems_master.py:72-103``).

        The chunk loop is a ``lax.scan`` over normal/mirror PAIRS: the
        compiled program contains exactly two pipeline schedules (one per
        direction — ``mirror`` changes the static ppermute wiring, so it
        cannot be a traced value) regardless of ``--times``; the reference's
        whole point of ``--times`` is raising it for effective batch
        (``gems_master.py:72-103``), which a Python unroll made quadratic-
        compile-cost here.
        """
        front_flat, stacked_local = params
        S = self.S
        flat = stacked_local[0]
        # Mirror exchange: device p receives device (S-1-p)'s stage params.
        flipped = lax.ppermute(
            stacked_local, AXIS_PIPE, [(i, S - 1 - i) for i in range(S)]
        )[0]

        def one_chunk(stage_flat, mirror, xc, yc):
            front_out = self._front(front_flat, xc)
            front_out, yc = self._back_inputs(front_out, yc)
            preds, stage_of = self._schedule(stage_flat, front_out, mirror)
            return self._contributions(preds, yc, stage_of)

        def pair_body(carry, inp):
            ce_tot, cc_tot = carry
            xp, yp = inp  # leading dim 2: (normal, mirrored) chunks
            for k, (stage_flat, mirror) in enumerate(
                ((flat, False), (flipped, True))
            ):
                ce, cc = one_chunk(
                    stage_flat, mirror, jax.tree.map(lambda a: a[k], xp), yp[k]
                )
                ce_tot = ce_tot + ce
                cc_tot = cc_tot + cc
            return (ce_tot, cc_tot), None

        xs = jax.tree.map(
            lambda a: a.reshape((self.config.times, 2) + tuple(a.shape[1:])), x
        )
        ys = y.reshape((self.config.times, 2) + tuple(y.shape[1:]))
        zero = jnp.zeros((), jnp.float32)
        (ce_tot, cc_tot), _ = lax.scan(pair_body, (zero, zero), (xs, ys))
        n_local = self.chunks * self.parts * self.mb_local
        return self._reduce_metrics(ce_tot, cc_tot, n_local)

    def shard_batch(self, x, y):
        """[2*times*B, H, W, C] → [2*times, parts, mb, H, W, C] on the mesh.
        Multi-process, (x, y) are this host's local batch shard."""
        from mpi4dl_tpu.parallel.multihost import put_global

        b = x.shape[0]
        if b % self.chunks:
            raise ValueError(
                f"GEMS batch must be 2*times*batch_size = {self.chunks} chunks"
            )
        per = b // self.chunks
        x = x.reshape((self.chunks, self.parts, per // self.parts) + tuple(x.shape[1:]))
        y = y.reshape((self.chunks, self.parts, per // self.parts))
        return put_global(self.mesh, (self.x_spec, self.y_spec), x, y)
