"""Halo exchange over the tile mesh axes.

TPU-native replacement for the reference's 9-neighbor isend/irecv machinery
(``src/torchgems/spatial.py:336-413``, neighbor model ``spatial.py:941-1017``).

The reference enumerates up to 8 neighbors (including corners) and posts
tagged MPI isend/irecv pairs per conv layer. On TPU the whole exchange is two
``lax.ppermute`` shift rounds inside ``shard_map``:

1. shift edge strips along ``tile_h`` (up and down);
2. shift edge strips (of the H-extended tile) along ``tile_w`` (left/right).

Round 2 operates on the output of round 1, so corner halos arrive via the
two-hop composition — no explicit diagonal neighbors needed. Devices at the
mesh boundary receive zeros from ``ppermute`` (sources absent from the
permutation), which reproduces the reference's ``ZeroPad2d`` edge semantics
(``spatial.py:130-144``) exactly.

Everything here runs *inside* ``shard_map`` on a local tile of layout
``[batch, H_local, W_local, C]`` (NHWC — the TPU-friendly layout; the
reference is NCHW).
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp
from jax import lax

from mpi4dl_tpu.compat import axis_size

# -- Pallas-impl safety plumbing (see halo_exchange's impl dispatch) ---------

_XLA_ONLY_DEPTH = [0]


@contextlib.contextmanager
def xla_halo_only():
    """Force the XLA halo implementation while tracing the enclosed region.

    Batched callers (the pipeline's vmapped front) MUST wrap their tracing
    in this: the Pallas remote-DMA kernel deadlocks under vmap batching,
    and tracer sniffing cannot see a vmap through initial-style transforms
    (checkpoint, scan)."""
    _XLA_ONLY_DEPTH[0] += 1
    try:
        yield
    finally:
        _XLA_ONLY_DEPTH[0] -= 1


def _xla_only_active() -> bool:
    return _XLA_ONLY_DEPTH[0] > 0


# Explicit-impl downgrade warns ONCE per process: the hazard (a caller who
# typed impl="pallas" silently running XLA) needs one loud line, not one
# per traced layer — a 54-cell model would emit hundreds of identical
# warnings per trace. Env-selected pallas downgrades silently by design.
_PALLAS_DOWNGRADE_WARNED = [False]


def _reset_pallas_downgrade_warning() -> None:
    """Test hook: re-arm the once-per-process downgrade warning."""
    _PALLAS_DOWNGRADE_WARNED[0] = False


def _is_batch_tracer(x) -> bool:
    try:  # private module — absence must degrade to "don't know", not crash
        from jax._src.interpreters import batching

        return isinstance(x, batching.BatchTracer)
    except Exception:  # pragma: no cover - jax internals moved
        return False


_SHIFT_COUNTERS: list = []  # stacked boxes armed by count_halo_shifts


@contextlib.contextmanager
def count_halo_shifts():
    """Count halo shift ppermutes issued while tracing the enclosed region.

    Each :func:`_shift` over an axis of size > 1 lowers to exactly one
    ``collective-permute``, so the count taken over ONE un-scanned forward
    pass is the partition-math floor for the compiled program's permute
    inventory (the backward re-runs the transposed shifts, at most doubling
    it) — the derivation :mod:`mpi4dl_tpu.analysis.rules` checks against.
    Yields a one-element list whose [0] is the running count.
    """
    box = [0]
    _SHIFT_COUNTERS.append(box)
    try:
        yield box
    finally:
        _SHIFT_COUNTERS.remove(box)


def _shift(x, axis_name: str, direction: int):
    """ppermute x one step along a mesh axis; missing sources yield zeros."""
    n = axis_size(axis_name)
    if n > 1:
        for box in _SHIFT_COUNTERS:
            box[0] += 1
    perm = [(i, i + direction) for i in range(n) if 0 <= i + direction < n]
    return lax.ppermute(x, axis_name, perm)


def gather_tiles(x, axis_h: str = "tile_h", axis_w: str = "tile_w"):
    """Reassemble the full image from tiles (inside shard_map).

    The join-rank merge of the reference (``merge_inputs_joint_cat``,
    ``train_spatial.py:1083-1188``): there, the first LP rank after the
    spatial stage irecvs one tile per spatial part and ``torch.cat``s them
    rows/cols per slice method. Here it is two tiled ``all_gather``s — rows
    along ``tile_h`` (concat on array axis 1), then cols along ``tile_w``
    (axis 2); gather order along a mesh axis is axis-index order, which is
    exactly the reference's row-major tile layout (``split_input``,
    ``train_spatial.py:241-290``).
    """
    if axis_size(axis_h) > 1:
        x = lax.all_gather(x, axis_h, axis=1, tiled=True)
    if axis_size(axis_w) > 1:
        x = lax.all_gather(x, axis_w, axis=2, tiled=True)
    return x


def halo_exchange(
    x,
    halo_h: int,
    halo_w: int,
    axis_h: str = "tile_h",
    axis_w: str = "tile_w",
    fill_value: float = 0.0,
    impl: str | None = None,
):
    """Return the local tile padded with ``halo_h``/``halo_w`` rows/cols of
    neighbor data (``fill_value`` at the global image boundary).

    x: [B, H, W, C] local tile (inside shard_map).
    Result: [B, H + 2*halo_h, W + 2*halo_w, C].

    Equivalent of ref ``start_halo_exchange`` + ``end_halo_exchange`` +
    ``copy_halo_exchange_values`` (``spatial.py:336-413``) fused into pure
    dataflow — no tags, no waits, no ``cuda.synchronize``.

    ``fill_value=0`` reproduces conv ``ZeroPad2d`` semantics
    (``spatial.py:130-144``); max pooling passes ``-inf`` so the distributed
    pool matches single-device max pooling exactly (the reference zero-pads
    its distributed max pool, silently diverging from torch's -inf-padded
    ``MaxPool2d`` for negative boundary activations — we fix that).

    ``impl``: ``"xla"`` (ppermute shifts, default) or ``"pallas"`` (one
    bidirectional remote-DMA kernel per axis —
    :mod:`mpi4dl_tpu.ops.halo_pallas`); unset → ``MPI4DL_TPU_HALO_IMPL``.
    """
    from mpi4dl_tpu.ops.halo_pallas import default_impl, halo_exchange_pallas

    explicit = impl is not None
    if impl is None:
        impl = default_impl()
    if impl == "pallas":
        # The remote-DMA kernel is only safe UN-batched: under vmap (the
        # pipeline's micro-batched front) the batching rule adds a grid
        # dimension whose per-step DMAs interleave across devices and
        # deadlock (reproduced on the 8-device interpreter mesh). Batched
        # callers declare themselves with :func:`xla_halo_only` (the
        # pipeline front does); a tracer sniff backs that up for direct
        # vmap use, but initial-style transforms (checkpoint/scan) between
        # the vmap and this call hide the batch tracer — the context
        # manager is the reliable mechanism.
        if not _xla_only_active() and not _is_batch_tracer(x):
            return halo_exchange_pallas(
                x, halo_h, halo_w, axis_h, axis_w, fill_value
            )
        if explicit and not _PALLAS_DOWNGRADE_WARNED[0]:
            import warnings

            _PALLAS_DOWNGRADE_WARNED[0] = True
            warnings.warn(
                "halo_exchange(impl='pallas') downgraded to the XLA path: "
                "the Pallas remote-DMA kernel deadlocks under batched "
                "(vmapped) tracing"
            )
        impl = "xla"
    if impl != "xla":
        raise ValueError(f"halo impl must be 'xla' or 'pallas', got {impl!r}")
    b, h, w, c = x.shape

    def _edge_fill(strip, axis_name, at_index):
        """Overwrite a received strip with fill_value on boundary devices
        (ppermute already delivered zeros there; rewrite if fill != 0)."""
        if fill_value == 0.0:
            return strip
        return jnp.where(
            lax.axis_index(axis_name) == at_index,
            jnp.full_like(strip, fill_value),
            strip,
        )

    if halo_h > 0:
        if halo_h > h:
            raise ValueError(f"halo_h={halo_h} exceeds local tile height {h}")
        # Neighbor above sends its bottom strip down (+1); neighbor below
        # sends its top strip up (-1).
        from_above = _shift(x[:, h - halo_h :, :, :], axis_h, +1)
        from_below = _shift(x[:, :halo_h, :, :], axis_h, -1)
        from_above = _edge_fill(from_above, axis_h, 0)
        from_below = _edge_fill(from_below, axis_h, axis_size(axis_h) - 1)
        x = jnp.concatenate([from_above, x, from_below], axis=1)
    if halo_w > 0:
        if halo_w > w:
            raise ValueError(f"halo_w={halo_w} exceeds local tile width {w}")
        from_left = _shift(x[:, :, w - halo_w :, :], axis_w, +1)
        from_right = _shift(x[:, :, :halo_w, :], axis_w, -1)
        from_left = _edge_fill(from_left, axis_w, 0)
        from_right = _edge_fill(from_right, axis_w, axis_size(axis_w) - 1)
        x = jnp.concatenate([from_left, x, from_right], axis=2)
    return x


def fill_boundary_halo(
    x,
    halo_h: int,
    halo_w: int,
    value: float = 0.0,
    axis_h: str = "tile_h",
    axis_w: str = "tile_w",
):
    """Overwrite the halo positions of a halo-carrying tile that lie OUTSIDE
    the global image with ``value``.

    Needed for exact D1<->D2 equivalence: in the D1 (per-conv exchange) form
    every windowed op pads *after* the preceding BN+ReLU, while the D2 fused
    form fetches the halo once up front — by op time the boundary pad values
    have been shifted by BN/ReLU. Re-filling the outside-image ring right
    before each VALID windowed op restores the D1 semantics layer-by-layer
    (the reference's D2 silently accepts this boundary divergence; we don't).
    ``value``: 0 for convs / zero-pad pools, ``-inf`` for max pools.
    """
    b, h, w, c = x.shape
    if halo_h:
        idx = lax.axis_index(axis_h)
        n = axis_size(axis_h)
        row = jnp.arange(h)
        outside = ((idx == 0) & (row < halo_h)) | (
            (idx == n - 1) & (row >= h - halo_h)
        )
        x = jnp.where(outside[None, :, None, None], value, x)
    if halo_w:
        idx = lax.axis_index(axis_w)
        n = axis_size(axis_w)
        col = jnp.arange(w)
        outside = ((idx == 0) & (col < halo_w)) | (
            (idx == n - 1) & (col >= w - halo_w)
        )
        x = jnp.where(outside[None, None, :, None], value, x)
    return x


def zero_boundary_halo(x, halo_h: int, halo_w: int, axis_h: str = "tile_h", axis_w: str = "tile_w"):
    """:func:`fill_boundary_halo` with value 0 (conv ``ZeroPad2d`` parity)."""
    return fill_boundary_halo(x, halo_h, halo_w, 0.0, axis_h, axis_w)
