from mpi4dl_tpu.parallel.halo import halo_exchange  # noqa: F401
