"""Stage partitioning + shape tracing.

TPU-native replacement for the reference's ``model_generator``
(``src/torchgems/mp_pipeline.py:28-168``). The reference slices a flat
``nn.Sequential`` into ``split_size`` contiguous stages (even split or a user
``balance`` list) and discovers per-stage output shapes by *dry-running* each
stage on a batch-1 zeros tensor on GPU (``get_output_shapes``
``mp_pipeline.py:126-168``). Here models are flat **cell lists** and shape
tracing is ``jax.eval_shape`` — exact, free, and no device round-trip, so no
two-phase "trace small then rescale" dance (``benchmark_resnet_lp.py:92-161``)
is needed; we trace at the real size directly.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax


def stage_bounds(
    num_layers: int, split_size: int, balance: Sequence[int] | None = None
) -> list[tuple[int, int]]:
    """Per-stage ``(start, end)`` cell indices.

    Parity with ``get_start_end_layer_index`` (``mp_pipeline.py:41-69``):
    even split is ``floor(n/split)`` per stage with the remainder folded into
    the last stage; ``balance`` gives explicit per-stage counts and must sum
    to the layer count.
    """
    if split_size < 1:
        raise ValueError("split_size must be >= 1")
    if balance is not None:
        if len(balance) != split_size:
            raise ValueError("balance list length must equal split_size")
        if sum(balance) != num_layers:
            raise ValueError(
                f"balance {tuple(balance)} sums to {sum(balance)}, "
                f"model has {num_layers} layers"
            )
        bounds, start = [], 0
        for b in balance:
            bounds.append((start, start + b))
            start += b
        return bounds
    if split_size > num_layers:
        raise ValueError(f"cannot split {num_layers} layers into {split_size} stages")
    per = num_layers // split_size
    bounds = [(i * per, (i + 1) * per) for i in range(split_size)]
    bounds[-1] = (bounds[-1][0], num_layers)
    return bounds


def split_cells(
    cells: Sequence[Any], split_size: int, balance: Sequence[int] | None = None
) -> list[list[Any]]:
    """Slice a flat cell list into per-stage cell lists (ref ``get_model``,
    ``mp_pipeline.py:71-83``)."""
    return [
        list(cells[s:e]) for s, e in stage_bounds(len(cells), split_size, balance)
    ]


def _apply_stage(stage_cells, variables_list, x):
    for cell, variables in zip(stage_cells, variables_list):
        x = cell.apply(variables, x)
    return x


def init_cells(cells: Sequence[Any], rng, x) -> list[Any]:
    """Initialize a flat cell list sequentially, threading activations.

    Returns one variables dict per cell. Must be called on the *plain*
    (non-spatial) twin of a model — spatial cells contain collectives that
    need mesh axis bindings; plain twins have identical parameter structure
    (same submodule names), so the resulting params drop into the spatial
    model unchanged.
    """
    rngs = jax.random.split(rng, len(cells))
    out = []
    for cell, r in zip(cells, rngs):
        variables = cell.init(r, x)
        x = cell.apply(variables, x)
        out.append(variables)
    return out


def eval_stage_shapes(cells: Sequence[Any], x):
    """One ``jax.eval_shape`` pass over a cell list on abstract input ``x``
    (pytree of ``ShapeDtypeStruct``). Returns ``(out_structs, shape_tree)``
    where shape_tree mirrors the output pytree with plain shape tuples.

    The single tracing primitive behind both :func:`trace_shapes` and the
    pipeline's wire-shape planning — the replacement for the reference's
    batch-1-zeros GPU dry-run (``get_output_shapes`` ``mp_pipeline.py:126-168``).
    """
    rng = jax.random.PRNGKey(0)

    def run(xx):
        vs = init_cells(cells, rng, xx)
        return _apply_stage(cells, vs, xx)

    out = jax.eval_shape(run, x)
    shapes = jax.tree.map(
        lambda s: tuple(s.shape),
        out,
        is_leaf=lambda s: isinstance(s, jax.ShapeDtypeStruct),
    )
    return out, shapes


def trace_shapes(
    cells: Sequence[Any],
    split_size: int,
    input_shape: Sequence[int],
    balance: Sequence[int] | None = None,
    dtype=None,
) -> list[Any]:
    """Per-stage output shapes (ref ``get_output_shapes``
    ``mp_pipeline.py:126-168``) via ``jax.eval_shape`` on the plain model.

    Returns one entry per stage: a shape tuple, or a pytree of shape tuples
    for multi-output stages (AmoebaNet cells return ``(concat, skip)``; the
    reference calls this ``MULTIPLE_INPUT/OUTPUT``).
    """
    import jax.numpy as jnp

    dtype = dtype or jnp.float32
    x = jax.ShapeDtypeStruct(tuple(input_shape), dtype)
    shapes: list[Any] = []
    for stage_cells in split_cells(cells, split_size, balance):
        x, stage_shapes = eval_stage_shapes(stage_cells, x)
        shapes.append(stage_shapes)
    return shapes


def spatial_shape(shape: Sequence[int], tile_shape: tuple[int, int]) -> tuple[int, ...]:
    """Per-tile shape of a spatially partitioned NHWC activation (ref
    ``get_shapes_spatial`` rescaling, ``train_spatial.py:61-238``)."""
    b, h, w, c = shape
    th, tw = tile_shape
    if h % th or w % tw:
        raise ValueError(f"activation {shape} not divisible by tile grid {tile_shape}")
    return (b, h // th, w // tw, c)
