"""Incident engine: cross-signal correlation, causal timelines, and
auto-postmortems (docs/OBSERVABILITY.md "Incidents").

The fleet already emits every signal a human postmortem hand-joins —
``alert.transition``, ``elastic.restart``, ``canary.failure``,
``oom.report``, ``tail.sample``, ``chaos.injected``, ``flight.dump`` —
across N processes' JSONL logs. :class:`IncidentManager` rides an alert
surface (the federation aggregator's ``/alertz`` payload, or an
engine-local SLO evaluator's) and turns pages into *incidents*:

- **open** when any watched alert reaches ``firing``; alerts that fire
  while an incident is open FOLD into it as members (one incident per
  fault, not one per symptom);
- **correlate**: evidence events within a lookback window are ordered
  on one causally consistent timeline (span segments, when asked for,
  are anchored via trace-export's wall-anchored monotonic marks so
  cross-pid ordering survives skewed wall clocks);
- **blame**: a small typed rule table (:data:`FIRST_CAUSE_RULES`) names
  the first-cause candidate — an injected chaos op beats everything,
  an OOM beats a restart, a canary failure explains a numerics page, a
  restart explains an availability page, and the first firing page
  itself is the honest fallback;
- **measure blast radius**: affected trace ids (tail samples +
  histogram exemplars), tenants, requeues/sheds and SLO budget burned
  across the window (from ``metrics`` snapshots when the log carries
  them — absent, not zero);
- **close** when every member alert resolves, emitting the
  machine-readable postmortem artifact next to the logs.

Everything the live manager computes goes through the same pure
builders (:func:`build_timeline`, :func:`first_cause`,
:func:`blast_radius`, :func:`build_postmortem`,
:func:`reconstruct_incidents`) the offline analyzer
(``python -m mpi4dl_tpu.analyze incident``) uses — the live
``/incidentz`` timeline and the from-logs reconstruction are the same
code over the same files, so they match event for event.

Lifecycle events (``incident.open`` / ``incident.update`` /
``incident.close``) are schema-valid ``kind="event"`` JSONL records and
flush immediately. Metrics (``incidents_total{state}``,
``incident_open``, ``incident_mtta_seconds``,
``incident_mttr_seconds``) are cataloged.
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading
import time

from mpi4dl_tpu.telemetry.jsonl import ENV_DIR, validate_event
from mpi4dl_tpu.telemetry.spans import _event_wall_start, new_trace_id

DEFAULT_LOOKBACK_S = 120.0

#: Event names that count as correlated evidence on an incident
#: timeline. All are ``kind="event"`` (immediate-flush) records, so a
#: timeline built at close time and one rebuilt later from the same
#: files agree. The incident's own lifecycle events are deliberately
#: NOT evidence.
EVIDENCE_EVENTS = (
    "chaos.injected",
    "oom.report",
    "canary.failure",
    "elastic.restart",
    "flight.dump",
    "journal.replay",
    "tail.sample",
    "alert.transition",
)

#: Causal tie-break at equal wall time: causes order before their
#: symptoms (a chaos op and the page it trips can share a timestamp at
#: coarse clock resolution).
_CAUSAL_RANK = {name: i for i, name in enumerate(EVIDENCE_EVENTS)}

#: The typed first-cause rule table, in PRIORITY order: the first rule
#: with a matching in-window event wins, earliest matching event first.
#: ``alerts`` are fnmatch patterns over the incident's member alert
#: names ("*" = the cause explains any page).
FIRST_CAUSE_RULES = (
    {"event": "chaos.injected", "alerts": ("*",),
     "label": "injected chaos op {op}"},
    {"event": "oom.report", "alerts": ("*",),
     "label": "out-of-memory in {program}"},
    {"event": "canary.failure", "alerts": ("numerics_divergence",),
     "label": "numerics canary failure ({check})"},
    {"event": "elastic.restart",
     "alerts": ("replica_unreachable", "availability_*",
                "fleet_circuit_*", "latency_*"),
     "label": "replica restart ({replica}: {reason})"},
    {"event": "alert.transition", "alerts": ("*",),
     "label": "first firing page {alert} (no earlier cause on the log)"},
)


class _Fmt(dict):
    """format_map that leaves unknown fields visible instead of raising."""

    def __missing__(self, key):  # pragma: no cover - trivial
        return f"<{key}?>"


def event_wall_ts(ev: dict) -> float:
    """Wall-clock position of an event on the shared timeline: plain
    events sit at their emission ``ts``; span events are anchored at
    their first span's wall start (``_event_wall_start``) — per-process
    monotonic marks re-based onto the shared wall clock, the same
    cross-pid alignment trace-export uses."""
    if ev.get("kind") == "span" and ev.get("spans"):
        return float(_event_wall_start(ev))
    return float(ev.get("ts", 0.0))


def collect_events(paths) -> "list[dict]":
    """Schema-valid events from JSONL files and/or directories, SKIPPING
    undecodable or invalid lines (a SIGKILLed writer can leave a
    truncated tail; a postmortem must survive its own crime scene).
    ``.jsonl`` files only when a directory is given."""
    files: "list[str]" = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(
                os.path.join(p, f) for f in sorted(os.listdir(p))
                if f.endswith(".jsonl")
            )
        else:
            files.append(p)
    out: "list[dict]" = []
    for path in files:
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(validate_event(json.loads(line)))
                    except (ValueError, TypeError):
                        continue
        except OSError:
            continue
    return out


def build_timeline(
    events,
    start_ts: float,
    end_ts: float,
    include_spans: bool = False,
    trace_ids=None,
) -> "list[dict]":
    """One causally consistent timeline over ``[start_ts, end_ts]``
    (wall clock): evidence events ordered by wall time with causes
    tie-breaking before symptoms. With ``include_spans``, span events
    (optionally restricted to ``trace_ids``) join at their wall-anchored
    START — two processes' segments interleave correctly even when one
    pid's spans were emitted (ts) after the other's despite starting
    first."""
    out: "list[dict]" = []
    for ev in events:
        kind = ev.get("kind")
        if kind == "event":
            name = ev.get("name")
            if name not in EVIDENCE_EVENTS:
                continue
            w = float(ev["ts"])
            if not start_ts <= w <= end_ts:
                continue
            out.append({
                "ts": round(w, 6),
                "kind": "event",
                "name": name,
                "attrs": dict(ev.get("attrs") or {}),
            })
        elif kind == "span" and include_spans:
            if trace_ids is not None and ev.get("trace_id") not in trace_ids:
                continue
            w = event_wall_ts(ev)
            if not start_ts <= w <= end_ts:
                continue
            spans = ev["spans"]
            out.append({
                "ts": round(w, 6),
                "kind": "span",
                "name": ev["name"],
                "trace_id": ev["trace_id"],
                "phases": [s["phase"] for s in spans],
                "duration_s": round(
                    spans[-1]["end_s"] - spans[0]["start_s"], 6
                ),
                "attrs": dict(ev.get("attrs") or {}),
            })
    out.sort(key=lambda e: (
        e["ts"], _CAUSAL_RANK.get(e["name"], len(EVIDENCE_EVENTS)),
        e["name"],
    ))
    return out


def first_cause(timeline, members) -> "dict | None":
    """Apply :data:`FIRST_CAUSE_RULES` to a timeline: the
    highest-priority rule whose alert patterns intersect the member
    alert names and that has at least one in-window event names the
    first-cause candidate (earliest such event)."""
    names = set(members or ())
    for rule in FIRST_CAUSE_RULES:
        pats = rule["alerts"]
        if "*" not in pats and not any(
            fnmatch.fnmatch(m, p) for m in names for p in pats
        ):
            continue
        for e in timeline:  # timeline is ordered: first hit = earliest
            if e["kind"] != "event" or e["name"] != rule["event"]:
                continue
            attrs = e.get("attrs", {})
            if e["name"] == "alert.transition":
                if attrs.get("to") != "firing":
                    continue
                if names and attrs.get("alert") not in names:
                    continue
            return {
                "event": e["name"],
                "ts": e["ts"],
                "label": str(rule["label"]).format_map(_Fmt(attrs)),
                "attrs": attrs,
                "rule": rule["event"],
            }
    return None


def _metric_total(metrics: dict, name: str) -> "float | None":
    m = metrics.get(name)
    if not isinstance(m, dict):
        return None
    total = 0.0
    seen = False
    for s in m.get("series", ()):
        v = s.get("value")
        if isinstance(v, (int, float)):
            total += v
            seen = True
    return total if seen else None


def _window_burn(snapshots, name: str) -> "float | None":
    """last - first of a counter total across the window's ``metrics``
    snapshots; None (absent, not zero) with fewer than two sightings."""
    vals = [
        v for v in (_metric_total(s["metrics"], name) for s in snapshots)
        if v is not None
    ]
    if len(vals) < 2:
        return None
    return round(max(0.0, vals[-1] - vals[0]), 6)


def blast_radius(events, start_ts: float, end_ts: float) -> dict:
    """Who and what the incident touched, from the window's events:
    affected trace ids (``tail.sample`` + histogram exemplars inside
    ``metrics`` snapshots), tenants, and — when the window carries at
    least two ``metrics`` snapshots (flight dumps embed one) —
    requeues, sheds, and SLO error budget burned across it."""
    trace_ids: "set[str]" = set()
    tenants: "set[str]" = set()
    snapshots: "list[dict]" = []
    budget_first: "dict[str, float]" = {}
    budget_last: "dict[str, float]" = {}
    for ev in events:
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or not start_ts <= ts <= end_ts:
            continue
        kind = ev.get("kind")
        if kind == "event" and ev.get("name") == "tail.sample":
            attrs = ev.get("attrs") or {}
            if attrs.get("trace_id"):
                trace_ids.add(str(attrs["trace_id"]))
            if attrs.get("tenant"):
                tenants.add(str(attrs["tenant"]))
        elif kind == "metrics":
            metrics = ev.get("metrics") or {}
            snapshots.append({"ts": ts, "metrics": metrics})
            for m in metrics.values():
                if not isinstance(m, dict):
                    continue
                for s in m.get("series", ()):
                    for ex in (s.get("exemplars") or {}).values():
                        tid = (ex or {}).get("trace_id")
                        if tid:
                            trace_ids.add(str(tid))
            rem = metrics.get("slo_error_budget_remaining")
            if isinstance(rem, dict):
                for s in rem.get("series", ()):
                    slo = (s.get("labels") or {}).get("slo", "")
                    v = s.get("value")
                    if isinstance(v, (int, float)):
                        budget_first.setdefault(slo, v)
                        budget_last[slo] = v
    snapshots.sort(key=lambda s: s["ts"])
    burned = {
        slo: round(max(0.0, budget_first[slo] - budget_last[slo]), 6)
        for slo in budget_first
    }
    sheds = [
        v for v in (
            _window_burn(snapshots, "serve_class_shed_total"),
            _window_burn(snapshots, "tenant_quota_sheds_total"),
        ) if v is not None
    ]
    return {
        "n_traces": len(trace_ids),
        "trace_ids": sorted(trace_ids)[:50],
        "tenants": sorted(tenants),
        "requeues": _window_burn(snapshots, "fleet_requeues_total"),
        "sheds": sum(sheds) if sheds else None,
        "slo_budget_burned": burned or None,
    }


def build_postmortem(record: dict, events, now: "float | None" = None) -> dict:
    """The machine-readable postmortem for one incident record: the
    lookback-windowed timeline, the named first cause, the blast
    radius, and the flight dumps captured in the window. Pure — the
    live manager and the offline analyzer both call exactly this."""
    lookback = float(record.get("lookback_s") or DEFAULT_LOOKBACK_S)
    start = float(record["opened_ts"]) - lookback
    # Evidence already explained by a PREVIOUS incident is not
    # re-blamed: the window never reaches past the prior close (the
    # floor travels in incident.open, so the offline rebuild agrees).
    floor = record.get("evidence_floor_ts")
    if isinstance(floor, (int, float)):
        start = max(start, float(floor))
    end = record.get("closed_ts")
    if end is None:
        end = now
    if end is None:
        tss = [
            ev["ts"] for ev in events
            if isinstance(ev.get("ts"), (int, float))
        ]
        end = max(tss) if tss else float(record["opened_ts"])
    timeline = build_timeline(events, start, float(end))
    members = record.get("members") or {}
    dumps = [
        {"ts": e["ts"], "reason": e["attrs"].get("reason"),
         "incident": e["attrs"].get("incident"),
         "trigger": e["attrs"].get("trigger"),
         "events": e["attrs"].get("events")}
        for e in timeline if e["name"] == "flight.dump"
    ]
    return {
        "incident": {
            "id": record["id"],
            "state": record.get("state", "open"),
            "opened_ts": record["opened_ts"],
            "closed_ts": record.get("closed_ts"),
            "opened_by": record.get("opened_by"),
            "members": members,
            "mtta_s": record.get("mtta_s"),
            "mttr_s": record.get("mttr_s"),
            "lookback_s": lookback,
            "evidence_floor_ts": record.get("evidence_floor_ts"),
        },
        "first_cause": first_cause(timeline, members),
        "blast_radius": blast_radius(events, start, float(end)),
        "dumps": dumps,
        "timeline": timeline,
    }


def reconstruct_incidents(events) -> "list[dict]":
    """Incident records rebuilt from ``incident.open/update/close``
    lifecycle events alone — the offline half. Ordered by open time."""
    recs: "dict[str, dict]" = {}
    lifecycle = sorted(
        (
            ev for ev in events
            if ev.get("kind") == "event"
            and str(ev.get("name", "")).startswith("incident.")
        ),
        key=lambda e: e["ts"],
    )
    for ev in lifecycle:
        attrs = ev.get("attrs") or {}
        iid = attrs.get("id")
        if not iid:
            continue
        if ev["name"] == "incident.open":
            recs[iid] = {
                "id": iid,
                "state": "open",
                "opened_ts": float(attrs.get("opened_ts", ev["ts"])),
                "closed_ts": None,
                "opened_by": attrs.get("alert"),
                "members": {
                    m["name"]: {
                        "severity": m.get("severity"),
                        "first_firing_ts": m.get("first_firing_ts"),
                        "resolved_ts": None,
                    }
                    for m in attrs.get("members", ())
                    if isinstance(m, dict) and m.get("name")
                },
                "mtta_s": attrs.get("mtta_s"),
                "mttr_s": None,
                "lookback_s": attrs.get("lookback_s"),
                "evidence_floor_ts": attrs.get("evidence_floor_ts"),
            }
        elif ev["name"] == "incident.update" and iid in recs:
            name = attrs.get("alert")
            if name:
                recs[iid]["members"][name] = {
                    "severity": attrs.get("severity"),
                    "first_firing_ts": attrs.get("first_firing_ts"),
                    "resolved_ts": None,
                }
        elif ev["name"] == "incident.close" and iid in recs:
            recs[iid]["state"] = "closed"
            recs[iid]["closed_ts"] = float(attrs.get("closed_ts", ev["ts"]))
            recs[iid]["mttr_s"] = attrs.get("mttr_s")
            for m in attrs.get("members", ()):
                if isinstance(m, dict) and m.get("name") in recs[iid][
                    "members"
                ]:
                    recs[iid]["members"][m["name"]]["resolved_ts"] = m.get(
                        "resolved_ts"
                    )
    return sorted(recs.values(), key=lambda r: r["opened_ts"])


class IncidentManager:
    """Alert-driven incident lifecycle daemon.

    alerts: callable returning an ``/alertz``-shaped payload
        (``{"alerts": [AlertState.snapshot(), ...], "transitions":
        [alert.transition events, ...]}``) — the federation
        aggregator's :meth:`alertz_state` or an engine SLO evaluator's
        :meth:`state`.
    registry: where the cataloged incident metrics are declared (None
        disables metrics).
    events: optional shared :class:`JsonlWriter` for the lifecycle
        events (never closed by the manager). flight: optional
        :class:`FlightRecorder` ring that mirrors them.
    telemetry_dir: directory scanned for correlated evidence; defaults
        to the events writer's directory, then ``MPI4DL_TPU_TELEMETRY_DIR``.
    lookback_s: evidence window reaching back before open.
    severities: alert severities that open/join incidents (advisory
        tickets do not page anyone at 3am).
    wall_clock: injectable wall clock (records and events are
        windowed against log timestamps, which are wall time).

    Drive it with :meth:`step` from an existing loop (the federation
    aggregator ticks it after every scrape) or :meth:`start` a daemon
    thread. :meth:`state` is the ``/incidentz`` payload.
    """

    def __init__(
        self,
        alerts,
        registry=None,
        events=None,
        flight=None,
        telemetry_dir: "str | None" = None,
        lookback_s: float = DEFAULT_LOOKBACK_S,
        severities=("page",),
        wall_clock=time.time,
        source: str = "federation",
    ):
        self.alerts = alerts
        self.events = events
        self.flight = flight
        self.telemetry_dir = telemetry_dir
        self.lookback_s = float(lookback_s)
        self.severities = tuple(severities)
        self.source = str(source)
        self._wall = wall_clock
        self._lock = threading.Lock()
        # Evidence at-or-before this wall time belongs to a PREVIOUS
        # incident (or predates this manager watching) and is excluded
        # from new windows; advanced to closed_ts at every close.
        self.evidence_floor_ts: "float | None" = None
        self.open_incident: "dict | None" = None
        self.closed: "list[dict]" = []
        self.opened_total = 0
        self.closed_total = 0
        self._m_total = self._m_open = None
        self._m_mtta = self._m_mttr = None
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._m_total = telemetry.declare(registry, "incidents_total")
            self._m_open = telemetry.declare(registry, "incident_open")
            self._m_mtta = telemetry.declare(
                registry, "incident_mtta_seconds"
            )
            self._m_mttr = telemetry.declare(
                registry, "incident_mttr_seconds"
            )
            self._m_open.set(0.0)
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None

    # -- lifecycle -------------------------------------------------------------

    def open_incident_id(self) -> "str | None":
        """The currently open incident's id (the flight recorder's
        ``incident=`` provider), or None."""
        with self._lock:
            return self.open_incident["id"] if self.open_incident else None

    def step(self, now: "float | None" = None) -> None:
        """One evaluation: poll the alert surface, open / fold /
        resolve / close. Exceptions stay inside — the host loop (a
        scrape tick) must survive a bad evaluation."""
        try:
            payload = self.alerts() or {}
        except Exception:  # noqa: BLE001 — a broken alert surface must
            return  # not take the scrape loop with it
        wall = self._wall() if now is None else float(now)
        firing = {
            a["name"]: a
            for a in payload.get("alerts", ())
            if a.get("state") == "firing"
            and a.get("severity") in self.severities
        }
        transitions = payload.get("transitions", ())
        with self._lock:
            inc = self.open_incident
            if inc is None:
                if firing:
                    self._open(firing, transitions, wall)
            else:
                for name, a in firing.items():
                    m = inc["members"].get(name)
                    if m is None:
                        inc["members"][name] = {
                            "severity": a.get("severity"),
                            "first_firing_ts": self._firing_ts(
                                name, transitions, wall
                            ),
                            "resolved_ts": None,
                        }
                        self._emit("incident.update", {
                            "id": inc["id"],
                            "alert": name,
                            "severity": a.get("severity"),
                            "first_firing_ts": inc["members"][name][
                                "first_firing_ts"
                            ],
                        }, wall)
                    elif m["resolved_ts"] is not None:
                        m["resolved_ts"] = None  # re-fired while open
                for name, m in inc["members"].items():
                    if name not in firing and m["resolved_ts"] is None:
                        m["resolved_ts"] = wall
                if all(
                    m["resolved_ts"] is not None
                    for m in inc["members"].values()
                ):
                    self._close(wall)
            if self._m_open is not None:
                self._m_open.set(1.0 if self.open_incident else 0.0)

    @staticmethod
    def _firing_ts(name: str, transitions, fallback: float) -> float:
        ts = fallback
        for tr in transitions:
            attrs = tr.get("attrs") or {}
            if attrs.get("alert") == name and attrs.get("to") == "firing":
                ts = float(tr.get("ts", fallback))
        return ts

    def _open(self, firing: dict, transitions, wall: float) -> None:
        members = {
            name: {
                "severity": a.get("severity"),
                "first_firing_ts": self._firing_ts(name, transitions, wall),
                "resolved_ts": None,
            }
            for name, a in firing.items()
        }
        opened_by = min(
            members, key=lambda n: members[n]["first_firing_ts"]
        )
        mtta = max(
            0.0, wall - min(m["first_firing_ts"] for m in members.values())
        )
        inc = {
            "id": new_trace_id("inc"),
            "state": "open",
            "opened_ts": wall,
            "closed_ts": None,
            "opened_by": opened_by,
            "members": members,
            "mtta_s": round(mtta, 6),
            "mttr_s": None,
            "lookback_s": self.lookback_s,
            "evidence_floor_ts": self.evidence_floor_ts,
            "source": self.source,
        }
        self.open_incident = inc
        self.opened_total += 1
        if self._m_total is not None:
            self._m_total.inc(state="opened")
            self._m_mtta.set(inc["mtta_s"])
        self._emit("incident.open", {
            "id": inc["id"],
            "opened_ts": wall,
            "alert": opened_by,
            "severity": members[opened_by]["severity"],
            "mtta_s": inc["mtta_s"],
            "lookback_s": self.lookback_s,
            "evidence_floor_ts": inc["evidence_floor_ts"],
            "source": self.source,
            "members": [
                {"name": n, "severity": m["severity"],
                 "first_firing_ts": m["first_firing_ts"]}
                for n, m in members.items()
            ],
        }, wall)

    def _close(self, wall: float) -> None:
        inc = self.open_incident
        inc["state"] = "closed"
        inc["closed_ts"] = wall
        inc["mttr_s"] = round(wall - inc["opened_ts"], 6)
        self.evidence_floor_ts = wall  # this incident consumed its window
        self.open_incident = None
        self.closed.append(inc)
        del self.closed[:-32]
        self.closed_total += 1
        if self._m_total is not None:
            self._m_total.inc(state="closed")
            self._m_mttr.set(inc["mttr_s"])
        # The postmortem: computed once over the evidence on disk NOW
        # (lifecycle events flush immediately, so a later offline
        # rebuild over the same files reproduces the same timeline).
        pm = build_postmortem(inc, self._scan(), now=wall)
        path = self._write_postmortem(pm)
        cause = pm.get("first_cause") or {}
        self._emit("incident.close", {
            "id": inc["id"],
            "closed_ts": wall,
            "mttr_s": inc["mttr_s"],
            "members": [
                {"name": n, "severity": m["severity"],
                 "resolved_ts": m["resolved_ts"]}
                for n, m in inc["members"].items()
            ],
            "first_cause": {
                "event": cause.get("event"),
                "label": cause.get("label"),
                "ts": cause.get("ts"),
            },
            "blast_radius": {
                k: v for k, v in pm["blast_radius"].items()
                if k != "trace_ids"
            },
            "dumps": pm["dumps"],
            "postmortem": path,
        }, wall)

    def _emit(self, name: str, attrs: dict, wall: float) -> None:
        ev = {"ts": wall, "kind": "event", "name": name, "attrs": attrs}
        if self.flight is not None:
            try:
                self.flight.record(ev)
            except Exception:  # noqa: BLE001 — telemetry, not control
                pass
        if self.events is not None and getattr(self.events, "enabled", False):
            try:
                self.events.write(ev)
            except Exception:  # noqa: BLE001 — telemetry, not control
                pass

    # -- evidence + surfaces ---------------------------------------------------

    def _evidence_dir(self) -> "str | None":
        if self.telemetry_dir:
            return self.telemetry_dir
        path = getattr(self.events, "path", None) if self.events else None
        if path:
            return os.path.dirname(path)
        return os.environ.get(ENV_DIR)

    def _scan(self) -> "list[dict]":
        d = self._evidence_dir()
        if not d or not os.path.isdir(d):
            return []
        return collect_events([d])

    def _write_postmortem(self, pm: dict) -> "str | None":
        d = self._evidence_dir()
        if not d or not os.path.isdir(d):
            return None
        # .json, not .jsonl: the artifact must not be re-read as events.
        path = os.path.join(d, f"incident-{pm['incident']['id']}.json")
        try:
            with open(path, "w") as fh:
                json.dump(pm, fh, indent=2, sort_keys=True)
        except OSError:
            return None
        return path

    def state(self) -> dict:
        """The ``/incidentz`` payload: open incidents with a LIVE
        timeline, the recent closed ones rebuilt over the same logs,
        and lifetime counts."""
        with self._lock:
            open_recs = (
                [dict(self.open_incident)] if self.open_incident else []
            )
            closed_recs = [dict(r) for r in self.closed[-8:]]
            counts = {
                "opened": self.opened_total,
                "closed": self.closed_total,
            }
        events = self._scan()
        now = self._wall()
        return {
            "open": [build_postmortem(r, events, now) for r in open_recs],
            "closed": [build_postmortem(r, events) for r in closed_recs],
            "counts": counts,
            "lookback_s": self.lookback_s,
            "severities": list(self.severities),
            "source": self.source,
        }

    # -- optional daemon -------------------------------------------------------

    def start(self, interval_s: float = 1.0) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()

        def _run():
            while not self._stop_evt.wait(interval_s):
                self.step()

        self._thread = threading.Thread(
            target=_run, name="mpi4dl-incidents", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
