"""Process-wide metrics registry: counters, gauges, histograms.

The reference's observability is paired CUDA events plus prints
(SURVEY.md §5.1); our port so far only had :class:`profiling.StepTimer`
summaries and private counters inside ``ServingEngine`` that die with the
process. This module is the cross-cutting fix: one threadsafe registry any
layer can publish into, snapshotted as JSON (the JSONL event log, bench.py
result lines) or rendered in Prometheus text exposition format
(:mod:`mpi4dl_tpu.telemetry.export`).

Semantics follow the Prometheus data model:

- :class:`Counter` — monotone; ``inc`` by a non-negative amount only.
- :class:`Gauge` — settable to anything; ``inc``/``dec`` for convenience.
- :class:`Histogram` — cumulative ``le`` buckets + ``_sum``/``_count``,
  plus a bounded uniform reservoir (Vitter's algorithm R, seeded — runs
  must be reproducible) so snapshots can answer p50/p90/p99 through the
  same :func:`mpi4dl_tpu.profiling.percentiles` helper the StepTimer and
  load generator use: one percentile definition across the whole repo.

Every metric carries a fixed tuple of label NAMES; per-call label VALUES
select the series (``counter.inc(1, outcome="served")``). Registering the
same name twice returns the existing metric when type/labels/help agree
and raises when they don't — two subsystems silently disagreeing about
what a name means is exactly the bug a registry exists to prevent.

Histograms additionally carry OpenMetrics-style **exemplars**: an
``observe(value, exemplar=trace_id)`` retains, per bucket, the most
recent ``(trace_id, value, ts)`` — the aggregate→instance link that lets
a scrape answer "which request landed in the p99 bucket" with a real
trace id instead of a distribution (docs/OBSERVABILITY.md "Tail
forensics"). Exemplars ride ``snapshot_series()`` (so ``/snapshotz`` and
the federation merge carry them) and render as ``# {trace_id="..."}``
suffixes in the text exposition (:mod:`mpi4dl_tpu.telemetry.export`).
"""

from __future__ import annotations

import random
import re
import threading
import time
from typing import Iterable, Sequence

from mpi4dl_tpu.profiling import percentiles

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-shaped default buckets (seconds): sub-millisecond serving spans
# through multi-second train steps.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

RESERVOIR_SIZE = 1024


class Reservoir:
    """Bounded uniform sample of an observation stream (algorithm R).

    Deterministically seeded: a telemetry snapshot must not make test runs
    flaky. Exact (keeps everything) until ``size`` observations, an
    unbiased uniform sample after.
    """

    def __init__(self, size: int = RESERVOIR_SIZE, seed: int = 0):
        self.size = int(size)
        self.count = 0
        self.values: list[float] = []
        self._rng = random.Random(seed)

    def observe(self, value: float) -> None:
        self.count += 1
        if len(self.values) < self.size:
            self.values.append(value)
        else:
            j = self._rng.randrange(self.count)
            if j < self.size:
                self.values[j] = value

    def percentiles(self, pcts: Sequence[float] = (50, 90, 99)) -> dict:
        return percentiles(self.values, pcts)


def _check_labels(labelnames: Sequence[str], labels: dict) -> tuple:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} != declared {sorted(labelnames)}"
        )
    return tuple(str(labels[k]) for k in labelnames)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: dict = {}

    def _signature(self) -> tuple:
        return (self.kind, self.labelnames)

    def series_labels(self) -> "list[dict]":
        with self._lock:
            keys = list(self._series)
        return [dict(zip(self.labelnames, k)) for k in keys]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up; inc({amount})")
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            return self._series.get(key, 0.0)

    def snapshot_series(self) -> list:
        with self._lock:
            items = list(self._series.items())
        return [
            {"labels": dict(zip(self.labelnames, k)), "value": v}
            for k, v in items
        ]


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1, **labels) -> None:
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            return self._series.get(key, 0.0)

    snapshot_series = Counter.snapshot_series


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")

    def _signature(self) -> tuple:
        return (self.kind, self.labelnames, self.buckets)

    def _state(self, key):
        st = self._series.get(key)
        if st is None:
            st = self._series[key] = {
                "bucket_counts": [0] * (len(self.buckets) + 1),  # +Inf last
                "sum": 0.0,
                "count": 0,
                "reservoir": Reservoir(),
                # Per-bucket most-recent exemplar ({trace_id, value, ts}
                # or None), +Inf last like bucket_counts.
                "exemplars": [None] * (len(self.buckets) + 1),
            }
        return st

    def observe(
        self, value: float, exemplar: "str | None" = None, **labels
    ) -> None:
        """Record one observation. ``exemplar`` (a trace id) tags the
        bucket the value lands in with ``{trace_id, value, ts}`` — most
        recent wins; the aggregate→instance link a scrape follows from a
        latency bucket back to a concrete request."""
        key = _check_labels(self.labelnames, labels)
        value = float(value)
        ex = (
            {"trace_id": str(exemplar), "value": value, "ts": time.time()}
            if exemplar
            else None
        )
        with self._lock:
            st = self._state(key)
            st["sum"] += value
            st["count"] += 1
            st["reservoir"].observe(value)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    st["bucket_counts"][i] += 1
                    if ex is not None:
                        st["exemplars"][i] = ex
                    return
            st["bucket_counts"][-1] += 1
            if ex is not None:
                st["exemplars"][-1] = ex

    def percentiles(self, pcts=(50, 90, 99), **labels) -> dict:
        key = _check_labels(self.labelnames, labels)
        with self._lock:
            st = self._series.get(key)
            vals = list(st["reservoir"].values) if st else []
        return percentiles(vals, pcts)

    def snapshot_series(self) -> list:
        with self._lock:
            items = [
                (k, {
                    "counts": list(st["bucket_counts"]),
                    "sum": st["sum"],
                    "count": st["count"],
                    "vals": list(st["reservoir"].values),
                    "exemplars": list(st["exemplars"]),
                })
                for k, st in self._series.items()
            ]
        out = []
        for k, st in items:
            cum, buckets = 0, {}
            for bound, n in zip(self.buckets, st["counts"]):
                cum += n
                buckets[f"{bound:g}"] = cum
            buckets["+Inf"] = cum + st["counts"][-1]
            bounds = [f"{b:g}" for b in self.buckets] + ["+Inf"]
            exemplars = {
                le: dict(ex)
                for le, ex in zip(bounds, st["exemplars"])
                if ex is not None
            }
            entry = {
                "labels": dict(zip(self.labelnames, k)),
                "count": st["count"],
                "sum": st["sum"],
                "buckets": buckets,
                "percentiles": percentiles(st["vals"]),
            }
            if exemplars:  # sparse: buckets with no exemplar carry no key
                entry["exemplars"] = exemplars
            out.append(entry)
        return out


class MetricsRegistry:
    """Threadsafe name → metric map with get-or-create registration."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _register(self, metric: _Metric) -> _Metric:
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is None:
                self._metrics[metric.name] = metric
                return metric
            if existing._signature() != metric._signature():
                raise ValueError(
                    f"metric {metric.name!r} re-registered with a different "
                    f"signature: {existing._signature()} vs "
                    f"{metric._signature()}"
                )
            return existing

    def counter(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Counter:
        return self._register(Counter(name, help, labels))

    def gauge(self, name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(name, help, labels))

    def histogram(
        self, name: str, help: str = "", labels: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._register(Histogram(name, help, labels, buckets))

    def get(self, name: str) -> "_Metric | None":
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._metrics)

    def metrics(self) -> Iterable[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def snapshot(self) -> dict:
        """JSON-serializable state of every registered metric — the
        ``metrics`` payload of a JSONL telemetry event and of bench.py
        result lines (one schema everywhere)."""
        out = {}
        for m in self.metrics():
            out[m.name] = {
                "type": m.kind,
                "help": m.help,
                "labels": list(m.labelnames),
                "series": m.snapshot_series(),
            }
        return out
