"""Declarative SLOs: objectives, SLIs, error budgets, burn rates.

An SLO here is an :class:`Objective` — "99.9% of requests end well"
(availability over the ``serve_requests_total`` outcome counter) or "99%
of served requests finish under 50 ms" (latency over the cumulative
buckets of the e2e latency histogram). The SLI for a window is the
good-event ratio computed from a :class:`~mpi4dl_tpu.telemetry.windows.
SnapshotWindow`; the **burn rate** is how fast the error budget is being
spent:

    burn = (1 - SLI(window)) / (1 - objective)

Burn 1.0 spends exactly the budget over the SLO period; 14.4 over a
1-hour window spends 2% of a 30-day budget in that hour — the Google SRE
workbook's paging threshold. Alerting uses the workbook's
**multi-window multi-burn-rate** scheme (:data:`DEFAULT_BURN_WINDOWS`):
a rule fires only when BOTH a long window (smooths blips) and a short
window (confirms the problem is still happening, and ends the alert
promptly once it stops) exceed the factor. Fast burn pages, slow burn
tickets. The default window lengths are scaled down from the workbook's
1h/5m + 6h/30m to fit an in-process snapshot ring (~6 min of history at
the evaluator's 1/s cadence); a real fleet deployment would lift the
same objectives into Prometheus with the canonical windows.

Latency SLIs are bucket-resolved conservatively: the threshold maps to
the LARGEST histogram bound ≤ threshold, so a threshold between bounds
undercounts good events rather than overcounting them (the SLO can only
be stricter than declared, never laxer).
"""

from __future__ import annotations

import dataclasses
import math


@dataclasses.dataclass(frozen=True)
class BurnWindow:
    """One multi-window burn-rate rule (long AND short must exceed
    ``factor``)."""

    name: str        # "fast" | "slow" — the alert-name component
    severity: str    # "page" | "ticket"
    long_s: float
    short_s: float
    factor: float


# Scaled from the SRE workbook's (1h/5m, 14.4) page + (6h/30m, 6) ticket
# to the in-process ring (see module doc); the factors are canonical.
DEFAULT_BURN_WINDOWS = (
    BurnWindow("fast", "page", long_s=60.0, short_s=5.0, factor=14.4),
    BurnWindow("slow", "ticket", long_s=300.0, short_s=30.0, factor=6.0),
)


@dataclasses.dataclass(frozen=True)
class Objective:
    """One SLO objective over a cataloged metric.

    kind="availability": good = sum of ``good_outcomes`` series of a
    labeled counter, total = sum of all its series.
    kind="latency": good = observations ≤ ``threshold_s`` (bucket-
    resolved, see module doc) of a histogram, total = its count.
    """

    name: str                 # label value on slo_* metrics
    kind: str                 # "availability" | "latency"
    target: float             # e.g. 0.999
    metric: str
    good_outcomes: tuple = ()
    outcome_label: str = "outcome"
    # Outcomes excluded from the availability denominator entirely:
    # neither good nor bad. "drained" (a deliberate stop/drain flushing
    # the queue) is the canonical member — a fleet scale-down is a
    # lifecycle event and must not burn the availability budget.
    # "canary" (the numerics sentinel's synthetic probes) rides the
    # same exclusion: probe traffic is neither served user work nor a
    # failure, in either direction.
    ignore_outcomes: tuple = ()
    threshold_s: float = 0.0
    # Series selector for latency objectives over a LABELED histogram:
    # ((label, value), ...) pairs — the per-SLO-class objectives select
    # their class's serve_class_latency_seconds{slo_class=} series with
    # this. Empty = the metric's unlabeled series (the classic e2e
    # objective).
    labels: tuple = ()
    # The tenant this objective is scoped to: the evaluator publishes
    # slo_burn_rate / slo_error_budget_remaining under tenant=<this>,
    # and per-(class, tenant) objectives carry it in their label
    # selector. "default" = untenanted (the pre-tenancy behavior).
    tenant: str = "default"

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1), got {self.target} — "
                "pass 0.999, not 99.9"
            )
        if self.kind not in ("availability", "latency"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")

    @property
    def budget(self) -> float:
        return 1.0 - self.target


def availability_objective(
    target: float,
    metric: str = "serve_requests_total",
    good: "tuple | list" = ("served",),
    ignore: "tuple | list" = ("drained", "canary"),
    name: str = "availability",
) -> Objective:
    return Objective(
        name=name, kind="availability", target=target, metric=metric,
        good_outcomes=tuple(good), ignore_outcomes=tuple(ignore),
    )


def latency_objective(
    target: float,
    threshold_s: float,
    metric: str = "serve_request_latency_seconds",
    name: str = "latency",
    labels: tuple = (),
    tenant: str = "default",
) -> Objective:
    if threshold_s <= 0:
        raise ValueError(f"latency threshold must be > 0, got {threshold_s}")
    return Objective(
        name=name, kind="latency", target=target, metric=metric,
        threshold_s=float(threshold_s), labels=tuple(labels),
        tenant=tenant,
    )


def resolve_bucket_bound(bounds, threshold_s: float) -> "float | None":
    """Largest histogram bound ≤ threshold (conservative; None when the
    threshold sits below every bound — then nothing can count as good
    and the caller should hear about it loudly)."""
    best = None
    for b in bounds:
        b = float(b)
        if b <= threshold_s * (1 + 1e-9) and (best is None or b > best):
            best = b
    return best


def _bucket_key(bound: float) -> str:
    # Snapshot bucket keys are rendered with %g (registry.snapshot_series).
    return f"{bound:g}"


def sli(window, objective: Objective, window_s: float) -> "float | None":
    """Good-event ratio over the window; None when the window holds no
    events (no data is not 100% and not 0% — alert conditions treat it
    as "condition not met")."""
    if objective.kind == "availability":
        return window.availability(
            objective.metric, window_s, objective.good_outcomes,
            label=objective.outcome_label,
            ignore=objective.ignore_outcomes,
        )
    # latency
    sel = dict(objective.labels)
    h = window.hist_increase(objective.metric, window_s, **sel)
    if not h or h["count"] <= 0:
        return None
    bounds = [float(le) for le in h["buckets"] if le != "+Inf"]
    bound = resolve_bucket_bound(bounds, objective.threshold_s)
    if bound is None:
        return 0.0
    return window.bucket_ratio(
        objective.metric, window_s, bound, **sel,
    )


def burn_rate(window, objective: Objective, window_s: float) -> "float | None":
    """Error-budget burn rate over the window (1.0 = spending exactly
    the budget); None when the window holds no events."""
    s = sli(window, objective, window_s)
    if s is None:
        return None
    return (1.0 - s) / objective.budget


def cumulative_sli(registry, objective: Objective) -> "float | None":
    """Good-event ratio since process start, straight off the registry
    (the error-budget accounting period of a single serving process)."""
    m = registry.get(objective.metric)
    if m is None:
        return None
    series = m.snapshot_series()
    if not series:
        return None
    if objective.kind == "availability":
        ignored = set(objective.ignore_outcomes)
        counted = [
            s for s in series
            if s["labels"].get(objective.outcome_label) not in ignored
        ]
        total = sum(s["value"] for s in counted)
        if total <= 0:
            return None
        good = sum(
            s["value"] for s in counted
            if s["labels"].get(objective.outcome_label)
            in objective.good_outcomes
        )
        return good / total
    # Latency: restrict to the objective's label selector (a per-class
    # objective reads only its class's series; an unlabeled objective
    # sums every series of the metric).
    sel = dict(objective.labels)
    if sel:
        series = [
            s for s in series
            if all(s["labels"].get(k) == v for k, v in sel.items())
        ]
    total = sum(s["count"] for s in series)
    if total <= 0:
        return None
    bound = resolve_bucket_bound(m.buckets, objective.threshold_s)
    if bound is None:
        return 0.0
    key = _bucket_key(bound)
    good = sum(s["buckets"].get(key, 0) for s in series)
    return good / total


def budget_remaining(registry, objective: Objective) -> "float | None":
    """Fraction of the error budget left over the process lifetime:
    1.0 = untouched, 0.0 = exactly spent, negative = overspent (the SLO
    is already violated for this process's accounting period)."""
    s = cumulative_sli(registry, objective)
    if s is None:
        return None
    return 1.0 - (1.0 - s) / objective.budget


@dataclasses.dataclass
class SLOConfig:
    """Declarative SLO + alerting + autoscale configuration for a
    :class:`~mpi4dl_tpu.serve.ServingEngine` (``slo=`` / the
    ``--slo-availability`` / ``--slo-latency-ms`` CLI flags).

    availability: good-outcome target ratio over ``serve_requests_total``
        (e.g. 0.999); None disables the availability objective.
    latency_threshold_s / latency_target: "``latency_target`` of served
        requests complete within ``latency_threshold_s``" over the e2e
        latency histogram; threshold None disables.
    burn_windows: multi-window burn-rate rules (see module doc).
    for_s: how long a burn condition must hold before ``pending``
        escalates to ``firing`` (0 = first evaluation fires).
    interval_s: evaluator tick (snapshot + evaluation cadence).
    window_capacity: snapshot-ring size; None (default) derives the
        smallest ring covering the longest burn window at ``interval_s``.
        An explicit value that can't cover the longest window raises.
    autoscale: advisory autoscale policy knobs; None = defaults
        (:class:`mpi4dl_tpu.telemetry.autoscale.AutoscaleConfig`).
    headroom_alert_ratio: opt-in ``memory_headroom_low`` page: fires
        when any device's ``device_hbm_headroom_ratio`` gauge (the
        :class:`~mpi4dl_tpu.telemetry.memory.MemoryMonitor` publishes
        it) drops below this fraction (e.g. 0.05 = under 5% HBM free).
        None disables; backends without memory stats never publish the
        gauge, so the alert structurally cannot trip there
        (absent-not-wrong).
    """

    availability: "float | None" = None
    latency_threshold_s: "float | None" = None
    latency_target: float = 0.99
    burn_windows: tuple = DEFAULT_BURN_WINDOWS
    for_s: float = 0.0
    interval_s: float = 1.0
    window_capacity: "int | None" = None
    autoscale: "object | None" = None
    headroom_alert_ratio: "float | None" = None

    def _longest_window_s(self) -> float:
        return max((bw.long_s for bw in self.burn_windows), default=0.0)

    def ring_capacity(self) -> int:
        """Snapshot-ring size the evaluator allocates: explicit, or the
        smallest ring that covers the longest burn window (+10% slack so
        the window boundary never falls off the edge mid-query)."""
        if self.window_capacity is not None:
            return int(self.window_capacity)
        return int(math.ceil(self._longest_window_s() / self.interval_s * 1.1)) + 2

    def objectives(self) -> "list[Objective]":
        out = []
        if self.availability is not None:
            out.append(availability_objective(self.availability))
        if self.latency_threshold_s is not None:
            out.append(
                latency_objective(self.latency_target, self.latency_threshold_s)
            )
        if not (math.isfinite(self.interval_s) and self.interval_s > 0):
            raise ValueError(f"interval_s must be > 0, got {self.interval_s}")
        longest = self._longest_window_s()
        if out and self.interval_s * self.ring_capacity() < longest:
            raise ValueError(
                f"window_capacity {self.window_capacity} x interval "
                f"{self.interval_s}s holds less history than the longest "
                f"burn window ({longest:g}s) — the slow-burn alert could "
                "never see its full window"
            )
        return out
