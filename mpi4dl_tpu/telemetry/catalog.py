"""The metric catalog: every metric this codebase publishes, in one place.

Publishers do not call ``registry.counter(...)`` with ad-hoc strings — they
call :func:`declare`, which looks the name up here and registers it with
the cataloged type/labels/help. That makes the catalog load-bearing rather
than aspirational: code physically cannot publish an uncataloged name
through :func:`declare`, and the tier-1 test
(``tests/test_telemetry.py``) closes the loop in both directions —

- the metric table in ``docs/OBSERVABILITY.md`` must list exactly these
  names/types/labels (no silently undocumented metrics), and
- a full-stack exercise (serving engine + load generator + trainer +
  hlolint publish) must expose exactly these names (no stale catalog
  entries for metrics nothing publishes anymore).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from mpi4dl_tpu.telemetry.registry import DEFAULT_BUCKETS, MetricsRegistry

# Bucket-occupancy is a ratio in (0, 1]; latency buckets would waste every
# bound above 1.
OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


@dataclasses.dataclass(frozen=True)
class MetricSpec:
    type: str  # "counter" | "gauge" | "histogram"
    labels: tuple
    help: str
    buckets: "tuple | None" = None  # histograms only; None = DEFAULT_BUCKETS


CATALOG: "dict[str, MetricSpec]" = {
    # -- serving engine (mpi4dl_tpu/serve/engine.py) -------------------------
    "serve_submitted_total": MetricSpec(
        "counter", (),
        "Requests accepted into the bounded queue by submit().",
    ),
    "serve_requests_total": MetricSpec(
        "counter", ("outcome",),
        "Terminal request outcomes: served, served_late, "
        "rejected_queue_full, rejected_quota (tenant token bucket "
        "empty — shed before any queue slot), rejected_deadline, "
        "drained (flushed by a deliberate stop/drain — excluded from "
        "the availability SLO), canary (a numerics-sentinel probe "
        "riding the real dispatch path — excluded like drained).",
    ),
    "serve_queue_depth": MetricSpec(
        "gauge", (),
        "Requests currently waiting in the bounded queue (the "
        "load-shedding / scale-up signal a fleet controller consumes).",
    ),
    "serve_batches_total": MetricSpec(
        "counter", ("bucket",),
        "Batches dispatched, by padded bucket size.",
    ),
    "serve_batch_occupancy": MetricSpec(
        "histogram", ("bucket",),
        "Real examples / bucket rows per dispatched batch (1.0 = no "
        "padding), by bucket.",
        buckets=OCCUPANCY_BUCKETS,
    ),
    "serve_pad_waste_ratio": MetricSpec(
        "gauge", (),
        "Cumulative padded rows / total dispatched rows — compute wasted "
        "on padding.",
    ),
    "serve_request_latency_seconds": MetricSpec(
        "histogram", (),
        "End-to-end latency of served requests (submit -> result ready).",
    ),
    "serve_class_latency_seconds": MetricSpec(
        "histogram", ("slo_class", "tenant"),
        "End-to-end latency of served requests, by SLO class and tenant "
        "— the per-class latency objectives (slo_burn_rate{slo="
        "latency_<class>}) the EDF scheduler's burn-rate feedback reads "
        "back, scoped per tenant (tenant=default when tenancy is off).",
    ),
    "serve_class_queue_depth": MetricSpec(
        "gauge", ("slo_class",),
        "Requests waiting in each SLO class's EDF admission queue "
        "(serve_queue_depth stays the cross-class total the autoscaler "
        "consumes).",
    ),
    "serve_class_shed_total": MetricSpec(
        "counter", ("slo_class",),
        "Admissions shed early by the burn-rate feedback policy: the "
        "class was deprioritized (burning budget slowest while another "
        "class burned hot) and its queue was past the shed ratio. "
        "Published by the engine scheduler and the fleet router alike.",
    ),
    "serve_class_deprioritized": MetricSpec(
        "gauge", ("slo_class",),
        "1 while the burn-rate feedback currently deprioritizes the "
        "class (it fills batch slots only after protected classes and "
        "sheds admissions early), else 0.",
    ),
    "serve_span_seconds": MetricSpec(
        "histogram", ("phase",),
        "Per-request lifecycle span durations: queue_wait, batch_form, "
        "h2d_stage, device_compute. Contiguous: they sum to the "
        "end-to-end latency.",
    ),
    "serve_phase_share": MetricSpec(
        "gauge", ("phase",),
        "Share of each lifecycle phase (queue_wait, batch_form, "
        "h2d_stage, device_compute) in cumulative served latency — the "
        "live phase mix a latency alert's attribution delta is computed "
        "against.",
    ),
    "serve_client_overhead_seconds": MetricSpec(
        "histogram", (),
        "Client-observed latency minus the engine's own e2e latency for "
        "the same request — the client/router-hop cost federation "
        "attributes when traces cross processes.",
    ),
    "serve_warm_latency_seconds": MetricSpec(
        "gauge", ("bucket",),
        "First post-compile execution latency per bucket, measured at "
        "AOT warm-up.",
    ),
    "serve_healthy": MetricSpec(
        "gauge", (),
        "1 while the engine's health state is OK, 0 after a watchdog "
        "trip or batcher crash — the scrapeable twin of /healthz.",
    ),
    "serve_mesh_devices": MetricSpec(
        "gauge", (),
        "Devices in the serving forward's mesh: 1 for a single-chip "
        "replica, tile_h*tile_w for a spatially-sharded one (serve/"
        "sharded.py) — the shard-for-model-size axis, orthogonal to "
        "fleet replication.",
    ),
    "serve_halo_shifts": MetricSpec(
        "gauge", (),
        "Forward halo-shift permutes per pass of the serving forward "
        "(Trainer.halo_shift_count on the sharded predictor; 0 on a "
        "single chip) — the partition-math input of the mesh-derived "
        "hlolint halo-permute window that gates every warmed bucket.",
    ),
    "canary_checks_total": MetricSpec(
        "counter", ("result",),
        "Numerics-sentinel canary verdicts (telemetry/canary.py): ok "
        "(exact digest match), tolerance (bitwise differs within the "
        "documented f32 bound — a changed executable, not corruption), "
        "divergence (beyond tolerance, or a params-checksum mismatch: "
        "real corruption — emits canary.failure and fences the "
        "worker), error (no reference), skipped (queue full).",
    ),
    "canary_max_divergence": MetricSpec(
        "gauge", (),
        "Largest max-abs divergence any canary check has seen against "
        "its warm-up reference (0 while every check lands ok/"
        "tolerance) — the magnitude behind a divergence verdict.",
    ),
    # -- gigapixel tiled inference (mpi4dl_tpu/serve/tiled.py) ---------------
    "tiled_tiles_total": MetricSpec(
        "counter", (),
        "Overlap-read tile windows streamed through the tiled forward's "
        "section executable (serve/tiled.py /predict_tiled).",
    ),
    "tiled_tile_batches_total": MetricSpec(
        "counter", ("bucket",),
        "Tile-batch dispatches of the tiled forward, by tile bucket "
        "(the power-of-two TILE buckets inside one request — orthogonal "
        "to the engine's per-image buckets).",
    ),
    "tiled_tiles_per_request": MetricSpec(
        "gauge", (),
        "Tiles per request of the configured tile geometry "
        "(grid_h * grid_w — constant per engine, derived from the "
        "image size, tile core, and receptive-field margin).",
    ),
    "tiled_stitch_seconds": MetricSpec(
        "histogram", (),
        "Per-request host-side stitch time of the tiled forward: "
        "feature-map assembly copies plus the head forward on the "
        "stitched features.",
    ),
    "tiled_tile_stream_seconds": MetricSpec(
        "histogram", (),
        "Per-request tile-streaming time of the tiled forward: window "
        "slicing, double-buffered H2D staging, and the section "
        "executable's device compute (everything but the stitch).",
    ),
    # -- memory observability (mpi4dl_tpu/telemetry/memory.py) ---------------
    "device_hbm_used_bytes": MetricSpec(
        "gauge", ("device",),
        "Live device memory in use, sampled from jax.Device."
        "memory_stats() at the monitor cadence; absent (no series, not "
        "zero) on backends that report no stats (CPU).",
    ),
    "device_hbm_limit_bytes": MetricSpec(
        "gauge", ("device",),
        "Device memory capacity from memory_stats(); absent on backends "
        "that report no stats.",
    ),
    "device_hbm_headroom_ratio": MetricSpec(
        "gauge", ("device",),
        "(limit - used) / limit per device — the memory_headroom_low "
        "alert's input; absent without a reported limit.",
    ),
    "serve_bucket_peak_hbm_bytes": MetricSpec(
        "gauge", ("bucket",),
        "Footprint-ledger predicted peak (buffer-assignment argument + "
        "output + temp - alias) of each warmed serving bucket's compiled "
        "executable, recorded at AOT warm-up before first execution.",
    ),
    "program_peak_hbm_bytes": MetricSpec(
        "gauge", ("program",),
        "Footprint-ledger predicted peak of a non-bucket compiled "
        "program (train_step, eval) — the compile-time twin of the "
        "hlolint peak gauge.",
    ),
    "oom_reports_total": MetricSpec(
        "counter", ("program",),
        "Structured RESOURCE_EXHAUSTED forensics (oom.report events) "
        "emitted, by program.",
    ),
    # -- cold start (mpi4dl_tpu/telemetry/coldstart.py) ----------------------
    "compile_seconds": MetricSpec(
        "gauge", ("program", "phase"),
        "Cumulative AOT cold-start seconds per program and phase — "
        "trace (jit lower), compile (XLA), warm (first zeros "
        "execution) — accumulated by the footprint ledger across "
        "buckets; the series analyze coldstart ranks executables by.",
    ),
    "warmup_wall_seconds": MetricSpec(
        "gauge", (),
        "Wall seconds of the engine's whole AOT warm-up (compile loop "
        "+ zeros runs) — the compile-bound part of a cold replica's "
        "spawn-to-ready time.",
    ),
    "compile_cache_enabled": MetricSpec(
        "gauge", (),
        "1 when the persistent compilation cache is on, 0 when off — "
        "including the jax-0.4.x segfault gate in "
        "utils.enable_compilation_cache, so fleet runs are honest "
        "about whether compiles are ever amortized.",
    ),
    # -- tail forensics (mpi4dl_tpu/telemetry/tail.py) -----------------------
    "tail_samples_total": MetricSpec(
        "counter", (),
        "Slow requests captured as tail.sample events: e2e latency over "
        "max(SLO latency threshold, factor x rolling p99), rate-limited.",
    ),
    "tail_threshold_seconds": MetricSpec(
        "gauge", (),
        "Live slow-request trip line of the tail watcher: max(SLO "
        "latency threshold, factor x rolling p99 seeded with the AOT "
        "warm latency).",
    ),
    # -- liveness + postmortem (mpi4dl_tpu/telemetry/health.py, flight.py) ---
    "watchdog_trips_total": MetricSpec(
        "counter", (),
        "Watchdog trips: work was outstanding but nothing completed "
        "within max(min timeout, K x rolling p99 completion time).",
    ),
    "flight_recorder_dumps_total": MetricSpec(
        "counter", ("reason",),
        "Flight-recorder postmortem dumps, by trigger: watchdog, crash, "
        "sigterm, manual; incident when the dump fired while an "
        "incident was open (the marker carries the incident id and the "
        "original trigger).",
    ),
    # -- SLO engine (mpi4dl_tpu/telemetry/slo.py, alerts.py, autoscale.py) ---
    "slo_error_budget_remaining": MetricSpec(
        "gauge", ("slo", "tenant"),
        "Fraction of the error budget left over the process lifetime: "
        "1 = untouched, 0 = exactly spent, negative = objective violated. "
        "Per tenant for per-class objectives (tenant=default otherwise).",
    ),
    "slo_burn_rate": MetricSpec(
        "gauge", ("slo", "window", "tenant"),
        "Error-budget burn rate per objective, burn window "
        "(fast_long/fast_short/slow_long/slow_short), and tenant "
        "(tenant=default for untenanted objectives); 1.0 spends exactly "
        "the budget over the SLO period.",
    ),
    "alert_active": MetricSpec(
        "gauge", ("alert", "severity"),
        "1 while the burn-rate alert is firing (pending and resolved are "
        "0) — the scrapeable twin of /alertz.",
    ),
    "autoscale_desired_replicas": MetricSpec(
        "gauge", (),
        "Advisory replica count a fleet controller should run, from "
        "windowed queue depth + rejection rate + page burn with "
        "hysteresis and cooldown (telemetry/autoscale.py).",
    ),
    # -- fleet (mpi4dl_tpu/fleet/: router.py, supervisor.py) -----------------
    "fleet_requests_total": MetricSpec(
        "counter", ("outcome",),
        "Router-terminal request outcomes: served, served_cached (a "
        "failover retry answered from a replica's idempotency cache — "
        "never re-executed), failed (retry budget spent), "
        "rejected_queue_full (router admission), rejected_quota (tenant "
        "token bucket empty at the front door — shed before any queue "
        "slot), rejected_deadline, drained (router stopped).",
    ),
    "fleet_requeues_total": MetricSpec(
        "counter", ("reason",),
        "Requests moved back to the router queue for a survivor, by "
        "reason: dispatch_error, replica_queue_full, replica_removed "
        "(supervisor-confirmed death).",
    ),
    "fleet_dispatches_total": MetricSpec(
        "counter", ("replica", "outcome"),
        "Per-attempt replica RPCs, by outcome: ok, error, queue_full, "
        "deadline.",
    ),
    "fleet_inflight": MetricSpec(
        "gauge", ("replica",),
        "Requests currently in a replica's in-flight ledger (dispatched, "
        "not yet resolved) — what gets requeued if the replica dies.",
    ),
    "fleet_replicas": MetricSpec(
        "gauge", ("state",),
        "Fleet membership by state: configured and healthy (router "
        "view), desired, running, starting, backoff, draining, "
        "circuit_open (supervisor view).",
    ),
    "fleet_replica_restarts_total": MetricSpec(
        "counter", ("replica", "reason"),
        "Supervisor-initiated replica replacements, by reason: exit, "
        "heartbeat (stale beats), unhealthy (/healthz 503 streak).",
    ),
    "fleet_recovery_seconds": MetricSpec(
        "gauge", (),
        "Most recent death-to-replacement-serving duration: from a "
        "replica's confirmed death to its successor joining the router "
        "(trend-tracked by the fleet_2replica bench extra).",
    ),
    "fleet_recovery_phase_seconds": MetricSpec(
        "gauge", ("phase",),
        "Decomposition of the most recent fleet_recovery_seconds over "
        "the fixed spawn/import/construct/compile/warm/ready phase "
        "vocabulary (worker-reported durations riding the ready "
        "handshake; spawn is the supervisor-side residual, so the "
        "phases sum to the scalar). A warm-pool promotion is pure "
        "ready time with compile/warm honestly zero.",
    ),
    "fleet_request_latency_seconds": MetricSpec(
        "histogram", (),
        "Router-observed end-to-end latency of served fleet requests "
        "(submit -> future resolved, requeues included); buckets carry "
        "exemplar trace ids, so the fleet p99 bucket names a real "
        "request.",
    ),
    "fleet_routers": MetricSpec(
        "gauge", ("state",),
        "Front-door router processes by state: desired, running, "
        "starting, backoff, circuit_open (supervisor view; each router "
        "slot rides the same backoff + breaker + paging as a replica "
        "slot).",
    ),
    "fleet_router_journal_replays_total": MetricSpec(
        "counter", ("outcome",),
        "Orphaned journal entries a successor router processed after a "
        "router death, by outcome: deduped (a replica had already "
        "served/held the trace id — completed without re-execution), "
        "redispatched (re-dispatched with a fresh epoch), expired "
        "(deadline passed while orphaned).",
    ),
    "fleet_standby_replicas": MetricSpec(
        "gauge", (),
        "Warm-pool replicas fully warmed (ready handshake / assert_warm "
        "passed) but unrouted, standing by for promotion; the "
        "supervisor backfills toward the warm_pool target.",
    ),
    "fleet_promotions_total": MetricSpec(
        "counter", (),
        "Standby-to-serving promotions after a replica death: a health "
        "handshake + routing flip replaced a cold spawn, which is what "
        "cuts fleet_recovery_seconds from warm-up-compile time to "
        "sub-second.",
    ),
    "fleet_replica_skew": MetricSpec(
        "gauge", ("replica",),
        "Straggler score per replica: its own e2e p99 (bucket-resolved "
        "from the scraped /snapshotz histogram) divided by the fleet "
        "median p99 — 1.0 = typical, >= the straggler factor trips the "
        "replica_straggler advisory page.",
    ),
    "fleet_numerics_skew": MetricSpec(
        "gauge", ("replica",),
        "Numerics-divergence score per replica: disagreements with the "
        "fleet majority on params checksum / canary digests plus its "
        "own self-reported canary failures (federation's numerics "
        "audit) — 0 = agrees, >= 1 trips the numerics_divergence page "
        "naming the replica. The straggler pattern applied to "
        "correctness.",
    ),
    # -- incident engine (mpi4dl_tpu/telemetry/incident.py) ------------------
    "incidents_total": MetricSpec(
        "counter", ("state",),
        "Incident lifecycle transitions by the IncidentManager, by "
        "state: opened (a watched alert reached firing with no incident "
        "open), closed (every member alert resolved).",
    ),
    "incident_open": MetricSpec(
        "gauge", (),
        "1 while an incident is currently open on this manager, else 0 "
        "— the scrapeable twin of /incidentz.",
    ),
    "incident_mtta_seconds": MetricSpec(
        "gauge", (),
        "Time-to-acknowledge of the most recently OPENED incident: "
        "first member alert firing -> incident open (one evaluation "
        "tick when the manager rides the scrape loop).",
    ),
    "incident_mttr_seconds": MetricSpec(
        "gauge", (),
        "Time-to-resolve of the most recently CLOSED incident: open -> "
        "all member alerts resolved (the number the incident bench "
        "extra trends as incident.mttr_s).",
    ),
    # -- federation (mpi4dl_tpu/telemetry/federation.py) ---------------------
    "federation_replicas": MetricSpec(
        "gauge", ("state",),
        "Replicas the federation aggregator knows about: configured "
        "(scrape targets) and up (last /snapshotz scrape succeeded).",
    ),
    "federation_scrapes_total": MetricSpec(
        "counter", ("replica", "outcome"),
        "Aggregator /snapshotz scrapes per replica, by outcome (ok, "
        "error).",
    ),
    # -- trace attribution (mpi4dl_tpu/analysis/trace.py) --------------------
    "trace_attribution_seconds": MetricSpec(
        "gauge", ("program", "category"),
        "Per-step mean device-time attribution from the latest XProf "
        "capture: compute, collective, transfer, host_gap (whole-range "
        "totals when the capture had no step annotations).",
    ),
    "trace_step_wall_seconds": MetricSpec(
        "gauge", ("program",),
        "Mean annotated-step wall time in the latest capture — the "
        "denominator the attribution categories sum to.",
    ),
    "trace_overlap_ratio": MetricSpec(
        "gauge", ("program",),
        "Measured fraction of collective time overlapped by concurrent "
        "compute in the latest capture (1.0 = fully hidden; absent when "
        "the capture saw no collectives). The sp-overlap A/B publishes "
        "it per arm (program=sp2x2_monolithic / sp2x2_decomposed); the "
        "serving-sharded A/B under program=serving_sharded_<arm>.",
    ),
    # -- pipeline lens (mpi4dl_tpu/analysis/trace.py, parallel/pipeline.py) --
    "pipeline_bubble_fraction": MetricSpec(
        "gauge", ("program",),
        "Measured fill/drain bubble of the latest pipeline capture: idle "
        "stage-switch slots / all slots, joined from the compiled "
        "program's branch closures to the real trace (gpipe model "
        "(S-1)/(S-1+M); the pipeline bench publishes one per schedule "
        "arm, program=pipeline_gpipe / pipeline_1f1b).",
    ),
    "pipeline_stage_device_seconds": MetricSpec(
        "gauge", ("program", "stage"),
        "Device seconds attributed to each pipe stage's switch branch "
        "(forward + AD-transpose backward) in the latest pipeline "
        "capture — the per-stage/per-device split of the step's device "
        "time.",
    ),
    "pipeline_img_per_s": MetricSpec(
        "gauge", ("program",),
        "Images/sec through the pipeline schedule during the latest "
        "capture (global batch images per mean captured step wall).",
    ),
    # -- tenancy (mpi4dl_tpu/tenancy/model.py TenantAdmission) ---------------
    "tenant_quota_tokens": MetricSpec(
        "gauge", ("tenant",),
        "Current token-bucket level per tenant at this admission edge "
        "(burst = full); refreshed on every admission decision.",
    ),
    "tenant_quota_sheds_total": MetricSpec(
        "counter", ("tenant",),
        "Admissions shed because the tenant's token bucket was empty — "
        "the QuotaExceededError count, charged before any queue slot.",
    ),
    "tenant_admitted_total": MetricSpec(
        "counter", ("tenant",),
        "Requests admitted past the tenant quota gate at this edge "
        "(tenant=default covers untenanted traffic).",
    ),
    # -- load generator (mpi4dl_tpu/serve/loadgen.py) ------------------------
    "loadgen_requests_total": MetricSpec(
        "counter", ("outcome",),
        "Client-side request outcomes: served, rejected_queue_full, "
        "deadline_miss, error.",
    ),
    "loadgen_request_latency_seconds": MetricSpec(
        "histogram", (),
        "Client-observed latency (submit call -> future resolved).",
    ),
    # -- training (mpi4dl_tpu/profiling.py StepTimer, train.py Trainer) ------
    "train_step_seconds": MetricSpec(
        "histogram", (),
        "Wall-clock per train step, forced to full execution "
        "(StepTimer's block-until-ready boundary).",
    ),
    "train_steps_total": MetricSpec(
        "counter", (),
        "Timed train steps (post-warmup).",
    ),
    "train_images_per_sec": MetricSpec(
        "gauge", (),
        "Throughput of the most recent timed step.",
    ),
    "train_remat_store_budget_mb": MetricSpec(
        "gauge", (),
        "Configured scanq/scan_save store budget (MPI4DL_TPU_SCANQ_"
        "STORE_MB / save budget), from Trainer.remat_report().",
    ),
    "train_remat_granted_bytes": MetricSpec(
        "gauge", (),
        "Bytes of activations actually granted storage at the last trace "
        "(Trainer.remat_report()).",
    ),
    "train_halo_shifts": MetricSpec(
        "gauge", (),
        "Forward halo-shift ppermutes per un-scanned pass "
        "(Trainer.halo_shift_count) — the partition-math floor hlolint "
        "checks the compiled inventory against.",
    ),
    # -- hlolint (mpi4dl_tpu/analysis/metrics.py) ----------------------------
    "hlolint_ok": MetricSpec(
        "gauge", ("program",),
        "1 when the program's lint report has no error-severity findings.",
    ),
    "hlolint_findings": MetricSpec(
        "gauge", ("program", "severity"),
        "Finding count by severity in the latest lint report.",
    ),
    "hlolint_collectives": MetricSpec(
        "gauge", ("program",),
        "Collective ops in the compiled program.",
    ),
    "hlolint_collective_bytes": MetricSpec(
        "gauge", ("program",),
        "Bytes moved by collectives in the compiled program.",
    ),
    "hlolint_peak_hbm_bytes": MetricSpec(
        "gauge", ("program",),
        "Peak buffer-assignment bytes (argument + output + temp - alias) "
        "of the compiled program; 0 when the backend cannot report it.",
    ),
    "hlolint_predicted_comms_seconds": MetricSpec(
        "gauge", ("program", "interconnect"),
        "Static cost-model prediction: total collective seconds under "
        "the named interconnect table "
        "(mpi4dl_tpu/analysis/costmodel.py ring/neighbor formulas).",
    ),
    "hlolint_predicted_overlap_ratio": MetricSpec(
        "gauge", ("program", "interconnect"),
        "Static cost-model prediction: achievable overlap CEILING — the "
        "fraction of predicted collective seconds whose start->done "
        "window has compute scheduled inside it (0 with no claim when "
        "the program's collectives are all synchronous, e.g. every "
        "CPU-mesh program).",
    ),
    "hlolint_predicted_bubble_fraction": MetricSpec(
        "gauge", ("program", "interconnect"),
        "Static cost-model prediction: schedule-model pipeline bubble "
        "(PipelineTrainer.analytic_bubble_fraction) — only published "
        "for pipeline programs; crosschecked against the measured "
        "pipeline_bubble_fraction by cost-model-crosscheck.",
    ),
}


def declare(registry: MetricsRegistry, name: str):
    """Register-or-fetch a cataloged metric on ``registry``. The only
    sanctioned way for stack code to obtain a metric object — an
    uncataloged name raises here, at the publisher, not in CI."""
    spec = CATALOG.get(name)
    if spec is None:
        raise KeyError(
            f"metric {name!r} is not in telemetry.catalog.CATALOG — add it "
            "there (and to docs/OBSERVABILITY.md) before publishing it"
        )
    if spec.type == "counter":
        return registry.counter(name, spec.help, spec.labels)
    if spec.type == "gauge":
        return registry.gauge(name, spec.help, spec.labels)
    return registry.histogram(
        name, spec.help, spec.labels,
        buckets=spec.buckets if spec.buckets is not None else DEFAULT_BUCKETS,
    )
