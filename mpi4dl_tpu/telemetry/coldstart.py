"""Cold-start observability: executable fingerprints + recovery phases.

Every elasticity number in this repo is compile-bound — replica recovery
is ~7s cold vs 0.05s warm-pool and every scale-out pays full AOT warm-up
per bucket per replica — but until this module that cost was one scalar
(``fleet_recovery_seconds``) and a per-bucket wall time. Three pieces
turn it into an instrument:

- **Executable fingerprints** (:func:`executable_fingerprint` /
  :func:`fingerprint_of`): a deterministic content key over the
  canonicalized lowered HLO text + jax/jaxlib versions + backend + mesh
  shape — the identity the ROADMAP's fleet-shared artifact store will be
  keyed by. Computed at every :class:`~mpi4dl_tpu.telemetry.memory.
  FootprintLedger` record site and stored in ledger entries/``dump()``.
- **Phase vocabulary** (:data:`RECOVERY_PHASES`,
  :func:`recovery_phase_decomposition`): the fixed spawn → import →
  construct → compile → warm → ready decomposition the worker stamps
  into its ready handshake and the supervisor publishes as
  ``fleet_recovery_phase_seconds{phase=}`` — durations, not timestamps,
  so the arithmetic is clock-skew-safe across processes.
- **Cache honesty** (:func:`publish_cache_status`): the
  ``compile_cache_enabled`` gauge, 0 under the jax-0.4.x segfault gate
  in :func:`mpi4dl_tpu.utils.enable_compilation_cache` — fleet runs
  stop silently paying compiles they believe are cached.

``python -m mpi4dl_tpu.analyze coldstart``
(:mod:`mpi4dl_tpu.analysis.coldstart`) joins the ledger dumps,
``elastic.restart`` events, and recovery phases into the ranked
"top executables by compile seconds" manifest the compile-cache service
will warm. jax is imported lazily here — the module itself stays
importable from pure-JSON analysis paths.
"""

from __future__ import annotations

import hashlib
import re

#: The fixed recovery-phase vocabulary. Worker-side durations cover
#: import → ready; ``spawn`` is the supervisor-side residual (process
#: fork + argv parse + anything before the worker's first stamp), so the
#: published phases always sum to ``fleet_recovery_seconds``. A warm-pool
#: promotion is pure ``ready`` (routing flip + health handshake): its
#: compile/warm phases are honestly zero — that IS the warm pool's claim.
RECOVERY_PHASES = ("spawn", "import", "construct", "compile", "warm", "ready")

# Volatile decoration stripped before hashing: per-op `metadata={...}`
# carries source_file absolute paths (checkout-dependent) and MLIR
# `loc(...)` / `#loc` lines carry the same — neither changes what the
# executable computes.
_METADATA_RE = re.compile(r",?\s*metadata=\{[^{}]*\}")
_LOC_RE = re.compile(r"\s*loc\([^()]*\)")
_LOC_LINE_RE = re.compile(r"^#loc\d*\s*=.*$", re.MULTILINE)
_WS_RE = re.compile(r"\s+")


def canonicalize_hlo(text: str) -> str:
    """Canonical form of lowered/compiled HLO or StableHLO text: volatile
    decoration (per-op ``metadata={...}``, MLIR ``loc(...)`` references
    and ``#loc`` lines) dropped, whitespace collapsed — two renderings of
    the same program hash equal, two different programs don't."""
    text = _METADATA_RE.sub("", text)
    text = _LOC_LINE_RE.sub("", text)
    text = _LOC_RE.sub("", text)
    return _WS_RE.sub(" ", text).strip()


def executable_fingerprint(
    hlo_text: str,
    *,
    backend: str = "",
    mesh_shape=None,
    in_shardings=None,
    out_shardings=None,
    donated=None,
    jax_version: "str | None" = None,
    jaxlib_version: "str | None" = None,
) -> str:
    """Deterministic content key of one executable: sha256 over the
    canonicalized program text plus everything that changes what XLA
    would emit for it — jax/jaxlib versions, backend, mesh shape, in/out
    shardings, donation. Same config in two processes → same key;
    perturb px/bucket/mesh/dtype → distinct key. This is the identity
    the fleet-shared artifact store (ROADMAP zero-cold-start item) keys
    serialized executables by."""
    if jax_version is None or jaxlib_version is None:
        jv, lv = _versions()
        jax_version = jax_version if jax_version is not None else jv
        jaxlib_version = jaxlib_version if jaxlib_version is not None else lv
    h = hashlib.sha256()
    for part in (
        canonicalize_hlo(hlo_text),
        jax_version,
        jaxlib_version,
        backend or "",
        repr(tuple(mesh_shape) if mesh_shape is not None else None),
        repr(in_shardings),
        repr(out_shardings),
        repr(donated),
    ):
        h.update(part.encode())
        h.update(b"\x00")
    return "xf" + h.hexdigest()[:16]


def _versions() -> "tuple[str, str]":
    try:
        import jax

        jv = jax.__version__
    except Exception:  # noqa: BLE001 — fingerprinting is best-effort
        jv = ""
    try:
        import jaxlib

        lv = getattr(jaxlib, "__version__", "")
    except Exception:  # noqa: BLE001
        lv = ""
    return jv, lv


def fingerprint_of(obj, *, mesh_shape=None, **config) -> "str | None":
    """Best-effort fingerprint of a ``jax.stages.Lowered`` or
    ``Compiled``: hashes ``obj.as_text()`` (prefer fingerprinting the
    LOWERED object — its pre-optimization text is the key a respawning
    worker can compute *before* paying the compile). Returns None when
    the object cannot render text; recording must never fail warm-up."""
    try:
        text = obj.as_text()
    except Exception:  # noqa: BLE001 — e.g. an executable without text
        return None
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # noqa: BLE001
        backend = ""
    return executable_fingerprint(
        text, backend=backend, mesh_shape=mesh_shape, **config
    )


def recovery_phase_decomposition(
    recovery_s: float, worker_phases: "dict | None"
) -> "dict[str, float]":
    """Fold a worker's self-reported phase DURATIONS into the fixed
    :data:`RECOVERY_PHASES` vocabulary: unknown keys are dropped, every
    phase is present (zeros for unused ones — so the published series
    stays honest across cold/promotion alternation instead of leaving a
    stale compile number standing), and ``spawn`` absorbs the residual
    ``recovery_s - sum(worker phases)`` clamped at 0. The result always
    sums to ``recovery_s`` (to within the clamp)."""
    phases = {p: 0.0 for p in RECOVERY_PHASES}
    total = 0.0
    for p, v in (worker_phases or {}).items():
        if p in phases and p != "spawn" and isinstance(v, (int, float)):
            phases[p] = float(v)
            total += float(v)
    phases["spawn"] = max(0.0, float(recovery_s) - total)
    return phases


def publish_cache_status(registry, attempt: bool = True) -> dict:
    """Publish the cataloged ``compile_cache_enabled`` gauge (1 = the
    persistent compilation cache is on, 0 = off — including the jax-0.4.x
    segfault gate) and return the status dict with the reason. With
    ``attempt=True`` (default) this first calls
    :func:`mpi4dl_tpu.utils.enable_compilation_cache`, which records its
    own gate decision and logs the reason once per process — so a
    serving engine's scrape is honest about cache state without every
    entry point having to remember the call."""
    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.utils import (
        compilation_cache_status,
        enable_compilation_cache,
    )

    if attempt:
        try:
            enable_compilation_cache()
        except Exception:  # noqa: BLE001 — status reflects the failure
            pass
    status = compilation_cache_status()
    telemetry.declare(registry, "compile_cache_enabled").set(
        1.0 if status.get("enabled") else 0.0
    )
    return status
