"""Slow-request capture: the per-request forensics behind a p99 page.

The SLO engine says *that* the tail regressed (burn alerts), phase
attribution says *which phase* grew — but neither names a REQUEST. The
tail watcher closes that gap: the engine's completion path offers every
served request's e2e latency; requests slower than

    max(SLO latency threshold, factor x rolling p99)

are captured as rate-limited, schema-valid ``tail.sample`` JSONL events
carrying everything known about that request at completion time — the
full span phases (whose durations sum exactly to the e2e latency, the
repo-wide invariant), the queue depth it saw at admission, the bucket /
batch size / pad-waste it was served in, its dispatch sequence number,
the pid, the watchdog state, and the latest sampled trace attribution.
The rolling p99 is seeded with the AOT warm latency so the threshold is
meaningful from request zero, and the SLO threshold floors it so a
healthy-but-volatile warm-up can't spam samples under the objective.

Samples land in three places: the JSONL event log (when enabled), the
flight-recorder ring (a postmortem dump shows the slow requests next to
the alert transitions they caused), and a bounded in-memory ring served
on ``/debugz`` (:meth:`TailWatcher.state`). ``python -m mpi4dl_tpu.analyze
tail`` joins them with histogram exemplars and cross-process span
segments to answer "why was this request slow" per trace id
(docs/OBSERVABILITY.md "Tail forensics").

Cost: one deque append per served request plus a percentile recompute
every ``RECOMPUTE_EVERY`` observations — measured inside the stack's
standing ±2% serving-overhead bound (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import collections
import os
import threading
import time

from mpi4dl_tpu.profiling import percentiles

#: Rolling-p99 recompute cadence (observations): sorting the window per
#: request would put an O(n log n) on the hot path for a threshold that
#: moves slowly; every 16 completions tracks a drifting tail closely
#: enough for a 4x trip factor.
RECOMPUTE_EVERY = 16


class TailWatcher:
    """Watches request completions; captures the slow ones.

    registry: metric sink — publishes the cataloged
        ``tail_samples_total`` counter and ``tail_threshold_seconds``
        gauge (the live trip line, scrapeable next to the histograms it
        polices).
    slo_threshold_s: the latency objective's threshold (floors the trip
        line — under a declared SLO, "slow" never means less than the
        objective says); None when no latency SLO is configured.
    factor: trip multiplier over the rolling p99.
    seed_s: initial p99 estimate (the engine passes its AOT warm
        latency — the only latency fact that exists before traffic).
    window: rolling-p99 sample window (completions).
    min_interval_s: rate limit between captured samples; slower requests
        than the current sample's are NOT exempt — a latency storm must
        produce a bounded event stream, the histograms carry the volume.
    capacity: in-memory sample ring size (the ``/debugz`` surface);
        0 disables capture entirely (the A/B-overhead arm).
    events: optional :class:`~mpi4dl_tpu.telemetry.jsonl.JsonlWriter`.
    flight: optional :class:`~mpi4dl_tpu.telemetry.flight.FlightRecorder`.
    clock: injectable monotonic clock for deterministic tests.
    """

    def __init__(
        self,
        registry=None,
        slo_threshold_s: "float | None" = None,
        factor: float = 4.0,
        seed_s: "float | None" = None,
        window: int = 256,
        min_interval_s: float = 1.0,
        capacity: int = 64,
        events=None,
        flight=None,
        clock=time.monotonic,
    ):
        from mpi4dl_tpu import telemetry

        self.slo_threshold_s = (
            float(slo_threshold_s) if slo_threshold_s is not None else None
        )
        self.factor = float(factor)
        self.min_interval_s = float(min_interval_s)
        self.capacity = int(capacity)
        self._events = events
        self._flight = flight
        self._clock = clock
        self._lock = threading.Lock()
        self._window: collections.deque = collections.deque(
            maxlen=max(2, int(window))
        )
        if seed_s is not None:
            self._window.append(float(seed_s))
        self._p99 = float(seed_s) if seed_s is not None else 0.0
        self._since_recompute = 0
        self._last_sample_t = float("-inf")
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, self.capacity)
        )
        self.captured = 0
        self.suppressed = 0  # over-threshold but inside the rate limit
        self._m_samples = None
        self._m_threshold = None
        if registry is not None:
            self._m_samples = telemetry.declare(registry, "tail_samples_total")
            self._m_threshold = telemetry.declare(
                registry, "tail_threshold_seconds"
            )
            self._m_threshold.set(self.threshold())

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def threshold(self) -> float:
        """The live trip line: ``max(SLO threshold, factor x rolling
        p99)``."""
        with self._lock:
            p99 = self._p99
        thr = self.factor * p99
        if self.slo_threshold_s is not None:
            thr = max(thr, self.slo_threshold_s)
        return thr

    def observe(
        self,
        trace_id: str,
        e2e_s: float,
        spans: "list[dict]",
        **context,
    ) -> "dict | None":
        """Offer one completed request. Returns the captured
        ``tail.sample`` event when the request tripped the threshold and
        the rate limiter admitted it, else None.

        The threshold is evaluated BEFORE this completion enters the
        rolling window, so a slow request cannot raise the bar it is
        judged against. ``context`` lands verbatim under ``attrs`` —
        the engine passes queue depth at admission, bucket/batch size,
        pad waste, dispatch seq, watchdog state, latest attribution.
        """
        if self.capacity <= 0:
            return None
        e2e_s = float(e2e_s)
        thr = self.threshold()
        tripped = thr > 0 and e2e_s > thr
        with self._lock:
            self._window.append(e2e_s)
            self._since_recompute += 1
            if self._since_recompute >= RECOMPUTE_EVERY:
                self._since_recompute = 0
                p = percentiles(list(self._window), (99,))
                if p["p99"] is not None:
                    self._p99 = p["p99"]
                refresh_gauge = True
            else:
                refresh_gauge = False
            if tripped:
                now = self._clock()
                if now - self._last_sample_t < self.min_interval_s:
                    self.suppressed += 1
                    tripped = False
                else:
                    self._last_sample_t = now
        if refresh_gauge and self._m_threshold is not None:
            self._m_threshold.set(self.threshold())
        if not tripped:
            return None
        return self._capture(trace_id, e2e_s, thr, spans, context)

    def _capture(self, trace_id, e2e_s, thr, spans, context) -> dict:
        from mpi4dl_tpu.telemetry.jsonl import validate_event

        with self._lock:
            p99 = self._p99
        ev = validate_event({
            "ts": time.time(),
            "kind": "event",
            "name": "tail.sample",
            "attrs": {
                "trace_id": str(trace_id),
                "e2e_latency_s": e2e_s,
                "threshold_s": thr,
                "rolling_p99_s": p99,
                "slo_threshold_s": self.slo_threshold_s,
                "factor": self.factor,
                "phases": {
                    s["phase"]: s["duration_s"] for s in spans
                },
                "spans": [dict(s) for s in spans],
                "pid": os.getpid(),
                **context,
            },
        })
        with self._lock:
            self._ring.append(ev)
            self.captured += 1
        if self._m_samples is not None:
            self._m_samples.inc()
        if self._flight is not None:
            self._flight.record(ev)
        if self._events is not None and self._events.enabled:
            self._events.write(ev)
        return ev

    def tail(self, n: int = 20) -> "list[dict]":
        """Most recent ``n`` captured samples, oldest first."""
        with self._lock:
            ring = list(self._ring)
        return ring[-int(n):]

    def state(self) -> dict:
        """The ``/debugz`` payload: the live trip line, its inputs, and
        the recent samples."""
        with self._lock:
            p99 = self._p99
            window_n = len(self._window)
        return {
            "threshold_s": self.threshold(),
            "rolling_p99_s": p99,
            "slo_threshold_s": self.slo_threshold_s,
            "factor": self.factor,
            "window_n": window_n,
            "captured": self.captured,
            "suppressed": self.suppressed,
            "samples": self.tail(),
        }
