"""Flight recorder: an always-on bounded ring of recent telemetry events.

The JSONL log (:mod:`mpi4dl_tpu.telemetry.jsonl`) is opt-in and grows
without bound — the wrong tool for "what were the last 500 requests doing
when the process died". The flight recorder is the postmortem tool: a
``deque(maxlen=capacity)`` of already-built span/marker events (plus a
rate-limited registry snapshot at most once per ``snapshot_interval_s``),
costing one lock-guarded append per request until something goes wrong.
On a watchdog trip, a batcher crash, SIGTERM, or an explicit call,
:meth:`FlightRecorder.dump` writes the ring — every line checked through
the same :func:`mpi4dl_tpu.telemetry.jsonl.validate_event` schema the
live log promises, with a fresh final metrics snapshot and a dump marker
appended — to a timestamped JSONL file, and counts it in the cataloged
``flight_recorder_dumps_total{reason=}``.

``capacity=0`` disables recording entirely (``record`` returns before
taking the lock), which is how the overhead claim in
docs/OBSERVABILITY.md is A/B-measured.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import signal
import tempfile
import threading
import time

from mpi4dl_tpu.telemetry.jsonl import ENV_DIR, metrics_event, validate_event


class FlightRecorder:
    """Bounded in-memory ring of telemetry events, dumpable as JSONL.

    capacity: ring size in events; 0 disables the recorder.
    registry: source for the rate-limited in-ring metric snapshots, the
        final at-dump snapshot, and the dump counter.
    directory: where dumps land; falls back to ``MPI4DL_TPU_TELEMETRY_DIR``
        then the system temp dir, resolved at dump time.
    incident: optional zero-arg callable returning the currently open
        incident's id (``IncidentManager.open_incident_id``) or None.
        A dump triggered while an incident is open files under
        ``reason="incident"`` with the incident id and the original
        trigger in the dump marker — the incident's ``close`` event
        links it back.
    """

    def __init__(
        self,
        capacity: int = 512,
        registry=None,
        directory: "str | None" = None,
        snapshot_interval_s: float = 1.0,
        incident=None,
    ):
        self.incident = incident
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, self.capacity)
        )
        self._lock = threading.Lock()
        self._registry = registry
        self._directory = directory
        self._interval = float(snapshot_interval_s)
        self._last_snap = 0.0
        self._seq = itertools.count()
        self._installed: dict = {}
        self._m_dumps = None
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._m_dumps = telemetry.declare(
                registry, "flight_recorder_dumps_total"
            )

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def record(self, event: dict) -> None:
        """Append one event (a dict in the JSONL event schema; validated
        at dump, not here — the hot path is one append)."""
        if self.capacity <= 0:
            return
        with self._lock:
            self._ring.append(event)
        if self._registry is not None:
            now = time.monotonic()
            if now - self._last_snap >= self._interval:
                self._last_snap = now
                snap = metrics_event(self._registry)
                with self._lock:
                    self._ring.append(snap)

    def tail(self, n: int = 50) -> "list[dict]":
        """Most recent ``n`` events, oldest first — the ``/debugz``
        payload."""
        with self._lock:
            ring = list(self._ring)
        return ring[-int(n):]

    def dump(self, path: "str | None" = None, reason: str = "manual") -> "str | None":
        """Write the ring (+ a final metrics snapshot + a dump marker) as
        schema-valid JSONL; returns the path, or None when disabled.
        Events that fail validation are dropped and counted in the dump
        marker rather than aborting the postmortem."""
        if self.capacity <= 0:
            return None
        with self._lock:
            events = list(self._ring)
        if self._registry is not None:
            events.append(metrics_event(self._registry))
        good, dropped = [], 0
        for ev in events:
            try:
                good.append(validate_event(ev))
            except ValueError:
                dropped += 1
        # A dump captured while an incident is open belongs to the
        # incident: it refiles under reason="incident" carrying the id
        # (and the original trigger), so the incident's close event can
        # link every postmortem artifact taken in its window.
        iid = None
        if self.incident is not None:
            try:
                iid = self.incident()
            except Exception:  # noqa: BLE001 — a broken provider must
                iid = None  # not break the postmortem dump
        marker_attrs = {"reason": reason, "events": len(good),
                        "dropped_invalid": dropped}
        if iid:
            marker_attrs["trigger"] = reason
            marker_attrs["incident"] = iid
            marker_attrs["reason"] = reason = "incident"
        good.append(validate_event({
            "ts": time.time(),
            "kind": "event",
            "name": "flight.dump",
            "attrs": marker_attrs,
        }))
        if path is None:
            directory = (
                self._directory
                or os.environ.get(ENV_DIR)
                or tempfile.gettempdir()
            )
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(
                directory,
                f"flight-{os.getpid()}-{next(self._seq)}-{reason}.jsonl",
            )
        with open(path, "w") as f:
            for ev in good:
                f.write(json.dumps(ev) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if self._m_dumps is not None:
            self._m_dumps.inc(reason=reason)
        return path

    # -- signal integration ---------------------------------------------------

    def install_signal_handlers(self, signums=(signal.SIGTERM,)) -> bool:
        """Dump on the given signals, then chain to whatever handler was
        installed before (or re-deliver with the default disposition, so
        SIGTERM still terminates). Main-thread only — returns False when
        the interpreter refuses (library code must not fight the host
        process for signals)."""
        ok = True
        for signum in signums:
            try:
                prev = signal.signal(signum, self._make_handler(signum))
            except ValueError:  # not the main thread
                ok = False
                continue
            self._installed[signum] = prev
        return ok

    def _make_handler(self, signum):
        def handler(sig, frame):
            try:
                self.dump(reason=signal.Signals(sig).name.lower())
            except Exception:  # noqa: BLE001 — the postmortem hook must
                pass  # never mask the signal itself
            prev = self._installed.get(sig)
            if callable(prev):
                prev(sig, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(sig, signal.SIG_DFL)
                os.kill(os.getpid(), sig)

        return handler

    def uninstall_signal_handlers(self) -> None:
        for signum, prev in self._installed.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):
                pass
        self._installed.clear()
