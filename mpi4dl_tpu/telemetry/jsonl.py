"""Structured JSONL telemetry event log + the schema it promises.

One line per event, three kinds:

- ``span`` — a request's lifecycle spans (:mod:`telemetry.spans`);
- ``metrics`` — a full registry snapshot (`registry.snapshot()` payload;
  the SAME dict bench.py embeds under ``"telemetry"`` in its result
  lines, so BENCH_*.json and the event log share one schema);
- ``event`` — a free-form named marker (engine start/stop, lint runs).

Writing is opt-in: construct :class:`JsonlWriter` with a directory, or set
``MPI4DL_TPU_TELEMETRY_DIR``; otherwise every write is a no-op costing one
attribute check. Every write validates against :func:`validate_event`
first — a malformed event fails at the publisher, where the bug is, not
in whatever later reads the log.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time

ENV_DIR = "MPI4DL_TPU_TELEMETRY_DIR"

EVENT_KINDS = ("span", "metrics", "event")
_METRIC_TYPES = ("counter", "gauge", "histogram")


def validate_event(event: dict) -> dict:
    """Check one telemetry event against the schema; returns it unchanged
    or raises ``ValueError`` naming the first violation."""
    if not isinstance(event, dict):
        raise ValueError(f"event must be a dict, got {type(event).__name__}")

    def need(key, types):
        v = event.get(key)
        if not isinstance(v, types):
            raise ValueError(
                f"event[{key!r}] must be {types}, got {type(v).__name__}"
            )
        return v

    need("ts", (int, float))
    kind = need("kind", str)
    if kind not in EVENT_KINDS:
        raise ValueError(f"unknown event kind {kind!r}; expected {EVENT_KINDS}")

    if kind == "span":
        need("name", str)
        need("trace_id", str)
        spans = need("spans", list)
        if not spans:
            raise ValueError("span event needs at least one span")
        for s in spans:
            if not isinstance(s, dict):
                raise ValueError("each span must be a dict")
            if not isinstance(s.get("phase"), str):
                raise ValueError("span['phase'] must be a string")
            for k in ("start_s", "end_s", "duration_s"):
                if not isinstance(s.get(k), (int, float)):
                    raise ValueError(f"span[{k!r}] must be a number")
            if s["end_s"] < s["start_s"]:
                raise ValueError(
                    f"span {s['phase']!r} ends before it starts"
                )
        if "attrs" in event and not isinstance(event["attrs"], dict):
            raise ValueError("event['attrs'] must be a dict")

    elif kind == "metrics":
        metrics = need("metrics", dict)
        for name, m in metrics.items():
            if not isinstance(m, dict):
                raise ValueError(f"metrics[{name!r}] must be a dict")
            if m.get("type") not in _METRIC_TYPES:
                raise ValueError(
                    f"metrics[{name!r}]['type'] must be one of "
                    f"{_METRIC_TYPES}, got {m.get('type')!r}"
                )
            series = m.get("series")
            if not isinstance(series, list):
                raise ValueError(f"metrics[{name!r}]['series'] must be a list")
            for s in series:
                if not isinstance(s.get("labels"), dict):
                    raise ValueError(
                        f"metrics[{name!r}] series needs a labels dict"
                    )
                if m["type"] == "histogram":
                    for k in ("count", "sum"):
                        if not isinstance(s.get(k), (int, float)):
                            raise ValueError(
                                f"metrics[{name!r}] histogram series "
                                f"[{k!r}] must be a number"
                            )
                    if not isinstance(s.get("buckets"), dict):
                        raise ValueError(
                            f"metrics[{name!r}] histogram series needs "
                            "cumulative buckets"
                        )
                elif not isinstance(s.get("value"), (int, float)):
                    raise ValueError(
                        f"metrics[{name!r}] series ['value'] must be a number"
                    )

    else:  # "event"
        need("name", str)
        if "attrs" in event and not isinstance(event["attrs"], dict):
            raise ValueError("event['attrs'] must be a dict")
    return event


def metrics_event(registry, ts: "float | None" = None) -> dict:
    """Registry snapshot as one schema-valid JSONL event."""
    return validate_event({
        "ts": time.time() if ts is None else float(ts),
        "kind": "metrics",
        "metrics": registry.snapshot(),
    })


class JsonlWriter:
    """Append-only, threadsafe, schema-validating JSONL sink.

    ``directory=None`` falls back to ``MPI4DL_TPU_TELEMETRY_DIR``; with
    neither set the writer is disabled and ``write`` is a no-op (telemetry
    must never be a tax on runs that didn't ask for it).
    """

    FLUSH_EVERY = 100  # span-rate events flush in batches; see write()

    def __init__(
        self, directory: "str | None" = None, filename: "str | None" = None
    ):
        directory = directory or os.environ.get(ENV_DIR)
        self._lock = threading.Lock()
        self._fh = None
        self._unflushed = 0
        self.path: "str | None" = None
        if directory:
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(
                directory, filename or f"telemetry-{os.getpid()}.jsonl"
            )
            self._fh = open(self.path, "a")
            # Span events flush in batches of FLUSH_EVERY; a process that
            # exits without close() must still land the final partial
            # batch on disk.
            atexit.register(self.close)

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def write(self, event: dict) -> None:
        if self._fh is None:
            return
        line = json.dumps(validate_event(event))
        # Per-request span events arrive at serving rate (measured ~4%
        # throughput lost to per-write flushes at ~2.3k rps on CPU), so
        # spans flush in batches; rare kinds (metrics snapshots, markers)
        # flush immediately. close() flushes the tail.
        with self._lock:
            if self._fh is None:  # closed under us
                return
            self._fh.write(line + "\n")
            self._unflushed += 1
            if event["kind"] != "span" or self._unflushed >= self.FLUSH_EVERY:
                self._fh.flush()
                self._unflushed = 0

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._unflushed = 0

    def close(self) -> None:
        """Flush (the final partial span batch included) and close; safe
        to call twice — the atexit hook and an explicit close coexist."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None
                self._unflushed = 0
        try:
            atexit.unregister(self.close)
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass


def read_events(path: str, validate: bool = True) -> "list[dict]":
    """Load a JSONL telemetry log; validates each event by default (the
    round-trip property the tier-1 tests pin)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            out.append(validate_event(ev) if validate else ev)
    return out
