"""Numerics sentinel: golden canary probes + parameter-integrity auditing.

Every correctness guarantee in this repo — sharded-vs-plain row parity,
single-device bitwise tiled identity, loss-golden pipelines — is asserted
at *test time* and then never checked again. A production replica whose
HBM bit-flips, whose params are torn by a bad restore, or whose
recompiled executable silently diverges serves wrong answers at full
availability, invisible to liveness watchdogs, SLO burn, and tail
forensics alike. This module is the fourth leg of the observability
stack (liveness, latency, memory, **correctness**): a measured verdict
about *what the model answers*, continuously, against a reference
recorded at warm-up.

Pieces:

- :func:`canary_example` — a deterministic probe input derived from
  MODEL-level facts only (example shape + dtype + seed), so every
  replica of the same model — single-chip, sharded, or tiled — derives
  the *same* canary and their output digests are comparable across the
  fleet and across predictor implementations.
- :func:`exact_digest` / :func:`quantized_digest` — two digest
  semantics matching the two equality regimes this repo documents:
  within one executable fingerprint (PR-18 ``xf…``) the forward is
  bitwise-deterministic, so the exact digest must match bit for bit;
  across *different* executables (another mesh, another predictor,
  another XLA version) parity only holds at the documented f32
  reduction-order tolerance, so the tolerance-quantized digest
  (:data:`CANARY_ATOL` grid) is the comparable form. Quantization is
  boundary-sensitive by construction — equal qdigests imply tolerance
  agreement, unequal qdigests across different fingerprints are
  advisory, never paging, evidence.
- :func:`params_checksum` — an order-deterministic checksum over the
  param tree + BN stats, recorded at load and re-audited on the
  sentinel cadence; the fleet compares it across replicas serving the
  same model (``fleet_numerics_skew{replica}`` — the straggler pattern
  applied to correctness).
- :class:`CanaryState` — per-bucket references, verify verdicts
  (``ok`` / ``tolerance`` / ``divergence`` / ``error`` / ``skipped``),
  the cataloged ``canary_checks_total{result}`` +
  ``canary_max_divergence`` series, schema-valid ``canary.failure``
  events into the JSONL log + flight ring, and failure callbacks (the
  fleet worker fences itself on the first divergence).
- :class:`CanarySentinel` — the daemon that ticks the engine's canary
  round (inject through the REAL dispatch path + re-audit the
  checksum) every ``interval_s``.
- :func:`corrupt_params` — the chaos hook (``corrupt:REPLICA[=BITS]``):
  flip exponent bits in a live predictor's largest param buffer, the
  end-to-end drill that proves detect → page → quarantine.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time

import numpy as np

#: The cross-executable comparison tolerance: the loosest documented f32
#: reduction-order bound in this repo (sharded-vs-plain row parity holds
#: at atol=1e-5; tiled-vs-monolithic at 5e-6 — docs/SERVING.md). A
#: canary row within this of its reference is ``tolerance``; beyond it
#: is ``divergence`` — real corruption, not reduction order.
CANARY_ATOL = 1e-5

#: Outcome vocabulary of one canary check (canary_checks_total{result}).
CANARY_RESULTS = ("ok", "tolerance", "divergence", "error", "skipped")


# -- probe derivation ---------------------------------------------------------


def canary_example(example_shape, dtype="float32", seed: int = 0):
    """The deterministic golden-probe input for one model configuration.

    Derived from MODEL-level facts only (shape, dtype, seed) — never
    from mesh/predictor/executable facts — so every replica serving the
    same model computes the identical probe and the fleet can compare
    their answers. Seeded through sha256 of the facts, not bare
    ``seed``, so two models with different shapes never share a probe
    by coincidence."""
    shape = tuple(int(d) for d in example_shape)
    material = json.dumps(
        {"example_shape": list(shape), "dtype": str(np.dtype(dtype).name),
         "seed": int(seed)},
        sort_keys=True,
    ).encode()
    h = hashlib.sha256(material).digest()
    rng = np.random.default_rng(int.from_bytes(h[:8], "big"))
    return rng.standard_normal(shape).astype(np.dtype(dtype))


# -- digests ------------------------------------------------------------------


def exact_digest(arr) -> str:
    """Bitwise digest (``xd`` + 16 hex) of one output row: shape, dtype,
    and raw bytes. Comparable only between runs of the SAME executable
    fingerprint, where the forward is bitwise-deterministic."""
    a = np.ascontiguousarray(np.asarray(arr))
    h = hashlib.sha256()
    h.update(str((a.shape, a.dtype.str)).encode())
    h.update(a.tobytes())
    return "xd" + h.hexdigest()[:16]


def quantized_digest(arr, atol: float = CANARY_ATOL) -> str:
    """Tolerance-quantized digest (``xq`` + 16 hex): values snapped to a
    ``2*atol`` grid before hashing, so two executables that agree at the
    documented f32 bound *usually* share it. Equal ⇒ tolerance-equal;
    unequal across different fingerprints is advisory (grid-boundary
    straddles exist by construction)."""
    a = np.asarray(arr, np.float64)
    q = np.round(a / (2.0 * float(atol))).astype(np.int64)
    h = hashlib.sha256()
    h.update(str((q.shape, float(atol))).encode())
    h.update(np.ascontiguousarray(q).tobytes())
    return "xq" + h.hexdigest()[:16]


def ulp_diff(a, b) -> int:
    """Max ULP distance between two f32 arrays: the int32 view of an
    IEEE-754 float is monotonic within a sign, so the lexicographic
    integer distance counts representable floats between the values —
    the resolution-independent form of max-abs."""
    fa = np.ascontiguousarray(np.asarray(a, np.float32))
    fb = np.ascontiguousarray(np.asarray(b, np.float32))
    ia = fa.view(np.int32).astype(np.int64)
    ib = fb.view(np.int32).astype(np.int64)
    # Map the sign-magnitude int pattern onto a monotonic number line.
    ia = np.where(ia < 0, np.int64(-(2**31)) - ia, ia)
    ib = np.where(ib < 0, np.int64(-(2**31)) - ib, ib)
    return int(np.max(np.abs(ia - ib))) if ia.size else 0


# -- parameter integrity ------------------------------------------------------


def _iter_leaves(tree, path=""):
    """Deterministic leaf traversal of a params/stats pytree without a
    jax dependency: dicts by sorted key, sequences by index, everything
    else an array leaf."""
    if tree is None:
        return
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _iter_leaves(tree[k], f"{path}/{k}")
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _iter_leaves(v, f"{path}/{i}")
    else:
        yield path, tree


def params_checksum(params, stats=None) -> str:
    """Order-deterministic checksum (``pc`` + 16 hex) of the param tree
    + BN stats: every leaf's path, shape, dtype, and raw bytes, in
    sorted-traversal order. Recorded at load, re-audited on the sentinel
    cadence, compared across replicas by federation — a torn restore or
    an in-memory bit-flip changes it; a healthy replica's never moves."""
    h = hashlib.sha256()
    for path, leaf in _iter_leaves({"params": params, "stats": stats}):
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(path.encode())
        h.update(str((a.shape, a.dtype.str)).encode())
        h.update(a.tobytes())
    return "pc" + h.hexdigest()[:16]


def flip_bits(arr: np.ndarray, bits: int = 3, seed: int = 0) -> "tuple[np.ndarray, dict]":
    """Flip one high exponent bit (bit 30 of the f32 pattern) in
    ``bits`` distinct elements of a float32 array — the HBM-corruption
    model of the ``corrupt:`` chaos drill. Returns the corrupted copy
    and forensics (flat indices, before/after samples)."""
    a = np.array(arr, np.float32, copy=True)
    flat = a.reshape(-1)
    n = max(1, min(int(bits), flat.size))
    rng = np.random.default_rng(int(seed))
    idx = rng.choice(flat.size, size=n, replace=False)
    before = flat[idx].tolist()
    iv = flat.view(np.int32)
    iv[idx] ^= np.int32(1 << 30)
    return a, {
        "bits": int(n),
        "indices": [int(i) for i in idx],
        "before": [float(v) for v in before],
        "after": [float(v) for v in flat[idx]],
    }


def corrupt_params(predictor, bits: int = 3, seed: int = 0) -> dict:
    """Bit-flip a live predictor's param buffer (the largest float32
    leaf) and reload the corrupted tree onto the device(s) through the
    predictor's own placement (:meth:`reload_params`). This is the
    ``corrupt:REPLICA[=BITS]`` chaos action's engine half — it models
    silent HBM/restore corruption, so it deliberately does NOT touch
    checksums or references: the sentinel must *discover* it."""
    params, _stats = predictor.param_tree()
    leaves = [
        (path, leaf) for path, leaf in _iter_leaves(params)
        if np.asarray(leaf).dtype == np.float32
    ]
    if not leaves:
        raise ValueError("predictor has no float32 param leaf to corrupt")
    path, victim = max(leaves, key=lambda pl: np.asarray(pl[1]).size)
    corrupted, forensics = flip_bits(np.asarray(victim), bits=bits, seed=seed)

    def _rebuild(tree, at):
        if at == path:
            return corrupted
        if isinstance(tree, dict):
            return {k: _rebuild(v, f"{at}/{k}") for k, v in tree.items()}
        if isinstance(tree, (list, tuple)):
            return type(tree)(
                _rebuild(v, f"{at}/{i}") for i, v in enumerate(tree)
            )
        return tree

    predictor.reload_params(_rebuild(params, ""))
    forensics["leaf"] = path
    forensics["leaf_size"] = int(np.asarray(victim).size)
    return forensics


# -- canary state -------------------------------------------------------------


class CanaryState:
    """Per-engine canary bookkeeping: warm-up references, verify
    verdicts, metrics, failure events, and the fence callbacks.

    registry / events / flight: the engine's telemetry surfaces; any
        may be None (metrics just aren't published / events just
        aren't written). ``flight`` may be bound after construction
        (the engine creates its flight ring post-warm-up).
    device / program: forensic labels for the ``canary.failure`` event.
    """

    def __init__(self, registry=None, events=None, flight=None,
                 atol: float = CANARY_ATOL, device: str = "",
                 program: str = ""):
        from mpi4dl_tpu import telemetry

        self.atol = float(atol)
        self.device = str(device)
        self.program = str(program)
        self.events = events
        self.flight = flight
        self._refs: "dict[int, dict]" = {}
        self._lock = threading.Lock()
        self._callbacks: "list" = []
        self.load_checksum: "str | None" = None
        self.current_checksum: "str | None" = None
        self.checks = 0
        self.failures = 0
        self.max_divergence = 0.0
        self.last: "dict | None" = None
        self._m_checks = self._m_divergence = None
        if registry is not None:
            self._m_checks = telemetry.declare(registry, "canary_checks_total")
            self._m_divergence = telemetry.declare(
                registry, "canary_max_divergence"
            )
            self._m_divergence.set(0.0)

    # -- references ----------------------------------------------------------

    def record_reference(self, bucket: int, row,
                         fingerprint: "str | None" = None) -> dict:
        """Record one bucket's golden reference: the canary row's full
        output (kept for max-abs/ulp forensics at verify time), its
        exact digest (valid for this executable fingerprint), and its
        tolerance-quantized digest (comparable across executables)."""
        row = np.array(np.asarray(row), copy=True)
        rec = {
            "row": row,
            "digest": exact_digest(row),
            "qdigest": quantized_digest(row, self.atol),
            "fingerprint": fingerprint,
        }
        with self._lock:
            self._refs[int(bucket)] = rec
        return {k: rec[k] for k in ("digest", "qdigest", "fingerprint")}

    def reference(self, bucket: int) -> "dict | None":
        with self._lock:
            return self._refs.get(int(bucket))

    def references_view(self) -> dict:
        """Digest-only view of every bucket reference (healthz /
        snapshotz / the ready ledger — no arrays)."""
        with self._lock:
            return {
                str(b): {k: r[k] for k in ("digest", "qdigest", "fingerprint")}
                for b, r in sorted(self._refs.items())
            }

    # -- integrity -----------------------------------------------------------

    def record_checksum(self, checksum: str, load: bool = False) -> bool:
        """Record a (re)computed params checksum. The first record (or
        ``load=True``) becomes the load-time reference; a later
        mismatch is parameter corruption — counted as a ``divergence``
        check and failed through the same event/callback path as a
        canary miss. Returns True while the checksum is consistent."""
        checksum = str(checksum)
        with self._lock:
            first = self.load_checksum is None
            if load or first:
                self.load_checksum = checksum
            self.current_checksum = checksum
            ok = checksum == self.load_checksum
        if not ok:
            self._conclude("divergence", {
                "check": "params_checksum",
                "expected": self.load_checksum,
                "got": checksum,
            })
        return ok

    # -- verification --------------------------------------------------------

    def on_failure(self, callback) -> None:
        """Register a divergence callback (called with the failure
        attrs). The fleet worker uses this to fence itself: stop
        answering /predict the moment the sentinel proves corruption."""
        self._callbacks.append(callback)

    def skip(self, reason: str = "") -> None:
        """Count a canary round that could not run (queue full)."""
        if self._m_checks is not None:
            self._m_checks.inc(result="skipped")
        with self._lock:
            self.last = {"result": "skipped", "reason": reason,
                         "ts": time.time()}

    def verify(self, bucket: int, row,
               fingerprint: "str | None" = None) -> dict:
        """Verdict for one canary row that came back through the real
        dispatch path, against the bucket's warm-up reference:

        - ``ok`` — exact digest match (the expected steady state inside
          one executable fingerprint: the forward is bitwise
          deterministic);
        - ``tolerance`` — bitwise differs but max-abs ≤ atol (a changed
          executable, e.g. post-respawn recompile — within documented
          bounds, not corruption);
        - ``divergence`` — beyond tolerance: real corruption. Emits the
          ``canary.failure`` event and fires the fence callbacks.
        - ``error`` — no reference for this bucket (a verify bug, not a
          model verdict).
        """
        ref = self.reference(bucket)
        row = np.asarray(row)
        if ref is None:
            return self._conclude("error", {
                "check": "probe", "bucket": int(bucket),
                "error": "no reference recorded for bucket",
            })
        attrs: dict = {
            "check": "probe",
            "bucket": int(bucket),
            "fingerprint": fingerprint,
            "reference_fingerprint": ref["fingerprint"],
            "expected_digest": ref["digest"],
        }
        got = exact_digest(row)
        attrs["got_digest"] = got
        if got == ref["digest"]:
            return self._conclude("ok", attrs)
        max_abs = float(np.max(np.abs(
            np.asarray(row, np.float64) - np.asarray(ref["row"], np.float64)
        )))
        attrs["max_abs"] = max_abs
        attrs["ulp"] = ulp_diff(row, ref["row"])
        attrs["argmax_moved"] = bool(
            int(np.argmax(row)) != int(np.argmax(ref["row"]))
        )
        if max_abs <= self.atol:
            return self._conclude("tolerance", attrs)
        return self._conclude("divergence", attrs)

    def _conclude(self, result: str, attrs: dict) -> dict:
        assert result in CANARY_RESULTS
        verdict = {"result": result, "ts": time.time(), **attrs}
        with self._lock:
            self.checks += 1
            self.last = verdict
            if result == "divergence":
                self.failures += 1
                self.max_divergence = max(
                    self.max_divergence, float(attrs.get("max_abs", 0.0))
                )
        if self._m_checks is not None:
            self._m_checks.inc(result=result)
        if self._m_divergence is not None:
            self._m_divergence.set(self.max_divergence)
        if result == "divergence":
            self._emit_failure(attrs)
        return verdict

    def _emit_failure(self, attrs: dict) -> None:
        """One schema-valid ``canary.failure`` event (JSONL log + flight
        ring) + the fence callbacks. Event first: the paper trail must
        exist even if a callback dies."""
        ev = {
            "ts": time.time(),
            "kind": "event",
            "name": "canary.failure",
            "attrs": {
                "device": self.device,
                "program": self.program,
                "failures": self.failures,
                "load_checksum": self.load_checksum,
                "current_checksum": self.current_checksum,
                **attrs,
            },
        }
        if self.flight is not None and getattr(self.flight, "enabled", False):
            self.flight.record(ev)
        if self.events is not None and getattr(self.events, "enabled", False):
            self.events.write(ev)
        for cb in self._callbacks:
            try:
                cb(ev["attrs"])
            except Exception:  # noqa: BLE001 — one dead fence callback
                pass  # must not stop the others (or the sentinel)

    # -- surfaces ------------------------------------------------------------

    def view(self) -> dict:
        """The numerics payload for /healthz, /snapshotz, and the ready
        handshake: checksums, check/failure counters, the last verdict
        (arrays stripped), and the per-bucket reference digests."""
        with self._lock:
            last = dict(self.last) if self.last else None
            return {
                "params_checksum": self.current_checksum,
                "load_checksum": self.load_checksum,
                "checks": self.checks,
                "failures": self.failures,
                "max_divergence": self.max_divergence,
                "last": last,
                "buckets": {
                    str(b): {
                        k: r[k] for k in ("digest", "qdigest", "fingerprint")
                    }
                    for b, r in sorted(self._refs.items())
                },
            }


class CanarySentinel:
    """The continuous-probe daemon: every ``interval_s`` it runs the
    engine's canary round (inject the golden probe through the REAL
    dispatch path, then re-audit the params checksum). The tick callable
    owns all engine knowledge; the sentinel owns only the cadence."""

    def __init__(self, tick, interval_s: float = 10.0, name: str = ""):
        self._tick = tick
        self.interval_s = float(interval_s)
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._name = name or "mpi4dl-canary-sentinel"
        self.ticks = 0

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name=self._name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self._tick()
                self.ticks += 1
            except Exception:  # noqa: BLE001 — the sentinel must outlive
                pass  # any single bad tick (like the supervisor's loop)
