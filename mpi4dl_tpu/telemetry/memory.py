"""Memory observability: live HBM gauges, footprint ledger, OOM forensics.

The paper's entire reason for 5D parallelism is that very-high-resolution
images don't fit in device memory, yet until this module the stack was
blind on exactly that axis: the bench walk died at 8192² with an unparsed
``RESOURCE_EXHAUSTED`` string and nothing scraped a single byte of HBM.
Three pieces (docs/OBSERVABILITY.md "Memory"):

- :class:`MemoryMonitor` — samples ``jax.Device.memory_stats()`` per
  device at the SLO-evaluator cadence into the cataloged
  ``device_hbm_used_bytes`` / ``device_hbm_limit_bytes`` /
  ``device_hbm_headroom_ratio`` gauges. Backends that report no stats
  (the CPU simulation) degrade to *absent-not-wrong*: the gauge names
  stay declared, no series is ever published, nothing trips, and the
  sampling thread retires itself after the first absent sample.
- :class:`FootprintLedger` — records
  :func:`mpi4dl_tpu.analysis.memory.memory_summary` peaks for every
  executable the process compiles (each warmed serving bucket, the train
  step, eval programs) under ``serve_bucket_peak_hbm_bytes{bucket=}`` /
  ``program_peak_hbm_bytes{program=}``, and keeps the full breakdown for
  ``engine.stats()`` / ``/debugz`` / the feasibility planner's artifact
  mode.
- **OOM forensics** — :func:`parse_resource_exhausted` turns XLA's
  RESOURCE_EXHAUSTED breakdown (the message carries the full HBM table —
  docs/PERF.md round 4 learned this the hard way after three rounds of
  truncating it) into a structured record naming the memory space,
  used/limit/exceeded bytes, and the largest program allocations with
  their padding expansion; :func:`emit_oom_report` wraps it as a
  schema-valid ``oom.report`` JSONL event into the event log, the
  flight ring (+ optional dump), and the ``oom_reports_total`` counter.
"""

from __future__ import annotations

import json
import re
import threading
import time

# -- size parsing -------------------------------------------------------------

# XLA renders sizes in binary units ("18.95G" == 18.95 GiB) — the same
# convention its allocation dumps and docs/PERF.md round 4 use.
_UNIT = {"": 1, "B": 1, "K": 2**10, "M": 2**20, "G": 2**30, "T": 2**40,
         "P": 2**50}
_SIZE_RE = re.compile(r"^([\d.]+)\s*([KMGTP]?)(?:i?B)?$")


def parse_size(text: str) -> "int | None":
    """``"18.95G"`` / ``"288.00M"`` / ``"276.0K"`` / ``"123456"`` →
    bytes (binary units, XLA's convention); None when unparseable."""
    m = _SIZE_RE.match(str(text).strip())
    if not m:
        return None
    try:
        return int(float(m.group(1)) * _UNIT[m.group(2)])
    except (ValueError, OverflowError):
        return None


# -- OOM detection + parsing --------------------------------------------------

OOM_SIGNATURES = (
    "RESOURCE_EXHAUSTED",
    "ResourceExhausted",
    "Ran out of memory",
    "Out of memory",
)


def exception_chain_text(exc) -> str:
    """str(exc) plus every chained ``__cause__``/``__context__`` message
    — the HBM table can sit in a wrapped cause while the outer message
    says only "compile helper died" (bench.py's lesson, ADVICE r4)."""
    if isinstance(exc, str):
        return exc
    parts, seen, todo = [], set(), [exc]
    while todo:
        e = todo.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        parts.append(str(e))
        todo.extend((e.__cause__, e.__context__))
    return "\n".join(parts)


def is_oom_error(exc_or_msg) -> bool:
    """True when the exception (whole chain) or message carries an XLA
    memory-exhaustion signature."""
    text = exception_chain_text(exc_or_msg)
    return any(sig in text for sig in OOM_SIGNATURES)


_SPACE_RE = re.compile(r"Ran out of memory in memory space (\w+)")
_USED_RE = re.compile(
    r"Used\s+([\d.]+[KMGTP]?i?B?)\s+of\s+([\d.]+[KMGTP]?i?B?)"
)
_EXCEEDED_RE = re.compile(r"Exceeded \w+ capacity by\s+([\d.]+[KMGTP]?i?B?)")
_PROGRAM_RE = re.compile(r"Program \w+ requirement\s+([\d.]+[KMGTP]?i?B?)")
_TOTAL_RE = re.compile(r"Total \w+ usage\s*>=\s*([\d.]+[KMGTP]?i?B?)")
_ALLOC_RE = re.compile(
    r"^\s*(\d+)\.\s+Size:\s+(\S+)\s*\n(.*?)(?:^\s*=====|\Z)",
    re.M | re.S,
)
_ALLOC_FIELDS = {
    "operator": re.compile(r"Operator:\s*(.+)"),
    "shape": re.compile(r"Shape:\s*(\S+)"),
    "unpadded": re.compile(r"Unpadded size:\s*(\S+)"),
    "padding": re.compile(
        r"Extra memory due to padding:\s*(\S+)\s*\(([\d.]+)x expansion\)"
    ),
    "xla_label": re.compile(r"XLA label:\s*(.+)"),
    "allocation_type": re.compile(r"Allocation type:\s*(.+)"),
}
_ALLOCATOR_RE = re.compile(
    r"(?:Out of memory allocating|Failed to allocate(?: request for)?)\s+"
    r"([\d.]+(?:[KMGTP]i?B?)?)\s*(?:bytes)?"
)


def _parse_allocations(text: str) -> list:
    out = []
    for m in _ALLOC_RE.finditer(text):
        entry = {
            "rank": int(m.group(1)),
            "size_bytes": parse_size(m.group(2)),
        }
        block = m.group(3)
        f = _ALLOC_FIELDS
        mm = f["shape"].search(block)
        if mm:
            # Drop the layout/tiling suffix: f32[1,3072,3072,16]{2,1,3,0:...}
            entry["shape"] = mm.group(1).split("{")[0]
        mm = f["unpadded"].search(block)
        if mm:
            entry["unpadded_bytes"] = parse_size(mm.group(1))
        mm = f["padding"].search(block)
        if mm:
            entry["padding_bytes"] = parse_size(mm.group(1))
            entry["padding_expansion"] = float(mm.group(2))
        mm = f["operator"].search(block)
        if mm:
            entry["operator"] = mm.group(1).strip()[:200]
        mm = f["xla_label"].search(block)
        if mm:
            entry["xla_label"] = mm.group(1).strip()[:200]
        mm = f["allocation_type"].search(block)
        if mm:
            entry["allocation_type"] = mm.group(1).strip()
        out.append(entry)
    out.sort(key=lambda e: e["rank"])
    return out


def parse_resource_exhausted(msg: str) -> "dict | None":
    """Structured parse of an XLA RESOURCE_EXHAUSTED message.

    Returns None when the text carries no OOM signature at all; else a
    dict with ``kind`` one of:

    - ``"hbm_oom"`` — the full compile-time HBM table ("Ran out of
      memory in memory space hbm", docs/PERF.md round 4): used/limit/
      exceeded/program bytes plus ``largest_allocations`` (size, shape,
      unpadded size, padding expansion, XLA label).
    - ``"allocator_oom"`` — a runtime allocator failure ("Out of memory
      allocating N bytes") with ``requested_bytes``.
    - ``"unclassified"`` — the signature without a parseable breakdown
      (e.g. the bare "TPU backend error (ResourceExhausted)" string the
      bench walk used to record raw).
    """
    if not is_oom_error(msg):
        return None
    text = str(msg)
    out: dict = {"kind": "unclassified", "memory_space": None}
    m = _SPACE_RE.search(text)
    if m:
        out["memory_space"] = m.group(1)
    m = _USED_RE.search(text)
    if m:
        out["used_bytes"] = parse_size(m.group(1))
        out["limit_bytes"] = parse_size(m.group(2))
    m = _EXCEEDED_RE.search(text)
    if m:
        out["exceeded_bytes"] = parse_size(m.group(1))
    m = _PROGRAM_RE.search(text)
    if m:
        out["program_bytes"] = parse_size(m.group(1))
    m = _TOTAL_RE.search(text)
    if m:
        out["total_bytes"] = parse_size(m.group(1))
    allocs = _parse_allocations(text)
    if allocs:
        out["largest_allocations"] = allocs
    if out.get("memory_space") or (
        out.get("used_bytes") is not None and allocs
    ):
        out["kind"] = "hbm_oom"
    else:
        m = _ALLOCATOR_RE.search(text)
        if m:
            req = parse_size(m.group(1))
            if req is not None:
                out["kind"] = "allocator_oom"
                out["requested_bytes"] = req
    return out


def largest_buffer(parsed: "dict | None") -> "str | None":
    """One-line name of the biggest program allocation in a parsed OOM —
    what a postmortem reader wants first ("the 4.50G padded copy of
    f32[1,3072,3072,16]")."""
    if not parsed:
        return None
    allocs = parsed.get("largest_allocations")
    if not allocs:
        return None
    a = allocs[0]
    bits = []
    if a.get("size_bytes") is not None:
        bits.append(f"{a['size_bytes'] / 2**30:.2f}G")
    if a.get("shape"):
        bits.append(a["shape"])
    if a.get("padding_expansion"):
        bits.append(f"{a['padding_expansion']:g}x padding")
    if a.get("xla_label"):
        bits.append(a["xla_label"].split(" = ")[0])
    return " ".join(bits) or None


def oom_report(
    exc_or_msg, program: str, bucket: "int | None" = None,
    attrs: "dict | None" = None,
) -> dict:
    """Build one schema-valid ``oom.report`` JSONL event: the structured
    parse alongside the raw message (truncated), naming the program,
    bucket, and largest buffer."""
    raw = exception_chain_text(exc_or_msg)
    parsed = parse_resource_exhausted(raw)
    ev_attrs = {
        "program": program,
        "parsed": parsed,
        "largest_buffer": largest_buffer(parsed),
        "raw": raw[:4000],
    }
    if bucket is not None:
        ev_attrs["bucket"] = int(bucket)
    if attrs:
        ev_attrs.update(attrs)
    from mpi4dl_tpu.telemetry.jsonl import validate_event

    return validate_event({
        "ts": time.time(), "kind": "event", "name": "oom.report",
        "attrs": ev_attrs,
    })


def emit_oom_report(
    exc_or_msg,
    program: str,
    bucket: "int | None" = None,
    registry=None,
    events=None,
    flight=None,
    dump: bool = False,
    attrs: "dict | None" = None,
) -> dict:
    """Build and fan out one ``oom.report``: JSONL event log (when
    enabled), flight ring (+ a ``reason="oom"`` dump when asked),
    ``oom_reports_total{program=}``. Returns the event. Never raises —
    forensics must not mask the OOM it is reporting."""
    ev = oom_report(exc_or_msg, program, bucket=bucket, attrs=attrs)
    try:
        if registry is not None:
            from mpi4dl_tpu import telemetry

            telemetry.declare(registry, "oom_reports_total").inc(
                program=program
            )
        if flight is not None and getattr(flight, "enabled", False):
            flight.record(ev)
            if dump:
                flight.dump(reason="oom")
        if events is not None and getattr(events, "enabled", False):
            events.write(ev)
    except Exception:  # noqa: BLE001 — postmortem is best-effort
        pass
    return ev


# -- live device memory -------------------------------------------------------


def device_memory_stats(device) -> "dict | None":
    """Normalized ``{"used_bytes", "limit_bytes", "peak_bytes"}`` from
    ``jax.Device.memory_stats()``; None when the backend reports nothing
    (the CPU simulation returns None — absence, not zeros)."""
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — backend-dependent, absence is fine
        return None
    if not stats:
        return None
    used = stats.get("bytes_in_use")
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    if used is None and limit is None:
        return None
    out: dict = {}
    if used is not None:
        out["used_bytes"] = int(used)
    if limit is not None:
        out["limit_bytes"] = int(limit)
    peak = stats.get("peak_bytes_in_use")
    if peak is not None:
        out["peak_bytes"] = int(peak)
    return out


def device_memory_limit(device=None) -> "int | None":
    """The device's HBM capacity in bytes, or None when the backend
    cannot report it (CPU) — the feasibility planner's default limit."""
    if device is None:
        import jax

        device = jax.devices()[0]
    stats = device_memory_stats(device)
    return None if stats is None else stats.get("limit_bytes")


class MemoryMonitor:
    """Samples per-device HBM occupancy into cataloged gauges.

    registry: gauges are DECLARED at construction (the catalog pin sees
        the names on every backend) but only SET when a device actually
        reports stats — absent-not-wrong on the CPU simulation.
    devices: explicit device list (tests pass stubs); None resolves
        ``jax.devices()`` lazily at the first sample.
    interval_s: sampling cadence of the daemon thread — the engine wires
        the SLO evaluator's cadence here so the headroom gauges move in
        step with the alert evaluation reading them.
    """

    def __init__(
        self, registry, devices=None, interval_s: float = 1.0,
    ):
        from mpi4dl_tpu import telemetry

        self._m_used = telemetry.declare(registry, "device_hbm_used_bytes")
        self._m_limit = telemetry.declare(registry, "device_hbm_limit_bytes")
        self._m_headroom = telemetry.declare(
            registry, "device_hbm_headroom_ratio"
        )
        self._devices = list(devices) if devices is not None else None
        self.interval_s = float(interval_s)
        self.supported: "bool | None" = None  # unknown until first sample
        self.last: "dict | None" = None
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None

    def sample_once(self) -> "dict | None":
        """One sample over every device; returns the per-device stats
        dict, or None when no device reports (then no gauge is set and
        nothing downstream can trip on a fabricated zero)."""
        if self._devices is None:
            import jax

            self._devices = list(jax.devices())
        out = {}
        for d in self._devices:
            stats = device_memory_stats(d)
            if stats is None:
                continue
            label = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
            used, limit = stats.get("used_bytes"), stats.get("limit_bytes")
            if used is not None:
                self._m_used.set(used, device=label)
            if limit:
                self._m_limit.set(limit, device=label)
                if used is not None:
                    stats["headroom_ratio"] = (limit - used) / limit
                    self._m_headroom.set(
                        stats["headroom_ratio"], device=label
                    )
            out[label] = stats
        self.supported = bool(out)
        self.last = out or None
        return out or None

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="mpi4dl-memory-monitor", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                if self.sample_once() is None:
                    # Backend reports nothing (CPU): retire the thread —
                    # absence costs zero steady-state work, and a process
                    # never grows HBM support mid-life.
                    return
            except Exception:  # noqa: BLE001 — sampling must never kill
                return  # the host process's sidecar thread
            if self._stop_evt.wait(self.interval_s):
                return

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def state(self) -> dict:
        """The ``/debugz`` payload."""
        return {"supported": self.supported, "devices": self.last}


# -- footprint ledger ---------------------------------------------------------


class FootprintLedger:
    """Per-program predicted-peak ledger over compiled executables.

    Every entry is :func:`mpi4dl_tpu.analysis.memory.memory_summary` of
    one ``jax.stages.Compiled`` — the buffer-assignment totals the
    allocator will actually request, available on every backend (CPU
    included), recorded at compile time so the answer to "what will this
    program hold" exists *before* the first execution. Bucket entries
    publish ``serve_bucket_peak_hbm_bytes{bucket=}``; everything else
    publishes ``program_peak_hbm_bytes{program=}``.

    Cold-start additions (:mod:`mpi4dl_tpu.telemetry.coldstart`): every
    entry carries the executable's content ``fingerprint`` (the artifact-
    store key — computed here, at the only place every AOT compile in
    the codebase already passes through), and entries recorded with
    ``trace_s`` / ``compile_s`` / ``warm_s`` phase durations accumulate
    into the cataloged ``compile_seconds{program, phase}`` gauge.
    ``dump()`` is the input of ``python -m mpi4dl_tpu.analyze coldstart``.
    """

    def __init__(self, registry=None):
        self._entries: "dict[str, dict]" = {}
        self._lock = threading.Lock()
        self._m_bucket = self._m_program = self._m_compile = None
        if registry is not None:
            from mpi4dl_tpu import telemetry

            # Declared up front so the catalog pin sees the names even
            # before the first record lands.
            self._m_bucket = telemetry.declare(
                registry, "serve_bucket_peak_hbm_bytes"
            )
            self._m_program = telemetry.declare(
                registry, "program_peak_hbm_bytes"
            )
            self._m_compile = telemetry.declare(registry, "compile_seconds")

    def record_compiled(
        self, program: str, compiled, bucket: "int | None" = None, **extra
    ) -> dict:
        """Record one compiled executable's footprint; returns the entry
        (``peak_bytes`` None when the backend cannot report it — the
        entry still exists, the gauges stay absent)."""
        from mpi4dl_tpu.analysis.memory import memory_summary

        entry: dict = {"program": program, "ts": time.time(), **extra}
        if bucket is not None:
            entry["bucket"] = int(bucket)
        summary = memory_summary(compiled)
        if summary:
            entry.update(summary)
        else:
            entry["peak_bytes"] = None
        if entry.get("fingerprint") is None:
            # Callers that timed the lowering pass the (preferable)
            # pre-optimization fingerprint in extra; fall back to the
            # optimized text so every entry still has an identity.
            from mpi4dl_tpu.telemetry.coldstart import fingerprint_of

            entry["fingerprint"] = fingerprint_of(
                compiled, mesh_shape=extra.get("mesh_shape")
            )
        key = program if bucket is None else f"{program}[{int(bucket)}]"
        with self._lock:
            self._entries[key] = entry
        peak = entry.get("peak_bytes")
        if peak is not None:
            if bucket is not None and self._m_bucket is not None:
                self._m_bucket.set(peak, bucket=int(bucket))
            elif bucket is None and self._m_program is not None:
                self._m_program.set(peak, program=program)
        self._publish_phases(program, entry)
        return entry

    def record_lowered(
        self, program: str, fn, *args, bucket: "int | None" = None, **extra
    ) -> dict:
        """Lower + compile a jitted callable on the given (abstract or
        concrete) arguments WITHOUT executing it, then record — a
        warm-cache no-op for programs the process already compiled
        (XLA memoizes by program identity). The trace/compile split is
        timed here and the fingerprint taken from the LOWERED text (the
        key a respawning worker could compute before paying the
        compile)."""
        from mpi4dl_tpu.telemetry.coldstart import fingerprint_of

        t0 = time.perf_counter()
        lowered = fn.lower(*args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        extra.setdefault("trace_s", round(t1 - t0, 6))
        extra.setdefault("compile_s", round(t2 - t1, 6))
        extra.setdefault(
            "fingerprint",
            fingerprint_of(lowered, mesh_shape=extra.get("mesh_shape")),
        )
        return self.record_compiled(program, compiled, bucket=bucket, **extra)

    def annotate(
        self, program: str, bucket: "int | None" = None, **extra
    ) -> "dict | None":
        """Merge late-arriving facts (the first-execute ``warm_s``, which
        only exists after the engine's zeros run) into an existing entry;
        phase durations publish into ``compile_seconds`` like recorded
        ones. No-op on an unknown key."""
        key = program if bucket is None else f"{program}[{int(bucket)}]"
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            entry.update(extra)
            entry = dict(entry)
        self._publish_phases(program, extra)
        return entry

    def _publish_phases(self, program: str, fields: dict) -> None:
        """Accumulate any ``{trace,compile,warm}_s`` durations present in
        ``fields`` into ``compile_seconds{program, phase}`` — cumulative
        per program across buckets, the shape ``analyze coldstart`` and a
        compile-cache A/B read. Entries marked ``rollup`` (the tiled
        engine's per-image-bucket aggregate of its serve_tiled_* entries)
        are skipped — their seconds are already published once by the
        fine-grained entries they sum."""
        if self._m_compile is None or fields.get("rollup"):
            return
        for phase in ("trace", "compile", "warm"):
            v = fields.get(f"{phase}_s")
            if isinstance(v, (int, float)):
                self._m_compile.inc(float(v), program=program, phase=phase)

    def entries(self) -> "list[dict]":
        with self._lock:
            return [dict(v) for _, v in sorted(self._entries.items())]

    def get(self, program: str, bucket: "int | None" = None) -> "dict | None":
        key = program if bucket is None else f"{program}[{int(bucket)}]"
        with self._lock:
            e = self._entries.get(key)
        return dict(e) if e else None

    def summary(self) -> dict:
        """JSON-serializable view (``engine.stats()['memory']['programs']``,
        ``/debugz``, and the planner's ``--ledger`` artifact input)."""
        return {"entries": self.entries()}

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.summary(), f, indent=2)
            f.write("\n")
        return path
