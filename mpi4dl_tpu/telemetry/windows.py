"""Windowed rates over a bounded ring of registry snapshots.

The registry (:mod:`mpi4dl_tpu.telemetry.registry`) holds *cumulative*
state — counters since process start, histograms with cumulative buckets.
Every alerting question is about a *window*: "what fraction of requests
failed in the last minute", "how fast is the queue-full counter moving".
An external Prometheus answers that with ``rate()``/``increase()`` over
its scrape history; the single-process serving story has no Prometheus,
so this module keeps the history in-process: a ``deque(maxlen=capacity)``
of timestamped, slimmed registry snapshots (the flight recorder's ≤1/s
snapshot cadence, owned here by the :class:`~mpi4dl_tpu.telemetry.alerts.
SLOEvaluator` tick) and Prometheus-shaped queries over it.

Window semantics (documented because alerting math depends on them):

- A query uses the NEWEST snapshot and the latest snapshot at or before
  ``newest.ts - window_s`` — i.e. the window covers *at least* the
  requested span once enough history exists, and shrinks to whatever is
  available during cold start (so alerts are live from the second
  snapshot onward rather than silent for a full window).
- ``increase`` is the raw delta between the two snapshots (no
  Prometheus-style extrapolation); ``rate`` divides by the actual elapsed
  time between them, so cold-start shortening never inflates a rate.
- A series absent from the older snapshot but present in the newest is
  treated as starting from 0 (a counter that began moving mid-window —
  e.g. the first ``rejected_queue_full`` — must count, not vanish).
- A negative delta means the underlying counter restarted; the query
  returns None (no data) rather than a fabricated value.
- Federation fallback: when an exact label match fails and the series
  carries the aggregator-injected ``replica`` label
  (:mod:`mpi4dl_tpu.telemetry.federation`), the query falls back to the
  ``replica="sum"`` rollup — so an unlabeled ``serve_queue_depth`` lookup
  against a FEDERATED snapshot answers with the fleet total, and the SLO
  evaluator / autoscaler run fleet-wide unchanged.
"""

from __future__ import annotations

import collections
import threading
import time


def _slim(snapshot: dict) -> dict:
    """Strip what windowed queries never read (help text, reservoir
    percentiles) so a few hundred ring entries stay cheap to hold."""
    out = {}
    for name, m in snapshot.items():
        if m["type"] == "histogram":
            series = [
                {"labels": s["labels"], "count": s["count"],
                 "sum": s["sum"], "buckets": s["buckets"]}
                for s in m["series"]
            ]
        else:
            series = [
                {"labels": s["labels"], "value": s["value"]}
                for s in m["series"]
            ]
        out[name] = {"type": m["type"], "series": series}
    return out


def _find_series(snap: dict, name: str, labels: dict) -> "dict | None":
    m = snap.get(name)
    if m is None:
        return None
    want = {k: str(v) for k, v in labels.items()}
    for s in m["series"]:
        if s["labels"] == want:
            return s
    if "replica" not in want:
        # Federated gauge: fall back to the fleet-wide rollup series.
        want_sum = dict(want, replica="sum")
        for s in m["series"]:
            if s["labels"] == want_sum:
                return s
    return None


class SnapshotWindow:
    """Bounded ring of timestamped registry snapshots + windowed queries.

    registry: the :class:`MetricsRegistry` to snapshot.
    capacity: ring size in snapshots; at the evaluator's default 1/s
        cadence the default holds ~6 minutes — enough for the scaled-down
        burn-rate windows in :mod:`mpi4dl_tpu.telemetry.slo`.
    clock: injectable monotonic clock (tests drive windows without
        real waits).
    """

    def __init__(self, registry, capacity: int = 360, clock=time.monotonic):
        self._registry = registry
        self._ring: collections.deque = collections.deque(
            maxlen=max(2, int(capacity))
        )
        self._clock = clock
        self._lock = threading.Lock()

    def record(self, now: "float | None" = None) -> None:
        """Append one timestamped snapshot (the evaluator tick)."""
        snap = _slim(self._registry.snapshot())
        ts = self._clock() if now is None else float(now)
        with self._lock:
            self._ring.append((ts, snap))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def span_s(self) -> float:
        """Seconds of history currently held."""
        with self._lock:
            if len(self._ring) < 2:
                return 0.0
            return self._ring[-1][0] - self._ring[0][0]

    def _bounds(self, window_s: float):
        """(old, new) snapshot pair for a window ending at the newest
        snapshot; None with fewer than two snapshots."""
        with self._lock:
            ring = list(self._ring)
        if len(ring) < 2:
            return None
        new_ts, new = ring[-1]
        cutoff = new_ts - float(window_s)
        old_ts, old = ring[0]
        for ts, snap in ring[:-1]:
            if ts <= cutoff:
                old_ts, old = ts, snap
            else:
                break
        if old_ts >= new_ts:
            return None
        return (old_ts, old), (new_ts, new)

    # -- point queries --------------------------------------------------------

    def value(self, name: str, **labels) -> "float | None":
        """Latest counter/gauge value for one series."""
        with self._lock:
            if not self._ring:
                return None
            _, snap = self._ring[-1]
        s = _find_series(snap, name, labels)
        return None if s is None else s["value"]

    def label_values(self, name: str, label: str) -> "list[str]":
        """Distinct values of one label across the newest snapshot's
        series of a metric (e.g. the phases ``serve_span_seconds`` has
        actually seen) — sorted, empty without data."""
        with self._lock:
            if not self._ring:
                return []
            _, snap = self._ring[-1]
        m = snap.get(name)
        if m is None:
            return []
        return sorted({
            s["labels"][label] for s in m["series"] if label in s["labels"]
        })

    def hist_total(self, name: str, **labels) -> "dict | None":
        """Cumulative ``{"count", "sum"}`` of a histogram series in the
        newest snapshot (the process-lifetime baseline windowed deltas
        are compared against)."""
        with self._lock:
            if not self._ring:
                return None
            _, snap = self._ring[-1]
        s = _find_series(snap, name, labels)
        if s is None or "buckets" not in s:
            return None
        return {"count": s["count"], "sum": s["sum"]}

    # -- windowed queries -----------------------------------------------------

    def increase(self, name: str, window_s: float, **labels) -> "float | None":
        """Counter increase over the window (raw delta, see module doc)."""
        b = self._bounds(window_s)
        if b is None:
            return None
        (_, old), (_, new) = b
        s_new = _find_series(new, name, labels)
        if s_new is None:
            return None
        s_old = _find_series(old, name, labels)
        delta = s_new["value"] - (0.0 if s_old is None else s_old["value"])
        return None if delta < 0 else delta

    def rate(self, name: str, window_s: float, **labels) -> "float | None":
        """Per-second rate of a counter over the window."""
        b = self._bounds(window_s)
        if b is None:
            return None
        (old_ts, _), (new_ts, _) = b
        inc = self.increase(name, window_s, **labels)
        if inc is None or new_ts <= old_ts:
            return None
        return inc / (new_ts - old_ts)

    def increases(self, name: str, window_s: float):
        """Per-series increases of a labeled counter over the window:
        ``[(labels_dict, delta), ...]`` over every series present in the
        newest snapshot (absent-in-old baselines at 0); None with
        insufficient history, negative deltas dropped as restarts."""
        b = self._bounds(window_s)
        if b is None:
            return None
        (_, old), (_, new) = b
        m = new.get(name)
        if m is None:
            return None
        out = []
        for s in m["series"]:
            s_old = _find_series(old, name, s["labels"])
            delta = s["value"] - (0.0 if s_old is None else s_old["value"])
            if delta >= 0:
                out.append((dict(s["labels"]), delta))
        return out

    def hist_increase(self, name: str, window_s: float, **labels):
        """Histogram increase over the window: ``{"count": d, "sum": d,
        "buckets": {le: d}}`` (cumulative le buckets, deltas)."""
        b = self._bounds(window_s)
        if b is None:
            return None
        (_, old), (_, new) = b
        s_new = _find_series(new, name, labels)
        if s_new is None or "buckets" not in s_new:
            return None
        s_old = _find_series(old, name, labels)
        if s_old is None:
            s_old = {"count": 0, "sum": 0.0, "buckets": {}}
        d_count = s_new["count"] - s_old["count"]
        if d_count < 0:
            return None
        buckets = {
            le: cum - s_old["buckets"].get(le, 0)
            for le, cum in s_new["buckets"].items()
        }
        return {
            "count": d_count,
            "sum": s_new["sum"] - s_old["sum"],
            "buckets": buckets,
        }

    def availability(
        self, name: str, window_s: float, good: "tuple | list",
        label: str = "outcome", ignore: "tuple | list" = (),
    ) -> "float | None":
        """Good-event ratio of a labeled counter over the window: sum of
        the ``good`` label values' increases / sum of ALL series'
        increases, except ``ignore`` label values, which leave the
        denominator too (drained requests are neither success nor
        failure). None when the window saw no events (no data is
        neither 100% nor 0%)."""
        incs = self.increases(name, window_s)
        if not incs:
            return None
        ignore_set = set(ignore)
        incs = [
            (labels_, d) for labels_, d in incs
            if labels_.get(label) not in ignore_set
        ]
        total = sum(d for _, d in incs)
        if total <= 0:
            return None
        good_set = set(good)
        return sum(
            d for labels_, d in incs if labels_.get(label) in good_set
        ) / total

    def bucket_ratio(
        self, name: str, window_s: float, le: float, **labels
    ) -> "float | None":
        """Fraction of a histogram's window observations at or under the
        cumulative bucket bound ``le`` (must be an exact bucket bound —
        callers resolve thresholds with
        :func:`mpi4dl_tpu.telemetry.slo.resolve_bucket_bound`). None
        when the window saw no observations."""
        h = self.hist_increase(name, window_s, **labels)
        if not h or h["count"] <= 0:
            return None
        return h["buckets"].get(f"{float(le):g}", 0) / h["count"]

    def mean_gauge(self, name: str, window_s: float, **labels) -> "float | None":
        """Mean of a gauge's sampled values over snapshots in the window
        (the autoscaler's smoothed queue depth — one hot scrape must not
        trigger a scale-up)."""
        with self._lock:
            ring = list(self._ring)
        if not ring:
            return None
        cutoff = ring[-1][0] - float(window_s)
        vals = []
        for ts, snap in ring:
            if ts < cutoff:
                continue
            s = _find_series(snap, name, labels)
            if s is not None:
                vals.append(s["value"])
        if not vals:
            return None
        return sum(vals) / len(vals)
