"""Advisory autoscale signals: the ROADMAP's fleet-controller consumer.

The telemetry PR published the raw scale-up signals (`serve_queue_depth`,
`serve_requests_total{outcome=~"rejected.*"}`); nothing consumed them.
This module closes that item with an *advisory* policy: a single-process
engine cannot add replicas of itself, but it can compute — continuously,
against the live window — what a fleet controller SHOULD run, and publish
it as the cataloged ``autoscale_desired_replicas`` gauge. A controller
(HPA-style reconciler, cron job, human with a dashboard) scrapes one
number instead of re-deriving policy from raw counters.

Policy (deliberately boring — hysteresis and cooldown do the real work):

- **scale up** (+1, capped at ``max_replicas``) when any pressure signal
  is high: the LATEST queue depth ≥ ``queue_high`` × queue capacity
  (scale-up must react to the spike, not wait for a mean to catch up),
  any queue-full rejections in the window, or the page-severity burn
  rate above ``burn_high``. At most one step per ``up_cooldown_s``.
- **scale down** (−1, floored at ``min_replicas``) only when EVERY
  signal has been quiet — the windowed MEAN depth ≤ ``queue_low`` ×
  capacity (sustained calm, not one empty scrape), zero rejections,
  burn below ``burn_low`` — for ``down_cooldown_s`` since the last
  change AND the last pressure sighting (flapping traffic must not saw
  the fleet).

The up/down thresholds are deliberately far apart (hysteresis): a depth
hovering between ``queue_low`` and ``queue_high`` changes nothing.
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class AutoscaleConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    queue_high: float = 0.5     # fraction of queue capacity → scale up
    queue_low: float = 0.1      # fraction of queue capacity → may scale down
    burn_high: float = 1.0      # page-window burn above this is pressure
    burn_low: float = 1.0       # must be below this to scale down
    signal_window_s: float = 30.0
    up_cooldown_s: float = 5.0
    down_cooldown_s: float = 60.0

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}..{self.max_replicas}"
            )
        if not 0.0 <= self.queue_low <= self.queue_high:
            raise ValueError(
                f"need queue_low <= queue_high, got "
                f"{self.queue_low} > {self.queue_high}"
            )


class Autoscaler:
    """Maps windowed pressure signals to a desired-replica count.

    registry: publishes ``autoscale_desired_replicas`` (declared at
        construction so the catalog pin sees it before the first tick).
    queue_capacity: the engine's bounded-queue size — thresholds are
        fractions of it.
    clock: injectable for deterministic tests.
    """

    def __init__(
        self,
        registry,
        config: "AutoscaleConfig | None" = None,
        queue_capacity: int = 64,
        clock=time.monotonic,
    ):
        from mpi4dl_tpu import telemetry

        self.config = config if config is not None else AutoscaleConfig()
        self.queue_capacity = max(1, int(queue_capacity))
        self._clock = clock
        self.desired = self.config.min_replicas
        self._last_change = clock()
        self._last_pressure = clock()
        self._last_signals: dict = {}
        self._m_desired = telemetry.declare(
            registry, "autoscale_desired_replicas"
        )
        self._m_desired.set(self.desired)

    def update(self, now, window, page_burn: "float | None") -> int:
        """One policy tick (driven by the SLO evaluator). ``window`` is
        the shared :class:`SnapshotWindow`; ``page_burn`` the worst
        page-severity long-window burn this tick (None = no data)."""
        cfg = self.config
        w = cfg.signal_window_s
        depth_now = window.value("serve_queue_depth")
        depth_mean = window.mean_gauge("serve_queue_depth", w)
        rej = window.increase(
            "serve_requests_total", w, outcome="rejected_queue_full"
        )
        depth_now = 0.0 if depth_now is None else depth_now
        depth_mean = 0.0 if depth_mean is None else depth_mean
        rej = 0.0 if rej is None else rej
        burn = 0.0 if page_burn is None else page_burn
        pressure = (
            depth_now >= cfg.queue_high * self.queue_capacity
            or rej > 0
            or burn > cfg.burn_high
        )
        calm = (
            depth_mean <= cfg.queue_low * self.queue_capacity
            and rej == 0
            and burn < cfg.burn_low
        )
        if pressure:
            self._last_pressure = now
            if (
                self.desired < cfg.max_replicas
                and now - self._last_change >= cfg.up_cooldown_s
            ):
                self.desired += 1
                self._last_change = now
        elif calm:
            quiet_since = max(self._last_change, self._last_pressure)
            if (
                self.desired > cfg.min_replicas
                and now - quiet_since >= cfg.down_cooldown_s
            ):
                self.desired -= 1
                self._last_change = now
        self._last_signals = {
            "queue_depth": depth_now,
            "queue_depth_mean": depth_mean,
            "rejections_in_window": rej,
            "page_burn": burn,
            "pressure": pressure,
            "calm": calm,
        }
        self._m_desired.set(self.desired)
        return self.desired

    def state(self) -> dict:
        return {
            "desired_replicas": self.desired,
            "min_replicas": self.config.min_replicas,
            "max_replicas": self.config.max_replicas,
            "queue_capacity": self.queue_capacity,
            "last_change_age_s": self._clock() - self._last_change,
            "signals": dict(self._last_signals),
        }
