"""Unified telemetry: metrics registry, request-span tracing, exporters.

The cross-cutting observability layer the ROADMAP's serving follow-ons
(autoscaling signals, continuous batching, multi-chip serving) read their
signals from. Four pieces:

- :mod:`.registry` — threadsafe counters/gauges/histograms with streaming
  reservoirs (percentiles via the shared
  :func:`mpi4dl_tpu.profiling.percentiles`);
- :mod:`.spans` — per-request lifecycle spans whose phase durations sum
  exactly to end-to-end latency;
- :mod:`.export` — Prometheus text format + stdlib ``http.server`` scrape
  endpoint (``ServingEngine(metrics_port=...)`` /
  ``python -m mpi4dl_tpu.serve --metrics-port``);
- :mod:`.jsonl` — schema-validated JSONL event log
  (``MPI4DL_TPU_TELEMETRY_DIR``), the same snapshot schema bench.py
  embeds in its result lines;
- :mod:`.catalog` — the single source of truth for metric names/types/
  labels; publishers go through :func:`declare`, and tier-1 tests pin the
  catalog against both ``docs/OBSERVABILITY.md`` and what a full-stack
  run actually exposes;
- :mod:`.flight` — always-on bounded ring of recent spans + metric
  snapshots, dumped as schema-valid JSONL on watchdog trip, crash, or
  SIGTERM (the postmortem story);
- :mod:`.health` — :class:`HealthState` behind the ``/healthz`` endpoint
  and the :class:`Watchdog` that flips it on hung-step / stalled-loop
  detection;
- :mod:`.windows` — bounded ring of registry snapshots answering
  Prometheus-shaped ``rate``/``increase``/availability queries in-process;
- :mod:`.slo` — declarative SLO objectives, error budgets, and
  Google-SRE multi-window multi-burn-rate math;
- :mod:`.alerts` — the ``pending → firing → resolved`` alert state
  machine and the daemon :class:`SLOEvaluator` behind ``/alertz``;
- :mod:`.autoscale` — advisory fleet signals: windowed pressure →
  the ``autoscale_desired_replicas`` gauge;
- :mod:`.tail` — slow-request capture: requests past
  max(SLO threshold, K × rolling p99) become rate-limited
  ``tail.sample`` events joining histogram exemplars to full span
  forensics (``python -m mpi4dl_tpu.analyze tail``);
- :mod:`.canary` — the numerics sentinel: deterministic golden probes
  re-verified through the real dispatch path on a daemon cadence,
  param-tree + BN-stats integrity checksums, and the corruption
  forensics (``canary.failure`` events) behind the fleet's
  ``numerics_divergence`` page and corrupt-drill quarantine.

Who publishes what: ``serve.ServingEngine`` (request outcomes, queue
depth, bucket occupancy, pad waste, latency + lifecycle spans),
``serve.loadgen`` (client-observed outcomes/latency),
``profiling.StepTimer`` (step-time histogram/throughput),
``train.Trainer.publish_telemetry`` (remat/halo facts),
``analysis.publish_report`` (hlolint verdicts). See
``docs/OBSERVABILITY.md`` for the full metric catalog and examples.
"""

import threading

from mpi4dl_tpu.telemetry.alerts import (  # noqa: F401
    AlertState,
    SLOEvaluator,
    phase_attribution,
)
from mpi4dl_tpu.telemetry.autoscale import (  # noqa: F401
    AutoscaleConfig,
    Autoscaler,
)
from mpi4dl_tpu.telemetry.canary import (  # noqa: F401
    CANARY_ATOL,
    CanarySentinel,
    CanaryState,
    canary_example,
    corrupt_params,
    exact_digest,
    params_checksum,
    quantized_digest,
    ulp_diff,
)
from mpi4dl_tpu.telemetry.catalog import (  # noqa: F401
    CATALOG,
    MetricSpec,
    declare,
)
from mpi4dl_tpu.telemetry.export import (  # noqa: F401
    MetricsServer,
    render_prometheus,
    unescape_help,
    unescape_label_value,
)
from mpi4dl_tpu.telemetry.federation import (  # noqa: F401
    FederatedAggregator,
    FederatedRegistry,
    ReplicaTarget,
    merge_snapshots,
)
from mpi4dl_tpu.telemetry.flight import FlightRecorder  # noqa: F401
from mpi4dl_tpu.telemetry.incident import (  # noqa: F401
    IncidentManager,
    build_postmortem,
    build_timeline,
    reconstruct_incidents,
)
from mpi4dl_tpu.telemetry.health import (  # noqa: F401
    HealthState,
    Watchdog,
)
from mpi4dl_tpu.telemetry.memory import (  # noqa: F401
    FootprintLedger,
    MemoryMonitor,
    device_memory_limit,
    device_memory_stats,
    emit_oom_report,
    is_oom_error,
    parse_resource_exhausted,
)
from mpi4dl_tpu.telemetry.jsonl import (  # noqa: F401
    ENV_DIR,
    JsonlWriter,
    metrics_event,
    read_events,
    validate_event,
)
from mpi4dl_tpu.telemetry.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Reservoir,
)
from mpi4dl_tpu.telemetry.slo import (  # noqa: F401
    BurnWindow,
    Objective,
    SLOConfig,
    availability_objective,
    latency_objective,
)
from mpi4dl_tpu.telemetry.tail import TailWatcher  # noqa: F401
from mpi4dl_tpu.telemetry.windows import SnapshotWindow  # noqa: F401
from mpi4dl_tpu.telemetry.spans import (  # noqa: F401
    chrome_trace,
    group_spans_by_trace,
    new_trace_id,
    record_spans,
    span_event,
    spans_from_marks,
)

_default_registry: "MetricsRegistry | None" = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The lazily-created process-wide registry, for publishers not handed
    an explicit one (``Trainer.publish_telemetry()`` with no argument)."""
    global _default_registry
    with _default_lock:
        if _default_registry is None:
            _default_registry = MetricsRegistry()
        return _default_registry
