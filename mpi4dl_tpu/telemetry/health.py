"""Liveness: health state + a progress watchdog for serving/training loops.

A serving process that hangs is worse than one that crashes — the crash
restarts, the hang serves 503s-by-silence until a human notices. Two
pieces close that gap:

- :class:`HealthState` — a threadsafe healthy/unhealthy flag with a
  reason, mirrored into the cataloged ``serve_healthy`` gauge and served
  by the ``/healthz`` endpoint (:class:`mpi4dl_tpu.telemetry.MetricsServer`):
  200 while healthy, 503 after a watchdog trip or loop crash.
- :class:`Watchdog` — hung-step / stalled-loop detection. Publishers call
  :meth:`Watchdog.begin` when work is admitted (a request enqueued, a
  train step started) and :meth:`Watchdog.done` when it completes; a
  monitor thread trips when work is outstanding but nothing has completed
  within ``max(min_timeout_s, factor × rolling-p99(completion
  durations))``. The threshold adapts to the workload (a 2048px step and
  a 32px serve batch need very different patience) instead of a hard pin.
  A trip flips the health state, bumps ``watchdog_trips_total``, and runs
  the registered callbacks (the serving engine dumps its flight recorder
  there); the next completed work item auto-recovers the health state —
  the process may have merely been starved, and flapping back to healthy
  on real progress is the correct load-balancer signal.

The clock is injectable so trip logic is unit-testable without real
waits; the monitor thread is optional (``start=False``) for the same
reason.
"""

from __future__ import annotations

import collections
import threading
import time

from mpi4dl_tpu.profiling import percentiles


class HealthState:
    """Threadsafe healthy/unhealthy + reason; the ``/healthz`` source of
    truth. With a ``registry``, mirrors into the ``serve_healthy`` gauge
    so fleet controllers can scrape what the probe endpoint serves."""

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._healthy = True
        self._reason = "ok"
        self._since = time.time()
        self._gauge = None
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._gauge = telemetry.declare(registry, "serve_healthy")
            self._gauge.set(1.0)

    def _set(self, healthy: bool, reason: str) -> None:
        with self._lock:
            changed = healthy != self._healthy
            self._healthy = healthy
            self._reason = reason
            if changed:
                self._since = time.time()
        if self._gauge is not None:
            self._gauge.set(1.0 if healthy else 0.0)

    def set_healthy(self, reason: str = "ok") -> None:
        self._set(True, reason)

    def set_unhealthy(self, reason: str) -> None:
        self._set(False, reason)

    @property
    def healthy(self) -> bool:
        with self._lock:
            return self._healthy

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "healthy": self._healthy,
                "reason": self._reason,
                "since": self._since,
            }


class Watchdog:
    """No-progress detector over a begin/done work stream.

    factor, min_timeout_s: trip when outstanding work has seen no
        completion for ``max(min_timeout_s, factor * p99)`` seconds,
        where p99 is over the last ``history`` completion durations
        (seed one with :meth:`seed` — e.g. the AOT warm latency — so the
        very first real work item is already covered).
    health: a :class:`HealthState` flipped unhealthy on trip and back on
        the next completion.
    on_trip: callbacks ``cb(reason: str)`` run (outside the lock) once
        per trip — the flight-recorder dump hook.
    registry: counts trips in the cataloged ``watchdog_trips_total``.
    start: start the daemon monitor thread (poll every ``poll_s``,
        default ``min(0.25, min_timeout_s / 4)``); ``start=False`` for
        deterministic tests driving :meth:`check` with a fake ``clock``.
    """

    def __init__(
        self,
        factor: float = 20.0,
        min_timeout_s: float = 2.0,
        poll_s: "float | None" = None,
        history: int = 256,
        registry=None,
        health: "HealthState | None" = None,
        on_trip=(),
        clock=time.monotonic,
        start: bool = True,
    ):
        self.factor = float(factor)
        self.min_timeout_s = float(min_timeout_s)
        self.poll_s = (
            float(poll_s) if poll_s is not None
            else min(0.25, self.min_timeout_s / 4)
        )
        self._clock = clock
        self._health = health
        self._on_trip = (
            (on_trip,) if callable(on_trip) else tuple(on_trip)
        )
        self._lock = threading.Lock()
        self._durations: collections.deque = collections.deque(maxlen=history)
        self._outstanding = 0
        self._last_progress = self._clock()
        self._tripped = False
        self.trips = 0
        self._m_trips = None
        if registry is not None:
            from mpi4dl_tpu import telemetry

            self._m_trips = telemetry.declare(registry, "watchdog_trips_total")
            # Materialize the zero series: rate()/increase() alerts need
            # an explicit 0 before the first trip, not an absent metric.
            self._m_trips.inc(0)
        self._stop_evt = threading.Event()
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._monitor, name="mpi4dl-watchdog", daemon=True
            )
            self._thread.start()

    # -- publisher surface ----------------------------------------------------

    def seed(self, duration_s: float) -> None:
        """Prime the rolling completion history (e.g. with the AOT warm
        latency) so the adaptive timeout is meaningful before the first
        real completion."""
        with self._lock:
            self._durations.append(float(duration_s))

    def begin(self) -> None:
        """Work admitted. Starts the no-progress clock when the system
        transitions idle -> busy."""
        with self._lock:
            if self._outstanding == 0:
                self._last_progress = self._clock()
            self._outstanding += 1

    def done(self, duration_s: "float | None" = None) -> None:
        """One work item finished (served, rejected, or failed — any
        terminal outcome is progress: the loop is alive)."""
        recovered = False
        with self._lock:
            self._outstanding = max(0, self._outstanding - 1)
            self._last_progress = self._clock()
            if duration_s is not None:
                self._durations.append(float(duration_s))
            if self._tripped:
                self._tripped = False
                recovered = True
        if recovered and self._health is not None:
            self._health.set_healthy("recovered: work completing again")

    def cancel(self) -> None:
        """Un-admit one work item WITHOUT counting it as progress — for
        work that never reached the loop (flushed at shutdown). Unlike
        :meth:`done` this does not reset the stall clock, so a stalled
        loop behind a churning admission path still trips."""
        with self._lock:
            self._outstanding = max(0, self._outstanding - 1)

    # -- monitor --------------------------------------------------------------

    def timeout_s(self) -> float:
        with self._lock:
            vals = list(self._durations)
        p = percentiles(vals, (99,)).get("p99", 0.0)
        return max(self.min_timeout_s, self.factor * p)

    def check(self, now: "float | None" = None) -> "str | None":
        """One watchdog evaluation; trips (and returns the reason) when
        outstanding work has stalled past the adaptive timeout."""
        now = self._clock() if now is None else now
        timeout = self.timeout_s()
        with self._lock:
            if self._tripped or self._outstanding == 0:
                return None
            gap = now - self._last_progress
            if gap <= timeout:
                return None
            self._tripped = True
            self.trips += 1
            outstanding = self._outstanding
        reason = (
            f"watchdog: no completion in {gap:.3f}s "
            f"(> {timeout:.3f}s = max({self.min_timeout_s:g}s, "
            f"{self.factor:g} x rolling p99)) with {outstanding} "
            "work item(s) outstanding"
        )
        if self._m_trips is not None:
            self._m_trips.inc()
        if self._health is not None:
            self._health.set_unhealthy(reason)
        for cb in self._on_trip:
            try:
                cb(reason)
            except Exception:  # noqa: BLE001 — a failing dump hook must
                pass  # not kill the monitor
        return reason

    def _monitor(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            self.check()

    def state(self) -> dict:
        with self._lock:
            return {
                "outstanding": self._outstanding,
                "tripped": self._tripped,
                "trips": self.trips,
                "last_progress_age_s": self._clock() - self._last_progress,
                "timeout_s": max(
                    self.min_timeout_s,
                    self.factor
                    * percentiles(list(self._durations), (99,)).get("p99", 0.0),
                ),
                "history": len(self._durations),
            }

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
