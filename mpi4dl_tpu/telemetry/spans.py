"""Request-span tracing: contiguous lifecycle phases per request.

A request's life in the serving engine is a chain of phases —
``queue_wait`` (submit → picked by the batch former), ``batch_form``
(picked → batch complete), ``h2d_stage`` (batch complete → host→device
staging + dispatch issued), ``device_compute`` (dispatch → result ready on
host). The engine records one monotonic timestamp at each boundary;
:func:`spans_from_marks` turns the boundary list into span dicts whose
durations sum EXACTLY to the end-to-end latency (each span starts where
the previous one ends — an invariant the tier-1 tests assert on real
JSONL logs, and the property that makes "where did my p99 go" answerable
by subtraction).

Span events are JSONL records (:mod:`mpi4dl_tpu.telemetry.jsonl`) keyed by
a process-unique ``trace_id`` that :func:`mpi4dl_tpu.profiling.annotate_step`
aligns with XProf step annotations, so a device-timeline trace and the
host-side span log can be joined on the same ids.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

_counter = itertools.count()
_counter_lock = threading.Lock()


def new_trace_id(prefix: str = "req") -> str:
    """Process-unique, monotonic, human-greppable trace id."""
    with _counter_lock:
        n = next(_counter)
    return f"{prefix}-{os.getpid():x}-{n}"


def spans_from_marks(marks: "list[tuple[str, float]]") -> "list[dict]":
    """``[(label, t0), (phase1, t1), (phase2, t2), ...]`` → span dicts.

    The first mark anchors the start; each subsequent ``(phase, t)`` closes
    the phase ending at ``t``. Timestamps must be non-decreasing (a clock
    that runs backwards would silently corrupt every duration downstream,
    so it raises instead).
    """
    if len(marks) < 2:
        raise ValueError("need an anchor mark plus at least one phase")
    spans = []
    prev = float(marks[0][1])
    for phase, t in marks[1:]:
        t = float(t)
        if t < prev:
            raise ValueError(
                f"span {phase!r} ends at {t} before it starts at {prev}"
            )
        spans.append({
            "phase": str(phase),
            "start_s": prev,
            "end_s": t,
            "duration_s": t - prev,
        })
        prev = t
    return spans


def span_event(
    name: str,
    trace_id: str,
    spans: "list[dict]",
    attrs: "dict | None" = None,
    ts: "float | None" = None,
) -> dict:
    """One JSONL span record (kind="span") — see jsonl.validate_event."""
    return {
        "ts": time.time() if ts is None else float(ts),
        "kind": "span",
        "name": str(name),
        "trace_id": str(trace_id),
        "spans": spans,
        "attrs": dict(attrs or {}),
    }


def record_spans(histogram, spans: "list[dict]") -> None:
    """Mirror span durations into a phase-labeled histogram (the catalog's
    ``serve_span_seconds``) so the per-phase distribution is scrapeable
    without replaying the JSONL log."""
    for s in spans:
        histogram.observe(s["duration_s"], phase=s["phase"])
