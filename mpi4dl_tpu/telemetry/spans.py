"""Request-span tracing: contiguous lifecycle phases per request.

A request's life in the serving engine is a chain of phases —
``queue_wait`` (submit → picked by the batch former), ``batch_form``
(picked → batch complete), ``h2d_stage`` (batch complete → host→device
staging + dispatch issued), ``device_compute`` (dispatch → result ready on
host). The engine records one monotonic timestamp at each boundary;
:func:`spans_from_marks` turns the boundary list into span dicts whose
durations sum EXACTLY to the end-to-end latency (each span starts where
the previous one ends — an invariant the tier-1 tests assert on real
JSONL logs, and the property that makes "where did my p99 go" answerable
by subtraction).

Span events are JSONL records (:mod:`mpi4dl_tpu.telemetry.jsonl`) keyed by
a ``trace_id`` that :func:`mpi4dl_tpu.profiling.annotate_step` aligns with
XProf step annotations, so a device-timeline trace and the host-side span
log can be joined on the same ids.

Distributed tracing: a trace id is globally unique (pid + a per-process
random component + a monotonic counter — see :func:`new_trace_id`), so
span events emitted by DIFFERENT processes for the SAME logical request
(a load-generator client and the replica engine that served it; tomorrow,
a fleet router and N replicas) join under one id. The client creates the
id and hands it down (``ServingEngine.submit(trace_id=...)``); each
process emits its own span *segment*; :func:`group_spans_by_trace`
re-joins the segments and :func:`chrome_trace` renders the joined
lifetime — client → queue → batch → device — as a Chrome trace
(``chrome://tracing`` / Perfetto), one process per track
(``python -m mpi4dl_tpu.analyze trace-export``).
"""

from __future__ import annotations

import itertools
import os
import threading
import time

_counter = itertools.count()
_counter_lock = threading.Lock()

# Per-process random tag, computed lazily so a fork (supervised replica
# restart, multiprocessing worker) gets a fresh one: pid alone is NOT
# collision-proof across a fleet — pids recycle, and two hosts can share a
# pid space — so the tag carries 32 random bits next to the pid.
_proc_tag: "str | None" = None
_proc_tag_pid: "int | None" = None


def _process_tag() -> str:
    global _proc_tag, _proc_tag_pid
    pid = os.getpid()
    if _proc_tag is None or _proc_tag_pid != pid:
        _proc_tag = f"{pid:x}-{os.urandom(4).hex()}"
        _proc_tag_pid = pid
    return _proc_tag


def new_trace_id(prefix: str = "req") -> str:
    """Globally-unique, per-process-monotonic, human-greppable trace id:
    ``<prefix>-<pid hex>-<random32 hex>-<counter>``. Safe to mint in N
    replica processes whose spans will later be federated into one
    stream — ids cannot collide across processes (pid + 32 random bits)
    and stay orderable within one (the counter)."""
    with _counter_lock:
        n = next(_counter)
    return f"{prefix}-{_process_tag()}-{n}"


def spans_from_marks(marks: "list[tuple[str, float]]") -> "list[dict]":
    """``[(label, t0), (phase1, t1), (phase2, t2), ...]`` → span dicts.

    The first mark anchors the start; each subsequent ``(phase, t)`` closes
    the phase ending at ``t``. Timestamps must be non-decreasing (a clock
    that runs backwards would silently corrupt every duration downstream,
    so it raises instead).
    """
    if len(marks) < 2:
        raise ValueError("need an anchor mark plus at least one phase")
    spans = []
    prev = float(marks[0][1])
    for phase, t in marks[1:]:
        t = float(t)
        if t < prev:
            raise ValueError(
                f"span {phase!r} ends at {t} before it starts at {prev}"
            )
        spans.append({
            "phase": str(phase),
            "start_s": prev,
            "end_s": t,
            "duration_s": t - prev,
        })
        prev = t
    return spans


def span_event(
    name: str,
    trace_id: str,
    spans: "list[dict]",
    attrs: "dict | None" = None,
    ts: "float | None" = None,
) -> dict:
    """One JSONL span record (kind="span") — see jsonl.validate_event."""
    return {
        "ts": time.time() if ts is None else float(ts),
        "kind": "span",
        "name": str(name),
        "trace_id": str(trace_id),
        "spans": spans,
        "attrs": dict(attrs or {}),
    }


def record_spans(
    histogram, spans: "list[dict]", exemplar: "str | None" = None
) -> None:
    """Mirror span durations into a phase-labeled histogram (the catalog's
    ``serve_span_seconds``) so the per-phase distribution is scrapeable
    without replaying the JSONL log. ``exemplar`` (the request's trace
    id) tags each phase bucket the durations land in, so a scrape links
    a slow ``queue_wait`` bucket straight to a concrete request."""
    for s in spans:
        histogram.observe(s["duration_s"], exemplar=exemplar, phase=s["phase"])


# -- joining + export across processes ----------------------------------------


def group_spans_by_trace(events) -> "dict[str, list[dict]]":
    """Join span events (possibly from N processes' JSONL logs) by
    ``trace_id``; within a trace, segments are ordered by wall-clock
    start. The aggregator-side half of distributed tracing: each process
    only ever emits its own segment."""
    out: "dict[str, list[dict]]" = {}
    for ev in events:
        if ev.get("kind") != "span" or not ev.get("trace_id"):
            continue
        out.setdefault(ev["trace_id"], []).append(ev)
    for evs in out.values():
        evs.sort(key=_event_wall_start)
    return out


def _event_wall_start(ev: dict) -> float:
    """Wall-clock time of the event's first span. Span marks are
    per-process ``time.monotonic`` values, NOT comparable across
    processes; the event's ``ts`` (``time.time`` at emission, which
    happens at the final span boundary) anchors them to a shared clock:
    wall(mark) = ts - (last_end - mark)."""
    spans = ev["spans"]
    return ev["ts"] - (spans[-1]["end_s"] - spans[0]["start_s"])


def chrome_trace(
    events,
    trace_id: "str | None" = None,
    process_names: "dict[int, str] | None" = None,
) -> dict:
    """Span events from any number of processes → a Chrome trace dict
    (``{"traceEvents": [...]}`` — load in chrome://tracing or Perfetto).

    Each span becomes a complete event (``ph="X"``) on the track
    ``pid`` = emitting process (``attrs["pid"]``, 0 when absent),
    ``tid`` = one row per trace within the process, so a request's full
    cross-process lifetime reads top-to-bottom: the client segment on the
    client process's track, queue→batch→device on the replica's.
    Monotonic span marks are anchored to wall clock per event (see
    :func:`_event_wall_start`) and the whole trace is normalized to start
    at t=0. ``trace_id`` exports one request; None exports every trace in
    ``events``.
    """
    groups = group_spans_by_trace(events)
    if trace_id is not None:
        groups = {trace_id: groups.get(trace_id, [])}
    picked = [(tid, ev) for tid, evs in groups.items() for ev in evs]
    if not any(ev for _, ev in picked):
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t0 = min(_event_wall_start(ev) for _, ev in picked)
    rows: "dict[tuple[int, str], int]" = {}  # (pid, trace_id) -> tid
    next_row: "dict[int, int]" = {}
    trace_events: "list[dict]" = []
    seen_pids: "dict[int, str]" = {}
    for tid_key, ev in sorted(picked, key=lambda p: _event_wall_start(p[1])):
        attrs = ev.get("attrs", {})
        pid = int(attrs.get("pid", 0))
        if pid not in seen_pids:
            seen_pids[pid] = (
                (process_names or {}).get(pid)
                or attrs.get("process")
                or attrs.get("role")
                or f"pid {pid}"
            )
        row = rows.get((pid, tid_key))
        if row is None:
            row = rows[(pid, tid_key)] = next_row.get(pid, 0)
            next_row[pid] = row + 1
        base = _event_wall_start(ev) - ev["spans"][0]["start_s"]
        for s in ev["spans"]:
            trace_events.append({
                "name": s["phase"],
                "cat": ev["name"],
                "ph": "X",
                "ts": (base + s["start_s"] - t0) * 1e6,  # microseconds
                "dur": s["duration_s"] * 1e6,
                "pid": pid,
                "tid": row,
                "args": {"trace_id": tid_key, **attrs},
            })
    for pid, name in seen_pids.items():
        trace_events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name},
        })
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}
