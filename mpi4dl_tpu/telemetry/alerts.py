"""Alert state machine + the in-process SLO evaluator thread.

The T3 lesson applied to alerting: evaluation runs *inside* the engine
against the live registry, continuously, instead of assuming an external
Prometheus deployment the single-process serving story doesn't have.
A daemon :class:`SLOEvaluator` ticks every ``interval_s``: one registry
snapshot into the :class:`~mpi4dl_tpu.telemetry.windows.SnapshotWindow`,
then for every :class:`~mpi4dl_tpu.telemetry.slo.Objective` × burn
window it computes long/short burn rates, publishes the cataloged
``slo_error_budget_remaining`` / ``slo_burn_rate`` / ``alert_active``
series, steps each alert's state machine, and drives the advisory
autoscaler (:mod:`mpi4dl_tpu.telemetry.autoscale`).

Alert lifecycle (Prometheus-shaped)::

    inactive ──condition──▶ pending ──held for_s──▶ firing
        ▲                      │ condition clears      │ condition clears
        └──────(cancelled)─────┴───────(resolved)──────┘

Every transition is emitted as a schema-valid JSONL ``event``
(``name="alert.transition"``) into the engine's event log (when enabled)
and ALWAYS into the flight-recorder ring — a postmortem dump shows the
alert history interleaved with the request spans that caused it.

Clock and ticking are injectable (``start=False`` +
:meth:`SLOEvaluator.evaluate_once`) so the trip math is unit-testable
with hand-computed golden values and no real waits.
"""

from __future__ import annotations

import collections
import threading
import time

from mpi4dl_tpu.telemetry import slo as slo_mod
from mpi4dl_tpu.telemetry.windows import SnapshotWindow

STATES = ("inactive", "pending", "firing")

#: The phase-labeled span histogram phase attribution reads.
SPAN_METRIC = "serve_span_seconds"


def phase_attribution(window, window_s: float) -> "dict | None":
    """Which lifecycle phase's share of served latency GREW in the recent
    window, vs the pre-window cumulative baseline — the first question a
    latency page asks ("where did my p99 go"), answered by subtraction
    from the contiguous-span invariant instead of by a human diffing
    histograms. Returns None without enough data (cold start, no served
    requests in the window, no pre-window baseline)."""
    phases = window.label_values(SPAN_METRIC, "phase")
    if not phases:
        return None
    recent: dict = {}
    totals: dict = {}
    for p in phases:
        h = window.hist_increase(SPAN_METRIC, window_s, phase=p)
        recent[p] = h["sum"] if h else 0.0
        t = window.hist_total(SPAN_METRIC, phase=p)
        totals[p] = t["sum"] if t else 0.0
    recent_total = sum(recent.values())
    # Baseline excludes the window itself, so a regression present since
    # step 0 still shows as zero delta (nothing *changed*) while a fresh
    # one stands out.
    baseline = {p: max(0.0, totals[p] - recent[p]) for p in phases}
    base_total = sum(baseline.values())
    if recent_total <= 0 or base_total <= 0:
        return None
    shares = {p: recent[p] / recent_total for p in phases}
    base_shares = {p: baseline[p] / base_total for p in phases}
    delta = {p: shares[p] - base_shares[p] for p in phases}
    regressed = max(delta, key=lambda p: delta[p])
    return {
        "window_s": float(window_s),
        "shares": {p: round(v, 4) for p, v in shares.items()},
        "baseline_shares": {p: round(v, 4) for p, v in base_shares.items()},
        "delta": {p: round(v, 4) for p, v in delta.items()},
        "regressed_phase": regressed,
        "regressed_delta": round(delta[regressed], 4),
    }


def latency_exemplars(registry, metric: str, k: int = 5) -> "list[dict]":
    """Top-``k`` slowest exemplars off a latency histogram's buckets
    (value-descending, deduped by trace id): the concrete requests a
    firing ``latency_*`` page attaches as ``evidence``. Empty when the
    metric is absent or carries no exemplars (old snapshots, exemplar-
    free publishers) — evidence degrades, pages still fire."""
    m = registry.get(metric)
    if m is None or getattr(m, "kind", None) != "histogram":
        return []
    best: "dict[str, dict]" = {}
    for s in m.snapshot_series():
        for le, ex in (s.get("exemplars") or {}).items():
            have = best.get(ex["trace_id"])
            if have is None or ex["value"] > have["value"]:
                best[ex["trace_id"]] = {
                    "trace_id": ex["trace_id"],
                    "value": ex["value"],
                    "ts": ex["ts"],
                    "le": le,
                    "labels": dict(s["labels"]),
                }
    out = sorted(best.values(), key=lambda e: e["value"], reverse=True)
    return out[: int(k)]


class AlertState:
    """One alert's ``inactive → pending → firing`` machine.

    ``step(active, now)`` returns the transition ``(old, new)`` when the
    state changed, else None. ``for_s`` is the hold time between the
    condition first turning true and the alert firing; 0 fires on the
    first true evaluation.
    """

    def __init__(self, name: str, severity: str, for_s: float = 0.0):
        self.name = name
        self.severity = severity
        self.for_s = float(for_s)
        self.state = "inactive"
        self.since: "float | None" = None     # state entry time
        self.pending_since: "float | None" = None
        self.fired_count = 0

    def step(self, active: bool, now: float):
        old = self.state
        if active:
            if self.state == "inactive":
                self.pending_since = now
                if self.for_s <= 0:
                    self.state = "firing"
                    self.fired_count += 1
                else:
                    self.state = "pending"
            elif self.state == "pending":
                if now - self.pending_since >= self.for_s:
                    self.state = "firing"
                    self.fired_count += 1
        else:
            if self.state in ("pending", "firing"):
                self.state = "inactive"
                self.pending_since = None
        if self.state != old:
            self.since = now
            return (old, self.state)
        return None

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            "severity": self.severity,
            "state": self.state,
            "for_s": self.for_s,
            "since": self.since,
            "fired_count": self.fired_count,
        }


def _alert_name(obj, bw) -> str:
    """Alert key for one (objective, burn window). Per-tenant objectives
    share their ``obj.name`` across tenants (the per-class SLO name),
    so the tenant joins the key — otherwise two tenants' burn alerts
    would collapse into one state machine and mask each other."""
    if getattr(obj, "tenant", "default") in ("", "default"):
        return f"{obj.name}_{bw.name}_burn"
    return f"{obj.name}_{obj.tenant}_{bw.name}_burn"


class SLOEvaluator:
    """Continuous SLO evaluation over the live registry.

    registry: the shared :class:`MetricsRegistry` (read for snapshots,
        written for the ``slo_*`` / ``alert_active`` series — all
        declared up front so the catalog pin sees them from tick zero).
    objectives: :class:`~mpi4dl_tpu.telemetry.slo.Objective` list
        (usually ``SLOConfig.objectives()``).
    config: the :class:`~mpi4dl_tpu.telemetry.slo.SLOConfig` supplying
        burn windows / for_s / interval / ring capacity.
    autoscaler: optional :class:`~mpi4dl_tpu.telemetry.autoscale.
        Autoscaler`, driven once per tick with the page-window burn.
    events: optional :class:`JsonlWriter` for transition events.
    flight: optional :class:`FlightRecorder`; transitions enter the ring.
    clock: injectable monotonic clock; ``start=False`` skips the daemon
        thread (tests call :meth:`evaluate_once`).
    """

    def __init__(
        self,
        registry,
        objectives,
        config,
        autoscaler=None,
        events=None,
        flight=None,
        clock=time.monotonic,
        start: bool = False,
    ):
        from mpi4dl_tpu import telemetry

        self.registry = registry
        self.objectives = list(objectives)
        self.config = config
        self.autoscaler = autoscaler
        self._events = events
        self._flight = flight
        self._clock = clock
        self.window = SnapshotWindow(
            registry, capacity=config.ring_capacity(), clock=clock
        )
        self._m_budget = telemetry.declare(
            registry, "slo_error_budget_remaining"
        )
        self._m_burn = telemetry.declare(registry, "slo_burn_rate")
        self._m_active = telemetry.declare(registry, "alert_active")
        self.alerts: "dict[str, AlertState]" = {}
        for obj in self.objectives:
            for bw in config.burn_windows:
                name = _alert_name(obj, bw)
                self.alerts[name] = AlertState(
                    name, bw.severity, for_s=config.for_s
                )
                self._m_active.set(0.0, alert=name, severity=bw.severity)
        # Opt-in resource alert (telemetry/memory.py): pages when any
        # device's live HBM headroom gauge drops under the configured
        # fraction. Rides the same AlertState/transition/alert_active
        # machinery as the burn alerts — one /alertz, one runbook shape.
        self._headroom_ratio = getattr(config, "headroom_alert_ratio", None)
        if self._headroom_ratio is not None:
            self._headroom_ratio = float(self._headroom_ratio)
            st = AlertState(
                "memory_headroom_low", "page", for_s=config.for_s
            )
            self.alerts[st.name] = st
            self._m_active.set(0.0, alert=st.name, severity=st.severity)
        self.transitions: collections.deque = collections.deque(maxlen=256)
        self.last_phase_attribution: "dict | None" = None
        self._last_burns: dict = {}
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None
        if start:
            self.start()

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="mpi4dl-slo-evaluator", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop_evt.wait(self.config.interval_s):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — a broken evaluation must
                pass  # not kill the serving loop's sidecar thread

    # -- evaluation -----------------------------------------------------------

    def evaluate_once(self, now: "float | None" = None) -> dict:
        """One tick: snapshot, burn rates, gauges, alert transitions,
        autoscale. Returns the burn map (tests read the golden values)."""
        now = self._clock() if now is None else float(now)
        self.window.record(now)
        burns: dict = {}
        page_burn = None
        for obj in self.objectives:
            rem = slo_mod.budget_remaining(self.registry, obj)
            if rem is not None:
                self._m_budget.set(rem, slo=obj.name, tenant=obj.tenant)
            for bw in self.config.burn_windows:
                b_long = slo_mod.burn_rate(self.window, obj, bw.long_s)
                b_short = slo_mod.burn_rate(self.window, obj, bw.short_s)
                burns[(obj.name, obj.tenant, bw.name)] = (b_long, b_short)
                if b_long is not None:
                    self._m_burn.set(
                        b_long, slo=obj.name, window=f"{bw.name}_long",
                        tenant=obj.tenant,
                    )
                if b_short is not None:
                    self._m_burn.set(
                        b_short, slo=obj.name, window=f"{bw.name}_short",
                        tenant=obj.tenant,
                    )
                if bw.severity == "page" and b_long is not None:
                    page_burn = (
                        b_long if page_burn is None else max(page_burn, b_long)
                    )
                active = (
                    b_long is not None and b_short is not None
                    and b_long > bw.factor and b_short > bw.factor
                )
                name = _alert_name(obj, bw)
                st = self.alerts[name]
                moved = st.step(active, now)
                self._m_active.set(
                    1.0 if st.state == "firing" else 0.0,
                    alert=name, severity=st.severity,
                )
                if moved is not None:
                    self._emit_transition(
                        st, moved, obj, bw, b_long, b_short
                    )
        if self._headroom_ratio is not None:
            self._evaluate_headroom(now)
        with self._lock:
            self._last_burns = dict(burns)
        if self.autoscaler is not None:
            self.autoscaler.update(now, self.window, page_burn)
        return burns

    def _evaluate_headroom(self, now: float) -> None:
        """Step the ``memory_headroom_low`` machine from the live
        per-device headroom gauges. No gauge series (CPU backend, or the
        monitor not yet sampled) means the condition is NOT met — no
        data must never page."""
        st = self.alerts["memory_headroom_low"]
        metric = "device_hbm_headroom_ratio"
        low_dev, low = None, None
        for dev in self.window.label_values(metric, "device"):
            v = self.window.value(metric, device=dev)
            if v is not None and (low is None or v < low):
                low_dev, low = dev, v
        active = low is not None and low < self._headroom_ratio
        moved = st.step(active, now)
        self._m_active.set(
            1.0 if st.state == "firing" else 0.0,
            alert=st.name, severity=st.severity,
        )
        if moved is not None:
            old, new = moved
            ev = {
                "ts": time.time(),
                "kind": "event",
                "name": "alert.transition",
                "attrs": {
                    "alert": st.name,
                    "severity": st.severity,
                    "from": old,
                    "to": new,
                    "threshold": self._headroom_ratio,
                    "headroom_min": low,
                    "device": low_dev,
                },
            }
            self.transitions.append(ev)
            if self._flight is not None:
                self._flight.record(ev)
            if self._events is not None:
                self._events.write(ev)

    def _emit_transition(self, st, moved, obj, bw, b_long, b_short) -> None:
        old, new = moved
        ev = {
            "ts": time.time(),
            "kind": "event",
            "name": "alert.transition",
            "attrs": {
                "alert": st.name,
                "severity": st.severity,
                "from": old,
                "to": new,
                "slo": obj.name,
                "tenant": obj.tenant,
                "objective": obj.target,
                "factor": bw.factor,
                "burn_long": b_long,
                "burn_short": b_short,
                "window_long_s": bw.long_s,
                "window_short_s": bw.short_s,
            },
        }
        if obj.kind == "latency" and new in ("pending", "firing"):
            # A latency alert names its suspect: the span phase whose
            # share of served latency grew over the alert's long window.
            try:
                pa = phase_attribution(self.window, bw.long_s)
            except Exception:  # noqa: BLE001 — attribution is advisory
                pa = None
            if pa is not None:
                ev["attrs"]["phase_attribution"] = pa
                self.last_phase_attribution = {
                    "alert": st.name, "ts": ev["ts"], **pa,
                }
            # ...and its victims: the top-K exemplar trace ids off the
            # objective's own histogram (the PR-9 breaker-evidence
            # pattern — the page links to the concrete slow requests,
            # `analyze tail --trace-id` takes it from there).
            try:
                exemplars = latency_exemplars(self.registry, obj.metric)
            except Exception:  # noqa: BLE001 — evidence is best-effort
                exemplars = []
            if exemplars:
                ev["attrs"]["evidence"] = {
                    "exemplar_trace_ids": [
                        e["trace_id"] for e in exemplars
                    ],
                    "exemplars": exemplars,
                }
        self.transitions.append(ev)
        if self._flight is not None:
            self._flight.record(ev)
        if self._events is not None:
            self._events.write(ev)

    # -- surfaces -------------------------------------------------------------

    def state(self) -> dict:
        """The ``/alertz`` payload: objectives + budgets + burns, alert
        states, recent transitions, autoscale view."""
        with self._lock:
            burns = dict(self._last_burns)
        slos = []
        for obj in self.objectives:
            key = (obj.name, obj.tenant)
            entry = {
                "slo": obj.name,
                "tenant": obj.tenant,
                "kind": obj.kind,
                "objective": obj.target,
                "metric": obj.metric,
                "sli_cumulative": slo_mod.cumulative_sli(self.registry, obj),
                "error_budget_remaining": slo_mod.budget_remaining(
                    self.registry, obj
                ),
                "burn": {
                    bw.name: {
                        "long": burns.get((*key, bw.name), (None, None))[0],
                        "short": burns.get((*key, bw.name), (None, None))[1],
                        "factor": bw.factor,
                        "long_s": bw.long_s,
                        "short_s": bw.short_s,
                        "severity": bw.severity,
                    }
                    for bw in self.config.burn_windows
                },
            }
            if obj.kind == "latency":
                entry["threshold_s"] = obj.threshold_s
            slos.append(entry)
        return {
            "slos": slos,
            "alerts": [a.snapshot() for a in self.alerts.values()],
            "phase_attribution": self.last_phase_attribution,
            "transitions": list(self.transitions)[-20:],
            "autoscale": (
                self.autoscaler.state() if self.autoscaler is not None
                else None
            ),
            "window": {
                "snapshots": len(self.window),
                "span_s": self.window.span_s(),
            },
        }

    def verdict(self) -> dict:
        """Compact end-of-run verdict (bench.py result lines): ok iff no
        page alert ever fired and every budget ends non-negative."""
        out = {"ok": True, "slos": {}, "alerts_fired": {}}
        for obj in self.objectives:
            key = (
                obj.name if obj.tenant == "default"
                else f"{obj.name}:{obj.tenant}"
            )
            out["slos"][key] = {
                "objective": obj.target,
                "sli": slo_mod.cumulative_sli(self.registry, obj),
                "budget_remaining": slo_mod.budget_remaining(
                    self.registry, obj
                ),
            }
            rem = out["slos"][key]["budget_remaining"]
            if rem is not None and rem < 0:
                out["ok"] = False
        for a in self.alerts.values():
            if a.fired_count:
                out["alerts_fired"][a.name] = a.fired_count
                if a.severity == "page":
                    out["ok"] = False
        return out
