"""Telemetry federation: merge N replica registries into one fleet view.

Every observability layer built so far — registry, spans, windows, SLO
evaluator, autoscaler — lives inside ONE process, while the system the
ROADMAP is heading for (a router fanning requests out to N single-chip
``ServingEngine`` replicas) is inherently multi-process, the same way
MPI4DL's 5D parallelism runs one rank per device. This module is the
cross-process substrate: a :class:`FederatedAggregator` scrapes each
child's machine-readable ``/snapshotz`` endpoint (the JSON twin of
``/metrics`` — no text-format parse on the hot path) and merges the
snapshots into one registry-shaped view with per-replica attribution:

- **counters** are summed across replicas per label set (the fleet's
  ``serve_requests_total{outcome=}`` is the sum of its parts — exactly
  what the availability SLO needs);
- **histograms** are merged bucket-wise (cumulative ``le`` counts, sums,
  and counts add — percentile and latency-SLO math over the merged
  buckets is exact, not an average-of-percentiles); per-bucket exemplars
  merge max-value-wins (the fleet bucket names its worst request), with
  same-trace-id/different-value disagreements surfaced in ``.conflicts``;
- **gauges** keep one series per replica under an injected ``replica``
  label (attribution: WHICH replica's queue is deep) plus ``min`` /
  ``max`` / ``sum`` rollup series (``replica="sum"`` et al — reserved
  replica names).

The merged view (:class:`FederatedRegistry`) answers the same
``snapshot()`` / ``get()`` protocol as a :class:`MetricsRegistry`, layered
over a real local registry for the aggregator's own publications — so a
:class:`~mpi4dl_tpu.telemetry.alerts.SLOEvaluator` and
:class:`~mpi4dl_tpu.telemetry.autoscale.Autoscaler` run FLEET-WIDE
unchanged (``SnapshotWindow`` queries fall back to the ``replica="sum"``
rollup for unlabeled gauge lookups), and a :class:`MetricsServer` over it
re-exports the whole fleet as one scrape — federation nests.

Span segments from the replicas' JSONL logs join by ``trace_id``
(:func:`mpi4dl_tpu.telemetry.spans.group_spans_by_trace`) and export as a
Chrome trace via ``python -m mpi4dl_tpu.analyze trace-export``.

Runnable standalone (the zero-to-fleet-dashboard path)::

    python -m mpi4dl_tpu.telemetry.federation \
        --replica r0=http://127.0.0.1:9100 \
        --replica r1=http://127.0.0.1:9101 --port 9200
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request

from mpi4dl_tpu.telemetry.registry import MetricsRegistry

#: Gauge rollup series injected next to the per-replica ones; replica
#: names must not collide with them.
ROLLUPS = ("sum", "min", "max")

_REPLICA_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]*$")


def _series_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


def merge_snapshots(children: "dict[str, dict]") -> "tuple[dict, list]":
    """Merge per-replica ``registry.snapshot()`` dicts into one
    snapshot-shaped dict (see module doc for the per-type semantics).
    Returns ``(merged, conflicts)`` where ``conflicts`` lists
    human-readable notes for series that could not be merged (a replica
    disagreeing about a metric's type/labels/buckets) — dropped rather
    than silently mis-summed, surfaced rather than silently dropped."""
    merged: dict = {}
    conflicts: "list[str]" = []
    for replica in sorted(children):
        snap = children[replica]
        for name, m in snap.items():
            out = merged.get(name)
            if out is None:
                labels = list(m.get("labels", ()))
                if m["type"] == "gauge":
                    labels = labels + ["replica"]
                out = merged[name] = {
                    "type": m["type"],
                    "help": m.get("help", ""),
                    "labels": labels,
                    "series": [],
                    "_acc": {},
                }
            elif out["type"] != m["type"] or (
                m["type"] != "gauge"
                and list(m.get("labels", ())) != out["labels"]
            ) or (
                m["type"] == "gauge"
                and list(m.get("labels", ())) + ["replica"] != out["labels"]
            ):
                conflicts.append(
                    f"{replica}:{name}: type/labels disagree with an "
                    "earlier replica — skipped"
                )
                continue
            for s in m["series"]:
                _merge_series(out, m["type"], replica, s, name, conflicts)
    for name, out in merged.items():
        out["series"] = _finalize(out.pop("_acc"), out["type"])
    return merged, conflicts


def _merge_series(out, mtype, replica, s, name, conflicts) -> None:
    acc = out["_acc"]
    key = _series_key(s["labels"])
    if mtype == "counter":
        cur = acc.get(key)
        acc[key] = {
            "labels": dict(s["labels"]),
            "value": (0.0 if cur is None else cur["value"]) + s["value"],
        }
    elif mtype == "gauge":
        per = acc.setdefault(key, {"labels": dict(s["labels"]), "by": {}})
        per["by"][replica] = s["value"]
    else:  # histogram
        cur = acc.get(key)
        if cur is None:
            entry = {
                "labels": dict(s["labels"]),
                "count": s["count"],
                "sum": s["sum"],
                "buckets": dict(s["buckets"]),
            }
            if s.get("exemplars"):
                entry["exemplars"] = {
                    le: dict(ex) for le, ex in s["exemplars"].items()
                }
            acc[key] = entry
        elif set(cur["buckets"]) != set(s["buckets"]):
            conflicts.append(
                f"{replica}:{name}: histogram bucket bounds disagree — "
                "series skipped"
            )
        else:
            cur["count"] += s["count"]
            cur["sum"] += s["sum"]
            for le, n in s["buckets"].items():
                cur["buckets"][le] += n
            _merge_exemplars(cur, s, replica, name, conflicts)
    out["_acc"] = acc


def _merge_exemplars(cur: dict, s: dict, replica, name, conflicts) -> None:
    """Per-bucket exemplar merge: the MAX-value exemplar wins (the fleet
    view should name the worst request in each bucket, not whichever
    replica was scraped last). Two replicas presenting the SAME trace id
    with different values for one bucket is a real disagreement — a
    requeued request double-observed, or clock skew corrupting values —
    surfaced in ``conflicts``, never silently averaged away (the max
    still wins so the merge stays usable)."""
    incoming = s.get("exemplars")
    if not incoming:
        return
    mine = cur.setdefault("exemplars", {})
    for le, ex in incoming.items():
        have = mine.get(le)
        if have is None:
            mine[le] = dict(ex)
            continue
        if (
            have["trace_id"] == ex["trace_id"]
            and have["value"] != ex["value"]
        ):
            conflicts.append(
                f"{replica}:{name}: bucket le={le} exemplar "
                f"{ex['trace_id']!r} reported with conflicting values "
                f"({have['value']:g} vs {ex['value']:g}) — max kept"
            )
        if ex["value"] > have["value"]:
            mine[le] = dict(ex)


def _finalize(acc: dict, mtype: str) -> "list[dict]":
    if mtype != "gauge":
        return list(acc.values())
    series = []
    for per in acc.values():
        vals = list(per["by"].values())
        for replica, v in sorted(per["by"].items()):
            series.append({
                "labels": {**per["labels"], "replica": replica}, "value": v,
            })
        for roll, v in (
            ("sum", sum(vals)), ("min", min(vals)), ("max", max(vals)),
        ):
            series.append({
                "labels": {**per["labels"], "replica": roll}, "value": v,
            })
    return series


def bucket_quantile(buckets: dict, q: float) -> "float | None":
    """Conservative quantile from cumulative ``le`` buckets: the smallest
    finite bound covering at least fraction ``q`` of observations. When
    the quantile lands in ``+Inf`` the largest finite bound is returned —
    a FLOOR ("p99 is at least this"), which is the safe direction for
    straggler scoring: a replica whose tail escapes the bucket range can
    only be under-scored relative to itself, never over-score a healthy
    peer. None with no observations."""
    total = buckets.get("+Inf", 0)
    if total <= 0:
        return None
    need = q * total
    finite = sorted(
        (float(le) for le in buckets if le != "+Inf")
    )
    for b in finite:
        if buckets[f"{b:g}"] >= need:
            return b
    return finite[-1] if finite else None


def replica_skew(
    children: "dict[str, dict]",
    metric: str = "serve_request_latency_seconds",
    quantile: float = 0.99,
    min_count: int = 20,
) -> dict:
    """Straggler scoring over the per-replica snapshots the aggregator
    already scraped (the merge collapses histograms fleet-wide; the
    per-replica tails live in the raw children): each replica's own
    bucket-resolved p99 of ``metric``, divided by the fleet MEDIAN p99.

    Median, not mean: one straggler must not drag the baseline toward
    itself — with a median the slow replica scores against what the
    healthy majority actually delivers. Even replica counts use the
    LOWER median: the interpolated midpoint of a 2-replica fleet sits
    halfway to the straggler, capping its own skew just under 2x no
    matter how slow it gets — leaning the baseline toward the faster
    half keeps the smallest fleets able to name their straggler.
    Replicas with fewer than ``min_count`` observations are excluded (a
    replica that served three requests has no tail to score).

    Returns ``{"p99": {replica: p99_s}, "median_p99": m,
    "skew": {replica: p99/m}, "excluded": [names]}`` — empty maps when
    fewer than two replicas qualify (skew needs a fleet to be relative
    to)."""
    p99s: "dict[str, float]" = {}
    excluded: "list[str]" = []
    for name in sorted(children):
        m = children[name].get(metric)
        if not m or m.get("type") != "histogram":
            excluded.append(name)
            continue
        agg: "dict[str, float]" = {}
        for s in m.get("series", ()):
            for le, cum in s.get("buckets", {}).items():
                agg[le] = agg.get(le, 0) + cum
        if agg.get("+Inf", 0) < min_count:
            excluded.append(name)
            continue
        p = bucket_quantile(agg, quantile)
        if p is None:
            excluded.append(name)
            continue
        p99s[name] = p
    if len(p99s) < 2:
        return {"p99": p99s, "median_p99": None, "skew": {},
                "excluded": excluded}
    ordered = sorted(p99s.values())
    median = ordered[(len(ordered) - 1) // 2]  # lower median, see above
    if median <= 0:
        # All-zero tails (every observation under the first bucket):
        # nobody is a straggler relative to anything.
        return {"p99": p99s, "median_p99": median, "skew": {},
                "excluded": excluded}
    return {
        "p99": p99s,
        "median_p99": median,
        "skew": {name: p / median for name, p in p99s.items()},
        "excluded": excluded,
    }


def numerics_skew(numerics: "dict[str, dict]") -> dict:
    """Numerics-divergence scoring over the per-replica ``numerics``
    payloads the aggregator scraped off ``/snapshotz`` — the straggler
    pattern applied to CORRECTNESS: score each replica's evidence of
    serving wrong answers, and name the divergent one.

    Three evidence sources, weighted by how conclusive they are:

    - **self-report** (weight 1.0 each): the replica's own sentinel
      counted canary ``failures``, its fence latched, or its live
      params checksum drifted from its load-time baseline — each alone
      is paging evidence (the sentinel only concludes ``divergence``
      beyond the documented tolerance).
    - **checksum vote** (weight 1.0): replicas serving the same model
      must agree on ``params_checksum``; a replica outvoted by a STRICT
      majority is corrupt even if its own sentinel hasn't fired yet
      (e.g. corrupted between ticks). A split with no majority (1v1) is
      recorded as evidence on both, unscored — two replicas alone
      cannot out-vote each other.
    - **canary digest vote**: warm-up reference digests, compared
      bitwise within one (bucket, executable fingerprint) group (weight
      1.0 — same binary must agree bit for bit: a minority reference
      means the replica warmed up ALREADY corrupted) and
      tolerance-quantized across fingerprints (weight 0.4 — advisory
      by construction: grid-boundary straddles exist, so a qdigest
      minority alone must stay below the page threshold of 1.0).

    Returns ``{"score": {replica: s}, "evidence": {replica: [notes]}}``
    — a score ≥ 1.0 is page-worthy (``numerics_divergence``)."""
    from collections import Counter

    names = [n for n, d in sorted(numerics.items()) if isinstance(d, dict)]
    score = {n: 0.0 for n in names}
    evidence: "dict[str, list]" = {n: [] for n in names}

    for n in names:
        d = numerics[n]
        fails = int(d.get("failures") or 0)
        if fails > 0:
            score[n] += 1.0
            evidence[n].append(f"self-reported {fails} canary failure(s)")
        if d.get("fenced"):
            score[n] += 1.0
            evidence[n].append("numerics fence latched")
        lc, cc = d.get("load_checksum"), d.get("params_checksum")
        if lc and cc and lc != cc:
            score[n] += 1.0
            evidence[n].append(
                f"params checksum drifted: {cc} (loaded {lc})"
            )

    def _vote(groups: dict, weight: float, what: str) -> None:
        for key, members in sorted(groups.items()):
            members = {n: v for n, v in members.items() if v}
            if len(members) < 2:
                continue
            counts = Counter(members.values())
            if len(counts) <= 1:
                continue
            top, topn = counts.most_common(1)[0]
            if topn * 2 > len(members):  # strict majority names minority
                for n, v in sorted(members.items()):
                    if v != top:
                        score[n] += weight
                        evidence[n].append(
                            f"{what}{key}: {v} vs majority {top}"
                        )
            else:  # split fleet: surfaced, never scored
                for n in sorted(members):
                    evidence[n].append(
                        f"{what}{key}: no majority "
                        f"({dict(sorted(counts.items()))})"
                    )

    _vote(
        {"": {n: numerics[n].get("params_checksum") for n in names}},
        1.0, "checksum",
    )
    exact: "dict[tuple, dict]" = {}
    quant: "dict[str, dict]" = {}
    for n in names:
        for b, ref in sorted((numerics[n].get("buckets") or {}).items()):
            fp = ref.get("fingerprint")
            if fp:
                exact.setdefault((b, fp), {})[n] = ref.get("digest")
            quant.setdefault(b, {})[n] = ref.get("qdigest")
    _vote(exact, 1.0, "canary digest @bucket,fingerprint ")
    _vote(quant, 0.4, "canary qdigest @bucket ")
    return {"score": score, "evidence": evidence}


class _MergedMetricView:
    """Read-only metric protocol (``kind`` / ``snapshot_series()`` /
    ``value()`` / ``buckets``) over one merged-snapshot entry, so SLO
    cumulative math (:func:`mpi4dl_tpu.telemetry.slo.cumulative_sli`)
    reads federated metrics exactly like local ones."""

    def __init__(self, name: str, entry: dict):
        self.name = name
        self.kind = entry["type"]
        self.help = entry.get("help", "")
        self.labelnames = tuple(entry.get("labels", ()))
        self._series = entry["series"]

    def snapshot_series(self) -> "list[dict]":
        return [dict(s) for s in self._series]

    def value(self, **labels) -> "float | None":
        want = {k: str(v) for k, v in labels.items()}
        for s in self._series:
            if s["labels"] == want:
                return s["value"]
        return None

    @property
    def buckets(self) -> tuple:
        for s in self._series:
            if "buckets" in s:
                return tuple(
                    float(le) for le in s["buckets"] if le != "+Inf"
                )
        return ()


class FederatedRegistry:
    """A merged fleet snapshot layered over a real local registry.

    Write API (``counter``/``gauge``/``histogram``, i.e. everything
    :func:`mpi4dl_tpu.telemetry.declare` needs) delegates to the local
    registry — the aggregator's own meta-metrics and a fleet-level SLO
    evaluator's ``slo_*`` gauges live there. Read API (``snapshot`` /
    ``get`` / ``names``) returns the union, LOCAL WINNING on a name
    clash: a fleet-level ``slo_burn_rate`` shadows the per-replica ones
    (whose label shape it couldn't share anyway).
    """

    def __init__(self, local: "MetricsRegistry | None" = None):
        self.local = local if local is not None else MetricsRegistry()
        self._lock = threading.Lock()
        self._merged: dict = {}

    # -- write API (delegated) ------------------------------------------------

    def counter(self, *a, **kw):
        return self.local.counter(*a, **kw)

    def gauge(self, *a, **kw):
        return self.local.gauge(*a, **kw)

    def histogram(self, *a, **kw):
        return self.local.histogram(*a, **kw)

    # -- merged state ---------------------------------------------------------

    def set_merged(self, merged: dict) -> None:
        with self._lock:
            self._merged = merged

    def snapshot(self) -> dict:
        with self._lock:
            merged = dict(self._merged)
        out = {
            name: {k: v for k, v in entry.items()}
            for name, entry in merged.items()
        }
        out.update(self.local.snapshot())  # local wins
        return out

    def get(self, name: str):
        local = self.local.get(name)
        if local is not None:
            return local
        with self._lock:
            entry = self._merged.get(name)
        return None if entry is None else _MergedMetricView(name, entry)

    def names(self) -> "list[str]":
        with self._lock:
            merged = set(self._merged)
        return sorted(merged | set(self.local.names()))


class ReplicaTarget:
    """One scrape target: a replica's telemetry base URL + scrape state."""

    def __init__(self, name: str, base_url: str):
        if not _REPLICA_RE.match(name) or name in ROLLUPS:
            raise ValueError(
                f"invalid replica name {name!r} (reserved: {ROLLUPS})"
            )
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.snapshot: "dict | None" = None
        self.numerics: "dict | None" = None
        self.pid: "int | None" = None
        self.last_ok_ts: "float | None" = None
        self.last_error: "str | None" = None
        self.consecutive_failures = 0

    def state(self) -> dict:
        return {
            "name": self.name,
            "url": self.base_url,
            "pid": self.pid,
            "up": self.consecutive_failures == 0
            and self.snapshot is not None,
            "last_ok_ts": self.last_ok_ts,
            "last_error": self.last_error,
            "consecutive_failures": self.consecutive_failures,
        }


class FederatedAggregator:
    """Scrape N replicas' ``/snapshotz``, merge, re-expose, evaluate.

    replicas: ``{name: base_url}`` (e.g. ``{"r0":
        "http://127.0.0.1:9100"}``); more via :meth:`add_replica`.
    registry: the LOCAL registry for the aggregator's own metrics
        (``federation_replicas``, ``federation_scrapes_total``) and any
        fleet-level evaluator output; None creates a private one. The
        merged view lives on :attr:`registry` (a
        :class:`FederatedRegistry` wrapping it).
    slo: a :class:`~mpi4dl_tpu.telemetry.slo.SLOConfig` — runs the SAME
        :class:`SLOEvaluator` + :class:`Autoscaler` the engine embeds,
        but over the federated view: fleet-wide burn rates, alerts, and
        a desired-replica count derived from the summed queue depth.
    queue_capacity: the fleet's total queue bound for autoscale
        thresholds (sum of the replicas' ``max_queue``).
    interval_s / timeout_s: scrape cadence (daemon thread via
        :meth:`start`) and per-replica HTTP timeout.
    straggler_factor / straggler_min_count: fleet straggler detection
        (:func:`replica_skew`): every scrape scores each replica's own
        e2e p99 against the fleet median and publishes the cataloged
        ``fleet_replica_skew{replica=}`` gauge; a replica whose skew
        reaches ``straggler_factor`` trips the advisory
        ``replica_straggler`` page (stock :class:`AlertState` →
        ``alert_active`` + ``alert.transition`` naming the replica, on
        ``/alertz``). The default factor (4.0) is TWO default-histogram
        buckets of separation: bucket-resolved p99s are quantized and
        adjacent default bounds sit 2-2.5x apart, so any factor ≤2.5
        would page on one-bucket noise between healthy replicas.
        ``straggler_factor=None`` disables the alert (the gauge still
        publishes). Replicas with fewer than ``straggler_min_count``
        served observations are not scored.
    unreachable_after: consecutive failed ``/snapshotz`` scrapes of any
        replica before the ``replica_unreachable`` page fires (the
        availability page a killed replica trips). ≥2 so one transient
        timeout does not page; ``None`` disables the alert.
    events: optional :class:`JsonlWriter` for ``alert.transition``
        events (the straggler page's paper trail).
    incidents: build the stock :class:`IncidentManager` riding this
        aggregator's alert surface (default on): every scrape tick also
        steps the incident lifecycle, and :meth:`serve` exposes
        ``/incidentz``. ``False`` for an aggregator that only merges.
    clock: injectable for deterministic tests (drives the evaluator's
        snapshot ring too).
    """

    def __init__(
        self,
        replicas: "dict[str, str] | None" = None,
        registry: "MetricsRegistry | None" = None,
        slo=None,
        queue_capacity: int = 64,
        interval_s: float = 1.0,
        timeout_s: float = 2.0,
        straggler_factor: "float | None" = 4.0,
        straggler_min_count: int = 20,
        unreachable_after: "int | None" = 2,
        events=None,
        incidents: bool = True,
        clock=time.monotonic,
        start: bool = False,
    ):
        from mpi4dl_tpu import telemetry

        self.registry = FederatedRegistry(local=registry)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._clock = clock
        self._targets: "dict[str, ReplicaTarget]" = {}
        self._lock = threading.Lock()
        self.conflicts: "list[str]" = []
        self._events = events
        self._m_replicas = telemetry.declare(
            self.registry, "federation_replicas"
        )
        self._m_scrapes = telemetry.declare(
            self.registry, "federation_scrapes_total"
        )
        self._m_replicas.set(0, state="configured")
        self._m_replicas.set(0, state="up")
        # Straggler detection: gauge + advisory alert machinery.
        self.straggler_factor = (
            float(straggler_factor) if straggler_factor is not None else None
        )
        self.straggler_min_count = int(straggler_min_count)
        self._m_skew = telemetry.declare(self.registry, "fleet_replica_skew")
        self._m_alert = telemetry.declare(self.registry, "alert_active")
        self.straggler_alert = telemetry.AlertState(
            "replica_straggler", "page", for_s=0.0
        )
        self._m_alert.set(
            0.0, alert=self.straggler_alert.name,
            severity=self.straggler_alert.severity,
        )
        self.last_skew: dict = {}
        self.straggler_transitions: "list[dict]" = []
        # Numerics-divergence detection (telemetry/canary.py): every
        # scrape scores each replica's numerics payload — self-reported
        # canary failures/fence/checksum drift + cross-replica checksum
        # and canary-digest votes (:func:`numerics_skew`) — publishes
        # the cataloged ``fleet_numerics_skew{replica=}`` gauge, and a
        # score ≥ 1.0 trips the ``numerics_divergence`` page naming the
        # corrupt replica. Stock AlertState, same shape as the
        # straggler page — the correctness analog of the latency one.
        self._m_numerics = telemetry.declare(
            self.registry, "fleet_numerics_skew"
        )
        self.numerics_alert = telemetry.AlertState(
            "numerics_divergence", "page", for_s=0.0
        )
        self._m_alert.set(
            0.0, alert=self.numerics_alert.name,
            severity=self.numerics_alert.severity,
        )
        self.last_numerics: dict = {}
        self.numerics_transitions: "list[dict]" = []
        # Availability detection: a replica whose /snapshotz scrape has
        # failed ``unreachable_after`` consecutive rounds is DOWN as far
        # as the fleet can tell — the page a killed replica trips (and
        # the one an elastic.restart later explains on the incident
        # timeline). Stock AlertState, same shape as the other two.
        self.unreachable_after = (
            int(unreachable_after) if unreachable_after is not None else None
        )
        self.unreachable_alert = telemetry.AlertState(
            "replica_unreachable", "page", for_s=0.0
        )
        self._m_alert.set(
            0.0, alert=self.unreachable_alert.name,
            severity=self.unreachable_alert.severity,
        )
        self.unreachable_transitions: "list[dict]" = []
        for name, url in (replicas or {}).items():
            self.add_replica(name, url)

        self.slo = None
        self.autoscaler = None
        if slo is not None:
            objectives = slo.objectives()
            if objectives:
                self.autoscaler = telemetry.Autoscaler(
                    registry=self.registry,
                    config=slo.autoscale,
                    queue_capacity=queue_capacity,
                    clock=clock,
                )
                self.slo = telemetry.SLOEvaluator(
                    registry=self.registry,
                    objectives=objectives,
                    config=slo,
                    autoscaler=self.autoscaler,
                    clock=clock,
                    start=False,  # the aggregator's tick drives it
                )

        # The incident engine rides this aggregator's alert surface by
        # default: the same scrape tick that moves an alert to firing
        # opens (or folds into / closes) the incident one line later.
        self.incidents = None
        if incidents:
            from mpi4dl_tpu.telemetry.incident import IncidentManager

            self.incidents = IncidentManager(
                self.alertz_state,
                registry=self.registry,
                events=self._events,
                source="federation",
            )

        self.server = None
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None
        if start:
            self.start()

    # -- replica set ----------------------------------------------------------

    def add_replica(self, name: str, base_url: str) -> ReplicaTarget:
        t = ReplicaTarget(name, base_url)
        with self._lock:
            self._targets[name] = t
            self._m_replicas.set(len(self._targets), state="configured")
        return t

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._targets.pop(name, None)
            self._m_replicas.set(len(self._targets), state="configured")

    def replicas(self) -> "list[ReplicaTarget]":
        with self._lock:
            return list(self._targets.values())

    # -- scraping + merging ---------------------------------------------------

    def scrape_once(self, now: "float | None" = None) -> dict:
        """One federation tick: scrape every replica's ``/snapshotz``,
        merge, refresh the federated view (+ the fleet SLO evaluation
        when configured). A failed scrape keeps the replica's LAST
        snapshot in the merge (counters freeze rather than dropping to
        zero, which would read as a restart) and counts the error."""
        now = self._clock() if now is None else float(now)
        up = 0
        for t in self.replicas():
            try:
                with urllib.request.urlopen(
                    t.base_url + "/snapshotz", timeout=self.timeout_s
                ) as resp:
                    payload = json.loads(resp.read().decode())
                t.snapshot = payload["metrics"]
                t.numerics = payload.get("numerics")
                t.pid = payload.get("pid")
                t.last_ok_ts = now
                t.last_error = None
                t.consecutive_failures = 0
                self._m_scrapes.inc(replica=t.name, outcome="ok")
            except Exception as e:  # noqa: BLE001 — a down replica is a
                # data point, not an aggregator crash
                t.last_error = f"{type(e).__name__}: {e}"
                t.consecutive_failures += 1
                self._m_scrapes.inc(replica=t.name, outcome="error")
            if t.consecutive_failures == 0 and t.snapshot is not None:
                up += 1
        children = {
            t.name: t.snapshot
            for t in self.replicas()
            if t.snapshot is not None
        }
        merged, conflicts = merge_snapshots(children)
        self.registry.set_merged(merged)
        self.conflicts = conflicts
        self._m_replicas.set(up, state="up")
        self._evaluate_straggler(children, now)
        self._evaluate_numerics(now)
        self._evaluate_unreachable(now)
        if self.slo is not None:
            try:
                self.slo.evaluate_once(now)
            except Exception:  # noqa: BLE001 — fleet evaluation is a
                pass  # sidecar; the scrape loop must survive it
        if self.incidents is not None:
            self.incidents.step()
        return merged

    def _evaluate_straggler(self, children: dict, now: float) -> None:
        """Per-replica skew scoring + the advisory ``replica_straggler``
        page. Scored from the RAW per-replica snapshots (the merge
        collapses the histograms), published on the aggregator's local
        registry so the gauge scrapes with the merged view."""
        skew = replica_skew(children, min_count=self.straggler_min_count)
        self.last_skew = skew
        for name, v in skew["skew"].items():
            self._m_skew.set(v, replica=name)
        if self.straggler_factor is None:
            return
        worst = max(
            skew["skew"], key=lambda n: skew["skew"][n], default=None
        )
        active = (
            worst is not None
            and skew["skew"][worst] >= self.straggler_factor
        )
        st = self.straggler_alert
        moved = st.step(active, now)
        self._m_alert.set(
            1.0 if st.state == "firing" else 0.0,
            alert=st.name, severity=st.severity,
        )
        if moved is None:
            return
        ev = {
            "ts": time.time(),
            "kind": "event",
            "name": "alert.transition",
            "attrs": {
                "alert": st.name,
                "severity": st.severity,
                "from": moved[0],
                "to": moved[1],
                # The page names its suspect: WHICH replica drags the
                # fleet tail, by how much, against what baseline.
                "replica": worst,
                "skew": skew["skew"].get(worst) if worst else None,
                "replica_p99_s": skew["p99"].get(worst) if worst else None,
                "fleet_median_p99_s": skew["median_p99"],
                "factor": self.straggler_factor,
            },
        }
        self.straggler_transitions.append(ev)
        del self.straggler_transitions[:-64]
        if self._events is not None and getattr(self._events, "enabled", False):
            self._events.write(ev)

    def _evaluate_numerics(self, now: float) -> None:
        """Cross-replica correctness comparison + the
        ``numerics_divergence`` page (see :func:`numerics_skew`)."""
        numerics = {
            t.name: t.numerics
            for t in self.replicas()
            if t.numerics is not None
        }
        skew = numerics_skew(numerics)
        self.last_numerics = skew
        for name, v in skew["score"].items():
            self._m_numerics.set(v, replica=name)
        worst = max(
            skew["score"], key=lambda n: skew["score"][n], default=None
        )
        active = worst is not None and skew["score"][worst] >= 1.0
        st = self.numerics_alert
        moved = st.step(active, now)
        self._m_alert.set(
            1.0 if st.state == "firing" else 0.0,
            alert=st.name, severity=st.severity,
        )
        if moved is None:
            return
        ev = {
            "ts": time.time(),
            "kind": "event",
            "name": "alert.transition",
            "attrs": {
                "alert": st.name,
                "severity": st.severity,
                "from": moved[0],
                "to": moved[1],
                # The page names its suspect: WHICH replica serves (or
                # would serve) wrong answers, on what evidence.
                "replica": worst,
                "score": skew["score"].get(worst) if worst else None,
                "evidence": skew["evidence"].get(worst) if worst else None,
            },
        }
        self.numerics_transitions.append(ev)
        del self.numerics_transitions[:-64]
        if self._events is not None and getattr(self._events, "enabled", False):
            self._events.write(ev)

    def _evaluate_unreachable(self, now: float) -> None:
        """The ``replica_unreachable`` availability page: fires while
        any replica's consecutive failed scrapes reach the threshold;
        resolves as soon as every configured replica answers again
        (a respawned successor re-registers its new port on ready)."""
        if self.unreachable_after is None:
            return
        targets = self.replicas()
        down = sorted(
            t.name for t in targets
            if t.consecutive_failures >= self.unreachable_after
        )
        worst = max(
            (t for t in targets if t.name in down),
            key=lambda t: t.consecutive_failures,
            default=None,
        )
        st = self.unreachable_alert
        moved = st.step(bool(down), now)
        self._m_alert.set(
            1.0 if st.state == "firing" else 0.0,
            alert=st.name, severity=st.severity,
        )
        if moved is None:
            return
        ev = {
            "ts": time.time(),
            "kind": "event",
            "name": "alert.transition",
            "attrs": {
                "alert": st.name,
                "severity": st.severity,
                "from": moved[0],
                "to": moved[1],
                # The page names its suspect: WHICH replica stopped
                # answering, for how many rounds, with the last error.
                "replica": worst.name if worst else None,
                "down": down,
                "consecutive_failures": (
                    worst.consecutive_failures if worst else None
                ),
                "last_error": worst.last_error if worst else None,
                "threshold": self.unreachable_after,
            },
        }
        self.unreachable_transitions.append(ev)
        del self.unreachable_transitions[:-64]
        if self._events is not None and getattr(self._events, "enabled", False):
            self._events.write(ev)

    # -- surfaces -------------------------------------------------------------

    def health_snapshot(self) -> dict:
        """Aggregated fleet health (the federated ``/healthz`` source):
        healthy iff every configured replica scraped OK last round."""
        targets = [t.state() for t in self.replicas()]
        down = [t["name"] for t in targets if not t["up"]]
        return {
            "healthy": not down and bool(targets),
            "reason": "ok" if not down else f"replicas down: {down}",
            "replicas": targets,
        }

    def state(self) -> dict:
        return {
            "replicas": [t.state() for t in self.replicas()],
            "conflicts": list(self.conflicts),
            "interval_s": self.interval_s,
            "straggler": self.straggler_state(),
            "numerics": self.numerics_state(),
            "unreachable": self.unreachable_state(),
            "slo": self.slo.state() if self.slo is not None else None,
        }

    def straggler_state(self) -> dict:
        return {
            "factor": self.straggler_factor,
            "min_count": self.straggler_min_count,
            "skew": dict(self.last_skew.get("skew", {})),
            "p99": dict(self.last_skew.get("p99", {})),
            "median_p99_s": self.last_skew.get("median_p99"),
            "alert": self.straggler_alert.snapshot(),
            "transitions": list(self.straggler_transitions)[-20:],
        }

    def numerics_state(self) -> dict:
        return {
            "score": dict(self.last_numerics.get("score", {})),
            "evidence": {
                k: list(v)
                for k, v in self.last_numerics.get("evidence", {}).items()
            },
            "alert": self.numerics_alert.snapshot(),
            "transitions": list(self.numerics_transitions)[-20:],
        }

    def unreachable_state(self) -> dict:
        return {
            "threshold": self.unreachable_after,
            "down": [
                t.name for t in self.replicas()
                if self.unreachable_after is not None
                and t.consecutive_failures >= self.unreachable_after
            ],
            "alert": self.unreachable_alert.snapshot(),
            "transitions": list(self.unreachable_transitions)[-20:],
        }

    def alertz_state(self) -> dict:
        """The fleet ``/alertz`` payload: the SLO evaluator's state (when
        configured) with the straggler alert folded into the same
        ``alerts`` / ``transitions`` lists — one page surface, one
        runbook shape."""
        base = (
            self.slo.state() if self.slo is not None
            else {"slos": [], "alerts": [], "transitions": [],
                  "phase_attribution": None, "autoscale": None}
        )
        base["alerts"] = list(base.get("alerts", ())) + [
            self.straggler_alert.snapshot(),
            self.numerics_alert.snapshot(),
            self.unreachable_alert.snapshot(),
        ]
        base["transitions"] = (
            list(base.get("transitions", ()))
            + list(self.straggler_transitions)[-20:]
            + list(self.numerics_transitions)[-20:]
            + list(self.unreachable_transitions)[-20:]
        )
        base["straggler"] = self.straggler_state()
        base["numerics"] = self.numerics_state()
        base["unreachable"] = self.unreachable_state()
        return base

    def serve(self, port: int = 0, host: str = "127.0.0.1"):
        """Expose the federated view as its own scrape surface
        (``/metrics`` + ``/snapshotz`` over the merged registry — so
        federation composes hierarchically — ``/healthz`` from the
        aggregated replica health, ``/debugz`` with scrape state, and
        ``/alertz``: the fleet SLO state when configured, always the
        straggler alert)."""
        from mpi4dl_tpu.telemetry.export import MetricsServer

        self.server = MetricsServer(
            self.registry, port=port, host=host,
            health=self.health_snapshot,
            debug=self.state,
            alerts=self.alertz_state,
            incidents=(
                self.incidents.state if self.incidents is not None else None
            ),
        )
        return self.server

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="mpi4dl-federation", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — keep scraping
                pass

    def close(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.incidents is not None:
            self.incidents.close()
        if self.server is not None:
            self.server.close()
            self.server = None


# -- trace export (python -m mpi4dl_tpu.analyze trace-export) -----------------


def _collect_events(paths) -> "list[dict]":
    """Span events from JSONL files and/or directories of ``*.jsonl``
    (telemetry logs, flight dumps — any file in the event schema)."""
    import os

    from mpi4dl_tpu.telemetry.jsonl import read_events

    files: "list[str]" = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(
                os.path.join(p, f) for f in sorted(os.listdir(p))
                if f.endswith(".jsonl")
            )
        else:
            files.append(p)
    events: "list[dict]" = []
    for f in files:
        events.extend(read_events(f))
    return events


def trace_export_main(argv=None) -> int:
    """``python -m mpi4dl_tpu.analyze trace-export LOG... [--trace-id ID]
    [-o OUT]`` — join span segments from N processes' JSONL logs by
    trace id and write one Chrome trace (chrome://tracing / Perfetto).
    Pure JSON: no jax, no devices — safe before any backend setup."""
    import argparse
    import sys

    from mpi4dl_tpu.telemetry.spans import chrome_trace, group_spans_by_trace

    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.analyze trace-export",
        description="Export federated request traces as a Chrome trace",
    )
    p.add_argument("logs", nargs="+",
                   help="JSONL telemetry logs / flight dumps, or "
                        "directories of them (N processes' logs join)")
    p.add_argument("--trace-id", default=None,
                   help="export one request's full cross-process "
                        "lifetime (default: every trace in the logs)")
    p.add_argument("--list", action="store_true",
                   help="list trace ids (with span/process counts) "
                        "instead of exporting")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: stdout)")
    args = p.parse_args(argv)

    events = _collect_events(args.logs)
    groups = group_spans_by_trace(events)
    if args.list:
        for tid in sorted(groups):
            evs = groups[tid]
            pids = sorted({e.get("attrs", {}).get("pid", 0) for e in evs})
            spans = sum(len(e["spans"]) for e in evs)
            print(
                f"{tid}  segments={len(evs)} spans={spans} "
                f"pids={','.join(str(x) for x in pids)}"
            )
        print(f"# {len(groups)} trace(s)", file=sys.stderr)
        return 0
    doc = chrome_trace(events, trace_id=args.trace_id)
    n = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    if n == 0:
        print(
            "trace-export: no matching span events"
            + (f" for trace id {args.trace_id!r}" if args.trace_id else ""),
            file=sys.stderr,
        )
        return 1
    text = json.dumps(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    else:
        print(text)
    pids = {e["pid"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    print(
        f"# trace-export: {n} span(s) across {len(pids)} process(es)"
        + (f" -> {args.out}" if args.out else ""),
        file=sys.stderr,
    )
    return 0


# -- standalone aggregator CLI ------------------------------------------------


def main(argv=None) -> int:
    """``python -m mpi4dl_tpu.telemetry.federation`` — run an aggregator
    over N replica endpoints and re-serve the merged fleet view."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="python -m mpi4dl_tpu.telemetry.federation",
        description="Federated telemetry aggregator over replica "
                    "/snapshotz endpoints",
    )
    p.add_argument("--replica", action="append", default=[],
                   metavar="NAME=URL", required=True,
                   help="replica to scrape, e.g. "
                        "r0=http://127.0.0.1:9100 (repeatable)")
    p.add_argument("--port", type=int, default=0,
                   help="serve the federated /metrics + /snapshotz here "
                        "(0 = ephemeral, echoed on stderr)")
    p.add_argument("--interval", type=float, default=1.0,
                   help="scrape cadence, seconds")
    p.add_argument("--timeout", type=float, default=2.0,
                   help="per-replica scrape timeout, seconds")
    p.add_argument("--once", action="store_true",
                   help="scrape once, print the merged snapshot JSON to "
                        "stdout, exit (nonzero if any replica failed)")
    args = p.parse_args(argv)

    replicas = {}
    for spec in args.replica:
        name, sep, url = spec.partition("=")
        if not sep:
            p.error(f"--replica must be NAME=URL, got {spec!r}")
        replicas[name] = url
    agg = FederatedAggregator(
        replicas=replicas, interval_s=args.interval, timeout_s=args.timeout,
    )
    if args.once:
        merged = agg.scrape_once()
        print(json.dumps({"ts": time.time(), "kind": "metrics",
                          "metrics": merged}))
        health = agg.health_snapshot()
        print(f"# {health['reason']}", file=sys.stderr)
        return 0 if health["healthy"] else 1
    agg.serve(port=args.port)
    print(
        f"# federation: http://127.0.0.1:{agg.server.port}/metrics "
        f"(merged; also /snapshotz, /healthz, /debugz) — scraping "
        f"{len(replicas)} replica(s) every {args.interval:g}s",
        file=sys.stderr, flush=True,
    )
    agg.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0
    finally:
        agg.close()


if __name__ == "__main__":  # pragma: no cover — exercised via tests
    import sys

    sys.exit(main())
