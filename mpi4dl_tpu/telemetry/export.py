"""Prometheus text exposition + the stdlib HTTP scrape endpoint.

:func:`render_prometheus` serializes a :class:`MetricsRegistry` in the
Prometheus text format (version 0.0.4): ``# HELP`` / ``# TYPE`` headers,
escaped label values, and for histograms the cumulative ``_bucket{le=}``
series plus ``_sum``/``_count``. :class:`MetricsServer` serves it from a
daemon ``http.server`` thread — stdlib only (the container must not need
``prometheus_client``), opt-in via ``ServingEngine(metrics_port=...)`` or
``python -m mpi4dl_tpu.serve --metrics-port`` (port 0 binds an ephemeral
port, reported back on :attr:`MetricsServer.port`).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from mpi4dl_tpu.telemetry.registry import MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def escape_help(text: str) -> str:
    r"""HELP-line escaping: backslash and newline."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def escape_label_value(text: str) -> str:
    r"""Label-value escaping: backslash, double-quote, newline."""
    return (
        text.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _fmt_value(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _labels_str(labels: dict, extra: "dict | None" = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(str(v))}"' for k, v in merged.items()
    )
    return "{" + inner + "}"


def render_prometheus(registry: MetricsRegistry) -> str:
    lines: list[str] = []
    for snap_name, m in registry.snapshot().items():
        if m["help"]:
            lines.append(f"# HELP {snap_name} {escape_help(m['help'])}")
        lines.append(f"# TYPE {snap_name} {m['type']}")
        for s in m["series"]:
            if m["type"] == "histogram":
                for le, cum in s["buckets"].items():
                    lines.append(
                        f"{snap_name}_bucket"
                        f"{_labels_str(s['labels'], {'le': le})} {cum}"
                    )
                lines.append(
                    f"{snap_name}_sum{_labels_str(s['labels'])} "
                    f"{_fmt_value(s['sum'])}"
                )
                lines.append(
                    f"{snap_name}_count{_labels_str(s['labels'])} "
                    f"{s['count']}"
                )
            else:
                lines.append(
                    f"{snap_name}{_labels_str(s['labels'])} "
                    f"{_fmt_value(s['value'])}"
                )
    return "\n".join(lines) + "\n"


class MetricsServer:
    """``/metrics`` scrape endpoint on a daemon thread.

    Binds immediately in the constructor (so an in-use port fails loudly at
    startup, not on the first scrape); ``port=0`` picks an ephemeral port,
    readable from :attr:`port`.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self.registry = registry
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = render_prometheus(server.registry).encode()
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mpi4dl-metrics-server",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
