"""Prometheus text exposition + the stdlib HTTP scrape endpoint.

:func:`render_prometheus` serializes a :class:`MetricsRegistry` in the
Prometheus text format (version 0.0.4): ``# HELP`` / ``# TYPE`` headers,
escaped label values, and for histograms the cumulative ``_bucket{le=}``
series plus ``_sum``/``_count``; buckets carrying an exemplar render the
OpenMetrics ``# {trace_id="..."} value ts`` suffix (docs/OBSERVABILITY.md
"Tail forensics"). :class:`MetricsServer` serves it from a
daemon ``http.server`` thread — stdlib only (the container must not need
``prometheus_client``), opt-in via ``ServingEngine(metrics_port=...)`` or
``python -m mpi4dl_tpu.serve --metrics-port`` (port 0 binds an ephemeral
port, reported back on :attr:`MetricsServer.port`).

Routes: ``/metrics`` scrapes the registry; ``/snapshotz`` serves the same
registry state as machine-readable JSON — a schema-valid ``metrics`` event
(:func:`mpi4dl_tpu.telemetry.jsonl.metrics_event`) plus the emitting
``pid``, the endpoint the federation aggregator
(:mod:`mpi4dl_tpu.telemetry.federation`) scrapes so child→parent merges
never round-trip through text-format parsing; ``/`` returns a small text
index of the endpoints this server actually has (an operator probing the
port discovers the surface instead of guessing paths); with providers
attached, ``/healthz`` answers 200/503 from a
:class:`mpi4dl_tpu.telemetry.HealthState` snapshot (the load-balancer /
uptime probe), ``/debugz`` serves the live diagnostic payload (flight
recorder tail, watchdog state, latest attribution), ``/alertz``
serves the SLO evaluator's alert/burn/budget state, and ``/incidentz``
the incident engine's open/recent incidents (correlated timelines,
first causes, blast radii). ``HEAD`` mirrors
``GET`` status/headers without a body — probes get 200, not 501 — and
non-GET/HEAD methods get 405.
"""

from __future__ import annotations

import json
import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from mpi4dl_tpu.telemetry.jsonl import metrics_event
from mpi4dl_tpu.telemetry.registry import MetricsRegistry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def escape_help(text: str) -> str:
    r"""HELP-line escaping: backslash and newline."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def escape_label_value(text: str) -> str:
    r"""Label-value escaping: backslash, double-quote, newline."""
    return (
        text.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPE_MAP = {"\\": "\\", "n": "\n", '"': '"'}


def _unescape(text: str) -> str:
    # Single left-to-right pass: 'a\\nb' is backslash+n (literal), not a
    # newline — sequential str.replace calls get exactly that case wrong,
    # which is why these exist as the tested inverse of the escapers.
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPE_MAP.get(m.group(1), m.group(0)), text
    )


def unescape_help(text: str) -> str:
    r"""Inverse of :func:`escape_help` (``\\`` → backslash, ``\n`` →
    newline; anything else passes through untouched)."""
    return _unescape(text)


def unescape_label_value(text: str) -> str:
    r"""Inverse of :func:`escape_label_value`."""
    return _unescape(text)


def _fmt_value(v: float) -> str:
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _labels_str(labels: dict, extra: "dict | None" = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(str(v))}"' for k, v in merged.items()
    )
    return "{" + inner + "}"


def _exemplar_suffix(ex: "dict | None") -> str:
    """OpenMetrics exemplar suffix for a ``_bucket`` sample line:
    ``# {trace_id="..."} value timestamp`` — the scrape-side link from a
    latency bucket to the concrete request that most recently landed in
    it. Empty when the bucket has none."""
    if not ex:
        return ""
    tid = escape_label_value(str(ex["trace_id"]))
    return (
        f' # {{trace_id="{tid}"}} {_fmt_value(ex["value"])} '
        f"{_fmt_value(ex['ts'])}"
    )


def render_prometheus(registry: MetricsRegistry) -> str:
    lines: list[str] = []
    for snap_name, m in registry.snapshot().items():
        if m["help"]:
            lines.append(f"# HELP {snap_name} {escape_help(m['help'])}")
        lines.append(f"# TYPE {snap_name} {m['type']}")
        for s in m["series"]:
            if m["type"] == "histogram":
                exemplars = s.get("exemplars", {})
                for le, cum in s["buckets"].items():
                    lines.append(
                        f"{snap_name}_bucket"
                        f"{_labels_str(s['labels'], {'le': le})} {cum}"
                        f"{_exemplar_suffix(exemplars.get(le))}"
                    )
                lines.append(
                    f"{snap_name}_sum{_labels_str(s['labels'])} "
                    f"{_fmt_value(s['sum'])}"
                )
                lines.append(
                    f"{snap_name}_count{_labels_str(s['labels'])} "
                    f"{s['count']}"
                )
            else:
                lines.append(
                    f"{snap_name}{_labels_str(s['labels'])} "
                    f"{_fmt_value(s['value'])}"
                )
    return "\n".join(lines) + "\n"


class MetricsServer:
    """``/metrics`` (+ ``/`` index, optional ``/healthz``, ``/debugz``,
    ``/alertz``) endpoint on a daemon thread.

    Binds immediately in the constructor (so an in-use port fails loudly at
    startup, not on the first scrape); ``port=0`` picks an ephemeral port,
    readable from :attr:`port`.

    health: zero-arg callable returning a dict with a boolean
        ``"healthy"`` key (``HealthState.snapshot``); ``/healthz`` then
        serves it as JSON with status 200/503. Without it ``/healthz``
        is 404 like any unknown path.
    debug: zero-arg callable returning a JSON-serializable diagnostic
        payload for ``/debugz`` (flight-recorder tail, watchdog state,
        latest attribution summary).
    alerts: zero-arg callable returning the SLO/alert state payload for
        ``/alertz`` (``SLOEvaluator.state``).
    incidents: zero-arg callable returning the incident-engine payload
        for ``/incidentz`` (``IncidentManager.state``): open/recent
        incidents with their correlated timelines, first-cause
        candidates, and blast radii.
    numerics: zero-arg callable returning the numerics-sentinel payload
        (``CanaryState.view``): embedded as the ``numerics`` key of
        ``/snapshotz``, so the federation's existing snapshot scrape
        carries the params checksum + canary digests with no extra
        round trip.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        port: int = 0,
        host: str = "127.0.0.1",
        health=None,
        debug=None,
        alerts=None,
        numerics=None,
        incidents=None,
    ):
        self.registry = registry
        self.health = health
        self.debug = debug
        self.alerts = alerts
        self.numerics = numerics
        self.incidents = incidents
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _payload(self):
                """(status, content-type, body) for GET/HEAD routing."""
                path = self.path.split("?")[0]
                if path == "/":
                    return (200, "text/plain; charset=utf-8",
                            server._index().encode())
                if path == "/metrics":
                    return (200, CONTENT_TYPE,
                            render_prometheus(server.registry).encode())
                if path == "/snapshotz":
                    snap = metrics_event(server.registry)
                    snap["pid"] = os.getpid()
                    if server.numerics is not None:
                        snap["numerics"] = server.numerics()
                    return (200, "application/json",
                            json.dumps(snap).encode())
                if path == "/healthz" and server.health is not None:
                    snap = dict(server.health())
                    status = 200 if snap.get("healthy") else 503
                    return (status, "application/json",
                            json.dumps(snap).encode())
                if path == "/debugz" and server.debug is not None:
                    return (200, "application/json",
                            json.dumps(server.debug(), default=str).encode())
                if path == "/alertz" and server.alerts is not None:
                    return (200, "application/json",
                            json.dumps(server.alerts(), default=str).encode())
                if path == "/incidentz" and server.incidents is not None:
                    return (200, "application/json",
                            json.dumps(server.incidents(),
                                       default=str).encode())
                return (404, "text/plain; charset=utf-8", b"not found\n")

            def _respond(self, send_body: bool):
                try:
                    status, ctype, body = self._payload()
                except Exception as e:  # noqa: BLE001 — a broken debug
                    # provider must answer 500, not kill the connection
                    status, ctype = 500, "text/plain; charset=utf-8"
                    body = f"provider error: {e}\n".encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if send_body:
                    self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                self._respond(send_body=True)

            def do_HEAD(self):  # noqa: N802 — LB/uptime probes use HEAD;
                self._respond(send_body=False)  # 501 would page someone

            def _method_not_allowed(self):
                self.send_error(405, "Method Not Allowed")

            # Observability endpoints are read-only: writes are a client
            # bug, answered 405 (wrong method) rather than 404 (no such
            # path) or 501 (server can't).
            do_POST = _method_not_allowed  # noqa: N815
            do_PUT = _method_not_allowed  # noqa: N815
            do_DELETE = _method_not_allowed  # noqa: N815
            do_PATCH = _method_not_allowed  # noqa: N815
            do_OPTIONS = _method_not_allowed  # noqa: N815

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mpi4dl-metrics-server",
            daemon=True,
        )
        self._thread.start()

    def _index(self) -> str:
        """The ``/`` endpoint index: only routes this server actually
        answers (operators probing the port discover the surface)."""
        lines = [
            "mpi4dl_tpu telemetry endpoints:",
            "  /metrics  Prometheus text exposition (0.0.4)",
            "  /snapshotz  registry snapshot as JSON (metrics-event "
            "schema + pid; the federation scrape surface)",
        ]
        if self.health is not None:
            lines.append("  /healthz  liveness JSON, 200 healthy / 503 not")
        if self.debug is not None:
            lines.append(
                "  /debugz   diagnostics JSON (stats, watchdog, flight tail)"
            )
        if self.alerts is not None:
            lines.append(
                "  /alertz   SLO + alert state JSON (burn rates, budgets)"
            )
        if self.incidents is not None:
            lines.append(
                "  /incidentz  incident engine JSON (timelines, first "
                "cause, blast radius)"
            )
        return "\n".join(lines) + "\n"

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
