// Native host-side data runtime: multithreaded synthetic batch synthesis and
// NHWC tile slicing.
//
// Role: the reference leans on torchvision DataLoader worker processes for
// host-side data work (benchmark_amoebanet_sp.py:264-306 uses FakeData /
// ImageFolder with --num-workers); at 2048px+ a single-threaded producer
// stalls the accelerator. This library does the hot host work — filling
// large float32 image batches and slicing spatial tiles — with a thread pool
// and SIMD-friendly inner loops, exposed to Python over ctypes (no pybind11
// in the image). The GIL is released for the whole call by construction
// (ctypes drops it around foreign calls).
//
// Determinism: counter-based RNG (splitmix64 per 64-bit lane) keyed on
// (seed, element index), so the produced stream is independent of the thread
// count — a property the tests pin.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

// 2^-24 scaling of the top 24 bits -> uniform float32 in [0, 1).
inline float u01(uint64_t bits) {
  return static_cast<float>(bits >> 40) * (1.0f / 16777216.0f);
}

void parallel_for(int64_t n, int num_threads, void (*body)(int64_t, int64_t, void*),
                  void* ctx) {
  if (num_threads < 1) num_threads = 1;
  if (n <= 0) return;
  int64_t chunk = (n + num_threads - 1) / num_threads;
  std::vector<std::thread> pool;
  for (int t = 0; t < num_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([=] { body(lo, hi, ctx); });
  }
  for (auto& th : pool) th.join();
}

struct FillCtx {
  float* out;
  uint64_t seed;
};

struct LabelCtx {
  int32_t* out;
  uint64_t seed;
  int32_t num_classes;
};

struct TileCtx {
  const float* src;
  float* dst;
  int64_t b, h, w, c;
  int64_t th, tw;   // tile grid
  int64_t ti, tj;   // this tile's coordinates
};

}  // namespace

extern "C" {

// Fill out[0..n) with deterministic uniform [0,1) floats. The stream is the
// splitmix64 output sequence starting at a per-seed offset: seeds that are
// numerically close (consecutive batch indices) still get statistically
// independent streams, unlike a plain `seed ^ i` keying where two batches
// would contain permutations of the same values.
void mpi4dl_fill_uniform(float* out, int64_t n, uint64_t seed, int num_threads) {
  FillCtx ctx{out, splitmix64(seed)};
  parallel_for(
      n, num_threads,
      [](int64_t lo, int64_t hi, void* p) {
        auto* c = static_cast<FillCtx*>(p);
        for (int64_t i = lo; i < hi; ++i) {
          c->out[i] = u01(splitmix64(
              c->seed + static_cast<uint64_t>(i) * 0x9E3779B97F4A7C15ull));
        }
      },
      &ctx);
}

// Fill out[0..n) with deterministic labels in [0, num_classes).
void mpi4dl_fill_labels(int32_t* out, int64_t n, uint64_t seed,
                        int32_t num_classes, int num_threads) {
  LabelCtx ctx{out, splitmix64(~seed), num_classes};
  parallel_for(
      n, num_threads,
      [](int64_t lo, int64_t hi, void* p) {
        auto* c = static_cast<LabelCtx*>(p);
        for (int64_t i = lo; i < hi; ++i) {
          uint64_t r = splitmix64(
              c->seed + static_cast<uint64_t>(i) * 0x9E3779B97F4A7C15ull);
          c->out[i] = static_cast<int32_t>(r % static_cast<uint64_t>(c->num_classes));
        }
      },
      &ctx);
}

// Copy tile (ti, tj) of an NHWC image batch into dst
// [b, h/th, w/tw, c], contiguous. Row-major tile grid — the same layout as
// split_input (reference train_spatial.py:241-290).
void mpi4dl_slice_tile(const float* src, float* dst, int64_t b, int64_t h,
                       int64_t w, int64_t c, int64_t th, int64_t tw, int64_t ti,
                       int64_t tj, int num_threads) {
  TileCtx ctx{src, dst, b, h, w, c, th, tw, ti, tj};
  int64_t hh = h / th;
  // Parallelize over (batch, tile-row) pairs.
  parallel_for(
      b * hh, num_threads,
      [](int64_t lo, int64_t hi, void* p) {
        auto* t = static_cast<TileCtx*>(p);
        int64_t hh = t->h / t->th, ww = t->w / t->tw;
        int64_t row_bytes = ww * t->c;
        for (int64_t i = lo; i < hi; ++i) {
          int64_t bi = i / hh, r = i % hh;
          const float* s = t->src +
                           ((bi * t->h + t->ti * hh + r) * t->w + t->tj * ww) * t->c;
          float* d = t->dst + (bi * hh + r) * row_bytes;
          std::memcpy(d, s, static_cast<size_t>(row_bytes) * sizeof(float));
        }
      },
      &ctx);
}

int mpi4dl_version() { return 1; }

}  // extern "C"
