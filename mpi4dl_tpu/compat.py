"""Version shims over the installed jax.

The package is written against the current jax API surface
(``jax.shard_map`` with ``check_vma``, ``lax.axis_size``,
``jax_num_cpu_devices``); runtimes in the field pin older releases where
those names live elsewhere or don't exist (0.4.x ships ``shard_map`` under
``jax.experimental`` with ``check_rep``, no ``lax.axis_size``, and CPU
device-count control only through ``XLA_FLAGS``). Every such seam is
resolved HERE, once — modules import :func:`shard_map` / :func:`axis_size`
/ :func:`set_cpu_devices` from this module instead of guessing per call
site. Nothing here changes semantics on a current jax: when the native
name exists it is re-exported untouched.
"""

from __future__ import annotations

import inspect
import os

__all__ = [
    "axis_size",
    "distributed_is_initialized",
    "optimization_barrier",
    "put_on_device",
    "put_on_host",
    "set_cpu_devices",
    "shard_map",
]


try:  # jax >= 0.6: a public top-level function
    from jax import shard_map as _shard_map_impl
except ImportError:  # 0.4.x: experimental module, check_rep instead of check_vma
    from jax.experimental.shard_map import shard_map as _shard_map_impl

if "check_vma" in inspect.signature(_shard_map_impl).parameters:
    shard_map = _shard_map_impl
else:

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        """``jax.shard_map`` call shape on the 0.4.x experimental impl
        (``check_vma`` was named ``check_rep`` there; same meaning)."""
        if check_vma is not None:
            kw.setdefault("check_rep", check_vma)
        return _shard_map_impl(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )


try:  # jax >= 0.4.4x
    from jax.lax import axis_size
except ImportError:
    import jax._src.core as _jax_core

    def axis_size(axis_name) -> int:
        """Static size of a named mesh axis inside ``shard_map`` — on
        0.4.x ``core.axis_frame(name)`` resolves to the bound int."""
        return _jax_core.axis_frame(axis_name)


def _jax_version() -> tuple:
    import jax

    return tuple(int(p) for p in jax.__version__.split(".")[:2])


if _jax_version() >= (0, 5):
    from jax.lax import optimization_barrier
else:
    import jax as _jax
    from jax import lax as _lax

    @_jax.custom_vjp
    def optimization_barrier(x):
        """0.4.x shipped ``lax.optimization_barrier`` without an AD rule;
        wrap it so the barrier applies to the forward value AND to the
        cotangent (what the newer native transpose rule does) — it stays
        a pure scheduling fence in both passes."""
        return _lax.optimization_barrier(x)

    def _ob_fwd(x):
        return _lax.optimization_barrier(x), None

    def _ob_bwd(_, ct):
        return (_lax.optimization_barrier(ct),)

    optimization_barrier.defvjp(_ob_fwd, _ob_bwd)


def _memory_transfers():
    """(to_host, to_device) single-array transfer fns: ``jax.memory.Space``
    on current jax, ``TransferToMemoryKind`` (same placement semantics,
    sharding-preserving) on 0.4.x."""
    import jax

    space = getattr(getattr(jax, "memory", None), "Space", None)
    if space is not None:
        return (
            lambda a: jax.device_put(a, space.Host),
            lambda a: jax.device_put(a, space.Device),
        )
    from jax._src.sharding_impls import TransferToMemoryKind

    return (
        lambda a: jax.device_put(a, TransferToMemoryKind("pinned_host")),
        lambda a: jax.device_put(a, TransferToMemoryKind("device")),
    )


def put_on_host(a):
    return _memory_transfers()[0](a)


def put_on_device(a):
    return _memory_transfers()[1](a)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized`` (absent on 0.4.x: probe the
    global client state instead, same truth)."""
    import jax

    if hasattr(jax.distributed, "is_initialized"):
        return jax.distributed.is_initialized()
    try:
        from jax._src import distributed as _dist

        state = getattr(_dist, "global_state", None)
        return bool(state is not None and state.client is not None)
    except Exception:  # noqa: BLE001 — internals moved: assume uninitialized
        return False


def set_cpu_devices(n: int) -> None:
    """Request ``n`` virtual CPU devices, before first backend use.

    New jax has a real config knob; on 0.4.x the only channel is the
    ``--xla_force_host_platform_device_count`` XLA flag, which is read at
    backend initialization — so this works only if called before the first
    ``jax.devices()``-like call (the same contract the config knob has).
    """
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flags = os.environ.get("XLA_FLAGS", "")
        flag = f"--xla_force_host_platform_device_count={n}"
        if "--xla_force_host_platform_device_count" in flags:
            import re

            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", flag, flags
            )
        else:
            flags = f"{flags} {flag}".strip()
        os.environ["XLA_FLAGS"] = flags
