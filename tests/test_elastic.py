"""Fault tolerance: supervised restart-from-checkpoint (mpi4dl_tpu/elastic.py).

The reference has no failure handling — a dead rank hangs the MPI world
(SURVEY §5.3). These tests cover the supervisor's two detectors (nonzero
exit, stale heartbeat) with trivial no-JAX workers, then the real
benchmark path end-to-end: a training run crash-injected mid-epoch must be
restarted by ``--max-restarts`` and resume from the checkpoint it left.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from mpi4dl_tpu import elastic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker(tmp_path, body: str) -> str:
    path = tmp_path / "worker.py"
    path.write_text(textwrap.dedent(body))
    return str(path)


def test_supervise_restarts_on_crash_and_appends_resume(tmp_path):
    marker = tmp_path / "state.txt"
    w = _worker(
        tmp_path,
        f"""
        import sys
        # Crash on the fresh run; succeed once restarted with --resume.
        if "--resume" not in sys.argv:
            sys.exit(3)
        open({str(marker)!r}, "w").write(" ".join(sys.argv[1:]))
        """,
    )
    msgs = []
    rc = elastic.supervise(
        [w], max_restarts=2, poll_interval=0.05, _print=msgs.append
    )
    assert rc == 0
    assert marker.read_text() == "--resume"
    assert any("restarting (1/2)" in m for m in msgs)
    assert any("completed after 1 restart" in m for m in msgs)


def test_supervise_gives_up_after_max_restarts(tmp_path):
    w = _worker(tmp_path, "raise SystemExit(7)")
    msgs = []
    rc = elastic.supervise(
        [w], max_restarts=2, resume_arg=None, poll_interval=0.05,
        _print=msgs.append,
    )
    assert rc == 7
    assert any("giving up after 2 restart(s)" in m for m in msgs)


@pytest.mark.slow
def test_supervise_kills_wedged_child_on_stale_heartbeat(tmp_path, monkeypatch):
    hb = tmp_path / "heartbeat"
    w = _worker(
        tmp_path,
        """
        import os, sys, time
        if "--resume" not in sys.argv:
            # Heartbeat once, then wedge (a deadlocked collective never
            # exits on its own — only staleness can catch it).
            os.utime(os.environ["MPI4DL_TPU_HEARTBEAT"], None)
            time.sleep(3600)
        """,
    )
    msgs = []
    rc = elastic.supervise(
        [w],
        max_restarts=1,
        # Interpreter startup alone is ~2s in this image (site plugins);
        # the timeout must cover it or the healthy restarted child is
        # killed as "wedged" before it can exit.
        hang_timeout=8.0,
        heartbeat_path=str(hb),
        poll_interval=0.1,
        _print=msgs.append,
    )
    assert rc == 0
    assert any("killing wedged child" in m for m in msgs)
    assert any("wedged — restarting" in m for m in msgs)


def test_hang_timeout_requires_heartbeat():
    with pytest.raises(ValueError):
        elastic.supervise(["x.py"], hang_timeout=5.0)


def test_heartbeat_reporter_gated_on_health(tmp_path):
    """ISSUE satellite, unit level: beats happen while healthy, stop the
    moment the health state flips (or the watchdog trips), resume on
    recovery — the silence the supervisor's staleness detector needs."""
    from mpi4dl_tpu import telemetry

    hb = tmp_path / "heartbeat"
    health = telemetry.HealthState()
    wd = telemetry.Watchdog(min_timeout_s=60.0, start=False)
    r = elastic.HeartbeatReporter(str(hb), health=health, watchdog=wd)
    assert r.beat_once() and hb.exists()
    os.utime(hb, (0, 0))
    health.set_unhealthy("batcher crashed")
    assert not r.beat_once()
    assert os.path.getmtime(hb) == 0  # untouched while unhealthy
    health.set_healthy()
    assert r.beat_once()
    assert os.path.getmtime(hb) > 0
    # A tripped watchdog silences beats even with healthy unset state.
    wd.begin()
    wd.seed(0.001)
    assert wd.check(now=1e9) is not None  # force the trip
    os.utime(hb, (0, 0))
    assert not r.beat_once()
    assert os.path.getmtime(hb) == 0


def test_supervise_restarts_replica_wedged_behind_live_threads(
    tmp_path, monkeypatch
):
    """ISSUE satellite, fault drill: a serving-shaped replica whose
    batcher wedges while its OTHER threads stay alive. An unconditional
    heartbeat would stay fresh forever; the health-gated
    HeartbeatReporter goes silent when the watchdog trips, so
    supervise() kills the wedged process and the restarted one
    completes."""
    # supervise() inherits our env; the worker imports mpi4dl_tpu from
    # the repo (APPEND, as in the end-to-end test below — the TPU
    # runtime delivers its plugin via PYTHONPATH).
    monkeypatch.setenv(
        "PYTHONPATH", REPO + os.pathsep + os.environ.get("PYTHONPATH", "")
    )
    hb = tmp_path / "heartbeat"
    w = _worker(
        tmp_path,
        """
        import os, sys, time
        from mpi4dl_tpu import elastic, telemetry
        if "--resume" in sys.argv:
            sys.exit(0)  # the restarted replica is healthy
        health = telemetry.HealthState()
        wd = telemetry.Watchdog(
            factor=1.0, min_timeout_s=0.3, poll_s=0.05, health=health,
        )
        hr = elastic.HeartbeatReporter(
            os.environ[elastic.HEARTBEAT_ENV], health=health,
            watchdog=wd, interval_s=0.05,
        )
        hr.start()
        wd.begin()        # work admitted...
        time.sleep(3600)  # ...and the loop wedges; threads stay alive
        """,
    )
    msgs = []
    rc = elastic.supervise(
        [w],
        max_restarts=1,
        # Covers interpreter + package import (~2s in this image) with
        # margin; the watchdog trips at 0.3s, so the beats are silent
        # long before this expires.
        hang_timeout=6.0,
        heartbeat_path=str(hb),
        poll_interval=0.1,
        _print=msgs.append,
    )
    assert rc == 0
    assert any("killing wedged child" in m for m in msgs)
    assert any("wedged — restarting" in m for m in msgs)


def test_maybe_supervise_noop_without_flag_or_in_child(monkeypatch):
    class A:
        max_restarts = 0

    elastic.maybe_supervise(A())  # returns (no sys.exit)
    monkeypatch.setenv(elastic.CHILD_ENV, "1")
    A.max_restarts = 3
    elastic.maybe_supervise(A())  # child: also a no-op


@pytest.mark.slow
def test_benchmark_crash_resume_end_to_end(tmp_path):
    """Real path: benchmark_resnet_lp crash-injected at step 2 restarts
    under --max-restarts and resumes from the step-2 checkpoint."""
    ckpt = tmp_path / "ckpt"
    env = dict(
        os.environ,
        # APPEND to PYTHONPATH: on the TPU runtime the accelerator plugin
        # itself is delivered via PYTHONPATH (/root/.axon_site) and a
        # replacement would silently knock the backend out.
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        MPI4DL_TPU_CRASH_AT_STEP="2",
        MPI4DL_TPU_CONV_IMPL="xla",
        JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jaxcache"),
    )
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(
                REPO, "benchmarks", "layer_parallelism", "benchmark_resnet_lp.py"
            ),
            "--batch-size", "2", "--image-size", "8", "--num-epochs", "1",
            "--max-steps", "4", "--precision", "fp32",
            "--checkpoint-dir", str(ckpt), "--checkpoint-every", "1",
            "--max-restarts", "2",
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=1800,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "restarting (1/2)" in out.stdout
    assert "resumed from step 2" in out.stdout
    # Fresh run: 2 steps then crash (checkpoint at step 2); resumed run
    # honors the restored step as done work and trains ONLY the remaining
    # 2 of the 4 requested steps -> newest checkpoint is step 4, not 6.
    steps = sorted(d for d in os.listdir(ckpt) if d.startswith("step_"))
    meta = json.load(open(os.path.join(ckpt, steps[-1], "meta.json")))
    assert meta["step"] == 4
