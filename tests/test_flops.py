"""FLOP accounting tests: analytic counts on known-cost layers."""

import jax.numpy as jnp
import numpy as np

from mpi4dl_tpu.flops import forward_flops, mfu, train_flops_per_image
from mpi4dl_tpu.ops.fastconv import FastConv


def test_conv_flops_analytic():
    # 3x3 SAME conv, 8->16ch @ 32x32: 2 * H*W*O * KH*KW*Cin MACs-as-FLOPs.
    cell = FastConv(features=16, kernel_size=(3, 3), use_bias=False)
    got = forward_flops([cell], (1, 32, 32, 8))
    want = 2 * 32 * 32 * 16 * 3 * 3 * 8
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_flops_scale_with_batch_and_resolution():
    cell = FastConv(features=16, kernel_size=(3, 3), use_bias=False)
    f1 = forward_flops([cell], (1, 32, 32, 8))
    f2 = forward_flops([cell], (4, 32, 32, 8))
    f3 = forward_flops([cell], (1, 64, 64, 8))
    np.testing.assert_allclose(f2, 4 * f1, rtol=1e-6)
    np.testing.assert_allclose(f3, 4 * f1, rtol=1e-6)


def test_resnet_train_flops_sane():
    from mpi4dl_tpu.models.resnet import get_resnet_v2

    # depth 9n+2 → BOTTLENECK v2 blocks (3 convs, 4x expansion): much more
    # FLOPs than the classic basic-block CIFAR ResNet of the same depth.
    cells = get_resnet_v2(depth=20, num_classes=10, pool_kernel=8)
    fwd = train_flops_per_image(cells, 32) / 3
    assert 150e6 < fwd < 400e6, fwd
    # Quadratic in resolution.
    fwd2 = train_flops_per_image(cells, 64) / 3
    np.testing.assert_allclose(fwd2 / fwd, 4.0, rtol=0.05)


def test_mfu_none_off_tpu():
    assert mfu(10.0, 1e12) is None  # CPU test process: unknown peak


def test_flops_counted_inside_cond_branches():
    """FLOPs inside lax.cond branches must be counted (ADVICE r2: the
    recursion previously skipped the 'branches' tuple-of-jaxprs param,
    silently deflating the MFU denominator)."""
    import flax.linen as nn
    import jax

    class CondCell(nn.Module):
        @nn.compact
        def __call__(self, x):
            w = self.param(
                "w", nn.initializers.ones_init(), (x.shape[-1], 16), jnp.float32
            )
            return jax.lax.cond(
                x.sum() > 0, lambda: x @ w, lambda: (x * 2) @ w
            )

    got = forward_flops([CondCell()], (1, 4, 4, 8))
    want = 2 * 4 * 4 * 16 * 8  # one branch's matmul (max over branches)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_forward_flops_rejects_packed_cells():
    """MFU must be counted on the logical model: packed cells execute
    inflated scattered-kernel FLOPs and are rejected at trace time."""
    import pytest

    from mpi4dl_tpu.models.resnet import get_resnet_v2

    packed = get_resnet_v2(depth=20, layout="packed")
    with pytest.raises(ValueError, match="logical"):
        forward_flops(packed, (1, 32, 32, 3))
