"""SLO engine (:mod:`mpi4dl_tpu.telemetry.windows` / ``.slo`` /
``.alerts`` / ``.autoscale``): windowed rate/increase semantics on a fake
clock, hand-computed golden burn-rate values, the alert state machine's
pending/for-duration/resolve transitions, autoscaler hysteresis +
cooldown, schema-valid transition events — and the ISSUE fault drill: a
stalled batcher floods queue-full rejections, the fast-burn ``page``
alert fires on ``/alertz`` while the watchdog flips ``/healthz``,
``desired_replicas`` rises, and recovery resolves everything. CPU-only,
tier-1.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.telemetry.alerts import AlertState, SLOEvaluator
from mpi4dl_tpu.telemetry.autoscale import AutoscaleConfig, Autoscaler
from mpi4dl_tpu.telemetry.slo import (
    BurnWindow,
    SLOConfig,
    availability_objective,
    budget_remaining,
    burn_rate,
    latency_objective,
    resolve_bucket_bound,
    sli,
)
from mpi4dl_tpu.telemetry.windows import SnapshotWindow


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# -- snapshot window ----------------------------------------------------------


def _reg_with_counter():
    reg = telemetry.MetricsRegistry()
    return reg, telemetry.declare(reg, "serve_requests_total")


def test_window_rate_and_increase_golden():
    reg, c = _reg_with_counter()
    clock = _Clock()
    w = SnapshotWindow(reg, clock=clock)
    c.inc(100, outcome="served")
    w.record(0.0)
    c.inc(60, outcome="served")
    clock.t = 30.0
    w.record(30.0)
    assert w.increase("serve_requests_total", 30, outcome="served") == 60
    assert w.rate("serve_requests_total", 30, outcome="served") == (
        pytest.approx(2.0)
    )
    # A window longer than the history uses what exists (cold start).
    assert w.increase("serve_requests_total", 9999, outcome="served") == 60
    # One snapshot only -> no data.
    w2 = SnapshotWindow(reg, clock=clock)
    w2.record(0.0)
    assert w2.increase("serve_requests_total", 30, outcome="served") is None
    assert w2.rate("serve_requests_total", 30, outcome="served") is None


def test_window_availability_ignores_drained_outcomes():
    """ISSUE satellite: drained requests (deliberate stop/drain) leave
    the availability denominator entirely — a fleet scale-down neither
    helps nor hurts the SLI."""
    reg, c = _reg_with_counter()
    clock = _Clock()
    w = SnapshotWindow(reg, clock=clock)
    w.record(0.0)
    c.inc(9, outcome="served")
    c.inc(1, outcome="rejected_queue_full")
    c.inc(40, outcome="drained")
    clock.t = 30.0
    w.record(30.0)
    # Without the ignore set the 40 drains would crater the SLI to 0.18.
    assert w.availability(
        "serve_requests_total", 30, ("served",)
    ) == pytest.approx(9 / 50)
    assert w.availability(
        "serve_requests_total", 30, ("served",), ignore=("drained",)
    ) == pytest.approx(0.9)


def test_window_uses_at_least_the_requested_span():
    """With snapshots at 0/10/20/30 a 15s window must pair the newest
    with t=10 (latest at-or-before the cutoff), not t=20 — windows cover
    at least the requested span once history allows."""
    reg, c = _reg_with_counter()
    w = SnapshotWindow(reg, clock=_Clock())
    for t in (0.0, 10.0, 20.0, 30.0):
        c.inc(10, outcome="served")
        w.record(t)
    # t=10 snapshot holds 20, newest holds 40.
    assert w.increase("serve_requests_total", 15, outcome="served") == 20
    assert w.rate("serve_requests_total", 15, outcome="served") == (
        pytest.approx(1.0)  # 20 over the actual 20s elapsed
    )


def test_window_series_appearing_mid_window_baselines_at_zero():
    """The first rejected_queue_full of a process's life must count as an
    increase, not vanish because the old snapshot lacks the series."""
    reg, c = _reg_with_counter()
    w = SnapshotWindow(reg, clock=_Clock())
    c.inc(5, outcome="served")
    w.record(0.0)
    c.inc(3, outcome="rejected_queue_full")
    w.record(10.0)
    assert w.increase(
        "serve_requests_total", 60, outcome="rejected_queue_full"
    ) == 3
    incs = dict(
        (labels["outcome"], d)
        for labels, d in w.increases("serve_requests_total", 60)
    )
    assert incs == {"served": 0, "rejected_queue_full": 3}
    # The windowed availability ratio: 0 good / 3 total.
    assert w.availability(
        "serve_requests_total", 60, good=("served",)
    ) == 0.0


def test_window_counter_restart_returns_none():
    reg = telemetry.MetricsRegistry()
    g = reg.gauge("serve_queue_depth")  # raw registry: simulate via gauge
    c = reg.counter("ctr_total")
    w = SnapshotWindow(reg, clock=_Clock())
    c.inc(10)
    g.set(4)
    w.record(0.0)
    c._series[()] = 2.0  # counter restarted (new process would)
    g.set(8)
    w.record(10.0)
    assert w.increase("ctr_total", 60) is None
    assert w.mean_gauge("serve_queue_depth", 60) == pytest.approx(6.0)


def test_window_hist_increase_and_bucket_resolution():
    reg = telemetry.MetricsRegistry()
    h = telemetry.declare(reg, "serve_request_latency_seconds")
    w = SnapshotWindow(reg, clock=_Clock())
    w.record(0.0)
    for v in (0.01, 0.03, 0.2):
        h.observe(v)
    w.record(10.0)
    d = w.hist_increase("serve_request_latency_seconds", 60)
    assert d["count"] == 3
    assert d["buckets"]["0.05"] == 2  # cumulative: the two fast ones
    assert w.bucket_ratio(
        "serve_request_latency_seconds", 60, 0.05
    ) == pytest.approx(2 / 3)
    # Threshold between bounds resolves DOWN (conservative).
    assert resolve_bucket_bound((0.01, 0.05, 0.1), 0.07) == 0.05
    assert resolve_bucket_bound((0.01, 0.05, 0.1), 0.05) == 0.05
    assert resolve_bucket_bound((0.01, 0.05), 0.001) is None


# -- burn-rate golden values --------------------------------------------------


def _evaluated_registry():
    """Registry + window with one hand-computed traffic hour: 900 served
    + 100 rejected, 1000 latency observations of which 950 <= 50 ms."""
    reg = telemetry.MetricsRegistry()
    req = telemetry.declare(reg, "serve_requests_total")
    lat = telemetry.declare(reg, "serve_request_latency_seconds")
    w = SnapshotWindow(reg, clock=_Clock())
    w.record(0.0)
    req.inc(900, outcome="served")
    req.inc(100, outcome="rejected_queue_full")
    for i in range(1000):
        lat.observe(0.04 if i < 950 else 0.2)
    w.record(60.0)
    return reg, w


def test_burn_rate_golden_values():
    """Hand-computed: 10% errors at a 99.9% objective burn the budget at
    0.1/0.001 = 100x; 5% slow at a 99% latency objective burn at
    0.05/0.01 = 5x. Budget remaining: 1 - 100 = -99 (overspent)."""
    reg, w = _evaluated_registry()
    avail = availability_objective(0.999)
    lat = latency_objective(0.99, threshold_s=0.05)
    assert sli(w, avail, 60) == pytest.approx(0.9)
    assert burn_rate(w, avail, 60) == pytest.approx(100.0)
    assert sli(w, lat, 60) == pytest.approx(0.95)
    assert burn_rate(w, lat, 60) == pytest.approx(5.0)
    assert budget_remaining(reg, avail) == pytest.approx(-99.0)
    assert budget_remaining(reg, lat) == pytest.approx(-4.0)


def test_burn_rate_no_traffic_is_no_data():
    reg = telemetry.MetricsRegistry()
    telemetry.declare(reg, "serve_requests_total")
    w = SnapshotWindow(reg, clock=_Clock())
    w.record(0.0)
    w.record(60.0)
    avail = availability_objective(0.999)
    assert sli(w, avail, 60) is None
    assert burn_rate(w, avail, 60) is None
    assert budget_remaining(reg, avail) is None


def test_slo_config_validation():
    with pytest.raises(ValueError, match="0.999, not 99.9"):
        SLOConfig(availability=99.9).objectives()
    with pytest.raises(ValueError, match="less history"):
        SLOConfig(availability=0.999, window_capacity=10).objectives()
    assert SLOConfig().objectives() == []  # no objectives -> nothing to run
    assert len(SLOConfig(availability=0.99,
                         latency_threshold_s=0.05).objectives()) == 2


# -- alert state machine ------------------------------------------------------


def test_alert_state_machine_for_duration():
    a = AlertState("x", "page", for_s=2.0)
    assert a.step(False, 0.0) is None and a.state == "inactive"
    assert a.step(True, 1.0) == ("inactive", "pending")
    assert a.step(True, 2.0) is None  # held 1s < for 2s
    assert a.step(True, 3.5) == ("pending", "firing")
    assert a.fired_count == 1
    assert a.step(True, 4.0) is None  # stays firing, no re-fire
    assert a.step(False, 5.0) == ("firing", "inactive")  # resolved
    # pending that clears before for_s cancels without ever firing
    assert a.step(True, 10.0) == ("inactive", "pending")
    assert a.step(False, 11.0) == ("pending", "inactive")
    assert a.fired_count == 1


def test_alert_zero_for_fires_immediately():
    a = AlertState("x", "page", for_s=0.0)
    assert a.step(True, 1.0) == ("inactive", "firing")


# -- evaluator: gauges, transitions, schema -----------------------------------


def _drive_evaluator(for_s=0.0):
    reg = telemetry.MetricsRegistry()
    req = telemetry.declare(reg, "serve_requests_total")
    telemetry.declare(reg, "serve_request_latency_seconds")
    telemetry.declare(reg, "serve_queue_depth").set(0)
    clock = _Clock()
    cfg = SLOConfig(availability=0.999, for_s=for_s, interval_s=1.0)
    flight = telemetry.FlightRecorder(capacity=64, registry=reg)
    ev = SLOEvaluator(
        reg, cfg.objectives(), cfg,
        autoscaler=Autoscaler(
            reg, AutoscaleConfig(up_cooldown_s=1.0, down_cooldown_s=5.0,
                                 signal_window_s=30.0, max_replicas=3),
            queue_capacity=64, clock=clock,
        ),
        flight=flight, clock=clock, start=False,
    )
    return reg, req, clock, ev, flight


def test_evaluator_fires_resolves_and_publishes():
    reg, req, clock, ev, flight = _drive_evaluator()
    req.inc(10, outcome="served")
    ev.evaluate_once(0.0)
    # Clean traffic: burn 0, nothing fires, desired stays at min.
    req.inc(10, outcome="served")
    clock.t = 10.0
    ev.evaluate_once(10.0)
    assert ev.alerts["availability_fast_burn"].state == "inactive"
    assert reg.get("slo_burn_rate").value(
        slo="availability", window="fast_long", tenant="default"
    ) == 0.0
    assert reg.get("autoscale_desired_replicas").value() == 1

    # 100% failures: burn 1000 >> 14.4 on both windows -> page fires,
    # autoscaler sees rejections -> desired rises.
    req.inc(20, outcome="rejected_queue_full")
    clock.t = 20.0
    ev.evaluate_once(20.0)
    st = ev.alerts["availability_fast_burn"]
    assert st.state == "firing" and st.severity == "page"
    assert reg.get("alert_active").value(
        alert="availability_fast_burn", severity="page"
    ) == 1.0
    assert reg.get("slo_error_budget_remaining").value(
        slo="availability", tenant="default"
    ) < 0
    assert reg.get("autoscale_desired_replicas").value() == 2

    # Recovery: enough clean traffic that BOTH windows drop below the
    # factor (short clears first; the long window needs the errors to
    # age past its span).
    req.inc(5000, outcome="served")
    for t in (90.0, 100.0):
        clock.t = t
        ev.evaluate_once(t)
    assert ev.alerts["availability_fast_burn"].state == "inactive"
    assert reg.get("alert_active").value(
        alert="availability_fast_burn", severity="page"
    ) == 0.0

    # Transitions were recorded — schema-valid, in order, and into the
    # flight ring for the postmortem story.
    trans = [t for t in ev.transitions
             if t["attrs"]["alert"] == "availability_fast_burn"]
    assert [(t["attrs"]["from"], t["attrs"]["to"]) for t in trans] == [
        ("inactive", "firing"), ("firing", "inactive"),
    ]
    for t in trans:
        telemetry.validate_event(t)
    ring_names = [e.get("name") for e in flight.tail(100)]
    assert ring_names.count("alert.transition") >= 2
    v = ev.verdict()
    assert v["ok"] is False
    assert v["alerts_fired"]["availability_fast_burn"] == 1


def test_evaluator_for_duration_pending_then_firing():
    reg, req, clock, ev, _ = _drive_evaluator(for_s=15.0)
    req.inc(10, outcome="served")
    ev.evaluate_once(0.0)
    req.inc(50, outcome="rejected_queue_full")
    clock.t = 10.0
    ev.evaluate_once(10.0)
    assert ev.alerts["availability_fast_burn"].state == "pending"
    assert reg.get("alert_active").value(
        alert="availability_fast_burn", severity="page"
    ) == 0.0  # pending is not active
    req.inc(50, outcome="rejected_queue_full")
    clock.t = 30.0
    ev.evaluate_once(30.0)
    assert ev.alerts["availability_fast_burn"].state == "firing"


# -- autoscaler ---------------------------------------------------------------


def test_autoscaler_hysteresis_and_cooldown():
    reg = telemetry.MetricsRegistry()
    req = telemetry.declare(reg, "serve_requests_total")
    qd = telemetry.declare(reg, "serve_queue_depth")
    clock = _Clock()
    w = SnapshotWindow(reg, clock=clock)
    auto = Autoscaler(
        reg,
        AutoscaleConfig(min_replicas=1, max_replicas=3, queue_high=0.5,
                        queue_low=0.1, signal_window_s=30.0,
                        up_cooldown_s=10.0, down_cooldown_s=20.0),
        queue_capacity=64, clock=clock,
    )
    qd.set(0)
    w.record(0.0)
    assert auto.update(0.0, w, None) == 1

    # Deep queue -> pressure, but the up cooldown paces the steps.
    qd.set(40)  # > 0.5 * 64
    clock.t = 5.0
    w.record(5.0)
    assert auto.update(5.0, w, None) == 1  # 5s < up_cooldown since start
    clock.t = 12.0
    w.record(12.0)
    assert auto.update(12.0, w, None) == 2
    clock.t = 13.0
    w.record(13.0)
    assert auto.update(13.0, w, None) == 2  # cooldown again
    clock.t = 25.0
    w.record(25.0)
    assert auto.update(25.0, w, None) == 3
    clock.t = 40.0
    w.record(40.0)
    assert auto.update(40.0, w, None) == 3  # capped at max_replicas

    # Mid-band depth (between low 6.4 and high 32 watermarks): neither
    # pressure nor calm — the hysteresis dead zone holds the count.
    qd.set(10)
    for t in (75.0, 80.0, 85.0):
        clock.t = t
        w.record(t)
        assert auto.update(t, w, None) == 3

    # Calm (depth under the low watermark, no rejections, burn low) for
    # down_cooldown -> steps back down.
    qd.set(0)
    desired = []
    for t in (120.0, 130.0, 141.0, 150.0, 162.0):
        clock.t = t
        w.record(t)
        desired.append(auto.update(t, w, 0.0))
    assert desired[-1] < 3  # decayed
    assert 1 in desired or desired[-1] >= 1

    # Rejections in the window veto scale-down even at depth 0.
    auto2 = Autoscaler(
        reg, AutoscaleConfig(down_cooldown_s=0.0, up_cooldown_s=0.0,
                             signal_window_s=30.0),
        queue_capacity=64, clock=clock,
    )
    req.inc(3, outcome="rejected_queue_full")
    clock.t = 200.0
    w.record(200.0)
    before = auto2.desired
    auto2.update(200.0, w, None)
    assert auto2.desired >= before  # pressure, not calm


# -- the ISSUE fault drill ----------------------------------------------------


@pytest.fixture(scope="module")
def engine_parts():
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells

    size = 16
    cells = get_resnet_v2(depth=11, num_classes=10, pool_kernel=size // 4)
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )
    return cells, params, stats, size


def _drill_slo_config():
    """Windows scaled to test time: fast 2s/0.5s page, slow 6s/1.5s
    ticket; evaluator ticks at 10 Hz so 'within one evaluation interval'
    is sub-second."""
    return SLOConfig(
        availability=0.999,
        latency_threshold_s=5.0,  # loose: the drill is about availability
        burn_windows=(
            BurnWindow("fast", "page", long_s=2.0, short_s=0.5, factor=14.4),
            BurnWindow("slow", "ticket", long_s=6.0, short_s=1.5, factor=6.0),
        ),
        interval_s=0.1,
        autoscale=AutoscaleConfig(
            min_replicas=1, max_replicas=3, signal_window_s=1.0,
            up_cooldown_s=0.2, down_cooldown_s=0.5,
        ),
    )


def _get_json(url):
    return json.loads(urllib.request.urlopen(url, timeout=10).read())


def test_slo_fault_drill(engine_parts, tmp_path):
    """ISSUE acceptance: a stalled batcher + queue-full flood trips the
    watchdog AND fires the availability fast-burn page alert on /alertz;
    desired_replicas rises during the stall; recovery resolves the alert,
    decays the replica count, and the flight dump carries the alert
    transitions."""
    from mpi4dl_tpu.serve import QueueFullError, ServingEngine

    cells, params, stats, size = engine_parts
    eng = ServingEngine(
        cells, params, stats, example_shape=(size, size, 3), max_batch=2,
        max_queue=4, default_deadline_s=30.0, metrics_port=0,
        watchdog_factor=2.0, watchdog_min_timeout_s=0.25,
        flight_dir=str(tmp_path), slo=_drill_slo_config(),
    )
    base = f"http://127.0.0.1:{eng.metrics_port}"
    x = np.zeros((size, size, 3), np.float32)

    # Index satellite: probing the root discovers the whole surface.
    index = urllib.request.urlopen(base + "/", timeout=10).read().decode()
    for route in ("/metrics", "/healthz", "/debugz", "/alertz"):
        assert route in index

    alertz = _get_json(f"{base}/alertz")
    assert {a["name"] for a in alertz["alerts"]} == {
        "availability_fast_burn", "availability_slow_burn",
        "latency_fast_burn", "latency_slow_burn",
    }
    assert all(a["state"] == "inactive" for a in alertz["alerts"])

    # Stall the loop: every bucket executable sleeps well past the
    # watchdog timeout before doing the real work.
    orig = dict(eng._compiled)

    def _slow(bucket):
        def call(p, s, batch):
            time.sleep(1.5)
            return orig[bucket](p, s, batch)
        return call

    eng._compiled = {b: _slow(b) for b in eng.buckets}
    eng.start()
    try:
        stalled = eng.submit(x, deadline_s=30.0)
        rejections = 0
        deadline = time.time() + 15
        fired = saw_503 = False
        max_desired = 1.0
        while time.time() < deadline:
            # Flood: the 4-deep queue fills while the loop sleeps; every
            # further submit is a rejected_queue_full — the availability
            # SLI craters while the stall is still in progress.
            try:
                eng.submit(x, deadline_s=30.0)
            except QueueFullError:
                rejections += 1
            state = _get_json(f"{base}/alertz")
            fast = next(
                a for a in state["alerts"]
                if a["name"] == "availability_fast_burn"
            )
            max_desired = max(
                max_desired,
                state["autoscale"]["desired_replicas"],
            )
            fired = fired or fast["state"] == "firing"
            # The watchdog side of the drill: /healthz flips too (the
            # stall is also a liveness event, not just an SLO event).
            # Polled DURING the stall — health auto-recovers on the next
            # completion, so a post-hoc poll could miss the 503 phase.
            try:
                status = urllib.request.urlopen(
                    f"{base}/healthz", timeout=10
                ).status
            except urllib.error.HTTPError as e:
                status = e.code
            saw_503 = saw_503 or status == 503
            if fired and saw_503 and max_desired > 1:
                break
            time.sleep(0.02)
        assert rejections > 0, "queue never filled — no availability signal"
        assert fired, "fast-burn page alert never fired during the stall"
        assert saw_503, "watchdog never flipped /healthz during the stall"
        assert eng.registry.get("watchdog_trips_total").value() >= 1
        assert max_desired > 1, "autoscale signal never rose"

        # Recovery: stop flooding, let the stalled batches drain, serve
        # clean traffic until the burn windows clear and the alert
        # resolves.
        assert stalled.result(timeout=30).shape == (10,)
        deadline = time.time() + 30
        resolved = False
        while time.time() < deadline:
            try:
                eng.submit(x, deadline_s=30.0).result(timeout=30)
            except QueueFullError:
                time.sleep(0.1)
                continue
            state = _get_json(f"{base}/alertz")
            fast = next(
                a for a in state["alerts"]
                if a["name"] == "availability_fast_burn"
            )
            if fast["state"] == "inactive":
                resolved = True
                break
        assert resolved, "page alert never resolved after recovery"

        # ... and the advisory replica count decays once calm holds past
        # the down cooldown.
        deadline = time.time() + 30
        decayed = False
        while time.time() < deadline:
            try:
                eng.submit(x, deadline_s=30.0).result(timeout=30)
            except QueueFullError:
                time.sleep(0.05)
                continue
            if eng.registry.get(
                "autoscale_desired_replicas"
            ).value() == 1:
                decayed = True
                break
        assert decayed, "desired_replicas never decayed after recovery"
    finally:
        eng._compiled = orig
        eng.stop()

    # The postmortem story: a flight dump after the incident carries the
    # alert transitions next to the request spans.
    path = eng.dump_flight(reason="manual")
    events = telemetry.read_events(path)  # schema-validates every line
    trans = [e for e in events if e.get("name") == "alert.transition"]
    pairs = [
        (t["attrs"]["from"], t["attrs"]["to"]) for t in trans
        if t["attrs"]["alert"] == "availability_fast_burn"
    ]
    assert ("inactive", "firing") in pairs
    assert ("firing", "inactive") in pairs

    # /debugz carries the SLO state for one-stop diagnostics.
    v = eng.slo.verdict()
    assert v["alerts_fired"]["availability_fast_burn"] >= 1
    assert v["ok"] is False  # a page fired during this process's life
