"""Collective-inventory regression tests over compiled train-step HLO.

Multi-chip *performance* is unmeasurable on this runtime (one real chip),
but the communication *structure* is checkable: these tests compile the
distributed train step on the 8-virtual-CPU mesh and pin the exact count
of each collective op in the optimized HLO (VERDICT r4 next #7). A change
that, say, doubles per-layer halo traffic or adds a stray resharding
all-to-all fails here instead of silently shipping — the discipline the
reference enforces by construction with its per-layer explicit
isend/irecv pairs (``spatial.py:336-413``).

Counting rides the shared static analyzer (:mod:`mpi4dl_tpu.analysis`) —
the same inventory the ``python -m mpi4dl_tpu.analyze`` CLI and the bench
hook report, so the pin semantics cannot drift from the lint rules. On top
of the exact pins, each config runs the full rule engine and asserts no
error-severity findings (the tier-1 lint gate; the rules themselves are
unit-tested on canned HLO in ``tests/test_hlolint.py``).

If a test fails after an INTENTIONAL engine change: re-derive the counts
(the probe is just ``trainer._jit_step.lower(...).compile().as_text()``
through ``collective_inventory``), check the delta is explained by the
change, and update the pins in the same commit. NOTE: the all-reduce
count is fusion-dependent — XLA versions differ in how far they bundle
the per-parameter gradient all-reduces (the jax-0.4.37 runtime emits them
unfused: 37/57/17 where a 2025 jax emitted 2/11/7). The structural ops
(permute / gather / all-to-all / reduce-scatter) have been stable across
compiler versions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.analysis import (
    analyze_compiled,
    collective_inventory,
    compose,
)
from mpi4dl_tpu.config import ParallelConfig
from mpi4dl_tpu.models.resnet import get_resnet_v1
from mpi4dl_tpu.train import Trainer

OPS = (
    "collective-permute",
    "all-gather",
    "all-reduce",
    "all-to-all",
    "reduce-scatter",
)


def _batch(b, size):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, size, size, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(b,)), jnp.int32)
    return x, y


def _no_errors(report):
    errors = [f for f in report.findings if f["severity"] == "error"]
    assert not errors, errors


def test_pure_dp_inventory():
    """DP=2, no spatial: gradient/metrics all-reduces only — any permute,
    gather, or all-to-all means input/param sharding regressed. The same
    property is what the analyzer's pure-DP stray-resharding rule lints."""
    cfg = ParallelConfig(
        batch_size=4, split_size=1, spatial_size=0, image_size=32,
        data_parallel=2,
    )
    cells = get_resnet_v1(depth=8)
    tr = Trainer(cells, num_spatial_cells=0, config=cfg)
    state = tr.init(jax.random.PRNGKey(0), (4, 32, 32, 3))
    xs, ys = tr.shard_batch(*_batch(4, 32))
    compiled = tr._jit_step.lower(state, xs, ys).compile()
    inv = collective_inventory(compiled.as_text(), ops=OPS)
    assert inv == {
        "collective-permute": 0,
        "all-gather": 0,
        "all-reduce": 37,  # unfused per-param grad all-reduces + loss/acc
        "all-to-all": 0,
        "reduce-scatter": 0,
    }, inv
    _no_errors(analyze_compiled(
        compiled,
        expected=compose(tr.collective_deltas(state.params, (4, 32, 32, 3))),
    ))


def test_spatial_trainer_inventory():
    """SP 2×2 tiles, 3 spatial cells (5 halo-exchanged 3×3 convs: stem +
    2 CellV1 × 2). Halo traffic rides collective-permutes (4 shift
    ppermutes per exchange forward, partially deduped with the backward's
    transposed shifts by XLA); the SP→LP join is the tiled all_gather
    pair (value + the backward's re-gather)."""
    cfg = ParallelConfig(
        batch_size=4, split_size=1, spatial_size=1, num_spatial_parts=(4,),
        slice_method="square", image_size=32, data_parallel=1,
    )
    plain = get_resnet_v1(depth=8)
    cells = get_resnet_v1(depth=8, spatial_cells=3)
    tr = Trainer(cells, num_spatial_cells=3, config=cfg, plain_cells=plain)
    state = tr.init(jax.random.PRNGKey(0), (4, 32, 32, 3))
    xs, ys = tr.shard_batch(*_batch(4, 32))
    compiled = tr._jit_step.lower(state, xs, ys).compile()
    inv = collective_inventory(compiled.as_text(), ops=OPS)
    assert inv == {
        "collective-permute": 36,  # ~4/exchange fwd + bwd over 5 conv layers
        "all-gather": 2,  # tile join (fwd) + its backward re-gather
        "all-reduce": 57,  # cross-tile BN stats + per-param grads + loss/acc
        "all-to-all": 0,
        "reduce-scatter": 2,
    }, inv

    # Partition-math derivation (no hand pin): one un-scanned forward
    # traces 20 shift ppermutes (5 exchanges x 4 shifts on the 2x2 grid),
    # so the compiled count must land in [20, 40] — and the full rule set
    # must be clean on the real program. The gate is the COMPOSED spatial
    # delta, not a hand-built Expectations.
    shifts = tr.halo_shift_count(state.params, (4, 32, 32, 3))
    assert shifts == 20, shifts
    (delta,) = tr.collective_deltas(state.params, (4, 32, 32, 3))
    assert delta.layer == "spatial" and delta.halo_shifts == shifts
    report = analyze_compiled(compiled, expected=compose(delta))
    _no_errors(report)
    # The report carries per-collective bytes for every record.
    assert report.overlap["total_bytes"] > 0
    assert all(r["bytes_moved"] > 0 for r in report.collectives)


@pytest.mark.slow
def test_sp_plus_lp_pipeline_inventory():
    """SP front (2×2 tiles) + LP stage, parts=2 micro-batches: the
    pipeline's stage ppermutes ride the same collective-permute class as
    the halo shifts; the join all_gather pair and grad reductions must
    not multiply with the schedule."""
    from mpi4dl_tpu.parallel.pipeline import PipelineTrainer

    cfg = ParallelConfig(
        batch_size=4, parts=2, split_size=2, spatial_size=1,
        num_spatial_parts=(4,), slice_method="square", image_size=32,
        data_parallel=1,
    )
    plain = get_resnet_v1(depth=8)
    n_sp = PipelineTrainer.spatial_cell_count(len(plain), cfg)
    cells = get_resnet_v1(depth=8, spatial_cells=n_sp)
    tr = PipelineTrainer(cells, cfg, plain_cells=plain)
    state = tr.init(jax.random.PRNGKey(0))
    xs, ys = tr.shard_batch(*_batch(4, 32))
    compiled = tr._jit_step.lower(state, xs, ys).compile()
    inv = collective_inventory(compiled.as_text(), ops=OPS)
    assert inv == {
        "collective-permute": 20,
        "all-gather": 2,
        "all-reduce": 17,
        "all-to-all": 0,
        "reduce-scatter": 2,
    }, inv

    # The STACKED gate (the ROADMAP's composition item): the pipeline
    # trainer contributes a spatial front delta (traced front halo
    # shifts), the SP->LP join gather claim, and the exact stage-permute
    # budget; compose() folds them into one window the full rule set is
    # clean under — no hand-summed constants anywhere.
    deltas = tr.collective_deltas(state, (4, 32, 32, 3))
    assert [d.layer for d in deltas] == ["spatial", "spatial_join", "pipeline"]
    front_shifts = tr.halo_shift_count(state, (4, 32, 32, 3))
    assert front_shifts > 0
    expected = compose(deltas)
    assert expected.halo_shifts == front_shifts
    assert expected.extra_permutes == tr.stage_permute_count()
    assert expected.join_gathers == 2
    _no_errors(analyze_compiled(compiled, expected=expected))


def test_spatial_trainer_decomposed_overlap_keeps_permute_window(monkeypatch):
    """ISSUE 9 acceptance: under MPI4DL_TPU_CONV_OVERLAP=decomposed the
    SAME SP 2×2 program decomposes each spatial conv into interior +
    boundary strips, but halo_exchange still runs exactly once per conv —
    so the counted forward shifts are unchanged (20) and the compiled
    permute inventory must stay inside the partition-math window
    [shifts, 2*shifts]; the full rule set (halo-window included) must be
    clean on the decomposed program."""
    monkeypatch.setenv("MPI4DL_TPU_CONV_OVERLAP", "decomposed")
    cfg = ParallelConfig(
        batch_size=4, split_size=1, spatial_size=1, num_spatial_parts=(4,),
        slice_method="square", image_size=32, data_parallel=1,
    )
    plain = get_resnet_v1(depth=8)
    cells = get_resnet_v1(depth=8, spatial_cells=3)
    tr = Trainer(cells, num_spatial_cells=3, config=cfg, plain_cells=plain)
    state = tr.init(jax.random.PRNGKey(0), (4, 32, 32, 3))
    xs, ys = tr.shard_batch(*_batch(4, 32))

    shifts = tr.halo_shift_count(state.params, (4, 32, 32, 3))
    assert shifts == 20, shifts  # identical to the monolithic derivation

    compiled = tr._jit_step.lower(state, xs, ys).compile()
    inv = collective_inventory(compiled.as_text(), ops=OPS)
    assert shifts <= inv["collective-permute"] <= 2 * shifts, inv
    assert inv["all-to-all"] == 0
    assert inv["all-gather"] == 2  # tile join pair, unchanged

    report = analyze_compiled(
        compiled,
        expected=compose(tr.collective_deltas(state.params, (4, 32, 32, 3))),
    )
    _no_errors(report)
