"""Collective-inventory regression tests over compiled train-step HLO.

Multi-chip *performance* is unmeasurable on this runtime (one real chip),
but the communication *structure* is checkable: these tests compile the
distributed train step on the 8-virtual-CPU mesh and pin the exact count
of each collective op in the optimized HLO (VERDICT r4 next #7). A change
that, say, doubles per-layer halo traffic or adds a stray resharding
all-to-all fails here instead of silently shipping — the discipline the
reference enforces by construction with its per-layer explicit
isend/irecv pairs (``spatial.py:336-413``).

If a test fails after an INTENTIONAL engine change: re-derive the counts
(the probe is just ``trainer._jit_step.lower(...).compile().as_text()``),
check the delta is explained by the change, and update the pins in the
same commit.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.config import ParallelConfig
from mpi4dl_tpu.models.resnet import get_resnet_v1
from mpi4dl_tpu.train import Trainer

OPS = (
    "collective-permute",
    "all-gather",
    "all-reduce",
    "all-to-all",
    "reduce-scatter",
)


def _inventory(hlo: str) -> dict:
    # Opcode position: space-delimited, directly before its operand paren
    # (tuple result shapes contain spaces; operand uses like
    # ``get-tuple-element(%all-to-all.4)`` must not count).
    return {
        op: len(re.findall(rf" {op}(?:-start)?\(", hlo)) for op in OPS
    }


def _batch(b, size):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, size, size, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(b,)), jnp.int32)
    return x, y


def test_pure_dp_inventory():
    """DP=2, no spatial: gradient/metrics all-reduces only — any permute,
    gather, or all-to-all means input/param sharding regressed."""
    cfg = ParallelConfig(
        batch_size=4, split_size=1, spatial_size=0, image_size=32,
        data_parallel=2,
    )
    cells = get_resnet_v1(depth=8)
    tr = Trainer(cells, num_spatial_cells=0, config=cfg)
    state = tr.init(jax.random.PRNGKey(0), (4, 32, 32, 3))
    xs, ys = tr.shard_batch(*_batch(4, 32))
    inv = _inventory(tr._jit_step.lower(state, xs, ys).compile().as_text())
    assert inv == {
        "collective-permute": 0,
        "all-gather": 0,
        "all-reduce": 2,  # fused grad bundle + loss/acc psum
        "all-to-all": 0,
        "reduce-scatter": 0,
    }, inv


def test_spatial_trainer_inventory():
    """SP 2×2 tiles, 3 spatial cells (5 halo-exchanged 3×3 convs: stem +
    2 CellV1 × 2). Halo traffic rides collective-permutes (4 shift
    ppermutes per exchange forward, partially deduped with the backward's
    transposed shifts by XLA); the SP→LP join is the tiled all_gather
    pair (value + the backward's re-gather)."""
    cfg = ParallelConfig(
        batch_size=4, split_size=1, spatial_size=1, num_spatial_parts=(4,),
        slice_method="square", image_size=32, data_parallel=1,
    )
    plain = get_resnet_v1(depth=8)
    cells = get_resnet_v1(depth=8, spatial_cells=3)
    tr = Trainer(cells, num_spatial_cells=3, config=cfg, plain_cells=plain)
    state = tr.init(jax.random.PRNGKey(0), (4, 32, 32, 3))
    xs, ys = tr.shard_batch(*_batch(4, 32))
    inv = _inventory(tr._jit_step.lower(state, xs, ys).compile().as_text())
    assert inv == {
        "collective-permute": 36,  # ~4/exchange fwd + bwd over 5 conv layers
        "all-gather": 2,  # tile join (fwd) + its backward re-gather
        "all-reduce": 11,  # cross-tile BN stats + grad bundle + loss/acc
        "all-to-all": 0,
        "reduce-scatter": 2,
    }, inv


@pytest.mark.slow
def test_sp_plus_lp_pipeline_inventory():
    """SP front (2×2 tiles) + LP stage, parts=2 micro-batches: the
    pipeline's stage ppermutes ride the same collective-permute class as
    the halo shifts; the join all_gather pair and grad reductions must
    not multiply with the schedule."""
    from mpi4dl_tpu.parallel.pipeline import PipelineTrainer

    cfg = ParallelConfig(
        batch_size=4, parts=2, split_size=2, spatial_size=1,
        num_spatial_parts=(4,), slice_method="square", image_size=32,
        data_parallel=1,
    )
    plain = get_resnet_v1(depth=8)
    n_sp = PipelineTrainer.spatial_cell_count(len(plain), cfg)
    cells = get_resnet_v1(depth=8, spatial_cells=n_sp)
    tr = PipelineTrainer(cells, cfg, plain_cells=plain)
    state = tr.init(jax.random.PRNGKey(0))
    xs, ys = tr.shard_batch(*_batch(4, 32))
    inv = _inventory(tr._jit_step.lower(state, xs, ys).compile().as_text())
    assert inv == {
        "collective-permute": 20,
        "all-gather": 2,
        "all-reduce": 7,
        "all-to-all": 0,
        "reduce-scatter": 2,
    }, inv
