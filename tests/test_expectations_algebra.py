"""ISSUE 16 tentpole: the expectations algebra
(:mod:`mpi4dl_tpu.analysis.expectations`).

Three layers of pinning:

1. **Composition laws** on pure deltas — all-silent → single-chip gate,
   all-DP → pure-DP gate, silent∘communicating and conflicting tile
   grids are type errors, communicating stacks sum their windows/exact
   budgets/join claims.
2. **Program-surface coverage** (the satellite): every footprint-ledger
   program surface exposes ``collective_deltas()`` and its composition
   reproduces today's hand-derived budget byte-for-byte — train pure-DP,
   train SP, serve single-chip, serve sharded, serve tiled, and the
   pipeline schedules (gpipe exact-2, 1f1b exact-6 stage permutes).
   Construction-only: nothing compiles here (the compiled-HLO gates live
   in test_collective_inventory / test_pipeline_lens / the serve tests).
3. **No hand-summed budgets** (ast scan): outside
   ``mpi4dl_tpu/analysis/``, no package source constructs
   ``Expectations(...)`` directly — surfaces contribute deltas and
   ``compose()`` derives the gate, so a new parallelism layer cannot
   fork the budget math.
"""

import ast
import dataclasses
import os

import jax
import jax.numpy as jnp
import pytest

from mpi4dl_tpu.analysis.expectations import (
    CollectiveDelta,
    compose,
    data_parallel_delta,
    pipeline_delta,
    single_chip_delta,
    spatial_delta,
    spatial_join_delta,
    tiled_delta,
)
from mpi4dl_tpu.analysis.rules import Expectations

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- 1. composition laws ------------------------------------------------------

def test_all_silent_composes_to_single_chip_gate():
    """Byte-for-byte the former hardcoded literal — dataclass equality,
    every field at its default except single_chip."""
    for deltas in ([single_chip_delta()], [tiled_delta()],
                   [single_chip_delta(), tiled_delta()]):
        exp = compose(*deltas)
        assert dataclasses.asdict(exp) == dataclasses.asdict(
            Expectations(single_chip=True)
        )


def test_all_dp_composes_to_pure_dp_gate():
    exp = compose(data_parallel_delta())
    assert dataclasses.asdict(exp) == dataclasses.asdict(
        Expectations(pure_dp=True)
    )
    assert compose(data_parallel_delta(), data_parallel_delta()).pure_dp


def test_silent_with_communicating_is_a_type_error():
    with pytest.raises(ValueError, match="zero-collective"):
        compose(single_chip_delta(), spatial_delta((2, 2), 12))
    with pytest.raises(ValueError, match="zero-collective"):
        compose(tiled_delta(), pipeline_delta(2))


def test_conflicting_tile_grids_are_a_type_error():
    with pytest.raises(ValueError, match="grid|tile"):
        compose(spatial_delta((2, 2), 12), spatial_delta((4, 1), 8))


def test_communicating_stack_sums_windows_budgets_and_joins():
    exp = compose(
        spatial_delta((2, 2), 12),
        spatial_join_delta(2),
        pipeline_delta(6),
    )
    assert exp.tile_shape == (2, 2)
    assert exp.halo_shifts == 12
    assert exp.extra_permutes == 6
    assert exp.join_gathers == 2
    assert exp.single_chip is False and exp.pure_dp is False
    # DP rides along silently-on-the-permute-axis: it neither adds to
    # the window nor disables the claim.
    both = compose(spatial_delta((2, 2), 12), data_parallel_delta())
    assert both.halo_shifts == 12 and both.pure_dp is False


def test_compose_accepts_iterables_and_rejects_junk():
    deltas = (spatial_delta((2, 2), 12), pipeline_delta(2))
    assert compose(deltas) == compose(*deltas)
    with pytest.raises(ValueError):
        compose()
    with pytest.raises((TypeError, ValueError)):
        compose("not a delta")


def test_constructors_validate_and_describe():
    with pytest.raises(ValueError):
        spatial_delta((2, 2), -1)
    with pytest.raises(ValueError):
        pipeline_delta(-2)
    with pytest.raises(ValueError):
        spatial_join_delta(-1)
    d = spatial_delta((2, 2), 12)
    assert isinstance(d, CollectiveDelta) and d.layer == "spatial"
    assert "halo" in d.describe()


def test_join_gathers_default_is_none_not_zero():
    """The algebra only claims the join when a layer contributes it —
    a None disables the join-gather-count rule, preserving byte-for-byte
    equality with the pre-algebra gates at every unchanged site."""
    assert Expectations().join_gathers is None
    assert compose(spatial_delta((2, 2), 12)).join_gathers is None
    assert compose(spatial_join_delta(2)).join_gathers == 2


# -- 2. program-surface coverage ----------------------------------------------

SIZE, N_SP = 32, 3


@pytest.fixture(scope="module")
def small_model():
    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.parallel.partition import init_cells

    plain = get_resnet_v1(depth=8)
    cells = get_resnet_v1(depth=8, spatial_cells=N_SP)
    params = init_cells(
        plain, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    stats = collect_batch_stats(
        plain, params, [jnp.zeros((2, SIZE, SIZE, 3), jnp.float32)]
    )
    return plain, cells, params, stats


def test_surface_train_pure_dp():
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.train import Trainer

    cfg = ParallelConfig(
        batch_size=4, split_size=1, spatial_size=0, image_size=SIZE,
        data_parallel=2,
    )
    tr = Trainer(get_resnet_v1(depth=8), num_spatial_cells=0, config=cfg)
    state = tr.init(jax.random.PRNGKey(0), (4, SIZE, SIZE, 3))
    deltas = tr.collective_deltas(state.params, (4, SIZE, SIZE, 3))
    assert [d.layer for d in deltas] == ["data_parallel"]
    assert dataclasses.asdict(compose(deltas)) == dataclasses.asdict(
        Expectations(pure_dp=True)
    )


def test_surface_train_spatial():
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.train import Trainer

    cfg = ParallelConfig(
        batch_size=4, split_size=1, spatial_size=1, num_spatial_parts=(4,),
        slice_method="square", image_size=SIZE, data_parallel=1,
    )
    tr = Trainer(
        get_resnet_v1(depth=8, spatial_cells=N_SP), num_spatial_cells=N_SP,
        config=cfg, plain_cells=get_resnet_v1(depth=8),
    )
    state = tr.init(jax.random.PRNGKey(0), (4, SIZE, SIZE, 3))
    (delta,) = tr.collective_deltas(state.params, (4, SIZE, SIZE, 3))
    shifts = tr.halo_shift_count(state.params, (4, SIZE, SIZE, 3))
    assert delta.layer == "spatial" and shifts > 0
    exp = compose(delta)
    assert exp.tile_shape == cfg.tile_shape == (2, 2)
    assert exp.halo_shifts == shifts
    assert exp.single_chip is False and exp.join_gathers is None


def test_surface_serve_single_chip(small_model):
    from mpi4dl_tpu.serve.engine import SingleChipPredictor

    plain, _, params, stats = small_model
    pred = SingleChipPredictor(
        plain, params, stats, (SIZE, SIZE, 3), jnp.float32
    )
    assert [d.layer for d in pred.collective_deltas()] == ["single_chip"]
    assert dataclasses.asdict(pred.expectations()) == dataclasses.asdict(
        Expectations(single_chip=True)
    )


def test_surface_serve_sharded(small_model):
    from mpi4dl_tpu.serve.sharded import serving_mesh_config
    from mpi4dl_tpu.train import Trainer
    from mpi4dl_tpu.serve.sharded import ShardedPredictor

    plain, cells, params, stats = small_model
    cfg = serving_mesh_config((2, 2), SIZE)
    trainer = Trainer(
        cells, num_spatial_cells=N_SP, config=cfg, plain_cells=plain
    )
    pred = ShardedPredictor(trainer, params, stats, (SIZE, SIZE, 3))
    (delta,) = pred.collective_deltas()
    assert delta.layer == "spatial"
    exp = pred.expectations()
    assert exp.tile_shape == (2, 2)
    assert exp.halo_shifts == pred.halo_shifts() > 0


def test_surface_serve_tiled(small_model):
    from mpi4dl_tpu.serve.tiled import TiledPredictor

    plain, _, params, stats = small_model
    pred = TiledPredictor(plain, params, stats, (SIZE, SIZE, 3), 16)
    assert [d.layer for d in pred.collective_deltas()] == ["tiled"]
    assert dataclasses.asdict(pred.expectations()) == dataclasses.asdict(
        Expectations(single_chip=True)
    )


@pytest.mark.parametrize("schedule,budget", [("gpipe", 2), ("1f1b", 6)])
def test_surface_pipeline_schedules(schedule, budget):
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.parallel.pipeline import PipelineTrainer

    cfg = ParallelConfig(
        batch_size=8, parts=4, split_size=2, spatial_size=0,
        image_size=SIZE,
    )
    tr = PipelineTrainer(get_resnet_v1(depth=8), cfg, schedule=schedule)
    state = tr.init(jax.random.PRNGKey(0))
    deltas = tr.collective_deltas(state, (8, SIZE, SIZE, 3))
    assert [d.layer for d in deltas] == ["pipeline"]
    exp = compose(deltas)
    assert exp.extra_permutes == tr.stage_permute_count() == budget
    # The exact budget shifts BOTH window bounds: a pure-LP program's
    # permute inventory must sit exactly at it (halo window is empty).
    assert exp.halo_shifts == 0 and exp.single_chip is False


# -- 3. no hand-summed budgets outside the algebra ----------------------------

def test_no_expectations_constructed_outside_analysis():
    """Every program surface derives its gate via collective_deltas +
    compose; direct Expectations(...) construction (hand-summed budgets)
    is confined to mpi4dl_tpu/analysis/ (the dataclass's home and the
    rule engine's default). An ast scan, not a grep: docstring mentions
    don't count, calls do."""
    offenders = []
    for dirpath, dirnames, filenames in os.walk(
        os.path.join(REPO, "mpi4dl_tpu")
    ):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        if os.path.basename(dirpath) == "analysis":
            dirnames[:] = []
            continue
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            tree = ast.parse(open(path, encoding="utf-8").read())
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                name = (
                    f.id if isinstance(f, ast.Name)
                    else f.attr if isinstance(f, ast.Attribute) else None
                )
                if name == "Expectations":
                    offenders.append(
                        f"{os.path.relpath(path, REPO)}:{node.lineno}"
                    )
    assert offenders == [], (
        "hand-built Expectations outside mpi4dl_tpu/analysis/ — "
        "contribute a CollectiveDelta and compose() instead: "
        + ", ".join(offenders)
    )
