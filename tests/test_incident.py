"""Incident engine unit tests (telemetry/incident.py): causal timeline
ordering, the first-cause rule table, blast-radius absent-not-zero,
the alert-driven open/fold/close lifecycle, cataloged metrics, the
postmortem artifact, and live-vs-reconstructed equality.

The tier-1 fleet drills (test_fleet.py) exercise the same engine
against real killed subprocesses; everything here is pure/in-process.
"""

import json
import os

import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.telemetry.incident import (
    EVIDENCE_EVENTS,
    IncidentManager,
    blast_radius,
    build_postmortem,
    build_timeline,
    collect_events,
    first_cause,
    reconstruct_incidents,
)


def _ev(ts, name, **attrs):
    return {"ts": ts, "kind": "event", "name": name, "attrs": attrs}


# -- timeline -----------------------------------------------------------------


def test_timeline_orders_causes_before_symptoms_at_equal_ts():
    # A chaos op and the page it trips can share a coarse timestamp;
    # the cause must still order first (EVIDENCE_EVENTS rank).
    events = [
        _ev(10.0, "alert.transition", alert="replica_unreachable",
            **{"from": "resolved", "to": "firing"}),
        _ev(10.0, "chaos.injected", op="kill:r1@+1s"),
        _ev(10.0, "elastic.restart", replica="r1", reason="death"),
    ]
    tl = build_timeline(events, 0.0, 20.0)
    assert [e["name"] for e in tl] == [
        "chaos.injected", "elastic.restart", "alert.transition",
    ]


def test_timeline_windows_and_filters_non_evidence():
    events = [
        _ev(1.0, "chaos.injected", op="early"),       # before window
        _ev(5.0, "oom.report", program="train_step"),
        _ev(6.0, "incident.open", id="inc-1"),        # lifecycle ≠ evidence
        _ev(7.0, "heartbeat"),                        # unknown name
        _ev(50.0, "tail.sample", trace_id="t-1"),     # after window
    ]
    tl = build_timeline(events, 4.0, 10.0)
    assert [e["name"] for e in tl] == ["oom.report"]
    assert all(e["name"] in EVIDENCE_EVENTS for e in tl)


def test_timeline_spans_anchor_across_skewed_wall_clocks():
    # Span A is EMITTED later (larger ts) but STARTED first once its
    # monotonic duration is rebased onto the wall clock — the
    # cross-pid alignment trace-export uses. Plain-event order is
    # untouched.
    span_a = {
        "ts": 100.0, "kind": "span", "name": "request", "trace_id": "t-a",
        "spans": [{"phase": "queue", "start_s": 0.0, "end_s": 2.0},
                  {"phase": "compute", "start_s": 2.0, "end_s": 6.0}],
    }
    span_b = {
        "ts": 99.0, "kind": "span", "name": "request", "trace_id": "t-b",
        "spans": [{"phase": "compute", "start_s": 0.0, "end_s": 1.0}],
    }
    tl = build_timeline([span_b, span_a], 90.0, 110.0, include_spans=True)
    assert [e["trace_id"] for e in tl] == ["t-a", "t-b"]  # 94.0 < 98.0
    assert tl[0]["ts"] == pytest.approx(94.0)
    assert tl[0]["phases"] == ["queue", "compute"]
    assert tl[0]["duration_s"] == pytest.approx(6.0)
    # Without include_spans the same call is events-only.
    assert build_timeline([span_b, span_a], 90.0, 110.0) == []


# -- first-cause rule table ---------------------------------------------------


def test_first_cause_priority_beats_timestamp_order():
    # oom.report outranks elastic.restart even when the restart is
    # earlier on the wall clock — rule priority, then earliest event.
    tl = build_timeline([
        _ev(5.0, "elastic.restart", replica="r1", reason="death"),
        _ev(6.0, "oom.report", program="conv_fwd"),
    ], 0.0, 10.0)
    cause = first_cause(tl, {"replica_unreachable"})
    assert cause["event"] == "oom.report"
    assert cause["label"] == "out-of-memory in conv_fwd"
    assert cause["rule"] == "oom.report"


def test_first_cause_chaos_beats_everything_and_takes_earliest():
    tl = build_timeline([
        _ev(3.0, "chaos.injected", op="kill:1"),
        _ev(4.0, "chaos.injected", op="corrupt:0"),
        _ev(2.0, "oom.report", program="x"),
    ], 0.0, 10.0)
    cause = first_cause(tl, {"replica_unreachable"})
    assert cause["event"] == "chaos.injected"
    assert cause["ts"] == pytest.approx(3.0)
    assert cause["label"] == "injected chaos op kill:1"


def test_first_cause_canary_rule_gated_on_numerics_page():
    events = [
        _ev(1.0, "canary.failure", check="digest"),
        _ev(2.0, "alert.transition", alert="replica_unreachable",
            **{"from": "resolved", "to": "firing"}),
    ]
    tl = build_timeline(events, 0.0, 10.0)
    # An availability page is NOT explained by a canary failure …
    cause = first_cause(tl, {"replica_unreachable"})
    assert cause["event"] == "alert.transition"
    assert "first firing page replica_unreachable" in cause["label"]
    # … but a numerics page is.
    cause = first_cause(tl, {"numerics_divergence"})
    assert cause["event"] == "canary.failure"
    assert cause["label"] == "numerics canary failure (digest)"


def test_first_cause_fallback_requires_member_firing_transition():
    tl = build_timeline([
        _ev(1.0, "alert.transition", alert="other_alert",
            **{"from": "resolved", "to": "firing"}),
        _ev(2.0, "alert.transition", alert="latency_p99",
            **{"from": "firing", "to": "resolved"}),
    ], 0.0, 10.0)
    assert first_cause(tl, {"latency_p99"}) is None
    assert first_cause([], {"latency_p99"}) is None


# -- blast radius -------------------------------------------------------------


def test_blast_radius_absent_not_zero_without_metrics_snapshots():
    events = [
        _ev(5.0, "tail.sample", trace_id="t-1", tenant="acme"),
        _ev(6.0, "tail.sample", trace_id="t-2"),
    ]
    blast = blast_radius(events, 0.0, 10.0)
    assert blast["n_traces"] == 2
    assert blast["trace_ids"] == ["t-1", "t-2"]
    assert blast["tenants"] == ["acme"]
    # No metrics snapshots in the window → unknown, NOT zero.
    assert blast["requeues"] is None
    assert blast["sheds"] is None
    assert blast["slo_budget_burned"] is None


def _metrics_snapshot(ts, requeues, budget, exemplar=None):
    lat = {"series": [{
        "labels": {}, "value": 1,
        "exemplars": {"0.1": {"trace_id": exemplar}} if exemplar else {},
    }]}
    return {
        "ts": ts, "kind": "metrics",
        "metrics": {
            "fleet_requeues_total": {
                "series": [{"labels": {}, "value": requeues}],
            },
            "slo_error_budget_remaining": {
                "series": [{"labels": {"slo": "availability"},
                            "value": budget}],
            },
            "serve_latency_seconds": lat,
        },
    }


def test_blast_radius_window_burn_and_exemplar_traces():
    events = [
        _metrics_snapshot(1.0, requeues=3, budget=0.9, exemplar="t-ex"),
        _metrics_snapshot(9.0, requeues=10, budget=0.4),
        _ev(5.0, "tail.sample", trace_id="t-1", tenant="acme"),
    ]
    blast = blast_radius(events, 0.0, 10.0)
    assert blast["requeues"] == pytest.approx(7.0)
    assert blast["slo_budget_burned"] == {
        "availability": pytest.approx(0.5)
    }
    assert set(blast["trace_ids"]) == {"t-1", "t-ex"}
    # A single snapshot cannot measure a burn → absent again.
    assert blast_radius(events[:1], 0.0, 10.0)["requeues"] is None


# -- collect_events tolerance -------------------------------------------------


def test_collect_events_skips_garbage_and_truncated_tails(tmp_path):
    p = tmp_path / "telemetry-1.jsonl"
    good = _ev(1.0, "chaos.injected", op="kill:1")
    p.write_text(
        json.dumps(good) + "\n"
        + "not json at all\n"
        + '{"ts": 2.0, "kind": "event"\n'          # truncated tail
        + '{"kind": "event", "name": "x"}\n'       # schema-invalid (no ts)
    )
    (tmp_path / "notes.txt").write_text("ignored: not .jsonl\n")
    events = collect_events([str(tmp_path)])
    assert len(events) == 1
    assert events[0]["name"] == "chaos.injected"


# -- manager lifecycle --------------------------------------------------------


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class _AlertSurface:
    """Scripted /alertz payload: set .firing to the currently-firing
    page names; transitions accumulate like the aggregator's."""

    def __init__(self, clock):
        self.clock = clock
        self.firing = []
        self.transitions = []

    def fire(self, name):
        self.firing.append(name)
        self.transitions.append(_ev(
            self.clock(), "alert.transition", alert=name, severity="page",
            **{"from": "resolved", "to": "firing"},
        ))

    def resolve(self, name):
        self.firing.remove(name)

    def __call__(self):
        return {
            "alerts": [
                {"name": n, "severity": "page", "state": "firing"}
                for n in self.firing
            ] + [{"name": "advisory_thing", "severity": "ticket",
                  "state": "firing"}],
            "transitions": self.transitions,
        }


@pytest.fixture()
def manager(tmp_path):
    clock = _Clock()
    surface = _AlertSurface(clock)
    writer = telemetry.JsonlWriter(str(tmp_path))
    reg = telemetry.MetricsRegistry()
    mgr = IncidentManager(
        surface, registry=reg, events=writer,
        telemetry_dir=str(tmp_path), wall_clock=clock,
    )
    yield mgr, surface, clock, reg, tmp_path
    writer.close()


def test_manager_opens_folds_and_closes(manager):
    mgr, surface, clock, reg, tmp_path = manager
    mgr.step()
    assert mgr.open_incident is None  # ticket-severity never pages

    clock.t = 1010.0
    surface.fire("replica_unreachable")
    clock.t = 1012.5
    mgr.step()
    inc = mgr.open_incident
    assert inc is not None and mgr.opened_total == 1
    assert inc["opened_by"] == "replica_unreachable"
    # MTTA = open wall time − the page's firing transition timestamp.
    assert inc["mtta_s"] == pytest.approx(2.5)
    assert mgr.open_incident_id() == inc["id"]
    assert reg.get("incident_open").value() == 1.0
    assert reg.get("incidents_total").value(state="opened") == 1

    # A second page fires while open: FOLDS into the same incident.
    clock.t = 1015.0
    surface.fire("latency_p99_burn")
    mgr.step()
    assert mgr.opened_total == 1
    assert set(mgr.open_incident["members"]) == {
        "replica_unreachable", "latency_p99_burn",
    }

    # Close only when EVERY member has resolved.
    clock.t = 1020.0
    surface.resolve("replica_unreachable")
    mgr.step()
    assert mgr.open_incident is not None
    clock.t = 1030.0
    surface.resolve("latency_p99_burn")
    mgr.step()
    assert mgr.open_incident is None and mgr.closed_total == 1
    closed = mgr.closed[-1]
    assert closed["mttr_s"] == pytest.approx(1030.0 - 1012.5)
    assert reg.get("incident_open").value() == 0.0
    assert reg.get("incidents_total").value(state="closed") == 1
    assert reg.get("incident_mttr_seconds").value() == pytest.approx(17.5)

    # Members re-firing while open clear their resolved mark.
    m = closed["members"]["replica_unreachable"]
    assert m["resolved_ts"] == pytest.approx(1020.0)


def test_manager_lifecycle_events_schema_valid_and_reconstructible(manager):
    mgr, surface, clock, reg, tmp_path = manager
    surface.fire("replica_unreachable")
    clock.t = 1001.0
    mgr.step()
    clock.t = 1002.0
    surface.fire("numerics_divergence")
    mgr.step()
    clock.t = 1005.0
    surface.resolve("replica_unreachable")
    surface.resolve("numerics_divergence")
    mgr.step()

    events = collect_events([str(tmp_path)])
    names = [e["name"] for e in events if e["name"].startswith("incident.")]
    assert names == ["incident.open", "incident.update", "incident.close"]
    for e in events:
        telemetry.validate_event(e)  # schema-valid end to end

    # The offline reconstruction equals the live closed record on every
    # field the lifecycle events carry.
    recs = reconstruct_incidents(events)
    assert len(recs) == 1
    rec, live = recs[0], mgr.closed[-1]
    assert rec["id"] == live["id"]
    assert rec["state"] == "closed"
    assert rec["opened_ts"] == pytest.approx(live["opened_ts"])
    assert rec["closed_ts"] == pytest.approx(live["closed_ts"])
    assert rec["mtta_s"] == pytest.approx(live["mtta_s"])
    assert rec["mttr_s"] == pytest.approx(live["mttr_s"])
    assert set(rec["members"]) == set(live["members"])
    for n, m in rec["members"].items():
        assert m["first_firing_ts"] == pytest.approx(
            live["members"][n]["first_firing_ts"]
        )

    # …and the postmortems built from the two records match event for
    # event (same pure builders over the same files).
    pm_live = build_postmortem(live, events)
    pm_rec = build_postmortem(rec, events)
    assert pm_rec["timeline"] == pm_live["timeline"]
    assert pm_rec["first_cause"] == pm_live["first_cause"]


def test_manager_writes_postmortem_artifact_and_blames_chaos(manager):
    mgr, surface, clock, reg, tmp_path = manager
    # The cause lands on the log BEFORE the page (the chaos module's
    # contract), inside the lookback window.
    mgr.events.write(_ev(
        clock() - 1.0, "chaos.injected", op="kill:1", action="kill",
        target="r1", pid=1234,
    ))
    surface.fire("replica_unreachable")
    clock.t = 1003.0
    mgr.step()
    clock.t = 1008.0
    surface.resolve("replica_unreachable")
    mgr.step()

    # incident.close names the first cause and links the artifact.
    close = [
        e for e in collect_events([str(tmp_path)])
        if e["name"] == "incident.close"
    ][0]
    assert close["attrs"]["first_cause"]["event"] == "chaos.injected"
    assert close["attrs"]["first_cause"]["label"] == (
        "injected chaos op kill:1"
    )
    path = close["attrs"]["postmortem"]
    assert path and os.path.exists(path)
    pm = json.load(open(path))
    assert pm["incident"]["id"] == close["attrs"]["id"]
    assert pm["first_cause"]["event"] == "chaos.injected"
    # The artifact is .json, NOT .jsonl: a rescan must not re-read it.
    assert path.endswith(".json") and not path.endswith(".jsonl")


def test_evidence_floor_prevents_reblaming_prior_incident(manager):
    """Back-to-back faults within one lookback window: the second
    incident's evidence window starts at the first's close, so the
    first drill's chaos op is never re-blamed for the second page —
    live and offline alike (the floor travels in incident.open)."""
    mgr, surface, clock, reg, tmp_path = manager
    mgr.events.write(_ev(999.0, "chaos.injected", op="corrupt:r1"))
    surface.fire("numerics_divergence")
    clock.t = 1001.0
    mgr.step()
    clock.t = 1005.0
    surface.resolve("numerics_divergence")
    mgr.step()
    assert mgr.evidence_floor_ts == pytest.approx(1005.0)

    mgr.events.write(_ev(1010.0, "chaos.injected", op="kill:r1"))
    clock.t = 1011.0
    surface.fire("replica_unreachable")
    mgr.step()
    clock.t = 1015.0
    surface.resolve("replica_unreachable")
    mgr.step()

    first, second = mgr.closed
    events = collect_events([str(tmp_path)])
    pm1 = build_postmortem(first, events)
    pm2 = build_postmortem(second, events)
    assert pm1["first_cause"]["label"] == "injected chaos op corrupt:r1"
    assert pm2["first_cause"]["label"] == "injected chaos op kill:r1"
    # Offline agrees: the floor is carried by incident.open.
    recs = reconstruct_incidents(events)
    assert recs[1]["evidence_floor_ts"] == pytest.approx(1005.0)
    pm2_off = build_postmortem(recs[1], events)
    assert pm2_off["first_cause"]["label"] == "injected chaos op kill:r1"
    assert pm2_off["timeline"] == pm2["timeline"]


def test_manager_state_is_incidentz_payload(manager):
    mgr, surface, clock, reg, tmp_path = manager
    surface.fire("replica_unreachable")
    mgr.step()
    st = mgr.state()
    assert st["counts"] == {"opened": 1, "closed": 0}
    assert len(st["open"]) == 1 and st["closed"] == []
    assert st["open"][0]["incident"]["state"] == "open"
    assert st["severities"] == ["page"]
    surface.resolve("replica_unreachable")
    clock.t += 5.0
    mgr.step()
    st = mgr.state()
    assert st["counts"] == {"opened": 1, "closed": 1}
    assert st["open"] == [] and len(st["closed"]) == 1
    assert json.dumps(st)  # JSON-serializable for the HTTP endpoint


def test_manager_survives_broken_alert_surface(tmp_path):
    def boom():
        raise RuntimeError("scrape exploded")

    mgr = IncidentManager(boom, telemetry_dir=str(tmp_path))
    mgr.step()  # must not raise
    assert mgr.open_incident is None and mgr.opened_total == 0
