"""Native (C++) data runtime tests: build via g++ + ctypes, determinism
independent of thread count, parity with the numpy fallback, tile slicing
correctness, and the prefetching synthetic stream."""

import numpy as np
import pytest

from mpi4dl_tpu import native
from mpi4dl_tpu.data import SyntheticImages


def test_native_builds_and_loads():
    assert native.available(), "native runtime failed to build/load"


def test_fill_uniform_deterministic_across_threads():
    a = native.fill_uniform((64, 33, 3), seed=42, num_threads=1)
    b = native.fill_uniform((64, 33, 3), seed=42, num_threads=7)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.float32
    assert float(a.min()) >= 0.0 and float(a.max()) < 1.0
    c = native.fill_uniform((64, 33, 3), seed=43)
    assert not np.array_equal(a, c)
    # Sane distribution, not constant/patterned.
    assert abs(float(a.mean()) - 0.5) < 0.02


def test_fill_labels_range_and_determinism():
    y1 = native.fill_labels(1000, 10, seed=5, num_threads=2)
    y2 = native.fill_labels(1000, 10, seed=5, num_threads=5)
    np.testing.assert_array_equal(y1, y2)
    assert y1.min() >= 0 and y1.max() < 10
    assert len(np.unique(y1)) == 10


@pytest.mark.parametrize("th,tw", [(2, 2), (1, 4), (4, 1)])
def test_slice_tile_matches_numpy(th, tw):
    rng = np.random.default_rng(0)
    batch = rng.standard_normal((2, 16, 8, 3)).astype(np.float32)
    hh, ww = 16 // th, 8 // tw
    for ti in range(th):
        for tj in range(tw):
            got = native.slice_tile(batch, th, tw, ti, tj)
            want = batch[:, ti * hh : (ti + 1) * hh, tj * ww : (tj + 1) * ww, :]
            np.testing.assert_array_equal(got, want)


def test_synthetic_stream_prefetch_matches_sync():
    kw = dict(batch_size=2, image_size=8, num_classes=10, length=8, seed=3)
    sync = list(SyntheticImages(prefetch=False, **kw))
    pre = list(SyntheticImages(prefetch=True, **kw))
    assert len(sync) == len(pre) == 4
    for (xa, ya), (xb, yb) in zip(sync, pre):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
