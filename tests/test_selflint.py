"""ISSUE 16 satellite: ``scripts/selflint.py`` — the stdlib-ast hygiene
lint over the repo's own source. Pins each rule on synthetic snippets
(golden findings), the allowlist mechanism, the scan scope, the CLI exit
codes, and — the point — that the real repo scans clean. Pure stdlib:
no jax, no device work."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "selflint.py")

spec = importlib.util.spec_from_file_location("selflint", SCRIPT)
selflint = importlib.util.module_from_spec(spec)
spec.loader.exec_module(selflint)


def _lint_src(tmp_path, src, rel="mpi4dl_tpu/snippet.py"):
    p = tmp_path / "snippet.py"
    p.write_text(src)
    return selflint.lint_file(str(p), rel=rel)


# -- rule goldens -------------------------------------------------------------

def test_wallclock_compare_flagged(tmp_path):
    src = (
        "import time\n"
        "def f(deadline):\n"
        "    while time.time() < deadline:\n"
        "        pass\n"
    )
    fs = _lint_src(tmp_path, src)
    assert [(f["rule"], f["line"]) for f in fs] == [("wallclock-compare", 3)]
    assert "time.monotonic()" in fs[0]["message"]


def test_wallclock_timestamp_uses_are_fine(tmp_path):
    """Timestamps (stored, subtracted, printed) are legitimate wall-clock
    uses — only a time.time() nested inside a Compare fires. monotonic
    and perf_counter comparisons are the fix, so they never fire."""
    src = (
        "import time\n"
        "t0 = time.time()\n"                       # stored timestamp
        "dt = time.time() - t0\n"                  # display arithmetic
        "def g(deadline):\n"
        "    return time.monotonic() < deadline\n"  # the correct clock
        "ok = time.perf_counter() < 5\n"
    )
    assert _lint_src(tmp_path, src) == []


def test_uncataloged_metric_flagged_and_declare_is_fine(tmp_path):
    src = (
        "from mpi4dl_tpu import telemetry\n"
        "def f(reg):\n"
        "    telemetry.declare(reg, 'serve_queue_depth').set(3)\n"  # fine
        "    reg.gauge('rogue_gauge', 'h').set(1)\n"
        "    reg.counter('rogue_total', 'h').inc()\n"
        "    reg.histogram('rogue_ms', 'h').observe(2.0)\n"
    )
    fs = _lint_src(tmp_path, src)
    assert [(f["rule"], f["line"]) for f in fs] == [
        ("uncataloged-metric", 4),
        ("uncataloged-metric", 5),
        ("uncataloged-metric", 6),
    ]
    assert all("telemetry.declare" in f["message"] for f in fs)


def test_unnamed_thread_flagged_name_or_daemon_passes(tmp_path):
    src = (
        "import threading\n"
        "t1 = threading.Thread(target=f)\n"                    # flagged
        "t2 = threading.Thread(target=f, name='worker')\n"     # fine
        "t3 = threading.Thread(target=f, daemon=True)\n"       # fine
        "from threading import Thread\n"
        "t4 = Thread(target=f)\n"                              # flagged
    )
    fs = _lint_src(tmp_path, src)
    assert [(f["rule"], f["line"]) for f in fs] == [
        ("unnamed-thread", 2), ("unnamed-thread", 6),
    ]


def test_allowlist_suppresses_by_relpath(tmp_path):
    """The telemetry internals that implement declare() call the raw
    registry constructors on purpose — the allowlist keys on the
    repo-relative path, nothing else."""
    src = "def f(reg):\n    reg.gauge('x', 'h')\n"
    assert _lint_src(
        tmp_path, src, rel="mpi4dl_tpu/telemetry/catalog.py"
    ) == []
    assert _lint_src(
        tmp_path, src, rel="mpi4dl_tpu/telemetry/federation.py"
    ) == []
    # Any other path still fires — the allowlist is not a rule switch.
    assert len(_lint_src(tmp_path, src, rel="mpi4dl_tpu/other.py")) == 1


# -- scope + repo cleanliness -------------------------------------------------

def test_scan_scope_covers_package_scripts_and_bench():
    rels = {rel for _, rel in selflint.iter_sources(REPO)}
    assert "bench.py" in rels
    assert "scripts/selflint.py" in rels
    assert any(r.startswith("mpi4dl_tpu/") for r in rels)
    assert any(r.startswith("mpi4dl_tpu/analysis/") for r in rels)
    # Tests are excluded by construction: they monkeypatch clocks and
    # registries on purpose.
    assert not any(r.startswith("tests/") for r in rels)


def test_repo_lints_clean():
    """The gate itself: the repo's own source carries zero hygiene
    findings. A new time.time() deadline loop, rogue metric series, or
    anonymous thread fails tier-1 right here."""
    findings = selflint.lint_repo(REPO)
    assert findings == [], "\n".join(
        f"{f['path']}:{f['line']}: {f['rule']}: {f['message']}"
        for f in findings
    )


def test_cli_exit_codes_and_json(tmp_path):
    """Exit 0 + summary on the clean repo; exit 1 + findings on a dirty
    tree; --json emits a machine-readable array. Runs the script as a
    subprocess — the pre-commit/CI invocation shape — which also proves
    it never imports jax (bare interpreter, no JAX_PLATFORMS set)."""
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    r = subprocess.run(
        [sys.executable, SCRIPT], capture_output=True, text=True, env=env,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 finding(s)" in r.stdout

    dirty = tmp_path / "repo"
    (dirty / "mpi4dl_tpu").mkdir(parents=True)
    (dirty / "mpi4dl_tpu" / "bad.py").write_text(
        "import threading\nthreading.Thread(target=print).start()\n"
    )
    r = subprocess.run(
        [sys.executable, SCRIPT, "--root", str(dirty), "--json"],
        capture_output=True, text=True, env=env,
    )
    assert r.returncode == 1
    fs = json.loads(r.stdout)
    assert [(f["rule"], f["path"]) for f in fs] == [
        ("unnamed-thread", "mpi4dl_tpu/bad.py"),
    ]
