"""Checkpoint/resume + profiling subsystem tests (capability additions over
the reference, which persists nothing — SURVEY.md §5.4)."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from mpi4dl_tpu.checkpoint import (
    all_checkpoints,
    checkpoint_metadata,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from mpi4dl_tpu.config import ParallelConfig
from mpi4dl_tpu.models.resnet import get_resnet_v1
from mpi4dl_tpu.profiling import StepTimer
from mpi4dl_tpu.train import Trainer


def _make_trainer():
    cfg = ParallelConfig(batch_size=2, split_size=1, spatial_size=0, image_size=16)
    cells = get_resnet_v1(depth=8, pool_kernel=4)
    return Trainer(cells, num_spatial_cells=0, config=cfg)


def _batch(seed):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(2,)), jnp.int32)
    return x, y


def test_save_restore_resume_parity(tmp_path):
    """Train 1 step → checkpoint → train 1 more; restoring the checkpoint and
    redoing step 2 must produce bit-identical parameters to the uninterrupted
    run."""
    ckpt = os.path.join(str(tmp_path), "ckpt")
    trainer = _make_trainer()
    state = trainer.init(jax.random.PRNGKey(0), (2, 16, 16, 3))

    x1, y1 = _batch(1)
    state, _ = trainer.train_step(state, *trainer.shard_batch(x1, y1))
    path = save_checkpoint(ckpt, state, metadata={"note": "after-step-1"})
    assert checkpoint_metadata(path)["note"] == "after-step-1"

    x2, y2 = _batch(2)
    state, _ = trainer.train_step(state, *trainer.shard_batch(x2, y2))
    final = jax.device_get(state.params)

    # Resume from the checkpoint into a fresh trainer/state skeleton.
    trainer2 = _make_trainer()
    target = trainer2.init(jax.random.PRNGKey(7), (2, 16, 16, 3))  # different init
    restored = restore_checkpoint(ckpt, target)
    assert int(restored.step) == 1
    restored, _ = trainer2.train_step(restored, *trainer2.shard_batch(x2, y2))
    jax.tree.map(
        lambda u, v: np.testing.assert_array_equal(np.asarray(u), np.asarray(v)),
        jax.device_get(restored.params),
        final,
    )


def test_checkpoint_pruning_and_latest(tmp_path):
    ckpt = os.path.join(str(tmp_path), "ckpt")
    trainer = _make_trainer()
    state = trainer.init(jax.random.PRNGKey(0), (2, 16, 16, 3))
    for s in range(5):
        save_checkpoint(ckpt, state, step=s, keep=2)
    steps = [s for s, _ in all_checkpoints(ckpt)]
    assert steps == [3, 4]
    assert latest_checkpoint(ckpt).endswith("step_00000004")


def test_resume_continues_curve(tmp_path):
    """The convergence-artifact logic (scripts/convergence_run.py) small on
    CPU: train N steps, stop, restore into a FRESH trainer from the
    checkpoint dir, continue the same deterministic stream — the combined
    log must be step-contiguous, the loss must fall, and the resumed curve
    must pick up where the stopped one left off (fast-tier stand-in for the
    real-chip SIGKILL artifact, docs/artifacts/convergence_r5.json)."""
    import importlib.util
    import json

    spec = importlib.util.spec_from_file_location(
        "convergence_run",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "convergence_run.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    run_phase = mod.run_phase

    kw = dict(
        depth=11,
        image_size=16,
        batch_size=16,
        ckpt_dir=os.path.join(str(tmp_path), "ckpt"),
        ckpt_every=6,
        log_path=os.path.join(str(tmp_path), "curve.jsonl"),
        lr=0.02,
        compile_cache=False,
    )
    run_phase(steps=12, resume=False, **kw)
    run_phase(steps=24, resume=True, **kw)

    curve = [json.loads(l) for l in open(kw["log_path"])]
    assert [r["step"] for r in curve] == list(range(1, 25))
    first = np.mean([r["loss"] for r in curve[:3]])
    last = np.mean([r["loss"] for r in curve[-3:]])
    assert last < first, (first, last)
    # Continuity at the kill/resume boundary: no restart-sized jump.
    pre, post = curve[11]["loss"], curve[12]["loss"]
    assert abs(post - pre) < max(0.5 * pre, 0.25), (pre, post)


def test_step_timer_tracks_throughput():
    timer = StepTimer(batch_size=4, warmup=1)
    for _ in range(3):
        with timer.step() as rec:
            rec(jnp.zeros((2, 2)) + 1)
    s = timer.summary()
    assert s["steps"] == 2
    assert s["images_per_sec_mean"] > 0
    # Tail percentiles (serving needs tails, not means): present, ordered,
    # and bracketed by the sample extremes.
    assert min(timer.times) <= s["step_time_p50_s"] <= s["step_time_p90_s"]
    assert s["step_time_p90_s"] <= s["step_time_p99_s"] <= max(timer.times)


def test_percentiles_helper_interpolates():
    from mpi4dl_tpu.profiling import percentiles

    assert percentiles([]) == {}
    vals = list(range(1, 101))  # 1..100
    p = percentiles(vals)
    assert p["p50"] == 50.5  # linear interpolation, numpy-default method
    np.testing.assert_allclose(p["p90"], 90.1)
    np.testing.assert_allclose(p["p99"], 99.01)
    assert percentiles([7.0]) == {"p50": 7.0, "p90": 7.0, "p99": 7.0}


def test_model_metadata_rebuild_round_trip(tmp_path):
    """Satellite: save → metadata → rebuild → restore. A self-describing
    checkpoint must reconstruct the cell list, the exact params, and the
    calibrated BN stats from the checkpoint path alone."""
    from mpi4dl_tpu.checkpoint import model_metadata, rebuild_from_checkpoint
    from mpi4dl_tpu.evaluate import collect_batch_stats

    ckpt = os.path.join(str(tmp_path), "ckpt")
    trainer = _make_trainer()
    cells = trainer.cells
    state = trainer.init(jax.random.PRNGKey(0), (2, 16, 16, 3))
    x1, y1 = _batch(1)
    state, _ = trainer.train_step(state, *trainer.shard_batch(x1, y1))
    stats = collect_batch_stats(cells, jax.device_get(state.params), [x1])
    save_checkpoint(
        ckpt, state, batch_stats=stats,
        metadata=model_metadata(
            "resnet_v1", image_size=16, depth=8, pool_kernel=4
        ),
    )

    cells2, state2, stats2, meta = rebuild_from_checkpoint(ckpt)
    assert meta["model"]["family"] == "resnet_v1"
    assert len(cells2) == len(cells)
    assert int(state2.step) == 1
    jax.tree.map(
        lambda u, v: np.testing.assert_array_equal(
            np.asarray(u), np.asarray(v)
        ),
        jax.device_get(state2.params),
        jax.device_get(state.params),
    )
    jax.tree.map(
        lambda u, v: np.testing.assert_array_equal(
            np.asarray(u), np.asarray(v)
        ),
        stats2,
        jax.device_get(stats),
    )
    # The rebuilt model is functionally the restored model: same logits.
    from mpi4dl_tpu.evaluate import make_predict

    want = make_predict(tuple(cells))(
        jax.device_get(state.params), stats, x1
    )
    got = make_predict(tuple(cells2))(state2.params, stats2, x1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-6
    )


def test_rebuild_without_model_metadata_refuses(tmp_path):
    from mpi4dl_tpu.checkpoint import rebuild_from_checkpoint, restore_batch_stats

    ckpt = os.path.join(str(tmp_path), "ckpt")
    trainer = _make_trainer()
    state = trainer.init(jax.random.PRNGKey(0), (2, 16, 16, 3))
    save_checkpoint(ckpt, state, step=0)
    assert restore_batch_stats(ckpt) is None  # saved without stats
    try:
        rebuild_from_checkpoint(ckpt)
    except ValueError as e:
        assert "model" in str(e)
    else:
        raise AssertionError("rebuild without model metadata must refuse")
