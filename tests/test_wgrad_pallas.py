"""Pallas wgrad kernel vs the stock XLA backward-filter conv (interpreter
mode — same math on CPU; the TPU lowering is exercised by bench runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from mpi4dl_tpu.ops import wgrad_pallas


def _ref_wgrad(xp, dy, kh, kw):
    wo = dy.shape[2]
    xt = xp[:, :, : wo + kw - 1, :]
    dw = lax.conv_general_dilated(
        xt,
        dy,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("CHWN", "IHWO", "NHWC"),
    )  # [C, kh, kw, O]
    return dw.transpose(1, 2, 0, 3)


@pytest.mark.parametrize(
    "b,ho,wo,c,o,k",
    [
        (2, 16, 16, 5, 7, 3),
        (1, 8, 24, 4, 4, 3),
        (2, 32, 8, 3, 5, 5),  # 5x5: tail = 4, th=8 multiple of 4
    ],
)
def test_wgrad_matches_xla(b, ho, wo, c, o, k):
    rng = np.random.default_rng(0)
    xp = jnp.asarray(
        rng.standard_normal((b, ho + k - 1, wo + k - 1, c)), jnp.float32
    )
    dy = jnp.asarray(rng.standard_normal((b, ho, wo, o)), jnp.float32)
    assert wgrad_pallas.supported(xp.shape, dy.shape, k, k)
    got = wgrad_pallas.wgrad(xp, dy, k, k, interpret=True)
    want = _ref_wgrad(xp, dy, k, k)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_supported_gate():
    # 1x1 → plain dot, not this kernel
    assert not wgrad_pallas.supported((2, 16, 16, 4), (2, 16, 16, 8), 1, 1)
    # Ho not divisible by the row chunk
    assert not wgrad_pallas.supported((2, 15, 18, 4), (2, 13, 16, 8), 3, 3)
    # mismatched padded height
    assert not wgrad_pallas.supported((2, 16, 18, 4), (2, 16, 16, 8), 3, 3)
