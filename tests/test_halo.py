"""Halo-exchange golden tests.

TPU rebuild of the reference's de-facto integration test: a deterministic
``arange`` image is tiled across ranks, halos are exchanged, and each tile is
compared for integer equality against ``np.pad`` ground truth computed from
the full image (``benchmarks/communication/halo/benchmark_sp_halo_exchange.py:417-584``).
Here the "ranks" are virtual CPU mesh devices and comparison is exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from mpi4dl_tpu.compat import shard_map

from mpi4dl_tpu.parallel.halo import halo_exchange


def _mesh(th, tw):
    dev = np.asarray(jax.devices()[: th * tw]).reshape(th, tw)
    return Mesh(dev, ("tile_h", "tile_w"))


def _golden_tiles(image, th, tw, halo_h, halo_w):
    """Expected halo'd tile per grid cell, from np.pad on the full image."""
    b, h, w, c = image.shape
    padded = np.pad(
        image, ((0, 0), (halo_h, halo_h), (halo_w, halo_w), (0, 0))
    )
    hh, ww = h // th, w // tw
    out = {}
    for i in range(th):
        for j in range(tw):
            out[(i, j)] = padded[
                :,
                i * hh : i * hh + hh + 2 * halo_h,
                j * ww : j * ww + ww + 2 * halo_w,
                :,
            ]
    return out


@pytest.mark.parametrize(
    "th,tw,halo_h,halo_w",
    [
        (2, 2, 1, 1),  # square slicing, 3x3-kernel halo
        (2, 2, 3, 3),  # square, halo_len=3 (7x7 kernel / D2 fused halo)
        (1, 4, 0, 2),  # vertical slicing
        (4, 1, 2, 0),  # horizontal slicing
        (2, 4, 1, 2),  # rectangular grid, asymmetric halo
    ],
)
def test_halo_exchange_matches_np_pad(th, tw, halo_h, halo_w):
    rng = np.random.default_rng(0)
    b, h, w, c = 2, 16, 16, 3
    image = rng.integers(0, 1000, size=(b, h, w, c)).astype(np.float32)

    mesh = _mesh(th, tw)
    spec = P(None, "tile_h", "tile_w", None)

    fn = shard_map(
        lambda x: halo_exchange(x, halo_h, halo_w),
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
    )
    # Output tiles overlap, so gather per-tile results along a stacked axis
    # instead: run with out spec stacking tiles is awkward — instead fetch
    # the per-device shards directly.
    x = jax.device_put(jnp.asarray(image), NamedSharding(mesh, spec))
    y = jax.jit(fn)(x)

    golden = _golden_tiles(image, th, tw, halo_h, halo_w)
    hh, ww = h // th, w // tw
    for shard in y.addressable_shards:
        # shard.index is the slice into the (overlapping) global result; use
        # device mesh position instead.
        pos = np.argwhere(mesh.devices == shard.device)
        assert pos.shape == (1, 2)
        i, j = map(int, pos[0])
        np.testing.assert_array_equal(np.asarray(shard.data), golden[(i, j)])


def test_halo_exchange_zero_halo_is_identity():
    mesh = _mesh(2, 2)
    spec = P(None, "tile_h", "tile_w", None)
    x = jnp.arange(2 * 8 * 8 * 1, dtype=jnp.float32).reshape(2, 8, 8, 1)
    fn = shard_map(
        lambda t: halo_exchange(t, 0, 0),
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
    )
    xs = jax.device_put(x, NamedSharding(mesh, spec))
    np.testing.assert_array_equal(np.asarray(jax.jit(fn)(xs)), np.asarray(x))


# -- Pallas->XLA downgrade warning (ISSUE satellite) --------------------------


def _exchange(x, mesh, **kw):
    spec = P(None, "tile_h", "tile_w", None)
    fn = jax.jit(shard_map(
        lambda t: halo_exchange(t, 1, 1, **kw),
        mesh=mesh, in_specs=(spec,), out_specs=spec,
    ))
    xs = jax.device_put(x, NamedSharding(mesh, spec))
    return np.asarray(fn(xs))


def test_explicit_pallas_under_xla_only_warns_once_and_is_correct():
    """ISSUE satellite: explicit ``impl="pallas"`` while the XLA-only
    guard is active downgrades with EXACTLY ONE warning per process — a
    54-cell model must not emit one warning per traced layer — and the
    downgraded output equals the XLA path's."""
    import warnings

    from mpi4dl_tpu.parallel import halo

    mesh = _mesh(2, 2)
    x = jnp.arange(2 * 8 * 8 * 2, dtype=jnp.float32).reshape(2, 8, 8, 2)
    halo._reset_pallas_downgrade_warning()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        with halo.xla_halo_only():
            got1 = _exchange(x, mesh, impl="pallas")
            # A second fresh trace in the same process: no second warning.
            got2 = _exchange(x + 1.0, mesh, impl="pallas")
    downgrades = [w for w in rec if "downgraded" in str(w.message)]
    assert len(downgrades) == 1, [str(w.message) for w in rec]
    ref = _exchange(x, mesh, impl="xla")
    np.testing.assert_array_equal(got1, ref)
    np.testing.assert_array_equal(
        got2, _exchange(x + 1.0, mesh, impl="xla")
    )


def test_env_selected_pallas_downgrades_silently(monkeypatch):
    """ISSUE satellite: MPI4DL_TPU_HALO_IMPL=pallas (no explicit impl=)
    under the XLA-only guard downgrades with NO warning — the env default
    is a preference, not a per-callsite promise — and stays correct."""
    import warnings

    from mpi4dl_tpu.parallel import halo

    monkeypatch.setenv("MPI4DL_TPU_HALO_IMPL", "pallas")
    mesh = _mesh(2, 2)
    x = jnp.arange(1 * 8 * 8 * 1, dtype=jnp.float32).reshape(1, 8, 8, 1)
    halo._reset_pallas_downgrade_warning()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        with halo.xla_halo_only():
            got = _exchange(x, mesh)
    assert [w for w in rec if "downgraded" in str(w.message)] == []
    monkeypatch.delenv("MPI4DL_TPU_HALO_IMPL")
    np.testing.assert_array_equal(got, _exchange(x, mesh, impl="xla"))
