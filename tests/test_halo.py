"""Halo-exchange golden tests.

TPU rebuild of the reference's de-facto integration test: a deterministic
``arange`` image is tiled across ranks, halos are exchanged, and each tile is
compared for integer equality against ``np.pad`` ground truth computed from
the full image (``benchmarks/communication/halo/benchmark_sp_halo_exchange.py:417-584``).
Here the "ranks" are virtual CPU mesh devices and comparison is exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from mpi4dl_tpu.compat import shard_map

from mpi4dl_tpu.parallel.halo import halo_exchange


def _mesh(th, tw):
    dev = np.asarray(jax.devices()[: th * tw]).reshape(th, tw)
    return Mesh(dev, ("tile_h", "tile_w"))


def _golden_tiles(image, th, tw, halo_h, halo_w):
    """Expected halo'd tile per grid cell, from np.pad on the full image."""
    b, h, w, c = image.shape
    padded = np.pad(
        image, ((0, 0), (halo_h, halo_h), (halo_w, halo_w), (0, 0))
    )
    hh, ww = h // th, w // tw
    out = {}
    for i in range(th):
        for j in range(tw):
            out[(i, j)] = padded[
                :,
                i * hh : i * hh + hh + 2 * halo_h,
                j * ww : j * ww + ww + 2 * halo_w,
                :,
            ]
    return out


@pytest.mark.parametrize(
    "th,tw,halo_h,halo_w",
    [
        (2, 2, 1, 1),  # square slicing, 3x3-kernel halo
        (2, 2, 3, 3),  # square, halo_len=3 (7x7 kernel / D2 fused halo)
        (1, 4, 0, 2),  # vertical slicing
        (4, 1, 2, 0),  # horizontal slicing
        (2, 4, 1, 2),  # rectangular grid, asymmetric halo
    ],
)
def test_halo_exchange_matches_np_pad(th, tw, halo_h, halo_w):
    rng = np.random.default_rng(0)
    b, h, w, c = 2, 16, 16, 3
    image = rng.integers(0, 1000, size=(b, h, w, c)).astype(np.float32)

    mesh = _mesh(th, tw)
    spec = P(None, "tile_h", "tile_w", None)

    fn = shard_map(
        lambda x: halo_exchange(x, halo_h, halo_w),
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
    )
    # Output tiles overlap, so gather per-tile results along a stacked axis
    # instead: run with out spec stacking tiles is awkward — instead fetch
    # the per-device shards directly.
    x = jax.device_put(jnp.asarray(image), NamedSharding(mesh, spec))
    y = jax.jit(fn)(x)

    golden = _golden_tiles(image, th, tw, halo_h, halo_w)
    hh, ww = h // th, w // tw
    for shard in y.addressable_shards:
        # shard.index is the slice into the (overlapping) global result; use
        # device mesh position instead.
        pos = np.argwhere(mesh.devices == shard.device)
        assert pos.shape == (1, 2)
        i, j = map(int, pos[0])
        np.testing.assert_array_equal(np.asarray(shard.data), golden[(i, j)])


def test_halo_exchange_zero_halo_is_identity():
    mesh = _mesh(2, 2)
    spec = P(None, "tile_h", "tile_w", None)
    x = jnp.arange(2 * 8 * 8 * 1, dtype=jnp.float32).reshape(2, 8, 8, 1)
    fn = shard_map(
        lambda t: halo_exchange(t, 0, 0),
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
    )
    xs = jax.device_put(x, NamedSharding(mesh, spec))
    np.testing.assert_array_equal(np.asarray(jax.jit(fn)(xs)), np.asarray(x))
