"""REAL multi-process integration test: spawns a 2-host world (2 CPU
devices per process, collectives over localhost) and trains through the
full stack — ``initialize_distributed`` → ``make_multihost_mesh`` →
``shard_batch``'s per-host feeding → jitted SPMD train step — validating
losses against a single-device golden model inside each worker.

This is coverage the reference cannot express without a GPU cluster
(SURVEY.md §4: its multi-node path requires ≥4 GPUs + MPI); here it runs in
CI on CPUs.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_world_trains():
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    # The workers configure platform/device-count themselves.
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=540)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out\n" + "\n".join(outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"proc {pid}: ALL OK" in out, f"worker {pid} output:\n{out}"
        # The spatial world (data across hosts, tiles host-local) and the
        # placement-contract rejection both ran (VERDICT r3 #8).
        assert f"proc {pid}: DPxSP case OK" in out, f"worker {pid}:\n{out}"
        assert f"proc {pid}: rejection case OK" in out, f"worker {pid}:\n{out}"
    # Both hosts must observe identical losses (one SPMD program).
    import re

    losses = [re.findall(r"loss=([0-9.]+)", o) for o in outs]
    assert losses[0] == losses[1], losses
