"""``python -m mpi4dl_tpu.analyze bench-history`` (ISSUE satellite): the
perf-trajectory comparator over committed bench round files — series
extraction from result lines, regression verdicts with a tolerance band,
CI exit codes, and the CLI dispatch through ``analysis.cli.main`` — plus
a run over the repo's real BENCH_r*.json history (it must parse, whatever
its verdict)."""

import glob
import json
import os

import pytest

from mpi4dl_tpu.analysis.bench_history import (
    compare,
    extract_series,
    main,
    render_table,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _round(n, rc, parsed):
    return {"n": n, "cmd": "python bench.py", "rc": rc, "tail": "",
            "parsed": parsed}


def _result(headline_value, extra_value, peak=None):
    extras = {"resnet110_2048px_bs1": {"value": extra_value, "remat": "scan"}}
    if peak is not None:
        extras["resnet_peak_pixels"] = {
            "peak_trainable_px_per_chip": peak, "img_per_sec_at_peak": 0.06,
        }
    return {
        "metric": "amoebanetd_1024px_bs2_train_tpu",
        "value": headline_value,
        "unit": "images/sec",
        "vs_baseline": None,
        "extras": extras,
    }


def _write_rounds(tmp_path, rounds):
    paths = []
    for i, r in enumerate(rounds, start=1):
        p = tmp_path / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps(r))
        paths.append(str(p))
    return paths


def test_extract_series_covers_headline_extras_and_peak():
    s = extract_series(_result(7.0, 0.5, peak=4096))
    assert s == {
        "amoebanetd_1024px_bs2_train_tpu": 7.0,
        "resnet110_2048px_bs1": 0.5,
        "resnet_peak_pixels.peak_px": 4096.0,
    }
    # A failed round (parsed value None) contributes nothing.
    assert extract_series({"metric": "m", "value": None}) == {}


def test_extract_series_memory_keys():
    """ISSUE satellite: the headline ``hlo`` block's peak and the
    serving extra's per-bucket predicted peaks become trend series."""
    r = _result(7.0, 0.5)
    r["hlo"] = {"peak_hbm_bytes": 17e9, "inventory": {}}
    r["extras"]["serving_amoebanet3_32px"] = {
        "value": 2000.0,
        "peak_hbm_bytes_by_bucket": {"1": 2.0e6, "32": 2.7e6},
    }
    s = extract_series(r)
    assert s["hlo.peak_hbm_bytes"] == 17e9
    assert s["serving_amoebanet3_32px"] == 2000.0
    assert s["serving_amoebanet3_32px.peak_hbm_bytes[b1]"] == 2.0e6
    assert s["serving_amoebanet3_32px.peak_hbm_bytes[b32]"] == 2.7e6


def test_fleet_recovery_series_trended_and_inverted(tmp_path):
    """ISSUE CI satellite: the fleet_2replica extra's recovery latency
    becomes a trend series with the regression sign inverted — a SLOWER
    death-to-replacement is the regression."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    r = _result(7.0, 0.5)
    r["extras"]["fleet_2replica"] = {
        "value": 350.0, "requeued": 4, "recovery_s": 7.1,
    }
    s = extract_series(r)
    assert s["fleet_2replica"] == 350.0            # rps: higher is better
    assert s["fleet_2replica.recovery_s"] == 7.1   # latency: lower is
    assert lower_is_better("fleet_2replica.recovery_s")
    assert not lower_is_better("fleet_2replica")
    fast, slow = _result(7.0, 0.5), _result(7.0, 0.5)
    fast["extras"]["fleet_2replica"] = {"value": 350.0, "recovery_s": 7.0}
    slow["extras"]["fleet_2replica"] = {"value": 350.0, "recovery_s": 9.0}
    paths = _write_rounds(tmp_path, [_round(1, 0, fast),
                                     _round(2, 0, slow)])
    assert main(paths) == 1  # +29% recovery latency: CI-visible
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [fast, slow]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["fleet_2replica.recovery_s"]["verdict"] == "regressed"


def test_numerics_series_trended_and_inverted(tmp_path):
    """ISSUE 19 satellite: the numerics extra's detection latency and
    canary-on throughput overhead become trend series with the
    regression sign INVERTED — slower corruption-to-fence detection or
    a grown canary tax is the regression, even when the headline rps
    holds. Rounds without the extra contribute nothing (absent-not-zero
    — a round benched before the sentinel existed is not a 0s detect)."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    r = _result(7.0, 0.5)
    r["extras"]["numerics"] = {
        "value": 340.0, "detect_s": 0.31, "rps_overhead_pct": 1.2,
        "detected": True, "canary_interval_s": 0.2,
    }
    s = extract_series(r)
    assert s["numerics"] == 340.0                  # rps: higher is better
    assert s["numerics.detect_s"] == 0.31
    assert s["numerics.rps_overhead_pct"] == 1.2
    assert lower_is_better("numerics.detect_s")
    assert lower_is_better("numerics.rps_overhead_pct")
    assert not lower_is_better("numerics")

    # Absent-not-zero: a pre-sentinel round has no numerics keys at all.
    old = extract_series(_result(7.0, 0.5))
    assert not any(k.startswith("numerics") for k in old)
    # An undetected corruption run records no detect_s rather than 0.0
    # (a vanishing detection latency must never read as an improvement).
    r2 = _result(7.0, 0.5)
    r2["extras"]["numerics"] = {"value": 340.0, "detected": False,
                                "rps_overhead_pct": 1.0}
    s2 = extract_series(r2)
    assert "numerics.detect_s" not in s2
    assert s2["numerics.rps_overhead_pct"] == 1.0

    # A slower detection across rounds is CI-visible as a regression.
    fast, slow = _result(7.0, 0.5), _result(7.0, 0.5)
    fast["extras"]["numerics"] = {"value": 340.0, "detect_s": 0.3,
                                  "rps_overhead_pct": 1.0}
    slow["extras"]["numerics"] = {"value": 340.0, "detect_s": 0.6,
                                  "rps_overhead_pct": 1.0}
    paths = _write_rounds(tmp_path, [_round(1, 0, fast),
                                     _round(2, 0, slow)])
    assert main(paths) == 1  # 2x detection latency: CI-visible
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [fast, slow]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["numerics.detect_s"]["verdict"] == "regressed"


def test_incident_series_trended_and_inverted(tmp_path):
    """ISSUE 20 satellite: the incident extra's MTTD (page→open) and
    MTTR (open→close) become trend series with the regression sign
    INVERTED — a slower-opening or slower-closing incident engine is
    the regression, even when the headline rps holds. Rounds without
    the extra contribute nothing, and a drill where the incident never
    opened (or never closed) records no mttd/mttr rather than 0.0
    (absent-not-zero: a vanishing time-to-detect must never read as an
    improvement)."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    r = _result(7.0, 0.5)
    r["extras"]["incident"] = {
        "value": 310.0, "mttd_s": 2.4, "mttr_s": 11.0,
        "incidents_opened": 1, "incidents_closed": 1,
        "blame_correct": True,
    }
    s = extract_series(r)
    assert s["incident"] == 310.0                  # rps: higher is better
    assert s["incident.mttd_s"] == 2.4
    assert s["incident.mttr_s"] == 11.0
    assert lower_is_better("incident.mttd_s")
    assert lower_is_better("incident.mttr_s")
    assert not lower_is_better("incident")

    # Absent-not-zero: a pre-engine round has no incident keys at all.
    old = extract_series(_result(7.0, 0.5))
    assert not any(k.startswith("incident") for k in old)
    # A drill whose incident never closed records no mttr_s.
    r2 = _result(7.0, 0.5)
    r2["extras"]["incident"] = {"value": 310.0, "mttd_s": 2.0,
                                "incidents_opened": 1,
                                "incidents_closed": 0}
    s2 = extract_series(r2)
    assert s2["incident.mttd_s"] == 2.0
    assert "incident.mttr_s" not in s2

    # A slower close across rounds is CI-visible as a regression.
    fast, slow = _result(7.0, 0.5), _result(7.0, 0.5)
    fast["extras"]["incident"] = {"value": 310.0, "mttd_s": 2.0,
                                  "mttr_s": 10.0}
    slow["extras"]["incident"] = {"value": 310.0, "mttd_s": 2.0,
                                  "mttr_s": 25.0}
    paths = _write_rounds(tmp_path, [_round(1, 0, fast),
                                     _round(2, 0, slow)])
    assert main(paths) == 1  # 2.5x MTTR: CI-visible
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [fast, slow]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["incident.mttr_s"]["verdict"] == "regressed"
    assert by_key["incident.mttd_s"]["verdict"] == "flat"


def test_coldstart_phase_series_trended_and_inverted(tmp_path):
    """ISSUE 18 satellite: the coldstart extra's per-arm per-phase
    recovery decomposition becomes ``{name}.phase_s.{arm}.{phase}``
    trend series with the INVERTED sign — a grown compile (or any
    other) phase is the regression, even when total recovery holds.
    Rounds without the extra contribute nothing (absent-not-zero)."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    def with_coldstart(compile_s):
        r = _result(7.0, 0.5)
        r["extras"]["coldstart"] = {
            "value": 700.0,
            "recovery_s": {"cold": 7.2, "promote": 0.01},
            "phases": {
                "cold": {"spawn": 0.7, "import": 0.3, "construct": 1.0,
                         "compile": compile_s, "warm": 0.1, "ready": 0.1},
                "promote": {"spawn": 0.0, "compile": 0.0, "ready": 0.01},
            },
        }
        return r

    s = extract_series(with_coldstart(5.0))
    assert s["coldstart.phase_s.cold.compile"] == 5.0
    assert s["coldstart.phase_s.cold.spawn"] == 0.7
    assert s["coldstart.phase_s.promote.compile"] == 0.0
    assert s["coldstart.recovery_s.cold"] == 7.2
    assert lower_is_better("coldstart.phase_s.cold.compile")
    assert lower_is_better("coldstart.recovery_s.promote")
    assert not lower_is_better("coldstart")  # the speedup headline

    # compile 5.0 → 7.0 across rounds: CI fails on the phase series.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_coldstart(5.0)),
        _round(2, 0, with_coldstart(7.0)),
    ])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(
             zip(paths, [with_coldstart(5.0), with_coldstart(7.0)])
         )],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["coldstart.phase_s.cold.compile"]["verdict"] == "regressed"
    assert by_key["coldstart.phase_s.promote.compile"]["verdict"] == "flat"

    # Absent-not-zero: an old round without the extra never reads as a
    # zero-second cold start.
    old = _result(7.0, 0.5)
    assert not any(".phase_s." in k for k in extract_series(old))


def test_tiled_gigapixel_series_trended_with_correct_signs(tmp_path):
    """ISSUE satellite: the tiled_gigapixel extra trends its capability
    point (peak_px — the largest image one chip served through the tile
    stream) with the NORMAL sign and its fixed-size per-request p99 with
    the INVERTED sign: a shrunk peak or a slower gigapixel request is
    the regression."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    def tiled(peak_px, p99):
        r = _result(7.0, 0.5)
        r["extras"]["tiled_gigapixel"] = {
            "peak_px": peak_px, "image_px": 8192, "tile": 2048,
            "latency_ms": {"p50": p99 / 2, "p99": p99},
        }
        return r

    s = extract_series(tiled(16384, 61000.0))
    assert s["tiled_gigapixel.peak_px"] == 16384.0
    assert s["tiled_gigapixel.latency_p99_ms"] == 61000.0
    assert not lower_is_better("tiled_gigapixel.peak_px")
    assert lower_is_better("tiled_gigapixel.latency_p99_ms")
    # The serving extra's own latency_ms stays UNtrended (its tail is
    # trended as the p99/p50 ratio; absolute latency is box noise) —
    # the extraction is gated on the tiled extra's peak_px shape.
    r = _result(7.0, 0.5)
    r["extras"]["serving_amoebanet3_32px"] = {
        "value": 2000.0, "latency_ms": {"p50": 10.0, "p99": 30.0},
    }
    assert "serving_amoebanet3_32px.latency_p99_ms" not in extract_series(r)
    # Shrunk capability regresses...
    good, shrunk = tiled(16384, 61000.0), tiled(8192, 61000.0)
    paths = _write_rounds(tmp_path, [_round(1, 0, good),
                                     _round(2, 0, shrunk)])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [good, shrunk]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["tiled_gigapixel.peak_px"]["verdict"] == "regressed"
    # ...and so does a slower fixed-size request at a held peak.
    slow = tiled(16384, 75000.0)
    paths = _write_rounds(tmp_path, [_round(1, 0, good),
                                     _round(2, 0, slow)])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [good, slow]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["tiled_gigapixel.latency_p99_ms"]["verdict"] == "regressed"


def test_fleet_recovery_by_domain_trended_and_inverted(tmp_path):
    """ISSUE CI satellite (HA front door): the fleet extra now records
    one recovery latency PER FAILURE DOMAIN ({"replica": ..., "router":
    ...} — warm-pool promotion vs router journal recovery); both become
    trend series with the regression sign inverted."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    r = _result(7.0, 0.5)
    r["extras"]["fleet_2replica"] = {
        "value": 350.0, "requeued": 4,
        "recovery_s": {"replica": 0.4, "router": 1.1},
        "journal_replays": {"deduped": 3, "redispatched": 1},
    }
    s = extract_series(r)
    assert s["fleet_2replica.recovery_s.replica"] == 0.4
    assert s["fleet_2replica.recovery_s.router"] == 1.1
    assert lower_is_better("fleet_2replica.recovery_s.replica")
    assert lower_is_better("fleet_2replica.recovery_s.router")
    # A None (unmeasured) domain contributes nothing rather than 0.0.
    r["extras"]["fleet_2replica"]["recovery_s"] = {
        "replica": 0.4, "router": None,
    }
    s = extract_series(r)
    assert "fleet_2replica.recovery_s.router" not in s
    # Regression drill: promotion recovery slipping back toward
    # cold-spawn time is CI-visible.
    fast, slow = _result(7.0, 0.5), _result(7.0, 0.5)
    fast["extras"]["fleet_2replica"] = {
        "value": 350.0, "recovery_s": {"replica": 0.4, "router": 1.0},
    }
    slow["extras"]["fleet_2replica"] = {
        "value": 350.0, "recovery_s": {"replica": 6.8, "router": 1.0},
    }
    paths = _write_rounds(tmp_path, [_round(1, 0, fast),
                                     _round(2, 0, slow)])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [fast, slow]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["fleet_2replica.recovery_s.replica"]["verdict"] \
        == "regressed"
    assert by_key["fleet_2replica.recovery_s.router"]["verdict"] == "flat"


def test_tail_ratio_trended_and_inverted(tmp_path):
    """ISSUE 10 CI satellite: the serving extra's tail summary
    (p99/p50 ratio) becomes a trend series with the regression sign
    inverted — a GROWING tail fails CI even when mean throughput holds."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    def with_tail(ratio):
        r = _result(7.0, 0.5)
        r["extras"]["serving_amoebanet3_32px"] = {
            "value": 2000.0,
            "tail": {"p99_p50_ratio": ratio, "samples": 3,
                     "threshold_ms": 45.0},
        }
        return r

    s = extract_series(with_tail(1.8))
    assert s["serving_amoebanet3_32px"] == 2000.0
    assert s["serving_amoebanet3_32px.tail_p99_p50_ratio"] == 1.8
    assert lower_is_better("serving_amoebanet3_32px.tail_p99_p50_ratio")
    assert not lower_is_better("serving_amoebanet3_32px")

    # Same throughput, fatter tail: CI-visible regression.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_tail(1.8)), _round(2, 0, with_tail(2.4)),
    ])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(
             paths, [with_tail(1.8), with_tail(2.4)]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key[
        "serving_amoebanet3_32px.tail_p99_p50_ratio"
    ]["verdict"] == "regressed"
    # A shrinking tail is the improvement direction.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_tail(2.4)), _round(2, 0, with_tail(1.8)),
    ])
    assert main(paths) == 0


def test_sched_ab_series_trended_and_inverted(tmp_path):
    """ISSUE 11 CI satellite: the serving extra's scheduler A/B embeds
    per-arm tight-class p99 under the fixed mixed-class load; bench-
    history trends it with the INVERTED sign (a growing tight-class p99
    fails CI) and the per-arm aggregate rps with the normal sign."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    def with_ab(edf_p99, fifo_p99=60.0, edf_rps=1700.0):
        r = _result(7.0, 0.5)
        r["extras"]["serving_amoebanet3_32px"] = {
            "value": 2000.0,
            "sched_ab": {
                "classes": "tight=250ms:99@10s,bulk=2.5s:99@60s",
                "arms": {
                    "edf": {"tight_p99_ms": edf_p99, "bulk_p99_ms": 70.0,
                            "rps": edf_rps, "deadline_misses": 0},
                    "fifo": {"tight_p99_ms": fifo_p99, "bulk_p99_ms": 55.0,
                             "rps": 1650.0, "deadline_misses": 0},
                },
                "tight_p99_improved": edf_p99 < fifo_p99,
            },
        }
        return r

    s = extract_series(with_ab(40.0))
    assert s["serving_amoebanet3_32px.sched_tight_p99_ms[edf]"] == 40.0
    assert s["serving_amoebanet3_32px.sched_tight_p99_ms[fifo]"] == 60.0
    assert s["serving_amoebanet3_32px.sched_rps[edf]"] == 1700.0
    assert lower_is_better(
        "serving_amoebanet3_32px.sched_tight_p99_ms[edf]"
    )
    assert not lower_is_better("serving_amoebanet3_32px.sched_rps[edf]")

    # Growing tight-class p99 on the EDF arm: CI-visible regression.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_ab(40.0)), _round(2, 0, with_ab(55.0)),
    ])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(
             paths, [with_ab(40.0), with_ab(55.0)]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key[
        "serving_amoebanet3_32px.sched_tight_p99_ms[edf]"
    ]["verdict"] == "regressed"
    # Shrinking tight p99 is the improvement; a dropped EDF rps is the
    # throughput regression (normal sign).
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_ab(55.0)), _round(2, 0, with_ab(40.0)),
    ])
    assert main(paths) == 0
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_ab(40.0, edf_rps=1700.0)),
        _round(2, 0, with_ab(40.0, edf_rps=1400.0)),
    ])
    assert main(paths) == 1


def test_peak_hbm_series_regresses_on_growth(tmp_path):
    """ISSUE satellite: memory series get the SAME verdict treatment as
    throughput — tolerance band, compare against the last round that
    measured — but with the sign inverted: a grown footprint regresses
    (CI exit 1), a shrunk one improves."""
    grown, shrunk = _result(7.0, 0.5), _result(7.0, 0.5)
    base = _result(7.0, 0.5)
    base["hlo"] = {"peak_hbm_bytes": 10e9}
    grown["hlo"] = {"peak_hbm_bytes": 12e9}     # +20% footprint
    shrunk["hlo"] = {"peak_hbm_bytes": 8e9}     # -20% footprint
    paths = _write_rounds(tmp_path, [_round(1, 0, base),
                                     _round(2, 0, grown)])
    assert main(paths) == 1  # growth is the regression
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [base, grown]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["hlo.peak_hbm_bytes"]["verdict"] == "regressed"
    # Throughput keys keep the normal direction in the same run.
    assert by_key["amoebanetd_1024px_bs2_train_tpu"]["verdict"] == "flat"

    cmp = compare(
        [{"path": "a", "n": 1, "rc": 0, "result": base},
         {"path": "b", "n": 2, "rc": 0, "result": shrunk}],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["hlo.peak_hbm_bytes"]["verdict"] == "improved"
    assert cmp["ok"] is True
    # Inside the band: flat, either direction.
    near = _result(7.0, 0.5)
    near["hlo"] = {"peak_hbm_bytes": 10.2e9}
    cmp = compare(
        [{"path": "a", "n": 1, "rc": 0, "result": base},
         {"path": "b", "n": 2, "rc": 0, "result": near}],
        tolerance=0.05, strict=False,
    )
    assert {k["key"]: k for k in cmp["keys"]}[
        "hlo.peak_hbm_bytes"
    ]["verdict"] == "flat"


def test_trend_improvement_exits_zero(tmp_path, capsys):
    paths = _write_rounds(tmp_path, [
        _round(1, 1, None),                      # failed round: no data
        _round(2, 0, _result(5.0, 0.50, peak=2048)),
        _round(3, 0, _result(7.0, 0.51, peak=4096)),
    ])
    rc = main(paths + ["--json", str(tmp_path / "cmp.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "improved" in out and "flat" in out
    assert "0 regression(s)" in out
    cmp = json.loads((tmp_path / "cmp.json").read_text())
    assert cmp["ok"] is True
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["amoebanetd_1024px_bs2_train_tpu"]["verdict"] == "improved"
    assert by_key["amoebanetd_1024px_bs2_train_tpu"]["values"] == [
        None, 5.0, 7.0,
    ]
    assert by_key["resnet110_2048px_bs1"]["verdict"] == "flat"  # +2% < 5%


def test_regression_beyond_tolerance_exits_nonzero(tmp_path, capsys):
    paths = _write_rounds(tmp_path, [
        _round(1, 0, _result(7.0, 0.50)),
        _round(2, 0, _result(6.0, 0.50)),        # -14% headline
    ])
    rc = main(paths)
    out = capsys.readouterr().out
    assert rc == 1
    assert "regressed" in out
    assert "1 regression(s)" in out
    # Inside a wider band the same delta passes.
    assert main(paths + ["--tolerance", "0.2"]) == 0


def test_regression_compares_against_last_round_that_measured(tmp_path):
    """A round that skipped a key (budget, failure) must not reset the
    baseline — the comparison reaches back to the last real measurement."""
    paths = _write_rounds(tmp_path, [
        _round(1, 0, _result(7.0, 0.50)),
        _round(2, 1, None),                      # nothing measured
        _round(3, 0, _result(6.0, 0.50)),        # vs r1, not vs nothing
    ])
    rounds = [json.load(open(p)) for p in paths]
    cmp = compare(
        [{"path": p, "n": r["n"], "rc": r["rc"], "result": r["parsed"]}
         for p, r in zip(paths, rounds)],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    head = by_key["amoebanetd_1024px_bs2_train_tpu"]
    assert head["previous"] == 7.0
    assert head["verdict"] == "regressed"
    assert cmp["ok"] is False
    render_table(cmp)  # renders with a None-valued middle round


def test_key_gone_is_reported_but_fails_only_in_strict(tmp_path):
    paths = _write_rounds(tmp_path, [
        _round(1, 0, _result(7.0, 0.50, peak=2048)),
        _round(2, 0, _result(7.0, 0.50)),        # peak walk skipped
    ])
    assert main(list(paths)) == 0
    assert main(list(paths) + ["--strict"]) == 1


def test_latest_round_without_result_fails(tmp_path):
    paths = _write_rounds(tmp_path, [
        _round(1, 0, _result(7.0, 0.50)),
        _round(2, 1, None),
    ])
    assert main(paths) == 1


def test_cli_dispatch_through_analyze(tmp_path, capsys):
    """ISSUE satellite (CLI smoke): the subcommand routes through the
    ``python -m mpi4dl_tpu.analyze`` front door without touching the
    lint path's jax setup."""
    from mpi4dl_tpu.analysis.cli import main as cli_main

    paths = _write_rounds(tmp_path, [
        _round(1, 0, _result(5.0, 0.50)),
        _round(2, 0, _result(7.0, 0.52)),
    ])
    rc = cli_main(["bench-history", *paths, "--tolerance", "0.1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "amoebanetd_1024px_bs2_train_tpu" in out


def test_runs_on_the_committed_round_files(capsys):
    """The real BENCH_r*.json history must parse and render end-to-end;
    the verdict is whatever the trajectory says (this test pins the
    reader, not the repo's perf)."""
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not files:
        pytest.skip("no committed bench rounds in this checkout")
    rc = main(files)
    out = capsys.readouterr().out
    assert rc in (0, 1)
    assert "regression(s)" in out
    # Round labels come from the files' own "n" fields.
    assert "r01" in out or "#0" in out

def test_overlap_series_trended_with_correct_signs(tmp_path):
    """ISSUE satellite: the sp2x2_overlap extra's per-arm measured
    overlap ratio and SP step time become trend series — a FALLING
    overlap ratio fails CI (normal higher-is-better direction), while
    the step time carries the inverted sign (growing fails), mirroring
    recovery_s/peak_hbm_bytes. The headline attribution's ratio is
    trended too."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    def with_overlap(mono_ratio, dec_ratio, dec_step):
        r = _result(7.0, 0.5)
        r["attribution"] = {
            "overlap": {"overlap_ratio": 0.61, "verdict": "overlapped"},
            "conv_impl": "monolithic",
        }
        r["extras"]["sp2x2_overlap"] = {"arms": {
            "monolithic": {"trace_overlap_ratio": mono_ratio,
                           "step_time_s": 0.9},
            "decomposed": {"trace_overlap_ratio": dec_ratio,
                           "step_time_s": dec_step},
        }}
        return r

    s = extract_series(with_overlap(0.60, 0.64, 1.4))
    assert s["attribution.trace_overlap_ratio"] == 0.61
    assert s["sp2x2_overlap.trace_overlap_ratio[monolithic]"] == 0.60
    assert s["sp2x2_overlap.trace_overlap_ratio[decomposed]"] == 0.64
    assert s["sp2x2_overlap.step_time_s[decomposed]"] == 1.4
    assert not lower_is_better("sp2x2_overlap.trace_overlap_ratio[decomposed]")
    assert not lower_is_better("attribution.trace_overlap_ratio")
    assert lower_is_better("sp2x2_overlap.step_time_s[decomposed]")

    # A falling decomposed overlap ratio is a CI-visible regression.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_overlap(0.60, 0.64, 1.4)),
        _round(2, 0, with_overlap(0.60, 0.50, 1.4)),   # ratio fell 22%
    ])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0,
          "result": r}
         for i, (p, r) in enumerate(zip(paths, [
             with_overlap(0.60, 0.64, 1.4), with_overlap(0.60, 0.50, 1.4),
         ]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key[
        "sp2x2_overlap.trace_overlap_ratio[decomposed]"
    ]["verdict"] == "regressed"

    # A grown SP step time regresses; a grown ratio improves.
    cmp = compare(
        [{"path": "a", "n": 1, "rc": 0,
          "result": with_overlap(0.60, 0.64, 1.4)},
         {"path": "b", "n": 2, "rc": 0,
          "result": with_overlap(0.60, 0.70, 1.8)}],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["sp2x2_overlap.step_time_s[decomposed]"][
        "verdict"] == "regressed"
    assert by_key["sp2x2_overlap.trace_overlap_ratio[decomposed]"][
        "verdict"] == "improved"
    assert cmp["ok"] is False


def test_pipeline_series_trended_with_correct_signs(tmp_path):
    """ISSUE 14 CI satellite: the pipeline extra's per-arm measured
    bubble fraction trends with the INVERTED sign (a grown bubble fails
    CI) and the per-arm img/s with the normal sign; rounds from before
    the extra existed contribute nothing (absent-not-zero)."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    def with_pipeline(fb_bubble, fb_ips):
        r = _result(7.0, 0.5)
        r["extras"]["pipeline"] = {"arms": {
            "gpipe": {"bubble_fraction": 0.2, "img_per_s": 5.6,
                      "analytic_bubble_fraction": 0.2},
            "1f1b": {"bubble_fraction": fb_bubble, "img_per_s": fb_ips,
                     "analytic_bubble_fraction": 0.1429},
        }, "bubble_improved": fb_bubble < 0.2}
        return r

    s = extract_series(with_pipeline(0.143, 4.8))
    assert s["pipeline.bubble_fraction[gpipe]"] == 0.2
    assert s["pipeline.bubble_fraction[1f1b]"] == 0.143
    assert s["pipeline.img_per_s[1f1b]"] == 4.8
    assert lower_is_better("pipeline.bubble_fraction[1f1b]")
    assert not lower_is_better("pipeline.img_per_s[1f1b]")

    # Absent-not-zero: an old round without the extra yields no pipeline
    # keys, and the comparison reaches past it to the last measurement.
    old = _result(7.0, 0.5)
    assert not any(k.startswith("pipeline.") for k in extract_series(old))
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_pipeline(0.143, 4.8)),
        _round(2, 0, old),
        _round(3, 0, with_pipeline(0.143, 4.8)),
    ])
    assert main(paths) == 0

    # A grown 1f1b bubble is a CI-visible regression even at flat img/s.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_pipeline(0.143, 4.8)),
        _round(2, 0, with_pipeline(0.19, 4.8)),
    ])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(
             paths, [with_pipeline(0.143, 4.8), with_pipeline(0.19, 4.8)]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["pipeline.bubble_fraction[1f1b]"]["verdict"] == "regressed"
    # A dropped img/s is the throughput regression (normal sign).
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_pipeline(0.143, 4.8)),
        _round(2, 0, with_pipeline(0.143, 3.9)),
    ])
    assert main(paths) == 1
    # A shrunk bubble is the improvement direction.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_pipeline(0.19, 4.8)),
        _round(2, 0, with_pipeline(0.143, 4.8)),
    ])
    assert main(paths) == 0


def test_serving_sharded_series_trended_with_correct_signs(tmp_path):
    """ISSUE CI satellite: the serving_sharded extra's per-arm measured
    overlap ratio trends with the normal sign (falling fails), the
    per-arm per-request p99 latency with the INVERTED sign (growing
    fails), and the per-arm serving throughput with the normal sign."""
    from mpi4dl_tpu.analysis.bench_history import compare, lower_is_better

    def with_sharded(dec_ratio, dec_p99, dec_rps):
        r = _result(7.0, 0.5)
        r["extras"]["serving_sharded"] = {"arms": {
            "monolithic": {
                "trace_overlap_ratio": 0.27,
                "latency_ms": {"p50": 12.0, "p99": 26.0},
                "throughput_rps": 300.0,
            },
            "decomposed": {
                "trace_overlap_ratio": dec_ratio,
                "latency_ms": {"p50": 13.0, "p99": dec_p99},
                "throughput_rps": dec_rps,
            },
        }}
        return r

    s = extract_series(with_sharded(0.58, 24.0, 295.0))
    assert s["serving_sharded.trace_overlap_ratio[decomposed]"] == 0.58
    assert s["serving_sharded.latency_p99_ms[decomposed]"] == 24.0
    assert s["serving_sharded.rps[decomposed]"] == 295.0
    assert s["serving_sharded.latency_p99_ms[monolithic]"] == 26.0
    assert lower_is_better("serving_sharded.latency_p99_ms[decomposed]")
    assert not lower_is_better(
        "serving_sharded.trace_overlap_ratio[decomposed]"
    )
    assert not lower_is_better("serving_sharded.rps[decomposed]")

    # Growing p99 regresses (inverted); falling ratio regresses (normal);
    # growing rps improves.
    cmp = compare(
        [{"path": "a", "n": 1, "rc": 0,
          "result": with_sharded(0.58, 24.0, 295.0)},
         {"path": "b", "n": 2, "rc": 0,
          "result": with_sharded(0.40, 32.0, 340.0)}],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["serving_sharded.latency_p99_ms[decomposed]"][
        "verdict"] == "regressed"
    assert by_key["serving_sharded.trace_overlap_ratio[decomposed]"][
        "verdict"] == "regressed"
    assert by_key["serving_sharded.rps[decomposed]"]["verdict"] == "improved"
    assert cmp["ok"] is False

    # CI exit: a round whose sharded p99 grew past tolerance fails.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_sharded(0.58, 24.0, 295.0)),
        _round(2, 0, with_sharded(0.58, 30.0, 295.0)),  # p99 +25%
    ])
    assert main(paths) == 1


def test_costmodel_series_trended_with_correct_signs(tmp_path):
    """ISSUE 16 satellite: bench lines embed the static cost model's
    predictions (hlo.costmodel per interconnect) and the predicted-vs-
    measured overlap drift (attribution.costmodel). bench-history trends
    the predicted overlap ceiling with the NORMAL sign (a falling ceiling
    means the compiled schedule lost hideability), predicted comms
    seconds with the INVERTED sign (more bytes / lost async pairs), and
    drift with the INVERTED sign — growing model divergence fails CI."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    def with_costmodel(ici_ratio, ici_comms, drift):
        r = _result(7.0, 0.5)
        r["hlo"] = {
            "peak_hbm_bytes": 10e9,
            "costmodel": {
                "cpu": {"comms_s": 3.1e-4, "exposed_s": 3.1e-4,
                        "predicted_overlap_ratio": 0.0,
                        "overlap_claim": False},
                "ici": {"comms_s": ici_comms, "exposed_s": 0.0,
                        "predicted_overlap_ratio": ici_ratio,
                        "overlap_claim": True},
            },
        }
        r["attribution"] = {
            "overlap": {"overlap_ratio": 0.61, "verdict": "overlapped"},
            "costmodel": {
                "interconnect": "cpu",
                "predicted_overlap_ratio": ici_ratio,
                "overlap_claim": drift is not None,
                "overlap_drift": drift,
                "crosscheck": [],
            },
        }
        return r

    s = extract_series(with_costmodel(0.85, 2.8e-5, 0.10))
    assert s["costmodel.predicted_overlap_ratio[ici]"] == 0.85
    assert s["costmodel.predicted_overlap_ratio[cpu]"] == 0.0
    assert s["costmodel.predicted_comms_s[ici]"] == 2.8e-5
    assert s["costmodel.overlap_drift"] == 0.10
    assert not lower_is_better("costmodel.predicted_overlap_ratio[ici]")
    assert lower_is_better("costmodel.predicted_comms_s[ici]")
    assert lower_is_better("costmodel.overlap_drift")
    # CPU-mesh rounds record null drift (no overlap claim): absent-not-
    # zero, so the series starts with the first round that claims.
    assert "costmodel.overlap_drift" not in extract_series(
        with_costmodel(0.85, 2.8e-5, None)
    )

    # Growing drift is the CI-visible regression even at flat headline.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_costmodel(0.85, 2.8e-5, 0.05)),
        _round(2, 0, with_costmodel(0.85, 2.8e-5, 0.12)),
    ])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [
             with_costmodel(0.85, 2.8e-5, 0.05),
             with_costmodel(0.85, 2.8e-5, 0.12),
         ]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["costmodel.overlap_drift"]["verdict"] == "regressed"
    # A falling predicted ceiling regresses (normal sign); grown
    # predicted comms time regresses (inverted sign).
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_costmodel(0.85, 2.8e-5, 0.05)),
        _round(2, 0, with_costmodel(0.60, 2.8e-5, 0.05)),
    ])
    assert main(paths) == 1
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_costmodel(0.85, 2.8e-5, 0.05)),
        _round(2, 0, with_costmodel(0.85, 6.0e-5, 0.05)),
    ])
    assert main(paths) == 1
    # Shrinking drift is the improvement direction.
    paths = _write_rounds(tmp_path, [
        _round(1, 0, with_costmodel(0.85, 2.8e-5, 0.12)),
        _round(2, 0, with_costmodel(0.85, 2.8e-5, 0.05)),
    ])
    assert main(paths) == 0


def test_multitenant_series_trended_with_correct_signs(tmp_path):
    """ISSUE 17 satellite: the multitenant extra trends the victim's
    flood/solo p99 ratio with the INVERTED sign (a grown ratio means
    tenant isolation regressed) and Jain's fairness index with the
    NORMAL sign (falling fairness regresses); the tenancy-on rps rides
    the generic ``value`` path."""
    from mpi4dl_tpu.analysis.bench_history import lower_is_better

    def multitenant(rps, ratio, jain):
        r = _result(7.0, 0.5)
        r["extras"]["multitenant"] = {
            "value": rps, "overhead_pct": 0.8,
            "victim_p99_ratio": ratio, "fairness_index": jain,
            "served_by_tenant": {"bully": 200, "victim": 20},
        }
        return r

    s = extract_series(multitenant(300.0, 1.12, 0.97))
    assert s["multitenant"] == 300.0
    assert s["multitenant.victim_p99_ratio"] == 1.12
    assert s["multitenant.fairness_index"] == 0.97
    assert lower_is_better("multitenant.victim_p99_ratio")
    assert not lower_is_better("multitenant.fairness_index")
    assert not lower_is_better("multitenant")
    # A grown victim ratio regresses (isolation lost under the flood)...
    good, worse = multitenant(300.0, 1.1, 0.97), multitenant(300.0, 1.4, 0.97)
    paths = _write_rounds(tmp_path, [_round(1, 0, good),
                                     _round(2, 0, worse)])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [good, worse]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["multitenant.victim_p99_ratio"]["verdict"] == "regressed"
    # ...and so does falling fairness at a held ratio.
    unfair = multitenant(300.0, 1.1, 0.72)
    paths = _write_rounds(tmp_path, [_round(1, 0, good),
                                     _round(2, 0, unfair)])
    assert main(paths) == 1
    cmp = compare(
        [{"path": p, "n": i + 1, "rc": 0, "result": r}
         for i, (p, r) in enumerate(zip(paths, [good, unfair]))],
        tolerance=0.05, strict=False,
    )
    by_key = {k["key"]: k for k in cmp["keys"]}
    assert by_key["multitenant.fairness_index"]["verdict"] == "regressed"
    # An improving (shrinking) ratio exits clean.
    better = multitenant(300.0, 1.02, 0.99)
    paths = _write_rounds(tmp_path, [_round(1, 0, good),
                                     _round(2, 0, better)])
    assert main(paths) == 0
