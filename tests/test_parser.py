"""CLI parser parity (ref ``torchgems/parser.py:21-143``): the reference's
benchmark invocations must parse unchanged, including csv flags and the
TPU-era additions."""

from mpi4dl_tpu.parser import get_parser


def test_reference_invocation_parses():
    # Flags straight from the reference README's SP example.
    args = get_parser().parse_args(
        [
            "--batch-size", "2",
            "--parts", "4",
            "--split-size", "3",
            "--spatial-size", "1",
            "--num-spatial-parts", "4",
            "--slice-method", "square",
            "--image-size", "1024",
            "--num-epochs", "1",
            "--halo-D2",
            "--fused-layers", "2",
            "--local-DP", "4",
            "--times", "2",
            "--app", "3",
            "--enable-master-comm-opt",
            "--num-workers", "2",
            "--verbose",
        ]
    )
    assert args.batch_size == 2
    assert args.parts == 4
    assert args.split_size == 3
    assert args.spatial_size == 1
    assert args.num_spatial_parts == "4"
    assert args.slice_method == "square"
    assert args.halo_d2 is True
    assert args.fused_layers == 2
    assert args.local_DP == 4
    assert args.times == 2
    assert args.app == 3
    assert args.enable_master_comm_opt is True
    assert args.num_workers == 2
    assert args.verbose is True


def test_csv_parsing():
    import sys, os

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "benchmarks")
    )
    from common import parse_csv_ints

    assert parse_csv_ints("4,2") == [4, 2]
    assert parse_csv_ints("8") == [8]
    assert parse_csv_ints(None) is None


def test_tpu_additions_defaults():
    args = get_parser().parse_args([])
    assert args.precision == "bf16"
    assert args.max_steps is None
    assert args.checkpoint_dir is None
    assert args.resume is False
    assert args.trace_dir is None
