"""Gigapixel tiled inference (:mod:`mpi4dl_tpu.serve.tiled`) — the
halo-correct tile-streaming forward and its ``/predict_tiled`` surfaces.

Covers the ISSUE's tentpole invariants and satellites:

- **stitch exactness**: the tiled forward is BIT-IDENTICAL to the
  monolithic single-chip forward at sizes where both fit, across tile
  grids (square/rect cores, ragged last tiles, the single-tile
  degenerate window), through the model's stride-2 cells, with
  global-boundary tiles exercised by every grid (windows clamp to the
  image edge, where the conv's own zero padding IS the monolithic
  padding) — the PR-9 ``overlap_decompose`` equivalence bar. The
  bitwise half runs on a one-device backend (the deployment topology)
  in a subprocess; in this process, whose conftest simulates an
  8-device mesh, cross-shape programs carry the repo's documented f32
  reduction-order boundary and the degenerate same-shape grid stays
  bitwise;
- the margin derivation (``record_windowed_ops`` partition math) and the
  axis-plan invariants (constant window extent, core partition, ≥ margin
  of real data at every interior window edge);
- **packed-layout refusal** (packed columns fold W into C — overlap
  windows cannot be sliced, so geometry refuses loudly);
- the engine surface: a tiled ``ServingEngine`` serves through the
  unchanged batcher/scheduler stack with its own ``tiled`` SLO class,
  tiled_* metrics, footprint-ledger entries (tile executable + head),
  and a clean single-chip lint gate;
- **bounded memory** (ISSUE acceptance, compile-predicted CPU half): the
  tile executable's peak is bounded by the TILE geometry — constant
  across image sizes — and far below the monolithic forward's peak at
  the same image size;
- the fleet passthrough: a spawned worker serves ``POST /predict_tiled``
  (geometry on ``/healthz``) and a Router routes ``submit(tiled=True)``
  to it with the ``tiled`` flag journaled for router-death replay.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.evaluate import aot_compile_predict, collect_batch_stats
from mpi4dl_tpu.models.resnet import get_resnet_v1, get_resnet_v2
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.serve.tiled import (
    TiledPredictor,
    _axis_plan,
    section_margin,
    tile_geometry,
    tiled_engine,
)

SIZE = 56
DEPTH = 8


@pytest.fixture(scope="module")
def model():
    """One calibrated plain ResNet-v1 triple at 56 px (ragged-friendly:
    not a multiple of the default tile), shared by every stitch check so
    all comparisons use one set of weights."""
    cells = get_resnet_v1(depth=DEPTH, num_classes=10, pool_kernel=SIZE // 4)
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    cal = [jnp.asarray(rng.standard_normal((4, SIZE, SIZE, 3)), jnp.float32)]
    stats = collect_batch_stats(cells, params, cal)
    return cells, params, stats


@pytest.fixture(scope="module")
def monolithic(model):
    """The single-chip AOT forward (the engine's own executable path) at
    bucket 1 — the golden the stitched output must match bitwise."""
    cells, params, stats = model
    compiled = aot_compile_predict(
        cells, params, stats, (SIZE, SIZE, 3), [1]
    )[1]
    return lambda x: np.asarray(compiled(params, stats, x[None]))[0]


def _examples(n, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
        for _ in range(n)
    ]


# -- geometry: partition math + plan invariants -------------------------------


def test_geometry_margin_matches_partition_math(model):
    """The derived margin is the hand-computed cumulative receptive-field
    growth of ResNet-v1 depth-8: stem 3×3 (p1·d1) + stack0 (p1·d1 twice)
    + stack1 (p1·d1 + p1·d2) + stack2 (p1·d2 + p1·d4) = 12, stride 4."""
    cells, params, stats = model
    g = tile_geometry(cells, params, stats, (SIZE, SIZE, 3), 16)
    assert g.stride_hw == (4, 4)
    assert g.margin_hw == (12, 12)
    assert g.window_hw == (16 + 24, 16 + 24)
    assert g.grid == (4, 4)  # cores 16,16,16,8 — ragged last tile
    assert [t[1] for t in g.tiles_h] == [16, 16, 16, 8]
    # The recorded op stack is the forensic trail the margin came from.
    assert all(op["kind"] in ("conv", "pool") for op in g.ops)
    assert section_margin(g.ops, (SIZE, SIZE)) == (12, 12)


def test_section_margin_formula_units():
    """Per-op contribution is max(pad, kernel−1−pad) × downsampling —
    odd SAME convs contribute pad·d, a padding-0 even pool contributes
    (k−1)·d, and a packed op refuses."""
    ops = [
        {"kind": "conv", "kernel": (3, 3), "strides": (1, 1),
         "padding": (1, 1), "input_hw": (64, 64)},
        {"kind": "conv", "kernel": (3, 3), "strides": (2, 2),
         "padding": (1, 1), "input_hw": (64, 64)},
        {"kind": "pool", "kernel": (2, 2), "strides": (2, 2),
         "padding": (0, 0), "input_hw": (32, 32)},
        {"kind": "conv", "kernel": (1, 1), "strides": (1, 1),
         "padding": (0, 0), "input_hw": (16, 16)},
    ]
    # 1·1 + 1·1 + (2−1−0)·2 + 0·4 = 4 per dim.
    assert section_margin(ops, (64, 64)) == (4, 4)
    with pytest.raises(ValueError, match="packed"):
        section_margin(
            [{"kind": "packed", "kernel": (3, 3), "strides": (1, 1),
              "padding": (1, 1), "input_hw": (64, 8)}], (64, 64),
        )
    # Non-uniform extents (op input does not divide the image) refuse.
    with pytest.raises(ValueError, match="downsampling"):
        section_margin(
            [{"kind": "conv", "kernel": (3, 3), "strides": (1, 1),
              "padding": (1, 1), "input_hw": (48, 48)}], (64, 64),
        )


def test_axis_plan_invariants():
    """Every window has the SAME extent (one executable shape); cores
    partition [0, n) exactly; every interior window edge sits ≥ margin
    from its core (a window edge inside the image carries real data),
    while an edge AT the image boundary may touch the core (the conv's
    zero padding there is the monolithic padding)."""
    for n, tile, margin in [
        (64, 16, 12), (56, 16, 12), (128, 32, 12), (64, 64, 12),
        (48, 16, 20), (256, 64, 4),
    ]:
        entries, win = _axis_plan(n, tile, margin)
        assert sum(e[1] for e in entries) == n
        pos = 0
        for c0, clen, a in entries:
            assert c0 == pos
            pos += clen
            assert 0 <= a <= n - win
            lo, hi = c0 - a, (a + win) - (c0 + clen)
            assert lo >= (margin if a > 0 else 0)
            assert hi >= (margin if a + win < n else 0)
            if win < n:
                assert lo >= 0 and hi >= 0
        if tile + 2 * margin >= n:
            assert entries == ((0, n, 0),) and win == n


# -- stitch exactness ---------------------------------------------------------


def test_tiled_forward_bit_identical_single_device_subprocess():
    """ISSUE acceptance: on a SINGLE-device backend — the tiled
    predictor's actual deployment topology (one chip serving huge
    images) — the tiled forward equals the monolithic forward BIT FOR
    BIT across tile grids (square/rect cores, ragged last tiles, the
    single-window degenerate) and model families (v1, and v2's
    pre-activation bottlenecks with 1×1 stride-2 shortcuts). Runs in a
    subprocess because this suite's conftest simulates an 8-device mesh,
    under which XLA:CPU partitions intra-op work per SHAPE and two
    programs computing the same window bytes can round differently in
    the last bit (the repo's standard cross-executable f32 boundary —
    see the in-harness tolerance test below)."""
    import re
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    # Undo the harness's 8-virtual-device XLA flag (jax 0.4.x channel).
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" in flags:
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "--xla_force_host_platform_device_count=1", flags,
        )
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tests",
                                      "_tiled_equiv_check.py")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    line = next(
        (ln for ln in reversed(proc.stdout.splitlines())
         if ln.startswith("{")), None,
    )
    assert line is not None, (
        f"equiv check emitted no JSON (rc={proc.returncode}): "
        f"{proc.stderr[-500:]}"
    )
    verdict = json.loads(line)
    assert verdict["ok"], verdict["bit_identical"]
    assert len(verdict["bit_identical"]) == 4  # 3 v1 grids + v2


@pytest.mark.parametrize("tile", [16, 48], ids=["t16-ragged", "t48-degen"])
def test_tiled_forward_matches_monolithic_under_mesh_harness(
    model, monolithic, tile
):
    """In-harness half of the equivalence suite (this process simulates
    an 8-device mesh): the tiled forward is deterministic run to run,
    agrees with the monolithic forward at the repo's documented
    cross-executable f32 boundary for shape-changing grids, and stays
    BITWISE for the degenerate single-window grid (window == image: the
    section program has the monolithic shape, which also pins that the
    section/head SPLIT itself is bitwise-safe)."""
    cells, params, stats = model
    pred = TiledPredictor(cells, params, stats, (SIZE, SIZE, 3), tile)
    handle = pred.compile_bucket(1)
    for i, x in enumerate(_examples(2, seed=3)):
        got = pred.run(handle, x[None])[0]
        want = monolithic(x)
        assert got.dtype == want.dtype and got.shape == want.shape
        assert np.array_equal(got, pred.run(handle, x[None])[0])
        if tile == 48:
            assert np.array_equal(got, want), f"example {i}"
        else:
            np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_batched_tile_buckets_tolerance_and_determinism(model, monolithic):
    """``tile_batch>1`` is the opt-in throughput lever: windows batched
    into power-of-two tile buckets are deterministic run to run and
    agree with the monolithic forward at the repo's documented
    cross-executable f32 reduction-order boundary (a batch-2 window
    program is a DIFFERENT program — the same ~1e-7 boundary as
    cross-bucket rows in the plain engine; ``tile_batch=1``, the
    default, is the bitwise path asserted above)."""
    cells, params, stats = model
    pred = TiledPredictor(
        cells, params, stats, (SIZE, SIZE, 3), 16, tile_batch=2
    )
    handle = pred.compile_bucket(1)
    x = _examples(1, seed=11)[0]
    a = pred.run(handle, x[None])[0]
    b = pred.run(handle, x[None])[0]
    assert np.array_equal(a, b)
    np.testing.assert_allclose(a, monolithic(x), rtol=0, atol=5e-6)


def test_packed_layout_refused():
    """Packed activations fold image columns into channels; overlap-read
    windows cannot be sliced from that layout, so geometry refuses
    loudly instead of mis-stitching (structural check — fires before any
    tracing, so no params are needed)."""
    cells = get_resnet_v2(depth=11, pool_kernel=8, layout="packed")
    with pytest.raises(ValueError, match="packed"):
        tile_geometry(
            cells, [{}] * len(cells), [{}] * len(cells), (32, 32, 3), 8
        )


def test_misaligned_tile_and_image_refused(model):
    cells, params, stats = model
    with pytest.raises(ValueError, match="multiple of the section stride"):
        tile_geometry(cells, params, stats, (SIZE, SIZE, 3), 10)
    with pytest.raises(ValueError, match="does not divide"):
        tile_geometry(cells, params, stats, (SIZE - 2, SIZE - 2, 3), 16)


# -- engine surface -----------------------------------------------------------


def test_tiled_engine_serves_bit_identical_with_own_slo_class(
    model, monolithic
):
    """End to end through the UNCHANGED batcher/EDF stack: the tiled
    engine AOT-warms, serves bit-identical results, accounts requests
    under its own ``tiled`` SLO class, publishes the tiled_* series,
    records tile + head executables in the footprint ledger, and passes
    the single-chip lint gate."""
    cells, params, stats = model
    eng = tiled_engine(
        cells, params, stats, (SIZE, SIZE, 3), tile=16, max_queue=8,
    )
    try:
        eng.assert_warm()
        assert eng.buckets == (1,)
        assert [c.name for c in eng.slo_classes] == ["tiled"]
        eng.start()
        xs = _examples(3, seed=7)
        futs = [eng.submit(x) for x in xs]
        outs = [f.result(timeout=120) for f in futs]
        for x, got in zip(xs, outs):
            # Under the 8-device harness, cross-shape programs carry the
            # documented f32 boundary; the bitwise claim is pinned by the
            # single-device subprocess test above.
            np.testing.assert_allclose(got, monolithic(x), rtol=0,
                                       atol=5e-6)
        s = eng.stats()
        # Geometry + per-request facts ride stats() (the loadgen/CLI
        # report's `tiled` block).
        assert s["tiled"]["grid"] == [4, 4]
        assert s["tiled"]["requests"] == 3  # warm-up runs excluded
        assert s["tiled"]["tiles_total"] == 3 * 16
        assert s["tiled"]["stitch_s"]["p50"] is not None
        # tiled_* series are live on the engine registry.
        reg = eng.registry
        assert reg.get("tiled_tiles_total").value() == 3 * 16
        assert reg.get("tiled_tiles_per_request").value() == 16
        assert reg.get("tiled_tile_batches_total").value(bucket=1) == 3 * 16
        # Requests burned the tiled class's series, nobody else's.
        lat_series = reg.get("serve_class_latency_seconds").snapshot_series()
        assert [
            (s["labels"]["slo_class"], s["count"]) for s in lat_series
        ] == [("tiled", 3)]
        # Footprint ledger: the engine bucket entry IS the tile
        # executable's peak; the head is its own entry.
        bucket_e = eng.memory_ledger.get("serve_tiled", bucket=1)
        tile_e = eng.memory_ledger.get("serve_tiled_tile", bucket=1)
        head_e = eng.memory_ledger.get("serve_tiled_head")
        assert bucket_e["peak_bytes"] == tile_e["peak_bytes"]
        assert head_e["peak_bytes"] > 0
        # Per-request tiled facts ride the span events (flight ring).
        ev = [
            e for e in eng.flight.tail(100)
            if e.get("name") == "serve.request"
        ]
        assert ev and ev[-1]["attrs"]["tiled"]["tiles"] == 16
        rep = eng.lint_report()
        assert rep.ok, rep.findings
    finally:
        eng.stop()


def test_bounded_memory_tile_executable_not_image(model):
    """ISSUE acceptance (compile-predicted half — the live device_hbm_*
    gauges are absent-not-wrong on CPU): the tiled forward's peak is
    bounded by the TILE geometry. The section executable's predicted
    peak is IDENTICAL across image sizes (same window, same program) and
    far below the monolithic forward's peak at the same image, which
    grows with the image instead."""
    from mpi4dl_tpu.analysis.memory_plan import (
        predict_serve_peak,
        predict_tiled_peak,
    )

    cells = get_resnet_v1(depth=DEPTH, num_classes=10, pool_kernel=32)
    t128 = predict_tiled_peak(cells, 128, 32, tile_bucket=1)
    cells = get_resnet_v1(depth=DEPTH, num_classes=10, pool_kernel=64)
    t256 = predict_tiled_peak(cells, 256, 32, tile_bucket=1)
    # Bounded: the hot-loop executable does not grow with the image.
    assert t128["tile_peak_bytes"] == t256["tile_peak_bytes"]
    # The stitched-feature head is the image-bound residual term — it
    # grows with the image (1/stride² of it), the tile term does not.
    assert t256["head_peak_bytes"] > t128["head_peak_bytes"]
    # And the monolithic forward at the same image dwarfs both.
    mono256 = predict_serve_peak(cells, 256, 1)
    assert mono256["peak_bytes"] > 4 * t256["peak_bytes"]


# -- fleet passthrough --------------------------------------------------------


def test_journal_carries_tiled_flag(tmp_path):
    """A tiled accept survives a router death as a TILED orphan — the
    successor re-dispatches to /predict_tiled, never /predict."""
    from mpi4dl_tpu.fleet.journal import RouterJournal, scan

    path = str(tmp_path / "rt.journal")
    j = RouterJournal(path)
    j.accept("t-plain", np.zeros((2, 2, 3), np.float32), 30.0)
    j.accept("t-tiled", np.zeros((4, 4, 3), np.float32), 30.0, tiled=True)
    j.done("t-plain", "served")
    j.close()
    rec = scan(path)
    assert [o.trace_id for o in rec.orphans] == ["t-tiled"]
    assert rec.orphans[0].tiled is True


def test_worker_and_router_tiled_passthrough(tmp_path):
    """ISSUE satellite (spawned-worker tier-1): a worker spawned with
    ``--tiled 48x48`` serves POST /predict_tiled (geometry on /healthz),
    the ReplicaClient reaches it with ``tiled=True``, and a Router
    routes ``submit(tiled=True)`` through its normal dispatch/ledger
    machinery to the same surface — with the tiled flag journaled."""
    import urllib.request

    from mpi4dl_tpu.fleet.journal import scan
    from mpi4dl_tpu.fleet.replica import (
        ReplicaClient,
        ReplicaProcess,
        worker_cmd,
    )
    from mpi4dl_tpu.fleet.router import Router

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    proc = ReplicaProcess(
        "r0",
        worker_cmd(["--image-size", "16", "--max-batch", "1",
                    "--tiled", "48x48", "--tile", "16"]),
        base_dir=str(tmp_path / "fleet"),
        env=env,
        log_path=str(tmp_path / "r0.log"),
    )
    router = None
    try:
        proc.spawn()
        ports = proc.wait_ready(timeout_s=420.0)
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{ports['metrics_port']}/healthz", timeout=10
        ).read().decode())
        assert snap["tiled"]["image"] == [48, 48]
        assert snap["tiled"]["grid"] == [3, 3]
        client = ReplicaClient(
            "r0", f"http://127.0.0.1:{ports['predict_port']}"
        )
        x = np.zeros((48, 48, 3), np.float32)
        direct, payload = client.predict(
            x, trace_id="tiled-rpc-1", deadline_s=120.0, timeout_s=180.0,
            tiled=True,
        )
        assert np.asarray(direct).shape == (10,)
        # The interactive surface still answers at ITS example shape.
        plain, _ = client.predict(
            np.zeros((16, 16, 3), np.float32), trace_id="plain-rpc-1",
            deadline_s=60.0, timeout_s=120.0,
        )
        assert np.asarray(plain).shape == (10,)
        # Router passthrough: engine-shaped admission, tiled dispatch,
        # journaled tiled flag.
        journal = str(tmp_path / "router.journal")
        router = Router(
            example_shape=(16, 16, 3), journal_path=journal,
            default_deadline_s=120.0,
        )
        router.add_replica(
            "r0", f"http://127.0.0.1:{ports['predict_port']}",
            f"http://127.0.0.1:{ports['metrics_port']}",
        )
        fut = router.submit(x, tiled=True, trace_id="tiled-routed-1")
        routed = fut.result(timeout=180.0)
        # The worker's idempotency cache served trace-id tiled-rpc-1
        # already; this NEW id executed on the tiled engine — and must
        # equal the direct RPC result bitwise (same executable).
        assert np.array_equal(np.asarray(routed), np.asarray(direct))
        lines = [json.loads(ln) for ln in open(journal)]
        acc = next(
            ln for ln in lines
            if ln.get("kind") == "accept"
            and ln["trace_id"] == "tiled-routed-1"
        )
        assert acc["tiled"] is True and acc["shape"] == [48, 48, 3]
        assert not scan(journal).orphans  # completed → nothing to replay
    finally:
        if router is not None:
            router.stop(drain=False)
        proc.terminate()


# -- CLI ----------------------------------------------------------------------


def test_serve_cli_tiled_end_to_end(tmp_path):
    """``python -m mpi4dl_tpu.serve --tiled HxW`` — builds the tiled
    engine, drives the load generator at the large example shape, and
    reports per-request tile counts + stitch latency alongside
    p50/p90/p99, with the lint gate green."""
    from mpi4dl_tpu.serve.__main__ import main

    out_path = tmp_path / "tiled.json"
    rc = main([
        "--tiled", "48x48", "--tile", "16",
        "--requests", "3", "--concurrency", "2", "--serial", "0",
        "--deadline-ms", "120000", "--lint", "--json", str(out_path),
    ])
    assert rc == 0
    rep = json.load(open(out_path))
    assert rep["buckets"] == [1]
    assert rep["loadgen"]["served"] == 3
    assert rep["loadgen"]["errors"] == 0
    t = rep["tiled"]
    assert t["grid"] == [3, 3] and t["tiles_per_request"] == 9
    assert t["requests"] == 3 and t["tiles_total"] == 27
    assert t["stitch_s"]["p50"] is not None
    assert t["tile_stream_s"]["p50"] is not None
    assert rep["lint"]["ok"]
