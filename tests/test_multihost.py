"""Multi-host plumbing, exercised on the single-process CPU mesh.

True multi-process coverage needs a pod; these tests pin down everything
testable in one process: slice detection, the single-slice mesh fallback,
DP-vs-slices divisibility validation, and that ``host_local_batch`` feeds a
trainer identically to ``shard_batch`` (local == global when there is one
process)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.config import ParallelConfig
from mpi4dl_tpu.models.resnet import get_resnet_v1
from mpi4dl_tpu.parallel import multihost
from mpi4dl_tpu.train import Trainer


def test_num_slices_single():
    assert multihost.num_slices() == 1


def test_make_multihost_mesh_falls_back_single_slice():
    cfg = ParallelConfig(
        batch_size=4, split_size=1, spatial_size=0, data_parallel=2
    )
    mesh = multihost.make_multihost_mesh(cfg)
    assert mesh.shape == dict(zip(multihost.MESH_AXES, cfg.mesh_shape))
    # Same device placement as the plain factory.
    assert (mesh.devices == cfg.make_mesh().devices).all()


def test_initialize_distributed_swallows_only_unconfigured(monkeypatch):
    """Single process with no coordinator: init failure is the expected
    'nothing to join' case. With a coordinator configured (env or argument),
    the same failure MUST propagate — swallowing it would silently degrade a
    pod launch to N independent single-host jobs."""
    calls = []

    def fake_init(coordinator_address=None, num_processes=None, process_id=None):
        calls.append(coordinator_address)
        raise RuntimeError("backend already initialized")

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    for var in multihost._COORDINATOR_ENV_VARS + multihost._MULTIPROC_ENV_MARKERS:
        monkeypatch.delenv(var, raising=False)
    # CI may itself run under Slurm/MPI; pin auto-detection off so the
    # "unconfigured" branch is what's actually exercised.
    monkeypatch.setattr(multihost, "_cluster_autodetected", lambda: False)
    multihost.initialize_distributed()  # unconfigured → swallowed
    assert calls == [None]  # initialize was actually attempted

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "badhost:1234")
    with pytest.raises(RuntimeError):
        multihost.initialize_distributed()
    with pytest.raises(RuntimeError):  # explicit argument, no env
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS")
        multihost.initialize_distributed(coordinator_address="badhost:1234")


def test_initialize_distributed_noop_when_initialized(monkeypatch):
    # raising=False: jax 0.4.x has no is_initialized; the compat probe
    # (mpi4dl_tpu.compat.distributed_is_initialized) prefers the
    # attribute whenever it exists, so the monkeypatch works on any jax.
    monkeypatch.setattr(
        jax.distributed, "is_initialized", lambda: True, raising=False
    )

    def boom(*a, **k):  # must not be reached
        raise AssertionError("initialize called despite is_initialized()")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    multihost.initialize_distributed()


class _FakeSliceDev:
    def __init__(self, slice_index):
        self.slice_index = slice_index


def test_num_slices_counts_granules():
    devs = [_FakeSliceDev(0), _FakeSliceDev(0), _FakeSliceDev(1), _FakeSliceDev(1)]
    assert multihost.num_slices(devs) == 2


def test_multihost_mesh_indivisible_dp(monkeypatch):
    monkeypatch.setattr(multihost, "num_slices", lambda devices=None: 2)
    # dp doesn't factor over the slices, but the whole mesh fits inside one
    # slice → runs there (pure SP/LP configs on multi-slice systems).
    cfg = ParallelConfig(batch_size=3, split_size=1, spatial_size=0, data_parallel=3)
    mesh = multihost.make_multihost_mesh(cfg, jax.devices()[:6])
    assert mesh.shape == dict(zip(multihost.MESH_AXES, cfg.mesh_shape))
    # ...and when it does NOT fit in one slice either, reject.
    cfg2 = ParallelConfig(batch_size=3, split_size=4, spatial_size=0, data_parallel=3)
    with pytest.raises(ValueError, match="must divide"):
        multihost.make_multihost_mesh(cfg2, jax.devices()[:6])


def test_data_shard_single_process():
    cfg = ParallelConfig(batch_size=4, split_size=1, spatial_size=0, data_parallel=2)
    mesh = cfg.make_mesh()
    assert multihost.data_shard(mesh) == (0, 1)
    assert multihost.local_batch_size(mesh, 8) == 8


def test_host_local_batch_feeds_trainer():
    """host_local_batch == shard_batch in a single-process world: a train
    step from each must produce identical metrics."""
    cfg = ParallelConfig(
        batch_size=8, split_size=1, spatial_size=0, data_parallel=4, image_size=32
    )
    cells = get_resnet_v1(depth=8)
    trainer = Trainer(cells, num_spatial_cells=0, config=cfg)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=(8,)).astype(np.int32)

    state = trainer.init(jax.random.PRNGKey(0), x.shape)
    xs, ys = trainer.shard_batch(jnp.asarray(x), jnp.asarray(y))
    _, want = trainer.train_step(state, xs, ys)

    state2 = trainer.init(jax.random.PRNGKey(0), x.shape)
    xg, yg = multihost.host_local_batch(
        trainer.mesh, (trainer.x_spec, trainer.y_spec), x, y
    )
    assert xg.shape == x.shape and yg.shape == y.shape
    _, got = trainer.train_step(state2, xg, yg)
    assert np.allclose(float(want["loss"]), float(got["loss"]))
    assert np.allclose(float(want["accuracy"]), float(got["accuracy"]))
