"""Stage partitioner + shape tracing tests (ref ``model_generator``
semantics, ``mp_pipeline.py:41-168``)."""

import jax.numpy as jnp
import pytest

from mpi4dl_tpu.models.resnet import get_resnet_v1
from mpi4dl_tpu.parallel.partition import (
    spatial_shape,
    split_cells,
    stage_bounds,
    trace_shapes,
)


def test_even_split_remainder_to_last_stage():
    # floor(10/3)=3 per stage, remainder folds into the last
    # (mp_pipeline.py:46-53).
    assert stage_bounds(10, 3) == [(0, 3), (3, 6), (6, 10)]
    assert stage_bounds(8, 2) == [(0, 4), (4, 8)]
    assert stage_bounds(5, 1) == [(0, 5)]


def test_balance_split():
    assert stage_bounds(10, 3, balance=[5, 3, 2]) == [(0, 5), (5, 8), (8, 10)]
    with pytest.raises(ValueError, match="sums to"):
        stage_bounds(10, 3, balance=[5, 3, 3])
    with pytest.raises(ValueError, match="length"):
        stage_bounds(10, 3, balance=[5, 5])


def test_split_cells_partition_is_exact():
    cells = list(range(11))
    stages = split_cells(cells, 4)
    assert [len(s) for s in stages] == [2, 2, 2, 5]
    assert sum(stages, []) == cells


def test_trace_shapes_resnet():
    cells = get_resnet_v1(depth=8, num_classes=10)
    shapes = trace_shapes(cells, split_size=2, input_shape=(4, 32, 32, 3))
    assert len(shapes) == 2
    # last stage output: logits
    assert shapes[-1] == (4, 10)
    # first stage output: NHWC activation
    assert len(shapes[0]) == 4 and shapes[0][0] == 4


def test_spatial_shape():
    assert spatial_shape((2, 32, 32, 3), (2, 2)) == (2, 16, 16, 3)
    assert spatial_shape((2, 32, 32, 3), (1, 4)) == (2, 32, 8, 3)
    with pytest.raises(ValueError):
        spatial_shape((2, 30, 32, 3), (4, 1))
