"""End-to-end CLI smoke tests for all 8 training entry scripts.

The trainer classes are golden-tested (test_pipeline/test_train); what those
tests never touch is the scripts' argument plumbing — ``benchmarks/common.py``
routing (build_config/build_resnet/build_amoebanet/make_trainer) driven by
real argparse vectors. The reference's de-facto integration surface is
exactly these scripts (``/root/reference/benchmarks/*/benchmark_*.py``,
SURVEY.md §2.3); here each one runs 1-2 real steps in-process on the 8
virtual CPU devices (conftest), covering the VERDICT-r3 flag matrix:
``--halo-D2``, ``--local-DP 4``, GEMS+SP, ``--enable-master-comm-opt``,
``--eval-batches``, and ``--times 2``.
"""

import json
import os
import runpy
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
B = os.path.join(REPO, "benchmarks")

# Tiny-but-real configs: ResNet scripts always build ResNet-110 (the
# reference hardcodes resnet_n=12 the same way), so they run @32px with 1-2
# steps; AmoebaNet scripts get shrunk via their own CLI (--num-layers /
# --num-filters — same knobs the reference exposes).
_COMMON = ["--image-size", "32", "--precision", "fp32", "--verbose"]
_AMOEBA_SMALL = ["--num-layers", "3", "--num-filters", "32"]

CASES = {
    "layer_parallelism/benchmark_resnet_lp.py": [
        "--batch-size", "4", "--parts", "2", "--split-size", "2",
        "--max-steps", "2", "--eval-batches", "1", *_COMMON,
    ],
    "layer_parallelism/benchmark_amoebanet_lp.py": [
        "--batch-size", "4", "--parts", "2", "--split-size", "2",
        "--max-steps", "2", *_AMOEBA_SMALL, "--image-size", "64",
        "--precision", "fp32", "--verbose",
    ],
    # --halo-D2: the fused-halo D2 spatial model through the full script.
    "spatial_parallelism/benchmark_resnet_sp.py": [
        "--batch-size", "2", "--parts", "1", "--split-size", "2",
        "--spatial-size", "1", "--num-spatial-parts", "4",
        "--slice-method", "square", "--halo-D2", "--fused-layers", "2",
        "--max-steps", "2", *_COMMON,
    ],
    # --local-DP 4: LBANN-style DP inside the LP stages after SP (8 devices).
    "spatial_parallelism/benchmark_amoebanet_sp.py": [
        "--batch-size", "8", "--parts", "1", "--split-size", "2",
        "--spatial-size", "1", "--num-spatial-parts", "4",
        "--slice-method", "square", "--local-DP", "4",
        "--max-steps", "2", *_AMOEBA_SMALL, "--image-size", "64",
        "--precision", "fp32", "--verbose",
    ],
    # --times 2: the GEMS effective-batch knob beyond its default.
    "gems_master_model/benchmark_resnet_gems_master.py": [
        "--batch-size", "2", "--parts", "2", "--split-size", "2",
        "--times", "2", "--max-steps", "2", *_COMMON,
    ],
    "gems_master_model/benchmark_amoebanet_gems_master.py": [
        "--batch-size", "2", "--parts", "2", "--split-size", "2",
        "--enable-master-comm-opt", "--max-steps", "2",
        *_AMOEBA_SMALL, "--image-size", "64", "--precision", "fp32",
        "--verbose",
    ],
    # GEMS+SP: spatial front + bidirectional pipeline (ref two-MPIComm path).
    "gems_master_with_spatial_parallelism/benchmark_resnet_gems_master_with_sp.py": [
        "--batch-size", "2", "--parts", "2", "--split-size", "3",
        "--spatial-size", "1", "--num-spatial-parts", "4",
        "--slice-method", "square", "--max-steps", "2", *_COMMON,
    ],
    "gems_master_with_spatial_parallelism/benchmark_amoebanet_gems_master_with_sp.py": [
        "--batch-size", "2", "--parts", "2", "--split-size", "3",
        "--spatial-size", "1", "--num-spatial-parts", "4",
        "--slice-method", "square", "--enable-master-comm-opt",
        "--max-steps", "2", *_AMOEBA_SMALL, "--image-size", "64",
        "--precision", "fp32", "--verbose",
    ],
}


# Every case compiles a full model on the CPU mesh — minutes each. The fast
# tier's engine coverage lives in the golden tests; these are the
# integration layer. (Marked per-test, not module-wide: the pure-JSON CLI
# smokes below belong to the fast tier.)
@pytest.mark.slow
@pytest.mark.parametrize("script", sorted(CASES), ids=lambda s: s.split("/")[-1])
def test_cli_script_smoke(script, monkeypatch, capsys):
    """Run the script's real __main__ path with a real argv; assert it
    trains (per-step loss lines via --verbose) and reports throughput."""
    # The scripts' apply_platform_env honors JAX_PLATFORMS — which this
    # container exports as "axon" (the real TPU). Point it at the CPU
    # simulation, exactly as the scripts' own usage message instructs.
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    # ResNet-20 instead of ResNet-110: the scripts' plumbing (what this
    # test covers) is depth-independent, and the 54-cell CPU compile is
    # not a cost 8 parametrized smoke runs should pay.
    monkeypatch.setenv("MPI4DL_TPU_RESNET_N", "2")
    monkeypatch.setenv(
        "XLA_FLAGS",
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8",
    )
    monkeypatch.setattr(
        sys, "argv", [os.path.basename(script)] + CASES[script]
    )
    runpy.run_path(os.path.join(B, script), run_name="__main__")
    out = capsys.readouterr().out
    assert "loss" in out, out  # --verbose per-step line → a step really ran
    assert "img/s" in out, out  # the end-of-run throughput report
    if "--enable-master-comm-opt" in CASES[script]:
        # CLI parity: the flag is accepted and explained, not ignored.
        assert "comm-opt" in out, out
    if "--eval-batches" in CASES[script]:
        assert "eval (" in out, out


def test_analyze_trace_export_cli(tmp_path, capsys):
    """ISSUE CI satellite: `python -m mpi4dl_tpu.analyze trace-export`
    end-to-end through the analysis CLI's real dispatch — two processes'
    JSONL span segments in, one joined Chrome trace out. Pure JSON (the
    subcommand dispatches before any jax setup), so it runs in the fast
    tier."""
    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.analysis.cli import main

    log = tmp_path / "telemetry-fleet.jsonl"
    with open(log, "w") as f:
        for pid, name, marks in (
            (11, "client.request",
             [("issue", 1.0), ("client_wait", 2.0)]),
            (22, "serve.request",
             [("submit", 5.0), ("queue_wait", 5.4),
              ("device_compute", 5.9)]),
        ):
            ev = telemetry.span_event(
                name, "trace-join-1", telemetry.spans_from_marks(marks),
                attrs={"pid": pid}, ts=100.0,
            )
            f.write(json.dumps(ev) + "\n")
    out = tmp_path / "chrome.json"
    rc = main(["trace-export", str(log), "--trace-id", "trace-join-1",
               "-o", str(out)])
    assert rc == 0
    assert "2 process(es)" in capsys.readouterr().err
    doc = json.load(open(out))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {11, 22}
    assert all(e["args"]["trace_id"] == "trace-join-1" for e in xs)
    # --list mode names the trace; a bogus id exits nonzero.
    assert main(["trace-export", str(log), "--list"]) == 0
    assert "trace-join-1" in capsys.readouterr().out
    assert main(["trace-export", str(log), "--trace-id", "missing"]) == 1


def test_analyze_tail_cli(tmp_path, capsys):
    """ISSUE 10 CI satellite: `python -m mpi4dl_tpu.analyze tail` through
    the analysis CLI's real dispatch — pure JSON, pre-jax, fast tier.
    Canned logs: two span populations + a tail.sample + an exemplar-
    carrying metrics event; the deep joins are covered in test_tail.py."""
    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.analysis.cli import main

    log = tmp_path / "telemetry-tail.jsonl"
    reg = telemetry.MetricsRegistry()
    telemetry.declare(reg, "serve_request_latency_seconds").observe(
        0.5, exemplar="t-slow"
    )
    with open(log, "w") as f:
        for tid, e2e in (("t-slow", 0.5), ("t-fast", 0.01)):
            ev = telemetry.span_event(
                "serve.request", tid,
                telemetry.spans_from_marks([
                    ("submit", 1.0), ("queue_wait", 1.0 + e2e / 2),
                    ("device_compute", 1.0 + e2e),
                ]),
                attrs={"pid": 7, "role": "engine", "outcome": "served",
                       "e2e_latency_s": e2e},
                ts=100.0,
            )
            f.write(json.dumps(ev) + "\n")
        f.write(json.dumps({
            "ts": 100.1, "kind": "event", "name": "tail.sample",
            "attrs": {"trace_id": "t-slow", "e2e_latency_s": 0.5,
                      "threshold_s": 0.04, "pid": 7},
        }) + "\n")
        f.write(json.dumps(telemetry.metrics_event(reg, ts=101.0)) + "\n")

    assert main(["tail", str(log), "--top", "2"]) == 0
    out = capsys.readouterr().out
    assert "t-slow" in out and "t-fast" in out
    assert main(["tail", str(log), "--trace-id", "t-slow"]) == 0
    out = capsys.readouterr().out
    assert "dominant phase" in out and "tail.sample" in out
    assert "exemplar: serve_request_latency_seconds" in out
    assert main(["tail", str(log), "--list-exemplars"]) == 0
    assert "t-slow" in capsys.readouterr().out
    assert main(["tail", str(log), "--trace-id", "missing"]) == 1


def test_analyze_incident_cli_md_timeline_golden(tmp_path, capsys):
    """ISSUE 20 CI satellite: `python -m mpi4dl_tpu.analyze incident`
    through the real dispatch — pure JSON, pre-jax. Canned MULTI-PID
    logs whose file order disagrees with wall-clock order, plus a
    cause/symptom pair sharing one coarse timestamp: the rendered
    ``--md`` timeline must come out in causal order regardless."""
    from mpi4dl_tpu.analysis.cli import main

    # pid-7 log (supervisor side): the chaos op, the restart, and the
    # incident lifecycle.
    (tmp_path / "telemetry-7.jsonl").write_text(
        "".join(json.dumps(e) + "\n" for e in [
            {"ts": 100.0, "kind": "event", "name": "chaos.injected",
             "attrs": {"op": "kill:r1@+1s", "action": "kill", "pid": 8}},
            {"ts": 100.4, "kind": "event", "name": "elastic.restart",
             "attrs": {"replica": "r1", "reason": "exit"}},
            {"ts": 100.3, "kind": "event", "name": "incident.open",
             "attrs": {"id": "inc-7", "opened_ts": 100.3,
                       "alert": "replica_unreachable", "severity": "page",
                       "mtta_s": 0.3, "lookback_s": 10.0,
                       "members": [{"name": "replica_unreachable",
                                    "severity": "page",
                                    "first_firing_ts": 100.0}]}},
            {"ts": 101.5, "kind": "event", "name": "incident.close",
             "attrs": {"id": "inc-7", "closed_ts": 101.5, "mttr_s": 1.2,
                       "members": [{"name": "replica_unreachable",
                                    "severity": "page",
                                    "resolved_ts": 101.5}]}},
        ])
    )
    # pid-8 log (worker side), listed AFTER pid-7 but carrying EARLIER
    # wall times — and a page transition tying the chaos op's ts
    # exactly (coarse clocks do that): the cause must still sort first.
    (tmp_path / "telemetry-8.jsonl").write_text(
        "".join(json.dumps(e) + "\n" for e in [
            {"ts": 100.0, "kind": "event", "name": "alert.transition",
             "attrs": {"alert": "replica_unreachable", "severity": "page",
                       "from": "resolved", "to": "firing"}},
            {"ts": 99.5, "kind": "event", "name": "oom.report",
             "attrs": {"program": "serve_predict"}},
        ])
    )

    assert main(["incident", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "inc-7" in out and "injected chaos op kill:r1@+1s" in out

    assert main(["incident", str(tmp_path), "--md"]) == 0
    md = capsys.readouterr().out
    assert "# Incident inc-7 — closed" in md
    assert "| MTTR | 1.200s |" in md
    rows = [
        line.split("`")[1] for line in md.splitlines()
        if line.startswith("| ") and "`" in line
        and "| t−open |" not in line
    ]
    # Golden causal order: wall time across pids, cause before symptom
    # at the shared timestamp — NOT file order, NOT emission order.
    assert rows == [
        "replica_unreachable",  # opened-by field row
        "replica_unreachable",  # members field row
        "oom.report", "chaos.injected", "alert.transition",
        "elastic.restart",
    ]

    assert main(["incident", str(tmp_path), "--json"]) == 0
    (pm,) = json.loads(capsys.readouterr().out)
    assert [e["ts"] for e in pm["timeline"]] == [99.5, 100.0, 100.0, 100.4]
    assert main(["incident", str(tmp_path), "--incident-id", "nope"]) == 1


def test_fleet_cli_plan_smoke(capsys):
    """ISSUE CI satellite: `python -m mpi4dl_tpu.fleet --plan` — the
    pure-dispatch path: chaos specs parsed + validated, the fleet plan
    printed as JSON, no process spawned, no model compiled. Bad specs
    and out-of-fleet targets are usage errors, not silent no-ops."""
    from mpi4dl_tpu.fleet.__main__ import main

    rc = main(["--replicas", "2", "--chaos", "kill:1@2",
               "--chaos", "delay-scrape:0=3", "--plan"])
    assert rc == 0
    plan = json.loads(capsys.readouterr().out)
    assert plan["replicas"] == 2
    assert plan["chaos"] == ["kill:r1@+2s", "delay-scrape:r0=3s@+1s"]
    assert "mpi4dl_tpu.fleet.worker" in " ".join(plan["worker_cmd"])
    assert plan["federation"] is True
    # Unknown action and a target outside the fleet: loud exit 2.
    assert main(["--replicas", "2", "--chaos", "explode:1", "--plan"]) == 2
    assert main(["--replicas", "2", "--chaos", "kill:5", "--plan"]) == 2

    # ISSUE 12: the HA front door joins the plan — router count, warm
    # pool, router_cmd, and the kill:router chaos domain, with
    # out-of-set router targets as loud usage errors.
    rc = main(["--replicas", "2", "--routers", "2", "--warm-pool", "1",
               "--chaos", "kill:router:1@2", "--plan"])
    assert rc == 0
    plan = json.loads(capsys.readouterr().out)
    assert plan["routers"] == 2 and plan["warm_pool"] == 1
    assert plan["chaos"] == ["kill:router1@+2s"]
    assert "mpi4dl_tpu.fleet.frontdoor" in " ".join(plan["router_cmd"])
    assert main(["--replicas", "2", "--routers", "2",
                 "--chaos", "kill:router:2", "--plan"]) == 2
    # A warm-pool slot is a legitimate replica kill target.
    assert main(["--replicas", "2", "--warm-pool", "1",
                 "--chaos", "kill:2", "--plan"]) == 0
    capsys.readouterr()


def test_analyze_memory_plan_cli(tmp_path, capsys):
    """ISSUE CI satellite: `python -m mpi4dl_tpu.analyze memory-plan`
    artifact mode end-to-end through the CLI's real dispatch — committed
    peaks (baseline format + a footprint-ledger dump) against a limit,
    fits/doesn't verdicts, machine-readable plan, CI exit codes. Pure
    JSON (dispatched before any backend setup, like bench-history), so
    it runs in the fast tier."""
    from mpi4dl_tpu.analysis.cli import main

    base = tmp_path / "baseline.json"
    base.write_text(json.dumps({
        "resnet_small": {"peak_bytes": 2 * 2**30},
        "resnet_huge": {"peak_bytes": 20 * 2**30},
    }))
    plan_path = tmp_path / "plan.json"
    rc = main(["memory-plan", "--baseline", str(base),
               "--limit-gb", "15.48", "--json", str(plan_path)])
    assert rc == 1  # the huge config does not fit → CI-visible
    out = capsys.readouterr().out
    assert "DOES NOT FIT" in out and "fits" in out
    plan = json.load(open(plan_path))
    assert plan["mode"] == "artifact"
    verdicts = {e["key"]: e["fits"] for e in plan["entries"]}
    assert verdicts == {"resnet_small": True, "resnet_huge": False}
    small = next(e for e in plan["entries"] if e["key"] == "resnet_small")
    assert small["headroom_ratio"] == pytest.approx(
        1 - 2 / 15.48, abs=1e-3
    )

    # Only the fitting key asked about → exit 0.
    assert main(["memory-plan", "--baseline", str(base), "--key",
                 "small", "--limit-gb", "15.48"]) == 0
    # No limit: peaks reported, verdict unknown, still usable (exit 0).
    assert main(["memory-plan", "--baseline", str(base)]) == 0
    # A ledger dump (engine stats()['memory'] shape) is also an input.
    ledger = tmp_path / "ledger.json"
    ledger.write_text(json.dumps({"entries": [
        {"program": "serve_predict", "bucket": 8, "peak_bytes": 2**30},
    ]}))
    rc = main(["memory-plan", "--ledger", str(ledger),
               "--limit-bytes", str(2**31), "--json", str(plan_path)])
    assert rc == 0
    plan = json.load(open(plan_path))
    assert plan["entries"][0]["key"] == "serve_predict[8]"
    assert plan["entries"][0]["fits"] is True
    # Empty input is a usage error, not a silent all-clear.
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert main(["memory-plan", "--baseline", str(empty)]) == 2


def test_analyze_coldstart_cli(tmp_path, capsys):
    """ISSUE 18 satellite: ``python -m mpi4dl_tpu.analyze coldstart``
    through the CLI's real dispatch — ledger dumps ranked into the
    top-executables manifest, the human-readable summary, and the
    ``--budget-s`` CI exit code. Pure JSON, fast tier."""
    from mpi4dl_tpu.analysis.cli import main

    ledger = tmp_path / "ledger.json"
    ledger.write_text(json.dumps({"entries": [
        {"program": "serve_predict", "bucket": 4,
         "fingerprint": "xf1111111111111111",
         "trace_s": 0.2, "compile_s": 1.5, "warm_s": 0.02},
        {"program": "serve_predict", "bucket": 1,
         "fingerprint": "xf2222222222222222",
         "trace_s": 0.1, "compile_s": 0.4, "warm_s": 0.01},
        {"program": "train_step",
         "fingerprint": "xf3333333333333333",
         "trace_s": 0.5, "compile_s": 2.5, "warm_s": 0.1},
    ]}))
    rc = main(["coldstart", str(ledger), "--top", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    # Ranked by compile seconds, --top truncates the listing.
    assert "1. train_step xf3333333333333333" in out
    assert "2. serve_predict[4]" in out
    assert "serve_predict[1]" not in out
    assert "compile 4.400s" in out

    # Same ledger recorded twice (two replicas): fingerprint grouping
    # merges each executable and counts occurrences.
    rc = main(["coldstart", str(ledger), str(ledger), "--top", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "x2" in out and "compile 5.000s" in out

    # The budget gate fails CI loudly.
    rc = main(["coldstart", str(ledger), "--budget-s", "2.0"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "OVER BUDGET" in err


def test_analyze_memory_plan_bisect_tile_cli(tmp_path, capsys):
    """ISSUE satellite: ``analyze memory-plan --bisect tile`` — the
    gigapixel pre-run question "what tile size fits this chip" answered
    in pure compile mode (section-window + stitched-head executables
    lowered abstractly, nothing executed), binary-searched over the
    tile ladder, exit 1 when no tile fits."""
    from mpi4dl_tpu.analysis.cli import main

    plan_path = tmp_path / "tileplan.json"
    rc = main([
        "memory-plan", "--program", "serve", "--size", "64",
        "--bisect", "tile", "--tile-candidates", "16",
        "--tile-bucket", "1", "--limit-gb", "4",
        "--json", str(plan_path),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "max feasible tile: 16" in out
    plan = json.load(open(plan_path))
    assert plan["bisect"]["axis"] == "tile"
    assert plan["bisect"]["max_feasible"] == 16
    # Every compiled candidate reports BOTH executables' peaks — the
    # head is the image-bound residual the tile size cannot shrink.
    cand = plan["bisect"]["candidates"][-1]
    assert cand["tile_peak_bytes"] > 0 and cand["head_peak_bytes"] > 0
    # No tile fits an absurd limit → CI-visible exit 1.
    rc = main([
        "memory-plan", "--program", "serve", "--size", "64",
        "--bisect", "tile", "--tile-candidates", "16",
        "--tile-bucket", "1", "--limit-bytes", "1000",
    ])
    assert rc == 1
    capsys.readouterr()


def test_analyze_sp_overlap_cli_decomposed_crosscheck(tmp_path, capsys):
    """ISSUE CI satellite: `python -m mpi4dl_tpu.analyze sp-overlap` on
    the DECOMPOSED arm — a live SP 2×2 capture of the decomposed-conv
    program, attributed, linted against partition math, and run through
    the trace-overlap-crosscheck, end-to-end via the analysis CLI's real
    dispatch (in-process: the 8-virtual-CPU mesh already exists)."""
    from mpi4dl_tpu.analysis.cli import main

    out_path = tmp_path / "sp_overlap.json"
    rc = main([
        "sp-overlap", "--arm", "decomposed", "--size", "32",
        "--steps", "2", "--warmup", "1", "--json", str(out_path),
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "decomposed:" in err
    out = json.load(open(out_path))
    arm = out["arms"]["decomposed"]
    assert arm["conv_impl"] == "decomposed"
    assert arm["halo_shifts"] == 20
    assert arm["halo_shifts"] <= arm["permutes"] <= 2 * arm["halo_shifts"]
    assert arm["hlolint_errors"] == []
    # CPU emits sync collectives (no static overlap claim), so the
    # crosscheck must report NO disagreement on the decomposed capture.
    assert arm["crosscheck"] == []
    assert arm["n_steps"] >= 2
    assert 0.0 <= arm["trace_overlap_ratio"] <= 1.0


def test_analyze_pipeline_cli_one_arm(tmp_path, capsys):
    """ISSUE 14 CI satellite: `python -m mpi4dl_tpu.analyze pipeline` on
    one schedule arm — a live LP-pipeline capture attributed through the
    stage-switch lens, the measured bubble cross-checked against the
    schedule model, and the compiled program linted at the exact
    stage-permute budget — end-to-end via the analysis CLI's real
    dispatch (in-process: the 8-virtual-CPU mesh already exists)."""
    from mpi4dl_tpu.analysis.cli import main

    out_path = tmp_path / "pipeline_ab.json"
    rc = main([
        "pipeline", "--schedule", "gpipe", "--steps", "2", "--warmup", "1",
        "--json", str(out_path),
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "gpipe:" in err
    out = json.load(open(out_path))
    arm = out["arms"]["gpipe"]
    assert arm["bubble_fraction"] == pytest.approx(
        arm["analytic_bubble_fraction"], abs=0.02
    )
    # Pure-LP program: the permute inventory sits exactly at the
    # stage-boundary budget and the window rule holds.
    assert arm["permutes"] == arm["permute_budget"] == 2
    assert arm["hlolint_errors"] == []
    assert arm["crosscheck"] == []
    assert arm["img_per_s"] > 0
    assert len(arm["stage_device_seconds"]) == 2


def test_serve_cli_mesh_sharded_smoke(tmp_path, capsys):
    """ISSUE CI satellite: `python -m mpi4dl_tpu.serve --mesh HxW` — the
    sharded synthetic engine end to end via the serve CLI: warms, serves
    a small closed loop, and the lint gate passes with the mesh-derived
    (halo-window) expectations instead of zero-collectives."""
    from mpi4dl_tpu.serve.__main__ import main

    out_path = tmp_path / "serve_mesh.json"
    rc = main([
        "--mesh", "2x2", "--image-size", "16", "--spatial-cells", "2",
        "--max-batch", "2", "--requests", "8", "--concurrency", "4",
        "--serial", "0", "--lint", "--json", str(out_path),
    ])
    assert rc == 0
    rep = json.load(open(out_path))
    assert rep["mesh"] == [2, 2]
    assert rep["loadgen"]["served"] == 8
    assert rep["loadgen"]["deadline_misses"] == 0
    assert rep["lint"]["ok"]
    assert rep["loadgen"]["engine"]["mesh"] == [2, 2]


def test_analyze_serving_sharded_cli_one_arm(tmp_path, capsys):
    """ISSUE CI satellite: `python -m mpi4dl_tpu.analyze serving-sharded`
    on one arm — a sharded engine under closed-loop load inside a live
    capture, attributed, mesh-lint gated, crosschecked — via the
    analysis CLI's real dispatch (in-process: the 8-virtual-CPU mesh
    already exists)."""
    from mpi4dl_tpu.analysis.cli import main

    out_path = tmp_path / "serving_sharded.json"
    rc = main([
        "serving-sharded", "--arm", "decomposed", "--size", "16",
        "--spatial-cells", "2", "--bucket", "2", "--requests", "12",
        "--concurrency", "4", "--json", str(out_path),
    ])
    assert rc == 0
    err = capsys.readouterr().err
    assert "decomposed:" in err
    out = json.load(open(out_path))
    arm = out["arms"]["decomposed"]
    assert arm["conv_impl"] == "decomposed"
    assert arm["hlolint_errors"] == []
    assert arm["crosscheck"] == []
    assert arm["deadline_misses"] == 0
    # Forward-only serving program: the permute inventory sits exactly
    # at the counted halo shifts.
    assert arm["permutes"] == arm["halo_shifts"] > 0
    assert arm["latency_ms"]["p99"] > 0
    assert 0.0 <= arm["trace_overlap_ratio"] <= 1.0
