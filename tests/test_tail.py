"""Tail-latency forensics (ISSUE 10 tentpole): histogram exemplars
(observe → snapshot → OpenMetrics render → federation merge, max-wins +
conflict surfacing), the slow-request TailWatcher (threshold math, rate
limiting, schema-valid tail.sample capture), fleet straggler detection
(replica_skew scoring + the replica_straggler advisory page over live
/snapshotz endpoints), latency-alert exemplar evidence, and the
``analyze tail`` CLI joining all three artifacts per trace id.
"""

import json
import urllib.request

import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.telemetry.alerts import latency_exemplars
from mpi4dl_tpu.telemetry.federation import (
    FederatedAggregator,
    bucket_quantile,
    merge_snapshots,
    replica_skew,
)
from mpi4dl_tpu.telemetry.tail import TailWatcher


# -- exemplar semantics (registry) --------------------------------------------


def test_histogram_exemplar_most_recent_per_bucket():
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("lat", "h", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="t-1")
    h.observe(0.06, exemplar="t-2")   # same bucket: most recent wins
    h.observe(0.5, exemplar="t-3")
    h.observe(5.0, exemplar="t-inf")  # +Inf bucket
    h.observe(0.07)                   # exemplar-less: leaves t-2 in place
    (s,) = h.snapshot_series()
    ex = s["exemplars"]
    assert ex["0.1"]["trace_id"] == "t-2"
    assert ex["0.1"]["value"] == 0.06
    assert ex["1"]["trace_id"] == "t-3"
    assert ex["+Inf"]["trace_id"] == "t-inf"
    assert ex["0.1"]["ts"] > 0
    # Labeled series keep independent exemplars.
    h2 = reg.histogram("spans", "h", labels=("phase",), buckets=(0.1,))
    h2.observe(0.05, exemplar="a", phase="queue")
    h2.observe(0.05, exemplar="b", phase="compute")
    by_phase = {
        s["labels"]["phase"]: s["exemplars"]["0.1"]["trace_id"]
        for s in h2.snapshot_series()
    }
    assert by_phase == {"queue": "a", "compute": "b"}
    # No exemplars ever observed → no key at all (sparse, not empty).
    h3 = reg.histogram("plain", "h", buckets=(0.1,))
    h3.observe(0.05)
    (s3,) = h3.snapshot_series()
    assert "exemplars" not in s3
    # Snapshots with exemplars stay schema-valid.
    telemetry.validate_event(telemetry.metrics_event(reg))


def test_exemplar_openmetrics_render_and_escaping_round_trip():
    """ISSUE satellite: the text exposition renders bucket exemplars as
    OpenMetrics ``# {trace_id="..."} value ts`` suffixes, with label-value
    escaping that survives a round trip even for hostile trace ids."""
    from mpi4dl_tpu.telemetry.export import unescape_label_value

    reg = telemetry.MetricsRegistry()
    h = reg.histogram("lat", "h", buckets=(0.1,))
    nasty = 'id"with\\quote\nand-newline'
    h.observe(0.05, exemplar=nasty)
    h.observe(5.0)  # +Inf bucket: count but no exemplar
    text = telemetry.render_prometheus(reg)
    lines = [l for l in text.splitlines() if l.startswith("lat_bucket")]
    (with_ex,) = [l for l in lines if "#" in l]
    assert with_ex.startswith('lat_bucket{le="0.1"} 1 # {trace_id="')
    # The exemplar suffix is a single line and the id parses back exactly.
    quoted = with_ex[
        with_ex.index('trace_id="') + len('trace_id="'):with_ex.rindex('"}')
    ]
    assert unescape_label_value(quoted) == nasty
    # Buckets without exemplars render the plain 0.0.4 sample line.
    (plain,) = [l for l in lines if "+Inf" in l]
    assert "#" not in plain and plain.endswith(" 2")


# -- federation merge ---------------------------------------------------------


def _hist_child(latencies, trace_prefix, buckets=(0.1, 1.0)):
    reg = telemetry.MetricsRegistry()
    h = reg.histogram(
        "serve_request_latency_seconds", "h", buckets=buckets
    )
    for i, v in enumerate(latencies):
        h.observe(v, exemplar=f"{trace_prefix}-{i}")
    return reg.snapshot()


def test_merge_exemplars_max_value_wins_per_bucket():
    """ISSUE tentpole golden: /snapshotz-shaped children merge their
    per-bucket exemplars MAX-VALUE-wins — the fleet bucket names its
    worst request, regardless of replica order."""
    a = _hist_child([0.05, 0.5], "a")     # a-0 in le=0.1, a-1 in le=1
    b = _hist_child([0.09, 0.2], "b")     # b-0 in le=0.1, b-1 in le=1
    merged, conflicts = merge_snapshots({"r0": a, "r1": b})
    assert conflicts == []
    (s,) = merged["serve_request_latency_seconds"]["series"]
    assert s["count"] == 4  # bucket-wise histogram merge unchanged
    assert s["exemplars"]["0.1"]["trace_id"] == "b-0"   # 0.09 > 0.05
    assert s["exemplars"]["1"]["trace_id"] == "a-1"     # 0.5 > 0.2
    # Replica order must not matter.
    merged2, _ = merge_snapshots({"r0": b, "r1": a})
    assert (
        merged2["serve_request_latency_seconds"]["series"][0]["exemplars"]
        == s["exemplars"]
    )


def test_merge_exemplar_conflict_surfaced_not_missummed():
    """Same trace id, same bucket, DIFFERENT values across replicas (a
    double-observed requeue, or clock skew): the merge keeps the max but
    surfaces the disagreement in conflicts instead of averaging."""
    a = _hist_child([0.05], "dup")
    b = _hist_child([0.09], "dup")  # dup-0 again, different value
    merged, conflicts = merge_snapshots({"r0": a, "r1": b})
    (s,) = merged["serve_request_latency_seconds"]["series"]
    assert s["exemplars"]["0.1"]["value"] == 0.09  # max kept
    assert len(conflicts) == 1
    assert "dup-0" in conflicts[0] and "conflicting values" in conflicts[0]
    # Same id with the SAME value (one request legitimately scraped off
    # two surfaces) is not a conflict.
    _, clean = merge_snapshots({"r0": a, "r1": _hist_child([0.05], "dup")})
    assert clean == []


# -- TailWatcher --------------------------------------------------------------


def _spans(e2e, queue=None):
    q = queue if queue is not None else e2e / 4
    return telemetry.spans_from_marks([
        ("submit", 0.0), ("queue_wait", q), ("batch_form", q + 0.001),
        ("h2d_stage", q + 0.002), ("device_compute", e2e),
    ])


def test_tail_threshold_is_max_of_slo_and_factor_p99():
    reg = telemetry.MetricsRegistry()
    w = TailWatcher(
        registry=reg, slo_threshold_s=0.5, factor=4.0, seed_s=0.01,
        min_interval_s=0.0,
    )
    # Seeded p99 = 10ms → factor arm 40ms; the SLO floor (500ms) wins.
    assert w.threshold() == 0.5
    assert reg.get("tail_threshold_seconds").value() == 0.5
    # Without an SLO, the factor arm stands alone.
    w2 = TailWatcher(factor=4.0, seed_s=0.01, min_interval_s=0.0)
    assert w2.threshold() == pytest.approx(0.04)
    # A latency storm raises the rolling p99 — the bar adapts upward.
    for _ in range(64):
        w2.observe("t", 0.03, _spans(0.03))
    assert w2.threshold() == pytest.approx(0.12, rel=0.01)


def test_tail_capture_contents_schema_and_span_sum_invariant():
    reg = telemetry.MetricsRegistry()
    events = []
    flight = telemetry.FlightRecorder(capacity=16)
    w = TailWatcher(
        registry=reg, factor=2.0, seed_s=0.01, min_interval_s=0.0,
        flight=flight,
    )

    class _W:  # duck-typed JsonlWriter
        enabled = True
        write = staticmethod(events.append)

    w._events = _W()
    # Under threshold (2 x 10ms): not captured.
    assert w.observe("fast", 0.015, _spans(0.015)) is None
    ev = w.observe(
        "slow-1", 0.2, _spans(0.2),
        outcome="served", bucket=4, batch_size=3,
        queue_depth_at_submit=7, dispatch_seq=42, pad_waste_ratio=0.25,
        watchdog={"tripped": False}, attribution=None,
    )
    assert ev is not None
    telemetry.validate_event(ev)  # already validated at build; idempotent
    a = ev["attrs"]
    assert a["trace_id"] == "slow-1"
    assert a["queue_depth_at_submit"] == 7
    assert a["dispatch_seq"] == 42
    assert a["bucket"] == 4 and a["batch_size"] == 3
    assert a["pad_waste_ratio"] == 0.25
    assert a["watchdog"] == {"tripped": False}
    assert set(a["phases"]) == {
        "queue_wait", "batch_form", "h2d_stage", "device_compute"
    }
    # ISSUE acceptance: span-sum == e2e holds ON the captured sample.
    assert sum(s["duration_s"] for s in a["spans"]) == pytest.approx(
        a["e2e_latency_s"], abs=1e-12
    )
    # Fan-out: counter, ring, flight ring, event sink.
    assert reg.get("tail_samples_total").value() == 1
    assert w.tail() == [ev]
    assert events == [ev]
    assert any(
        e.get("name") == "tail.sample" for e in flight.tail()
    )
    assert w.state()["captured"] == 1


def test_tail_rate_limit_and_disabled_capacity():
    t = [0.0]
    w = TailWatcher(factor=1.0, seed_s=0.01, min_interval_s=1.0,
                    clock=lambda: t[0])
    assert w.observe("a", 5.0, _spans(5.0)) is not None
    # Slower request inside the rate window: suppressed, counted.
    assert w.observe("b", 50.0, _spans(50.0)) is None
    assert w.suppressed == 1
    t[0] = 1.5
    assert w.observe("c", 50.0, _spans(50.0)) is not None
    assert w.captured == 2
    # capacity=0 disables capture entirely (the A/B-overhead arm).
    off = TailWatcher(factor=1.0, seed_s=0.01, capacity=0)
    assert off.observe("d", 99.0, _spans(99.0)) is None
    assert not off.enabled and off.captured == 0


def test_tail_slow_request_does_not_raise_its_own_bar():
    """The threshold is evaluated BEFORE the completion enters the
    rolling window: the very request that breaks the tail open must be
    judged against the healthy history."""
    w = TailWatcher(factor=2.0, seed_s=0.01, min_interval_s=0.0, window=4)
    # One massive outlier: captured even though including it in the
    # window first would have set the bar at 2 x itself.
    assert w.observe("huge", 10.0, _spans(10.0)) is not None


# -- latency alert evidence ---------------------------------------------------


def test_latency_exemplars_top_k_value_ordered():
    reg = telemetry.MetricsRegistry()
    h = telemetry.declare(reg, "serve_request_latency_seconds")
    for i, v in enumerate((0.004, 0.04, 0.4, 4.0)):
        h.observe(v, exemplar=f"t-{i}")
    top = latency_exemplars(reg, "serve_request_latency_seconds", k=2)
    assert [e["trace_id"] for e in top] == ["t-3", "t-2"]
    assert top[0]["value"] == 4.0
    # Absent metric / exemplar-free series degrade to empty, not raise.
    assert latency_exemplars(reg, "nope") == []
    telemetry.declare(reg, "loadgen_request_latency_seconds").observe(0.1)
    assert latency_exemplars(reg, "loadgen_request_latency_seconds") == []


def test_latency_alert_transition_carries_exemplar_evidence():
    """ISSUE satellite: a firing latency_* transition attaches the top-K
    exemplar trace ids as `evidence` (the PR-9 breaker-evidence pattern)
    — pages link straight to the requests that burned the budget."""
    reg = telemetry.MetricsRegistry()
    spans = telemetry.declare(reg, "serve_span_seconds")
    lat = telemetry.declare(reg, "serve_request_latency_seconds")

    def serve(n, queue_s, compute_s, tag):
        for i in range(n):
            spans.observe(queue_s, phase="queue_wait")
            spans.observe(compute_s, phase="device_compute")
            lat.observe(queue_s + compute_s, exemplar=f"{tag}-{i}")

    cfg = telemetry.SLOConfig(
        latency_threshold_s=0.025, latency_target=0.99, interval_s=1.0
    )
    ev = telemetry.SLOEvaluator(
        registry=reg, objectives=cfg.objectives(), config=cfg,
        clock=lambda: 0, start=False,
    )
    serve(200, 0.002, 0.008, "ok")      # healthy baseline
    ev.evaluate_once(now=0.0)
    serve(100, 0.050, 0.008, "slow")    # regression
    ev.evaluate_once(now=30.0)
    trans = [
        t for t in ev.transitions
        if t["attrs"]["alert"] == "latency_fast_burn"
        and t["attrs"]["to"] == "firing"
    ]
    evidence = trans[-1]["attrs"]["evidence"]
    assert 1 <= len(evidence["exemplar_trace_ids"]) <= 5
    # The worst request in the registry leads the evidence list.
    assert evidence["exemplar_trace_ids"][0].startswith("slow-")
    assert evidence["exemplars"][0]["value"] == pytest.approx(0.058)
    telemetry.validate_event(trans[-1])  # schema holds with evidence on


# -- fleet straggler detection ------------------------------------------------


def test_bucket_quantile_conservative():
    assert bucket_quantile({"0.1": 99, "1": 100, "+Inf": 100}, 0.99) == 0.1
    assert bucket_quantile({"0.1": 98, "1": 100, "+Inf": 100}, 0.99) == 1.0
    # Quantile past the finite range: floored at the largest bound.
    assert bucket_quantile({"0.1": 0, "1": 90, "+Inf": 100}, 0.99) == 1.0
    assert bucket_quantile({"+Inf": 0}, 0.99) is None


def test_replica_skew_scores_against_fleet_median():
    healthy = [0.01] * 99 + [0.02]
    slow = [0.01] * 50 + [0.4] * 50
    children = {
        "r0": _hist_child(healthy, "a", buckets=(0.025, 0.05, 0.5)),
        "r1": _hist_child(healthy, "b", buckets=(0.025, 0.05, 0.5)),
        "r2": _hist_child(slow, "c", buckets=(0.025, 0.05, 0.5)),
    }
    skew = replica_skew(children, min_count=20)
    assert skew["p99"] == {"r0": 0.025, "r1": 0.025, "r2": 0.5}
    assert skew["median_p99"] == 0.025  # the straggler can't drag it
    assert skew["skew"]["r2"] == 20.0
    assert skew["skew"]["r0"] == 1.0
    # Under min_count → excluded; fewer than 2 scored → no skew at all.
    children["r3"] = _hist_child([0.01] * 5, "d", buckets=(0.025, 0.05, 0.5))
    assert "r3" in replica_skew(children, min_count=20)["excluded"]
    only_one = {"r0": children["r0"], "r3": children["r3"]}
    assert replica_skew(only_one, min_count=20)["skew"] == {}


def test_aggregator_flags_straggler_and_pages_on_alertz():
    """ISSUE tentpole drill (deterministic half): three live /snapshotz
    endpoints, one with a fat tail — the aggregator's scrape publishes
    fleet_replica_skew naming it and fires the replica_straggler
    advisory page on /alertz, with a transition naming the replica. The
    end-to-end chaos `delay` version runs in test_fleet.py."""
    regs = {
        "r0": _child_registry([0.01] * 40),
        "r1": _child_registry([0.01] * 40),
        "r2": _child_registry([0.01] * 20 + [0.4] * 20),
    }
    servers = {n: telemetry.MetricsServer(r, port=0) for n, r in regs.items()}
    agg = FederatedAggregator(
        replicas={
            n: f"http://127.0.0.1:{s.port}" for n, s in servers.items()
        },
        straggler_factor=2.0, straggler_min_count=20,
        clock=lambda: 0,
    )
    try:
        agg.scrape_once(now=0.0)
        skew = {
            s["labels"]["replica"]: s["value"]
            for s in agg.registry.get("fleet_replica_skew").snapshot_series()
        }
        assert skew["r2"] > 2.0 >= skew["r0"]
        assert agg.registry.get("alert_active").value(
            alert="replica_straggler", severity="page"
        ) == 1.0
        (t,) = agg.straggler_transitions
        assert t["attrs"]["replica"] == "r2"
        assert t["attrs"]["to"] == "firing"
        assert t["attrs"]["fleet_median_p99_s"] is not None
        telemetry.validate_event(t)
        srv = agg.serve(port=0)
        alertz = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/alertz", timeout=10
        ).read())
        assert any(
            a["name"] == "replica_straggler" and a["state"] == "firing"
            for a in alertz["alerts"]
        )
        assert alertz["straggler"]["skew"]["r2"] > 2.0
        # Replacing the straggler (the supervisor's move) resolves the
        # page: the remaining replicas score ~1 against each other.
        # (Scores are cumulative-histogram-based, so recovery by
        # dilution alone is slow by design — an advisory page should
        # clear when the operator acts, not flap on a lucky minute.)
        agg.remove_replica("r2")
        agg.scrape_once(now=1.0)
        assert agg.registry.get("alert_active").value(
            alert="replica_straggler", severity="page"
        ) == 0.0
        assert agg.straggler_transitions[-1]["attrs"]["to"] == "inactive"
    finally:
        agg.close()
        for s in servers.values():
            s.close()


def _child_registry(latencies):
    reg = telemetry.MetricsRegistry()
    h = telemetry.declare(reg, "serve_request_latency_seconds")
    for v in latencies:
        h.observe(v)
    return reg


# -- full stack: a live engine under load captures real samples ---------------


def test_full_stack_engine_captures_schema_valid_tail_samples(tmp_path):
    """ISSUE satellite + acceptance: a REAL engine + load generator with
    the tail watcher forced hot (sub-p99 factor, no rate limit, a
    latency SLO low enough not to floor it away) writes schema-valid
    tail.sample events into the JSONL log, every one carrying the full
    forensics context, spans summing exactly to the captured e2e, and
    an exemplar for the same trace id in the engine's own histogram;
    /debugz serves the tail state live."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.utils import get_depth

    size = 16
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=size // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )
    tdir = str(tmp_path / "tele")
    engine = ServingEngine(
        cells, params, stats, example_shape=(size, size, 3), max_batch=4,
        default_deadline_s=30.0, telemetry_dir=tdir, metrics_port=0,
        slo=telemetry.SLOConfig(
            availability=0.99, latency_threshold_s=0.001, interval_s=0.2,
        ),
        tail_factor=0.5, tail_min_interval_s=0.0,
    )
    engine.start()
    try:
        run_closed_loop(
            engine, 32, concurrency=8, deadline_s=30.0, events=engine.events,
        )
        dbg = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{engine.metrics_port}/debugz", timeout=10
        ).read())
        assert dbg["tail"]["captured"] >= 1
        assert dbg["tail"]["threshold_s"] > 0
        assert dbg["tail"]["samples"], "debugz serves the sample ring"
        scraped = urllib.request.urlopen(
            f"http://127.0.0.1:{engine.metrics_port}/metrics", timeout=10
        ).read().decode()
        assert "tail_samples_total" in scraped
        assert '# {trace_id="' in scraped  # exemplars on the wire
    finally:
        engine.stop()
    events = telemetry.read_events(
        os.path.join(tdir, [
            f for f in os.listdir(tdir) if f.startswith("telemetry-")
        ][0])
    )
    samples = [
        e for e in events
        if e["kind"] == "event" and e["name"] == "tail.sample"
    ]
    assert samples, "the hot watcher must capture on a real run"
    served = {
        e["trace_id"]: e for e in events
        if e["kind"] == "span" and e["name"] == "serve.request"
        and e["attrs"]["outcome"].startswith("served")
    }
    for s in samples:
        a = s["attrs"]
        # Forensics context present on every capture.
        for key in ("trace_id", "e2e_latency_s", "threshold_s", "phases",
                    "spans", "queue_depth_at_submit", "dispatch_seq",
                    "bucket", "batch_size", "pad_waste_ratio", "pid"):
            assert key in a, key
        assert a["dispatch_seq"] >= 0
        # ISSUE acceptance: span-sum == e2e ON the captured samples.
        assert sum(
            sp["duration_s"] for sp in a["spans"]
        ) == pytest.approx(a["e2e_latency_s"], abs=1e-9)
        # The captured id is a real served request in the same log.
        assert a["trace_id"] in served
    # The registry's latency histogram carries an exemplar for at least
    # one captured id (the aggregate→instance link, on a live run).
    h = engine.registry.get("serve_request_latency_seconds")
    (series,) = h.snapshot_series()
    exemplar_ids = {e["trace_id"] for e in series["exemplars"].values()}
    assert exemplar_ids & set(served)


# -- analyze tail CLI ---------------------------------------------------------


def _requeued_trace_logs(tmp_path):
    """Canned multi-process logs of ONE fleet-requeued slow request
    (client → router → dead-replica attempt → survivor engine) next to a
    population of fast requests, plus a tail.sample and a metrics event
    carrying the exemplar — the full join surface."""
    tid = "fleet-aaaa-bbbbcccc-7"
    log = tmp_path / "telemetry-drill.jsonl"
    events = []
    # Fast population → phase baselines (p50s) to compare against.
    for i in range(20):
        events.append(telemetry.span_event(
            "serve.request", f"fast-{i}",
            telemetry.spans_from_marks([
                ("submit", 1.0 + i), ("queue_wait", 1.002 + i),
                ("batch_form", 1.0021 + i), ("h2d_stage", 1.0024 + i),
                ("device_compute", 1.010 + i),
            ]),
            attrs={"pid": 33, "role": "engine", "outcome": "served",
                   "e2e_latency_s": 0.010},
            ts=100.0 + i,
        ))
    # The slow request's cross-process segments.
    events += [
        telemetry.span_event(
            "client.request", tid,
            telemetry.spans_from_marks(
                [("issue", 50.0), ("client_submit", 50.001),
                 ("client_wait", 50.9)]
            ),
            attrs={"pid": 11, "role": "client", "outcome": "served",
                   "e2e_latency_s": 0.9}, ts=200.9,
        ),
        telemetry.span_event(
            "router.dispatch", tid,
            telemetry.spans_from_marks([("sent", 10.0), ("rpc_r1", 10.4)]),
            attrs={"pid": 22, "role": "router", "replica": "r1",
                   "attempt": 1, "outcome": "error"}, ts=200.4,
        ),
        telemetry.span_event(
            "router.dispatch", tid,
            telemetry.spans_from_marks([("sent", 10.45), ("rpc_r0", 10.85)]),
            attrs={"pid": 22, "role": "router", "replica": "r0",
                   "attempt": 2, "outcome": "ok"}, ts=200.85,
        ),
        telemetry.span_event(
            "serve.request", tid,
            telemetry.spans_from_marks([
                ("submit", 5.0), ("queue_wait", 5.3), ("batch_form", 5.31),
                ("h2d_stage", 5.32), ("device_compute", 5.4),
            ]),
            attrs={"pid": 33, "role": "engine", "outcome": "served",
                   "e2e_latency_s": 0.4}, ts=200.8,
        ),
    ]
    # tail.sample for the id (engine-side capture).
    events.append({
        "ts": 200.81, "kind": "event", "name": "tail.sample",
        "attrs": {"trace_id": tid, "e2e_latency_s": 0.4,
                  "threshold_s": 0.05, "queue_depth_at_submit": 9,
                  "bucket": 4, "batch_size": 4, "dispatch_seq": 17,
                  "pad_waste_ratio": 0.0, "pid": 33},
    })
    # Exemplar-carrying metrics event (the fleet histogram's p99 bucket).
    reg = telemetry.MetricsRegistry()
    telemetry.declare(reg, "fleet_request_latency_seconds").observe(
        0.9, exemplar=tid
    )
    events.append(telemetry.metrics_event(reg, ts=201.0))
    with open(log, "w") as f:
        for e in events:
            f.write(json.dumps(telemetry.validate_event(e)) + "\n")
    return tid, str(log)


def test_analyze_tail_trace_report_renders_requeued_lifetime(tmp_path, capsys):
    """ISSUE tentpole acceptance: `analyze tail --trace-id` renders a
    fleet-requeued slow request's client → router (dead attempt +
    survivor attempt) → replica lifetime end to end, each phase against
    the window p50, with the dominant phase named — through the real
    analysis-CLI dispatch (pure JSON, pre-jax)."""
    from mpi4dl_tpu.analysis.cli import main

    tid, log = _requeued_trace_logs(tmp_path)
    assert main(["tail", log, "--trace-id", tid, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["trace_id"] == tid
    assert rep["e2e_s"] == pytest.approx(0.9)       # the client's view
    assert rep["processes"] == [11, 22, 33]          # 3 processes joined
    names = [s["name"] for s in rep["segments"]]
    assert names.count("router.dispatch") == 2       # dead + survivor
    assert {"client.request", "serve.request"} <= set(names)
    assert rep["dominant_phase"] == "client_wait"
    # The engine segment's queue_wait is compared against the fast
    # population's p50 (2ms) — the slow request waited 150x longer.
    engine_seg = [s for s in rep["segments"] if s["name"] == "serve.request"]
    qw = [p for p in engine_seg[0]["phases"] if p["phase"] == "queue_wait"][0]
    assert qw["vs_p50"] == pytest.approx(0.3 / 0.002, rel=0.01)
    # tail.sample + exemplar joined under the same id.
    assert rep["tail_samples"][0]["attrs"]["queue_depth_at_submit"] == 9
    assert rep["exemplars"][0]["metric"] == "fleet_request_latency_seconds"
    # Text mode renders without error and names the dominant phase.
    assert main(["tail", log, "--trace-id", tid]) == 0
    out = capsys.readouterr().out
    assert "dominant phase: client_wait" in out
    assert "rpc_r1" in out and "rpc_r0" in out      # both attempts visible


def test_analyze_tail_top_table_and_exit_codes(tmp_path, capsys):
    from mpi4dl_tpu.analysis.cli import main

    tid, log = _requeued_trace_logs(tmp_path)
    assert main(["tail", log, "--top", "3", "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert len(rows) == 3
    assert rows[0]["trace_id"] == tid               # slowest first
    assert rows[0]["tail_sampled"] and rows[0]["exemplar"]
    assert rows[0]["e2e_s"] >= rows[1]["e2e_s"] >= rows[2]["e2e_s"]
    assert main(["tail", log, "--list-exemplars"]) == 0
    assert tid in capsys.readouterr().out
    # Missing trace id / empty logs exit nonzero.
    assert main(["tail", log, "--trace-id", "nope"]) == 1
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main(["tail", str(empty)]) == 1
