"""D2 (fused-halo) design tests: one wide halo exchange amortized over
``fused_layers`` shrink-conv cells must be bit-equivalent to the per-cell
(D1) exchange and to the plain single-device model — the property the
reference asserts only by construction (``resnet_spatial_d2.py``).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.config import ParallelConfig
from mpi4dl_tpu.models.resnet import get_resnet_v2_d2
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.train import Trainer, TrainState, single_device_step


def _forward(cells, params, x):
    for c, p in zip(cells, params):
        x = c.apply(p, x)
    return x


@pytest.mark.parametrize("fused_layers", [2, 3])
def test_d2_front_matches_plain_forward(fused_layers):
    cells, plain, nsp = get_resnet_v2_d2(
        depth=20, spatial_cells=4, fused_layers=fused_layers
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 32, 32, 3)), jnp.float32)
    params = init_cells(plain, jax.random.PRNGKey(0), x)
    golden = _forward(plain[:nsp], params[:nsp], x)

    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2), ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), spec), out_specs=spec, check_vma=False
    )
    def dist(p, tile):
        return _forward(cells[:nsp], p, tile)

    out = dist(params[:nsp], jax.device_put(x, NamedSharding(mesh, spec)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden), rtol=2e-5, atol=2e-5)


def test_d2_trainer_step_matches_golden():
    """Full D2 training step (loss + grads via updated params) against the
    plain golden — covers the wide exchange, shrink convs, interior-masked
    cross-tile BN, and skip trimming under AD."""
    cfg = ParallelConfig(
        batch_size=2,
        split_size=1,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=32,
        halo_d2=True,
        fused_layers=2,
    )
    cells, plain, nsp = get_resnet_v2_d2(depth=20, spatial_cells=4, fused_layers=2)
    trainer = Trainer(cells, num_spatial_cells=nsp, config=cfg, plain_cells=plain)
    state = trainer.init(jax.random.PRNGKey(0), (2, 32, 32, 3))
    _, golden_step = single_device_step(plain)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    y = jnp.asarray(np.random.default_rng(2).integers(0, 10, size=(2,)), jnp.int32)
    xs, ys = trainer.shard_batch(x, y)
    state, metrics = trainer.train_step(state, xs, ys)
    golden_state, golden_metrics = golden_step(golden_state, x, y)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
    )
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), rtol=2e-4, atol=1e-5
        ),
        state.params,
        golden_state.params,
    )
