"""Eval/inference path: BN calibration + frozen-stats evaluation.

The reference never evaluates (no eval entry point; BN buffers written,
never read) — this is a capability addition, so the goldens here are
self-referential: (1) moments pooled over the calibration set are exact,
(2) running mode with stats from exactly ONE batch reproduces the
train-mode forward on that batch, (3) the default mode stays "batch" so
the training path is provably untouched.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.evaluate import (
    collect_batch_stats,
    evaluate,
    make_eval_step,
    make_predict,
)
from mpi4dl_tpu.models.resnet import get_resnet_v2
from mpi4dl_tpu.ops.layers import TrainBatchNorm, bn_stats_mode, current_bn_mode
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.train import apply_cells
from mpi4dl_tpu.utils import get_depth


def _batches(n, shape, seed=0):
    rng = np.random.default_rng(seed)
    return [
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(n)
    ]


def _tiny_resnet(layout=None):
    kwargs = {"layout": layout} if layout else {}
    return get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=8, **kwargs
    )


def test_bn_mode_default_and_restore():
    assert current_bn_mode() == "batch"
    with bn_stats_mode("collect"):
        assert current_bn_mode() == "collect"
    assert current_bn_mode() == "batch"
    with pytest.raises(ValueError):
        with bn_stats_mode("nope"):
            pass


def test_collected_stats_are_exact_pooled_moments():
    # One bare BN module: the calibrated {mean, var} must equal the
    # analytic moments of the concatenated calibration set.
    bn = TrainBatchNorm()
    xs = _batches(3, (2, 4, 4, 5))
    params = bn.init(jax.random.PRNGKey(0), xs[0])
    stats = collect_batch_stats([bn], [params], xs)[0]
    allx = np.concatenate([np.asarray(x) for x in xs], axis=0)
    want_mean = allx.reshape(-1, 5).mean(0)
    want_var = allx.reshape(-1, 5).var(0)
    np.testing.assert_allclose(np.asarray(stats["mean"]), want_mean, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["var"]), want_var, atol=1e-5)


@pytest.mark.parametrize("layout", [None, "packed"])
def test_single_batch_calibration_reproduces_train_forward(layout):
    # Stats collected from exactly one batch == that batch's statistics,
    # so running mode must reproduce the train-mode forward bit-near-exactly
    # — covering every BN site of a real model (incl. PackedTrainBatchNorm).
    cells = _tiny_resnet(layout)
    x = _batches(1, (2, 32, 32, 3))[0]
    params = init_cells(
        _tiny_resnet(None), jax.random.PRNGKey(1), jnp.zeros_like(x)
    )
    train_out = apply_cells(cells, params, x)
    stats = collect_batch_stats(cells, params, [x])
    eval_out = make_predict(cells)(params, stats, x)
    np.testing.assert_allclose(
        np.asarray(eval_out), np.asarray(train_out), atol=1e-5
    )


def test_eval_step_and_evaluate_aggregate():
    cells = _tiny_resnet()
    xs = _batches(2, (4, 32, 32, 3))
    ys = [jnp.asarray([0, 1, 2, 3], jnp.int32), jnp.asarray([4, 5, 6, 7], jnp.int32)]
    params = init_cells(cells, jax.random.PRNGKey(2), jnp.zeros_like(xs[0]))
    stats = collect_batch_stats(cells, params, xs)

    step = make_eval_step(cells)
    m = step(params, stats, xs[0], ys[0])
    assert np.isfinite(float(m["loss"]))
    assert 0 <= int(m["correct"]) <= 4

    agg = evaluate(cells, params, stats, list(zip(xs, ys)))
    assert agg["count"] == 8
    assert 0.0 <= agg["accuracy"] <= 1.0
    assert np.isfinite(agg["loss"])

    # Frozen stats ⇒ deterministic and batch-composition independent:
    # evaluating one example alone matches its logits inside the batch.
    pred = make_predict(cells)
    full = pred(params, stats, xs[0])
    one = pred(params, stats, xs[0][:1])
    np.testing.assert_allclose(
        np.asarray(one[0]), np.asarray(full[0]), atol=1e-5
    )


def test_running_mode_needs_no_stats_for_bn_free_cells():
    # Cells without BN get an empty stats entry; the plumbing must not
    # invent a batch_stats collection for them.
    from mpi4dl_tpu.ops.layers import Dense

    cells = [Dense(features=3)]
    x = jnp.ones((2, 5), jnp.float32)
    params = [cells[0].init(jax.random.PRNGKey(0), x)]
    stats = collect_batch_stats(cells, params, [x])
    assert stats == [{}]
    out = make_predict(cells)(params, stats, x)
    assert out.shape == (2, 3)
