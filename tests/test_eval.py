"""Eval/inference path: BN calibration + frozen-stats evaluation.

The reference never evaluates (no eval entry point; BN buffers written,
never read) — this is a capability addition, so the goldens here are
self-referential: (1) moments pooled over the calibration set are exact,
(2) running mode with stats from exactly ONE batch reproduces the
train-mode forward on that batch, (3) the default mode stays "batch" so
the training path is provably untouched.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.evaluate import (
    collect_batch_stats,
    evaluate,
    make_eval_step,
    make_predict,
)
from mpi4dl_tpu.models.resnet import get_resnet_v2
from mpi4dl_tpu.ops.layers import TrainBatchNorm, bn_stats_mode, current_bn_mode
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.train import apply_cells
from mpi4dl_tpu.utils import get_depth


def _batches(n, shape, seed=0):
    rng = np.random.default_rng(seed)
    return [
        jnp.asarray(rng.standard_normal(shape), jnp.float32) for _ in range(n)
    ]


def _tiny_resnet(layout=None):
    kwargs = {"layout": layout} if layout else {}
    return get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=8, **kwargs
    )


def test_bn_mode_default_and_restore():
    assert current_bn_mode() == "batch"
    with bn_stats_mode("collect"):
        assert current_bn_mode() == "collect"
    assert current_bn_mode() == "batch"
    with pytest.raises(ValueError):
        with bn_stats_mode("nope"):
            pass


def test_collected_stats_are_exact_pooled_moments():
    # One bare BN module: the calibrated {mean, var} must equal the
    # analytic moments of the concatenated calibration set.
    bn = TrainBatchNorm()
    xs = _batches(3, (2, 4, 4, 5))
    params = bn.init(jax.random.PRNGKey(0), xs[0])
    stats = collect_batch_stats([bn], [params], xs)[0]
    allx = np.concatenate([np.asarray(x) for x in xs], axis=0)
    want_mean = allx.reshape(-1, 5).mean(0)
    want_var = allx.reshape(-1, 5).var(0)
    np.testing.assert_allclose(np.asarray(stats["mean"]), want_mean, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["var"]), want_var, atol=1e-5)


@pytest.mark.parametrize("layout", [None, "packed"])
def test_single_batch_calibration_reproduces_train_forward(layout):
    # Stats collected from exactly one batch == that batch's statistics,
    # so running mode must reproduce the train-mode forward bit-near-exactly
    # — covering every BN site of a real model (incl. PackedTrainBatchNorm).
    cells = _tiny_resnet(layout)
    x = _batches(1, (2, 32, 32, 3))[0]
    params = init_cells(
        _tiny_resnet(None), jax.random.PRNGKey(1), jnp.zeros_like(x)
    )
    train_out = apply_cells(cells, params, x)
    stats = collect_batch_stats(cells, params, [x])
    eval_out = make_predict(cells)(params, stats, x)
    np.testing.assert_allclose(
        np.asarray(eval_out), np.asarray(train_out), atol=1e-5
    )


def test_eval_step_and_evaluate_aggregate():
    cells = _tiny_resnet()
    xs = _batches(2, (4, 32, 32, 3))
    ys = [jnp.asarray([0, 1, 2, 3], jnp.int32), jnp.asarray([4, 5, 6, 7], jnp.int32)]
    params = init_cells(cells, jax.random.PRNGKey(2), jnp.zeros_like(xs[0]))
    stats = collect_batch_stats(cells, params, xs)

    step = make_eval_step(cells)
    m = step(params, stats, xs[0], ys[0])
    assert np.isfinite(float(m["loss"]))
    assert 0 <= int(m["correct"]) <= 4

    agg = evaluate(cells, params, stats, list(zip(xs, ys)))
    assert agg["count"] == 8
    assert 0.0 <= agg["accuracy"] <= 1.0
    assert np.isfinite(agg["loss"])

    # Frozen stats ⇒ deterministic and batch-composition independent:
    # evaluating one example alone matches its logits inside the batch.
    pred = make_predict(cells)
    full = pred(params, stats, xs[0])
    one = pred(params, stats, xs[0][:1])
    np.testing.assert_allclose(
        np.asarray(one[0]), np.asarray(full[0]), atol=1e-5
    )


def _spatial_trainer(image_size=32, depth=None, batch=4):
    """Spatial Trainer (2x2 tiles) + its plain twin cells."""
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.train import Trainer

    depth = depth if depth is not None else get_depth(2, 1)
    plain = get_resnet_v2(
        depth=depth, num_classes=10, pool_kernel=image_size // 4
    )
    n_sp = len(plain) - 1
    cells = get_resnet_v2(
        depth=depth, num_classes=10, pool_kernel=image_size // 4,
        spatial_cells=n_sp,
    )
    cfg = ParallelConfig(
        batch_size=batch, split_size=1, spatial_size=1,
        num_spatial_parts=(4,), slice_method="square", image_size=image_size,
    )
    return Trainer(
        cells, num_spatial_cells=n_sp, config=cfg, plain_cells=plain
    ), plain


def test_spatial_eval_matches_plain_twin():
    """Sharded calibration + eval through the spatial Trainer forward must
    reproduce the single-device plain-twin eval on the same data — the
    cross-check that makes the sharded path trustworthy at resolutions
    where the plain twin CANNOT run (VERDICT r3 weak #4)."""
    from mpi4dl_tpu.evaluate import (
        spatial_collect_batch_stats,
        spatial_evaluate,
    )

    trainer, plain = _spatial_trainer()
    x0 = jnp.zeros((4, 32, 32, 3), jnp.float32)
    params = init_cells(plain, jax.random.PRNGKey(3), x0)

    cal = _batches(2, (4, 32, 32, 3), seed=10)
    rng = np.random.default_rng(11)
    test = [
        (
            jnp.asarray(rng.standard_normal((4, 32, 32, 3)), jnp.float32),
            jnp.asarray(rng.integers(0, 10, size=(4,)), jnp.int32),
        )
        for _ in range(2)
    ]

    # Golden: plain-twin calibration + eval on one device.
    stats_plain = collect_batch_stats(plain, params, cal)
    golden = evaluate(plain, params, stats_plain, test)

    # Sharded: the trainer's own spatial cells over the 2x2 tile mesh.
    stats_sp = spatial_collect_batch_stats(trainer, params, cal)
    got = spatial_evaluate(trainer, params, stats_sp, test)

    # The calibrated statistics themselves must agree site-for-site.
    for sp, pl in zip(stats_sp, stats_plain):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5
            ),
            sp,
            pl,
        )
    assert got["count"] == golden["count"]
    assert got["accuracy"] == golden["accuracy"]
    np.testing.assert_allclose(got["loss"], golden["loss"], rtol=1e-5)


def test_spatial_eval_footprint_recorded():
    """Memory observability (docs/OBSERVABILITY.md "Memory"): the sharded
    eval step's predicted peak lands in the footprint ledger through the
    generic record_lowered hook — compile-only, nothing executes, and the
    per-device number is what the tiled-inference sizing math reads."""
    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.evaluate import make_spatial_eval_step

    trainer, plain = _spatial_trainer()
    x0 = jnp.zeros((4, 32, 32, 3), jnp.float32)
    params = init_cells(plain, jax.random.PRNGKey(3), x0)
    stats = collect_batch_stats(plain, params, _batches(1, (4, 32, 32, 3)))
    xs, ys = trainer.shard_batch(x0, jnp.zeros((4,), jnp.int32))

    reg = telemetry.MetricsRegistry()
    ledger = telemetry.FootprintLedger(registry=reg)
    entry = ledger.record_lowered(
        "spatial_eval", make_spatial_eval_step(trainer),
        params, stats, xs, ys,
    )
    assert entry["peak_bytes"] > 0
    assert reg.get("program_peak_hbm_bytes").value(
        program="spatial_eval"
    ) == entry["peak_bytes"]


def test_spatial_eval_scales_past_single_device_footprint():
    """The point of the sharded path: per-device activations are the train
    step's forward tiles — 1/num_tiles of the full image. Runs a config
    distributed-only (256px through a deeper stack; the equivalent plain
    twin would hold the full 256x256 activations at every layer on one
    device) and checks the per-device input really is the 128x128 tile."""
    from mpi4dl_tpu.evaluate import (
        spatial_collect_batch_stats,
        spatial_evaluate,
    )

    trainer, plain = _spatial_trainer(image_size=256, batch=2)
    x0 = jnp.zeros((2, 256, 256, 3), jnp.float32)
    params = init_cells(plain, jax.random.PRNGKey(4), x0)

    xs, _ = trainer.shard_batch(
        x0, jnp.zeros((2,), jnp.int32)
    )
    shard_shapes = {s.data.shape for s in xs.addressable_shards}
    assert shard_shapes == {(2, 128, 128, 3)}, shard_shapes  # tiles, not image

    cal = _batches(1, (2, 256, 256, 3), seed=12)
    rng = np.random.default_rng(13)
    test = [
        (
            jnp.asarray(rng.standard_normal((2, 256, 256, 3)), jnp.float32),
            jnp.asarray(rng.integers(0, 10, size=(2,)), jnp.int32),
        )
    ]
    stats = spatial_collect_batch_stats(trainer, params, cal)
    res = spatial_evaluate(trainer, params, stats, test)
    assert res["count"] == 2
    assert np.isfinite(res["loss"])


def test_running_mode_needs_no_stats_for_bn_free_cells():
    # Cells without BN get an empty stats entry; the plumbing must not
    # invent a batch_stats collection for them.
    from mpi4dl_tpu.ops.layers import Dense

    cells = [Dense(features=3)]
    x = jnp.ones((2, 5), jnp.float32)
    params = [cells[0].init(jax.random.PRNGKey(0), x)]
    stats = collect_batch_stats(cells, params, [x])
    assert stats == [{}]
    out = make_predict(cells)(params, stats, x)
    assert out.shape == (2, 3)
