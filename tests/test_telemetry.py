"""Telemetry subsystem (:mod:`mpi4dl_tpu.telemetry`): registry semantics,
reservoir percentiles vs the shared ``percentiles()`` ground truth,
Prometheus exposition-format escaping, JSONL schema round-trip, thread
safety under concurrent load, the catalog↔docs↔exposed-names CI gates,
and the end-to-end acceptance invariants — a scraped endpoint whose
latency histogram agrees with the load generator's own report, and a JSONL
span log where per-request phase durations sum exactly to the observed
end-to-end latency.
"""

import json
import os
import re
import threading
import urllib.request

import numpy as np
import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.profiling import StepTimer, percentiles
from mpi4dl_tpu.telemetry.catalog import CATALOG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- registry semantics -------------------------------------------------------


def test_counter_semantics():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("requests_total", "help", labels=("outcome",))
    c.inc(outcome="ok")
    c.inc(2, outcome="ok")
    c.inc(outcome="err")
    assert c.value(outcome="ok") == 3
    assert c.value(outcome="err") == 1
    with pytest.raises(ValueError):  # counters are monotone
        c.inc(-1, outcome="ok")
    with pytest.raises(ValueError):  # label names are declared up front
        c.inc(bucket="4")
    # Same name, same signature → same object; different signature → error.
    assert reg.counter("requests_total", "help", labels=("outcome",)) is c
    with pytest.raises(ValueError):
        reg.counter("requests_total", "help", labels=("other",))
    with pytest.raises(ValueError):
        reg.gauge("requests_total")
    with pytest.raises(ValueError):  # invalid prometheus name
        reg.counter("bad-name")


def test_gauge_semantics():
    reg = telemetry.MetricsRegistry()
    g = reg.gauge("queue_depth")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value() == 6
    g.set(-3)  # gauges may be anything
    assert g.value() == -3


def test_histogram_buckets_and_snapshot():
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("lat", "h", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    (series,) = h.snapshot_series()
    assert series["count"] == 4
    assert series["sum"] == pytest.approx(5.555)
    # Cumulative le buckets, +Inf == count.
    assert series["buckets"] == {"0.01": 1, "0.1": 2, "1": 3, "+Inf": 4}


def test_reservoir_percentiles_match_ground_truth():
    rng = np.random.default_rng(0)
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("lat")
    small = rng.standard_exponential(200).tolist()
    for v in small:
        h.observe(v)
    # Below reservoir capacity the reservoir holds EVERY observation:
    # percentiles are bit-identical to the shared helper on the raw data.
    assert h.percentiles() == percentiles(small)

    # Above capacity it is a uniform sample: p50 within a loose tolerance.
    big = rng.standard_exponential(20_000).tolist()
    r = telemetry.Reservoir(size=1024)
    for v in big:
        r.observe(v)
    assert r.count == 20_000 and len(r.values) == 1024
    truth = percentiles(big)
    approx = r.percentiles()
    assert approx["p50"] == pytest.approx(truth["p50"], rel=0.15)
    assert approx["p90"] == pytest.approx(truth["p90"], rel=0.25)


def test_thread_safety_under_concurrent_load():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("hits_total", labels=("worker",))
    h = reg.histogram("obs")
    n_threads, n_iter = 8, 2000

    def work(wid):
        for i in range(n_iter):
            c.inc(worker=wid % 2)  # contended series
            h.observe(i * 1e-4)

    threads = [
        threading.Thread(target=work, args=(w,)) for w in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(worker=0) + c.value(worker=1) == n_threads * n_iter
    (series,) = h.snapshot_series()
    assert series["count"] == n_threads * n_iter
    assert series["buckets"]["+Inf"] == n_threads * n_iter


# -- Prometheus exposition format --------------------------------------------


def test_prometheus_rendering_shape():
    reg = telemetry.MetricsRegistry()
    reg.counter("req_total", "requests", labels=("outcome",)).inc(
        3, outcome="served"
    )
    reg.gauge("depth", "queue").set(7)
    reg.histogram("lat", "latency", buckets=(0.1, 1.0)).observe(0.5)
    text = telemetry.render_prometheus(reg)
    assert "# TYPE req_total counter" in text
    assert 'req_total{outcome="served"} 3' in text
    assert "# HELP depth queue" in text
    assert "depth 7" in text
    assert 'lat_bucket{le="0.1"} 0' in text
    assert 'lat_bucket{le="1"} 1' in text
    assert 'lat_bucket{le="+Inf"} 1' in text
    assert "lat_sum 0.5" in text
    assert "lat_count 1" in text


def test_prometheus_escaping():
    reg = telemetry.MetricsRegistry()
    reg.counter(
        "esc_total", 'help with \\ and\nnewline', labels=("path",)
    ).inc(path='a"b\\c\nd')
    text = telemetry.render_prometheus(reg)
    assert r"# HELP esc_total help with \\ and\nnewline" in text
    assert r'esc_total{path="a\"b\\c\nd"} 1' in text
    # One logical line per sample — the newline really was escaped.
    assert len(text.strip().splitlines()) == 3


# -- JSONL schema + round-trip ------------------------------------------------


def test_jsonl_round_trip(tmp_path):
    w = telemetry.JsonlWriter(str(tmp_path))
    assert w.enabled
    reg = telemetry.MetricsRegistry()
    reg.counter("c_total").inc()
    reg.histogram("h").observe(0.25)
    spans = telemetry.spans_from_marks(
        [("submit", 1.0), ("queue_wait", 1.5), ("compute", 2.25)]
    )
    events = [
        telemetry.span_event("serve.request", "trace-1", spans,
                             attrs={"outcome": "served"}),
        telemetry.metrics_event(reg),
        {"ts": 3.0, "kind": "event", "name": "engine.start", "attrs": {}},
    ]
    for e in events:
        w.write(e)
    w.close()
    back = telemetry.read_events(w.path)  # validates every line
    assert back == json.loads(json.dumps(events))  # float-stable round trip
    assert back[0]["spans"][0]["duration_s"] == 0.5
    assert back[1]["metrics"]["c_total"]["series"][0]["value"] == 1


def test_jsonl_disabled_without_dir(monkeypatch):
    monkeypatch.delenv(telemetry.ENV_DIR, raising=False)
    w = telemetry.JsonlWriter()
    assert not w.enabled
    w.write({"ts": 0, "kind": "event", "name": "x"})  # silent no-op
    w.close()


def test_validate_event_rejects_malformed():
    ok = {"ts": 1.0, "kind": "event", "name": "x"}
    telemetry.validate_event(ok)
    bad = [
        {"kind": "event", "name": "x"},  # no ts
        {"ts": 1.0, "kind": "bogus", "name": "x"},  # unknown kind
        {"ts": 1.0, "kind": "span", "name": "x", "trace_id": "t",
         "spans": []},  # empty spans
        {"ts": 1.0, "kind": "span", "name": "x", "trace_id": "t",
         "spans": [{"phase": "p", "start_s": 2.0, "end_s": 1.0,
                    "duration_s": -1.0}]},  # ends before start
        {"ts": 1.0, "kind": "metrics",
         "metrics": {"m": {"type": "counter", "series": [{}]}}},
    ]
    for ev in bad:
        with pytest.raises(ValueError):
            telemetry.validate_event(ev)


def test_spans_from_marks_contiguity():
    spans = telemetry.spans_from_marks(
        [("t0", 0.0), ("a", 1.0), ("b", 1.0), ("c", 4.5)]
    )
    assert [s["phase"] for s in spans] == ["a", "b", "c"]
    for prev, nxt in zip(spans, spans[1:]):
        assert prev["end_s"] == nxt["start_s"]
    assert sum(s["duration_s"] for s in spans) == 4.5  # == end - anchor
    with pytest.raises(ValueError):  # clock running backwards
        telemetry.spans_from_marks([("t0", 1.0), ("a", 0.5)])
    with pytest.raises(ValueError):  # anchor alone is not a span
        telemetry.spans_from_marks([("t0", 1.0)])


# -- scrape endpoint ----------------------------------------------------------


def test_metrics_server_scrape():
    reg = telemetry.MetricsRegistry()
    reg.counter("up_total").inc(4)
    srv = telemetry.MetricsServer(reg, port=0)
    try:
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        assert "up_total 4" in body
        reg.counter("up_total").inc()  # live: next scrape sees the update
        body = urllib.request.urlopen(srv.url, timeout=10).read().decode()
        assert "up_total 5" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=10
            )
    finally:
        srv.close()


def test_metrics_server_root_is_an_endpoint_index():
    """ISSUE satellite: probing the bare port discovers the surface — a
    text index of the routes this server actually answers, not a 404
    (and not a surprise full scrape). Provider-less routes are absent."""
    reg = telemetry.MetricsRegistry()
    reg.counter("up_total").inc()
    srv = telemetry.MetricsServer(reg, port=0)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/", timeout=10
        ).read().decode()
        assert "/metrics" in body
        assert "up_total" not in body  # index, not a scrape
        assert "/healthz" not in body  # no provider wired
    finally:
        srv.close()
    srv = telemetry.MetricsServer(
        reg, port=0, health=lambda: {"healthy": True},
        debug=lambda: {}, alerts=lambda: {},
    )
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/", timeout=10
        ).read().decode()
        for route in ("/metrics", "/healthz", "/debugz", "/alertz"):
            assert route in body
    finally:
        srv.close()


def test_metrics_server_head_probe_gets_200():
    """ISSUE satellite: load-balancer/uptime probes use HEAD — they must
    get 200 with headers and no body, not http.server's default 501."""
    reg = telemetry.MetricsRegistry()
    reg.counter("up_total").inc(4)
    srv = telemetry.MetricsServer(reg, port=0)
    try:
        resp = urllib.request.urlopen(
            urllib.request.Request(srv.url, method="HEAD"), timeout=10
        )
        assert resp.status == 200
        assert int(resp.headers["Content-Length"]) > 0
        assert resp.read() == b""  # headers only
    finally:
        srv.close()


def test_metrics_server_non_get_head_is_405():
    """ISSUE satellite: the endpoints are read-only — writes answer 405
    (wrong method), not 404 (missing path) or 501 (unimplemented)."""
    reg = telemetry.MetricsRegistry()
    srv = telemetry.MetricsServer(reg, port=0)
    try:
        for method in ("POST", "PUT", "DELETE"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(urllib.request.Request(
                    srv.url, data=b"x" if method != "DELETE" else None,
                    method=method,
                ), timeout=10)
            assert exc.value.code == 405, method
    finally:
        srv.close()


def test_metrics_server_healthz_and_debugz():
    reg = telemetry.MetricsRegistry()
    state = {"healthy": True, "reason": "ok"}
    srv = telemetry.MetricsServer(
        reg, port=0, health=lambda: dict(state),
        debug=lambda: {"tail": [1, 2, 3]},
    )
    base = f"http://127.0.0.1:{srv.port}"
    try:
        assert urllib.request.urlopen(f"{base}/healthz", timeout=10).status == 200
        dbg = json.loads(
            urllib.request.urlopen(f"{base}/debugz", timeout=10).read()
        )
        assert dbg == {"tail": [1, 2, 3]}
        state["healthy"] = False
        state["reason"] = "watchdog tripped"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["reason"] == "watchdog tripped"
        # HEAD mirrors the status so probes need no body parsing.
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/healthz", method="HEAD"), timeout=10)
        assert exc.value.code == 503
    finally:
        srv.close()


def test_jsonl_close_flushes_partial_span_batch(tmp_path):
    """ISSUE satellite: span events flush in batches of 100; a writer
    closed with a partial batch (7 < 100) must still land every event."""
    w = telemetry.JsonlWriter(str(tmp_path))
    spans = telemetry.spans_from_marks([("t0", 0.0), ("phase", 1.0)])
    for i in range(7):
        w.write(telemetry.span_event("t", f"id-{i}", spans))
    w.close()
    assert len(telemetry.read_events(w.path)) == 7
    w.close()  # idempotent alongside the atexit hook


def test_steptimer_zero_dt_summary_does_not_raise():
    """ISSUE satellite: a step whose measured dt is 0 (clock too coarse)
    reports 0.0 img/s — the telemetry gauge's convention — instead of
    ZeroDivisionError inside summary()."""
    timer = StepTimer(batch_size=4, warmup=0)
    timer.times[:] = [0.0, 0.1]
    assert timer.images_per_sec == [0.0, 40.0]
    s = timer.summary()
    assert s["steps"] == 2
    assert s["images_per_sec_mean"] == 20.0


def test_prometheus_escaping_round_trips():
    """ISSUE satellite: HELP text and label values containing newlines,
    quotes, and backslashes survive escape → render → unescape exactly —
    including the sequences naive replace-chains corrupt (a literal
    backslash before an 'n', a trailing backslash)."""
    from mpi4dl_tpu.telemetry.export import (
        escape_help,
        escape_label_value,
        unescape_help,
        unescape_label_value,
    )

    nasty = [
        'plain',
        'a"b\\c\nd',
        'line1\nline2\n',
        'backslash-n literal \\n not newline',
        'trailing backslash \\',
        '\\\n"',
        '\\\\n',  # two backslashes then n — must not become \ + newline
    ]
    for s in nasty:
        assert unescape_label_value(escape_label_value(s)) == s, s
        assert unescape_help(escape_help(s)) == s, s
        # Escaped forms are single-line (the format's framing invariant).
        assert "\n" not in escape_label_value(s)
        assert "\n" not in escape_help(s)
    # And through a full render: the escaped sample parses back to the
    # original value from the exposition text itself.
    reg = telemetry.MetricsRegistry()
    reg.counter("rt_total", "h", labels=("path",)).inc(path='a"b\\c\nd')
    text = telemetry.render_prometheus(reg)
    (line,) = [l for l in text.splitlines() if l.startswith("rt_total{")]
    quoted = line[line.index('path="') + len('path="'):line.rindex('"')]
    assert unescape_label_value(quoted) == 'a"b\\c\nd'


def test_trace_ids_unique_across_processes(tmp_path):
    """ISSUE satellite: trace ids embed pid + a random component, so N
    replica processes minting ids concurrently cannot collide in the
    federated span stream — checked across two real spawned processes."""
    import subprocess
    import sys

    prog = (
        "from mpi4dl_tpu.telemetry import new_trace_id\n"
        "print('\\n'.join(new_trace_id('serve') for _ in range(200)))\n"
    )
    env = dict(
        os.environ,
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    outs = []
    for _ in range(2):
        outs.append(subprocess.run(
            [sys.executable, "-c", prog], env=env,
            capture_output=True, text=True, timeout=120, check=True,
        ).stdout.split())
    a, b = (set(o) for o in outs)
    assert len(a) == len(b) == 200
    assert not (a & b), "trace ids collided across processes"
    # Format: prefix-pidhex-rand32-counter; in-process ids stay ordered.
    assert outs[0][0].endswith("-0") and outs[0][199].endswith("-199")
    assert len(outs[0][0].split("-")) == 4


def test_metrics_server_snapshotz_is_machine_readable():
    """Tentpole seam: /snapshotz serves the registry as a schema-valid
    metrics event + the emitting pid — what the federation aggregator
    scrapes instead of parsing Prometheus text."""
    reg = telemetry.MetricsRegistry()
    reg.counter("up_total").inc(4)
    reg.histogram("lat", buckets=(0.1,)).observe(0.05)
    srv = telemetry.MetricsServer(reg, port=0)
    try:
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/snapshotz", timeout=10
        ).read())
        telemetry.validate_event(snap)
        assert snap["kind"] == "metrics"
        assert snap["pid"] == os.getpid()
        assert snap["metrics"]["up_total"]["series"][0]["value"] == 4
        index = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/", timeout=10
        ).read().decode()
        assert "/snapshotz" in index
    finally:
        srv.close()


def test_client_overhead_and_phase_shares_published(full_stack):
    """ISSUE satellites: the client-vs-engine latency gap is a real
    histogram (one observation per served request), and the engine's
    phase-share gauges mirror the span mix, summing to ~1."""
    reg, _, report, _, scraped = full_stack
    (ov,) = reg.get("serve_client_overhead_seconds").snapshot_series()
    assert ov["count"] == 48
    assert ov["sum"] >= 0
    assert report["client_overhead_s"] is not None
    assert report["client_overhead_s"]["p50"] >= 0
    assert "serve_client_overhead_seconds_bucket" in scraped

    shares = {
        s["labels"]["phase"]: s["value"]
        for s in reg.get("serve_phase_share").snapshot_series()
    }
    assert set(shares) == {
        "queue_wait", "batch_form", "h2d_stage", "device_compute"
    }
    assert sum(shares.values()) == pytest.approx(1.0, abs=1e-6)


# -- catalog gates: docs <-> catalog <-> what the stack exposes ---------------

_DOC_ROW = re.compile(r"^\|\s*`([a-z_][a-z0-9_]*)`\s*\|([^|]+)\|([^|]+)\|")


def _docs_catalog():
    path = os.path.join(REPO, "docs", "OBSERVABILITY.md")
    out = {}
    with open(path) as f:
        for line in f:
            m = _DOC_ROW.match(line.strip())
            if not m:
                continue
            name, mtype = m.group(1), m.group(2).strip()
            labels = tuple(re.findall(r"`([a-z_]+)`", m.group(3)))
            out[name] = (mtype, labels)
    return out


def test_docs_metric_table_matches_catalog():
    """CI satellite: docs/OBSERVABILITY.md lists exactly the cataloged
    metrics with matching types and labels — no silently undocumented and
    no stale documented names."""
    docs = _docs_catalog()
    assert set(docs) == set(CATALOG), (
        f"docs-only: {sorted(set(docs) - set(CATALOG))}, "
        f"catalog-only: {sorted(set(CATALOG) - set(docs))}"
    )
    for name, spec in CATALOG.items():
        assert docs[name] == (spec.type, spec.labels), (
            f"{name}: docs say {docs[name]}, catalog says "
            f"{(spec.type, spec.labels)}"
        )


def test_declare_refuses_uncataloged_names():
    reg = telemetry.MetricsRegistry()
    with pytest.raises(KeyError, match="CATALOG"):
        telemetry.declare(reg, "totally_new_metric")


# -- full stack: one registry, every publisher, every invariant ---------------


@pytest.fixture(scope="module")
def full_stack(tmp_path_factory):
    """One shared registry exercised by every publisher in the repo —
    serving engine (+ spans JSONL + scrape endpoint), load generator,
    StepTimer, Trainer.publish_telemetry, hlolint publish — then handed to
    the tests below as (registry, engine, loadgen report, jsonl events,
    scraped text)."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.serve.loadgen import run_closed_loop
    from mpi4dl_tpu.train import Trainer
    from mpi4dl_tpu.utils import get_depth

    size = 16
    tdir = str(tmp_path_factory.mktemp("tele"))
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=size // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )
    reg = telemetry.MetricsRegistry()
    engine = ServingEngine(
        cells, params, stats, example_shape=(size, size, 3), max_batch=4,
        default_deadline_s=30.0, registry=reg, metrics_port=0,
        telemetry_dir=tdir,
        # SLOs on (ISSUE CI satellite): the run must expose the slo_* /
        # alert_active / autoscale_desired_replicas names the catalog
        # now pins. headroom_alert_ratio arms memory_headroom_low — on
        # the CPU backend the gauge never publishes, so the alert is
        # armed but structurally untrippable (absent-not-wrong).
        slo=telemetry.SLOConfig(
            availability=0.999, latency_threshold_s=2.5, interval_s=0.2,
            headroom_alert_ratio=0.05,
        ),
    )
    engine.start()
    report = run_closed_loop(
        engine, 48, concurrency=12, deadline_s=30.0, events=engine.events,
    )
    scraped = urllib.request.urlopen(
        f"http://127.0.0.1:{engine.metrics_port}/metrics", timeout=10
    ).read().decode()
    # Federation publisher against the same registry: an aggregator
    # scraping this engine's own /snapshotz (the catalog pin must see
    # federation_replicas / federation_scrapes_total from a real scrape).
    from mpi4dl_tpu.telemetry.federation import FederatedAggregator

    agg = FederatedAggregator(
        replicas={"r0": f"http://127.0.0.1:{engine.metrics_port}"},
        registry=reg,
    )
    agg.scrape_once()
    assert agg.registry.get("federation_replicas").value(state="up") == 1
    # Fleet publisher (mpi4dl_tpu/fleet): the router/supervisor declare
    # the fleet_* names at construction; the one-call declare keeps the
    # catalog==runtime pin honest without spawning a fleet here (the
    # live series are exercised by tests/test_fleet.py).
    from mpi4dl_tpu import fleet

    fleet.declare_metrics(reg)
    # Tiled publisher (mpi4dl_tpu/serve/tiled.py): same pattern — the
    # tiled_* names declared in one call; the live series (a real tiled
    # engine streaming + stitching) are exercised by
    # tests/test_serve_tiled.py, and running a second engine against
    # THIS registry would perturb the counters the span/scrape tests
    # below reconcile against the loadgen report.
    from mpi4dl_tpu.serve import tiled as serve_tiled

    serve_tiled.declare_metrics(reg)
    engine.stop()
    engine.lint_report()  # hlolint_* gauges

    # Train-side publishers against the same registry.
    timer = StepTimer(batch_size=4, warmup=0, registry=reg)
    for _ in range(3):
        with timer.step():
            pass
    trainer = Trainer(
        cells, num_spatial_cells=0,
        config=ParallelConfig(
            batch_size=2, split_size=1, spatial_size=0, image_size=size
        ),
    )
    trainer.publish_telemetry(
        reg, params=params, x_shape=(2, size, size, 3)
    )
    # Footprint ledger, train side: the compiled step's predicted peak
    # under program_peak_hbm_bytes (the serve side recorded its buckets
    # at AOT warm-up above).
    state = trainer.init(jax.random.PRNGKey(0), (2, size, size, 3))
    xs, ys = trainer.shard_batch(
        jnp.zeros((2, size, size, 3), jnp.float32),
        jnp.zeros((2,), jnp.int32),
    )
    trainer.record_memory_footprint(state, xs, ys, registry=reg)
    # OOM forensics publisher: one canned-drill report so the counter
    # carries a real series in the full-stack run.
    from test_memory_obs import HBM_OOM

    telemetry.emit_oom_report(HBM_OOM, program="drill", registry=reg)

    # Trace-attribution publisher (profiling.capture -> analysis.trace):
    # a ppermute ring on the CPU mesh so the capture carries collective
    # slices and the overlap-ratio gauge gets a value.
    from jax.sharding import Mesh, PartitionSpec as P

    from mpi4dl_tpu import profiling
    from mpi4dl_tpu.analysis.trace import publish_attribution
    from mpi4dl_tpu.compat import shard_map

    mesh = Mesh(np.array(jax.devices()), ("x",))
    n = len(jax.devices())

    def body(v):
        w = jax.lax.ppermute(v, "x", [(i, (i + 1) % n) for i in range(n)])
        m = v[0]
        return v * (m @ m.T).sum() + w

    g = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_vma=False,
    ))
    v = jnp.ones((n, 64, 64))
    g(v).block_until_ready()
    cap = profiling.capture(
        lambda i: g(v), steps=3, logdir=str(tmp_path_factory.mktemp("tr"))
    )
    summary = cap.attribution()
    if summary["collective"]["overlap_ratio"] is None:
        # tiny programs can finish their collectives with no concurrent
        # compute sampled; the gauge must still be exercised
        summary["collective"]["overlap_ratio"] = 0.0
    publish_attribution(summary, reg, program="unit")

    # Pipeline-lens publisher (analysis/trace.py): a canned summary keeps
    # the catalog==runtime pin honest without compiling a pipeline
    # trainer here — the live capture behind these numbers is exercised
    # by tests/test_pipeline_lens.py (the fleet.declare_metrics pattern).
    from mpi4dl_tpu.analysis.trace import publish_pipeline_attribution

    publish_pipeline_attribution(
        {"bubble_fraction": 0.2, "stage_device_seconds": [0.5, 0.7],
         "img_per_s": 7.9},
        reg, program="pipeline_gpipe",
    )

    # Cost-model publisher (analysis/costmodel.py): same canned-publish
    # pattern — the live pricing path is exercised by
    # tests/test_costmodel.py and the bench extras.
    from mpi4dl_tpu.analysis.costmodel import (
        predict_program as _cm_predict, publish_prediction,
    )

    cm_pred = _cm_predict(
        [{"opcode": "collective-permute", "bytes_moved": 1 << 20,
          "is_async": True, "compute_between": 2}],
        interconnect="ici", analytic_bubble=0.2,
    )
    cm_pred["program"] = "train_step"
    publish_prediction(cm_pred, reg)

    events = telemetry.read_events(
        os.path.join(tdir, os.listdir(tdir)[0])
    )
    return reg, engine, report, events, scraped


def test_full_stack_exposes_exactly_the_catalog(full_stack):
    """CI satellite, the other direction: a run touching every publisher
    exposes exactly the cataloged names — a stale catalog entry nothing
    publishes anymore fails here."""
    reg = full_stack[0]
    assert set(reg.names()) == set(CATALOG)


def test_span_durations_sum_to_e2e_latency(full_stack):
    """ISSUE acceptance: in the JSONL span log, queue+form+stage+compute
    sum to the observed end-to-end latency, per request, exactly — the
    spans are contiguous by construction."""
    events = full_stack[3]
    span_events = [
        e for e in events
        if e["kind"] == "span" and e["name"] == "serve.request"
    ]
    served = [e for e in span_events if e["attrs"]["outcome"] == "served"]
    assert len(served) == 48
    # The in-process client wrote its own span segments into the same
    # log, sharing trace ids with the engine's — the joined view the
    # trace exporter renders.
    client = [
        e for e in events
        if e["kind"] == "span" and e["name"] == "client.request"
    ]
    assert len(client) == 48
    assert {e["trace_id"] for e in client} == {e["trace_id"] for e in served}
    for e in served:
        phases = [s["phase"] for s in e["spans"]]
        assert phases == [
            "queue_wait", "batch_form", "h2d_stage", "device_compute"
        ]
        for prev, nxt in zip(e["spans"], e["spans"][1:]):
            assert prev["end_s"] == nxt["start_s"]
        total = sum(s["duration_s"] for s in e["spans"])
        assert total == pytest.approx(e["attrs"]["e2e_latency_s"], abs=1e-9)


def test_scraped_endpoint_carries_serving_signals(full_stack):
    """ISSUE acceptance: the Prometheus endpoint of a loadgen run exposes
    request counts by outcome, queue depth, bucket occupancy, and latency
    histograms whose percentiles agree with loadgen's own report."""
    reg, engine, report, _, scraped = full_stack
    assert 'serve_requests_total{outcome="served"} 48' in scraped
    assert "serve_queue_depth" in scraped
    assert "serve_batch_occupancy_bucket" in scraped
    assert "serve_request_latency_seconds_bucket" in scraped
    assert "loadgen_requests_total" in scraped

    # Engine-side e2e percentiles vs the loadgen client's own measurement:
    # same requests, so they differ only by client-side future overhead.
    hist = reg.get("serve_request_latency_seconds")
    engine_p = hist.percentiles()
    client_p = report["latency_s"]
    assert engine_p["p50"] <= client_p["p50"] + 1e-3  # server <= client
    for p in ("p50", "p99"):
        assert abs(engine_p[p] - client_p[p]) <= max(
            0.05, 0.5 * client_p[p]
        ), f"{p}: engine {engine_p[p]} vs client {client_p[p]}"

    # Registry mirrors the engine's own stats() counters.
    s = engine.stats()
    assert reg.get("serve_requests_total").value(outcome="served") == s["served"]
    occupancy = reg.get("serve_batch_occupancy").snapshot_series()
    assert sum(x["count"] for x in occupancy) == s["batches"]
    assert sum(s["bucket_dispatches"].values()) == s["batches"]


def test_memory_observability_exposed(full_stack):
    """ISSUE acceptance: the full-stack run exposes every new memory
    metric name (the catalog pin above covers exactness): per-bucket
    ledger peaks with real values, the train step's program peak, the
    drill's oom report count — and the device gauges declared but
    series-less on the CPU backend (absent-not-wrong)."""
    reg, engine = full_stack[0], full_stack[1]
    bucket_peaks = reg.get("serve_bucket_peak_hbm_bytes")
    for b in engine.buckets:
        assert bucket_peaks.value(bucket=b) > 0
        assert bucket_peaks.value(bucket=b) == engine.memory_ledger.get(
            "serve_predict", bucket=b
        )["peak_bytes"]
    assert reg.get("program_peak_hbm_bytes").value(program="train_step") > 0
    assert reg.get("oom_reports_total").value(program="drill") == 1
    for name in ("device_hbm_used_bytes", "device_hbm_limit_bytes",
                 "device_hbm_headroom_ratio"):
        assert reg.get(name).snapshot_series() == []  # declared, absent
    # The engine's stats()/debugz memory view mirrors the ledger.
    mem = engine.stats()["memory"]
    assert set(mem["bucket_peak_hbm_bytes"]) == {
        str(b) for b in engine.buckets
    }
    # memory_headroom_low is armed on /alertz but untrippable on CPU.
    alerts = {a["name"]: a["state"] for a in engine.slo.state()["alerts"]}
    assert alerts["memory_headroom_low"] == "inactive"


def test_trainer_and_hlolint_gauges_published(full_stack):
    reg = full_stack[0]
    assert reg.get("train_steps_total").value() == 3
    assert reg.get("train_halo_shifts").value() == 0  # no spatial cells
    assert (
        reg.get("hlolint_ok").value(program="serve_predict") == 1.0
    )
    assert (
        reg.get("hlolint_findings").value(
            program="serve_predict", severity="error"
        ) == 0
    )


# -- bench.py result-line schema ----------------------------------------------


def test_bench_emit_telemetry_matches_jsonl_schema(capsys):
    """CI satellite: bench.py result lines embed the registry snapshot in
    the JSONL metrics-event schema — validated with the same validator the
    writer enforces."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_for_telemetry", os.path.join(REPO, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    reg = telemetry.MetricsRegistry()
    telemetry.declare(reg, "train_steps_total").inc(5)
    telemetry.declare(reg, "train_step_seconds").observe(0.1)
    bench._REGISTRY = reg
    bench._RESULT.update(
        metric="unit_test", value=1.0, unit="images/sec", vs_baseline=None
    )
    bench._emit()
    line = [
        l for l in capsys.readouterr().out.splitlines() if l.startswith("{")
    ][-1]
    rec = json.loads(line)
    ev = telemetry.validate_event(rec["telemetry"])  # raises on drift
    assert ev["kind"] == "metrics"
    assert ev["metrics"]["train_steps_total"]["series"][0]["value"] == 5
