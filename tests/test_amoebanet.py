"""AmoebaNet-D model family tests: architecture shape fixtures, spatial
forward parity, and tuple-valued ("MULTIPLE_INPUT/OUTPUT") stage interfaces
through the partitioner.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.models.amoebanet import amoebanetd
from mpi4dl_tpu.parallel.partition import init_cells, trace_shapes


def _forward(cells, params, x):
    h = x
    for c, p in zip(cells, params):
        h = c.apply(p, h)
    return h


def test_amoebanet_structure_and_shapes():
    """Cell count = 3r+6 + classify is num_layers//3 normal-cell triples with
    reductions between (ref builder ``amoebanet.py:535-615``); channel widths
    double at each reduction; final state concat width = channels * len(concat)."""
    cells = amoebanetd(num_classes=10, num_layers=3, num_filters=32)
    assert len(cells) == 9  # stem + 2 red + 3x(1 normal) + 2 red + classify
    shapes = trace_shapes(cells, split_size=1, input_shape=(2, 64, 64, 3))
    assert shapes[-1] == (2, 10)

    # Two-stage split produces a tuple wire (concat, skip) at the boundary.
    shapes2 = trace_shapes(cells, split_size=2, input_shape=(2, 64, 64, 3))
    boundary = shapes2[0]
    assert isinstance(boundary, tuple) and len(boundary) == 2
    assert all(len(s) == 4 for s in boundary)


def test_amoebanet_deeper_variant():
    cells = amoebanetd(num_classes=100, num_layers=6, num_filters=64)
    assert len(cells) == 12
    shapes = trace_shapes(cells, split_size=1, input_shape=(1, 64, 64, 3))
    assert shapes[-1] == (1, 100)


@pytest.mark.slow
@pytest.mark.parametrize("n_spatial", [3])
def test_amoebanet_spatial_forward_matches_plain(n_spatial):
    """Spatial cells (halo-exchange convs/pools, incl. the
    count_include_pad=False distributed avg pool and FactorizedReduce) must
    reproduce the plain model's activations on 2x2 tiles."""
    spatial_cells = amoebanetd(num_layers=3, num_filters=32, spatial_cells=n_spatial)
    plain_cells = amoebanetd(num_layers=3, num_filters=32)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, 64, 64, 3)), jnp.float32)
    params = init_cells(plain_cells, jax.random.PRNGKey(0), x)

    golden = _forward(plain_cells[:n_spatial], params[:n_spatial], x)

    dev = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(dev, ("tile_h", "tile_w"))
    spec = P(None, "tile_h", "tile_w", None)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), spec),
        out_specs=spec,
        check_vma=False,
    )
    def dist(p, tile):
        return _forward(spatial_cells[:n_spatial], p, tile)

    xs = jax.device_put(x, NamedSharding(mesh, spec))
    out = dist(params[:n_spatial], xs)
    # Spatial cells emit (concat, skip) tuples — compare leaf-wise.
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), rtol=2e-5, atol=2e-5
        ),
        out,
        golden,
    )
