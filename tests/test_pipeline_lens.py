"""Pipeline observability lens (ISSUE 14 tentpole): per-stage trace
attribution + measured bubble fraction for the scan-over-ticks pipeline
engine, canned and live.

Canned tests pin the branch-closure join (synthetic HLO + synthetic trace
slices with known counts). The live tier-1 acceptance captures real GPipe
and 1F1B train steps on the CPU mesh and asserts the measured
``pipeline_bubble_fraction`` matches the analytic schedule model —
``(S-1)/(S-1+M)`` for GPipe, ``(S-1)/(M+v*S-1)`` for interleaved 1F1B —
within :data:`~mpi4dl_tpu.analysis.trace.BUBBLE_TOL_ABS`/``_REL``, and
that the 1F1B arm's measured bubble is STRICTLY below the GPipe arm's at
equal (stages, micro-batches). Slot counting is deterministic (branch
executions of the compiled schedule), so the tolerance absorbs only trace
truncation, not scheduling noise.
"""

import numpy as np
import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.analysis.trace import (
    TraceError,
    crosscheck_bubble,
    pipeline_attribution,
    publish_pipeline_attribution,
    stage_switches,
)

# -- canned fixture: a 2-stage switch (3 branches) + its bwd twin -------------

CANNED_HLO = """\
HloModule pipe, is_scheduled=true

%stage0 (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0)
  ROOT %conv_s0.1 = f32[4]{0} multiply(f32[4]{0} %p0, f32[4]{0} %p0)
}

%stage1 (p1: f32[4]) -> f32[4] {
  %p1 = f32[4]{0} parameter(0)
  ROOT %conv_s1.1 = f32[4]{0} add(f32[4]{0} %p1, f32[4]{0} %p1)
}

%idle (p2: f32[4]) -> f32[4] {
  %p2 = f32[4]{0} parameter(0)
  ROOT %zeros.1 = f32[4]{0} broadcast(f32[4]{0} %p2), dimensions={0}
}

ENTRY %main.1 (i: s32[], x: f32[4]) -> f32[4] {
  %i = s32[] parameter(0)
  %x = f32[4]{0} parameter(1)
  %collective-permute.9 = f32[4]{0} collective-permute(f32[4]{0} %x), channel_id=1, source_target_pairs={{0,1}}
  ROOT %conditional.7 = f32[4]{0} conditional(s32[] %i, f32[4]{0} %x, f32[4]{0} %x, f32[4]{0} %x), branch_computations={%stage0, %stage1, %idle}
}
"""

_META = [
    {"ph": "M", "pid": 1, "name": "process_name", "args": {"name": "/host:CPU"}},
    {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
     "args": {"name": "python"}},
    {"ph": "M", "pid": 1, "tid": 20, "name": "thread_name",
     "args": {"name": "tf_XLAEigen/1"}},
]


def _slice(name, ts, dur=10, tid=20):
    return {"ph": "X", "pid": 1, "tid": tid, "ts": ts, "dur": dur,
            "name": name}


def _canned_events(active0=4, active1=4, idle=2, permutes=5):
    """One 1000us step window; stage0/stage1/idle branch markers executed
    a known number of times, plus wire permutes."""
    ev = list(_META)
    ev.append({"ph": "X", "pid": 1, "tid": 10, "ts": 0, "dur": 1000,
               "name": "mpi4dl_capture", "args": {"step_num": "0"}})
    t = 5
    for _ in range(active0):
        ev.append(_slice("conv_s0.1", t, dur=20)); t += 25
    for _ in range(active1):
        ev.append(_slice("conv_s1.1", t, dur=30)); t += 35
    for _ in range(idle):
        ev.append(_slice("zeros.1", t, dur=1)); t += 2
    for _ in range(permutes):
        ev.append(_slice("collective-permute.9", t, dur=4)); t += 5
    return ev


def test_stage_switches_finds_branch_closures():
    sw = stage_switches(CANNED_HLO, n_stages=2)
    assert len(sw) == 1 and sw[0]["name"] == "conditional.7"
    u = sw[0]["unique_names"]
    assert "conv_s0.1" in u[0] and "conv_s1.1" in u[1] and "zeros.1" in u[2]
    # Branch parameters are branch-local names; the conditional itself is
    # no branch's member.
    assert all("conditional.7" not in names for names in u)
    # A module without an (S+1)-branch conditional finds nothing.
    assert stage_switches(CANNED_HLO, n_stages=5) == []


def test_canned_pipeline_attribution_counts_and_bubble():
    """ISSUE tentpole (unit): slot counts per branch, the idle count as
    the bubble numerator, per-stage device seconds from the closure
    durations, and permute seconds — all from known canned values."""
    out = pipeline_attribution(
        _canned_events(active0=4, active1=4, idle=2, permutes=5),
        CANNED_HLO, n_stages=2,
    )
    assert out["active_slots_by_stage"] == [4, 4]
    assert out["idle_slots"] == 2
    assert out["total_slots"] == 10
    assert out["bubble_fraction"] == pytest.approx(0.2)
    # 4 x 20us and 4 x 30us of per-stage device time; 5 x 4us permute.
    assert out["stage_device_seconds"][0] == pytest.approx(80e-6)
    assert out["stage_device_seconds"][1] == pytest.approx(120e-6)
    assert out["permute_seconds"] == pytest.approx(20e-6)
    # Per-device idle share: each device idled 1 of its 5 slots.
    assert out["idle_share_by_stage"] == [pytest.approx(0.2)] * 2
    assert out["n_steps"] == 1 and out["n_switches"] == 1


def test_pipeline_attribution_requires_a_stage_switch():
    with pytest.raises(TraceError, match="no conditional"):
        pipeline_attribution(_canned_events(), CANNED_HLO, n_stages=4)


def test_crosscheck_bubble_verdicts():
    ok = {"bubble_fraction": 0.2}
    assert crosscheck_bubble(0.2, ok) == []
    # Inside tolerance: no finding.
    assert crosscheck_bubble(0.2, {"bubble_fraction": 0.21}) == []
    off = crosscheck_bubble(0.2, {"bubble_fraction": 0.4})
    assert off and off[0].rule == "pipeline-bubble-crosscheck"
    assert "above" in off[0].message
    low = crosscheck_bubble(0.2, {"bubble_fraction": 0.05})
    assert low and "below" in low[0].message
    missing = crosscheck_bubble(0.2, {"bubble_fraction": None})
    assert missing and "unmeasurable" in missing[0].message


def test_publish_pipeline_attribution_gauges():
    reg = telemetry.MetricsRegistry()
    publish_pipeline_attribution(
        {"bubble_fraction": 0.25, "stage_device_seconds": [0.5, 0.75],
         "img_per_s": 12.5},
        reg, program="pipeline_gpipe",
    )
    assert reg.get("pipeline_bubble_fraction").value(
        program="pipeline_gpipe") == 0.25
    assert reg.get("pipeline_stage_device_seconds").value(
        program="pipeline_gpipe", stage="1") == 0.75
    assert reg.get("pipeline_img_per_s").value(
        program="pipeline_gpipe") == 12.5


# -- live acceptance: measured vs analytic on the CPU mesh --------------------


S, PARTS = 2, 4


def _trainer(schedule):
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.parallel.pipeline import PipelineTrainer

    cfg = ParallelConfig(
        batch_size=2 * PARTS, parts=PARTS, split_size=S, spatial_size=0,
        image_size=32,
    )
    tr = PipelineTrainer(get_resnet_v1(depth=8), cfg, schedule=schedule)
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((2 * PARTS, 32, 32, 3)), jnp.float32
    )
    y = jnp.asarray(rng.integers(0, 10, size=(2 * PARTS,)), jnp.int32)
    xs, ys = tr.shard_batch(x, y)
    state = tr.init(jax.random.PRNGKey(0))
    state, metrics = tr.train_step(state, xs, ys)  # warm before capture
    float(metrics["loss"])
    return tr, state, xs, ys


@pytest.fixture(scope="module")
def live_captures(tmp_path_factory):
    """One real capture per schedule arm on the CPU mesh, shared by the
    assertions below; gauges published into one registry so the A/B
    coexistence is exercised too."""
    reg = telemetry.MetricsRegistry()
    out = {}
    for schedule in ("gpipe", "1f1b"):
        tr, state, xs, ys = _trainer(schedule)
        # One AOT compile per arm, shared by the capture's stage-switch
        # join AND the permute-budget lint below (the AOT path does not
        # hit the jit cache, so letting each consumer recompile would
        # triple the mesh compiles).
        hlo_text = tr._jit_step.lower(state, xs, ys).compile().as_text()
        logdir = str(tmp_path_factory.mktemp(f"lens-{schedule}"))
        state, summary = tr.capture_trace_attribution(
            state, xs, ys, steps=2, logdir=logdir, registry=reg,
            hlo_text=hlo_text,
        )
        out[schedule] = (tr, summary, hlo_text)
    return reg, out


def test_live_gpipe_bubble_matches_analytic(live_captures):
    """ISSUE acceptance (tier-1): measured GPipe pipeline_bubble_fraction
    matches the analytic (S-1)/(S-1+M) within the documented tolerance on
    a live CPU-mesh capture, and the crosscheck agrees."""
    _, caps = live_captures
    tr, summary = caps["gpipe"][:2]
    pipe = summary["pipeline"]
    analytic = (S - 1) / (S - 1 + PARTS)
    assert tr.analytic_bubble_fraction() == pytest.approx(analytic)
    assert pipe["bubble_fraction"] == pytest.approx(analytic, abs=0.02)
    assert crosscheck_bubble(analytic, pipe) == []
    # Both stages really attributed device time, on every switch (fwd +
    # backward replays).
    assert all(s > 0 for s in pipe["stage_device_seconds"])
    assert pipe["n_switches"] >= 2
    assert all(
        share == pytest.approx(analytic, abs=0.05)
        for share in pipe["idle_share_by_stage"]
    )


def test_live_1f1b_bubble_strictly_below_gpipe(live_captures):
    """ISSUE acceptance (tier-1): the 1F1B arm's measured bubble is
    strictly lower than the GPipe arm's at equal (stages, micro-batches),
    and matches ITS analytic model (S-1)/(M+v*S-1)."""
    _, caps = live_captures
    tr, summary = caps["1f1b"][:2]
    pipe = summary["pipeline"]
    analytic = (S - 1) / (PARTS + tr.n_virtual - 1)
    assert pipe["bubble_fraction"] == pytest.approx(analytic, abs=0.02)
    assert crosscheck_bubble(analytic, pipe) == []
    gp = caps["gpipe"][1]["pipeline"]
    assert pipe["bubble_fraction"] < gp["bubble_fraction"], (
        "interleaved 1f1b must measure a strictly smaller bubble"
    )


def test_live_gauges_published_per_arm(live_captures):
    reg, caps = live_captures
    g = reg.get("pipeline_bubble_fraction")
    assert g.value(program="pipeline_gpipe") == pytest.approx(
        caps["gpipe"][1]["pipeline"]["bubble_fraction"]
    )
    assert g.value(program="pipeline_1f1b") == pytest.approx(
        caps["1f1b"][1]["pipeline"]["bubble_fraction"]
    )
    assert reg.get("pipeline_img_per_s").value(
        program="pipeline_gpipe") > 0
    assert reg.get("pipeline_stage_device_seconds").value(
        program="pipeline_1f1b", stage="0") > 0


def test_live_permute_inventory_sits_at_the_budget(live_captures):
    """ISSUE acceptance: the compiled pipeline program passes hlolint
    INSIDE the stage-permute window — pinned exactly, since a pure-LP
    pipeline has zero halo shifts and the wire permutes have no dedupe
    slack. Linted from the fixture's compiled text (no recompile)."""
    from mpi4dl_tpu.analysis import analyze_hlo_text, compose, pipeline_delta

    _, caps = live_captures
    for schedule, (tr, _, hlo_text) in caps.items():
        rep = analyze_hlo_text(
            hlo_text,
            expected=compose(pipeline_delta(tr.stage_permute_count())),
        )
        assert rep.inventory.get("collective-permute", 0) == (
            tr.stage_permute_count()
        ), schedule
        assert not any(
            f["rule"] == "halo-permute-count" for f in rep.findings
        ), (schedule, rep.findings)


# -- 1f1b construction validation ---------------------------------------------


def test_1f1b_validation_errors():
    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v1
    from mpi4dl_tpu.parallel.pipeline import (
        GemsMasterTrainer,
        PipelineTrainer,
    )

    cfg = ParallelConfig(
        batch_size=4, parts=2, split_size=2, spatial_size=0, image_size=32
    )
    cells = get_resnet_v1(depth=8)
    with pytest.raises(ValueError, match="mirror"):
        PipelineTrainer(cells, cfg, schedule="1f1b", mirror=True)
    with pytest.raises(ValueError, match="virtual_stages"):
        PipelineTrainer(cells, cfg, schedule="1f1b", virtual_stages=1)
    with pytest.raises(ValueError, match="schedule"):
        PipelineTrainer(cells, cfg, schedule="pipedream")
    with pytest.raises(ValueError, match="gpipe"):
        GemsMasterTrainer(cells, cfg, schedule="1f1b")
    # Too few cells for the virtual split is a loud error, not a crash
    # three layers down.
    with pytest.raises(ValueError, match="virtual stages"):
        PipelineTrainer(cells, cfg, schedule="1f1b", virtual_stages=4)
