"""Fused one-pass 1x1-conv backward kernel (ops/dot1x1_pallas.py):
interpreter-mode equality against the stock two-dot backward it
replaces (``fastconv._conv2d_s1_bwd``'s 1x1 branch)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.ops import dot1x1_pallas


@pytest.mark.parametrize(
    "b,h,w,c,o",
    [
        (2, 16, 16, 104, 208),  # AmoebaNet-class widths
        (1, 8, 8, 128, 128),
        (2, 4, 8, 416, 104),  # c > o reduce
    ],
)
def test_fused_1x1_bwd_matches_two_dots(b, h, w, c, o):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, h, w, c)), jnp.float32)
    dy = jnp.asarray(rng.standard_normal((b, h, w, o)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((c, o)), jnp.float32)

    dx, dw = dot1x1_pallas.bwd_1x1(x, dy, w2, interpret=True)

    want_dx = jax.lax.dot_general(dy, w2, (((3,), (1,)), ((), ())))
    want_dw = jax.lax.dot_general(
        x, dy, (((0, 1, 2), (0, 1, 2)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx), rtol=2e-5)
    # dw accumulates across grid steps: f32 reduction order differs from
    # the single fused dot.
    np.testing.assert_allclose(
        np.asarray(dw), np.asarray(want_dw), rtol=1e-4, atol=1e-4
    )
    assert dw.dtype == jnp.float32


def test_conv2d_grad_with_fused_kernel_matches_stock(monkeypatch):
    """End-to-end VJP through fastconv.conv2d with the fused kernel forced
    on (interpreter): gradients must match the stock two-dot backward."""
    from mpi4dl_tpu.ops import fastconv

    monkeypatch.setattr(
        dot1x1_pallas, "dispatchable", lambda x, dy, w=None: True
    )
    monkeypatch.setattr(
        dot1x1_pallas, "bwd_1x1",
        lambda x, dy, w2: dot1x1_pallas._bwd_impl(x, dy, w2, interpret=True),
    )
    monkeypatch.setattr(fastconv, "_on_tpu", lambda: True)

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 104)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((1, 1, 104, 128)) * 0.1, jnp.float32)

    def loss(x, w):
        return jnp.sum(fastconv.conv2d(x, w) ** 2)

    gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)

    monkeypatch.setattr(
        dot1x1_pallas, "dispatchable", lambda x, dy, w=None: False
    )
    gx0, gw0 = jax.grad(loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx0), rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(gw), np.asarray(gw0), rtol=1e-4, atol=1e-4
    )


def test_probe_key_includes_weight_dtype(monkeypatch):
    """Mixed-precision params must reach the compile probe as their own
    dtype: a probe passed for x's dtype must not green-light an unprobed
    Mosaic program (ADVICE r5)."""
    import jax as jax_mod

    probed = []
    monkeypatch.setenv("MPI4DL_TPU_DOT1X1", "auto")
    monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        dot1x1_pallas, "_compiles",
        lambda x_shape, dtype, o, w_dtype: probed.append(
            (x_shape, dtype, o, w_dtype)
        ) or True,
    )
    x = jnp.zeros((2, 64, 64, 208), jnp.float32)
    dy = jnp.zeros((2, 64, 64, 208), jnp.float32)
    w32 = jnp.zeros((208, 208), jnp.float32)
    w16 = jnp.zeros((208, 208), jnp.bfloat16)
    assert dot1x1_pallas.dispatchable(x, dy, w32)
    assert dot1x1_pallas.dispatchable(x, dy, w16)
    assert probed[0][3] == "float32"
    assert probed[1][3] == "bfloat16"  # distinct probe, not a cache hit
    # Legacy call shape (no weight) keeps assuming w.dtype == x.dtype.
    assert dot1x1_pallas.dispatchable(x, dy)
    assert probed[2][3] == "float32"


def test_plan_respects_vmem_budget():
    # Huge rows force smaller chunks; an impossible shape returns None.
    assert dot1x1_pallas._plan(1, 256, 256, 208, 208, 2) is not None
    assert dot1x1_pallas._plan(1, 1, 512 * 512, 1664, 1664, 2) is None


def test_supported_gates():
    # narrow channels are rejected (lane-waste regime)
    assert not dot1x1_pallas.supported((2, 16, 16, 64), 104)
    assert not dot1x1_pallas.supported((2, 16, 16, 104), 64)
    # dx-result-size guard (VMEM stack wall)
    assert not dot1x1_pallas.supported((2, 1024, 1024, 208), 208)
    assert dot1x1_pallas.supported((2, 64, 64, 208), 208)
