"""Pallas halo-exchange kernel vs the XLA (ppermute) path.

The kernel (``mpi4dl_tpu/ops/halo_pallas.py``) runs under the Pallas TPU
interpreter on the CPU test mesh; forward output and input gradients must be
bit-identical to the XLA implementation (which the golden ``np.pad`` suite in
``test_halo.py`` already pins to single-device semantics).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from mpi4dl_tpu.compat import shard_map

from mpi4dl_tpu.ops import halo_pallas
from mpi4dl_tpu.parallel.halo import halo_exchange

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu" and not halo_pallas.interpret_available(),
    reason="this jax has no TPU-Pallas CPU interpreter "
    "(InterpretParams/TPUInterpretParams)",
)

SPEC = P(None, "tile_h", "tile_w", None)


def _mesh(th, tw):
    dev = np.asarray(jax.devices()[: th * tw]).reshape(th, tw)
    return Mesh(dev, ("tile_h", "tile_w"))


def _run(mesh, image, halo_h, halo_w, impl, fill=0.0):
    fn = shard_map(
        lambda x: halo_exchange(x, halo_h, halo_w, fill_value=fill, impl=impl),
        mesh=mesh,
        in_specs=(SPEC,),
        out_specs=SPEC,
        check_vma=False,
    )
    x = jax.device_put(jnp.asarray(image), NamedSharding(mesh, SPEC))
    y = jax.jit(fn)(x)
    return {
        tuple(map(int, np.argwhere(mesh.devices == s.device)[0])): np.asarray(s.data)
        for s in y.addressable_shards
    }


@pytest.mark.parametrize(
    "th,tw,halo_h,halo_w,fill",
    [
        (2, 2, 1, 1, 0.0),  # square slicing, corners via two-phase
        (2, 2, 2, 2, -np.inf),  # max-pool fill value
        (1, 4, 0, 2, 0.0),  # vertical slicing
        (4, 1, 3, 0, 0.0),  # horizontal, wide halo
    ],
)
def test_pallas_matches_xla_forward(th, tw, halo_h, halo_w, fill):
    rng = np.random.default_rng(1)
    image = rng.integers(0, 1000, size=(2, 16, 16, 3)).astype(np.float32)
    mesh = _mesh(th, tw)
    ref = _run(mesh, image, halo_h, halo_w, "xla", fill)
    got = _run(mesh, image, halo_h, halo_w, "pallas", fill)
    assert ref.keys() == got.keys()
    for k in ref:
        np.testing.assert_array_equal(got[k], ref[k])


@pytest.mark.parametrize("th,tw,halo_h,halo_w", [(2, 2, 1, 1), (1, 4, 0, 2)])
def test_pallas_gradient_matches_xla(th, tw, halo_h, halo_w):
    """custom_vjp of the strip-swap kernel == AD of the ppermute path."""
    rng = np.random.default_rng(2)
    image = rng.standard_normal((1, 8, 8, 2)).astype(np.float32)
    mesh = _mesh(th, tw)

    def make_loss(impl):
        def local(x):
            ext = halo_exchange(x, halo_h, halo_w, impl=impl)
            # Nontrivial reduction touching halo and interior differently.
            w = jnp.arange(ext.size, dtype=jnp.float32).reshape(ext.shape)
            from jax import lax

            return lax.psum(jnp.sum(ext * w), ("tile_h", "tile_w"))

        fn = shard_map(
            local,
            mesh=mesh,
            in_specs=(SPEC,),
            out_specs=P(),
            check_vma=False,
        )
        return lambda x: fn(x)

    x = jax.device_put(jnp.asarray(image), NamedSharding(mesh, SPEC))
    g_ref = jax.jit(jax.grad(make_loss("xla")))(x)
    g_pal = jax.jit(jax.grad(make_loss("pallas")))(x)
    np.testing.assert_allclose(np.asarray(g_pal), np.asarray(g_ref), rtol=0, atol=0)
