"""Serving engine (:mod:`mpi4dl_tpu.serve`) — batching correctness,
deadline/admission semantics, the no-compile-after-warm-up contract, the
hlolint serving gate, and the ISSUE acceptance measurement (dynamic
batching ≥2x batch-size-1 serial throughput at high offered load, every
admitted request inside its deadline, p50/p90/p99 in the report).

Bit-identity scope (probed, not assumed): XLA compiles a DIFFERENT program
per batch shape, and programs of different shapes legally differ in f32
reduction order (~1e-7 — the same "bit-for-bit up to f32 reduction order"
boundary every golden test in this repo draws). So the bit-exact claims
here are *within* one bucket executable — a request's logits must be
byte-identical whatever rides in the padding rows or in neighboring batch
slots, and identical to an unpadded batch of the same bucket shape — while
cross-bucket parity (bucket-1 vs bucket-4 executables) is checked to 1e-5.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.evaluate import (
    aot_compile_predict,
    collect_batch_stats,
    make_predict,
)
from mpi4dl_tpu.models.resnet import get_resnet_v2
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.serve import (
    DeadlineExceededError,
    QueueFullError,
    ServingEngine,
    bucket_for,
    pad_batch,
    power_of_two_buckets,
)
from mpi4dl_tpu.utils import get_depth

SIZE = 16


@pytest.fixture(scope="module")
def model():
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=SIZE // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    cal = [jnp.asarray(rng.standard_normal((4, SIZE, SIZE, 3)), jnp.float32)]
    stats = collect_batch_stats(cells, params, cal)
    return cells, params, stats


def _engine(model, **kw):
    cells, params, stats = model
    kw.setdefault("example_shape", (SIZE, SIZE, 3))
    kw.setdefault("max_batch", 4)
    kw.setdefault("default_deadline_s", 30.0)
    return ServingEngine(cells, params, stats, **kw)


def _examples(n, seed=1):
    rng = np.random.default_rng(seed)
    return [
        rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
        for _ in range(n)
    ]


# -- bucket policy -----------------------------------------------------------


def test_bucket_policy_helpers():
    assert power_of_two_buckets(8) == (1, 2, 4, 8)
    with pytest.raises(ValueError):
        power_of_two_buckets(6)
    assert bucket_for(3, (1, 2, 4)) == 4
    assert bucket_for(1, (4, 2, 1)) == 1
    with pytest.raises(ValueError):
        bucket_for(5, (1, 2, 4))
    batch = pad_batch(_examples(3), 4, np.float32)
    assert batch.shape == (4, SIZE, SIZE, 3)
    assert np.array_equal(batch[3], np.zeros((SIZE, SIZE, 3)))
    with pytest.raises(ValueError):
        pad_batch(_examples(5), 4, np.float32)


# -- batching correctness ----------------------------------------------------


def test_padded_bucket_rows_bit_identical(model):
    """The satellite's bit-identity requirement: a real row's logits from a
    padded bucketed batch are byte-equal to the unpadded eval of the same
    bucket shape — and independent of pad content and batch neighbors."""
    cells, params, stats = model
    compiled = aot_compile_predict(
        cells, params, stats, (SIZE, SIZE, 3), (4,)
    )[4]
    xs = _examples(3)

    padded = pad_batch(xs, 4, np.float32)
    got = np.asarray(compiled(params, stats, padded))

    # Unpadded eval at the same shape: same program (make_predict jits the
    # identical frozen-stats forward), 4 REAL examples — rows 0-2 must be
    # byte-identical to the padded run's.
    full = np.stack([*xs, _examples(1, seed=9)[0]])
    golden = np.asarray(make_predict(cells)(params, stats, full))
    np.testing.assert_array_equal(got[:3], golden[:3])

    # Pad content is inert: garbage in the pad row changes nothing.
    garbage = padded.copy()
    garbage[3] = 1e6
    np.testing.assert_array_equal(
        np.asarray(compiled(params, stats, garbage))[:3], got[:3]
    )

    # Slot independence: swapping neighbors permutes rows byte-exactly.
    swapped = pad_batch([xs[1], xs[0], xs[2]], 4, np.float32)
    out = np.asarray(compiled(params, stats, swapped))
    np.testing.assert_array_equal(out[0], got[1])
    np.testing.assert_array_equal(out[1], got[0])

    # Cross-bucket (different executable → different f32 reduction order):
    # per-request bucket-1 eval agrees to float tolerance.
    one = aot_compile_predict(cells, params, stats, (SIZE, SIZE, 3), (1,))[1]
    for i, ex in enumerate(xs):
        np.testing.assert_allclose(
            np.asarray(one(params, stats, ex[None]))[0], got[i], atol=1e-5
        )


def test_engine_serves_correct_results(model):
    cells, params, stats = model
    eng = _engine(model)
    eng.start()
    try:
        xs = _examples(10)
        futs = [eng.submit(x) for x in xs]
        results = [f.result(timeout=60) for f in futs]
    finally:
        eng.stop()
    pred = make_predict(cells)
    for x, got in zip(xs, results):
        want = np.asarray(pred(params, stats, x[None]))[0]
        np.testing.assert_allclose(got, want, atol=1e-5)
    s = eng.stats()
    assert s["served"] == 10
    assert s["batches"] >= 3  # max_batch=4 → at least ceil(10/4)
    assert set(s["latency_s"]) == {"p50", "p90", "p99"}
    # Telemetry satellite: live queue depth + per-bucket dispatch counts
    # (the autoscaling signal) are part of the stats surface.
    assert s["queue_depth"] == 0  # everything drained
    assert set(s["bucket_dispatches"]) == set(eng.buckets)
    assert sum(s["bucket_dispatches"].values()) == s["batches"]
    total_rows = sum(b * n for b, n in s["bucket_dispatches"].items())
    padded_rows = total_rows - s["batched_examples"]
    assert s["pad_waste_ratio"] == pytest.approx(padded_rows / total_rows)
    # The registry mirrors the same counters (one source of truth).
    assert eng.registry.get("serve_requests_total").value(
        outcome="served"
    ) == 10


def test_submit_propagates_caller_trace_id(model, tmp_path):
    """Distributed-trace seam: a caller-minted trace id rides through the
    engine's span events, and the resolved future reports the id plus
    the engine-side e2e latency (the client-overhead input)."""
    from mpi4dl_tpu import telemetry

    eng = _engine(model, telemetry_dir=str(tmp_path))
    eng.start()
    try:
        fut = eng.submit(_examples(1)[0], trace_id="hop-abc-7")
        fut.result(timeout=60)
    finally:
        eng.stop()
    assert fut.trace_id == "hop-abc-7"
    assert fut.e2e_latency_s > 0
    (log,) = tmp_path.iterdir()
    (ev,) = [
        e for e in telemetry.read_events(str(log)) if e["kind"] == "span"
    ]
    assert ev["trace_id"] == "hop-abc-7"
    assert ev["attrs"]["pid"] == os.getpid()


# -- deadlines + admission control -------------------------------------------


def test_deadline_expired_request_rejected_not_served(model):
    eng = _engine(model)  # not started: requests queue up
    f_dead = eng.submit(_examples(1)[0], deadline_s=0.0)
    f_live = eng.submit(_examples(1)[0], deadline_s=30.0)
    time.sleep(0.01)
    eng.start()
    try:
        with pytest.raises(DeadlineExceededError):
            f_dead.result(timeout=60)
        f_live.result(timeout=60)  # the live request still gets served
    finally:
        eng.stop()
    s = eng.stats()
    assert s["rejected_deadline"] == 1
    assert s["served"] == 1


def test_admission_control_bounded_queue(model):
    eng = _engine(model, max_queue=2)
    eng.submit(_examples(1)[0])
    eng.submit(_examples(1)[0])
    with pytest.raises(QueueFullError):
        eng.submit(_examples(1)[0])
    eng.start()
    eng.stop()  # drains the two admitted requests
    s = eng.stats()
    assert s["rejected_queue_full"] == 1
    assert s["served"] == 2


def test_queue_full_carries_retry_after_hint(model):
    """ISSUE satellite: a queue-full rejection tells the client WHEN to
    come back — the live batch cadence (floored at the formation
    window), seeded from the warm latency before the first batch."""
    eng = _engine(model, max_queue=1)
    eng.submit(_examples(1)[0])
    with pytest.raises(QueueFullError) as ei:
        eng.submit(_examples(1)[0])
    hint = ei.value.retry_after_s
    assert hint is not None and hint > 0
    # Pre-first-batch: the warm latency (or the wait window) stands in.
    assert hint >= max(eng._max_wait_s, 0.0)
    eng.start()
    eng.stop()
    # Post-serving: the hint follows the measured completion cadence.
    assert eng.retry_after_hint() > 0


def test_stop_without_drain_counts_drained_outcome(model):
    """ISSUE satellite: flushed-on-stop requests resolve with the typed
    DrainedError and the distinct outcome="drained" counter label —
    and the availability SLO math EXCLUDES them (a router-initiated
    drain must not burn the availability budget)."""
    from mpi4dl_tpu.serve.engine import DrainedError
    from mpi4dl_tpu.telemetry.slo import (
        availability_objective,
        cumulative_sli,
    )

    eng = _engine(model, max_queue=8)
    futs = [eng.submit(x) for x in _examples(3)]
    eng.stop(drain=False)  # engine never started: pure flush
    for f in futs:
        with pytest.raises(DrainedError):
            f.result(timeout=5)
    s = eng.stats()
    assert s["drained"] == 3 and s["served"] == 0
    assert eng.registry.get("serve_requests_total").value(
        outcome="drained"
    ) == 3
    # Drained-only traffic: no availability data at all (not 0%).
    obj = availability_objective(0.999)
    assert cumulative_sli(eng.registry, obj) is None
    # Mixed traffic: drained leaves the denominator entirely.
    eng.registry.get("serve_requests_total").inc(7, outcome="served")
    assert cumulative_sli(eng.registry, obj) == 1.0


def test_loadgen_retries_queue_full_with_backoff(model):
    """ISSUE satellite: opt-in bounded retry on admission bounces — the
    run measures shed-and-retry behavior (retries counted, requests
    eventually served) instead of instant failures."""
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    eng = _engine(model, max_queue=2, max_wait_s=0.001)
    # Deterministic bounces: the engine starts 50ms into the load, so
    # the 2-slot queue fills instantly and every further submit bounces
    # into the retry loop until the batcher comes up.
    starter = threading.Timer(0.05, eng.start)
    starter.start()
    try:
        rep = run_closed_loop(
            eng, 24, concurrency=8, deadline_s=30.0,
            queue_full_retries=200, retry_backoff_s=0.002,
        )
    finally:
        starter.join()
        eng.stop()
    # Every bounce was absorbed by a retry; nothing was lost.
    assert rep["served"] + rep["rejected_queue_full"] == 24
    assert rep["served"] == 24
    assert rep["queue_full_retries"] >= 1  # the queue DID bounce


def test_submit_after_stop_raises(model):
    eng = _engine(model)
    eng.start()
    eng.stop()
    with pytest.raises(RuntimeError):
        eng.submit(_examples(1)[0])


# -- no-compile-after-warm-up contract ---------------------------------------


def test_every_bucket_precompiled_and_missing_bucket_fails_loudly(model):
    eng = _engine(model, max_batch=4)
    assert set(eng._compiled) == {1, 2, 4} == set(eng.buckets)
    eng.assert_warm()
    # Sabotage one bucket: the engine must fail that batch's requests with
    # the assertion (never JIT on a live request, never hang the futures).
    missing = eng._compiled.pop(4)
    try:
        with pytest.raises(AssertionError, match="pre-(built|compiled)"):
            eng.assert_warm()
        # Queue 3 requests BEFORE starting so one bucket-4 batch forms.
        futs = [eng.submit(x) for x in _examples(3)]
        eng.start()
        with pytest.raises(AssertionError, match="pre-(built|compiled)"):
            futs[0].result(timeout=60)
    finally:
        eng._compiled[4] = missing
        eng.stop()


def test_sampled_attribution_publishes_live_trace_gauges(model):
    """ISSUE tentpole: with attribution_every on (interval floor lifted
    for the test), the serving loop itself captures a batch, parses it,
    and publishes the trace_* gauges under program=serve_sampled — the
    continuous twin of the one-shot --trace-dir report. With the floor
    at its default, the same traffic never samples (rate-limit works)."""
    eng = _engine(
        model, attribution_every=2, attribution_min_interval_s=0.0
    )
    eng.start()
    try:
        futs = [eng.submit(x) for x in _examples(10)]
        for f in futs:
            f.result(timeout=60)
    finally:
        eng.stop()
    assert eng.last_attribution is not None
    assert eng.last_attribution["program"] == "serve_sampled"
    wall = eng.registry.get("trace_step_wall_seconds")
    assert wall.value(program="serve_sampled") > 0
    att = eng.registry.get("trace_attribution_seconds")
    assert att.value(program="serve_sampled", category="compute") > 0
    # Sampled batches still serve correct results (checked implicitly by
    # result(); the futures resolved, none errored).

    # Default 30 s floor: same config, no sample fires after the
    # constructor's throwaway warm-up.
    eng2 = _engine(model, attribution_every=2)
    eng2.start()
    try:
        futs = [eng2.submit(x) for x in _examples(6)]
        for f in futs:
            f.result(timeout=60)
    finally:
        eng2.stop()
    assert eng2.last_attribution is None


# -- hlolint serving gate ----------------------------------------------------


def test_hlolint_gate_serving_hlo_has_zero_collectives(model):
    """CI gate over the real compiled serving executable: the single-chip
    serve path must contain zero collectives and no stray resharding."""
    eng = _engine(model)
    for bucket in eng.buckets:
        rep = eng.lint_report(bucket=bucket)
        assert rep.ok, rep.findings
        assert all(n == 0 for n in rep.inventory.values()), rep.inventory
        assert not any(
            f["rule"] in ("single-chip-collectives", "stray-all-to-all")
            for f in rep.findings
        )


# -- checkpoint → serve ------------------------------------------------------


def test_engine_from_checkpoint_path_alone(model, tmp_path):
    from mpi4dl_tpu.checkpoint import model_metadata, save_checkpoint
    from mpi4dl_tpu.train import TrainState, make_optimizer

    cells, params, stats = model
    state = TrainState(
        params=params,
        opt_state=make_optimizer().init(params),
        step=jnp.asarray(7, jnp.int32),
    )
    meta = model_metadata(
        "resnet_v2", image_size=SIZE,
        depth=get_depth(2, 1), num_classes=10, pool_kernel=SIZE // 4,
    )
    save_checkpoint(str(tmp_path), state, metadata=meta, batch_stats=stats)

    eng = ServingEngine.from_checkpoint(str(tmp_path), max_batch=2)
    x = _examples(1)[0]
    want = np.asarray(make_predict(cells)(params, stats, x[None]))[0]
    np.testing.assert_allclose(eng.predict_one(x), want, atol=1e-6)


def test_from_checkpoint_without_batch_stats_refuses(model, tmp_path):
    from mpi4dl_tpu.checkpoint import model_metadata, save_checkpoint
    from mpi4dl_tpu.train import TrainState, make_optimizer

    cells, params, _ = model
    state = TrainState(
        params=params,
        opt_state=make_optimizer().init(params),
        step=jnp.asarray(0, jnp.int32),
    )
    meta = model_metadata(
        "resnet_v2", image_size=SIZE,
        depth=get_depth(2, 1), num_classes=10, pool_kernel=SIZE // 4,
    )
    save_checkpoint(str(tmp_path), state, metadata=meta)
    with pytest.raises(ValueError, match="batch_stats"):
        ServingEngine.from_checkpoint(str(tmp_path))


# -- acceptance: dynamic batching beats serial at high offered load ----------


@pytest.fixture(scope="module")
def amoeba_engine():
    """Small AmoebaNet — many small ops per cell, the op-overhead-bound
    shape where micro-batching pays (on the TPU runtime a ~23 ms dispatch
    floor makes this THE serving story; on this CPU backend per-op launch
    overhead plays the same role at a smaller scale)."""
    from mpi4dl_tpu.models.amoebanet import amoebanetd

    size = 32
    cells = amoebanetd(num_classes=10, num_layers=3, num_filters=16)
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )
    eng = ServingEngine(
        cells, params, stats, example_shape=(size, size, 3),
        buckets=(1, 32), max_wait_s=0.003, max_queue=512,
        default_deadline_s=30.0,
    )
    yield eng
    eng.stop()


def test_loadgen_dynamic_batching_beats_serial(amoeba_engine):
    """ISSUE acceptance: at high offered load (closed loop, 96 clients ≫
    the 32-bucket), throughput ≥2x the batch-size-1 serial baseline, zero
    deadline misses, and the report carries p50/p90/p99.

    De-flake rationale (ISSUE 14 satellite): the SERIAL side is the noisy
    half of the ratio on the shared 1-core CI box — measured per-trial
    spread of ±12% on the bs-1 denominator (PR 10), while the batched
    numerator holds within a few percent, and the ratio grazed 1.99x once
    purely on a slow serial sample. So each attempt anchors the
    denominator at the MEDIAN of 3 serial measurements (a single fast or
    slow outlier cannot move a median-of-3), keeps the two re-measures for
    whole-box noise bursts, and the bound itself stays 2.0 — the claim
    "dynamic batching at least doubles serial throughput" is unchanged,
    only the estimator of serial throughput got robust."""
    from mpi4dl_tpu.profiling import percentiles
    from mpi4dl_tpu.serve.loadgen import run_closed_loop, serial_throughput

    eng = amoeba_engine
    eng.start()
    best = 0.0
    for _ in range(3):
        serial_rps = percentiles(
            [serial_throughput(eng, 32)["throughput_rps"] for _ in range(3)],
            (50,),
        )["p50"]
        rep = run_closed_loop(eng, 384, concurrency=96, deadline_s=30.0)
        assert rep["served"] == 384  # everything admitted was served...
        assert rep["deadline_misses"] == 0  # ...inside its deadline
        assert rep["errors"] == 0
        assert {"p50", "p90", "p99"} <= set(rep["latency_s"])
        assert json.loads(json.dumps(rep))  # report is JSON-serializable
        best = max(best, rep["throughput_rps"] / serial_rps)
        if best >= 2.0:
            break
    assert best >= 2.0, f"dynamic batching speedup {best:.2f}x < 2x"
    # Batches really formed (dynamic batching, not serial dispatch).
    assert rep["engine"]["mean_batch_size"] > 8


# -- CLI ---------------------------------------------------------------------


def test_serve_cli_end_to_end(capsys, tmp_path):
    from mpi4dl_tpu import telemetry
    from mpi4dl_tpu.serve.__main__ import main

    rc = main([
        "--image-size", "16", "--depth", "11", "--max-batch", "4",
        "--requests", "24", "--concurrency", "8", "--serial", "8",
        "--lint", "--metrics-port", "0", "--telemetry-dir", str(tmp_path),
        "--slo-availability", "99.9", "--slo-latency-ms", "2500",
        "--slo-interval", "0.2",
    ])
    assert rc == 0
    line = [
        l for l in capsys.readouterr().out.splitlines() if l.startswith("{")
    ][-1]
    rep = json.loads(line)
    assert rep["loadgen"]["served"] == 24
    assert {"p50", "p90", "p99"} <= set(rep["loadgen"]["latency_s"])
    assert rep["lint"]["ok"]
    assert rep["serial"]["throughput_rps"] > 0
    # Telemetry surface: the report names the bound scrape port, stats
    # carry the registry-backed fields, and the JSONL span log landed.
    assert isinstance(rep["metrics_port"], int)
    assert rep["loadgen"]["engine"]["queue_depth"] == 0
    # SLO verdict (ISSUE tentpole): 24/24 served inside a 1 s threshold
    # leaves both budgets untouched and no alert fired.
    assert rep["slo"]["ok"] is True
    assert rep["slo"]["slos"]["availability"]["sli"] == 1.0
    assert rep["slo"]["slos"]["availability"]["budget_remaining"] == 1.0
    assert rep["slo"]["alerts_fired"] == {}
    # Client-hop accounting (ISSUE satellite): the report carries the
    # measured client-vs-engine latency gap, non-negative by definition.
    assert rep["loadgen"]["client_overhead_s"]["p50"] >= 0
    (log,) = tmp_path.iterdir()
    events = telemetry.read_events(str(log))
    served = [
        e for e in events
        if e["kind"] == "span" and e["name"] == "serve.request"
        and e["attrs"]["outcome"] == "served"
    ]
    assert len(served) == 24
    # Distributed-trace join (ISSUE tentpole): the in-process client's
    # span segments share trace ids with the engine's — one id covers
    # client_submit→client_wait AND queue→batch→device.
    client = [
        e for e in events
        if e["kind"] == "span" and e["name"] == "client.request"
    ]
    assert {e["trace_id"] for e in client} >= {
        e["trace_id"] for e in served
    }
