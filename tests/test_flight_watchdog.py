"""Flight recorder + watchdog + health endpoints
(:mod:`mpi4dl_tpu.telemetry.flight` / ``.health``): ring-buffer bounds,
schema-valid dumps, deterministic trip/recovery logic on a fake clock,
SIGTERM dump chaining, StepTimer wiring, and the ISSUE fault drill — an
artificially stalled serving loop trips the watchdog, dumps a
schema-valid flight-recorder JSONL, and flips ``/healthz`` from 200 to
503 (then back on recovery). CPU-only, tier-1.
"""

import glob
import json
import os
import signal
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.profiling import StepTimer

# -- flight recorder ----------------------------------------------------------


def _marker(i):
    return {"ts": float(i), "kind": "event", "name": f"m{i}", "attrs": {}}


def test_flight_ring_is_bounded_and_tail_ordered():
    fr = telemetry.FlightRecorder(capacity=8)
    for i in range(20):
        fr.record(_marker(i))
    tail = fr.tail(3)
    assert [e["name"] for e in tail] == ["m17", "m18", "m19"]
    assert len(fr.tail(100)) == 8  # ring dropped the oldest 12


def test_flight_capacity_zero_disables():
    fr = telemetry.FlightRecorder(capacity=0)
    assert not fr.enabled
    fr.record(_marker(0))
    assert fr.tail() == []
    assert fr.dump(reason="manual") is None


def test_flight_dump_is_schema_valid_jsonl(tmp_path):
    reg = telemetry.MetricsRegistry()
    telemetry.declare(reg, "serve_submitted_total").inc(3)
    fr = telemetry.FlightRecorder(
        capacity=32, registry=reg, directory=str(tmp_path)
    )
    spans = telemetry.spans_from_marks([("t0", 0.0), ("phase", 0.5)])
    fr.record(telemetry.span_event("serve.request", "t-1", spans,
                                   attrs={"outcome": "served"}))
    fr.record(_marker(1))
    fr.record({"ts": 2.0, "kind": "bogus"})  # invalid: dropped, counted
    path = fr.dump(reason="manual")
    events = telemetry.read_events(path)  # validates every line
    assert events[-1]["name"] == "flight.dump"
    assert events[-1]["attrs"]["reason"] == "manual"
    assert events[-1]["attrs"]["dropped_invalid"] == 1
    kinds = [e["kind"] for e in events]
    assert "span" in kinds and "metrics" in kinds  # ring + final snapshot
    assert reg.get("flight_recorder_dumps_total").value(reason="manual") == 1


def test_flight_dump_refiles_under_open_incident(tmp_path):
    """Satellite (ISSUE 20): a dump fired while an incident is open is
    refiled under ``reason="incident"`` — the marker (and filename)
    carries the incident id plus the ORIGINAL trigger, so the close
    event's dump list links it and nothing about why it fired is
    lost. With no open incident the provider is a no-op."""
    reg = telemetry.MetricsRegistry()
    incident_id = []
    fr = telemetry.FlightRecorder(
        capacity=32, registry=reg, directory=str(tmp_path),
        incident=lambda: incident_id[0] if incident_id else None,
    )
    fr.record(_marker(0))
    path = fr.dump(reason="watchdog")
    assert path.endswith("-watchdog.jsonl")  # closed: untouched

    incident_id.append("inc-abc-123")
    path = fr.dump(reason="watchdog")
    assert path.endswith("-incident.jsonl")
    marker = telemetry.read_events(path)[-1]
    assert marker["name"] == "flight.dump"
    assert marker["attrs"]["reason"] == "incident"
    assert marker["attrs"]["trigger"] == "watchdog"
    assert marker["attrs"]["incident"] == "inc-abc-123"
    assert reg.get("flight_recorder_dumps_total").value(
        reason="incident"
    ) == 1
    # A broken provider must never lose the dump itself.
    fr.incident = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    path = fr.dump(reason="crash")
    assert path.endswith("-crash.jsonl")
    assert telemetry.read_events(path)[-1]["attrs"]["reason"] == "crash"


def test_flight_sigterm_dump_chains_previous_handler(tmp_path):
    fr = telemetry.FlightRecorder(capacity=8, directory=str(tmp_path))
    fr.record(_marker(0))
    hits = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
    try:
        assert fr.install_signal_handlers()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5
        while not hits and time.time() < deadline:
            time.sleep(0.01)
        assert hits == [signal.SIGTERM]  # previous handler still ran
        dumps = glob.glob(str(tmp_path / "flight-*-sigterm.jsonl"))
        assert len(dumps) == 1
        assert telemetry.read_events(dumps[0])[-1]["attrs"]["reason"] == (
            "sigterm"
        )
    finally:
        fr.uninstall_signal_handlers()
        signal.signal(signal.SIGTERM, prev)


# -- watchdog (fake clock: deterministic, no real waits) ----------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_watchdog_trips_only_when_work_is_outstanding_and_stalled():
    clock = _Clock()
    reg = telemetry.MetricsRegistry()
    health = telemetry.HealthState(registry=reg)
    tripped = []
    wd = telemetry.Watchdog(
        factor=2.0, min_timeout_s=1.0, registry=reg, health=health,
        on_trip=tripped.append, clock=clock, start=False,
    )
    # Idle: no amount of elapsed time trips.
    clock.t += 100
    assert wd.check() is None
    # Outstanding work within the timeout: no trip.
    wd.begin()
    clock.t += 0.9
    assert wd.check() is None
    # Past the timeout: trip once (not once per poll).
    clock.t += 0.2
    reason = wd.check()
    assert reason and "no completion" in reason
    assert wd.check() is None
    assert len(tripped) == 1
    assert not health.healthy
    assert reg.get("watchdog_trips_total").value() == 1
    assert reg.get("serve_healthy").value() == 0.0
    # Completion recovers the health state.
    wd.done(0.5)
    assert health.healthy
    assert reg.get("serve_healthy").value() == 1.0
    wd.close()


def test_watchdog_timeout_adapts_to_rolling_p99():
    clock = _Clock()
    wd = telemetry.Watchdog(
        factor=10.0, min_timeout_s=0.5, clock=clock, start=False,
    )
    assert wd.timeout_s() == 0.5  # empty history -> floor
    wd.seed(0.2)
    assert wd.timeout_s() == pytest.approx(2.0)  # 10 x p99(0.2)
    for _ in range(100):
        wd.begin()
        wd.done(0.01)
    assert wd.timeout_s() == pytest.approx(0.5)  # fast again -> floor
    wd.close()


def test_watchdog_cancel_is_not_progress():
    """A queue-full admission bounce must not reset the stall clock —
    otherwise a stalled loop behind a churning submit path never trips."""
    clock = _Clock()
    wd = telemetry.Watchdog(
        factor=2.0, min_timeout_s=1.0, clock=clock, start=False,
    )
    wd.begin()  # the stuck request
    clock.t += 0.8
    wd.begin()
    wd.cancel()  # admission rejected another request meanwhile
    clock.t += 0.4  # 1.2s since the stuck request; cancel didn't reset
    assert wd.check() is not None
    wd.close()


def test_steptimer_reports_to_watchdog():
    clock = _Clock()
    wd = telemetry.Watchdog(
        factor=2.0, min_timeout_s=1.0, clock=clock, start=False,
    )
    timer = StepTimer(batch_size=2, warmup=0, watchdog=wd)
    for _ in range(3):
        with timer.step():
            pass
    st = wd.state()
    assert st["outstanding"] == 0
    assert st["history"] == 3  # every step's duration landed
    wd.close()


# -- the ISSUE fault drill ----------------------------------------------------


@pytest.fixture(scope="module")
def engine_parts():
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells

    size = 16
    cells = get_resnet_v2(depth=11, num_classes=10, pool_kernel=size // 4)
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )
    return cells, params, stats, size


def _get_status(url):
    try:
        return urllib.request.urlopen(url, timeout=10).status
    except urllib.error.HTTPError as e:
        return e.code


def test_serving_fault_drill(engine_parts, tmp_path):
    """ISSUE acceptance: an artificially stalled serving loop trips the
    watchdog, dumps a schema-valid flight-recorder JSONL, and flips
    /healthz from 200 to 503 — then recovers to 200 when the stalled
    batch finally completes."""
    from mpi4dl_tpu.serve import ServingEngine

    cells, params, stats, size = engine_parts
    eng = ServingEngine(
        cells, params, stats, example_shape=(size, size, 3), max_batch=2,
        default_deadline_s=30.0, metrics_port=0,
        watchdog_factor=2.0, watchdog_min_timeout_s=0.25,
        flight_dir=str(tmp_path),
    )
    base = f"http://127.0.0.1:{eng.metrics_port}"
    assert _get_status(f"{base}/healthz") == 200

    # Stall the loop: every bucket executable sleeps well past the
    # watchdog timeout before doing the real work.
    orig = dict(eng._compiled)

    def _slow(bucket):
        def call(p, s, batch):
            time.sleep(1.5)
            return orig[bucket](p, s, batch)
        return call

    eng._compiled = {b: _slow(b) for b in eng.buckets}
    eng.start()
    try:
        x = np.zeros((size, size, 3), np.float32)
        fut = eng.submit(x, deadline_s=30.0)
        deadline = time.time() + 5
        status = 200
        while status != 503 and time.time() < deadline:
            status = _get_status(f"{base}/healthz")
            time.sleep(0.05)
        assert status == 503, "watchdog never flipped /healthz"
        assert not eng.health.healthy
        assert eng.registry.get("watchdog_trips_total").value() == 1

        # The trip dumped the ring as schema-valid JSONL.
        dumps = glob.glob(str(tmp_path / "flight-*-watchdog.jsonl"))
        assert len(dumps) == 1
        events = telemetry.read_events(dumps[0])  # validates every line
        names = [e.get("name") for e in events]
        assert "serve.watchdog_trip" in names
        assert names[-1] == "flight.dump"
        assert eng.registry.get("flight_recorder_dumps_total").value(
            reason="watchdog"
        ) == 1

        # /debugz serves the postmortem context live while unhealthy.
        dbg = json.loads(
            urllib.request.urlopen(f"{base}/debugz", timeout=10).read()
        )
        assert dbg["watchdog"]["tripped"] is True
        assert any(
            e.get("name") == "serve.watchdog_trip" for e in dbg["flight_tail"]
        )

        # The stalled batch eventually completes: request served,
        # health self-recovers to 200.
        assert fut.result(timeout=10).shape == (10,)
        deadline = time.time() + 5
        while status != 200 and time.time() < deadline:
            status = _get_status(f"{base}/healthz")
            time.sleep(0.05)
        assert status == 200
        assert eng.health.healthy
    finally:
        eng._compiled = orig
        eng.stop()
    assert eng.stats()["healthy"] is True


def test_engine_crash_dumps_flight(engine_parts, tmp_path):
    """A batcher-thread crash (not just a bad batch) flips health and
    leaves a crash dump for the postmortem."""
    from mpi4dl_tpu.serve import ServingEngine

    cells, params, stats, size = engine_parts
    eng = ServingEngine(
        cells, params, stats, example_shape=(size, size, 3), max_batch=2,
        default_deadline_s=30.0, watchdog_factor=None,
        flight_dir=str(tmp_path),
    )
    # Break the loop itself (batch formation), not one batch's dispatch.
    eng._form_batch = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
    x = np.zeros((size, size, 3), np.float32)
    fut = eng.submit(x)  # queued before the loop starts (and crashes)
    eng.start()
    with pytest.raises(RuntimeError, match="boom|crashed"):
        fut.result(timeout=10)
    deadline = time.time() + 5
    while eng.health.healthy and time.time() < deadline:
        time.sleep(0.02)
    assert not eng.health.healthy
    dumps = glob.glob(str(tmp_path / "flight-*-crash.jsonl"))
    assert len(dumps) == 1
    telemetry.read_events(dumps[0])  # schema-valid
    eng.stop()
