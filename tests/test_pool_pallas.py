"""Pallas one-pass max-pool backward vs XLA's reduce_window gradient
(interpreter mode — same math on CPU; the TPU lowering is exercised by
the compile probe + bench runs).

The kernel's tie rule is row-major first-max-wins == XLA's
``select_and_scatter``, so with integer-valued cotangents (float sums
exact regardless of accumulation order) the comparison is bit-exact even
on tie-heavy integer inputs.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.ops import pool_pallas


def _kernel_dx(x, dy, kh, kw, sh, sw, ph, pw):
    neg = jnp.asarray(float("-inf"), x.dtype)
    xp = jax.lax.pad(
        x, neg, ((0, 0, 0), (ph, ph, 0), (pw, pw, 0), (0, 0, 0))
    )
    dxp = pool_pallas._bwd_padded(
        xp, dy, kh=kh, kw=kw, sh=sh, sw=sw, interpret=True
    )
    h, w = x.shape[1], x.shape[2]
    return dxp[:, ph : ph + h, pw : pw + w, :]


def _xla_dx(x, dy, kh, kw, sh, sw, ph, pw):
    f = functools.partial(
        pool_pallas._fwd_val, kh=kh, kw=kw, sh=sh, sw=sw, ph=ph, pw=pw
    )
    _, vjp = jax.vjp(f, x)
    (dx,) = vjp(dy)
    return dx


@pytest.mark.parametrize(
    "shape,k,s,p,tie_heavy",
    [
        ((2, 16, 16, 8), 3, 1, 1, True),  # normal-cell 3x3 s1 pool
        ((2, 16, 16, 8), 3, 1, 1, False),
        ((1, 18, 18, 8), 3, 1, 0, True),  # pre-padded VALID form
        ((2, 16, 16, 8), 3, 2, 1, True),  # reduction-cell 3x3 s2 pool
        ((2, 16, 16, 8), 3, 2, 1, False),  # (even size: uncovered pad row)
        ((1, 8, 32, 16), 3, 1, 1, True),  # rectangular
        ((1, 32, 8, 128), 3, 2, 1, True),
    ],
)
def test_bwd_matches_select_and_scatter(shape, k, s, p, tie_heavy):
    rng = np.random.default_rng(0)
    if tie_heavy:
        x = jnp.asarray(rng.integers(0, 3, size=shape), jnp.float32)
    else:
        x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    ho = (shape[1] + 2 * p - k) // s + 1
    wo = (shape[2] + 2 * p - k) // s + 1
    dy = jnp.asarray(
        rng.integers(-64, 64, size=(shape[0], ho, wo, shape[3])), jnp.float32
    )
    assert pool_pallas.supported(shape, k, k, s, s, p, p, 4)
    got = _kernel_dx(x, dy, k, k, s, s, p, p)
    want = _xla_dx(x, dy, k, k, s, s, p, p)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_forward_matches_tree():
    from mpi4dl_tpu.ops.layers import max_pool_s1_valid

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 18, 18, 8)), jnp.float32)
    y_tree = max_pool_s1_valid(x, 3, 3)  # CPU: tree path (pallas not usable)
    y_pool = pool_pallas._fwd_val(x, 3, 3, 1, 1, 0, 0)
    np.testing.assert_array_equal(np.asarray(y_tree), np.asarray(y_pool))


def test_gates(monkeypatch):
    # non-overlapping windows: XLA's backward is fine, kernel declines
    assert not pool_pallas.supported((2, 16, 16, 8), 2, 2, 2, 2, 0, 0)
    # CPU backend: usable() is False even for supported shapes
    x = jnp.zeros((2, 16, 16, 8), jnp.float32)
    if jax.default_backend() != "tpu":
        assert not pool_pallas.usable(x, 3, 3, 1, 1, 1, 1)
    # env off-switch
    monkeypatch.setenv("MPI4DL_TPU_POOL_PALLAS", "off")
    assert not pool_pallas.usable(x, 3, 3, 1, 1, 1, 1)
    monkeypatch.setenv("MPI4DL_TPU_POOL_PALLAS", "bogus")
    with pytest.raises(ValueError):
        pool_pallas.pool_pallas_mode()


def test_disable_context():
    """Trainer arms pool_pallas.disable() for >=2048px traces: injecting
    the kernel's VMEM-stack-allocated results into a program compiled
    against the HBM ceiling kills the compile helper (round-4 incident:
    AmoebaNet@2048 bs1 compiled with the kernels off, died with them on).
    The context must gate dispatchable() regardless of backend."""
    x = jnp.zeros((2, 18, 18, 8), jnp.float32)
    with pool_pallas.disable():
        assert not pool_pallas.dispatchable(x, 3, 3, 1, 1, 0, 0)
        with pool_pallas.disable():  # re-entrant
            assert not pool_pallas.dispatchable(x, 3, 3, 1, 1, 0, 0)
        assert not pool_pallas.dispatchable(x, 3, 3, 1, 1, 0, 0)
    assert not pool_pallas._DISABLED[0]
