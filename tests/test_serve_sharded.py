"""Multi-chip sharded serving (:mod:`mpi4dl_tpu.serve.sharded`) — the
spatial-parallel forward on the serving hot path.

Covers the ISSUE's tier-1 equivalence suite and gates:

- each sharded bucket's output rows vs the single-chip forward on the
  CPU mesh, for BOTH overlap arms and a non-square (1×2) mesh — the two
  arms of one mesh are bit-identical to each other (the PR-9 invariant,
  now on serving), and sharded-vs-plain agrees at the documented f32
  reduction-order tolerance (different program → different reduction
  order, the same boundary every cross-program golden in this repo
  draws);
- the mesh-derived hlolint expectations: single-chip engines keep the
  zero-collectives gate byte-for-byte, sharded engines flip to the
  partition-math halo-permute window off ``Trainer.halo_shift_count``,
  and every warmed bucket's HLO sits EXACTLY at the counted forward
  shifts (forward-only program — no backward doubling);
- a ``memory_guard`` refusal drill on a sharded bucket (per-chip share
  vs limit, reasons in ``stats()``);
- the end-to-end acceptance: a 2×2-sharded engine AOT-warms, lints
  clean, and serves a closed-loop load with zero deadline misses
  through the unchanged batcher/scheduler stack.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.analysis.rules import Expectations
from mpi4dl_tpu.evaluate import collect_batch_stats, make_predict
from mpi4dl_tpu.models.resnet import get_resnet_v1
from mpi4dl_tpu.parallel.partition import init_cells
from mpi4dl_tpu.serve import ServingEngine, SingleChipPredictor
from mpi4dl_tpu.serve.sharded import (
    ShardedPredictor,
    parse_mesh,
    serving_mesh_config,
    sharded_engine,
)

SIZE = 16
DEPTH = 8
N_SP = 2


@pytest.fixture(scope="module")
def model():
    """Calibrated spatial-ResNet triple: spatial cells (first N_SP
    flagged), plain twin (identical param/BN structure), params, pooled
    BN stats — the input of both the sharded and the single-chip
    engine, so every comparison below shares one set of weights."""
    plain = get_resnet_v1(depth=DEPTH, num_classes=10, pool_kernel=SIZE // 4)
    cells = get_resnet_v1(
        depth=DEPTH, num_classes=10, pool_kernel=SIZE // 4,
        spatial_cells=N_SP,
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        plain, jax.random.PRNGKey(0), jnp.zeros((1, SIZE, SIZE, 3))
    )
    cal = [jnp.asarray(rng.standard_normal((4, SIZE, SIZE, 3)), jnp.float32)]
    stats = collect_batch_stats(plain, params, cal)
    return cells, plain, params, stats


def _sharded(model, mesh_shape=(2, 2), conv_overlap=None, **kw):
    cells, plain, params, stats = model
    kw.setdefault("buckets", (1, 4))
    kw.setdefault("default_deadline_s", 60.0)
    kw.setdefault("watchdog_factor", None)
    kw.setdefault("memory_monitor", False)
    return sharded_engine(
        cells, plain, N_SP, params, stats,
        example_shape=(SIZE, SIZE, 3), mesh_shape=mesh_shape,
        conv_overlap=conv_overlap, **kw,
    )


def _examples(n, seed=1):
    rng = np.random.default_rng(seed)
    return [
        rng.standard_normal((SIZE, SIZE, 3)).astype(np.float32)
        for _ in range(n)
    ]


def _golden(model, xs):
    _, plain, params, stats = model
    pred = make_predict(plain)
    full = np.stack(xs)
    return np.asarray(pred(params, stats, full))


# -- mesh-derived lint expectations (ISSUE satellite) -------------------------


def test_lint_expectations_mesh_derived(model):
    """Both derivations of the engine's lint gate: a single-chip
    predictor derives EXACTLY the former hardcoded
    ``Expectations(single_chip=True)`` (byte-for-byte — no field
    drifts), a sharded predictor derives the partition-math halo window
    (tile grid + counted forward shifts) with single_chip OFF."""
    cells, plain, params, stats = model
    single = SingleChipPredictor(
        plain, params, stats, (SIZE, SIZE, 3), jnp.float32
    )
    assert dataclasses.asdict(single.expectations()) == dataclasses.asdict(
        Expectations(single_chip=True)
    )
    assert single.mesh_shape == (1, 1) and single.num_devices == 1
    assert single.halo_shifts() == 0

    from mpi4dl_tpu.train import Trainer

    cfg = serving_mesh_config((2, 2), SIZE)
    trainer = Trainer(
        cells, num_spatial_cells=N_SP, config=cfg, plain_cells=plain
    )
    sharded = ShardedPredictor(trainer, params, stats, (SIZE, SIZE, 3))
    exp = sharded.expectations()
    assert exp.single_chip is False
    assert exp.tile_shape == (2, 2)
    assert exp.halo_shifts == trainer.halo_shift_count(
        sharded.params, (1, SIZE, SIZE, 3)
    ) > 0
    assert sharded.num_devices == 4


def test_parse_mesh_and_config_validation():
    assert parse_mesh("2x2") == (2, 2)
    assert parse_mesh("1x2") == (1, 2)
    with pytest.raises(ValueError, match="HxW"):
        parse_mesh("four")
    assert serving_mesh_config((1, 2), SIZE).slice_method == "vertical"
    assert serving_mesh_config((2, 1), SIZE).slice_method == "horizontal"
    assert serving_mesh_config((2, 2), SIZE).slice_method == "square"
    with pytest.raises(ValueError, match="single-chip"):
        serving_mesh_config((1, 1), SIZE)
    with pytest.raises(ValueError, match="unsupported mesh"):
        serving_mesh_config((2, 4), SIZE)


# -- tier-1 equivalence suite (ISSUE satellite) -------------------------------


def test_sharded_bucket_rows_match_single_chip_both_arms(model):
    """Each sharded bucket's output rows vs the single-chip forward, for
    both overlap arms on the 2×2 mesh: the arms are bit-identical to
    EACH OTHER (same mesh, different schedule), and both match the
    plain forward at the f32 reduction-order tolerance."""
    mono = _sharded(model, (2, 2), conv_overlap="monolithic")
    dec = _sharded(model, (2, 2), conv_overlap="decomposed")
    xs = _examples(4)
    golden = _golden(model, xs)
    try:
        for bucket in mono.buckets:
            batch = np.stack(xs[:bucket])
            got_m = np.asarray(
                mono._predictor.run(mono._compiled[bucket], batch)
            )
            got_d = np.asarray(
                dec._predictor.run(dec._compiled[bucket], batch)
            )
            # PR-9 invariant on the serving forward: the decomposition
            # changes the schedule, never the numbers.
            np.testing.assert_array_equal(got_m, got_d)
            np.testing.assert_allclose(got_m, golden[:bucket], atol=1e-5)
        # The two arms derive the SAME permute inventory (halo_exchange
        # runs once per windowed op either way).
        assert (
            mono._predictor.halo_shifts() == dec._predictor.halo_shifts()
        )
    finally:
        mono.stop()
        dec.stop()


def test_sharded_equivalence_non_square_mesh(model):
    """The 1×2 (vertical-slice) mesh: W splits across 2 chips, H stays
    whole — same rows as the plain forward."""
    eng = _sharded(model, (1, 2), buckets=(2,))
    xs = _examples(2, seed=3)
    golden = _golden(model, xs)
    try:
        assert eng.mesh_shape == (1, 2)
        got = np.asarray(eng._predictor.run(eng._compiled[2], np.stack(xs)))
        np.testing.assert_allclose(got, golden, atol=1e-5)
        rep = eng.lint_report(bucket=2)
        assert rep.ok, rep.findings
    finally:
        eng.stop()


# -- halo-window lint gate ----------------------------------------------------


def test_every_sharded_bucket_lints_at_exact_halo_window(model):
    """Every warmed bucket's HLO passes the mesh-derived lint with zero
    errors, and the compiled permute inventory sits EXACTLY at the
    counted forward halo shifts — a forward-only program has no
    backward re-shifts, so the partition-math floor is also the
    ceiling. Zero stray resharding: no all-to-all at any bucket."""
    eng = _sharded(model, (2, 2))
    try:
        shifts = eng._predictor.halo_shifts()
        assert shifts > 0
        for bucket in eng.buckets:
            rep = eng.lint_report(bucket=bucket)
            assert rep.ok, rep.findings
            assert not any(
                f["severity"] == "error" for f in rep.findings
            )
            assert rep.inventory.get("collective-permute", 0) == shifts
            assert rep.inventory.get("all-to-all", 0) == 0
        # The scrapeable mesh facts the catalog pins.
        assert eng.registry.get("serve_mesh_devices").value() == 4
        assert eng.registry.get("serve_halo_shifts").value() == shifts
    finally:
        eng.stop()


# -- memory guard on a sharded bucket (ISSUE satellite) -----------------------


def test_memory_guard_refuses_unfit_sharded_bucket(model):
    """The refusal drill on the SHARDED path: with a limit set between
    the small and the large bucket's per-chip predicted peak, the large
    bucket is refused at warm-up with the reason in ``stats()`` and the
    engine degrades to the bucket that fits."""
    probe = _sharded(model, (2, 2), buckets=(1, 4))
    peaks = {
        int(b): v
        for b, v in probe.memory_view()["bucket_peak_hbm_bytes"].items()
    }
    probe.stop()
    if peaks.get(1) is None or peaks.get(4) is None:
        pytest.skip("backend reports no compile-time peaks")
    assert peaks[4] > peaks[1]  # bigger bucket, bigger per-chip share
    limit = (peaks[1] + peaks[4]) // 2

    eng = _sharded(
        model, (2, 2), buckets=(1, 4),
        memory_guard=True, memory_limit_bytes=limit,
    )
    try:
        assert eng.buckets == (1,)  # degraded, not crashed
        eng.assert_warm()
        refused = eng.stats()["memory"]["refused_buckets"]
        assert set(refused) == {"4"}
        assert refused["4"]["reason"] == "predicted_peak_exceeds_limit"
        assert refused["4"]["peak_bytes"] == peaks[4]
        assert refused["4"]["limit_bytes"] == limit
        # The fitting bucket still serves.
        x = _examples(1)[0]
        np.testing.assert_allclose(
            eng.predict_one(x), _golden(model, [x])[0], atol=1e-5
        )
    finally:
        eng.stop()


# -- fleet: a replica claims a device subset (ISSUE tentpole, fleet side) -----


def test_worker_mesh_flag_rides_healthz_payload(tmp_path):
    """A fleet replica spawned with ``--mesh 1x2`` claims a 1×2 device
    subset, serves the sharded forward over it, and advertises the mesh
    shape in its ``/healthz`` payload — the router-visible half of
    "shard for model size, replicate for traffic"."""
    import json
    import os
    import urllib.request

    from mpi4dl_tpu.fleet.replica import ReplicaClient, ReplicaProcess, worker_cmd

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ,
        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
    )
    proc = ReplicaProcess(
        "r0",
        worker_cmd(["--image-size", "16", "--max-batch", "2",
                    "--mesh", "1x2", "--spatial-cells", "2"]),
        base_dir=str(tmp_path / "fleet"),
        env=env,
        log_path=str(tmp_path / "r0.log"),
    )
    try:
        proc.spawn()
        ports = proc.wait_ready(timeout_s=420.0)
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{ports['metrics_port']}/healthz", timeout=10
        ).read().decode())
        assert snap["mesh"] == [1, 2]
        assert snap["healthy"] is True
        # The sharded replica serves over the worker RPC unchanged.
        client = ReplicaClient(
            "r0", f"http://127.0.0.1:{ports['predict_port']}"
        )
        logits, payload = client.predict(
            np.zeros((16, 16, 3), np.float32), trace_id="mesh-smoke-1",
            deadline_s=60.0, timeout_s=120.0,
        )
        assert np.asarray(logits).shape == (10,)
    finally:
        proc.terminate()


# -- end-to-end acceptance ----------------------------------------------------


def test_sharded_engine_serves_closed_loop_with_zero_misses(model):
    """ISSUE acceptance (CPU-mesh half): the 2×2-sharded engine AOT-warms
    its buckets, serves a closed-loop load through the UNCHANGED
    batcher/scheduler stack with zero deadline misses and zero errors,
    and every served row matches the single-chip forward."""
    from mpi4dl_tpu.serve.loadgen import run_closed_loop

    eng = _sharded(model, (2, 2), buckets=(1, 2, 4), max_queue=128)
    try:
        eng.assert_warm()
        eng.start()
        rep = run_closed_loop(eng, 24, concurrency=6, deadline_s=60.0)
        assert rep["served"] == 24
        assert rep["deadline_misses"] == 0
        assert rep["errors"] == 0
        s = eng.stats()
        assert s["mesh"] == [2, 2]
        assert s["served"] == 24 and s["batches"] >= 1
        # Result correctness through the live queue path.
        xs = _examples(3, seed=5)
        futs = [eng.submit(x) for x in xs]
        outs = [f.result(timeout=120) for f in futs]
        golden = _golden(model, xs)
        for got, want in zip(outs, golden):
            np.testing.assert_allclose(got, want, atol=1e-5)
    finally:
        eng.stop()


# -- checkpoint -> sharded serve (ISSUE 14 satellite) --------------------------


def test_checkpoint_round_trips_to_sharded_engine(model, tmp_path):
    """ROADMAP PR-13 follow-on (b): a checkpoint whose metadata records
    the spatial twin's builder args (``model_metadata(...,
    spatial_cells=N)``) round-trips to a spatially-sharded engine from
    the path + mesh alone — and the restored sharded rows match the same
    checkpoint's single-chip predictions at the documented f32
    reduction-order tolerance (tile-local convs are a different program).
    Without the stored arg (and no override) the sharded path still
    refuses loudly; the plain rebuild keeps ignoring the arg so the
    single-chip restore stays collective-free."""
    from mpi4dl_tpu.checkpoint import (
        model_metadata,
        rebuild_cells,
        save_checkpoint,
    )
    from mpi4dl_tpu.serve.sharded import sharded_engine_from_checkpoint
    from mpi4dl_tpu.train import TrainState, make_optimizer

    _, plain, params, stats = model
    state = TrainState(
        params=params, opt_state=make_optimizer().init(params),
        step=jnp.zeros((), jnp.int32),
    )
    meta = model_metadata(
        "resnet_v1", image_size=SIZE, depth=DEPTH, num_classes=10,
        pool_kernel=SIZE // 4, spatial_cells=N_SP,
    )
    save_checkpoint(str(tmp_path), state, metadata=meta, batch_stats=stats)

    # The plain rebuild ignores spatial_cells: no halo cells, and the
    # single-chip engine from the same path lints at zero collectives.
    plain_again = rebuild_cells(meta)
    assert not any(
        getattr(c, "spatial", False) for c in plain_again
    )
    single = ServingEngine.from_checkpoint(
        str(tmp_path), buckets=(2,), watchdog_factor=None,
        memory_monitor=False,
    )
    xs = _examples(2, seed=7)
    batch = np.stack(xs)
    try:
        assert single.lint_report().ok
        ref = np.asarray(single._predictor.run(single._compiled[2], batch))
    finally:
        single.stop()

    eng = sharded_engine_from_checkpoint(
        str(tmp_path), (2, 2), buckets=(2,), watchdog_factor=None,
        memory_monitor=False,
    )
    try:
        assert eng.mesh_shape == (2, 2)
        got = np.asarray(eng._predictor.run(eng._compiled[2], batch))
        np.testing.assert_allclose(got, ref, atol=1e-5)
        assert eng.lint_report().ok  # mesh-derived halo window
    finally:
        eng.stop()

    # No stored spatial_cells and no override: loud refusal...
    bare = model_metadata(
        "resnet_v1", image_size=SIZE, depth=DEPTH, num_classes=10,
        pool_kernel=SIZE // 4,
    )
    bare_dir = tmp_path / "bare"
    save_checkpoint(str(bare_dir), state, metadata=bare, batch_stats=stats)
    with pytest.raises(ValueError, match="spatial_cells"):
        sharded_engine_from_checkpoint(str(bare_dir), (2, 2))
    # ...while an explicit --spatial-cells-style override still works.
    eng2 = sharded_engine_from_checkpoint(
        str(bare_dir), (2, 2), spatial_cells=N_SP, buckets=(2,),
        watchdog_factor=None, memory_monitor=False,
    )
    try:
        assert eng2.mesh_shape == (2, 2)
    finally:
        eng2.stop()


def test_serve_cli_ckpt_with_mesh(model, tmp_path, capsys):
    """ISSUE 14 satellite (CLI surface): ``python -m mpi4dl_tpu.serve
    --ckpt ... --mesh 2x2`` — previously a loud refusal — restores the
    spatial twin from the checkpoint metadata, warms, serves, and passes
    the mesh-derived lint gate."""
    from mpi4dl_tpu.checkpoint import model_metadata, save_checkpoint
    from mpi4dl_tpu.serve.__main__ import main
    from mpi4dl_tpu.train import TrainState, make_optimizer

    _, plain, params, stats = model
    state = TrainState(
        params=params, opt_state=make_optimizer().init(params),
        step=jnp.zeros((), jnp.int32),
    )
    meta = model_metadata(
        "resnet_v1", image_size=SIZE, depth=DEPTH, num_classes=10,
        pool_kernel=SIZE // 4, spatial_cells=N_SP,
    )
    save_checkpoint(str(tmp_path), state, metadata=meta, batch_stats=stats)
    out_path = tmp_path / "serve_ckpt_mesh.json"
    rc = main([
        "--ckpt", str(tmp_path), "--mesh", "2x2", "--max-batch", "2",
        "--requests", "6", "--concurrency", "3", "--serial", "0",
        "--lint", "--json", str(out_path),
    ])
    assert rc == 0
    import json as _json

    rep = _json.load(open(out_path))
    assert rep["mesh"] == [2, 2]
    assert rep["loadgen"]["served"] == 6
    assert rep["lint"]["ok"]
