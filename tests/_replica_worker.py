"""Two-replica federation drill worker (tests/test_federation.py).

One tiny ServingEngine in its own process: binds an ephemeral metrics
port (printed as ``PORT <n>`` on stdout), then serves one request per
trace id handed on stdin — the ids are minted by the PARENT process, so
the engine's span segments join the parent's client segments under the
same trace ids across the process hop. Exits 0 after stdin closes with
``SERVED <n>`` on stdout.
"""

import os
import sys


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    telemetry_dir = sys.argv[1]

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve import ServingEngine
    from mpi4dl_tpu.utils import get_depth

    size = 16
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=size // 4
    )
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )
    engine = ServingEngine(
        cells, params, stats, example_shape=(size, size, 3), max_batch=2,
        default_deadline_s=30.0, metrics_port=0, telemetry_dir=telemetry_dir,
    )
    print(f"PORT {engine.metrics_port}", flush=True)
    engine.start()
    example = rng.standard_normal((size, size, 3)).astype(np.float32)
    futures = []
    for line in sys.stdin:
        trace_id = line.strip()
        if not trace_id or trace_id == "DONE":
            break
        futures.append(engine.submit(example, trace_id=trace_id))
    for f in futures:
        f.result(timeout=60)
    engine.stop()
    print(f"SERVED {len(futures)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
