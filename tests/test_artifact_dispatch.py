"""ISSUE 16 satellite: pure-JSON dispatch of every artifact-mode analyze
subcommand, pinned with a POISONED jax.

The contract (docs/ANALYSIS.md): ``bench-history``, ``tail``,
``trace-export``, ``memory-plan --ledger``, and ``costmodel --artifact``
run on logs from a dead machine — no devices, no backend init, no jax
*use*. The pin: each subcommand runs as a subprocess with a fake ``jax``
package shadowing the real one on PYTHONPATH that raises on ANY
attribute access or class instantiation (module import itself is
tolerated — the package ``__init__`` imports jax at module level, and
Python resolves that before the CLI ever dispatches). If a future edit
makes an artifact path call ``jax.devices()``, build a Mesh, or touch
``jnp`` at import time, these tests fail with the poison message."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_POISON_INIT = '''\
"""Poisoned jax stand-in: importable, unusable."""
_MSG = "poisoned jax touched: artifact-mode path must stay pure JSON"


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    raise RuntimeError(f"{_MSG} (jax.{name})")
'''

_POISON_SHARDING = '''\
_MSG = "poisoned jax touched: artifact-mode path must stay pure JSON"


class _PoisonType:
    def __init__(self, *a, **k):
        raise RuntimeError(_MSG + f" ({type(self).__name__}())")


class Mesh(_PoisonType):
    pass


class NamedSharding(_PoisonType):
    pass


class PartitionSpec(_PoisonType):
    pass


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    raise RuntimeError(f"{_MSG} (jax.sharding.{name})")
'''

_POISON_NUMPY = '''\
_MSG = "poisoned jax touched: artifact-mode path must stay pure JSON"


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    raise RuntimeError(f"{_MSG} (jax.numpy.{name})")
'''


@pytest.fixture(scope="module")
def poison(tmp_path_factory):
    """A fake jax package dir + the env that puts it FIRST on sys.path
    of any subprocess (and drops JAX_PLATFORMS — backend selection must
    never matter on these paths)."""
    root = tmp_path_factory.mktemp("poisoned")
    pkg = root / "jax"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(_POISON_INIT)
    (pkg / "sharding.py").write_text(_POISON_SHARDING)
    (pkg / "numpy.py").write_text(_POISON_NUMPY)
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = os.pathsep.join([str(root), REPO])
    return env


def _run(args, env):
    return subprocess.run(
        [sys.executable, "-m", "mpi4dl_tpu.analyze", *args],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120,
    )


def test_poison_actually_poisons(poison):
    """Guard on the guard: the fake jax shadows the real one and raises
    on use — otherwise every pin below would vacuously pass."""
    r = subprocess.run(
        [sys.executable, "-c", "import jax; jax.devices()"],
        capture_output=True, text=True, env=poison, cwd=REPO, timeout=60,
    )
    assert r.returncode != 0
    assert "poisoned jax touched" in r.stderr


def test_bench_history_dispatches_pure_json(poison, tmp_path):
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps({
        "n": 1, "rc": 0,
        "parsed": {"metric": "m", "value": 5.0, "extras": {}},
    }))
    r = _run(["bench-history", str(p)], poison)
    assert r.returncode == 0, r.stderr
    assert "0 regression(s)" in r.stdout
    assert "poisoned" not in r.stderr


def _span_log(tmp_path):
    """Handcrafted span-event JSONL (the telemetry wire shape) — built
    without importing mpi4dl_tpu here, so this module itself stays
    independent of the package's import-time jax pull."""
    log = tmp_path / "telemetry.jsonl"
    log.write_text(json.dumps({
        "ts": 100.0, "kind": "span", "name": "serve.request",
        "trace_id": "t-1",
        "spans": [{"phase": "device_compute", "start_s": 1.0,
                   "end_s": 1.5, "duration_s": 0.5}],
        "attrs": {"pid": 7, "outcome": "served", "e2e_latency_s": 0.5},
    }) + "\n")
    return log


def test_tail_dispatches_pure_json(poison, tmp_path):
    log = _span_log(tmp_path)
    r = _run(["tail", str(log), "--top", "1"], poison)
    assert r.returncode == 0, r.stderr
    assert "t-1" in r.stdout
    assert "poisoned" not in r.stderr


def test_trace_export_dispatches_pure_json(poison, tmp_path):
    log = _span_log(tmp_path)
    out = tmp_path / "chrome.json"
    r = _run(
        ["trace-export", str(log), "--trace-id", "t-1", "-o", str(out)],
        poison,
    )
    assert r.returncode == 0, r.stderr
    doc = json.loads(out.read_text())
    assert any(e.get("ph") == "X" for e in doc["traceEvents"])
    assert "poisoned" not in r.stderr


def test_memory_plan_ledger_dispatches_pure_json(poison, tmp_path):
    ledger = tmp_path / "ledger.json"
    ledger.write_text(json.dumps({"entries": [
        {"program": "serve_predict", "bucket": 8, "peak_bytes": 2**30},
    ]}))
    r = _run(
        ["memory-plan", "--ledger", str(ledger),
         "--limit-bytes", str(2**31)],
        poison,
    )
    assert r.returncode == 0, r.stderr
    assert "fits" in r.stdout
    assert "poisoned" not in r.stderr


def test_costmodel_artifact_dispatches_pure_json(poison, tmp_path):
    """ISSUE 16 tentpole surface: ``costmodel --artifact`` prices a
    committed lint-report JSON under the ICI table with jax poisoned —
    the campaign's prediction artifacts regenerate on any machine."""
    rep = tmp_path / "report.json"
    rep.write_text(json.dumps({
        "module_name": "m",
        "config": {"program": "sp2x2_train", "n_devices": 8},
        "collectives": [
            {"opcode": "collective-permute", "bytes_moved": 1048576,
             "is_async": False, "compute_between": 0},
            {"opcode": "all-gather", "bytes_moved": 2097152,
             "is_async": True, "compute_between": 3},
        ],
    }))
    out = tmp_path / "pred.json"
    r = _run(
        ["costmodel", "--artifact", str(rep), "--interconnect", "ici",
         "--json", str(out)],
        poison,
    )
    assert r.returncode == 0, r.stderr
    assert "costmodel[sp2x2_train] ici" in r.stdout
    payload = json.loads(out.read_text())
    assert payload["interconnect"] == "ici"
    (pred,) = payload["predictions"]
    assert pred["program"] == "sp2x2_train"
    assert pred["n_collectives"] == 2 and pred["n_async"] == 1
    assert pred["comms_s"] > 0 and pred["overlap_claim"] is True
    assert "poisoned" not in r.stderr


def test_numerics_artifact_dispatches_pure_json(poison, tmp_path):
    """ISSUE 19 satellite: ``analyze numerics --artifact`` re-gates a
    committed cross-predictor audit report and summarizes canary.failure
    events from JSONL logs with jax poisoned — the numerics paper trail
    stays auditable off a dead machine."""
    rep = tmp_path / "numerics.json"
    rep.write_text(json.dumps({"pairs": [
        {"a": "single_chip", "b": "sharded", "max_abs": 2.5e-6,
         "max_ulp": 12, "atol": 1e-5, "ok": True},
        # atol omitted: the re-gate recomputes the composed bound from
        # the pair names (sharded|tiled = 1e-5 + 5e-6).
        {"a": "sharded", "b": "tiled", "max_abs": 1.2e-5, "max_ulp": 40},
    ]}))
    log = tmp_path / "telemetry.jsonl"
    log.write_text(json.dumps({
        "ts": 100.0, "kind": "event", "name": "canary.failure",
        "attrs": {"check": "params_checksum", "expected": "pcaa",
                  "got": "pcbb"},
    }) + "\n")
    out = tmp_path / "regated.json"
    r = _run(
        ["numerics", "--artifact", str(rep), "--artifact", str(log),
         "--json", str(out)],
        poison,
    )
    assert r.returncode == 0, r.stderr
    assert "poisoned" not in r.stderr
    assert "2 pair(s)" in r.stdout
    assert "canary.failure events: 1 (params_checksum=1)" in r.stdout
    doc = json.loads(out.read_text())
    assert doc["ok"] is True
    assert doc["inputs"] == {"reports": 1, "logs": 1}
    assert doc["pairs"][1]["atol"] == pytest.approx(1.5e-5)
    assert len(doc["failures"]) == 1

    # A doctored report cannot vouch for itself: the recorded bound is
    # re-applied to the recorded max_abs, and a breach exits 1.
    bad = tmp_path / "breach.json"
    bad.write_text(json.dumps({"pairs": [
        {"a": "single_chip", "b": "tiled", "max_abs": 1e-3, "ok": True},
    ]}))
    r = _run(["numerics", "--artifact", str(bad)], poison)
    assert r.returncode == 1
    assert "BREACH" in r.stdout
    assert "poisoned" not in r.stderr

    # Empty artifacts are a usage error, not a vacuous pass.
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"pairs": []}))
    r = _run(["numerics", "--artifact", str(empty)], poison)
    assert r.returncode == 1
    assert "no audit pairs" in r.stderr
    assert "poisoned" not in r.stderr


def test_incident_dispatches_pure_json(poison, tmp_path):
    """ISSUE 20 satellite: ``analyze incident`` reconstructs incident
    timelines + auto-postmortems from JSONL logs with jax poisoned —
    the on-call hand-off doc renders off a dead machine."""
    log = tmp_path / "telemetry.jsonl"
    lines = [
        {"ts": 99.0, "kind": "event", "name": "chaos.injected",
         "attrs": {"op": "kill:r1@+1s", "action": "kill", "pid": 42}},
        {"ts": 99.5, "kind": "event", "name": "alert.transition",
         "attrs": {"alert": "replica_unreachable", "severity": "page",
                   "from": "resolved", "to": "firing", "replica": "r1"}},
        {"ts": 100.0, "kind": "event", "name": "incident.open",
         "attrs": {"id": "inc-1", "opened_ts": 100.0,
                   "alert": "replica_unreachable", "severity": "page",
                   "mtta_s": 0.5, "lookback_s": 30.0,
                   "members": [{"name": "replica_unreachable",
                                "severity": "page",
                                "first_firing_ts": 99.5}]}},
        {"ts": 103.0, "kind": "event", "name": "incident.close",
         "attrs": {"id": "inc-1", "closed_ts": 103.0, "mttr_s": 3.0,
                   "members": [{"name": "replica_unreachable",
                                "severity": "page",
                                "resolved_ts": 103.0}]}},
    ]
    log.write_text("".join(json.dumps(e) + "\n" for e in lines))

    r = _run(["incident", str(log)], poison)
    assert r.returncode == 0, r.stderr
    assert "poisoned" not in r.stderr
    assert "inc-1" in r.stdout and "closed" in r.stdout
    assert "injected chaos op kill:r1@+1s" in r.stdout
    assert "1 incident(s)" in r.stderr

    r = _run(["incident", str(log), "--json"], poison)
    assert r.returncode == 0, r.stderr
    (pm,) = json.loads(r.stdout)
    assert pm["incident"]["id"] == "inc-1"
    assert pm["incident"]["mttr_s"] == 3.0
    assert pm["first_cause"]["event"] == "chaos.injected"
    assert [e["name"] for e in pm["timeline"]] == [
        "chaos.injected", "alert.transition",
    ]

    r = _run(["incident", str(log), "--md"], poison)
    assert r.returncode == 0, r.stderr
    assert "# Incident inc-1 — closed" in r.stdout
    assert "## Timeline" in r.stdout
    assert "poisoned" not in r.stderr

    # An unknown incident id is a usage error, not a vacuous pass.
    r = _run(["incident", str(log), "--incident-id", "inc-nope"], poison)
    assert r.returncode == 1
    assert "no incident" in r.stderr
    assert "poisoned" not in r.stderr


def test_coldstart_dispatches_pure_json(poison, tmp_path):
    """ISSUE 18 satellite: ``analyze coldstart --artifact`` joins ledger
    dumps, elastic.restart JSONL events, and a fleet state report into
    the executable manifest with jax poisoned — cold-start forensics
    run on artifacts from a dead machine."""
    ledger = tmp_path / "ledger.json"
    ledger.write_text(json.dumps({"entries": [
        {"program": "serve_predict", "bucket": 4, "peak_bytes": 2**20,
         "fingerprint": "xfaaaaaaaaaaaaaaaa",
         "trace_s": 0.2, "compile_s": 1.5, "warm_s": 0.01},
        {"program": "serve_predict", "bucket": 1, "peak_bytes": 2**18,
         "fingerprint": "xfbbbbbbbbbbbbbbbb",
         "trace_s": 0.1, "compile_s": 0.5, "warm_s": 0.01},
    ]}))
    log = tmp_path / "telemetry.jsonl"
    log.write_text(json.dumps({
        "ts": 100.0, "kind": "event", "name": "elastic.restart",
        "attrs": {"reason": "heartbeat_stale", "replica": 0},
    }) + "\n")
    fleet = tmp_path / "fleet.json"
    fleet.write_text(json.dumps({
        "last_recovery_s": 6.0,
        "last_recovery_phases": {
            "spawn": 0.5, "import": 1.5, "construct": 1.0,
            "compile": 2.5, "warm": 0.3, "ready": 0.2,
        },
    }))
    out = tmp_path / "manifest.json"
    r = _run(
        ["coldstart", str(ledger), str(log), str(fleet),
         "--artifact", str(out), "--top", "3"],
        poison,
    )
    assert r.returncode == 0, r.stderr
    assert "poisoned" not in r.stderr
    doc = json.loads(out.read_text())
    first = doc["executables"][0]
    assert first["executable"] == "serve_predict[4]"
    assert first["fingerprint"] == "xfaaaaaaaaaaaaaaaa"
    assert doc["totals"]["compile_s"] == 2.0
    assert doc["restarts"]["by_reason"] == {"heartbeat_stale": 1}
    assert doc["recovery"]["phase_sum_s"] == 6.0
    # The CI gate is part of the dispatch surface: over-budget exits 1,
    # still without touching jax.
    r = _run(["coldstart", str(ledger), "--budget-s", "1.0"], poison)
    assert r.returncode == 1
    assert "OVER BUDGET" in r.stderr
    assert "poisoned" not in r.stderr
