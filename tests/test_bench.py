"""bench.py contract tests: the driver parses its stdout, so the output
protocol (one complete JSON line per milestone, headline first, explicit
error shape, nonzero exit on no-measurement) is product surface. Runs the
real script as a subprocess on CPU with tiny shapes."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


@pytest.fixture(scope="session")
def cache_dir(tmp_path_factory):
    """One compilation cache for all bench subprocesses: the three
    measurement tests compile overlapping programs (the amoebanet 64px
    headline twice), and the cache is keyed by program, so sharing it
    saves minutes with no isolation cost."""
    return str(tmp_path_factory.mktemp("jaxcache"))


def _run(cache_dir, extra_env, timeout=900):
    # Strip inherited BENCH_* knobs: a developer's exported BENCH_IMAGE_SIZE
    # would disable bench.py's CPU shrink path and train at full resolution
    # on CPU (a guaranteed timeout), or silently change what's under test.
    base = {k: v for k, v in os.environ.items() if not k.startswith("BENCH_")}
    env = dict(
        base,
        PYTHONPATH=REPO + os.pathsep + base.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        MPI4DL_TPU_CONV_IMPL="xla",
        JAX_COMPILATION_CACHE_DIR=cache_dir,
        **extra_env,
    )
    return subprocess.run(
        [sys.executable, BENCH],
        env=env, capture_output=True, text=True, timeout=timeout,
    )


def _json_lines(out):
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    return [json.loads(l) for l in lines]


@pytest.mark.slow
def test_amoebanet_headline_line_shape(cache_dir):
    out = _run(cache_dir, {"BENCH_MODEL": "amoebanet"})
    assert out.returncode == 0, out.stderr[-2000:]
    records = _json_lines(out)
    assert records, "no JSON line emitted"
    # Every line is a complete record; the driver may keep first OR last.
    for r in records:
        assert r["unit"] == "images/sec"
        assert r["metric"].startswith("amoebanetd_")
        assert isinstance(r["value"], (int, float)) and r["value"] > 0
        assert "vs_baseline" in r
    # Result lines carry a registry snapshot in the JSONL metrics-event
    # schema (docs/OBSERVABILITY.md) — validated with the same validator
    # the event log enforces, and the train-side series must be populated.
    from mpi4dl_tpu import telemetry

    tele = telemetry.validate_event(records[-1]["telemetry"])
    assert tele["metrics"]["train_steps_total"]["series"][0]["value"] > 0


@pytest.mark.slow
def test_resnet_headline(cache_dir):
    out = _run(cache_dir, {"BENCH_MODEL": "resnet"})
    assert out.returncode == 0, out.stderr[-2000:]
    records = _json_lines(out)
    assert records[0]["metric"].startswith("resnet110_")
    assert records[0]["value"] > 0
    assert records[0]["vs_baseline"] is not None


@pytest.mark.slow
def test_budget_exhaustion_skips_extras_but_keeps_headline(cache_dir):
    # BENCH_MODEL=all on CPU: a 1-second budget cannot erase the headline
    # (the budget gates extras only), and EVERY extra — the resnet point
    # plus the serving/fleet/overlap/pipeline suite — must be skipped
    # with an explicit marker, never silently absent or half-run.
    # (Was `(extra,) = ...` from when the CPU path had one extra; every
    # extra added since landed its own skip entry here.)
    out = _run(cache_dir, {"BENCH_MODEL": "all", "BENCH_TIME_BUDGET": "1"})
    assert out.returncode == 0, out.stderr[-2000:]
    final = _json_lines(out)[-1]
    assert final["metric"].startswith("amoebanetd_")
    assert final["value"] > 0
    assert final["extras"], "no extras recorded at all"
    for tag, extra in final["extras"].items():
        assert "insufficient budget" in extra.get("skipped", ""), (tag, extra)
    assert "pipeline" in final["extras"]  # the PR-14 extra is wired in


def test_bad_budget_fails_before_compile(cache_dir):
    out = _run(cache_dir, {"BENCH_TIME_BUDGET": "not-a-number"}, timeout=120)
    assert out.returncode != 0
    # The failure must still leave one parseable line on stdout.
    records = _json_lines(out)
    assert records and records[-1].get("error")


def _load_bench():
    """Import bench.py in-process (it is a script, not a package module)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench", BENCH)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_sentinel_skip_reason():
    """Known-fatal sentinel policy (VERDICT r3 weak #6 + ADVICE r3 medium):
    confirmed failures skip only at the same code revision; provisional
    (never-concluded) markers auto-retry when the budget allows; legacy
    string entries and force-retry always rerun."""
    bench = _load_bench()
    skip = bench.sentinel_skip_reason

    confirmed = {"status": "confirmed", "rev": "aaaa", "msg": "HTTP 500"}
    provisional = {"status": "provisional", "rev": "aaaa", "msg": "killed"}

    # Confirmed at the SAME revision skips; at a different revision reruns.
    assert skip(confirmed, "aaaa", 1e9, False) is not None
    assert "HTTP 500" in skip(confirmed, "aaaa", 1e9, False)
    assert skip(confirmed, "bbbb", 1e9, False) is None
    # Unknown current revision fails open (rerun), even if stored matches.
    assert skip({**confirmed, "rev": "unknown"}, "unknown", 1e9, False) is None
    # Provisional: rerun with a fat budget, skip with a thin one.
    assert skip(provisional, "aaaa", 1200.0, False) is None
    assert skip(provisional, "aaaa", 120.0, False) is not None
    # A second never-concluded attempt at the same revision is fatal —
    # retry "once", not on every sufficiently-budgeted run.
    twice = {**provisional, "tries": 2}
    assert skip(twice, "aaaa", 1e9, False) is not None
    assert skip(twice, "bbbb", 1e9, False) is None  # new rev resets
    assert skip(twice, "aaaa", 1e9, True) is None  # force overrides
    # Legacy pre-r4 string entries always rerun.
    assert skip("JaxRuntimeError: ...", "aaaa", 120.0, False) is None
    # BENCH_RETRY_FATAL overrides everything.
    assert skip(confirmed, "aaaa", 1e9, True) is None


def test_bad_model_rejected(cache_dir):
    out = _run(cache_dir, {"BENCH_MODEL": "vgg"}, timeout=120)
    assert out.returncode != 0
    records = _json_lines(out)
    assert records and "BENCH_MODEL" in records[-1]["error"]


def test_transient_failure_classifier():
    """Transport flakes from the tunneled compile helper must never be
    recorded as confirmed-fatal (round-4 incident: a 'response body
    closed' flake confirmed-fataled the 3072px walk that had measured
    0.165 img/s the same day); genuine compile failures must be."""
    bench = _load_bench()
    t = bench._is_transient_failure

    assert t(
        "JaxRuntimeError: INTERNAL: http://127.0.0.1:8083/remote_compile: "
        "read body: response body closed before all bytes were read"
    )
    assert t("ConnectionResetError: Connection reset by peer")
    # deliberately NOT transient: deadline-style timeouts can be
    # deterministic for too-large programs
    assert not t("TimeoutError: request timed out")
    # Genuine compile verdicts stay confirmed-fatal.
    assert not t(
        "JaxRuntimeError: INTERNAL: http://127.0.0.1:8083/remote_compile: "
        "HTTP 500: tpu_compile_helper subprocess exit code 1"
    )
    assert not t("RESOURCE_EXHAUSTED: Out of memory in memory space hbm")


def test_transient_signature_past_truncation_still_classified():
    """The classifier must see the UNTRUNCATED exception text: wrapped
    transport flakes can carry their signature past the 120-char display
    prefix (review finding, round 4)."""
    bench = _load_bench()
    long_prefix = (
        "INTERNAL: Failed to execute remote compilation request against "
        "http://127.0.0.1:8083/remote_compile after 3 attempts; most "
        "recent error follows on the next line: "
    )
    assert len(long_prefix) > 120
    assert bench._is_transient_failure(
        long_prefix + "read body: response body closed before all bytes"
    )


def test_transient_signature_in_cause_chain_still_classified():
    """A transport flake wrapped in an exception whose OWN message lacks
    the signature must classify via __cause__/__context__ (ADVICE r4)."""
    bench = _load_bench()
    try:
        try:
            raise OSError("Connection reset by peer")
        except OSError as inner:
            raise RuntimeError("remote compile failed") from inner
    except RuntimeError as e:
        wrapped = e
    assert "Connection reset" not in str(wrapped)
    assert bench._is_transient_failure(wrapped)
    # Implicit chaining (__context__) counts too.
    try:
        try:
            raise OSError("Broken pipe")
        except OSError:
            raise ValueError("helper died")
    except ValueError as e:
        ctx = e
    assert bench._is_transient_failure(ctx)
    # A plain string still works, and a clean exception stays fatal.
    assert not bench._is_transient_failure(RuntimeError("Mosaic rejected op"))
