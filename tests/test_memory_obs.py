"""Memory observability (`telemetry/memory.py` + `analysis/memory_plan.py`,
docs/OBSERVABILITY.md "Memory"): OOM-forensics goldens on canned real XLA
messages (the docs/PERF.md round-4 shapes), CPU-backend degradation of the
live monitor (absent-not-wrong), the footprint ledger, the feasibility
planner's exactness against the engine's actually-compiled executables,
the opt-in admission guard, the injected-OOM drill (schema-valid
oom.report in both the JSONL log and the flight dump, naming the
offending program's largest buffer), and the memory_headroom_low alert.
"""

import glob
import json
import os

import numpy as np
import pytest

from mpi4dl_tpu import telemetry
from mpi4dl_tpu.telemetry import memory as memobs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Canned real-shape XLA messages. The HBM table is the docs/PERF.md
# round-4 incident: the compile helper dying at buffer assignment with
# the full breakdown — including the 16x-padded wgrad copy of
# f32[1,3072,3072,16] that PERF.md's whack-a-mole ledger names.
HBM_OOM = """\
RESOURCE_EXHAUSTED: XLA:TPU compile permanent error. Ran out of memory in \
memory space hbm. Used 18.95G of 15.48G hbm. Exceeded hbm capacity by 3.46G.

Total hbm usage >= 19.46G:
    reserved        530.00M
    program          18.95G
    arguments       unknown size

Output size unknown.

Program hbm requirement 18.95G:
    global            276.0K
    scoped            253.0K
    HLO temp         18.94G (33.0% utilization: Unpadded (6.26G) \
Padded (18.94G), 0.0% fragmentation (1.60M))

  Largest program allocations in hbm:

  1. Size: 4.50G
     Operator: op_name="jit(train_step)/jit(main)/transpose[permutation=(3, 1, 2, 0)]"
     Shape: f32[1,3072,3072,16]{2,1,3,0:T(8,128)}
     Unpadded size: 288.00M
     Extra memory due to padding: 4.22G (16.0x expansion)
     XLA label: %copy.1234 = f32[1,3072,3072,16]{2,1,3,0:T(8,128)} copy(%transpose.56)
     Allocation type: HLO temp
     ==========================

  2. Size: 1.12G
     Operator: op_name="jit(train_step)/while/body/dynamic-update-slice"
     Shape: f32[11,1,768,768,64]{4,3,2,1,0:T(8,128)}
     Unpadded size: 1.12G
     XLA label: %fusion.789 = f32[11,1,768,768,64]{4,3,2,1,0:T(8,128)} fusion(...)
     Allocation type: HLO temp
     ==========================
"""

ALLOCATOR_OOM = (
    "RESOURCE_EXHAUSTED: Out of memory allocating 25769803776 bytes."
)

# The exact shape BENCH_r05.json recorded raw — the string this PR's
# forensics exists to stop losing information on.
BARE_OOM = "ValueError: RESOURCE_EXHAUSTED: TPU backend error (ResourceExhausted)."


# -- size + message parsing (goldens) -----------------------------------------


def test_parse_size_units():
    assert memobs.parse_size("18.95G") == int(18.95 * 2**30)
    assert memobs.parse_size("288.00M") == int(288.0 * 2**20)
    assert memobs.parse_size("276.0K") == int(276.0 * 2**10)
    assert memobs.parse_size("123456") == 123456
    assert memobs.parse_size("1.5GiB") == int(1.5 * 2**30)
    assert memobs.parse_size("530.00MB") == int(530.0 * 2**20)
    assert memobs.parse_size("nonsense") is None


def test_parse_hbm_table_golden():
    p = memobs.parse_resource_exhausted(HBM_OOM)
    assert p["kind"] == "hbm_oom"
    assert p["memory_space"] == "hbm"
    assert p["used_bytes"] == int(18.95 * 2**30)
    assert p["limit_bytes"] == int(15.48 * 2**30)
    assert p["exceeded_bytes"] == int(3.46 * 2**30)
    assert p["program_bytes"] == int(18.95 * 2**30)
    assert p["total_bytes"] == int(19.46 * 2**30)
    a1, a2 = p["largest_allocations"]
    assert a1["rank"] == 1
    assert a1["size_bytes"] == int(4.50 * 2**30)
    # The layout/tiling suffix is stripped; the logical shape survives.
    assert a1["shape"] == "f32[1,3072,3072,16]"
    assert a1["unpadded_bytes"] == int(288.0 * 2**20)
    assert a1["padding_expansion"] == 16.0
    assert a1["allocation_type"] == "HLO temp"
    assert "%copy.1234" in a1["xla_label"]
    assert a2["rank"] == 2
    assert a2["shape"] == "f32[11,1,768,768,64]"
    assert "padding_expansion" not in a2
    # The postmortem one-liner names the biggest buffer.
    lb = memobs.largest_buffer(p)
    assert "4.50G" in lb and "f32[1,3072,3072,16]" in lb
    assert "16x padding" in lb and "%copy.1234" in lb


def test_parse_allocator_and_bare_messages():
    p = memobs.parse_resource_exhausted(ALLOCATOR_OOM)
    assert p["kind"] == "allocator_oom"
    assert p["requested_bytes"] == 25769803776
    p = memobs.parse_resource_exhausted(BARE_OOM)
    assert p["kind"] == "unclassified"
    assert memobs.largest_buffer(p) is None
    assert memobs.parse_resource_exhausted("a perfectly fine message") is None


def test_is_oom_error_walks_exception_chain():
    try:
        try:
            raise RuntimeError(HBM_OOM)
        except RuntimeError as inner:
            raise ValueError("compile helper died") from inner
    except ValueError as e:
        wrapped = e
    assert memobs.is_oom_error(wrapped)
    # The chain text carries the table, so the parse works on it too.
    p = memobs.parse_resource_exhausted(memobs.exception_chain_text(wrapped))
    assert p["kind"] == "hbm_oom"
    assert not memobs.is_oom_error(ValueError("shape mismatch"))


def test_oom_report_event_is_schema_valid():
    ev = memobs.oom_report(HBM_OOM, program="serve_predict", bucket=32)
    telemetry.validate_event(ev)  # raises on drift
    assert ev["name"] == "oom.report"
    assert ev["attrs"]["program"] == "serve_predict"
    assert ev["attrs"]["bucket"] == 32
    assert ev["attrs"]["parsed"]["kind"] == "hbm_oom"
    assert "f32[1,3072,3072,16]" in ev["attrs"]["largest_buffer"]
    assert "Ran out of memory" in ev["attrs"]["raw"]


def test_emit_oom_report_fans_out(tmp_path):
    reg = telemetry.MetricsRegistry()
    events = telemetry.JsonlWriter(str(tmp_path))
    flight = telemetry.FlightRecorder(capacity=16, directory=str(tmp_path))
    memobs.emit_oom_report(
        HBM_OOM, program="train_step", registry=reg, events=events,
        flight=flight, dump=True,
    )
    events.close()
    assert reg.get("oom_reports_total").value(program="train_step") == 1
    logged = [
        e for e in telemetry.read_events(events.path)
        if e["name"] == "oom.report"
    ]
    assert len(logged) == 1
    (dump,) = glob.glob(str(tmp_path / "flight-*-oom.jsonl"))
    dumped = [
        e for e in telemetry.read_events(dump) if e.get("name") == "oom.report"
    ]
    assert dumped[0]["attrs"]["largest_buffer"] == logged[0]["attrs"]["largest_buffer"]


# -- live monitor: CPU degradation + stub-device publishing -------------------


class _StubDevice:
    platform = "stubtpu"

    def __init__(self, i, used, limit):
        self.id = i
        self._stats = {"bytes_in_use": used, "bytes_limit": limit,
                       "peak_bytes_in_use": used}

    def memory_stats(self):
        return self._stats


def test_monitor_cpu_backend_publishes_nothing():
    """ISSUE satellite: memory_stats() absent (the real CPU devices
    return None) → the gauge NAMES are declared (catalog pin) but no
    series exists, and nothing can trip on a fabricated zero."""
    import jax

    reg = telemetry.MetricsRegistry()
    mon = telemetry.MemoryMonitor(reg, devices=jax.devices())
    assert mon.sample_once() is None
    assert mon.supported is False
    for name in ("device_hbm_used_bytes", "device_hbm_limit_bytes",
                 "device_hbm_headroom_ratio"):
        assert name in reg.names()
        assert reg.get(name).snapshot_series() == []
    # The headroom alert cannot activate without data.
    from mpi4dl_tpu.telemetry.alerts import SLOEvaluator

    ev = SLOEvaluator(
        reg, [], telemetry.SLOConfig(headroom_alert_ratio=0.5),
    )
    ev.evaluate_once(now=1.0)
    ev.evaluate_once(now=2.0)
    assert ev.alerts["memory_headroom_low"].state == "inactive"


def test_monitor_publishes_per_device_gauges():
    reg = telemetry.MetricsRegistry()
    devs = [_StubDevice(0, used=12 << 30, limit=16 << 30),
            _StubDevice(1, used=4 << 30, limit=16 << 30)]
    mon = telemetry.MemoryMonitor(reg, devices=devs)
    out = mon.sample_once()
    assert mon.supported is True
    assert set(out) == {"stubtpu:0", "stubtpu:1"}
    assert reg.get("device_hbm_used_bytes").value(device="stubtpu:0") == 12 << 30
    assert reg.get("device_hbm_limit_bytes").value(device="stubtpu:1") == 16 << 30
    assert reg.get("device_hbm_headroom_ratio").value(
        device="stubtpu:0"
    ) == pytest.approx(0.25)
    assert reg.get("device_hbm_headroom_ratio").value(
        device="stubtpu:1"
    ) == pytest.approx(0.75)


def test_headroom_alert_fires_and_resolves(tmp_path):
    """memory_headroom_low rides the existing alert machinery: AlertState
    lifecycle, alert_active gauge, transition events into the flight
    ring — and the transition names the offending device."""
    from mpi4dl_tpu.telemetry.alerts import SLOEvaluator

    reg = telemetry.MetricsRegistry()
    devs = [_StubDevice(0, used=2 << 30, limit=16 << 30)]
    mon = telemetry.MemoryMonitor(reg, devices=devs)
    flight = telemetry.FlightRecorder(capacity=32, directory=str(tmp_path))
    ev = SLOEvaluator(
        reg, [], telemetry.SLOConfig(headroom_alert_ratio=0.1),
        flight=flight,
    )
    mon.sample_once()
    ev.evaluate_once(now=1.0)
    st = ev.alerts["memory_headroom_low"]
    assert st.state == "inactive"  # 87.5% headroom

    devs[0]._stats["bytes_in_use"] = 15 << 30  # 6.25% headroom < 10%
    mon.sample_once()
    ev.evaluate_once(now=2.0)
    assert st.state == "firing"
    assert reg.get("alert_active").value(
        alert="memory_headroom_low", severity="page"
    ) == 1.0
    trans = [
        t for t in ev.transitions
        if t["attrs"]["alert"] == "memory_headroom_low"
    ]
    assert trans[-1]["attrs"]["to"] == "firing"
    assert trans[-1]["attrs"]["device"] == "stubtpu:0"
    assert trans[-1]["attrs"]["headroom_min"] == pytest.approx(0.0625)
    telemetry.validate_event(trans[-1])
    assert any(
        t.get("name") == "alert.transition"
        and t["attrs"]["alert"] == "memory_headroom_low"
        for t in flight.tail(32)
    )
    # /alertz surface + verdict: a page that fired is a failed verdict.
    assert any(
        a["name"] == "memory_headroom_low" for a in ev.state()["alerts"]
    )
    assert ev.verdict()["ok"] is False

    devs[0]._stats["bytes_in_use"] = 2 << 30
    mon.sample_once()
    ev.evaluate_once(now=3.0)
    assert st.state == "inactive"
    assert reg.get("alert_active").value(
        alert="memory_headroom_low", severity="page"
    ) == 0.0


# -- footprint ledger ---------------------------------------------------------


def test_footprint_ledger_records_and_publishes(tmp_path):
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.analysis.memory import memory_summary

    reg = telemetry.MetricsRegistry()
    ledger = telemetry.FootprintLedger(registry=reg)
    # Declared up front, before any record (catalog-pin behavior).
    assert "serve_bucket_peak_hbm_bytes" in reg.names()
    assert "program_peak_hbm_bytes" in reg.names()

    fn = jax.jit(lambda x: (x @ x).sum())
    x = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    compiled = fn.lower(x).compile()
    want = memory_summary(compiled)["peak_bytes"]

    # record_lowered: compile-only (abstract input), no execution.
    entry = ledger.record_lowered("unit_prog", fn, x)
    assert entry["peak_bytes"] == want
    assert reg.get("program_peak_hbm_bytes").value(program="unit_prog") == want

    entry = ledger.record_compiled("serve_predict", compiled, bucket=4)
    assert reg.get("serve_bucket_peak_hbm_bytes").value(bucket=4) == want
    assert ledger.get("serve_predict", bucket=4)["peak_bytes"] == want

    # dump → the planner's --ledger artifact mode reads it, pure JSON.
    path = ledger.dump(str(tmp_path / "ledger.json"))
    from mpi4dl_tpu.analysis.cli import main

    rc = main([
        "memory-plan", "--ledger", path,
        "--limit-bytes", str(want + 1), "--json",
        str(tmp_path / "plan.json"),
    ])
    assert rc == 0
    plan = json.load(open(tmp_path / "plan.json"))
    assert all(e["fits"] for e in plan["entries"])
    assert {e["key"] for e in plan["entries"]} == {
        "unit_prog", "serve_predict[4]"
    }
    assert main([
        "memory-plan", "--ledger", path, "--limit-bytes", str(want - 1),
    ]) == 1


# -- the serving engine + planner on a real model -----------------------------


@pytest.fixture(scope="module")
def tiny_serving_model():
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells

    size = 16
    cells = get_resnet_v2(depth=11, num_classes=10, pool_kernel=size // 4)
    rng = np.random.default_rng(0)
    params = init_cells(
        cells, jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3))
    )
    stats = collect_batch_stats(
        cells, params,
        [jnp.asarray(rng.standard_normal((4, size, size, 3)), jnp.float32)],
    )
    return size, cells, params, stats


def _make_engine(tiny_serving_model, **kw):
    from mpi4dl_tpu.serve import ServingEngine

    size, cells, params, stats = tiny_serving_model
    kw.setdefault("example_shape", (size, size, 3))
    kw.setdefault("default_deadline_s", 30.0)
    return ServingEngine(cells, params, stats, **kw)


def test_planner_matches_engine_compiled_exactly(tiny_serving_model):
    """ISSUE acceptance: memory-plan's predicted peak equals
    memory_analysis() of the executable the engine actually compiles for
    the same config — exactly, not approximately. The planner lowered
    abstractly (no params materialized, nothing executed); the engine
    warmed real device arrays; same program, same buffer assignment."""
    from mpi4dl_tpu.analysis.memory import memory_summary
    from mpi4dl_tpu.analysis.memory_plan import predict_serve_peak

    size, cells, params, stats = tiny_serving_model
    engine = _make_engine(tiny_serving_model, buckets=(1, 4))
    try:
        for b in (1, 4):
            engine_summary = memory_summary(engine._compiled[b])
            planned = predict_serve_peak(cells, size, b)
            assert planned == engine_summary, f"bucket {b}"
            # And the ledger recorded the same number at warm-up.
            assert engine.memory_ledger.get("serve_predict", bucket=b)[
                "peak_bytes"
            ] == engine_summary["peak_bytes"]
    finally:
        engine.stop()


def test_engine_memory_surface_and_bucket_gauges(tiny_serving_model):
    engine = _make_engine(tiny_serving_model, buckets=(1, 4))
    try:
        mem = engine.stats()["memory"]
        assert set(mem["bucket_peak_hbm_bytes"]) == {"1", "4"}
        assert all(v > 0 for v in mem["bucket_peak_hbm_bytes"].values())
        assert mem["refused_buckets"] == {}
        # CPU: no device limit, monitor unsupported — absent, not zero.
        assert mem["limit_bytes"] is None
        for b in (1, 4):
            assert engine.registry.get("serve_bucket_peak_hbm_bytes").value(
                bucket=b
            ) == mem["bucket_peak_hbm_bytes"][str(b)]
    finally:
        engine.stop()


def test_admission_guard_refuses_unfit_bucket(tiny_serving_model):
    """ISSUE tentpole: with the guard on and a limit between the small
    and large buckets' predicted peaks, the large bucket is refused at
    warm-up and the engine serves with what fits — graceful degradation
    instead of a crash."""
    probe = _make_engine(tiny_serving_model, buckets=(1, 8))
    peaks = {
        e["bucket"]: e["peak_bytes"]
        for e in probe.memory_ledger.entries()
    }
    probe.stop()
    limit = (peaks[1] + peaks[8]) // 2

    engine = _make_engine(
        tiny_serving_model, buckets=(1, 8),
        memory_guard=True, memory_limit_bytes=limit,
    )
    try:
        assert engine.buckets == (1,)
        refused = engine.stats()["memory"]["refused_buckets"]["8"]
        assert refused["reason"] == "predicted_peak_exceeds_limit"
        assert refused["peak_bytes"] == peaks[8]
        assert refused["limit_bytes"] == limit
        # It still serves.
        engine.start()
        size = tiny_serving_model[0]
        out = engine.submit(np.zeros((size, size, 3), np.float32)).result(
            timeout=30
        )
        assert out.shape == (10,)
    finally:
        engine.stop()

    # Nothing fits → a loud construction-time error, not a wedged engine.
    with pytest.raises(RuntimeError, match="no serving bucket fits"):
        _make_engine(
            tiny_serving_model, buckets=(1, 8),
            memory_guard=True, memory_limit_bytes=1,
        )


def test_injected_oom_drill(tiny_serving_model, tmp_path):
    """ISSUE acceptance: an injected RESOURCE_EXHAUSTED on a live batch
    produces a schema-valid oom.report in BOTH the JSONL log and the
    flight dump, naming the program, bucket, and the offending program's
    largest buffer — and the batcher survives (only that batch's
    requests fail)."""
    import jax

    size = tiny_serving_model[0]
    engine = _make_engine(
        tiny_serving_model, buckets=(1,),
        telemetry_dir=str(tmp_path), flight_dir=str(tmp_path),
        watchdog_factor=None,
    )
    orig = dict(engine._compiled)
    calls = {"n": 0}

    def boom(p, s, batch):
        calls["n"] += 1
        if calls["n"] == 1:
            raise jax.errors.JaxRuntimeError(HBM_OOM)
        return orig[1](p, s, batch)

    engine._compiled[1] = boom
    engine.start()
    try:
        x = np.zeros((size, size, 3), np.float32)
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            engine.submit(x).result(timeout=30)
        # The loop survived: the next request is served normally.
        assert engine.submit(x).result(timeout=30).shape == (10,)
    finally:
        engine.stop()

    assert engine.registry.get("oom_reports_total").value(
        program="serve_predict"
    ) == 1
    logged = [
        e for e in telemetry.read_events(engine._events.path)
        if e.get("name") == "oom.report"
    ]
    assert len(logged) == 1
    attrs = logged[0]["attrs"]
    assert attrs["program"] == "serve_predict"
    assert attrs["bucket"] == 1
    assert attrs["parsed"]["kind"] == "hbm_oom"
    assert "f32[1,3072,3072,16]" in attrs["largest_buffer"]

    (dump,) = glob.glob(str(tmp_path / "flight-*-oom.jsonl"))
    dumped = [
        e for e in telemetry.read_events(dump)  # read_events validates
        if e.get("name") == "oom.report"
    ]
    assert dumped and dumped[0]["attrs"]["largest_buffer"] == attrs["largest_buffer"]
    assert engine.registry.get("flight_recorder_dumps_total").value(
        reason="oom"
    ) == 1


def test_planner_answers_without_device_limit(tiny_serving_model):
    """ISSUE satellite (CPU degradation): with no device limit (CPU
    reports none) the planner still answers from memory_analysis()
    alone — peak reported, verdict None, exit 0 — instead of inventing
    a limit or failing."""
    from mpi4dl_tpu.analysis.memory import feasibility
    from mpi4dl_tpu.analysis.memory_plan import predict_serve_peak

    size, cells, _, _ = tiny_serving_model
    summary = predict_serve_peak(cells, size, 2)
    assert summary["peak_bytes"] > 0
    v = feasibility(summary["peak_bytes"], memobs.device_memory_limit())
    assert v["fits"] is None and v["peak_bytes"] == summary["peak_bytes"]


def test_trainer_record_memory_footprint_and_oom_wiring(tmp_path, monkeypatch):
    """The trainer side: record_memory_footprint lands the compiled
    step's peak in the ledger/gauge, and an OOM raised by the step
    emits oom.report into the env-gated JSONL log before surfacing."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.models.resnet import get_resnet_v2
    from mpi4dl_tpu.train import Trainer
    from mpi4dl_tpu.utils import get_depth

    size = 16
    cells = get_resnet_v2(
        depth=get_depth(2, 1), num_classes=10, pool_kernel=size // 4
    )
    trainer = Trainer(
        cells, num_spatial_cells=0,
        config=ParallelConfig(
            batch_size=2, split_size=1, spatial_size=0, image_size=size
        ),
    )
    state = trainer.init(jax.random.PRNGKey(0), (2, size, size, 3))
    x = jnp.zeros((2, size, size, 3), jnp.float32)
    y = jnp.zeros((2,), jnp.int32)
    xs, ys = trainer.shard_batch(x, y)

    reg = telemetry.MetricsRegistry()
    entry = trainer.record_memory_footprint(state, xs, ys, registry=reg)
    assert entry["peak_bytes"] > 0
    assert reg.get("program_peak_hbm_bytes").value(
        program="train_step"
    ) == entry["peak_bytes"]

    # OOM forensics: force the dispatch to raise an OOM-shaped error.
    monkeypatch.setenv(telemetry.ENV_DIR, str(tmp_path))
    monkeypatch.setattr(
        trainer, "_jit_step",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError(HBM_OOM)),
    )
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        trainer.train_step(state, xs, ys)
    (log,) = glob.glob(str(tmp_path / "telemetry-*.jsonl"))
    reports = [
        e for e in telemetry.read_events(log) if e.get("name") == "oom.report"
    ]
    assert len(reports) == 1
    assert reports[0]["attrs"]["program"] == "train_step"
    assert reports[0]["attrs"]["image_size"] == size
    assert reports[0]["attrs"]["parsed"]["used_bytes"] == int(18.95 * 2**30)
