"""Subprocess half of the tiled stitch-exactness suite: run on a
SINGLE-device CPU backend — the tiled predictor's actual deployment
topology (one chip serving huge images) — and compare the tile-streaming
forward against the monolithic forward BIT FOR BIT across tile grids and
model families. Prints one JSON verdict line.

Why a subprocess: the test harness simulates an 8-device mesh
(``conftest.set_cpu_devices(8)``), under which XLA:CPU partitions each
program's intra-op work differently per SHAPE — two programs computing
the same window bytes (a 40×40 section window vs the 56×56 monolithic
forward) can then round differently in the last bit, the repo's standard
cross-executable f32 boundary. On one device the per-shape partitioning
coincides and the stitched forward is bit-identical, which is the claim
that matters for the single-chip gigapixel deployment.
"""

import json
import sys

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.evaluate import aot_compile_predict, collect_batch_stats
    from mpi4dl_tpu.models.resnet import get_resnet_v1, get_resnet_v2
    from mpi4dl_tpu.parallel.partition import init_cells
    from mpi4dl_tpu.serve.tiled import TiledPredictor

    assert len(jax.devices()) == 1, "this check needs ONE device"
    results = {}

    def check(tag, cells, size, tile, seed):
        rng = np.random.default_rng(seed)
        params = init_cells(
            cells, jax.random.PRNGKey(seed), jnp.zeros((1, size, size, 3))
        )
        stats = collect_batch_stats(
            cells, params,
            [jnp.asarray(
                rng.standard_normal((2, size, size, 3)), jnp.float32
            )],
        )
        mono = aot_compile_predict(
            cells, params, stats, (size, size, 3), [1]
        )[1]
        for t in tile if isinstance(tile, list) else [tile]:
            pred = TiledPredictor(
                cells, params, stats, (size, size, 3), t
            )
            handle = pred.compile_bucket(1)
            x = rng.standard_normal((1, size, size, 3)).astype(np.float32)
            got = pred.run(handle, x)
            want = np.asarray(mono(params, stats, x))
            results[f"{tag}_t{t}"] = bool(np.array_equal(got, want))

    # v1 at a ragged size: square/rect cores, ragged last tiles, the
    # single-window degenerate; v2 (pre-activation bottlenecks, 1x1
    # stride-2 shortcuts) at a tiny tile (8x8 grid).
    check(
        "v1_56",
        get_resnet_v1(depth=8, num_classes=10, pool_kernel=14),
        56, [16, (16, 24), 48], seed=0,
    )
    check(
        "v2_32",
        get_resnet_v2(depth=11, num_classes=10, pool_kernel=8),
        32, [4], seed=1,
    )
    ok = all(results.values())
    print(json.dumps({"ok": ok, "bit_identical": results}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
