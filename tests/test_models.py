"""Model zoo tests: shape parity and distributed-vs-sequential equivalence.

The reference's only model-level check is a runtime shape print
(``resnet_spatial.py:494-497``); here a spatially-partitioned ResNet running
on a virtual tile mesh must reproduce the plain single-device model's output
(cross-tile BN makes the distributed model bit-compatible with the golden)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from mpi4dl_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.models.resnet import get_resnet_v1, get_resnet_v2
from mpi4dl_tpu.ops.layers import Sequential
from mpi4dl_tpu.utils import get_depth

SPEC = P(None, "tile_h", "tile_w", None)


def _mesh(th, tw):
    dev = np.asarray(jax.devices()[: th * tw]).reshape(th, tw)
    return Mesh(dev, ("tile_h", "tile_w"))


def test_get_depth_parity():
    # ref utils.py:26-30
    assert get_depth(1, 3) == 20
    assert get_depth(2, 6) == 56


@pytest.mark.parametrize(
    "version,n",
    [pytest.param(1, 2, marks=pytest.mark.slow),
     pytest.param(2, 2, marks=pytest.mark.slow)],
)
def test_resnet_shapes(version, n):
    depth = get_depth(version, n)
    cells = (get_resnet_v1 if version == 1 else get_resnet_v2)(depth, num_classes=10)
    model = Sequential(layers=cells)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 10)


@pytest.mark.parametrize("version", [1, 2])
def test_spatial_resnet_matches_plain(version):
    """All cells spatial, on a 2x2 tile mesh, vs plain golden (logits)."""
    builder = get_resnet_v1 if version == 1 else get_resnet_v2
    depth = get_depth(version, 2)
    plain_cells = builder(depth, num_classes=10, spatial_cells=0)
    n_cells = len(plain_cells)
    # spatial until the head (head is never spatial)
    spatial_cells = builder(depth, num_classes=10, spatial_cells=n_cells - 1)

    mesh = _mesh(2, 2)
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    plain = Sequential(layers=plain_cells)
    params = plain.init(jax.random.PRNGKey(1), x)
    golden = plain.apply(params, x)

    spatial_model = Sequential(layers=spatial_cells[:-1])
    head = Sequential(layers=spatial_cells[-1:])

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), SPEC),
        out_specs=SPEC,
        check_vma=False,
    )
    def spatial_body(p, tile):
        # run the spatial trunk on the local tile
        return spatial_model.apply(p, tile)

    # param tree of Sequential is keyed layers_<i>; split trunk/head params
    # (head re-keyed to layers_0 since it's wrapped in its own Sequential)
    head_params = {
        "params": {"layers_0": params["params"][f"layers_{n_cells-1}"]}
    }
    trunk_params = {
        "params": {
            f"layers_{i}": params["params"][f"layers_{i}"] for i in range(n_cells - 1)
        }
    }

    xs = jax.device_put(x, NamedSharding(mesh, SPEC))
    feats = spatial_body(trunk_params, xs)  # sharded feature map
    # join: gather tiles (the reference's join-rank torch.cat merge,
    # train_spatial.py:1083-1188) — here just a resharding to replicated.
    feats_full = jax.device_get(feats)
    out = head.apply(head_params, jnp.asarray(feats_full))
    np.testing.assert_allclose(np.asarray(out), np.asarray(golden), rtol=2e-4, atol=2e-4)
