"""MXU-packed conv (ops/fastconv.py): exactness vs stock XLA conv.

The packed formulation is a layout identity — same products, same sums
(modulo f32 accumulation order) — so forward values and both gradients must
match ``lax.conv_general_dilated`` to tight f32 tolerances for every
(kernel, padding, factor) combination, including the VALID convs the
spatial/D2 paths use.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import flax.linen as nn
from jax import lax

from mpi4dl_tpu.ops import fastconv


def _rand(shape, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal(shape), jnp.float32)


def _ref_conv(x, w, strides, padding):
    return lax.conv_general_dilated(
        x, w, strides, padding, dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


@pytest.mark.parametrize(
    "k,pad,f",
    [
        (3, 1, (2, 2)),
        (3, 1, (4, 4)),
        (3, 1, (1, 8)),
        (3, 0, (2, 2)),  # VALID conv (D2 shrink style)
        (5, 2, (2, 4)),
        (1, 0, (2, 2)),  # 1x1: packing never selected, but math must hold
        (3, 2, (2, 2)),  # overwide padding (D2 wide-halo style)
    ],
)
def test_packed_conv_matches_plain(k, pad, f):
    x = _rand((2, 16, 24, 5))
    w = _rand((k, k, 5, 7), seed=1) * 0.3
    padding = ((pad, pad), (pad, pad))
    got = fastconv._conv_packed(x, w, padding, *f)
    want = _ref_conv(x, w, (1, 1), padding)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_scatter_kernel_shape_and_content():
    w = _rand((3, 3, 2, 4))
    wp = fastconv._scatter_kernel(w, 2, 2)
    assert wp.shape == (4, 4, 2, 16)
    # group (0,0) = kernel at offset (0,0), zeros in the last row/col
    blk = wp[:, :, :, 0:4]
    np.testing.assert_array_equal(blk[:3, :3], w)
    assert float(jnp.abs(blk[3]).max()) == 0.0
    # group (1,1) = kernel shifted by one
    blk = wp[:, :, :, 12:16]
    np.testing.assert_array_equal(blk[1:, 1:], w)


def test_unknown_impl_rejected(monkeypatch):
    monkeypatch.setenv("MPI4DL_TPU_CONV_IMPL", "PACKED")
    x = _rand((1, 4, 4, 2))
    w = _rand((1, 1, 2, 2))
    with pytest.raises(ValueError, match="auto|packed|xla"):
        fastconv.conv2d(x, w, (1, 1), ((0, 0), (0, 0)))


@pytest.mark.parametrize("k,pad", [(3, 1), (3, 0), (1, 0), (5, 2), (3, 3)])
def test_custom_vjp_grads_match(k, pad, monkeypatch):
    monkeypatch.setenv("MPI4DL_TPU_CONV_IMPL", "packed")
    x = _rand((2, 8, 16, 5))
    w = _rand((k, k, 5, 7), seed=1) * 0.3
    padding = ((pad, pad), (pad, pad))
    cot = _rand(
        (2, 8 + 2 * pad - k + 1, 16 + 2 * pad - k + 1, 7), seed=2
    )

    def loss_fast(x, w):
        return jnp.sum(fastconv.conv2d(x, w, (1, 1), padding) * cot)

    def loss_ref(x, w):
        return jnp.sum(_ref_conv(x, w, (1, 1), padding) * cot)

    gx, gw = jax.grad(loss_fast, (0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, (0, 1))(x, w)
    np.testing.assert_allclose(gx, rx, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(gw, rw, rtol=2e-4, atol=2e-4)


def test_strided_conv_falls_back_and_matches(monkeypatch):
    monkeypatch.setenv("MPI4DL_TPU_CONV_IMPL", "packed")
    x = _rand((2, 16, 16, 4))
    w = _rand((3, 3, 4, 6), seed=1) * 0.3
    padding = ((1, 1), (1, 1))
    got = fastconv.conv2d(x, w, (2, 2), padding)
    want = _ref_conv(x, w, (2, 2), padding)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("strides", [(1, 1), (2, 2)])
def test_taps_wgrad_grads_match(strides, monkeypatch):
    """The big-size per-tap wgrad (and the strided custom VJP around it)
    must equal stock XLA AD. The production gate needs >=256 MB operands;
    MIN_MB=0 forces the taps branch on small shapes so the path is
    exercised in CI (it is otherwise dead below 2048px)."""
    monkeypatch.setenv("MPI4DL_TPU_CONV_IMPL", "packed")
    monkeypatch.setenv("MPI4DL_TPU_WGRAD_TAPS_MIN_MB", "0")
    x = _rand((1, 16, 16, 4))
    w = _rand((3, 3, 4, 6), seed=1) * 0.3
    padding = ((1, 1), (1, 1))

    def loss_fast(x, w):
        return jnp.sum(jnp.square(fastconv.conv2d(x, w, strides, padding)))

    def loss_ref(x, w):
        return jnp.sum(jnp.square(_ref_conv(x, w, strides, padding)))

    gx, gw = jax.grad(loss_fast, (0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, (0, 1))(x, w)
    np.testing.assert_allclose(gx, rx, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(gw, rw, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("k,s", [(3, 1), (3, 2), (1, 1)])
def test_packed_core_taps_grads_match(k, s, monkeypatch):
    """The packed-layout core conv's taps backward (bs=1 engages the
    batch<=2 gate with MIN_MB=0) must equal stock AD of the plain conv
    through the pack/unpack round trip."""
    monkeypatch.setenv("MPI4DL_TPU_WGRAD_TAPS_MIN_MB", "0")
    from mpi4dl_tpu.ops.packed import conv2d_packed, pack, pack_factor, unpack

    c = o = 8  # equal c/o keeps f_in == f_out valid for every stride here
    f_in, f_out = pack_factor(c, 32), pack_factor(o, 32 // s)
    x = _rand((1, 16, 32, c))
    w = _rand((k, k, c, o), seed=1) * 0.3
    p = (k - 1) // 2
    padding = ((p, p), (p, p))

    def loss_packed(x, w):
        y = conv2d_packed(pack(x, f_in), w, f_in, f_out, (s, s), padding)
        return jnp.sum(jnp.square(unpack(y, f_out)))

    def loss_ref(x, w):
        return jnp.sum(jnp.square(_ref_conv(x, w, (s, s), padding)))

    gx, gw = jax.grad(loss_packed, (0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, (0, 1))(x, w)
    np.testing.assert_allclose(gx, rx, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(gw, rw, rtol=2e-4, atol=2e-4)


def test_pack_factors_policy():
    # 1x1 never packs
    assert fastconv.pack_factors(1, 1, 16, 64) == (1, 1)
    # >=128 output channels never packs
    assert fastconv.pack_factors(3, 3, 128, 64) == (1, 1)
    # small-N 3x3 packs along W only, factor divides the output extent
    fh, fw = fastconv.pack_factors(3, 3, 16, 64)
    assert fh == 1 and fw > 1 and 64 % fw == 0
    # indivisible output extent: no packing
    assert fastconv.pack_factors(3, 3, 16, 7) == (1, 1)


def test_fastconv_module_params_match_nn_conv(monkeypatch):
    monkeypatch.setenv("MPI4DL_TPU_CONV_IMPL", "packed")
    x = _rand((2, 8, 8, 4))
    ref = nn.Conv(
        features=6, kernel_size=(3, 3), strides=(1, 1),
        padding=((1, 1), (1, 1)), name="conv",
    )
    fast = fastconv.FastConv(
        features=6, kernel_size=(3, 3), strides=(1, 1),
        padding=((1, 1), (1, 1)), name="conv",
    )
    vref = ref.init(jax.random.PRNGKey(0), x)
    vfast = fast.init(jax.random.PRNGKey(0), x)
    assert jax.tree.structure(vref) == jax.tree.structure(vfast)
    for a, b in zip(jax.tree.leaves(vref), jax.tree.leaves(vfast)):
        assert a.shape == b.shape and a.dtype == b.dtype
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(
        fast.apply(vref, x), ref.apply(vref, x), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("strides", [(1, 1), (2, 2)])
def test_fastconv_same_padding_string(strides, monkeypatch):
    monkeypatch.setenv("MPI4DL_TPU_CONV_IMPL", "packed")
    x = _rand((1, 12, 16, 3))
    fast = fastconv.FastConv(
        features=5, kernel_size=(3, 3), strides=strides, padding="SAME",
        name="conv",
    )
    v = fast.init(jax.random.PRNGKey(0), x)
    ref = nn.Conv(
        features=5, kernel_size=(3, 3), strides=strides, padding="SAME",
        name="conv",
    )
    np.testing.assert_allclose(
        fast.apply(v, x), ref.apply(v, x), rtol=2e-5, atol=2e-5
    )


def test_fastconv_valid_padding_string(monkeypatch):
    monkeypatch.setenv("MPI4DL_TPU_CONV_IMPL", "packed")
    x = _rand((1, 10, 12, 3))
    fast = fastconv.FastConv(
        features=5, kernel_size=(3, 3), padding="VALID", name="conv"
    )
    v = fast.init(jax.random.PRNGKey(0), x)
    ref = nn.Conv(
        features=5, kernel_size=(3, 3), padding="VALID", name="conv"
    )
    np.testing.assert_allclose(
        fast.apply(v, x), ref.apply(v, x), rtol=2e-5, atol=2e-5
    )


def test_packed_spatial_conv_matches_golden(monkeypatch):
    """The production TPU shape: Conv2d(spatial=True) under shard_map with
    the packed impl, forward AND gradient vs the full-image plain golden."""
    monkeypatch.setenv("MPI4DL_TPU_CONV_IMPL", "packed")
    from mpi4dl_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from mpi4dl_tpu.config import ParallelConfig
    from mpi4dl_tpu.ops.layers import Conv2d

    cfg = ParallelConfig(
        batch_size=2,
        split_size=1,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=16,
    )
    mesh = cfg.make_mesh()
    x = _rand((2, 16, 16, 4))
    cot = _rand((2, 16, 16, 6), seed=3)

    plain = Conv2d(features=6, kernel_size=3)
    spatial = Conv2d(features=6, kernel_size=3, spatial=True)
    v = plain.init(jax.random.PRNGKey(0), x)

    def golden(v, x):
        return jnp.sum(plain.apply(v, x) * cot)

    def local(v, x, cot):
        return jax.lax.psum(
            jnp.sum(spatial.apply(v, x) * cot), ("tile_h", "tile_w")
        )

    dist = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(None, "tile_h", "tile_w", None),
                  P(None, "tile_h", "tile_w", None)),
        out_specs=P(),
        check_vma=False,
    )
    np.testing.assert_allclose(dist(v, x, cot), golden(v, x), rtol=2e-5)
    gd = jax.grad(lambda v: dist(v, x, cot))(v)
    gg = jax.grad(lambda v: golden(v, x))(v)
    for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gg)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)
