"""Runtime trace attribution (:mod:`mpi4dl_tpu.analysis.trace`): canned
Chrome-trace fixtures with known category times, the degradation paths
(missing/empty dir, no step annotations), the static<->measured overlap
cross-check, and the live CPU acceptance — ``profiling.capture`` over ≥3
annotated steps whose attribution buckets sum to the measured step wall
time and whose measured-overlap verdict agrees with hlolint's static
finding on the same executable. CPU-only, tier-1.
"""

import gzip
import json
import os

import numpy as np
import pytest

from mpi4dl_tpu import profiling, telemetry
from mpi4dl_tpu.analysis.trace import (
    TraceError,
    analyze_events,
    analyze_trace_dir,
    categorize,
    crosscheck_overlap,
    publish_attribution,
    static_overlap_verdict,
)

# -- canned fixture -----------------------------------------------------------

# Two annotated 1000us steps on a host thread; device ops on two XLA
# executor threads. All times in microseconds (the Chrome trace unit).
_META = [
    {"ph": "M", "pid": 1, "name": "process_name", "args": {"name": "/host:CPU"}},
    {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
     "args": {"name": "python"}},
    {"ph": "M", "pid": 1, "tid": 20, "name": "thread_name",
     "args": {"name": "tf_XLATfrtCpuClient/111"}},
    {"ph": "M", "pid": 1, "tid": 21, "name": "thread_name",
     "args": {"name": "tf_XLATfrtCpuClient/222"}},
]

_STEPS = [
    {"ph": "X", "pid": 1, "tid": 10, "ts": 0, "dur": 1000,
     "name": "mpi4dl_capture", "args": {"step_num": "0"}},
    {"ph": "X", "pid": 1, "tid": 10, "ts": 1000, "dur": 1000,
     "name": "mpi4dl_capture", "args": {"step_num": "1"}},
]

_DEVICE = [
    # step 0: 400us compute, then a 200us collective with 100us of
    # concurrent compute on the OTHER executor thread, then 100us d2d.
    {"ph": "X", "pid": 1, "tid": 20, "ts": 100, "dur": 400, "name": "fusion.1"},
    {"ph": "X", "pid": 1, "tid": 20, "ts": 500, "dur": 200,
     "name": "collective-permute.3"},
    {"ph": "X", "pid": 1, "tid": 21, "ts": 550, "dur": 100, "name": "dot.7"},
    {"ph": "X", "pid": 1, "tid": 20, "ts": 700, "dur": 100,
     "name": "D2D Dispatch"},
    # step 1: compute only.
    {"ph": "X", "pid": 1, "tid": 20, "ts": 1200, "dur": 300,
     "name": "convolution.2"},
    # runtime bookkeeping that must NOT count as device busy time — the
    # ExecuteHelper wrapper spans the whole step and would double it.
    {"ph": "X", "pid": 1, "tid": 20, "ts": 0, "dur": 1000,
     "name": "TfrtCpuExecutable::ExecuteHelper"},
    {"ph": "X", "pid": 1, "tid": 20, "ts": 0, "dur": 50,
     "name": "ThreadpoolListener::StartRegion"},
    {"ph": "X", "pid": 1, "tid": 20, "ts": 600, "dur": 300,
     "name": "ThunkExecutor::Execute (wait for completion)"},
]

CANNED = _META + _STEPS + _DEVICE


def _write_trace(root, events, gz=True):
    run = os.path.join(str(root), "plugins", "profile", "2026_01_01_00_00_00")
    os.makedirs(run, exist_ok=True)
    payload = json.dumps({"displayTimeUnit": "ms", "traceEvents": events})
    if gz:
        with gzip.open(os.path.join(run, "host.trace.json.gz"), "wb") as f:
            f.write(payload.encode())
    else:
        with open(os.path.join(run, "host.trace.json"), "w") as f:
            f.write(payload)
    return str(root)


def test_canned_attribution_known_category_times(tmp_path):
    """ISSUE satellite: a canned .trace.json.gz with known per-category
    times parses to exactly those times, wrapper/bookkeeping excluded,
    and the four buckets sum to each step's wall time."""
    summary = analyze_trace_dir(_write_trace(tmp_path, CANNED))
    assert summary["n_steps"] == 2
    s0, s1 = summary["steps"]
    assert s0["wall_s"] == pytest.approx(1000e-6)
    assert s0["compute_s"] == pytest.approx(400e-6)  # dot.7 is inside the
    # collective window on another thread -> overlap, not extra compute
    assert s0["collective_s"] == pytest.approx(200e-6)
    assert s0["transfer_s"] == pytest.approx(100e-6)
    assert s0["host_gap_s"] == pytest.approx(300e-6)
    assert s1["compute_s"] == pytest.approx(300e-6)
    assert s1["collective_s"] == 0.0
    assert s1["host_gap_s"] == pytest.approx(700e-6)
    for s in (s0, s1):
        total = (s["compute_s"] + s["collective_s"] + s["transfer_s"]
                 + s["host_gap_s"])
        assert total == pytest.approx(s["wall_s"], abs=1e-12)
    # Measured overlap: 100us of the 200us collective had concurrent
    # compute on the other executor thread.
    coll = summary["collective"]
    assert coll["total_s"] == pytest.approx(200e-6)
    assert coll["overlapped_s"] == pytest.approx(100e-6)
    assert coll["overlap_ratio"] == pytest.approx(0.5)
    assert coll["verdict"] == "overlapped"
    assert coll["by_op"]["collective-permute"]["n"] == 1


def test_canned_attribution_uncompressed_trace(tmp_path):
    summary = analyze_trace_dir(_write_trace(tmp_path, CANNED, gz=False))
    assert summary["n_steps"] == 2


def test_missing_and_empty_trace_dir_raise(tmp_path):
    """ISSUE satellite degradation: missing dir, dir without profiler
    runs, and a run without trace files all raise TraceError at the
    reader — not a KeyError three layers down."""
    with pytest.raises(TraceError, match="does not exist"):
        analyze_trace_dir(str(tmp_path / "nope"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(TraceError, match="no profiler runs"):
        analyze_trace_dir(str(empty))
    run = tmp_path / "norun" / "plugins" / "profile" / "r1"
    run.mkdir(parents=True)
    with pytest.raises(TraceError, match="no .*trace.json"):
        analyze_trace_dir(str(tmp_path / "norun"))


def test_trace_without_step_annotations_degrades_to_range(tmp_path):
    """ISSUE satellite degradation: no StepTraceAnnotation events ->
    n_steps == 0, but the whole-range bucket still answers where device
    time went."""
    summary = analyze_trace_dir(_write_trace(tmp_path, _META + _DEVICE))
    assert summary["n_steps"] == 0
    assert summary["per_step_mean"] is None
    rng = summary["range"]
    assert rng["compute_s"] == pytest.approx(400e-6 + 300e-6)
    assert rng["collective_s"] == pytest.approx(200e-6)
    assert rng["transfer_s"] == pytest.approx(100e-6)
    # Publishing falls back to range totals and must not raise.
    reg = telemetry.MetricsRegistry()
    publish_attribution(summary, reg, program="rangetest")
    attr = reg.get("trace_attribution_seconds")
    assert attr.value(program="rangetest", category="compute") == (
        pytest.approx(700e-6)
    )
    assert reg.get("trace_step_wall_seconds") is None  # no steps -> no wall


def test_categorize_noise_filter():
    assert categorize("collective-permute.12") == "collective"
    assert categorize("all-reduce-start.1") == "collective"
    assert categorize("all_reduce_fusion") == "compute"  # fusion kernel
    assert categorize("D2D Dispatch") == "transfer"
    assert categorize("TransferToDeviceStream") == "transfer"
    assert categorize("fusion.3") == "compute"
    assert categorize("TfrtCpuExecutable::ExecuteHelper") is None
    assert categorize("ThunkExecutor::Execute (wait for completion)") is None
    assert categorize("$profiling.py:141 annotate_step") is None


# -- static <-> measured cross-check ------------------------------------------


def _summary_with(total_s, ratio):
    verdict = (
        "no-collectives" if total_s == 0
        else ("overlapped" if ratio >= 0.5 else "exposed")
    )
    return {"collective": {
        "total_s": total_s,
        "overlapped_s": total_s * ratio if total_s else 0.0,
        "overlap_ratio": ratio if total_s else None,
        "by_op": {},
        "verdict": verdict,
    }}


def test_static_overlap_verdicts():
    assert static_overlap_verdict(
        {"n_collectives": 0, "async_pairs": 0, "zero_overlap": []}
    ) == "no-collectives"
    assert static_overlap_verdict(
        {"n_collectives": 4, "async_pairs": 0, "zero_overlap": []}
    ) == "sync"
    assert static_overlap_verdict(
        {"n_collectives": 4, "async_pairs": 2, "zero_overlap": ["a"]}
    ) == "exposed"
    assert static_overlap_verdict(
        {"n_collectives": 4, "async_pairs": 2, "zero_overlap": []}
    ) == "overlapped"


def test_crosscheck_disagreements_are_findings():
    overlapped_static = {"overlap": {
        "n_collectives": 2, "async_pairs": 2, "zero_overlap": [],
    }}
    # Static promises overlap, trace measured exposed latency: the T3
    # lost-overlap signature the static rule cannot see.
    (f,) = crosscheck_overlap(overlapped_static, _summary_with(1e-3, 0.1))
    assert f.rule == "trace-overlap-crosscheck" and f.severity == "warn"
    # Agreement in both directions -> no findings.
    assert crosscheck_overlap(overlapped_static, _summary_with(1e-3, 0.9)) == []
    none_static = {"overlap": {
        "n_collectives": 0, "async_pairs": 0, "zero_overlap": [],
    }}
    assert crosscheck_overlap(none_static, _summary_with(0.0, 0.0)) == []
    # Static saw nothing, trace recorded collectives (wrong program).
    (f,) = crosscheck_overlap(none_static, _summary_with(1e-3, 0.9))
    assert f.severity == "warn"
    # Static flagged exposed, runtime overlapped anyway: informational.
    exposed_static = {"overlap": {
        "n_collectives": 2, "async_pairs": 2, "zero_overlap": ["x"],
    }}
    (f,) = crosscheck_overlap(exposed_static, _summary_with(1e-3, 0.9))
    assert f.severity == "info"
    # "sync" schedules make no overlap claim: nothing to disagree with.
    sync_static = {"overlap": {
        "n_collectives": 2, "async_pairs": 0, "zero_overlap": [],
    }}
    assert crosscheck_overlap(sync_static, _summary_with(1e-3, 0.1)) == []


# -- live capture (the ISSUE acceptance) --------------------------------------


def test_capture_live_attribution_sums_and_crosscheck(tmp_path):
    """ISSUE acceptance: capture() over >=3 annotated steps on a live
    multi-device CPU program (halo-style ppermute ring + compute) yields
    an attribution whose category times sum to within 10% of the
    host-measured step wall time, and whose measured-overlap verdict is
    consistent with hlolint's static finding on the same executable."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from mpi4dl_tpu.analysis import analyze_compiled
    from mpi4dl_tpu.compat import shard_map

    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()), ("x",))

    def body(v):
        w = jax.lax.ppermute(v, "x", [(i, (i + 1) % n) for i in range(n)])
        m = v[0]
        return v * (m @ m.T).sum() + w

    f = jax.jit(shard_map(
        body, mesh=mesh, in_specs=P("x"), out_specs=P("x"), check_vma=False,
    ))
    x = jnp.ones((n, 256, 256), jnp.float32)
    f(x).block_until_ready()  # compile outside the capture

    cap = profiling.capture(lambda i: f(x), steps=3, logdir=str(tmp_path))
    summary = cap.attribution()
    assert summary["n_steps"] >= 3
    assert summary["n_device_slices"] > 0

    # Buckets sum to the annotation wall exactly (construction), and the
    # annotation wall matches the independent host clock within 10%.
    for step, host_dt in zip(summary["steps"], cap.step_times_s):
        parts = (step["compute_s"] + step["collective_s"]
                 + step["transfer_s"] + step["host_gap_s"])
        assert parts == pytest.approx(step["wall_s"], rel=1e-9)
        assert step["wall_s"] == pytest.approx(host_dt, rel=0.10)
    assert summary["per_step_mean"]["compute_s"] > 0
    assert summary["collective"]["total_s"] > 0  # the ppermutes

    # Static analysis of the SAME executable: CPU emits sync collectives
    # (no -start/-done pairs), so the schedule makes no overlap promise
    # and any measured verdict is consistent -> zero crosscheck findings.
    report = analyze_compiled(f.lower(x).compile(), platform="cpu")
    assert report.overlap["n_collectives"] > 0
    assert crosscheck_overlap(report, summary) == []


def test_capture_single_chip_consistent_with_static_no_collectives(tmp_path):
    """The serving-shaped case: a one-device program has zero collectives
    statically AND in the trace — verdicts agree, no findings."""
    import jax
    import jax.numpy as jnp

    from mpi4dl_tpu.analysis import analyze_compiled

    f = jax.jit(lambda v: (v @ v.T).sum())
    x = jnp.ones((512, 512), jnp.float32)
    f(x).block_until_ready()
    cap = profiling.capture(lambda i: f(x), steps=3, logdir=str(tmp_path))
    summary = cap.attribution()
    assert summary["collective"]["verdict"] == "no-collectives"
    report = analyze_compiled(f.lower(x).compile(), platform="cpu")
    assert static_overlap_verdict(report.overlap) == "no-collectives"
    assert crosscheck_overlap(report, summary) == []


def test_analyze_events_empty_is_graceful():
    summary = analyze_events([], step_name="mpi4dl_capture")
    assert summary["n_steps"] == 0
    assert summary["range"]["span_s"] == 0.0
    assert summary["collective"]["verdict"] == "no-collectives"
