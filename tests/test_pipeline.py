"""Pipeline engine parity tests: the GPipe fill-drain schedule over the
``pipe`` mesh axis must reproduce the single-device golden training step —
loss, accuracy, and updated parameters — for LP, LP+balance, DP+LP, SP+LP,
and the GEMS mirror placement.

The reference can only validate its pipeline by running benchmarks on a real
GPU+MPI cluster; here every schedule runs single-process on the 8 virtual CPU
devices (conftest) against a golden model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.config import ParallelConfig
from mpi4dl_tpu.models.resnet import get_resnet_v1
from mpi4dl_tpu.parallel.pipeline import GemsMasterTrainer, PipelineTrainer
from mpi4dl_tpu.train import TrainState, single_device_step


def _batch(b, size, classes=10, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((b, size, size, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, classes, size=(b,)), jnp.int32)
    return x, y


def _golden_from(trainer, state):
    """Single-device golden state sharing the pipeline trainer's init."""
    cell_params = jax.tree.map(np.asarray, trainer.unstack_params(state.params))
    chunks = getattr(trainer, "chunks", 1)  # GEMS runs 2*times chunks
    # local_dp multiplies the effective micro-batch count: each tile device
    # pipelines its own 1/local_dp slice (per-slice BN statistics, matching
    # the reference's per-replica DDP BN under LOCAL_DP_LP).
    _, step = single_device_step(
        trainer.plain_cells,
        parts=chunks
        * trainer.config.parts
        * trainer.config.data_parallel
        * trainer.config.local_dp,
    )
    return (
        step,
        TrainState(
            params=cell_params,
            opt_state=trainer.tx.init(cell_params),
            step=jnp.zeros((), jnp.int32),
        ),
    )


def _run_and_compare(trainer, steps=2, batch_seed=0, rtol=2e-4, atol=1e-5,
                     loss_rtol=1e-5):
    cfg = trainer.config
    state = trainer.init(jax.random.PRNGKey(0))
    golden_step, golden_state = _golden_from(trainer, state)
    global_b = getattr(trainer, "chunks", 1) * cfg.batch_size

    for i in range(steps):
        x, y = _batch(global_b, cfg.image_size, cfg.num_classes, seed=batch_seed + i)
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        golden_state, golden_metrics = golden_step(golden_state, x, y)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(golden_metrics["loss"]),
            rtol=loss_rtol, err_msg=f"loss mismatch at step {i}",
        )
        np.testing.assert_allclose(
            float(metrics["accuracy"]), float(golden_metrics["accuracy"]), rtol=1e-6
        )

    got = jax.tree.map(np.asarray, trainer.unstack_params(state.params))
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), rtol=rtol, atol=atol
        ),
        got,
        golden_state.params,
    )


@pytest.mark.parametrize(
    "parts",
    [pytest.param(1, marks=pytest.mark.slow), 2,
     pytest.param(4, marks=pytest.mark.slow)],
)
def test_lp_pipeline_matches_golden(parts):
    """Plain LP/PP: 2 stages, varying micro-batch counts (ref `--parts`)."""
    cfg = ParallelConfig(
        batch_size=4, parts=parts, split_size=2, spatial_size=0, image_size=32
    )
    cells = get_resnet_v1(depth=8)
    trainer = PipelineTrainer(cells, cfg)
    _run_and_compare(trainer)


@pytest.mark.slow
def test_lp_pipeline_balance_and_4_stages():
    """Uneven user balance over 4 stages (ref `--balance`)."""
    cfg = ParallelConfig(
        batch_size=4,
        parts=2,
        split_size=4,
        spatial_size=0,
        image_size=32,
        balance=[2, 1, 1, 4],
    )
    cells = get_resnet_v1(depth=14)  # 8 cells
    trainer = PipelineTrainer(cells, cfg)
    _run_and_compare(trainer)


@pytest.mark.slow
def test_dp_lp_pipeline():
    """DP=2 x 2 stages: gradient reduction across replicas composes with the
    pipeline schedule."""
    cfg = ParallelConfig(
        batch_size=8, parts=2, split_size=2, spatial_size=0, image_size=32,
        data_parallel=2,
    )
    cells = get_resnet_v1(depth=8)
    trainer = PipelineTrainer(cells, cfg)
    _run_and_compare(trainer)


@pytest.mark.parametrize(
    "slice_method,parts_sp,split,depth,parts",
    [
        ("square", 4, 2, 8, 2),  # front + single LP stage (4 devices)
        pytest.param("vertical", 2, 2, 8, 2, marks=pytest.mark.slow),
        # front + 2-stage LP pipeline (8 devices), parts % lp == 0 →
        # front micro-batches shard over the pipe axis
        pytest.param("square", 4, 3, 14, 2, marks=pytest.mark.slow),
        # parts % lp != 0 → replicated-front path
        pytest.param("square", 4, 3, 14, 3, marks=pytest.mark.slow),
    ],
)
def test_sp_lp_pipeline(slice_method, parts_sp, split, depth, parts):
    """SP+LP hybrid: spatial front (halo-exchange cells on tiles, vmap-ed per
    micro-batch, join at the end), then the LP fill-drain pipeline (the
    reference's flagship configuration)."""
    cfg = ParallelConfig(
        batch_size=parts,
        parts=parts,
        split_size=split,
        spatial_size=1,
        num_spatial_parts=(parts_sp,),
        slice_method=slice_method,
        image_size=32,
    )
    n_cells = len(get_resnet_v1(depth=depth))
    n_spatial = PipelineTrainer.spatial_cell_count(n_cells, cfg)
    cells = get_resnet_v1(depth=depth, spatial_cells=n_spatial)
    plain = get_resnet_v1(depth=depth)
    trainer = PipelineTrainer(cells, cfg, plain_cells=plain)
    _run_and_compare(trainer)


def _local_dp_golden_step(plain_cells, n_front, parts, ldp, chunks=1, dp=1):
    """Golden for LOCAL_DP_LP: front cells see whole micro-batches (BN stats
    over mb_local), back cells see per-device slices (BN stats over mb_back)
    — a uniform ``parts`` golden can't express the mixed grouping (the
    reference has the same semantics: spatial ranks batch-norm full tiles,
    the scattered LP replicas batch-norm their slice). ``dp`` > 1 adds data
    replicas: each (chunk, part) micro-batch splits into dp contiguous
    slices, matching the trainer's data-axis sharding order."""
    from mpi4dl_tpu.train import (
        TrainState,
        correct_count,
        cross_entropy_sum,
        make_optimizer,
    )

    tx = make_optimizer()

    @jax.jit
    def step(state: TrainState, x, y):
        def loss_fn(params):
            b = y.shape[0]
            groups = chunks * parts * dp
            xm = x.reshape((groups, b // groups) + tuple(x.shape[1:]))
            ym = y.reshape((groups, b // groups))
            ce = jnp.zeros((), jnp.float32)
            cc = jnp.zeros((), jnp.float32)
            for g in range(groups):
                h = xm[g]
                for cell, p in zip(plain_cells[:n_front], params[:n_front]):
                    h = cell.apply(p, h)
                k = h.shape[0] // ldp
                for d in range(ldp):
                    hs = h[d * k : (d + 1) * k]
                    for cell, p in zip(plain_cells[n_front:], params[n_front:]):
                        hs = cell.apply(p, hs)
                    ce += cross_entropy_sum(hs, ym[g][d * k : (d + 1) * k])
                    cc += correct_count(hs, ym[g][d * k : (d + 1) * k]).astype(
                        jnp.float32
                    )
            return ce / b, cc / b

        import optax

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            {"loss": loss, "accuracy": acc},
        )

    return step


def _run_and_compare_local_dp(trainer, steps=2):
    cfg = trainer.config
    state = trainer.init(jax.random.PRNGKey(0))
    cell_params = jax.tree.map(np.asarray, trainer.unstack_params(state.params))
    chunks = getattr(trainer, "chunks", 1)
    golden_step = _local_dp_golden_step(
        trainer.plain_cells,
        trainer.n_spatial_cells,
        cfg.parts,
        cfg.local_dp,
        chunks=chunks,
        dp=cfg.data_parallel,
    )
    golden_state = TrainState(
        params=cell_params,
        opt_state=trainer.tx.init(cell_params),
        step=jnp.zeros((), jnp.int32),
    )
    for i in range(steps):
        x, y = _batch(chunks * cfg.batch_size, cfg.image_size, seed=10 + i)
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        golden_state, golden_metrics = golden_step(golden_state, x, y)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
        )
    got = jax.tree.map(np.asarray, trainer.unstack_params(state.params))
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(
            np.asarray(u), np.asarray(v), rtol=2e-4, atol=1e-5
        ),
        got,
        golden_state.params,
    )


@pytest.mark.slow
def test_local_dp_lp_matches_golden():
    """LOCAL_DP_LP (ref ``train_spatial.py:809-1028``): with ``--local-DP``,
    the post-join LP stages batch-shard over the 4 tile devices (each
    pipelines a distinct quarter of every micro-batch) instead of computing
    redundantly."""
    cfg = ParallelConfig(
        batch_size=8,
        parts=1,
        split_size=2,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=32,
        local_dp=4,
    )
    n_cells = len(get_resnet_v1(depth=8))
    n_spatial = PipelineTrainer.spatial_cell_count(n_cells, cfg)
    cells = get_resnet_v1(depth=8, spatial_cells=n_spatial)
    plain = get_resnet_v1(depth=8)
    trainer = PipelineTrainer(cells, cfg, plain_cells=plain)
    assert trainer.mb_back == 2
    _run_and_compare_local_dp(trainer)


@pytest.mark.slow
def test_local_dp_lp_with_gems():
    """LOCAL_DP_LP composes with the GEMS bidirectional schedule."""
    cfg = ParallelConfig(
        batch_size=4,
        parts=1,
        split_size=2,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=32,
        local_dp=4,
        times=1,
    )
    n_cells = len(get_resnet_v1(depth=8))
    n_spatial = GemsMasterTrainer.spatial_cell_count(n_cells, cfg)
    cells = get_resnet_v1(depth=8, spatial_cells=n_spatial)
    plain = get_resnet_v1(depth=8)
    trainer = GemsMasterTrainer(cells, cfg, plain_cells=plain)
    _run_and_compare_local_dp(trainer)


@pytest.mark.slow
def test_skewed_multistage_sp_matches_golden():
    """Skewed multi-stage SP (ref ``--num-spatial-parts 4,2``,
    ``train_spatial.py:453-641``): two spatial stages with decreasing part
    counts. TPU-native execution keeps the finest (4-tile) grid for both
    stages — numerically identical to the reference's coarser re-tiling,
    whose only purpose is GPU rank mapping — so the golden comparison proves
    the capability, not just the flag parsing."""
    cfg = ParallelConfig(
        batch_size=2,
        parts=2,
        split_size=3,
        spatial_size=2,
        num_spatial_parts=(4, 2),
        slice_method="square",
        image_size=32,
    )
    n_cells = len(get_resnet_v1(depth=14))
    n_spatial = PipelineTrainer.spatial_cell_count(n_cells, cfg)
    cells = get_resnet_v1(depth=14, spatial_cells=n_spatial)
    plain = get_resnet_v1(depth=14)
    trainer = PipelineTrainer(cells, cfg, plain_cells=plain)
    _run_and_compare(trainer)


def test_skewed_sp_validation():
    """Increasing part lists are rejected; decreasing ones are accepted and
    run on the finest grid (a superset of the reference, whose config check
    rejects all non-uniform lists, train_spatial.py:55-58, even though its
    skewed-transition machinery exists at train_spatial.py:453-641)."""
    base = dict(
        batch_size=2, parts=1, split_size=3, spatial_size=2,
        slice_method="square", image_size=32,
    )
    with pytest.raises(ValueError):
        ParallelConfig(num_spatial_parts=(2, 4), **base)
    ParallelConfig(num_spatial_parts=(4, 2), **base)  # valid


@pytest.mark.slow
def test_mirror_pipeline_matches_golden():
    """GEMS_INVERSE placement: stage s on pipe device S-1-s, wire flow
    reversed (ref ``mp_pipeline.py:238-248``) — must be numerically identical
    to the normal placement."""
    cfg = ParallelConfig(
        batch_size=4, parts=2, split_size=2, spatial_size=0, image_size=32
    )
    cells = get_resnet_v1(depth=8)
    trainer = PipelineTrainer(cells, cfg, mirror=True)
    _run_and_compare(trainer)


def test_1f1b_pipeline_matches_golden_and_gpipe():
    """ISSUE 14: the interleaved 1F1B schedule (virtual stages ringing
    through the pipe, AD-transposed backward) is numerically the SAME
    training step as GPipe — loss equal per step against a GPipe twin
    sharing the init, updated params equal at the repo's standard
    tolerance. The GPipe twin itself is golden-anchored against the
    single-device model at this exact config
    (test_lp_pipeline_matches_golden[2]), so equality here IS golden
    equality without paying a third compile. The schedules may only
    differ in WHEN work runs (the measured bubble, tests/
    test_pipeline_lens.py), never in what it computes."""
    cfg = ParallelConfig(
        batch_size=4, parts=2, split_size=2, spatial_size=0, image_size=32
    )
    cells = get_resnet_v1(depth=8)
    trainer = PipelineTrainer(cells, cfg, schedule="1f1b", virtual_stages=2)
    assert trainer.n_virtual == 4
    assert len(trainer.wire_metas) == 3  # v*S - 1 ring boundaries
    gpipe = PipelineTrainer(cells, cfg)  # same PRNG init below

    state = trainer.init(jax.random.PRNGKey(0))
    g_state = gpipe.init(jax.random.PRNGKey(0))
    for i in range(2):
        x, y = _batch(4, 32, seed=i)
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        g_state, g_metrics = gpipe.train_step(g_state, xs, ys)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(g_metrics["loss"]), rtol=1e-6,
            err_msg=f"1f1b loss diverged from gpipe at step {i}",
        )
        np.testing.assert_allclose(
            float(metrics["accuracy"]), float(g_metrics["accuracy"]),
            rtol=1e-6,
        )
    got = jax.tree.map(np.asarray, trainer.unstack_params(state.params))
    want = jax.tree.map(np.asarray, gpipe.unstack_params(g_state.params))
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(u, v, rtol=2e-4, atol=1e-5),
        got, want,
    )


# jax 0.4.x cannot differentiate the GEMS schedule's shard_map at all:
# with check_vma/check_rep=False its transpose rule trips an internal
# _SpecError on the scalar loss outputs, and the check_rep=True rewrite
# path rejects the chunk scan's lax.cond ("branches produced mismatched
# replication types" — the workaround it suggests IS check_rep=False).
# Fixed upstream in later jax; nothing repo-side short of rewriting the
# schedule can dodge both.
_GEMS_GRAD_BROKEN = tuple(
    int(p) for p in jax.__version__.split(".")[:2]
) < (0, 5)


@pytest.mark.skipif(
    _GEMS_GRAD_BROKEN,
    reason="jax 0.4.x shard_map transpose cannot differentiate the GEMS "
    "schedule (_SpecError with check_rep=False, cond rep-type mismatch "
    "with check_rep=True)",
)
@pytest.mark.parametrize(
    "times",
    [
        1,
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(4, marks=pytest.mark.slow),
    ],
)
def test_gems_master_matches_golden(times):
    """GEMS-MASTER: 2*times alternating normal/mirrored chunks with one
    parameter copy (mirror ppermute of stage rows) must equal the golden
    sequential pass over the same 2*times*B examples (ref
    ``gems_master.py:72-103`` + allreduce merge ``comm.py:460-504``).
    times=4 exercises the pair-scan chunk loop (compile cost flat in
    ``--times``) beyond the scan's first two iterations."""
    cfg = ParallelConfig(
        batch_size=4, parts=2, split_size=2, spatial_size=0, image_size=32,
        times=times,
    )
    cells = get_resnet_v1(depth=8)
    trainer = GemsMasterTrainer(cells, cfg)
    _run_and_compare(trainer)


@pytest.mark.skipif(
    _GEMS_GRAD_BROKEN,
    reason="tracing the GEMS train-step jaxpr differentiates the schedule "
    "(same jax 0.4.x shard_map transpose limitation)",
)
def test_gems_times_constant_program_size():
    """The GEMS chunk loop is a ``lax.scan`` over normal/mirror pairs
    (``GemsMasterTrainer._local_loss``): the traced program must contain
    exactly two pipeline schedules regardless of ``--times`` — the
    reference's effective-batch knob (``gems_master.py:72-103``) must be
    free to raise. Proof: the train-step jaxpr has an IDENTICAL equation
    count for times=1 and times=4 (only the scan length — a shape — may
    differ). Golden parity at times=4 is test_gems_master_matches_golden."""

    def count_eqns(jaxpr) -> int:
        n = 0
        for eqn in jaxpr.eqns:
            n += 1
            for v in eqn.params.values():
                vals = v if isinstance(v, (list, tuple)) else [v]
                for item in vals:
                    inner = getattr(item, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        n += count_eqns(inner)
                    elif hasattr(item, "eqns"):
                        n += count_eqns(item)
        return n

    counts = {}
    for times in (1, 4):
        cfg = ParallelConfig(
            batch_size=4, parts=2, split_size=2, spatial_size=0,
            image_size=32, times=times,
        )
        cells = get_resnet_v1(depth=8)
        trainer = GemsMasterTrainer(cells, cfg)
        state = trainer.init(jax.random.PRNGKey(0))
        x, y = _batch(trainer.chunks * cfg.batch_size, cfg.image_size)
        xs, ys = trainer.shard_batch(x, y)
        jaxpr = jax.make_jaxpr(trainer._train_step)(state, xs, ys)
        counts[times] = count_eqns(jaxpr.jaxpr)

    assert counts[1] == counts[4], (
        f"program size grew with --times: {counts} — the chunk loop is "
        "no longer a constant-size scan"
    )


@pytest.mark.slow
def test_gems_master_with_spatial():
    """SP+GEMS (ref ``train_spatial_master.py``): spatial front + both pipe
    directions, composing without the reference's rank-disjointness
    constraint."""
    cfg = ParallelConfig(
        batch_size=2,
        parts=2,
        split_size=3,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=32,
        times=1,
    )
    n_cells = len(get_resnet_v1(depth=14))
    n_spatial = GemsMasterTrainer.spatial_cell_count(n_cells, cfg)
    cells = get_resnet_v1(depth=14, spatial_cells=n_spatial)
    plain = get_resnet_v1(depth=14)
    trainer = GemsMasterTrainer(cells, cfg, plain_cells=plain)
    _run_and_compare(trainer)


@pytest.mark.slow
def test_five_d_parallelism_matches_golden():
    """The reference's headline "5D parallelism" (README.md:90-101) composed
    in ONE jitted SPMD program over the 8 virtual devices: Spatial (vertical
    tiles with the D2 fused-halo model) x Pipeline (2 LP stages, fill-drain)
    x Data (2 replicas) x GEMS bidirectional (2 mirrored chunks) x
    LOCAL_DP_LP (post-join stages batch-shard over the tile devices) —
    golden-compared on loss AND updated parameters. The reference needs two
    MPIComm worlds, mirrored rank maps, and a GPU cluster to even launch
    this combination."""
    from mpi4dl_tpu.models.resnet import get_resnet_v2, get_resnet_v2_d2

    cfg = ParallelConfig(
        batch_size=8,
        parts=1,
        split_size=3,
        spatial_size=1,
        num_spatial_parts=(2,),
        slice_method="vertical",
        image_size=32,
        data_parallel=2,
        local_dp=2,
        times=1,
        halo_d2=True,
        fused_layers=2,
    )
    n_plain = len(get_resnet_v2(depth=20))
    n_sp_plain = GemsMasterTrainer.spatial_cell_count(n_plain, cfg)
    cells, plain, nsp = get_resnet_v2_d2(
        depth=20, spatial_cells=n_sp_plain, fused_layers=2
    )
    trainer = GemsMasterTrainer(
        cells, cfg, plain_cells=plain, num_spatial_cells=nsp
    )
    assert trainer.S == 2  # real pipeline
    assert trainer.chunks == 2  # GEMS bidirectional pair
    assert trainer.mb_back == trainer.mb_local // 2  # LOCAL_DP_LP slice
    _run_and_compare_local_dp(trainer)


# -- AmoebaNet through the pipeline engine (tuple-state wires) ---------------
#
# The reference's MULTIPLE_INPUT/MULTIPLE_OUTPUT machinery
# (mp_pipeline.py:215-223, 337-363) exists for AmoebaNet's (concat, skip)
# stage interface; round-1 VERDICT flagged that no pipeline golden exercised
# it here. These run amoebanetd cells through PipelineTrainer (LP, SP+LP)
# and GemsMasterTrainer with pytree wires, parameter-equality vs golden.


def _amoeba(spatial_cells=0):
    from mpi4dl_tpu.models.amoebanet import amoebanetd

    kw = dict(num_classes=10, num_layers=3, num_filters=32)
    return (
        amoebanetd(spatial_cells=spatial_cells, **kw),
        amoebanetd(**kw),
    )


@pytest.mark.slow
def test_amoebanet_lp_pipeline_matches_golden():
    """Plain LP: the stage-boundary wires carry (concat, skip) tuples."""
    cfg = ParallelConfig(
        batch_size=4, parts=2, split_size=2, spatial_size=0, image_size=64
    )
    cells, plain = _amoeba()
    trainer = PipelineTrainer(cells, cfg, plain_cells=plain)
    # The boundary really is a tuple wire (2 leaves), or this test proves
    # nothing about pytree plumbing.
    assert any(len(m.shapes) == 2 for m in trainer.wire_metas), [
        m.shapes for m in trainer.wire_metas
    ]
    # AmoebaNet's untrained gradients reach ~1e7 (see test_train's scan
    # test), so f32 reassociation noise amplifies across the 2 update steps;
    # the per-step LOSS assertions (rtol 1e-5, inside _run_and_compare)
    # carry the engine-correctness rigor, the param check is a sanity net.
    _run_and_compare(trainer, rtol=2e-2, atol=1e-4)


@pytest.mark.slow
def test_amoebanet_sp_lp_pipeline_matches_golden():
    """SP front (2x2 tiles, halo-exchanged cells) + LP back with tuple wires."""
    cfg = ParallelConfig(
        batch_size=4,
        parts=2,
        split_size=3,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=64,
    )
    n_sp = PipelineTrainer.spatial_cell_count(9, cfg)
    cells, plain = _amoeba(spatial_cells=n_sp)
    trainer = PipelineTrainer(cells, cfg, plain_cells=plain)
    # loss_rtol loosened one notch too: cross-tile BN pmean adds another
    # reassociation layer to the same amplification (see LP test note).
    _run_and_compare(trainer, rtol=2e-2, atol=1e-4, loss_rtol=2e-4)


@pytest.mark.slow
def test_amoebanet_gems_matches_golden():
    """GEMS mirror pairs with tuple wires (ref train_spatial_master lineage)."""
    cfg = ParallelConfig(
        batch_size=4, parts=2, split_size=2, spatial_size=0, image_size=64,
        times=1,
    )
    cells, plain = _amoeba()
    trainer = GemsMasterTrainer(cells, cfg, plain_cells=plain)
    _run_and_compare(trainer, rtol=2e-2, atol=1e-4)  # see LP test note
