"""End-to-end trainer parity: distributed SP(+DP) training step must match
the single-device golden step bit-for-bit (up to f32 reduction order).

This covers what the reference can only check by eyeballing loss curves on a
real GPU+MPI cluster: loss value, gradient correctness (via updated params),
and optimizer semantics under spatial tiling + data parallelism.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi4dl_tpu.config import ParallelConfig
from mpi4dl_tpu.models.resnet import get_resnet_v1
from mpi4dl_tpu.ops.layers import Conv2d, Dense, Pool
from mpi4dl_tpu.train import Trainer, TrainState, single_device_step


def _batch(b=4, size=32, classes=10, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((b, size, size, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, classes, size=(b,)), jnp.int32)
    return x, y


def _assert_tree_close(a, b, **kw):
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(np.asarray(u), np.asarray(v), **kw),
        a,
        b,
    )


@pytest.mark.parametrize(
    "slice_method,parts",
    [("square", 4), pytest.param("vertical", 4, marks=pytest.mark.slow)],
)
def test_resnet_spatial_trainer_matches_single_device(slice_method, parts):
    cfg = ParallelConfig(
        batch_size=4,
        split_size=1,
        spatial_size=1,
        num_spatial_parts=(parts,),
        slice_method=slice_method,
        image_size=32,
        data_parallel=1,
    )
    spatial = get_resnet_v1(depth=8, spatial_cells=3, cross_tile_bn=True)
    plain = get_resnet_v1(depth=8, spatial_cells=0)
    trainer = Trainer(spatial, num_spatial_cells=3, config=cfg, plain_cells=plain)

    state = trainer.init(jax.random.PRNGKey(0), (4, 32, 32, 3))
    _, golden_step = single_device_step(plain)
    gp = jax.tree.map(jnp.copy, state.params)  # trainer donates its state
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )

    x, y = _batch()
    for seed in (1, 2):
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        golden_state, golden_metrics = golden_step(golden_state, x, y)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
        )
        np.testing.assert_allclose(
            float(metrics["accuracy"]), float(golden_metrics["accuracy"]), rtol=1e-6
        )
        x, y = _batch(seed=seed + 10)
    _assert_tree_close(state.params, golden_state.params, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_dp_plus_sp_trainer_matches_golden():
    """DP=2 × 2×2 tiles (all 8 virtual devices). BN-free cells so per-shard
    batch statistics can't mask a gradient-reduction bug."""
    cfg = ParallelConfig(
        batch_size=8,
        split_size=1,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=16,
        num_classes=10,
        data_parallel=2,
    )

    def build(spatial):
        return [
            Conv2d(features=8, kernel_size=3, spatial=spatial),
            Pool(kind="max", kernel_size=2, spatial=spatial),
            Conv2d(features=16, kernel_size=3, strides=2, spatial=spatial),
            Dense(10),
        ]

    spatial_cells, plain_cells = build(True), build(False)
    trainer = Trainer(spatial_cells, num_spatial_cells=3, config=cfg, plain_cells=plain_cells)
    state = trainer.init(jax.random.PRNGKey(1), (8, 16, 16, 3))
    _, golden_step = single_device_step(plain_cells)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )

    x, y = _batch(b=8, size=16)
    xs, ys = trainer.shard_batch(x, y)
    state, metrics = trainer.train_step(state, xs, ys)
    golden_state, golden_metrics = golden_step(golden_state, x, y)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
    )
    _assert_tree_close(state.params, golden_state.params, rtol=1e-4, atol=1e-6)


def test_pure_dp_no_spatial():
    """spatial_size=0 → batch-sharded only; mesh tile axes collapse to 1."""
    cfg = ParallelConfig(batch_size=8, split_size=1, spatial_size=0, data_parallel=4)
    cells = [Conv2d(features=4, kernel_size=3), Dense(10)]
    trainer = Trainer(cells, num_spatial_cells=0, config=cfg)
    state = trainer.init(jax.random.PRNGKey(2), (8, 8, 8, 3))
    _, golden_step = single_device_step(cells)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x, y = _batch(b=8, size=8)
    xs, ys = trainer.shard_batch(x, y)
    state, metrics = trainer.train_step(state, xs, ys)
    golden_state, golden_metrics = golden_step(golden_state, x, y)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
    )
    _assert_tree_close(state.params, golden_state.params, rtol=1e-4, atol=1e-6)


def test_scan2_nested_remat_matches_golden(remat="scan2"):
    """The "scan2" policy (two-level checkpointing inside scan runs) and
    the "scanlog" policy (whole-model logarithmic recursion — the deepest-
    memory tier, ≥3072px) are pure scheduling choices: depth-44 gives
    7-cell runs, exercising BOTH scan2's chunked outer scan (g=3, m=2) and
    its remainder head-chunk path (rem=1), and odd left/right splits in
    scanlog's recursion; depth-20's 3-cell runs (below scan2's nesting
    threshold) are covered by the "scan" parametrization below."""
    cells = get_resnet_v1(depth=44)
    cfg = ParallelConfig(batch_size=2, split_size=1, spatial_size=0, image_size=32)
    trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat=remat)
    state = trainer.init(jax.random.PRNGKey(3), (2, 32, 32, 3))
    _, golden_step = single_device_step(cells)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x, y = _batch(b=2, size=32)
    for seed in (1, 2):
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        golden_state, golden_metrics = golden_step(golden_state, x, y)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
        )
        x, y = _batch(b=2, size=32, seed=seed + 20)
    _assert_tree_close(state.params, golden_state.params, rtol=2e-4, atol=1e-5)


def test_scanlog_matches_golden():
    test_scan2_nested_remat_matches_golden(remat="scanlog")


def test_scanq_matches_golden():
    """"scanq" (anchored-quadratic run backward, chain_quadratic): pure
    scheduling — depth-44's 7-cell runs exercise the masked recompute
    sweep and the per-cell vjp accumulation. The n=3 gate edge (depth-20's
    3-cell runs) is covered by the slow-tier
    ``test_remat_policies_match_golden[scanq]``."""
    test_scan2_nested_remat_matches_golden(remat="scanq")


def test_scanq_store_budget_matches_golden(monkeypatch):
    """MPI4DL_TPU_SCANQ_STORE_MB grants runs the plain stored-carry scan
    BACK-TO-FRONT until the budget runs out (the late stages free their
    carries before the early stages' backward runs — the safe grants);
    the rest stay anchored — a storage-placement choice only: numerics
    must equal the golden step. Re-pinned for ISSUE 10's grant-order fix
    (was front-to-back, the opposite of the docstring's own rationale):
    a 1 MB budget now covers depth-44's LATER stage runs (per-stage
    compact-carry bytes roughly halve stage over stage) and denies the
    ~0.92 MB first run, still exercising BOTH paths in one trace."""
    monkeypatch.setenv("MPI4DL_TPU_SCANQ_STORE_MB", "1")
    test_scan2_nested_remat_matches_golden(remat="scanq")


def test_scanq_store_budget_grants_back_to_front(monkeypatch):
    """ISSUE 10 satellite (ADVICE-r5): the store budget must go to the
    LATEST fitting runs — they free their carries before the early runs'
    backward executes — not be consumed front-to-back. Pure unit: a
    stub plan of three equal-size eligible runs and a budget that covers
    exactly two must grant the LAST TWO and deny the first. (The golden
    tests can't pin this: grant order is numerics-neutral.)"""
    import types

    monkeypatch.setenv("MPI4DL_TPU_SCANQ_STORE_MB", "0.0024")  # 2400 B

    ident = types.SimpleNamespace(apply=lambda p, h: h)
    stub = types.SimpleNamespace(
        _scan_plan=[[0, 1, 2], [3, 4, 5], [6, 7, 8]],
        _scan_plan_key=("k",),
        _at_join=lambda i, h: h,
        cells={i: ident for i in range(9)},
    )
    x = jnp.zeros((100,), jnp.float32)  # 400 B carry; 1200 B per run
    params = {i: {} for i in range(9)}
    granted = {
        run[0]: Trainer._scanq_store_granted(stub, run, params, x)
        for run in stub._scan_plan
    }
    assert granted == {0: False, 3: True, 6: True}
    # Grant bytes recorded for the remat-effectiveness rule, per run.
    assert stub._scanq_grant_bytes == {3: 1200, 6: 1200}
    assert stub._scanq_budget_left == pytest.approx(0.0)


def test_scan2_offload_matches_golden(monkeypatch):
    """MPI4DL_TPU_SCAN2_OFFLOAD=1 moves scan2's outer chunk boundaries to
    pinned host memory between forward and backward (the ≥4096px HBM
    lever) — a pure storage-placement choice: numerics must equal the
    on-device scan2 run and the golden step."""
    monkeypatch.setenv("MPI4DL_TPU_SCAN2_OFFLOAD", "1")
    test_scan2_nested_remat_matches_golden()


@pytest.mark.slow
@pytest.mark.parametrize(
    "remat",
    ["cell", "sqrt", "scan", "scan2", "scanlog", "scanq", "scan_save",
     "group_save"],
)
def test_remat_policies_match_golden(remat):
    """Every remat policy is a pure scheduling choice: losses, metrics, and
    updated parameters must be identical to the no-remat golden step. "scan"
    additionally rewrites repeated cells into a stacked-parameter lax.scan
    with compact [B, H, W*C] carries — still bit-equivalent."""
    cells = get_resnet_v1(depth=20)  # 3 stages x 3 repeated blocks → scannable runs
    cfg = ParallelConfig(batch_size=4, split_size=1, spatial_size=0, image_size=32)
    trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat=remat)
    state = trainer.init(jax.random.PRNGKey(3), (4, 32, 32, 3))
    _, golden_step = single_device_step(cells)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x, y = _batch(b=4, size=32)
    for seed in (1, 2):
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        golden_state, golden_metrics = golden_step(golden_state, x, y)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
        )
        x, y = _batch(b=4, size=32, seed=seed + 20)
    _assert_tree_close(state.params, golden_state.params, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_scan_unroll_matches_golden(monkeypatch):
    """MPI4DL_TPU_SCAN_UNROLL amortizes scan machinery without changing
    numerics: an unrolled scan run must equal the no-remat golden exactly
    like unroll=1 does (unroll=2 on a 3-cell run also covers the remainder
    handling)."""
    monkeypatch.setenv("MPI4DL_TPU_SCAN_UNROLL", "2")
    test_remat_policies_match_golden("scan_save")


@pytest.mark.slow
def test_scan_remat_spatial_matches_golden():
    """The "scan" policy composes with a spatial front: runs never span the
    SP→LP join and spatial (halo-exchanging) repeated cells scan inside
    shard_map."""
    cfg = ParallelConfig(
        batch_size=4,
        split_size=1,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=32,
    )
    spatial = get_resnet_v1(depth=14, spatial_cells=5, cross_tile_bn=True)
    plain = get_resnet_v1(depth=14, spatial_cells=0)
    trainer = Trainer(
        spatial, num_spatial_cells=5, config=cfg, plain_cells=plain, remat="scan"
    )
    state = trainer.init(jax.random.PRNGKey(4), (4, 32, 32, 3))
    _, golden_step = single_device_step(plain)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x, y = _batch(b=4, size=32)
    xs, ys = trainer.shard_batch(x, y)
    state, metrics = trainer.train_step(state, xs, ys)
    golden_state, golden_metrics = golden_step(golden_state, x, y)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
    )
    _assert_tree_close(state.params, golden_state.params, rtol=2e-4, atol=1e-5)


def test_local_dp_without_lp_stage_rejected():
    """--local-DP configs with no LP stage after the spatial front used to
    route to the non-pipeline Trainer, which silently ignored the flag
    (round-1 VERDICT weak #6). The config must now fail loudly."""
    import pytest

    from mpi4dl_tpu.config import ParallelConfig

    with pytest.raises(ValueError, match="LP stage"):
        ParallelConfig(
            batch_size=8,
            split_size=1,
            spatial_size=1,
            num_spatial_parts=(4,),
            image_size=32,
            local_dp=4,
        )


@pytest.mark.slow
@pytest.mark.parametrize(
    "num_filters",
    [32, pytest.param(288, marks=pytest.mark.slow)],  # 288F: ~4 min on CPU
)
def test_scan_remat_amoebanet_tuple_state_matches_golden(num_filters):
    """The "scan" planner accepts pytree (tuple-state) fixed points: an
    AmoebaNet run of identical normal cells rewrites into one stacked-param
    lax.scan whose carry is the ``(concat, skip)`` tuple — round-1 VERDICT
    weak: the planner only accepted single tensors, so AmoebaNet degenerated
    to per-cell checkpointing.

    num_filters=288 puts every carry leaf past the 64-channel pad-tax
    boundary, so the scan runs with 4-D (un-flattened) carries — the
    branch of ``Trainer._compact`` that real AmoebaNet-D (416F) takes by
    default since the round-4 conditional flatten (review finding: the
    32F case flattens every leaf, leaving the pass-through path covered
    only by on-TPU benches).

    Comparison is loss + one-step GRADIENTS at relative tolerance, not
    multi-step parameters: an untrained AmoebaNet's input-side gradients
    reach ~1e7 (measured), so the f32 reassociation noise between the
    scanned and per-cell schedules amplifies chaotically across update
    steps and makes multi-step bitwise-style comparison meaningless for
    this model. 64px keeps the last stage at 2x2 spatial — at 32px it
    degenerates to 1x1 (every windowed op all-padding), where the
    conditioning makes even same-math program pairs diverge visibly."""
    from mpi4dl_tpu.models.amoebanet import amoebanetd

    cells = amoebanetd(num_classes=10, num_layers=12, num_filters=num_filters)
    cfg = ParallelConfig(batch_size=2, split_size=1, spatial_size=0, image_size=64)
    trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat="scan")
    state = trainer.init(jax.random.PRNGKey(5), (2, 32, 32, 3))
    # The plan must contain at least one multi-cell (scanned) run.
    plan = trainer._plan_scan_runs(state.params, jnp.zeros((2, 32, 32, 3)))
    assert any(len(r) > 1 for r in plan), plan

    golden = Trainer(cells, num_spatial_cells=0, config=cfg, remat=False)
    x, y = _batch(b=2, size=64)
    xs, ys = trainer.shard_batch(x, y)

    def loss_and_grad(tr):
        val, g = jax.jit(
            jax.value_and_grad(lambda p: tr._sharded_loss(p, xs, ys)[0])
        )(state.params)
        return float(val), jax.tree.map(np.asarray, g)

    loss_s, grad_s = loss_and_grad(trainer)
    loss_g, grad_g = loss_and_grad(golden)
    np.testing.assert_allclose(loss_s, loss_g, rtol=1e-6)
    for gs, gg in zip(grad_s, grad_g):
        for u, v in zip(jax.tree.leaves(gs), jax.tree.leaves(gg)):
            scale = max(float(np.max(np.abs(v))), 1e-6)
            np.testing.assert_allclose(u / scale, v / scale, atol=3e-4)


@pytest.mark.slow
@pytest.mark.parametrize("remat", [False, "scan_save"])
def test_packed_layout_matches_golden(remat):
    """The persistently-packed activation layout (ops/packed.py) is a pure
    layout change: same parameter tree, same math (mod f32 accumulation
    order) — train steps must match the stock NHWC golden."""

    from mpi4dl_tpu.models.resnet import get_resnet_v2

    # depth 29 → 3 blocks/stage → the 2 trailing identical cells form a
    # scannable run (depth 20 has only 2 blocks: block0 differs, no runs).
    kw = dict(depth=29 if remat == "scan_save" else 20, num_classes=10, pool_kernel=8)
    packed = get_resnet_v2(layout="packed", **kw)
    stock = get_resnet_v2(**kw)
    cfg = ParallelConfig(batch_size=4, split_size=1, spatial_size=0, image_size=32)
    trainer = Trainer(packed, num_spatial_cells=0, config=cfg, remat=remat)
    state = trainer.init(jax.random.PRNGKey(7), (4, 32, 32, 3))
    if remat == "scan_save":
        plan = trainer._plan_scan_runs(state.params, jnp.zeros((4, 32, 32, 3)))
        assert any(len(r) > 1 for r in plan), plan  # packed cells still scan
    _, golden_step = single_device_step(stock)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x, y = _batch(b=4, size=32)
    for seed in (1, 2):
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        golden_state, golden_metrics = golden_step(golden_state, x, y)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-4
        )
        x, y = _batch(b=4, size=32, seed=seed + 30)
    _assert_tree_close(state.params, golden_state.params, rtol=5e-3, atol=1e-4)


@pytest.mark.slow
def test_packed_spatial_matches_golden():
    """Packed layout under spatial partitioning (round-2 VERDICT #4): the
    packed conv's zero-pad columns become halo-exchanged packed columns
    (``conv2d_packed`` spatial mode) — the distributed packed train step
    must match the single-device stock-NHWC golden like the plain spatial
    trainer does."""

    from mpi4dl_tpu.models.resnet import get_resnet_v2

    kw = dict(depth=20, num_classes=10, pool_kernel=8)
    plain = get_resnet_v2(**kw)
    n_sp = len(plain) - 1  # every cell but the head runs on 2x2 tiles
    packed_sp = get_resnet_v2(layout="packed", spatial_cells=n_sp, **kw)
    cfg = ParallelConfig(
        batch_size=4,
        split_size=1,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=32,
    )
    trainer = Trainer(
        packed_sp, num_spatial_cells=n_sp, config=cfg, plain_cells=plain
    )
    state = trainer.init(jax.random.PRNGKey(7), (4, 32, 32, 3))
    _, golden_step = single_device_step(plain)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x, y = _batch(b=4, size=32)
    for seed in (1, 2):
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        golden_state, golden_metrics = golden_step(golden_state, x, y)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-4
        )
        x, y = _batch(b=4, size=32, seed=seed + 30)
    _assert_tree_close(state.params, golden_state.params, rtol=5e-3, atol=1e-4)


@pytest.mark.parametrize(
    "accum", [pytest.param(2, marks=pytest.mark.slow), 4]
)
def test_grad_accum_matches_golden(accum):
    """grad_accum=k applies the MEAN of k per-chunk gradients in one
    update, each chunk a batch-of-B/k forward (own BN statistics — the
    reference's GEMS --times chunk semantics, gems_master.py:72-103).
    Golden: explicit per-chunk value_and_grad + one SGD-momentum update."""
    import optax

    from mpi4dl_tpu.train import apply_cells, cross_entropy_sum, make_optimizer

    cells = get_resnet_v1(depth=8)
    cfg = ParallelConfig(batch_size=4, split_size=1, spatial_size=0, image_size=32)
    trainer = Trainer(
        cells, num_spatial_cells=0, config=cfg, grad_accum=accum
    )
    state = trainer.init(jax.random.PRNGKey(5), (4, 32, 32, 3))
    params0 = jax.tree.map(jnp.copy, state.params)
    x, y = _batch(b=4, size=32)
    xs, ys = trainer.shard_batch(x, y)
    state, metrics = trainer.train_step(state, xs, ys)

    def chunk_loss(params, xc, yc):
        logits = apply_cells(cells, params, xc)
        return cross_entropy_sum(logits, yc) / xc.shape[0]

    b = 4 // accum
    losses, grads = [], []
    for i in range(accum):
        l, g = jax.value_and_grad(chunk_loss)(
            params0, x[i * b : (i + 1) * b], y[i * b : (i + 1) * b]
        )
        losses.append(l)
        grads.append(g)
    mean_grads = jax.tree.map(lambda *gs: sum(gs) / accum, *grads)
    tx = make_optimizer()
    updates, _ = tx.update(mean_grads, tx.init(params0), params0)
    want_params = optax.apply_updates(params0, updates)

    np.testing.assert_allclose(
        float(metrics["loss"]), float(sum(losses) / accum), rtol=1e-5
    )
    _assert_tree_close(state.params, want_params, rtol=1e-4, atol=1e-6)


def test_dp_times_grad_accum_matches_unchunked_dp():
    """DP=2 × grad_accum=2 == unchunked DP=2 (parameter equality; BN-free
    cells so per-chunk batch statistics can't mask a reduction bug — with
    linear loss normalization, mean-of-chunk-grads equals the full-batch
    gradient exactly). Also pins what the chunk reshape EMITS on a
    DP-sharded batch (train.py ``_accum_grads`` caveat): each contiguous
    chunk lives on one device, so feeding it back through the
    batch-sharded loss inserts exactly one resharding ``all-to-all`` per
    input (x and y — 2 total), and the unchunked step has none. A change
    that doubles the resharding traffic fails here. Measured cost note in
    docs/PERF.md round 5."""
    from mpi4dl_tpu.analysis import collective_inventory as _inventory

    def build():
        return [
            Conv2d(features=8, kernel_size=3),
            Pool(kind="max", kernel_size=2),
            Conv2d(features=16, kernel_size=3, strides=2),
            Dense(10),
        ]

    cfg = ParallelConfig(
        batch_size=8, split_size=1, spatial_size=0, image_size=16,
        data_parallel=2,
    )
    x, y = _batch(b=8, size=16)
    states, hlos = [], []
    for accum in (1, 2):
        trainer = Trainer(
            build(), num_spatial_cells=0, config=cfg, grad_accum=accum
        )
        state = trainer.init(jax.random.PRNGKey(3), (8, 16, 16, 3))
        xs, ys = trainer.shard_batch(x, y)
        hlos.append(trainer._jit_step.lower(state, xs, ys).compile().as_text())
        state, metrics = trainer.train_step(state, xs, ys)
        states.append((jax.device_get(state.params), float(metrics["loss"])))

    (p1, l1), (p2, l2) = states
    np.testing.assert_allclose(l1, l2, rtol=1e-5)
    _assert_tree_close(p1, p2, rtol=1e-4, atol=1e-6)

    inv1, inv2 = _inventory(hlos[0]), _inventory(hlos[1])
    assert inv1["all-to-all"] == 0
    assert inv2["all-to-all"] == 2, (
        "DP x grad_accum chunk resharding changed: expected one all-to-all "
        f"per input (x, y), got {inv2}"
    )
    # Both steps reduce gradients the same way (psum-of-contributions);
    # chunking must not multiply gradient reductions.
    assert inv1["all-reduce"] == inv2["all-reduce"]


def test_grad_accum_rejects_indivisible_batch():
    cells = [Dense(10)]
    cfg = ParallelConfig(batch_size=3, split_size=1, spatial_size=0, image_size=8)
    trainer = Trainer(cells, num_spatial_cells=0, config=cfg, grad_accum=2)
    state = trainer.init(jax.random.PRNGKey(0), (3, 8, 8, 3))
    x, y = _batch(b=3, size=8)
    xs, ys = trainer.shard_batch(x, y)
    with pytest.raises(ValueError, match="not divisible"):
        trainer.train_step(state, xs, ys)


@pytest.mark.slow
def test_save_budget_matches_golden(monkeypatch):
    """MPI4DL_TPU_SAVE_BUDGET_MB only changes which runs save conv outputs
    (a scheduling choice) — params/metrics must match the no-remat golden
    exactly, even with a budget so small nothing gets saved."""
    monkeypatch.setenv("MPI4DL_TPU_SAVE_BUDGET_MB", "0.001")
    cells = get_resnet_v1(depth=20)
    cfg = ParallelConfig(batch_size=4, split_size=1, spatial_size=0, image_size=32)
    trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat="scan_save")
    state = trainer.init(jax.random.PRNGKey(3), (4, 32, 32, 3))
    _, golden_step = single_device_step(cells)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x, y = _batch(b=4, size=32)
    xs, ys = trainer.shard_batch(x, y)
    state, metrics = trainer.train_step(state, xs, ys)
    golden_state, golden_metrics = golden_step(golden_state, x, y)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
    )
    _assert_tree_close(state.params, golden_state.params, rtol=2e-4, atol=1e-5)


def test_nockpt_budget_matches_golden(monkeypatch):
    """MPI4DL_TPU_NOCKPT_BUDGET_MB grants the cheapest runs a no-checkpoint
    tier (residuals stored, nothing replayed in backward) — a pure
    scheduling choice: params/metrics must match the no-remat golden. The
    10 MB budget covers some-but-not-all depth-20 runs at 32px, exercising
    the mixed grant path on both the saving and plain scan policies."""
    monkeypatch.setenv("MPI4DL_TPU_NOCKPT_BUDGET_MB", "10")
    for remat in ("scan_save", "scan"):
        cells = get_resnet_v1(depth=20)
        cfg = ParallelConfig(
            batch_size=4, split_size=1, spatial_size=0, image_size=32
        )
        trainer = Trainer(cells, num_spatial_cells=0, config=cfg, remat=remat)
        state = trainer.init(jax.random.PRNGKey(3), (4, 32, 32, 3))
        _, golden_step = single_device_step(cells)
        gp = jax.tree.map(jnp.copy, state.params)
        golden_state = TrainState(
            params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
        )
        x, y = _batch(b=4, size=32)
        xs, ys = trainer.shard_batch(x, y)
        state, metrics = trainer.train_step(state, xs, ys)
        golden_state, golden_metrics = golden_step(golden_state, x, y)
        np.testing.assert_allclose(
            float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
        )
        _assert_tree_close(
            state.params, golden_state.params, rtol=2e-4, atol=1e-5
        )


@pytest.mark.slow
def test_save_budget_spatial_matches_golden(monkeypatch):
    """The save-budget estimator must account for the SP→LP tile merge
    (join shapes are 4x the per-tile walk on a 2x2 grid) and still produce
    golden-exact numerics for a spatial scan_save trainer."""
    monkeypatch.setenv("MPI4DL_TPU_SAVE_BUDGET_MB", "2")
    cfg = ParallelConfig(
        batch_size=4,
        split_size=1,
        spatial_size=1,
        num_spatial_parts=(4,),
        slice_method="square",
        image_size=32,
    )
    spatial = get_resnet_v1(depth=14, spatial_cells=5, cross_tile_bn=True)
    plain = get_resnet_v1(depth=14, spatial_cells=0)
    trainer = Trainer(
        spatial, num_spatial_cells=5, config=cfg, plain_cells=plain,
        remat="scan_save",
    )
    state = trainer.init(jax.random.PRNGKey(4), (4, 32, 32, 3))
    _, golden_step = single_device_step(plain)
    gp = jax.tree.map(jnp.copy, state.params)
    golden_state = TrainState(
        params=gp, opt_state=trainer.tx.init(gp), step=jnp.zeros((), jnp.int32)
    )
    x, y = _batch(b=4, size=32)
    xs, ys = trainer.shard_batch(x, y)
    state, metrics = trainer.train_step(state, xs, ys)
    golden_state, golden_metrics = golden_step(golden_state, x, y)
    np.testing.assert_allclose(
        float(metrics["loss"]), float(golden_metrics["loss"]), rtol=1e-5
    )
    _assert_tree_close(state.params, golden_state.params, rtol=2e-4, atol=1e-5)


def test_compact_restore_mixed_tree_roundtrip():
    """_compact flattens only leaves whose lane-pad factor is >= 2; a
    mixed tree (C=16 flattens, C=72 passes through 4-D) must round-trip
    exactly through _restore (round-4 conditional flatten)."""
    rng = np.random.default_rng(0)
    tree = {
        "narrow": jnp.asarray(rng.standard_normal((2, 4, 4, 16)), jnp.float32),
        "wide": jnp.asarray(rng.standard_normal((2, 4, 4, 72)), jnp.float32),
        "vec": jnp.asarray(rng.standard_normal((7,)), jnp.float32),
    }
    compact, meta = Trainer._compact(tree)
    assert compact["narrow"].shape == (2, 4, 4 * 16)   # tax 8x: flattened
    assert compact["wide"].shape == (2, 4, 4, 72)      # tax 1.78x: kept 4-D
    assert compact["vec"].shape == (7,)
    restored = Trainer._restore(compact, meta)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(restored[k]), np.asarray(tree[k]))
