"""Distributed-vs-sequential layer equivalence tests.

TPU rebuild of the reference's conv validation benchmarks
(``benchmark_sp_halo_exchange_with_compute_val.py:704-780``,
``benchmark_sp_halo_exchange_conv.py:940-1092``): a spatially-partitioned
conv/pool over the tile mesh must produce exactly the tiles of the
single-device ("sequential") op on the full image. Unlike the reference we
don't need to force weights to 1.0 — CPU simulation is deterministic — but we
keep one ones-weight case for parity with the reference harness.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpi4dl_tpu.config import tile_grid
from mpi4dl_tpu.ops.layers import Conv2d, Pool

SPEC = P(None, "tile_h", "tile_w", None)


def _mesh(th, tw):
    dev = np.asarray(jax.devices()[: th * tw]).reshape(th, tw)
    return Mesh(dev, ("tile_h", "tile_w"))


def _run_distributed(module_spatial, module_plain, x, mesh, params=None):
    """Init plain module single-device, run spatial module under shard_map
    with the same params, return (distributed_out, golden_out)."""
    key = jax.random.PRNGKey(0)
    if params is None:
        params = module_plain.init(key, x)
    golden = module_plain.apply(params, x)

    @jax.jit
    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), SPEC),
        out_specs=SPEC,
        check_vma=False,
    )
    def dist_apply(p, tile):
        return module_spatial.apply(p, tile)

    xs = jax.device_put(x, NamedSharding(mesh, SPEC))
    out = dist_apply(params, xs)
    return np.asarray(out), np.asarray(golden)


@pytest.mark.parametrize("slice_method,parts", [("square", 4), ("vertical", 4), ("horizontal", 4)])
@pytest.mark.parametrize("kernel,stride", [(3, 1), (3, 2), (1, 1), (5, 1)])
def test_spatial_conv_matches_sequential(slice_method, parts, kernel, stride):
    th, tw = tile_grid(parts, slice_method)
    mesh = _mesh(th, tw)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), dtype=jnp.float32)

    plain = Conv2d(features=8, kernel_size=kernel, strides=stride, spatial=False)
    spatial = Conv2d(features=8, kernel_size=kernel, strides=stride, spatial=True)
    out, golden = _run_distributed(spatial, plain, x, mesh)
    np.testing.assert_allclose(out, golden, rtol=1e-5, atol=1e-5)


def test_spatial_conv_ones_weights_integer_exact():
    """Reference-parity case: weights/bias forced to 1.0 on an arange image
    (``benchmark_sp_halo_exchange_with_compute_val.py:704-706``)."""
    mesh = _mesh(2, 2)
    x = jnp.arange(1 * 8 * 8 * 2, dtype=jnp.float32).reshape(1, 8, 8, 2)
    plain = Conv2d(features=4, kernel_size=3, spatial=False)
    spatial = Conv2d(features=4, kernel_size=3, spatial=True)
    params = plain.init(jax.random.PRNGKey(0), x)
    params = jax.tree.map(lambda a: jnp.ones_like(a), params)
    out, golden = _run_distributed(spatial, plain, x, mesh, params=params)
    np.testing.assert_array_equal(out, golden)


@pytest.mark.parametrize("kind", ["max", "avg"])
@pytest.mark.parametrize("kernel,stride,padding", [(2, 2, 0), (3, 2, 1), (3, 1, 1)])
def test_spatial_pool_matches_sequential(kind, kernel, stride, padding):
    mesh = _mesh(2, 2)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), dtype=jnp.float32)
    plain = Pool(kind=kind, kernel_size=kernel, strides=stride, padding=padding)
    spatial = Pool(
        kind=kind, kernel_size=kernel, strides=stride, padding=padding, spatial=True
    )
    out, golden = _run_distributed(spatial, plain, x, mesh)
    np.testing.assert_allclose(out, golden, rtol=1e-6, atol=1e-6)


def test_spatial_window_coverage_check():
    """Spatial windowed ops whose halo can't cover cross-boundary windows
    must fail loudly instead of silently dropping windows."""
    mesh = _mesh(2, 2)
    x = jnp.zeros((1, 8, 8, 2), jnp.float32)
    for mod in (
        Conv2d(features=2, kernel_size=3, padding=0, spatial=True),
        Pool(kind="max", kernel_size=3, strides=2, padding=0, spatial=True),
    ):
        with pytest.raises(ValueError, match="cover tile-boundary windows"):
            fn = shard_map(
                lambda t, m=mod: m.apply({"params": {}}, t),
                mesh=mesh,
                in_specs=(SPEC,),
                out_specs=SPEC,
                check_vma=False,
            )
            jax.eval_shape(fn, jax.ShapeDtypeStruct(x.shape, x.dtype))
